"""NHWC BatchNorm with device-group statistics — groupbn parity.

Rebuild of `apex.contrib.groupbn.BatchNorm2d_NHWC`
(`apex/contrib/groupbn/batch_norm.py:1-225`, kernels
`apex/contrib/csrc/groupbn/*`): NHWC BN whose statistics are exchanged
across a group of ``bn_group`` devices, with fused residual-add and ReLU.

Where the CUDA machinery went:

- **NHWC persistent kernels + occupancy tuning** (`nhwc_batch_norm_kernel.h`)
  → channels-last is the native TPU layout and XLA fuses the normalize/
  add/relu elementwise chain into one HBM pass on its own; there is no
  occupancy knob to tune. The capability survives; the machinery is the
  compiler's.
- **CUDA-IPC peer stat exchange** (`ipc.cu:68-130`, ``bn_group`` ranks
  share buffers intra-node) → a stats sub-group over the mesh axis
  (``axis_index_groups``), riding ICI. Group construction mirrors
  `batch_norm.py`'s rank/bn_group bookkeeping.
- **fused add+relu fwd/bwd** (`batch_norm_add_relu.cu`) → the ``z``/
  ``fuse_relu`` arguments of the shared syncbn core; the ReLU mask backward
  falls out of autodiff.

The module is the same object as :class:`apex_tpu.parallel.SyncBatchNorm`
specialized to the groupbn surface, because on TPU "optimized NHWC BN" and
"sync BN" are one implementation — the reference needed two CUDA codebases
for them.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import (
    SyncBatchNorm, sync_batch_norm, syncbn_stats_groups,
)


def bn_group_spec(world_size: int, bn_group: int):
    """axis_index_groups for a ``bn_group``-way stat exchange — the peer
    set the reference derives from (rank, bn_group) and shares via IPC
    handles (`batch_norm.py:46-76`)."""
    return syncbn_stats_groups(world_size, bn_group)


def BatchNorm2d_NHWC(num_features: int, *, fuse_relu: bool = False,
                     bn_group: int = 1, world_size: Optional[int] = None,
                     axis_name: Optional[str] = None,
                     momentum: float = 0.1, epsilon: float = 1e-5,
                     param_dtype: Any = jnp.float32) -> SyncBatchNorm:
    """Constructor mirror of ``BatchNorm2d_NHWC(planes, fuse_relu=...,
    bn_group=...)`` (`apex/contrib/groupbn/batch_norm.py:18-90`).

    With ``bn_group > 1`` an ``axis_name`` (and ``world_size``) must be
    given; statistics then combine across each group of ``bn_group``
    adjacent devices on that axis. Call with a residual: ``bn(x, z)`` for
    the bn_add_relu variant.
    """
    groups = None
    if bn_group > 1:
        if axis_name is None or world_size is None:
            raise ValueError("bn_group > 1 needs axis_name and world_size")
        groups = bn_group_spec(world_size, bn_group)
    return SyncBatchNorm(
        num_features=num_features,
        epsilon=epsilon,
        # reference momentum semantics (torch convention, inherited from
        # _BatchNorm and applied by the kernel as
        # running = (1-m)*running + m*new, `nhwc_batch_norm_kernel.h:1250`);
        # SyncBatchNorm uses the same convention — pass through unchanged.
        momentum=momentum,
        axis_name=axis_name if bn_group > 1 else None,
        axis_index_groups=groups,
        channel_axis=-1,
        fuse_relu=fuse_relu,
        param_dtype=param_dtype,
    )
