"""Static per-step collective-traffic accounting from compiled HLO.

The reference could only *infer* allreduce volume from its own bucketing
bookkeeping (`apex/parallel/distributed.py:425-475`); on TPU the compiled
program itself is the ground truth: every collective the step performs is
an instruction in the optimized HLO with a typed result shape. This
module walks that text and sums result bytes per collective opcode —
a compile-time constant per executable, fetched once and attached to
every logged record (the accounting DynamiQ-style compressed collectives
need as their uncompressed baseline).

Async pairs (``all-reduce-start``/``all-reduce-done``) are counted once,
at the ``-done`` (whose result is the actual output shape); the
``-start`` result tuples carry both operand and result buffers and would
double-count.
"""

from __future__ import annotations

import re
from typing import Dict, Optional

from apex_tpu.prof import hlo as _hlo
from apex_tpu.prof import xplane as _xplane

__all__ = ["COLLECTIVE_OPCODES", "collective_bytes",
           "collective_bytes_from_text", "collective_bytes_by_dtype",
           "collective_bytes_by_hop", "collective_bytes_by_axis",
           "scope_hop", "scope_axis_row", "wire_report"]

# The canonical prefix list lives next to the trace categorizer so live
# accounting and post-hoc attribution bucket opcodes identically.
COLLECTIVE_OPCODES = _xplane.COLLECTIVE_PREFIXES


def collective_bytes_from_text(hlo_text: str) -> Dict[str, int]:
    """Sum collective result bytes per opcode over an optimized-HLO dump.

    Returns ``{opcode: bytes, ..., "total": bytes}`` (opcodes with zero
    traffic are omitted; ``total`` is always present). A thin rollup of
    :func:`collective_bytes_by_dtype` — one scan, two views.

    Known limit: each instruction is counted ONCE — a collective inside
    a ``while``/``scan`` body (e.g. a per-microbatch psum) executes
    trip-count times per step but is summed once, so loop-wrapped steps
    are under-reported by the trip count. Hoist collectives out of the
    loop (the usual accumulate-then-sync pattern) or scale the estimate
    by the trip count yourself.
    """
    totals = {op: sum(per.values())
              for op, per in collective_bytes_by_dtype(hlo_text).items()}
    totals["total"] = sum(totals.values())
    return totals


def _iter_collective_rows(hlo_text: str):
    """Yield ``(opcode_prefix, dtype, bytes, stripped_scope)`` per
    collective result buffer of an optimized module. Async ``-start``
    halves are skipped (counted at the matching ``-done``) — the one
    scan behind both the per-dtype and the per-hop views."""
    for raw in hlo_text.splitlines():
        line = raw.strip()
        m = _hlo._INSTR_RE.match(line)
        if not m:
            continue
        op = m.group("op")
        for prefix in COLLECTIVE_OPCODES:
            if op.startswith(prefix):
                if op.endswith("-start"):
                    break  # counted at the matching -done
                sm = _SCOPE_RE.search(line)
                scope = _xplane.strip_scope(sm.group(1)) if sm else ""
                for dt, dims in _hlo._SHAPE_RE.findall(m.group("shape")):
                    if dt not in _hlo._DTYPE_BYTES:
                        continue
                    elems = 1
                    for d in dims.split(","):
                        if d:
                            elems *= int(d)
                    yield (prefix, dt,
                           elems * _hlo._DTYPE_BYTES[dt], scope)
                break


_SCOPE_RE = re.compile(r'op_name="((?:[^"\\]|\\.)*)"')

#: hop classification of a collective's stripped scope: the
#: hierarchical sync nests each hop under a ``bucketNN/ici`` or
#: ``bucketNN/dcn`` sub-span (apex_tpu.parallel.hierarchy), so the
#: link class each byte rides is readable from the compiled program.
#: Everything else — the flat sync's whole traffic included — lands in
#: ``"unattributed"``.
_HOP_RES = (("dcn", re.compile(r"(^|/)dcn(/|$)")),
            ("ici", re.compile(r"(^|/)ici(/|$)")))


def scope_hop(scope: str) -> str:
    """Link-hop class of a stripped collective scope — the ONE
    classifier for the ``bucketNN/ici|dcn`` sub-span convention
    (``pod_comm_budget``'s hierarchical structure audit keys off the
    same function, so the audit and ``by_hop`` cannot drift apart)."""
    for hop, rx in _HOP_RES:
        if rx.search(scope):
            return hop
    return "unattributed"


def scope_axis_row(scope: str) -> str:
    """Mesh-axis attribution row of a stripped collective scope: the
    :func:`apex_tpu.parallel.registry.scope_axis` answer, or the
    explicit ``"unknown"`` row for a scope the registry doesn't know.
    This is the ONE scope→axis join every per-axis consumer shares
    (``wire_report``'s ``by_axis``, the goodput ledger's
    ``comm_axes_ms`` split, ``mesh_explain``'s wire pricing) — the
    registry stays the single source (APX102's allowlist), and
    unattributable traffic lands in a visible row, never silently
    dropped. tests/test_goodput.py pins that no second private copy of
    the table exists."""
    from apex_tpu.parallel import registry
    axis = registry.scope_axis(scope)
    return axis if axis else "unknown"


def collective_bytes_by_dtype(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Collective result bytes per opcode, split per wire dtype:
    ``{opcode: {dtype: bytes}}``. The breakdown is what makes compressed
    collectives auditable — a ``compress="bf16"`` DDP step shows its
    grad traffic under ``{"all-reduce": {"bf16": ...}}`` while the
    logical gradient is fp32. Async ``-start`` halves are skipped
    (counted at the matching ``-done``)."""
    out: Dict[str, Dict[str, int]] = {}
    for prefix, dt, nbytes, _scope in _iter_collective_rows(hlo_text):
        slot = out.setdefault(prefix, {})
        slot[dt] = slot.get(dt, 0) + nbytes
    return out


def collective_bytes_by_hop(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Collective result bytes per **hop**, split per wire dtype:
    ``{"ici" | "dcn" | "unattributed": {dtype: bytes}}``.

    The hop comes from the collective's named scope (the hierarchical
    sync's ``bucketNN/ici`` / ``bucketNN/dcn`` sub-spans survive into
    the compiled program), so a hierarchical step shows its per-hop
    dtype split — int8 inside the slice, bf16-or-int8 across — while a
    flat sync reports everything ``unattributed``. This is the static
    complement of the goodput ledger's exposed-collective buckets
    (``comm_skew`` + ``comm_wire``): the ledger measures how much
    collective time a step exposed, this says which link class and
    wire dtype the bytes behind it rode."""
    out: Dict[str, Dict[str, int]] = {}
    for _prefix, dt, nbytes, scope in _iter_collective_rows(hlo_text):
        slot = out.setdefault(scope_hop(scope), {})
        slot[dt] = slot.get(dt, 0) + nbytes
    return out


def collective_bytes_by_axis(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Collective result bytes per **mesh axis**, split per wire dtype:
    ``{axis | "unknown": {dtype: bytes}}``.

    The axis comes from joining the collective's stripped scope through
    the ONE planned-collective registry
    (:func:`apex_tpu.parallel.registry.scope_axis` via
    :func:`scope_axis_row`), so a hierarchical DDP + ZeRO step splits
    its traffic into ``data_intra`` / ``data_inter`` / ``data`` rows
    while anything outside the registry — the same population APX102
    flags — lands in an explicit ``"unknown"`` row. The static
    complement of the goodput ledger's per-axis ``comm_axes_ms``
    split: the ledger says which axis's exposed time, this says which
    axis's bytes."""
    out: Dict[str, Dict[str, int]] = {}
    for _prefix, dt, nbytes, scope in _iter_collective_rows(hlo_text):
        slot = out.setdefault(scope_axis_row(scope), {})
        slot[dt] = slot.get(dt, 0) + nbytes
    return out


def wire_report(fn=None, *args, hlo_text: Optional[str] = None,
                logical_bytes: Optional[int] = None, **kwargs) -> Dict:
    """Logical-vs-wire collective accounting for one compiled step.

    ``logical_bytes`` is the uncompressed payload the step *semantically*
    moves (e.g. ``4 * n_params`` for an fp32 grad sync); the wire bytes
    come from the optimized HLO's collective result shapes. Returns::

        {"wire_bytes": int, "by_opcode": {op: {dtype: bytes}},
         "by_hop": {hop: {dtype: bytes}},
         "by_axis": {axis: {dtype: bytes}},
         "logical_bytes": int | None, "wire_to_logical": float | None}

    A bucketed+``compress="bf16"`` DDP step reports
    ``wire_to_logical ≈ 0.5`` — the number the acceptance audit pins
    (tests/test_pod_hlo.py) and the uncompressed baseline DynamiQ-style
    collectives are judged against. ``by_hop`` is the per-hop per-dtype
    split of the hierarchical schedule (``"ici"``/``"dcn"`` from the
    hop sub-span scopes; flat traffic is ``"unattributed"``) — see
    :func:`collective_bytes_by_hop`. ``by_axis`` joins each scope
    through the planned-collective registry
    (:func:`collective_bytes_by_axis`; unregistered scopes land in the
    explicit ``"unknown"`` row).
    """
    if hlo_text is None:
        if fn is None:
            raise ValueError("pass a step function or hlo_text=")
        hlo_text = _hlo.compiled_hlo(fn, *args, **kwargs)
    by_op: Dict[str, Dict[str, int]] = {}
    by_hop: Dict[str, Dict[str, int]] = {}
    by_axis: Dict[str, Dict[str, int]] = {}
    for prefix, dt, nbytes, scope in _iter_collective_rows(hlo_text):
        slot = by_op.setdefault(prefix, {})
        slot[dt] = slot.get(dt, 0) + nbytes
        slot = by_hop.setdefault(scope_hop(scope), {})
        slot[dt] = slot.get(dt, 0) + nbytes
        slot = by_axis.setdefault(scope_axis_row(scope), {})
        slot[dt] = slot.get(dt, 0) + nbytes
    wire = sum(b for per in by_op.values() for b in per.values())
    ratio = (wire / logical_bytes) if logical_bytes else None
    return {"wire_bytes": wire, "by_opcode": by_op, "by_hop": by_hop,
            "by_axis": by_axis,
            "logical_bytes": logical_bytes, "wire_to_logical": ratio}


def collective_bytes(fn=None, *args, hlo_text: Optional[str] = None,
                     **kwargs) -> Dict[str, int]:
    """Per-step collective bytes of a jittable step function.

    Either pass the step function + example args (compiled here via
    :func:`apex_tpu.prof.hlo.compiled_hlo`) or a pre-dumped optimized-HLO
    text via ``hlo_text=``.
    """
    if hlo_text is None:
        if fn is None:
            raise ValueError("pass a step function or hlo_text=")
        hlo_text = _hlo.compiled_hlo(fn, *args, **kwargs)
    return collective_bytes_from_text(hlo_text)
