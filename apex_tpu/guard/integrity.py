"""Cross-replica integrity fingerprints: prove the replication invariant.

Data parallelism's core invariant — post-sync gradients and committed
parameters are **bitwise identical** on every replica of the dp axis —
is assumed everywhere (the checkpoint layer saves one replica, apexlint
reasons over one program, the compressed collectives requantize against
it) and verified nowhere at runtime. The guard ladder only fires on
*loud* faults: a flipped mantissa bit that still reads as a plausible
finite number, or a subtle bug in a compressed multi-hop sync, silently
diverges one replica's "replicated" state — and every later step,
checkpoint and bench number is quietly wrong.

This module is the runtime proof, built from three pieces:

- **the fold** (:func:`fingerprint_tree`): each replica reduces its
  committed params (and optionally its post-sync grads) to one uint32
  scalar — every element's bit pattern is seeded with its (leaf,
  position) identity, avalanched through a 32-bit mix, and the hashed
  terms are summed mod 2³² (integer wraparound addition is exactly
  associative and commutative, so the fold is *reduction-order*-
  independent, while the avalanche makes every bit of every element
  matter: single flips, swapped elements, and compensating
  same-significance pairs — including opposite sign-bit flips, which
  a linear weighted sum provably misses — all change it);
- **the in-graph compare** (:func:`integrity_check`): every
  ``check_every`` steps the fold runs inside the jitted step and the
  scalar is compared across the dp axis with ``pmin``/``pmax`` (equal ⇔
  all replicas agree), plus an ``all_gather`` of the per-replica
  fingerprints so the host can *name* the diverged minority without
  another dispatch. Off-steps take the empty ``lax.cond`` branch —
  no fold, no collective, no host op (the
  ``integrity/no-extra-dispatch`` compile-check case pins it). The
  result is an :class:`IntegrityState` pytree carried next to
  ``GuardState``: checkpointable, donate-able, scan-carryable.
- **the repair** (:func:`make_repair_fn` over
  :func:`apex_tpu.parallel.replica_broadcast`): the policy rung *below*
  rewind — re-broadcast the majority's parameters to the diverged
  minority over the existing DDP comm (a psum of the where-selected
  **bit pattern**, integer-exact, so ``-0.0`` signs and NaN payloads
  survive), re-verify the fingerprint, leave the data cursor untouched.
  Falling through to a coordinated rewind only when no majority exists
  (all replicas disagree — the collective itself is broken, not one
  replica) or the repair re-fails.

Detection feeds :func:`apex_tpu.guard.guard_observe` via ``replica_ok``:
a failed check raises the ``A_REPLICA_DIVERGENCE`` anomaly class, which
is skip-class — the step's update (polluted through the gradient psum by
the diverged replica) never commits, on ANY replica, while the host
decides. The decision itself lives in
:meth:`apex_tpu.guard.GuardPolicy.update_integrity`.

Cadence is the knob (docs/resilience.md#integrity): ``check_every=1``
catches a divergence before any gradient sync can smear it across the
healthy majority — repair is then **bitwise-exact** (the audit's oracle
claim). A coarser cadence amortizes the scalar collectives but lets up
to ``check_every - 1`` polluted updates commit first — repair still
restores replica agreement, but the pollution stays in the trajectory;
prefer rewind there if bitwise history matters.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = [
    "IntegrityConfig", "IntegrityState", "IntegrityVote",
    "integrity_init", "integrity_check", "integrity_ok",
    "integrity_commit", "integrity_resize", "fingerprint_tree",
    "vote", "absorb_verify", "make_repair_fn", "make_verify_fn",
]

#: golden-ratio odd constant for the per-leaf mixers (any odd multiplier
#: is a bijection mod 2^32; distinct per-leaf odds keep two equal leaves
#: at different positions from folding to the same contribution)
_MIX = 0x9E3779B1


class IntegrityConfig(NamedTuple):
    """Static fingerprint configuration (hashable; safe to close over in
    jit)."""

    check_every: int = 1    #: fingerprint-compare cadence in steps.
                            #: 1 = every step (repair stays bitwise-
                            #: exact); N amortizes the scalar
                            #: collectives at up to N-1 steps of
                            #: detection latency


class IntegrityState(NamedTuple):
    """The in-graph integrity monitor: all device scalars plus one
    ``uint32[world]`` vector of per-replica fingerprints — carried
    through the jitted step next to ``GuardState`` (checkpointable,
    donate-able, ``lax.scan``-carryable). ``divergent`` describes THIS
    step only (False on off-steps); ``mismatch_count`` is cumulative
    and never reset, so a host poll at any cadence recovers every
    missed event by differencing.
    """

    step: jax.Array            # i32 observed (attempted) steps
    check_count: jax.Array     # i32 cumulative checks executed
    mismatch_count: jax.Array  # i32 cumulative checks that diverged
    divergent: jax.Array       # bool: this step's check found mismatch
    fingerprint: jax.Array     # u32 this replica's fp at the last check
    fp_min: jax.Array          # u32 cross-replica min at the last check
    fp_max: jax.Array          # u32 cross-replica max at the last check
    rank_fps: jax.Array        # u32[world] per-replica fps, last check
    last_check_step: jax.Array  # i32 step of the last executed check


def integrity_init(cfg: IntegrityConfig = IntegrityConfig(), *,
                   world: int) -> IntegrityState:
    """Fresh integrity state for a dp axis of ``world`` replicas —
    thread through the step like ``GuardState``."""
    if int(cfg.check_every) < 1:
        raise ValueError(f"IntegrityConfig.check_every must be >= 1, "
                         f"got {cfg.check_every}")
    if int(world) < 2:
        raise ValueError(f"integrity fingerprints compare across a dp "
                         f"axis — world must be >= 2, got {world}")
    z = jnp.int32(0)
    u = jnp.uint32(0)
    return IntegrityState(
        step=z, check_count=z, mismatch_count=z,
        divergent=jnp.bool_(False),
        fingerprint=u, fp_min=u, fp_max=u,
        rank_fps=jnp.zeros((int(world),), jnp.uint32),
        last_check_step=jnp.int32(-1),
    )


def _leaf_bits(x: jax.Array) -> jax.Array:
    """A leaf's raw bit pattern as (flattened-compatible) uint — the
    fold's unit. Floats AND 8-byte integers bitcast through the shared
    :func:`apex_tpu.utils.uint_view_dtype` table (8-byte types land as
    a trailing pair of uint32 lanes — both halves enter the sum, so
    int64/f64 flips in the high bits are seen); narrower ints/bools
    reinterpret into uint32 (injective for every ≤ 32-bit width)."""
    from apex_tpu.utils import uint_view_dtype
    x = jnp.asarray(x)
    if jnp.issubdtype(x.dtype, jnp.floating):
        bits = lax.bitcast_convert_type(x, uint_view_dtype(x.dtype))
        return bits.astype(jnp.uint32)
    if x.dtype == jnp.bool_:
        return x.astype(jnp.uint32)
    if jnp.issubdtype(x.dtype, jnp.integer):
        if jnp.dtype(x.dtype).itemsize > 4:
            # astype would silently truncate bits 32-63 — the exact
            # blindness a fingerprint must not have
            return lax.bitcast_convert_type(x, jnp.uint32)
        return x.astype(jnp.uint32)
    # an uncovered dtype (complex, ...) silently excluded would be a
    # hole in the very guarantee this module sells — refuse loudly
    raise TypeError(
        f"fingerprint_tree cannot fold dtype {x.dtype} bit-exactly — "
        f"a leaf this fold skipped would be undetectable (and "
        f"replica_broadcast unrepaired); exclude it from the "
        f"fingerprinted subtree explicitly or extend _leaf_bits")


def _mix32(x: jax.Array) -> jax.Array:
    """An avalanche finalizer (lowbias32 constants): every input bit
    flips ~half the output bits. This is what makes the fold's SUM
    safe: a weighted-linear fold has *structural* blind spots (a pair
    of opposite sign-bit flips contributes ±2³¹·Δw, which is ≡ 0 mod
    2³² for every even weight difference — exactly the multi-bit SDC
    class the module defends); hashing each term first leaves only
    generic ~2⁻³² collisions, no class of corruption that cancels by
    construction."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def fingerprint_tree(tree) -> jax.Array:
    """Reduction-order-independent, position-sensitive uint32
    fingerprint of a pytree's bit content.

    Each element's raw bit pattern is XOR-seeded with its GLOBAL lane
    position across the whole flattened tree (an injective identity —
    a per-leaf (index, offset) pair would form overlapping arithmetic
    progressions whose aliased seeds let an exact cross-leaf element
    exchange cancel), avalanched through :func:`_mix32`, and the
    hashed terms are summed with uint32 wraparound — addition mod 2³²
    is exactly associative/commutative, so the *reduction order*
    cannot change the result (the property that makes the scalar
    comparable across replicas regardless of per-device scheduling),
    while the per-term avalanche makes every bit of every element
    matter: single flips, swapped elements (within or across leaves),
    and compensating flips at any significance (including opposite
    sign-bit pairs, which a linear weighted sum provably misses) all
    change the fold. Identities stay injective up to 2³² lanes (≈4.3 B
    fp32 params per fingerprinted tree — shard the fold before that);
    what remains below the bound is the generic ~2⁻³² hash-collision
    floor, with no corruption class that cancels by construction.
    Pure local ``jnp`` — the cross-replica compare is
    :func:`integrity_check`'s job."""
    fp = jnp.uint32(0)
    offset = 0            # global lane offset — static at trace time
    for leaf in jax.tree_util.tree_leaves(tree):
        bits = _leaf_bits(leaf)
        if bits.size == 0:
            continue
        flat = jnp.reshape(bits, (-1,))
        gpos = (jnp.arange(flat.size, dtype=jnp.uint32)
                + jnp.uint32(offset & 0xFFFFFFFF))
        # odd multiplier = bijection: distinct lanes, distinct seeds
        fp = fp + jnp.sum(_mix32(flat ^ (gpos * jnp.uint32(_MIX))),
                          dtype=jnp.uint32)
        offset += int(flat.size)
    return fp


def integrity_check(ist: IntegrityState, cfg: IntegrityConfig, params,
                    *, axis_name, grads=None) -> IntegrityState:
    """Observe one step: fold + cross-replica compare every
    ``cfg.check_every`` steps, advance counters. Call inside the
    ``shard_map``-ped step, on the COMMITTED params the step started
    from (divergence is a property of state, not of this update);
    pass the post-sync ``grads`` too to additionally prove the
    gradient collective (the compressed-sync runtime proof —
    identical synced grads are part of the invariant).

    Off-steps take the empty ``lax.cond`` branch: no fold, no
    collective (``check_every=1`` skips the cond entirely). The
    collectives run under the registered ``guard/integrity_check``
    scope, so apexlint APX102/APX202 stay clean.
    """
    from apex_tpu.trace.spans import span as _span

    world = ist.rank_fps.shape[0]
    subject = params if grads is None else (params, grads)

    def _do(s: IntegrityState) -> IntegrityState:
        with _span("guard/integrity_check", kind="collective"):
            fp = fingerprint_tree(subject)
            mn = lax.pmin(fp, axis_name)
            mx = lax.pmax(fp, axis_name)
            fps = jnp.reshape(lax.all_gather(fp, axis_name), (world,))
        div = mn != mx
        return s._replace(
            fingerprint=fp, fp_min=mn, fp_max=mx, rank_fps=fps,
            divergent=div,
            check_count=s.check_count + 1,
            mismatch_count=(s.mismatch_count
                            + jnp.where(div, 1, 0).astype(jnp.int32)),
            last_check_step=s.step)

    def _skip(s: IntegrityState) -> IntegrityState:
        return s._replace(divergent=jnp.bool_(False))

    if int(cfg.check_every) <= 1:
        new = _do(ist)
    else:
        new = lax.cond((ist.step % cfg.check_every) == 0, _do, _skip,
                       ist)
    return new._replace(step=ist.step + 1)


def integrity_ok(ist: IntegrityState) -> jax.Array:
    """Commit predicate: True unless THIS step's check found a
    divergence. Feed it to ``guard_observe(replica_ok=...)`` (the
    ``A_REPLICA_DIVERGENCE`` skip class then vetoes the commit through
    ``guard_commit``), or to :func:`integrity_commit` on guard-less
    steps."""
    return jnp.logical_not(ist.divergent)


def integrity_commit(ist: IntegrityState, new_tree, old_tree):
    """Commit ``new_tree`` unless this step's integrity check failed —
    the polluted update (the diverged replica's gradients entered the
    psum) must not commit anywhere while the host decides repair vs
    rewind. Redundant when the step already routes the veto through
    ``guard_observe(replica_ok=...)`` + ``guard_commit``."""
    from apex_tpu.utils import tree_select
    return tree_select(integrity_ok(ist), new_tree, old_tree)


# -- the host half: quorum vote + repair programs ------------------------------

class IntegrityVote(NamedTuple):
    """The host-side quorum verdict over one check's gathered
    per-replica fingerprints (replicated by construction — every host
    computes the same vote from the same vector)."""

    has_majority: bool            #: a strict majority (> world/2)
                                  #: agrees on one fingerprint
    source_rank: Optional[int]    #: lowest-numbered majority replica
                                  #: (the repair broadcast source), or
                                  #: None without a majority
    minority: Tuple[int, ...]     #: replicas whose fingerprint differs
                                  #: from the majority's (empty without
                                  #: a majority — nobody can be named)
    majority_fp: Optional[int]    #: the agreed fingerprint, or None
    n_ranks: int                  #: electorate size (the dp world)


def vote(rank_fps) -> IntegrityVote:
    """Name the diverged minority from the gathered fingerprints.

    A strict majority (> world/2 replicas sharing one fingerprint)
    distinguishes "one bad replica" (repairable in place) from "the
    collective itself is broken" (every replica disagrees, or a tie —
    there is no trustworthy source to broadcast from, fall through to
    the coordinated rewind). Ranks here are dp-axis replica indices
    (``lax.axis_index`` order), not process ranks."""
    import numpy as np
    fps = [int(v) for v in np.asarray(rank_fps).reshape(-1)]
    n = len(fps)
    counts: dict = {}
    for fp in fps:
        counts[fp] = counts.get(fp, 0) + 1
    best_fp, best_n = max(counts.items(), key=lambda kv: (kv[1], -kv[0]))
    if best_n * 2 <= n:
        return IntegrityVote(False, None, (), None, n)
    minority = tuple(r for r, fp in enumerate(fps) if fp != best_fp)
    source = min(r for r, fp in enumerate(fps) if fp == best_fp)
    return IntegrityVote(True, source, minority, best_fp, n)


def _axis_world(mesh, axis_name) -> int:
    names = (axis_name,) if isinstance(axis_name, str) else \
        tuple(axis_name)
    world = 1
    for a in names:
        world *= mesh.shape[a]
    return world


def make_verify_fn(mesh, axis_name):
    """A jitted ``tree -> (fp_min, fp_max, rank_fps)`` over ``mesh`` —
    the host's standalone fingerprint compare (repair re-verification,
    post-restore hygiene). Compiled on first use; a rare-path program,
    never part of the step."""
    world = _axis_world(mesh, axis_name)

    def _verify(tree):
        from apex_tpu.trace.spans import span as _span
        with _span("guard/integrity_check", kind="collective"):
            fp = fingerprint_tree(tree)
            return (lax.pmin(fp, axis_name), lax.pmax(fp, axis_name),
                    jnp.reshape(lax.all_gather(fp, axis_name), (world,)))

    return jax.jit(jax.shard_map(
        _verify, mesh=mesh, in_specs=(P(),),
        out_specs=(P(), P(), P()), check_vma=False))


def integrity_resize(ist: IntegrityState, *,
                     world: int) -> IntegrityState:
    """Re-shape a (restored) IntegrityState for a different dp world —
    the elastic-resume hook. A checkpointed state bakes the old
    electorate into ``rank_fps``; resumed onto a different mesh size
    (the :mod:`apex_tpu.ckpt` elastic flow), the first
    :func:`integrity_check` would gather a different-length vector and
    fail at trace time — and a stale vector would vote with the wrong
    electorate even if it didn't. Cumulative counters are *history*
    and survive; the per-replica vector and the last-check transients
    describe replicas that no longer exist and are re-initialized.
    Same-world states pass through untouched, so the call is safe
    unconditionally after every restore::

        restored, mf = mgr.restore(like)
        ist = guard.integrity_resize(restored["ist"],
                                     world=mesh.shape["data"])
    """
    if int(world) < 2:
        raise ValueError(f"integrity fingerprints compare across a dp "
                         f"axis — world must be >= 2, got {world}")
    if int(world) == int(ist.rank_fps.shape[0]):
        return ist
    u = jnp.uint32(0)
    return ist._replace(
        rank_fps=jnp.zeros((int(world),), jnp.uint32),
        divergent=jnp.bool_(False),
        fingerprint=u, fp_min=u, fp_max=u,
        last_check_step=jnp.int32(-1))


def absorb_verify(ist: IntegrityState, fp_min, fp_max,
                  rank_fps) -> IntegrityState:
    """Fold a host-side re-verification result (the
    :func:`make_verify_fn` output a successful
    :meth:`~apex_tpu.guard.GuardPolicy.repair` produced — kept on
    ``policy.last_verify``) back into the carried state::

        params, ok = policy.repair(step, params, repair_fn=rf,
                                   verify_fn=vf)
        ist = guard.absorb_verify(ist, *policy.last_verify)

    Without this, a checkpoint taken on the repair step freezes the
    DETECTION-time fields — disagreeing ``rank_fps`` next to a nonzero
    cumulative ``mismatch_count`` — and a restart's fresh policy would
    replay the stale vote and fire a spurious repair on
    already-agreeing replicas. Counters are cumulative history and
    stay untouched."""
    fps = jnp.asarray(rank_fps, jnp.uint32)
    return ist._replace(
        divergent=jnp.bool_(False),
        fingerprint=jnp.asarray(fp_min, jnp.uint32),
        fp_min=jnp.asarray(fp_min, jnp.uint32),
        fp_max=jnp.asarray(fp_max, jnp.uint32),
        rank_fps=jnp.reshape(fps, ist.rank_fps.shape))


def make_repair_fn(mesh, axis_name):
    """A jitted ``(tree, source_rank) -> tree`` over ``mesh`` — the
    in-place repair: every replica's buffers are overwritten with the
    ``source_rank`` replica's exact bits via
    :func:`apex_tpu.parallel.replica_broadcast` (a psum of the
    where-selected bit pattern over the existing DDP comm, under the
    registered ``guard/integrity_repair`` scope). The majority's
    buffers are rewritten with their own bits — a no-op there, the fix
    on the minority. The data cursor is untouched by construction:
    repair is state surgery, not time travel."""
    def _repair(tree, src):
        from apex_tpu.parallel.distributed import replica_broadcast
        from apex_tpu.trace.spans import span as _span
        with _span("guard/integrity_repair", kind="collective"):
            return replica_broadcast(tree, axis_name, source=src)

    return jax.jit(jax.shard_map(
        _repair, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False))
