#!/usr/bin/env python
"""Regenerate tests/fixtures/resnet_step.xplane.pb.

A miniature XSpace trace shaped exactly like an on-chip
``jax.profiler.trace`` capture of one ResNet O2 step (device plane
"/device:TPU:0" with "XLA Modules" + "XLA Ops" lines, per-op HLO
metadata carrying fusion kinds and named-scope paths, plus a host plane
the parser must skip). Written with a pure-stdlib protobuf encoder —
regenerating the fixture needs no tensorflow, and
``tests/test_prof.py::TestXplaneFixture`` pins the decoded per-op table
against the values below, so a parser regression surfaces in CI instead
of only on-chip.

The op set is a faithful miniature of a real v5e capture's shape
(mega-fusions dominating, one conv, one all-reduce, a copy) with
hand-chosen durations — small enough to commit, rich enough to exercise
opcode extraction, fusion-kind categories, collective classification,
scope attribution, and occurrence aggregation.

Usage: python scripts/make_xplane_fixture.py [OUT.pb]
"""

import os
import sys


# --- minimal protobuf encoder (wire format) ----------------------------------

def _uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def field_varint(fno: int, v: int) -> bytes:
    return _uvarint(fno << 3 | 0) + _uvarint(v)


def field_bytes(fno: int, v: bytes) -> bytes:
    return _uvarint(fno << 3 | 2) + _uvarint(len(v)) + v


def field_str(fno: int, s: str) -> bytes:
    return field_bytes(fno, s.encode())


# --- XSpace schema subset (field numbers per the tsl xplane proto) -----------

def event(metadata_id: int, duration_ps: int, offset_ps: int = 0) -> bytes:
    return (field_varint(1, metadata_id) + field_varint(2, offset_ps)
            + field_varint(3, duration_ps))


def line(name: str, events) -> bytes:
    body = field_str(2, name)
    for ev in events:
        body += field_bytes(4, ev)
    return body


def event_metadata(mid: int, name: str) -> bytes:
    return field_varint(1, mid) + field_str(2, name)


def plane(name: str, lines=(), metadata=()) -> bytes:
    body = field_str(2, name)
    for l in lines:
        body += field_bytes(3, l)
    for mid, md in metadata:
        body += field_bytes(4, field_varint(1, mid) + field_bytes(2, md))
    return body


def xspace(planes) -> bytes:
    return b"".join(field_bytes(1, p) for p in planes)


# --- the fixture content -----------------------------------------------------

#: (metadata_id, HLO text, [duration_us per occurrence]) — the pinned
#: per-op table lives in tests/test_prof.py; keep the two in lockstep.
OPS = [
    (10, '%fusion.31 = bf16[64,14,14,256]{3,2,1,0:T(8,128)(2,1)} '
         'fusion(bf16[64,14,14,256]{3,2,1,0} %p0, bf16[256]{0} %p1), '
         'kind=kOutput, calls=%fused_computation.31, '
         'metadata={op_name="jit(step)/jvp(amp/fwd)/stage3/bn_relu"}',
     [93.0, 91.5]),
    (11, '%convolution.7 = bf16[64,14,14,256]{3,2,1,0:T(8,128)(2,1)} '
         'convolution(bf16[64,14,14,256]{3,2,1,0} %x, '
         'bf16[3,3,256,256]{3,2,1,0} %w), window={size=3x3 pad=1_1x1_1}, '
         'dim_labels=b01f_01io->b01f, '
         'metadata={op_name="jit(step)/jvp(amp/fwd)/stage3/conv"}',
     [74.2, 73.8]),
    (12, '%all-reduce.3 = f32[524288]{0:T(1024)} all-reduce('
         'f32[524288]{0} %grads), replica_groups={{0,1,2,3,4,5,6,7}}, '
         'to_apply=%sum, metadata={op_name='
         '"jit(step)/ddp/sync_gradients/bucket00/psum"}',
     [41.0]),
    (13, '%fusion.88 = (f32[1024]{0}, f32[1024]{0}) fusion('
         'bf16[64,14,14,1024]{3,2,1,0} %dz), kind=kInput, '
         'calls=%fused_computation.88, metadata={op_name='
         '"jit(step)/transpose(jvp(amp/fwd))/stage3/bn_bwd_sums"}',
     [49.7, 50.3]),
    (14, '%copy.5 = bf16[64,56,56,64]{3,2,1,0:T(8,128)(2,1)} '
         'copy(bf16[64,56,56,64]{1,3,2,0} %p4)',
     [12.5]),
    (15, '%custom-call.9 = bf16[64,512,8,64]{3,2,1,0} custom-call('
         'bf16[64,512,8,64]{3,2,1,0} %q), custom_call_target='
         '"tpu_custom_call", metadata={op_name='
         '"jit(step)/jvp(amp/fwd)/attn/flash_attention"}',
     [31.0]),
]

MODULE_RUNS = [990.0, 1010.0]     # us — two steps captured


def build() -> bytes:
    md = [(1, event_metadata(1, "jit_step(1234)"))]
    op_events = []
    t = 0
    for mid, hlo, durs in OPS:
        md.append((mid, event_metadata(mid, hlo)))
        for d in durs:
            op_events.append(event(mid, int(d * 1e6), offset_ps=t))
            t += int(d * 1e6)
    mod_events = [event(1, int(d * 1e6), offset_ps=i * 10 ** 9)
                  for i, d in enumerate(MODULE_RUNS)]
    device = plane("/device:TPU:0",
                   lines=[line("XLA Modules", mod_events),
                          line("XLA Ops", op_events)],
                   metadata=md)
    host = plane("/host:CPU",
                 lines=[line("python", [event(1, 5_000_000)])],
                 metadata=[(1, event_metadata(1, "hostloop"))])
    return xspace([host, device])


def main() -> int:
    out = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests", "fixtures", "resnet_step.xplane.pb")
    data = build()
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "wb") as f:
        f.write(data)
    print(f"wrote {out} ({len(data)} bytes, {len(OPS)} ops, "
          f"{len(MODULE_RUNS)} module runs)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
