"""The guard policy ladder: skip → backoff → repair → rewind → escalate.

The in-graph half (:mod:`apex_tpu.guard.detect`) already *acted* on the
common case before the host ever sees it: skip-class anomalies never
commit and the LR backs off, all inside the step program. This module is
the host-side escalation on top — the rungs that need the checkpoint
manager and the data pipeline:

1. **skip / backoff** (in-graph, observed here): each new anomaly is
   emitted as a ``guard_anomaly`` event; the in-graph veto is reported
   as a ``guard_action`` with ``action="skip"``.
1b. **repair** (`update_integrity` / `repair`) — the rung *below*
   rewind, for the silent-divergence class
   (:mod:`apex_tpu.guard.integrity`): when the cross-replica
   fingerprint check names a diverged minority and a strict majority
   still agrees, the minority's parameters are re-broadcast in place
   from the lowest-numbered majority replica (bit-exact, over the
   registered DDP comm), the fingerprint re-verified, and training
   continues — **no checkpoint restore, cursor untouched**. Only when
   no majority exists (every replica disagrees — the collective
   itself is suspect, not one replica) or the repair re-fails does
   the incident fall through to rung 2, via the cluster's
   :class:`~apex_tpu.cluster.RecoveryCoordinator` when one is wired.
2. **rewind** — when the committed state itself is corrupt
   (nonfinite-param class) or skipping stopped converging (more than
   ``skip_budget`` skips inside a ``skip_window``-step window): restore
   the last *good* snapshot via :class:`apex_tpu.ckpt.CheckpointManager`
   and fast-forward the exact :mod:`apex_tpu.data.pipeline` cursor past
   the offending window, so the resumed run is bitwise-equal to a run
   that never saw those batches (``scripts/chaos_audit.py`` asserts
   this). Snapshots whose params are non-finite, or whose files fail the
   manifest hash (a truncated/corrupted checkpoint), are rejected and
   the policy falls back to the next-older committed checkpoint.
3. **escalate** — the rewind budget is exhausted (or no loadable
   checkpoint exists): hand off to the existing
   :class:`apex_tpu.ckpt.EscalationPolicy` (checkpoint + crash dump +
   exit 75), the same path the hang watchdog takes.

Hysteresis: a ``cooldown_steps`` window after each rewind during which
the skip-budget accounting restarts from zero — one rough patch of data
must not chain-rewind; rewind-class (state-corruption) anomalies are
exempt, because waiting cannot un-corrupt params.

Every decision is a ``guard`` JSONL event
(``check_metrics_schema.py --kind guard``); wire
``MetricsLogger(guard_sink=...)`` via ``event_sink=logger.record_guard``
and a :class:`apex_tpu.trace.FlightRecorder` via ``recorder=`` so crash
dumps carry the recent interventions.

The per-step host poll (`update`) fetches a handful of scalars from the
``GuardState`` — with JAX's async dispatch this rides the sync the loss
read already forces. ``poll_every=N`` amortizes it further: the in-graph
skip/backoff protection is always on regardless of polling, and the
cumulative counters let a coarse poll recover every missed event; the
only cost of coarser polling is rewind latency (≤ N extra steps inside
the offending window).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import numpy as np

from apex_tpu.guard.detect import (REWIND_MASK, SKIP_MASK, GuardState,
                                   anomaly_classes)

__all__ = ["GuardPolicy", "GuardAction", "GuardEscalation"]


class GuardAction(NamedTuple):
    """One `update` / `update_integrity` verdict. ``kind`` ∈ none |
    skip | repair | rewind | escalate (observe-only policies report
    what they *would* do in ``reason`` but always return
    kind="none")."""
    kind: str
    step: int
    classes: Tuple[str, ...] = ()
    reason: str = ""


class GuardEscalation(RuntimeError):
    """Raised by `escalate` when no :class:`~apex_tpu.ckpt.EscalationPolicy`
    is wired — the guard refuses to train on irrecoverable state."""


def _rank() -> int:
    import jax
    try:
        return jax.process_index()
    except Exception:
        import os
        return int(os.environ.get("RANK", "0"))


class GuardPolicy:
    """See the module docstring.

    ``manager`` is a :class:`apex_tpu.ckpt.CheckpointManager` (required
    for the rewind rung); ``escalation`` an
    :class:`apex_tpu.ckpt.EscalationPolicy` (required for the final
    rung — without one, `escalate` raises :class:`GuardEscalation`).
    ``observe_only=True`` turns the policy into a pure witness: every
    event is still emitted, no action is ever taken and `update` never
    asks for one — the zero-intervention contract the chaos audit's
    clean-run check and the ``guard/no-extra-dispatch`` compile check
    both pin.
    """

    def __init__(self, *, manager=None, escalation=None,
                 event_sink: Optional[Callable[[Dict], None]] = None,
                 integrity_sink: Optional[Callable[[Dict], None]] = None,
                 recorder=None, observe_only: bool = False,
                 rewind_budget: int = 2, skip_budget: int = 4,
                 skip_window: int = 32, cooldown_steps: int = 16,
                 poll_every: int = 1,
                 generation: Optional[Callable[[], int]] = None):
        self.manager = manager
        self.escalation = escalation
        self.event_sink = event_sink
        #: the ``integrity`` event channel (kind="integrity_check"/
        #: "integrity_vote"/"integrity_repair" — validate with
        #: ``check_metrics_schema.py --kind integrity``); wire
        #: ``MetricsLogger(integrity_sink=...).record_integrity``.
        #: Separate from ``event_sink`` because the two channels carry
        #: different schemas.
        self.integrity_sink = integrity_sink
        #: callable returning the cluster's committed generation
        #: (``membership.refresh``) — integrity events are fenced with
        #: it when wired (null otherwise), so a zombie's late repair
        #: claim is attributable in the forensic stream
        self.generation = generation
        self.recorder = recorder
        self.observe_only = bool(observe_only)
        self.rewind_budget = int(rewind_budget)
        self.skip_budget = int(skip_budget)
        self.skip_window = int(skip_window)
        self.cooldown_steps = int(cooldown_steps)
        self.poll_every = max(int(poll_every), 1)
        self.rank = _rank()
        #: rewinds performed so far (the budget's odometer)
        self.rewinds_done = 0
        #: loop step below which skip-budget accounting is suspended
        self.cooldown_until = -1
        self._skip_steps: list = []      # loop steps of recent skips
        self._prev: Optional[Dict[str, int]] = None
        self._last_poll = -1
        #: in-place repairs performed (the integrity rung's odometer)
        self.repairs_done = 0
        #: the last mismatch's quorum verdict (integrity.IntegrityVote)
        #: — kept for forensics; `repair` consumes the ARMED flag, so
        #: a stale verdict from a previous incident can never drive a
        #: broadcast
        self.last_vote = None
        self._vote_armed = False
        #: (fp_min, fp_max, rank_fps) of the last repair's
        #: re-verification — feed ``guard.absorb_verify(ist,
        #: *policy.last_verify)`` so the carried IntegrityState (and
        #: any checkpoint taken this step) reflects the POST-repair
        #: agreement instead of the detection-time disagreement
        self.last_verify = None
        self._iprev: Optional[Dict[str, int]] = None
        self._last_ipoll = -1
        #: (step, like, tree, manifest) of the last probe_good_step
        #: winner — rewind() reuses it when the agreed target IS this
        #: rank's own good step (the healthy-majority case), halving
        #: the shared-fs read traffic of a coordinated recovery round
        self._probe_cache: Optional[tuple] = None

    # -- events ----------------------------------------------------------------

    def _emit_to(self, sink, event: Dict) -> None:
        """The one event-hygiene pipeline both channels share: stamp
        rank + wall time, null non-finite gauges (strict-JSON contract
        — the crash-dump ring serializes these verbatim), note the
        flight recorder, deliver to ``sink`` — and telemetry must
        never break recovery, so every consumer failure is
        swallowed."""
        ev = dict(event, rank=self.rank, wall_time=time.time())
        for k, v in ev.items():
            if isinstance(v, float) and not np.isfinite(v):
                ev[k] = None
        if self.recorder is not None:
            try:
                self.recorder.note_guard(ev)
            except Exception:
                pass
        if sink is None:
            return
        try:
            sink(ev)
        except Exception:
            pass

    def _emit(self, event: Dict) -> None:
        self._emit_to(self.event_sink, event)

    # -- the per-step poll ------------------------------------------------------

    @staticmethod
    def _fetch(gs: GuardState) -> Dict[str, float]:
        """One small host fetch of the policy-relevant scalars."""
        import jax
        vals = jax.device_get((
            gs.anomaly, gs.z, gs.lr_scale, gs.consecutive,
            gs.skip_count, gs.spike_count, gs.grad_explosion_count,
            gs.nonfinite_grad_count, gs.nonfinite_loss_count,
            gs.nonfinite_param_count, gs.replica_divergence_count,
            gs.step))
        keys = ("anomaly", "z", "lr_scale", "consecutive", "skip_count",
                "spike_count", "grad_explosion_count",
                "nonfinite_grad_count", "nonfinite_loss_count",
                "nonfinite_param_count", "replica_divergence_count",
                "step")
        return {k: (float(v) if k in ("z", "lr_scale") else int(v))
                for k, v in zip(keys, vals)}

    def update(self, step: int, gs: GuardState) -> GuardAction:
        """Poll the guard state after loop step ``step`` and decide.

        Returns the ladder verdict; the CALLER performs the returned
        action (`rewind`/`escalate`) — the policy never mutates training
        state behind the loop's back. ``kind="skip"`` is informational:
        the in-graph veto already protected the state.
        """
        step = int(step)
        if (step - self._last_poll) < self.poll_every and step != 0:
            return GuardAction("none", step)
        self._last_poll = step
        cur = self._fetch(gs)
        prev = self._prev or {k: 0 for k in cur}
        self._prev = cur

        # new-event deltas since the last poll (counters are cumulative,
        # so a poll_every > 1 cadence still sees every event)
        deltas = {k: cur[k] - prev.get(k, 0)
                  for k in ("skip_count", "spike_count",
                            "grad_explosion_count", "nonfinite_grad_count",
                            "nonfinite_loss_count",
                            "nonfinite_param_count",
                            "replica_divergence_count")}
        new_any = any(v > 0 for v in deltas.values())
        classes = tuple(
            name for key, name in (
                ("spike_count", "loss_spike"),
                ("grad_explosion_count", "grad_explosion"),
                ("nonfinite_grad_count", "nonfinite_grad"),
                ("nonfinite_loss_count", "nonfinite_loss"),
                ("nonfinite_param_count", "nonfinite_param"),
                ("replica_divergence_count", "replica_divergence"))
            if deltas[key] > 0)
        if not new_any:
            return GuardAction("none", step)

        self._emit({"kind": "guard_anomaly", "step": step,
                    "classes": list(classes),
                    "z": cur["z"], "lr_scale": cur["lr_scale"],
                    "consecutive": cur["consecutive"],
                    "skip_count": cur["skip_count"]})

        # ladder: rewind-class corruption, or skip budget exhausted
        want_rewind = deltas["nonfinite_param_count"] > 0
        reason = "nonfinite_param" if want_rewind else ""
        if deltas["skip_count"] > 0:
            in_cooldown = step < self.cooldown_until
            # one entry PER skip, not per poll — a coarse poll_every
            # must not undercount a storm of skips into never reaching
            # the budget. Skips during the cooldown are NOT recorded:
            # "accounting restarts from zero" means the rough patch the
            # rewind just handled cannot be banked toward an immediate
            # chain-rewind the moment the cooldown expires
            if not in_cooldown:
                self._skip_steps.extend(
                    [step] * int(deltas["skip_count"]))
                self._skip_steps = [s for s in self._skip_steps
                                    if s > step - self.skip_window]
            if (not want_rewind and not in_cooldown
                    and len(self._skip_steps) > self.skip_budget):
                want_rewind = True
                reason = (f"skip_budget: {len(self._skip_steps)} skips "
                          f"in {self.skip_window} steps")

        if want_rewind:
            if self.observe_only:
                self._emit({"kind": "guard_action", "step": step,
                            "action": "observe", "classes": list(classes),
                            "reason": f"would rewind ({reason})"})
                return GuardAction("none", step, classes, reason)
            if self.rewinds_done >= self.rewind_budget:
                self._emit({"kind": "guard_action", "step": step,
                            "action": "escalate",
                            "classes": list(classes),
                            "reason": f"rewind budget exhausted "
                                      f"({self.rewinds_done}/"
                                      f"{self.rewind_budget}); {reason}"})
                return GuardAction("escalate", step, classes, reason)
            self._emit({"kind": "guard_action", "step": step,
                        "action": "rewind", "classes": list(classes),
                        "reason": reason})
            return GuardAction("rewind", step, classes, reason)

        act = "observe" if self.observe_only else "skip"
        self._emit({"kind": "guard_action", "step": step, "action": act,
                    "classes": list(classes),
                    "reason": f"in-graph skip; lr_scale="
                              f"{cur['lr_scale']:.4g}"})
        return GuardAction("none" if self.observe_only else "skip",
                           step, classes)

    # -- the integrity rung: vote + in-place repair ----------------------------

    def _emit_integrity(self, event: Dict) -> None:
        """Like `_emit`, but onto the integrity channel — every event
        fenced with the cluster generation when one is wired (null
        otherwise)."""
        gen = None
        if self.generation is not None:
            try:
                gen = int(self.generation())
            except Exception:
                gen = None
        self._emit_to(self.integrity_sink, dict(event, generation=gen))

    @staticmethod
    def _fetch_integrity(ist) -> Dict[str, int]:
        """One small host fetch of the integrity scalars (the
        per-replica fingerprint vector is fetched only on mismatch)."""
        import jax
        vals = jax.device_get((
            ist.step, ist.check_count, ist.mismatch_count,
            ist.last_check_step, ist.fp_min, ist.fp_max))
        keys = ("step", "check_count", "mismatch_count",
                "last_check_step", "fp_min", "fp_max")
        return {k: int(v) for k, v in zip(keys, vals)}

    def update_integrity(self, step: int, ist) -> GuardAction:
        """Poll the :class:`~apex_tpu.guard.IntegrityState` after loop
        step ``step`` and decide the silent-divergence response.

        On a new mismatch (cumulative ``mismatch_count`` moved since
        the last poll — a coarse ``poll_every`` cadence still sees
        every incident) the gathered per-replica fingerprints are
        fetched and put to a quorum vote
        (:func:`apex_tpu.guard.integrity.vote`):

        - a strict majority → ``kind="repair"`` naming the diverged
          minority and the broadcast source (the caller runs
          :meth:`repair`, NO checkpoint is touched);
        - no majority (all replicas disagree, or a tie) → the
          collective itself is suspect; ``kind="rewind"`` — route it
          through the :class:`~apex_tpu.cluster.RecoveryCoordinator`
          (or :meth:`rewind` directly on a single host).

        Like `update`, the policy only *decides*; the caller acts.
        Every decision lands on the integrity channel
        (``integrity_check`` + ``integrity_vote`` events)."""
        step = int(step)
        if (step - self._last_ipoll) < self.poll_every and step != 0:
            return GuardAction("none", step)
        self._last_ipoll = step
        cur = self._fetch_integrity(ist)
        prev = self._iprev or {k: 0 for k in cur}
        self._iprev = cur
        new_mismatches = cur["mismatch_count"] - prev.get(
            "mismatch_count", 0)
        if new_mismatches <= 0:
            return GuardAction("none", step)
        # -1 = "no check since init/resize" (the elastic-resume
        # sentinel) — null on the wire, never a negative counter
        check_step = (cur["last_check_step"]
                      if cur["last_check_step"] >= 0 else None)

        import jax
        from apex_tpu.guard import integrity as _integrity
        rank_fps = jax.device_get(ist.rank_fps)
        v = _integrity.vote(rank_fps)
        if v.has_majority and not v.minority:
            # the gathered fingerprints all AGREE: the cumulative
            # counter moved but the divergence is already healed — a
            # transient incident whose later checks re-converged
            # before this poll, or the first poll of a fresh policy
            # over a restored IntegrityState whose mismatch_count
            # predates the restart. A repair with nobody to repair
            # would be noise, but the DETECTION is still forensic
            # record: emit the check event (flagged healed, no vote)
            # and stay quiet.
            self._emit_integrity({
                "kind": "integrity_check", "step": step,
                "check_step": check_step,
                "n_ranks": v.n_ranks,
                "mismatch_count": cur["mismatch_count"],
                "new_mismatches": int(new_mismatches),
                "fp_min": cur["fp_min"], "fp_max": cur["fp_max"],
                "healed": True})
            return GuardAction("none", step)
        self.last_vote = v
        self._emit_integrity({
            "kind": "integrity_check", "step": step,
            "check_step": check_step,
            "n_ranks": v.n_ranks,
            "mismatch_count": cur["mismatch_count"],
            "new_mismatches": int(new_mismatches),
            "fp_min": cur["fp_min"], "fp_max": cur["fp_max"]})
        classes = ("replica_divergence",)
        if self.observe_only:
            reason = ("would repair" if v.has_majority
                      else "would rewind (no majority)")
            self._emit_integrity({
                "kind": "integrity_vote", "step": step,
                "action": "observe", "n_ranks": v.n_ranks,
                "minority": list(v.minority),
                "source_rank": v.source_rank,
                "majority_fp": v.majority_fp, "reason": reason})
            return GuardAction("none", step, classes, reason)
        if v.has_majority:
            reason = (f"minority {list(v.minority)} diverged from "
                      f"{v.n_ranks - len(v.minority)}-replica majority")
            self._emit_integrity({
                "kind": "integrity_vote", "step": step,
                "action": "repair", "n_ranks": v.n_ranks,
                "minority": list(v.minority),
                "source_rank": v.source_rank,
                "majority_fp": v.majority_fp, "reason": reason})
            self._vote_armed = True
            return GuardAction("repair", step, classes, reason)
        if self.rewinds_done >= self.rewind_budget:
            # same terminal rung update() enforces for the guard
            # ladder's rewind classes: a deterministic fault that
            # re-diverges after every restore must not loop
            # restore→diverge forever — hand it to the operator
            reason = (f"no majority fingerprint across {v.n_ranks} "
                      f"replicas AND rewind budget exhausted "
                      f"({self.rewinds_done}/{self.rewind_budget})")
            self._emit_integrity({
                "kind": "integrity_vote", "step": step,
                "action": "escalate", "n_ranks": v.n_ranks,
                "minority": list(v.minority), "source_rank": None,
                "majority_fp": None, "reason": reason})
            return GuardAction("escalate", step, classes, reason)
        reason = (f"no majority fingerprint across {v.n_ranks} "
                  f"replicas — the collective itself is suspect; "
                  f"falling through to coordinated rewind")
        self._emit_integrity({
            "kind": "integrity_vote", "step": step,
            "action": "rewind", "n_ranks": v.n_ranks,
            "minority": list(v.minority), "source_rank": None,
            "majority_fp": None, "reason": reason})
        return GuardAction("rewind", step, classes, reason)

    def repair(self, step: int, tree, *, repair_fn, verify_fn,
               reason: str = "") -> Tuple[Any, bool]:
        """Execute the in-place repair `update_integrity` decided.

        ``repair_fn``/``verify_fn`` are the mesh-bound programs from
        :func:`apex_tpu.guard.integrity.make_repair_fn` /
        :func:`make_verify_fn` (the policy never owns a mesh). The
        minority replica's buffers are overwritten with the majority
        source's exact bits, then the fingerprint is re-verified
        before anyone trains on the result. Returns
        ``(repaired_tree, verified)`` — on ``verified=False`` the
        caller MUST fall through to the rewind rung (the audit pins
        this ladder), and the repaired tree should be discarded. On
        success, fold the re-verification into the carried state
        before the next checkpoint — ``ist = guard.absorb_verify(ist,
        *policy.last_verify)`` — so a snapshot taken this step records
        the post-repair agreement, not the detection-time
        disagreement.

        The checkpoint manager and the data cursor are untouched by
        construction: repair is state surgery on the current step, not
        time travel."""
        import jax
        import jax.numpy as jnp
        v = self.last_vote
        if v is None or not v.has_majority or not self._vote_armed:
            raise ValueError(
                "repair called without a FRESH majority vote — "
                "update_integrity must decide immediately before each "
                "repair (a stale verdict from a previous incident "
                "must never choose the broadcast source)")
        self._vote_armed = False     # one vote drives at most one repair
        repaired = repair_fn(tree, jnp.int32(v.source_rank))
        mn, mx, fps = verify_fn(repaired)
        self.last_verify = (mn, mx, fps)
        ok = int(jax.device_get(mn)) == int(jax.device_get(mx))
        if ok:
            self.repairs_done += 1
        self._emit_integrity({
            "kind": "integrity_repair", "step": int(step),
            "action": "repair" if ok else "repair_failed",
            "source_rank": v.source_rank,
            "minority": list(v.minority), "verified": bool(ok),
            "reason": reason or None})
        return repaired, ok

    # -- rewind -----------------------------------------------------------------

    @staticmethod
    def _params_finite(tree) -> bool:
        """Every float leaf finite — EXCEPT inside GuardState nodes,
        whose rolling windows use NaN as the empty-slot marker by
        design (a checkpointed young guard would otherwise read as
        corruption and torpedo every rewind)."""
        import jax
        for leaf in jax.tree_util.tree_leaves(
                tree, is_leaf=lambda x: isinstance(x, GuardState)):
            if isinstance(leaf, GuardState):
                continue
            arr = np.asarray(leaf)
            if (np.issubdtype(arr.dtype, np.floating)
                    and not np.isfinite(arr).all()):
                return False
        return True

    def probe_good_step(self, like) -> Optional[int]:
        """The newest checkpoint step this rank can actually restore —
        manifest hash verified AND finite params — or None when no
        loadable checkpoint exists. This is the rank's *vote* in a
        coordinated recovery round
        (:meth:`apex_tpu.cluster.RecoveryCoordinator.propose` posts it
        as ``good_step``; resolution takes the cluster-wide minimum —
        "oldest good step wins" — because that is the only step every
        rank can restore). Costs a restore per rejected candidate;
        acceptable at recovery time — and the winner is cached so the
        :meth:`rewind` that follows in the same round reuses it
        instead of re-gathering the identical checkpoint when the
        cluster target equals this rank's own good step.
        """
        from apex_tpu.ckpt import format as _fmt
        from apex_tpu.ckpt.format import CheckpointError
        self._probe_cache = None
        if self.manager is None:
            return None
        for s in reversed(list(self.manager.all_steps())):
            d = _fmt.step_dir(self.manager.root, s)
            try:
                cand, mf = self.manager.restore(like, ckpt_dir=d)
            except CheckpointError:
                continue
            if self._params_finite(cand):
                self._probe_cache = (int(s), like, cand, mf)
                return int(s)
        return None

    def drop_probe_cache(self) -> None:
        """Release :meth:`probe_good_step`'s cached restored tree — a
        full params+optimizer copy — when no :meth:`rewind` will
        consume it (a coordination round that failed before deciding);
        leaving it pinned could cost the HBM the recovery retry
        itself needs."""
        self._probe_cache = None

    def rewind(self, step: int, like, source, *,
               reason: str = "",
               target_step: Optional[int] = None) -> Tuple[Any, Dict]:
        """Restore the newest *good* snapshot and fast-forward ``source``
        past the offending window.

        ``like`` is the current training tuple (structure + shardings
        define restore targets, exactly :meth:`CheckpointManager.restore`);
        ``source`` any cursor-bearing pipeline
        (:class:`apex_tpu.data.ImageFolderSource` or duck-typed:
        ``state()/load_state()/skip_batches()/cursor_index()``) whose
        cursor was captured in each checkpoint's ``extra["cursor"]``.

        Fallback chain: a candidate checkpoint is rejected — and the
        next-older one tried — when its files fail the manifest hash
        (truncation/corruption) or its restored params are non-finite
        (the corruption predates the snapshot). ``target_step`` caps
        the search (only steps ≤ it are candidates) — the coordinated-
        recovery hook: when an
        :class:`apex_tpu.cluster.RecoveryCoordinator` round resolved to
        an older step than this rank's own newest good one, the rank
        MUST honor the cluster target or the ranks diverge (the exact
        split-brain the coordinator exists to prevent). Returns
        ``(restored_tree, manifest)``; raises :class:`GuardEscalation`
        (or trips ``escalation``) when nothing loadable remains.
        """
        from apex_tpu.ckpt import format as _fmt
        from apex_tpu.ckpt.format import CheckpointError
        if self.manager is None:
            return self.escalate(f"rewind requested but no "
                                 f"CheckpointManager wired ({reason})")
        cur_index = int(source.cursor_index())
        steps = list(self.manager.all_steps())
        if target_step is not None:
            steps = [s for s in steps if s <= int(target_step)]
        fallbacks = 0
        restored = manifest = None
        probe, self._probe_cache = self._probe_cache, None
        for s in reversed(steps):
            # the probe of this same recovery round already restored
            # and finite-checked this exact candidate — reuse it
            # rather than re-gathering the checkpoint from the shared
            # fs (identity-matched on `like`: a different target tree
            # means a different placement, so no reuse)
            if (probe is not None and probe[0] == s
                    and probe[1] is like):
                restored, manifest = probe[2], probe[3]
                break
            d = _fmt.step_dir(self.manager.root, s)
            try:
                cand, mf = self.manager.restore(like, ckpt_dir=d)
            except CheckpointError:
                fallbacks += 1
                continue
            if not self._params_finite(cand):
                fallbacks += 1
                continue
            restored, manifest = cand, mf
            break
        if restored is None:
            return self.escalate(
                f"rewind found no loadable finite checkpoint under "
                f"{self.manager.root!r} ({fallbacks} rejected; {reason})")
        cursor = (manifest.get("extra") or {}).get("cursor")
        if cursor is None:
            return self.escalate(
                f"checkpoint at step {manifest['step']} carries no data "
                f"cursor in extra['cursor'] — cannot fast-forward past "
                f"the offending window ({reason})")
        source.load_state(cursor)
        skipped = cur_index - int(source.cursor_index())
        if skipped < 0:
            return self.escalate(
                f"data cursor moved backwards across the rewind "
                f"({cur_index} -> {source.cursor_index()}) — the source "
                f"does not match the checkpointed stream ({reason})")
        source.skip_batches(skipped)
        self.rewinds_done += 1
        self.cooldown_until = int(step) + self.cooldown_steps
        self._skip_steps = []
        # resync the counter baseline to the RESTORED guard state: its
        # cumulative counters rewound below the cached high-water mark,
        # and without this a post-rewind anomaly whose counter has not
        # yet re-crossed the stale baseline would difference to <= 0
        # and be silently missed
        import jax
        from apex_tpu.guard.integrity import IntegrityState
        is_state = lambda x: isinstance(x, (GuardState, IntegrityState))
        for leaf in jax.tree_util.tree_leaves(restored, is_leaf=is_state):
            if isinstance(leaf, GuardState):
                self._prev = self._fetch(leaf)
            elif isinstance(leaf, IntegrityState):
                # same baseline resync for the integrity counters — a
                # restored mismatch_count below the cached high-water
                # mark would otherwise mask the next real divergence
                self._iprev = self._fetch_integrity(leaf)
        self._emit({"kind": "guard_rewind", "step": int(step),
                    "from_step": int(step),
                    "to_step": int(manifest["step"]),
                    "path": str(self.manager.root),
                    "skipped_batches": int(skipped),
                    "fallbacks": int(fallbacks),
                    "reason": reason or None})
        return restored, manifest

    # -- the last rung ----------------------------------------------------------

    def escalate(self, reason: str):
        """Hand off to the wired :class:`~apex_tpu.ckpt.EscalationPolicy`
        (checkpoint + dump + exit 75 / PreemptionError), or raise
        :class:`GuardEscalation` when none is wired."""
        self.drop_probe_cache()    # don't pin a restored tree across it
        self._emit({"kind": "guard_action",
                    "step": int(self._prev["step"]) if self._prev else 0,
                    "action": "escalate", "classes": [],
                    "reason": reason})
        if self.escalation is not None:
            self.escalation.trip(f"guard:{reason}")
            # trip() only returns in raise-mode off the main thread
            # (its documented polling contract); callers of
            # rewind()/escalate() expect a raise or an exit, never a
            # None return they would unpack into a TypeError
        raise GuardEscalation(reason)
