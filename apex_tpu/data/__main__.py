"""``python -m apex_tpu.data`` — loader-only throughput probe.

    python -m apex_tpu.data --bench DIR -b 128 --size 224 --workers 8
    python -m apex_tpu.data --make-fake /tmp/fakeimagenet

Prints images/sec of decode+augment+batch assembly alone; compare with
the model's synthetic-data img/s to tell input-bound from compute-bound.
"""

import argparse

from apex_tpu.data import (ImageFolderSource, make_fake_imagefolder,
                           measure_source)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--bench", metavar="DIR")
    p.add_argument("--make-fake", metavar="DIR")
    p.add_argument("-b", "--batch", type=int, default=128)
    p.add_argument("--size", type=int, default=224)
    p.add_argument("--workers", type=int, default=None)
    p.add_argument("--steps", type=int, default=20)
    args = p.parse_args()
    if args.make_fake:
        make_fake_imagefolder(args.make_fake)
        print(f"wrote fake ImageFolder tree at {args.make_fake}")
    if args.bench:
        src = ImageFolderSource(args.bench, args.batch, args.size,
                                workers=args.workers)
        rate = measure_source(src.batches(args.steps + 1),
                              steps=args.steps)
        print(f"loader: {rate:.1f} img/s (batch {args.batch}, "
              f"size {args.size}, workers {src.workers})")


if __name__ == "__main__":
    main()
