"""fp16 utility family — `apex/fp16_utils/fp16util.py:1-187` rebuilt.

The reference operates on ``nn.Module`` instances in place (``.half()``
walks, master clones, ``_flat_master`` concat); here the same operations
are pure functions over param pytrees, with the flat-master path backed
by the arena (one contiguous fp32 buffer — exactly the
``_flatten_dense_tensors`` trick, `fp16util.py:108-113`, minus the
per-tensor marshalling).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu import arena
from apex_tpu.amp.policy import _NORM_COMPONENT_RE
from apex_tpu.utils import global_norm, tree_cast


def tofp16(tree):
    """Cast floating leaves to fp16 — the ``tofp16`` module
    (`fp16util.py:7-12`) as a function."""
    return tree_cast(tree, jnp.float16)


def _norm_exempt(path, _leaf) -> bool:
    names = [str(getattr(k, "key", getattr(k, "name", k))).lower()
             for k in path]
    return any(_NORM_COMPONENT_RE.match(n) for n in names)


def convert_network(params, dtype):
    """Cast params to ``dtype`` keeping norm-layer params fp32 —
    ``convert_network`` + ``BN_convert_float`` (`fp16util.py:22-71`)."""
    return tree_cast(params, dtype,
                     predicate=lambda p, x: not _norm_exempt(p, x))


def network_to_half(params, half_dtype=jnp.float16):
    """``network_to_half`` (`fp16util.py:35-41`): half params, fp32 norms."""
    return convert_network(params, half_dtype)


class FP16Model(nn.Module):
    """Wrap a flax module: inputs cast to half and the network run at
    half params (`fp16util.py:73-84`: ``network_to_half`` + input cast).

    Storage params stay fp32 (they are the masters a wrapping
    ``FP16_Optimizer`` owns); the half cast happens in-graph on the way
    into the wrapped module, with norm-layer params exempt — exactly
    ``convert_network``'s contract.
    """

    network: nn.Module
    half_dtype: Any = jnp.float16

    @nn.compact
    def __call__(self, *args, **kwargs):
        args = tree_cast(args, jnp.dtype(self.half_dtype))
        half = jnp.dtype(self.half_dtype)

        def run(net, *a, **k):
            return net(*a, **k)

        mapped = nn.map_variables(
            run, "params",
            trans_in_fn=lambda vs: convert_network(vs, half),
            init=self.is_initializing())
        return mapped(self.network, *args, **kwargs)


class MasterParams(NamedTuple):
    """Result of :func:`prep_param_lists`.

    ``flat`` is None for per-tensor masters (a pytree mirroring the model
    params in fp32), or the arena (buffers, spec) pair when
    ``flat_master=True`` — the single contiguous fp32 buffer of
    `fp16util.py:108-113`.
    """
    tree: Optional[Any]
    flat: Optional[Tuple[Any, Any]]   # ({dtype: buffer}, ArenaSpec)

    def to_tree(self):
        if self.flat is not None:
            bufs, spec = self.flat
            return arena.unflatten(bufs, spec)
        return self.tree


def prep_param_lists(params, flat_master: bool = False):
    """(model_params, master_params): fp32 master copies of the params
    (`prep_param_lists`, `fp16util.py:90-134`). With ``flat_master`` the
    masters live in one flat fp32 arena buffer."""
    if flat_master:
        spec = arena.plan(params)
        bufs = arena.flatten(params, spec, cast=jnp.float32)
        # one fp32 buffer regardless of model dtypes, like the reference's
        # single concatenated master (`fp16util.py:108`)
        merged = {jnp.dtype(jnp.float32): jnp.concatenate(
            [b.astype(jnp.float32) for b in bufs.values()])} \
            if len(bufs) > 1 else {k: v.astype(jnp.float32)
                                   for k, v in bufs.items()}
        if len(bufs) > 1:
            raise NotImplementedError(
                "flat_master with mixed model dtypes is not supported "
                "(the reference raises here too, fp16util.py:104-107)")
        return params, MasterParams(tree=None, flat=(merged, spec))
    masters = tree_cast(params, jnp.float32)
    return params, MasterParams(tree=masters, flat=None)


def model_grads_to_master_grads(model_grads, master: MasterParams):
    """fp16 model grads → fp32 master grads, matching the master layout
    (`fp16util.py:136-155`)."""
    if master.flat is not None:
        _, spec = master.flat
        return arena.flatten(model_grads, spec, cast=jnp.float32)
    return tree_cast(model_grads, jnp.float32)


def master_params_to_model_params(master: MasterParams, model_params):
    """fp32 masters → model-dtype params (`fp16util.py:158-173`)."""
    tree = master.to_tree()
    return jax.tree_util.tree_map(
        lambda m, p: m.astype(p.dtype), tree, model_params)


def clip_grad_norm(grads, max_norm, norm_type=2):
    """Global-norm gradient clipping returning (clipped, total_norm) —
    the fp16-safe ``clip_grad_norm`` (`fp16util.py:187`, re-exported from
    torch but listed as part of this surface; norms accumulate fp32)."""
    total = global_norm(grads, ord=norm_type)
    scale = jnp.minimum(1.0, max_norm / (total + 1e-6))
    return jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads), \
        total


def to_python_float(t):
    """Host scalar fetch (`fp16util.py:176-180`)."""
    return float(jax.device_get(t))
