#!/usr/bin/env python
"""Validate apex_tpu observability JSONL streams.

Every event channel shares one validator core (JSON-object-per-line,
per-kind REQUIRED keys, per-kind NULLABLE sets, finite-or-null numbers,
typed counters, field enums), driven by a declarative **channel
registry** (:data:`SCHEMAS`) — one :class:`ChannelSchema` row per
``--kind``, mirroring the ``apex_tpu.monitor.logger.CHANNELS`` table;
channel-specific semantics (generation monotonicity, rewind direction,
bucket tables, …) live in small per-channel ``special`` hooks. Adding a
channel's validator is one registry row + one hook, not another
hand-rolled 60-line walker.

``--kind metrics`` (default) — the buffered stream emitted by
``apex_tpu.monitor.JSONLSink`` (keep in lockstep with
``apex_tpu/monitor/sinks.py`` / ``logger.py``): flat records, REQUIRED
keys on every line, ``step`` strictly increasing, counters
non-negative, every number finite (the logger nulls non-finite gauges).

``--kind trace`` — span/step/crash/watchdog events
(``apex_tpu/trace/spans.py``, ``recorder.py``, ``watchdog.py``).

``--kind memory`` — allocator samples, compiled-step memory reports,
retrace/compile events (``apex_tpu/prof/memory.py``,
``compile_watch.py``).

``--kind lint`` — apexlint report headers + findings
(``apex_tpu/lint/findings.py``); severity enum, SPMD evidence
(axes/ranks/hop) nullable on single-program findings.

``--kind ckpt`` — save/restore/escalation records
(``apex_tpu/ckpt/manager.py``, ``escalate.py``).

``--kind guard`` — anomaly/action/rewind records
(``apex_tpu/guard/policy.py``); classes enum, rewinds never go
forwards.

``--kind goodput`` — wall-time decompositions, straggler warnings,
link-calibration fits (``apex_tpu/monitor/goodput.py``,
``trace/straggler.py``, ``monitor/linkbench.py``); bucket-name enum,
positive bytes_per_s.

``--kind roofline`` — per-op roofline verdicts + perf-sentinel
regression verdicts (``apex_tpu/prof/roofline.py``, ``sentinel.py``);
bound/direction enums, efficiency ∈ [0, 1].

``--kind cluster`` — membership leases, generation commits (bumps
strictly increase and stay monotone across the stream), fence
refusals, coordination rounds (``apex_tpu/cluster/membership.py``,
``coordinator.py``).

``--kind integrity`` — fingerprint mismatches, quorum votes (minority
rank lists; a no-majority vote has a null source), repair records
(action must agree with the re-verification verdict)
(``apex_tpu/guard/integrity.py``, ``guard/policy.py``).

``--kind numerics`` — the numerics-observatory channel
(``apex_tpu/monitor/numerics.py``, ``apex_tpu/amp/scale_history.py``):
``kind`` in {numerics_check, scale_update, precision_verdict}. A
``numerics_check`` is one host poll of the in-graph per-site
statistics — ``site`` is null on the aggregate row only, and every
``*_frac`` field is a fraction ∈ [0, 1]; a ``scale_update`` records a
per-tensor delayed-scaling move (action in {grow, shrink, backoff,
hold}, scale a positive power-of-two gauge); a ``precision_verdict``
is one site's format-ladder verdict (required/current dtype in the
FORMAT enum, predicted underflow/saturation fractions ∈ [0, 1], a
positive recommended_scale, the stable ``numerics|kind|site``
fingerprint).

``--kind podview`` — the pod-observatory channel
(``apex_tpu/trace/podview.py``, ``apex_tpu/monitor/comm_drift.py``):
a ``pod_align`` is one rank's fitted clock model (offset/drift vs the
reference rank; residual null when the rank shared no collective); a
``pod_skew`` is one matched collective's wait-for-laggard vs wire
split with its (rank, span) blame; a ``pod_drift`` is one CommPlan
hop's plan-vs-measured row (link enum, positive milliseconds, stable
``comm_drift|op|axis/link`` fingerprint, boolean ``stale`` flag).

``--kind sharding`` — the sharding-observatory channel
(``apex_tpu/prof/sharding.py``, ``scripts/mesh_explain.py``): a
``sharding_mesh`` header declares the mesh's axis names, then one
``kind="sharding"`` row per axis carries the per-axis HBM
sharded/replicated bytes, wire bytes, and α–β-predicted comm seconds
— the axis is enum'd against the header's axes (plus the explicit
``"unknown"`` overflow row), byte fields are non-negative ints, and
``predicted_s`` is null on unmeasured links.

``--kind dynamics`` — the training-dynamics-observatory channel
(``apex_tpu/monitor/dynamics.py``, ``convergence.py``): a
``dynamics_check`` is one host poll of the in-graph per-site
statistics — ``site`` is null on the aggregate row only (which
carries the eff-LR/uw maxima plus the replica-geometry scalars;
cosines ∈ [-1, 1]); a ``gns`` row is the gradient-noise-scale
estimate (``gns``/``b_crit`` positive or null — null whenever the
estimator is undefined: no probe, world ≤ 1, or a noise-free
trajectory); a ``convergence_verdict`` is one A/B comparator answer
(verdict in {pass, flag}; a flag names its first_flag_step, a pass
nulls it; n_flagged ≤ n_steps; a positive band_threshold; the stable
``dynamics|convergence|loss`` fingerprint).

Pure stdlib on purpose: CI and log-shipping hosts can run it without
jax. Exit status 0 = valid, 1 = violations (printed one per line),
2 = usage/IO error.

Usage: python scripts/check_metrics_schema.py
           [--kind metrics|trace|memory|lint|ckpt|guard|goodput|roofline
                   |cluster|integrity|numerics|podview|sharding|dynamics]
           FILE
"""

from __future__ import annotations

import json
import math
import sys
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

REQUIRED = (
    "step", "loss", "loss_scale", "grad_norm", "param_norm",
    "overflow_count", "skip_count", "growth_count", "backoff_count",
    "step_time_ms", "throughput_steps_per_s", "mfu",
)
COUNTERS = ("step", "overflow_count", "skip_count", "growth_count",
            "backoff_count")
NULLABLE = ("step_time_ms", "throughput_steps_per_s", "mfu",
            "collective_bytes", "loss", "grad_norm", "param_norm",
            "wire_by_dtype", "logical_bytes", "wire_to_logical")


# --- shared core -------------------------------------------------------------

def _iter_objects(lines, errors: List[str]):
    """Parse JSONL, reporting bad lines; yields (lineno, dict)."""
    for i, raw in enumerate(lines, start=1):
        line = raw.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError as e:
            errors.append(f"line {i}: not valid JSON ({e})")
            continue
        if not isinstance(rec, dict):
            errors.append(f"line {i}: not a JSON object")
            continue
        yield i, rec


def _is_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _check_finite_numbers(i: int, rec: Dict, errors: List[str],
                          prefix: str = "") -> None:
    """Every numeric value (one level of nesting included) is finite —
    Infinity/NaN are not strict JSON and never belong on the wire."""
    for k, v in rec.items():
        if _is_number(v) and not math.isfinite(v):
            errors.append(f"line {i}: {prefix}{k!r} is non-finite ({v!r})")
        elif isinstance(v, dict):
            _check_finite_numbers(i, v, errors, prefix=f"{k}.")
        elif isinstance(v, list):
            for j, item in enumerate(v):
                if isinstance(item, dict):
                    _check_finite_numbers(i, item, errors,
                                          prefix=f"{k}[{j}].")
                elif _is_number(item) and not math.isfinite(item):
                    errors.append(f"line {i}: {k}[{j}] is non-finite "
                                  f"({item!r})")


def _check_counter(i: int, rec: Dict, key: str, errors: List[str],
                   what: str = "counter") -> None:
    v = rec.get(key)
    if v is None or key not in rec:
        return
    if not isinstance(v, int) or isinstance(v, bool) or v < 0:
        errors.append(f"line {i}: {what} {key!r} must be a "
                      f"non-negative int, got {v!r}")


def _check_nonneg(i: int, rec: Dict, key: str,
                  errors: List[str]) -> None:
    v = rec.get(key)
    if key not in rec or v is None:
        return
    if not _is_number(v) or v < 0:
        errors.append(f"line {i}: {key!r} must be a non-negative "
                      f"number, got {v!r}")


def _check_rank_list(i: int, rec: Dict, key: str,
                     errors: List[str], what: str) -> None:
    v = rec.get(key)
    if key not in rec or v is None:
        return
    if not (isinstance(v, list)
            and all(isinstance(r, int) and not isinstance(r, bool)
                    and r >= 0 for r in v)):
        errors.append(f"line {i}: {key!r} must be a list of "
                      f"non-negative {what}, got {v!r}")


class ChannelSchema(NamedTuple):
    """One ``--kind``'s declarative validation row (see the module
    docstring): the shared walker enforces kinds / required / nullable
    / finite numbers / counters / non-negative gauges / enums, the
    ``special`` hook carries the channel's cross-field semantics
    (it receives a mutable per-stream ``state`` dict for cross-record
    invariants like generation monotonicity)."""

    kinds: Tuple[str, ...]
    required: Dict[str, Tuple[str, ...]]
    nullable: Dict[str, Tuple[str, ...]]
    counters: Tuple[str, ...] = ()
    nonneg: Tuple[str, ...] = ()
    #: field → allowed values, or field → {kind: allowed values}
    enums: Dict[str, object] = {}
    special: Optional[Callable[[int, Dict, str, Dict, List[str]],
                               None]] = None


def _check_channel(schema: ChannelSchema, lines) -> List[str]:
    errors: List[str] = []
    n_records = 0
    state: Dict = {}
    for i, rec in _iter_objects(lines, errors):
        n_records += 1
        kind = rec.get("kind")
        if kind not in schema.kinds:
            errors.append(f"line {i}: 'kind' must be one of "
                          f"{schema.kinds}, got {kind!r}")
            continue
        for key in schema.required[kind]:
            if key not in rec:
                errors.append(f"line {i}: {kind} event missing required "
                              f"key {key!r}")
        nullable = schema.nullable.get(kind, ())
        for key, v in rec.items():
            if v is None and key not in nullable:
                errors.append(f"line {i}: {kind} key {key!r} is null "
                              f"(only {nullable} may be)")
        _check_finite_numbers(i, rec, errors)
        for key in schema.counters:
            _check_counter(i, rec, key, errors, what="field")
        for key in schema.nonneg:
            _check_nonneg(i, rec, key, errors)
        for field, allowed in schema.enums.items():
            v = rec.get(field)
            if field not in rec or v is None:
                continue
            vals = allowed.get(kind) if isinstance(allowed, dict) \
                else allowed
            if vals is not None and v not in vals:
                errors.append(f"line {i}: {field!r} must be one of "
                              f"{vals}, got {v!r}")
        if schema.special is not None:
            schema.special(i, rec, kind, state, errors)
    if n_records == 0:
        errors.append("no records found")
    return errors


def _make_checker(schema: ChannelSchema):
    def _checker(lines) -> List[str]:
        return _check_channel(schema, lines)
    return _checker


# --- trace-event / crash-dump schema -----------------------------------------

TRACE_KINDS = ("span", "step", "crash", "watchdog")
TRACE_REQUIRED = {
    "span": ("name", "dur_ms"),
    "step": ("step", "spans"),
    "crash": ("reason", "rank", "last_completed_span", "in_flight_spans"),
    "watchdog": ("reason", "rank", "seconds_since_last_step", "stacks",
                 "silent_ranks"),
}
TRACE_NULLABLE = {
    "span": ("step",),
    "step": ("step", "dur_ms", "metrics", "loss_scale"),
    "crash": ("last_completed_span", "in_flight_collective"),
    "watchdog": ("last_step", "last_completed_span",
                 "in_flight_collective"),
}


def _trace_special(i, rec, kind, state, errors):
    for dk in ("dur_ms", "t_ms", "wall_time",
               "seconds_since_last_step", "deadline_s"):
        if dk not in rec or rec[dk] is None:
            continue
        v = rec[dk]
        if not _is_number(v):
            errors.append(f"line {i}: {dk!r} must be a number, "
                          f"got {v!r}")
        elif v < 0 and dk != "t_ms":
            errors.append(f"line {i}: {dk!r} must be >= 0, got {v!r}")
    if kind == "span" and not isinstance(rec.get("name"), str):
        errors.append(f"line {i}: span 'name' must be a string")
    if kind == "step":
        spans = rec.get("spans")
        if not isinstance(spans, list):
            errors.append(f"line {i}: step 'spans' must be a list")
        else:
            for j, s in enumerate(spans):
                if (not isinstance(s, dict)
                        or not isinstance(s.get("name"), str)
                        or not _is_number(s.get("dur_ms"))):
                    errors.append(f"line {i}: spans[{j}] must be "
                                  "{name: str, dur_ms: number}")
        _check_counter(i, rec, "step", errors, what="field")
    if kind in ("crash", "watchdog"):
        if not isinstance(rec.get("reason"), str):
            errors.append(f"line {i}: {kind} 'reason' must be a "
                          "string")
        lcs = rec.get("last_completed_span")
        if lcs is not None and not isinstance(lcs, str):
            errors.append(f"line {i}: 'last_completed_span' must be "
                          "a string or null")
        ifs = rec.get("in_flight_spans")
        if ifs is not None and not isinstance(ifs, list):
            errors.append(f"line {i}: 'in_flight_spans' must be a "
                          "list")
    if kind == "watchdog":
        if not isinstance(rec.get("stacks"), dict):
            errors.append(f"line {i}: watchdog 'stacks' must be an "
                          "object")
        sr = rec.get("silent_ranks")
        if not (isinstance(sr, list)
                and all(isinstance(r, int) and not isinstance(r, bool)
                        and r >= 0 for r in sr)):
            errors.append(f"line {i}: 'silent_ranks' must be a list "
                          "of non-negative ints")


# --- memory / compile channel schema -----------------------------------------

MEMORY_KINDS = ("memory", "memory_report", "retrace", "compile")
MEMORY_REQUIRED = {
    "memory": ("rank",),
    "memory_report": ("rank", "total_bytes", "peak_live_bytes",
                      "classes"),
    "retrace": ("fn", "changed"),
    "compile": ("fn", "dur_ms"),
}
MEMORY_NULLABLE = {
    "memory": ("step", "bytes_in_use", "peak_bytes_in_use",
               "bytes_limit"),
    "memory_report": ("step", "hbm_limit", "batch_size"),
    "retrace": ("step",),
    "compile": ("step", "changed"),
}
MEMORY_BYTE_FIELDS = ("total_bytes", "attributed_bytes",
                      "peak_live_bytes", "batch_bytes", "bytes_in_use",
                      "peak_bytes_in_use", "bytes_limit", "hbm_limit")


def _memory_special(i, rec, kind, state, errors):
    if kind in ("retrace", "compile"):
        if not isinstance(rec.get("fn"), str):
            errors.append(f"line {i}: {kind} 'fn' must be a string")
        dm = rec.get("dur_ms")
        if dm is not None and "dur_ms" in rec and (
                not _is_number(dm) or dm < 0):
            errors.append(f"line {i}: 'dur_ms' must be a "
                          f"non-negative number, got {dm!r}")
    if kind == "memory_report":
        classes = rec.get("classes")
        if not isinstance(classes, dict):
            errors.append(f"line {i}: 'classes' must be an object")
        else:
            for ck, cv in classes.items():
                if (not isinstance(cv, int) or isinstance(cv, bool)
                        or cv < 0):
                    errors.append(
                        f"line {i}: classes[{ck!r}] must be a "
                        f"non-negative int, got {cv!r}")
        tb = rec.get("top_buffers")
        if tb is not None and not (
                isinstance(tb, list)
                and all(isinstance(b, dict)
                        and isinstance(b.get("name"), str)
                        and isinstance(b.get("bytes"), int)
                        for b in tb)):
            errors.append(f"line {i}: 'top_buffers' must be a list "
                          "of {name: str, bytes: int, ...}")


# --- lint channel schema ------------------------------------------------------

LINT_KINDS = ("lint_report", "lint_finding")
LINT_SEVERITIES = ("error", "warning", "info")
LINT_HOPS = ("ici", "dcn")
#: the supported format ladder for APX3xx dtype evidence — mirrors
#: apex_tpu.lint.findings.DTYPE_NAMES (numerics.FORMAT_LADDER + fp64)
LINT_DTYPES = ("fp8_e4m3", "fp8_e5m2", "fp16", "bf16", "fp32", "fp64")
#: mirrors apex_tpu.lint.findings.PROVENANCES
LINT_PROVENANCES = ("unscaled", "loss-scaled", "site-scaled",
                    "unscaled-after-narrow")
LINT_REQUIRED = {
    "lint_report": ("n_findings", "by_severity"),
    "lint_finding": ("rule", "id", "severity", "message"),
}
LINT_NULLABLE = {
    "lint_report": ("step", "fn"),
    # dtype_from/dtype_to/scale_provenance: APX3xx precision evidence,
    # absent (null) on every other rule — nullable + enum-checked
    "lint_finding": ("step", "fn", "op", "scope", "bytes", "fix",
                     "axes", "ranks", "hop", "dtype_from", "dtype_to",
                     "scale_provenance"),
}


def _lint_special(i, rec, kind, state, errors):
    if kind == "lint_report":
        _check_counter(i, rec, "n_findings", errors, what="field")
        _check_counter(i, rec, "suppressed", errors, what="field")
        sev = rec.get("by_severity")
        if not isinstance(sev, dict):
            errors.append(f"line {i}: 'by_severity' must be an "
                          "object")
        else:
            for sk, sv in sev.items():
                if sk not in LINT_SEVERITIES:
                    errors.append(f"line {i}: by_severity key "
                                  f"{sk!r} not in {LINT_SEVERITIES}")
                if (not isinstance(sv, int) or isinstance(sv, bool)
                        or sv < 0):
                    errors.append(f"line {i}: by_severity[{sk!r}] "
                                  f"must be a non-negative int, got "
                                  f"{sv!r}")
    if kind == "lint_finding":
        for key in ("rule", "id", "message"):
            if key in rec and not isinstance(rec.get(key), str):
                errors.append(f"line {i}: {key!r} must be a string")
        axes = rec.get("axes")
        if axes is not None and not (
                isinstance(axes, list)
                and all(isinstance(a, str) for a in axes)):
            errors.append(f"line {i}: 'axes' must be a list of "
                          "mesh-axis names")
        ranks = rec.get("ranks")
        if ranks is not None and not (
                isinstance(ranks, list) and len(ranks) == 2
                and all(isinstance(r, int)
                        and not isinstance(r, bool)
                        and r >= 0 for r in ranks)):
            errors.append(f"line {i}: 'ranks' must be a pair of "
                          "non-negative rank ids")


# --- ckpt channel schema ------------------------------------------------------

CKPT_KINDS = ("ckpt_save", "ckpt_restore", "ckpt_escalation")
CKPT_ACTIONS = ("checkpoint+dump+exit", "checkpoint+dump")
CKPT_REQUIRED = {
    "ckpt_save": ("step", "path", "bytes", "stall_ms", "dur_ms"),
    "ckpt_restore": ("step", "path", "dur_ms"),
    "ckpt_escalation": ("reason", "action"),
}
CKPT_NULLABLE = {
    "ckpt_save": (),
    "ckpt_restore": (),
    "ckpt_escalation": ("path", "step", "exit_code"),
}


def _ckpt_special(i, rec, kind, state, errors):
    _check_counter(i, rec, "bytes", errors, what="byte field")
    if kind != "ckpt_escalation":
        p = rec.get("path")
        if "path" in rec and not isinstance(p, str):
            errors.append(f"line {i}: 'path' must be a string, "
                          f"got {p!r}")
    if kind == "ckpt_escalation":
        if not isinstance(rec.get("reason"), str):
            errors.append(f"line {i}: escalation 'reason' must be a "
                          "string")


# --- guard channel schema -----------------------------------------------------

GUARD_KINDS = ("guard_anomaly", "guard_action", "guard_rewind")
GUARD_ACTIONS = ("skip", "rewind", "escalate", "observe")
GUARD_CLASSES = ("loss_spike", "grad_explosion", "nonfinite_grad",
                 "nonfinite_loss", "nonfinite_param",
                 "replica_divergence")
GUARD_REQUIRED = {
    "guard_anomaly": ("step", "classes"),
    "guard_action": ("step", "action"),
    "guard_rewind": ("step", "from_step", "to_step", "path",
                     "skipped_batches"),
}
GUARD_NULLABLE = {
    "guard_anomaly": ("z",),
    "guard_action": ("reason",),
    "guard_rewind": ("reason",),
}


def _guard_special(i, rec, kind, state, errors):
    classes = rec.get("classes")
    if classes is not None:
        if not isinstance(classes, list):
            errors.append(f"line {i}: 'classes' must be a list")
        else:
            for c in classes:
                if c not in GUARD_CLASSES:
                    errors.append(f"line {i}: classes entry {c!r} "
                                  f"not in {GUARD_CLASSES}")
    if kind == "guard_rewind":
        p = rec.get("path")
        if "path" in rec and not isinstance(p, str):
            errors.append(f"line {i}: 'path' must be a string, "
                          f"got {p!r}")
        fs, ts = rec.get("from_step"), rec.get("to_step")
        if (isinstance(fs, int) and isinstance(ts, int)
                and not isinstance(fs, bool)
                and not isinstance(ts, bool) and ts > fs):
            errors.append(f"line {i}: rewind goes forwards "
                          f"(to_step {ts} > from_step {fs})")


# --- goodput / straggler / linkfit channel schema -----------------------------

GOODPUT_KINDS = ("goodput", "straggler", "linkfit")
GOODPUT_BUCKETS = ("compute", "comm_skew", "comm_wire", "input_wait",
                   "host_callback", "ckpt_stall", "recompile",
                   "guard_rewind", "other")
GOODPUT_LINKS = ("ici", "dcn")
GOODPUT_REQUIRED = {
    "goodput": ("rank", "wall_ms", "buckets_ms", "closure_err"),
    "straggler": ("step", "rank", "lag_ms", "z", "consecutive",
                  "n_ranks"),
    "linkfit": ("link", "bytes_per_s", "residual", "n_samples"),
}
GOODPUT_NULLABLE = {
    "goodput": ("step", "goodput_frac", "comm_axes_ms"),
    "straggler": ("slowest_span", "span_class", "slowest_span_ms"),
    "linkfit": ("axis", "alpha_us"),
}


def _goodput_special(i, rec, kind, state, errors):
    if kind == "goodput":
        buckets = rec.get("buckets_ms")
        if not isinstance(buckets, dict):
            errors.append(f"line {i}: 'buckets_ms' must be an object")
        else:
            for bk, bv in buckets.items():
                if bk not in GOODPUT_BUCKETS:
                    errors.append(f"line {i}: buckets_ms key {bk!r} "
                                  f"not in {GOODPUT_BUCKETS}")
                if not _is_number(bv) or bv < 0:
                    errors.append(
                        f"line {i}: buckets_ms[{bk!r}] must be a "
                        f"non-negative number, got {bv!r}")
        # the per-axis exposed-comm split (StepLedger.comm_axes_ms):
        # {axis: {"wire": ms, "skew": ms}} — axis names are free-form
        # (the registry's canonical names plus "unknown"), the leaves
        # are non-negative milliseconds
        axes = rec.get("comm_axes_ms")
        if "comm_axes_ms" in rec and axes is not None:
            if not isinstance(axes, dict):
                errors.append(f"line {i}: 'comm_axes_ms' must be an "
                              f"object")
            else:
                for ax, parts in axes.items():
                    if not isinstance(parts, dict):
                        errors.append(
                            f"line {i}: comm_axes_ms[{ax!r}] must be "
                            f"an object, got {parts!r}")
                        continue
                    for pk, pv in parts.items():
                        if pk not in ("wire", "skew"):
                            errors.append(
                                f"line {i}: comm_axes_ms[{ax!r}] key "
                                f"{pk!r} not in ('wire', 'skew')")
                        if not _is_number(pv) or pv < 0:
                            errors.append(
                                f"line {i}: comm_axes_ms[{ax!r}]"
                                f"[{pk!r}] must be a non-negative "
                                f"number, got {pv!r}")
        gf = rec.get("goodput_frac")
        if gf is not None and "goodput_frac" in rec and (
                not _is_number(gf) or gf < 0):
            errors.append(f"line {i}: 'goodput_frac' must be a "
                          f"non-negative number, got {gf!r}")
    if kind == "straggler":
        for dk in ("lag_ms", "z"):
            v = rec.get(dk)
            if v is not None and dk in rec and not _is_number(v):
                errors.append(f"line {i}: {dk!r} must be a number, "
                              f"got {v!r}")
        for sk in ("slowest_span", "span_class"):
            v = rec.get(sk)
            if v is not None and sk in rec and not isinstance(v, str):
                errors.append(f"line {i}: {sk!r} must be a string "
                              f"or null, got {v!r}")
    if kind == "linkfit":
        bps = rec.get("bytes_per_s")
        if bps is not None and "bytes_per_s" in rec and (
                not _is_number(bps) or bps <= 0):
            errors.append(f"line {i}: 'bytes_per_s' must be a "
                          f"positive number, got {bps!r}")


# --- roofline / sentinel channel schema ---------------------------------------

ROOFLINE_KINDS = ("roofline", "regress", "tune")
ROOFLINE_BOUNDS = ("compute", "memory", "unknown")
REGRESS_DIRECTIONS = ("higher", "lower")
#: autotuner lifecycle on the same channel (apex_tpu/ops/autotune.py):
#: a grid was swept, a trace-time consult hit/missed the committed DB,
#: or a tuned entry was refused (stale / off-grid block)
TUNE_ACTIONS = ("sweep", "hit", "miss", "refused")
ROOFLINE_REQUIRED = {
    "roofline": ("op", "family", "bound", "flops", "bytes",
                 "attainable_us", "fingerprint"),
    "regress": ("metric", "direction", "regressed", "n_history",
                "fingerprint"),
    "tune": ("family", "fingerprint", "action"),
}
ROOFLINE_NULLABLE = {
    "roofline": ("step", "measured_us", "efficiency", "gap_us",
                 "scope", "dtype"),
    "regress": ("latest", "baseline", "mad", "threshold",
                "degradation"),
    "tune": ("step", "best_us", "default_us", "gap_us", "speedup",
             "n_candidates", "block_rows", "block_q", "block_k",
             "chip", "dtype"),
}


def _roofline_special(i, rec, kind, state, errors):
    if "fingerprint" in rec and not isinstance(
            rec.get("fingerprint"), str):
        errors.append(f"line {i}: 'fingerprint' must be a string")
    if kind == "roofline":
        eff = rec.get("efficiency")
        if eff is not None and "efficiency" in rec:
            if not _is_number(eff) or not 0.0 <= eff <= 1.0:
                errors.append(f"line {i}: 'efficiency' must be in "
                              f"[0, 1] or null, got {eff!r}")
        for dk in ("flops", "bytes", "attainable_us", "measured_us",
                   "gap_us"):
            _check_nonneg(i, rec, dk, errors)
        for sk in ("op", "family"):
            if sk in rec and not isinstance(rec.get(sk), str):
                errors.append(f"line {i}: {sk!r} must be a string")
    if kind == "regress":
        if not isinstance(rec.get("metric"), str):
            errors.append(f"line {i}: 'metric' must be a string")
        for bk in ("regressed", "waived"):
            v = rec.get(bk)
            if v is not None and bk in rec and not isinstance(v,
                                                              bool):
                errors.append(f"line {i}: {bk!r} must be a boolean")
        for dk in ("mad", "threshold"):
            _check_nonneg(i, rec, dk, errors)
    if kind == "tune":
        if not isinstance(rec.get("family"), str):
            errors.append(f"line {i}: 'family' must be a string")
        for dk in ("best_us", "default_us", "gap_us", "speedup"):
            _check_nonneg(i, rec, dk, errors)
        for sk in ("chip", "dtype"):
            v = rec.get(sk)
            if v is not None and sk in rec and not isinstance(v, str):
                errors.append(f"line {i}: {sk!r} must be a string "
                              f"or null, got {v!r}")


# --- cluster control-plane channel schema -------------------------------------

CLUSTER_KINDS = ("cluster_lease", "cluster_generation", "cluster_fence",
                 "cluster_coord")
CLUSTER_ACTIONS = {
    "cluster_lease": ("acquire", "release", "expire", "gc"),
    "cluster_generation": ("bump", "observe"),
    "cluster_fence": ("refused_commit", "refused_write",
                      "refused_delete", "refused_intent"),
    "cluster_coord": ("propose", "resolve", "barrier_timeout",
                      "collective_hang"),
}
CLUSTER_REQUIRED = {
    "cluster_lease": ("action", "generation"),
    "cluster_generation": ("action", "generation"),
    "cluster_fence": ("action", "generation", "current_generation"),
    "cluster_coord": ("action", "generation"),
}
CLUSTER_NULLABLE = {
    "cluster_lease": ("expires_at", "reason"),
    "cluster_generation": ("reason", "prev_generation"),
    "cluster_fence": ("path", "step", "reason"),
    "cluster_coord": ("good_step", "target_step", "deadline_s",
                      "reason"),
}


def _cluster_special(i, rec, kind, state, errors):
    act = rec.get("action")
    for dk in ("ttl_s", "deadline_s", "age_s", "wall_time",
               "expires_at"):
        v = rec.get(dk)
        if dk not in rec or v is None:
            continue
        if not _is_number(v) or (v < 0 and dk != "expires_at"):
            errors.append(f"line {i}: {dk!r} must be a non-negative "
                          f"number, got {v!r}")
    if kind == "cluster_generation" and act == "bump":
        gen, prev = rec.get("generation"), rec.get("prev_generation")
        if (isinstance(gen, int) and isinstance(prev, int)
                and not isinstance(gen, bool)
                and not isinstance(prev, bool) and gen <= prev):
            errors.append(f"line {i}: generation bump goes backwards "
                          f"({prev} -> {gen})")
        if isinstance(gen, int) and not isinstance(gen, bool):
            last_bump = state.get("last_bump")
            if last_bump is not None and gen < last_bump:
                errors.append(f"line {i}: bump generation {gen} "
                              f"below an earlier bump {last_bump} — "
                              "epochs must be monotone")
            state["last_bump"] = gen
    if kind == "cluster_fence":
        what = rec.get("what")
        if what is not None and not isinstance(what, str):
            errors.append(f"line {i}: 'what' must be a string")
    if kind == "cluster_coord":
        for lk in ("ranks", "missing"):
            if lk in rec:
                _check_rank_list(i, rec, lk, errors, "rank ids")
        for sk in ("proposed", "decided", "collective", "what"):
            v = rec.get(sk)
            if v is not None and sk in rec and not isinstance(v, str):
                errors.append(f"line {i}: {sk!r} must be a string")


# --- integrity channel schema -------------------------------------------------

INTEGRITY_KINDS = ("integrity_check", "integrity_vote",
                   "integrity_repair")
INTEGRITY_VOTE_ACTIONS = ("repair", "rewind", "escalate", "observe")
INTEGRITY_REPAIR_ACTIONS = ("repair", "repair_failed")
INTEGRITY_REQUIRED = {
    "integrity_check": ("step", "check_step", "n_ranks",
                        "mismatch_count"),
    "integrity_vote": ("step", "action", "n_ranks", "minority"),
    "integrity_repair": ("step", "action", "source_rank", "minority",
                         "verified"),
}
INTEGRITY_NULLABLE = {
    # check_step is null when the counter moved but no check ran under
    # THIS electorate (the integrity_resize elastic-resume sentinel)
    "integrity_check": ("generation", "check_step"),
    "integrity_vote": ("generation", "reason", "source_rank",
                       "majority_fp"),
    "integrity_repair": ("generation", "reason"),
}


def _integrity_special(i, rec, kind, state, errors):
    _check_rank_list(i, rec, "minority", errors, "replica ranks")
    if kind == "integrity_check":
        h = rec.get("healed")
        if "healed" in rec and not isinstance(h, bool):
            errors.append(f"line {i}: 'healed' must be a boolean, "
                          f"got {h!r}")
    if kind == "integrity_repair":
        ver = rec.get("verified")
        if "verified" in rec and not isinstance(ver, bool):
            errors.append(f"line {i}: 'verified' must be a "
                          f"boolean, got {ver!r}")
        act = rec.get("action")
        if (isinstance(ver, bool) and isinstance(act, str)
                and act in INTEGRITY_REPAIR_ACTIONS
                and (act == "repair") != ver):
            errors.append(f"line {i}: action {act!r} contradicts "
                          f"verified={ver}")


# --- numerics channel schema --------------------------------------------------

NUMERICS_KINDS = ("numerics_check", "scale_update", "precision_verdict")
#: format-name enum (keep in lockstep with
#: apex_tpu/monitor/numerics.py FORMAT_TABLE / FORMAT_LADDER)
NUMERICS_FORMATS = ("fp8_e4m3", "fp8_e5m2", "fp16", "bf16", "fp32")
#: delayed-scaling move enum (apex_tpu/amp/scale_history.py
#: scale_update_events)
NUMERICS_SCALE_ACTIONS = ("grow", "shrink", "backoff", "hold")
#: fraction-valued fields — must sit in [0, 1] when present
NUMERICS_FRACTIONS = ("underflow_frac", "overflow_frac", "zero_frac",
                      "nonfinite_frac", "predicted_underflow_frac",
                      "predicted_saturation_frac")
NUMERICS_REQUIRED = {
    "numerics_check": ("step", "check_count", "n_sites"),
    "scale_update": ("step", "site", "action", "scale"),
    "precision_verdict": ("site", "required_dtype",
                          "predicted_underflow_frac",
                          "predicted_saturation_frac",
                          "recommended_scale", "fingerprint"),
}
NUMERICS_NULLABLE = {
    # site is null on the AGGREGATE numerics_check row only — the
    # per-site fraction gauges are null there too (they are priced per
    # site against one format; the aggregate carries the maxima)
    "numerics_check": ("site", "amax", "amin", "uw_ratio",
                       "underflow_frac", "overflow_frac"),
    "scale_update": ("prev_scale", "amax"),
    "precision_verdict": ("step", "current_dtype", "amax", "ok"),
}


def _numerics_special(i, rec, kind, state, errors):
    for fk in NUMERICS_FRACTIONS:
        v = rec.get(fk)
        if fk not in rec or v is None:
            continue
        if not _is_number(v) or not 0.0 <= v <= 1.0:
            errors.append(f"line {i}: {fk!r} must be a fraction in "
                          f"[0, 1], got {v!r}")
    site = rec.get("site")
    if site is not None and "site" in rec and not isinstance(site, str):
        errors.append(f"line {i}: 'site' must be a string, got "
                      f"{site!r}")
    if kind == "scale_update":
        for sk in ("scale", "prev_scale"):
            v = rec.get(sk)
            if sk in rec and v is not None and (
                    not _is_number(v) or v <= 0):
                errors.append(f"line {i}: {sk!r} must be a positive "
                              f"number, got {v!r}")
    if kind == "precision_verdict":
        if "fingerprint" in rec and not isinstance(
                rec.get("fingerprint"), str):
            errors.append(f"line {i}: 'fingerprint' must be a string")
        rs = rec.get("recommended_scale")
        if rs is not None and "recommended_scale" in rec and (
                not _is_number(rs) or rs <= 0):
            errors.append(f"line {i}: 'recommended_scale' must be a "
                          f"positive number, got {rs!r}")
        ok = rec.get("ok")
        if ok is not None and "ok" in rec and not isinstance(ok, bool):
            errors.append(f"line {i}: 'ok' must be a boolean or null, "
                          f"got {ok!r}")


# --- podview channel schema ---------------------------------------------------

PODVIEW_KINDS = ("pod_align", "pod_skew", "pod_drift")
PODVIEW_LINKS = ("ici", "dcn")
PODVIEW_REQUIRED = {
    "pod_align": ("rank", "offset_ms", "n_shared", "aligned",
                  "reference", "wall_time"),
    "pod_skew": ("name", "occurrence", "n_ranks", "skew_ms",
                 "wire_ms", "wall_time"),
    "pod_drift": ("hop", "op", "axis", "link", "predicted_ms",
                  "measured_ms", "ratio", "stale", "fingerprint",
                  "wall_time"),
}
PODVIEW_NULLABLE = {
    # residual/drift are null for an unaligned rank (no shared
    # collectives to fit against)
    "pod_align": ("residual_ms", "drift_ppm"),
    # blame is null when no host span overlapped the skew window
    "pod_skew": ("step", "blamed_rank", "blamed_span"),
    "pod_drift": ("dtype",),
}


def _podview_special(i, rec, kind, state, errors):
    # offset_ms / drift_ppm are signed (a rank's clock may lead or
    # lag the reference) — everything else numeric is a magnitude,
    # enforced via the registry row's nonneg tuple.
    for bk in ("aligned", "stale"):
        v = rec.get(bk)
        if bk in rec and not isinstance(v, bool):
            errors.append(f"line {i}: {bk!r} must be a boolean, "
                          f"got {v!r}")
    if kind == "pod_align":
        ns, al = rec.get("n_shared"), rec.get("aligned")
        rk, ref = rec.get("rank"), rec.get("reference")
        if (al is True and ns == 0 and rk is not None
                and rk != ref):
            errors.append(f"line {i}: rank {rk} claims aligned with "
                          f"no shared collectives (only the "
                          f"reference may)")
    if kind == "pod_skew":
        for sk in ("name", "blamed_span"):
            v = rec.get(sk)
            if sk in rec and v is not None and not isinstance(v, str):
                errors.append(f"line {i}: {sk!r} must be a string "
                              f"or null, got {v!r}")
    if kind == "pod_drift":
        for sk in ("op", "axis", "fingerprint"):
            v = rec.get(sk)
            if sk in rec and not isinstance(v, str):
                errors.append(f"line {i}: {sk!r} must be a string, "
                              f"got {v!r}")
        r = rec.get("ratio")
        if "ratio" in rec and r is not None and (
                not _is_number(r) or r <= 0):
            errors.append(f"line {i}: 'ratio' must be a positive "
                          f"number, got {r!r}")


# --- sharding channel schema --------------------------------------------------

SHARDING_KINDS = ("sharding_mesh", "sharding")
SHARDING_REQUIRED = {
    "sharding_mesh": ("rank", "mesh", "axes", "axis_sizes",
                      "wall_time"),
    "sharding": ("rank", "axis", "hbm_sharded_bytes",
                 "hbm_replicated_bytes", "wall_time"),
}
SHARDING_NULLABLE = {
    # extra_axes: composite attribution rows beyond the mesh axes the
    # stream will carry (e.g. the registry's flat "data" axis over a
    # factored data_inter x data_intra mesh)
    "sharding_mesh": ("step", "candidate", "extra_axes"),
    # wire_bytes is null when the caller only measured HBM; predicted_s
    # stays null on unmeasured links by contract (never a made-up 0)
    "sharding": ("step", "candidate", "wire_bytes", "predicted_s",
                 "link"),
}


def _sharding_special(i, rec, kind, state, errors):
    """The ``sharding_mesh`` header declares the mesh's axis names
    (plus any ``extra_axes`` composite attribution rows — e.g. the
    registry's flat ``data`` axis over a factored mesh); every
    subsequent per-axis row's ``axis`` must be one of them or the
    explicit ``"unknown"`` overflow row — the enum is *per-stream*
    (read from the header), not a global table, because every mesh
    declares its own axes."""
    if kind == "sharding_mesh":
        axes = rec.get("axes")
        if not (isinstance(axes, list) and axes
                and all(isinstance(a, str) for a in axes)):
            errors.append(f"line {i}: 'axes' must be a non-empty list "
                          f"of axis names, got {axes!r}")
        else:
            state["axes"] = tuple(axes)
        extra = rec.get("extra_axes")
        if extra is not None:
            if not (isinstance(extra, list)
                    and all(isinstance(a, str) for a in extra)):
                errors.append(f"line {i}: 'extra_axes' must be a list "
                              f"of axis names, got {extra!r}")
            elif "axes" in state:
                state["axes"] = state["axes"] + tuple(extra)
        sizes = rec.get("axis_sizes")
        if sizes is not None and not (
                isinstance(sizes, dict)
                and all(isinstance(v, int) and not isinstance(v, bool)
                        and v >= 1 for v in sizes.values())):
            errors.append(f"line {i}: 'axis_sizes' must map axis "
                          f"names to sizes >= 1, got {sizes!r}")
    if kind == "sharding":
        ax = rec.get("axis")
        if not isinstance(ax, str):
            errors.append(f"line {i}: 'axis' must be a string, "
                          f"got {ax!r}")
        else:
            known = state.get("axes")
            if known is not None and ax != "unknown" \
                    and ax not in known:
                errors.append(f"line {i}: axis {ax!r} not among the "
                              f"mesh header's axes {known} (or "
                              f"'unknown')")


# --- dynamics channel schema --------------------------------------------------

DYNAMICS_KINDS = ("dynamics_check", "gns", "convergence_verdict")
#: comparator verdict enum (apex_tpu/monitor/convergence.py)
DYNAMICS_VERDICTS = ("pass", "flag")
#: cosine-valued fields — must sit in [-1, 1] when present
DYNAMICS_COSINES = ("cos_min", "cos_mean")
DYNAMICS_REQUIRED = {
    "dynamics_check": ("step", "check_count", "n_sites"),
    "gns": ("step", "check_count", "local_batch", "fingerprint"),
    "convergence_verdict": ("verdict", "n_flagged", "n_steps",
                            "band_threshold", "band_z", "fingerprint"),
}
DYNAMICS_NULLABLE = {
    # site is null on the AGGREGATE dynamics_check row only (which
    # carries the maxima + the geometry scalars); the per-site rows
    # null the geometry instead, and the gauges are null until their
    # companion (grad / weight / probe) has folded
    "dynamics_check": ("site", "eff_lr", "uw_ratio", "cos_min",
                       "cos_mean", "world"),
    # the GNS estimate is null by contract until a probe folded with
    # world > 1, and whenever the estimator algebra is undefined (a
    # noise-free trajectory drives it non-positive)
    "gns": ("gns", "b_crit", "local_sq", "pooled_sq", "world",
            "cos_min", "cos_mean"),
    # step mirrors first_flag_step: both null on a pass verdict
    "convergence_verdict": ("step", "first_flag_step", "max_gap"),
}


def _dynamics_special(i, rec, kind, state, errors):
    for ck in DYNAMICS_COSINES:
        v = rec.get(ck)
        if ck not in rec or v is None:
            continue
        if not _is_number(v) or not -1.0 <= v <= 1.0:
            errors.append(f"line {i}: {ck!r} must be a cosine in "
                          f"[-1, 1], got {v!r}")
    site = rec.get("site")
    if site is not None and "site" in rec and not isinstance(site, str):
        errors.append(f"line {i}: 'site' must be a string, got "
                      f"{site!r}")
    if "fingerprint" in rec and not isinstance(
            rec.get("fingerprint"), str):
        errors.append(f"line {i}: 'fingerprint' must be a string")
    if kind == "gns":
        for pk in ("gns", "b_crit"):
            v = rec.get(pk)
            if pk in rec and v is not None and (
                    not _is_number(v) or v <= 0):
                errors.append(f"line {i}: {pk!r} must be a positive "
                              f"number or null, got {v!r}")
    if kind == "convergence_verdict":
        bt = rec.get("band_threshold")
        if "band_threshold" in rec and (
                not _is_number(bt) or bt <= 0):
            errors.append(f"line {i}: 'band_threshold' must be a "
                          f"positive number, got {bt!r}")
        ver = rec.get("verdict")
        ffs = rec.get("first_flag_step")
        if ver == "pass" and ffs is not None:
            errors.append(f"line {i}: verdict 'pass' with "
                          f"first_flag_step={ffs!r} (must be null)")
        if ver == "flag" and "first_flag_step" in rec and ffs is None:
            errors.append(f"line {i}: verdict 'flag' needs a "
                          f"first_flag_step")
        nf, ns = rec.get("n_flagged"), rec.get("n_steps")
        if (isinstance(nf, int) and isinstance(ns, int)
                and not isinstance(nf, bool)
                and not isinstance(ns, bool) and nf > ns):
            errors.append(f"line {i}: n_flagged {nf} exceeds "
                          f"n_steps {ns}")


# --- the channel registry -----------------------------------------------------

SCHEMAS: Dict[str, ChannelSchema] = {
    "trace": ChannelSchema(
        TRACE_KINDS, TRACE_REQUIRED, TRACE_NULLABLE,
        counters=("rank", "pid"), special=_trace_special),
    "memory": ChannelSchema(
        MEMORY_KINDS, MEMORY_REQUIRED, MEMORY_NULLABLE,
        counters=("rank",) + MEMORY_BYTE_FIELDS,
        special=_memory_special),
    "lint": ChannelSchema(
        LINT_KINDS, LINT_REQUIRED, LINT_NULLABLE,
        counters=("bytes", "count", "step"),
        enums={"severity": LINT_SEVERITIES, "hop": LINT_HOPS,
               "dtype_from": LINT_DTYPES, "dtype_to": LINT_DTYPES,
               "scale_provenance": LINT_PROVENANCES},
        special=_lint_special),
    "ckpt": ChannelSchema(
        CKPT_KINDS, CKPT_REQUIRED, CKPT_NULLABLE,
        counters=("rank", "step", "n_arrays", "resharded",
                  "from_processes", "exit_code"),
        nonneg=("stall_ms", "dur_ms", "wall_time"),
        enums={"action": CKPT_ACTIONS}, special=_ckpt_special),
    "guard": ChannelSchema(
        GUARD_KINDS, GUARD_REQUIRED, GUARD_NULLABLE,
        counters=("rank", "step", "from_step", "to_step",
                  "skipped_batches", "fallbacks", "consecutive",
                  "skip_count"),
        enums={"action": {"guard_action": GUARD_ACTIONS}},
        special=_guard_special),
    "goodput": ChannelSchema(
        GOODPUT_KINDS, GOODPUT_REQUIRED, GOODPUT_NULLABLE,
        counters=("rank", "step", "consecutive", "n_ranks",
                  "n_samples"),
        nonneg=("wall_ms", "closure_err", "slowest_span_ms",
                "wall_time", "residual", "alpha_us"),
        enums={"link": GOODPUT_LINKS}, special=_goodput_special),
    "roofline": ChannelSchema(
        ROOFLINE_KINDS, ROOFLINE_REQUIRED, ROOFLINE_NULLABLE,
        counters=("rank", "step", "occurrences", "n_history",
                  "n_candidates", "block_rows", "block_q", "block_k"),
        enums={"bound": ROOFLINE_BOUNDS,
               "direction": REGRESS_DIRECTIONS,
               "action": {"tune": TUNE_ACTIONS}},
        special=_roofline_special),
    "cluster": ChannelSchema(
        CLUSTER_KINDS, CLUSTER_REQUIRED, CLUSTER_NULLABLE,
        counters=("rank", "generation", "current_generation",
                  "prev_generation", "new_generation", "good_step",
                  "target_step", "step", "expired_rank", "leader",
                  "n_removed", "n_refused", "n_intents"),
        enums={"action": CLUSTER_ACTIONS}, special=_cluster_special),
    "integrity": ChannelSchema(
        INTEGRITY_KINDS, INTEGRITY_REQUIRED, INTEGRITY_NULLABLE,
        counters=("rank", "step", "check_step", "n_ranks",
                  "mismatch_count", "new_mismatches", "fp_min",
                  "fp_max", "source_rank", "majority_fp",
                  "generation"),
        enums={"action": {"integrity_vote": INTEGRITY_VOTE_ACTIONS,
                          "integrity_repair":
                              INTEGRITY_REPAIR_ACTIONS}},
        special=_integrity_special),
    "numerics": ChannelSchema(
        NUMERICS_KINDS, NUMERICS_REQUIRED, NUMERICS_NULLABLE,
        counters=("rank", "step", "check_count", "n_sites"),
        nonneg=("amax", "amin", "uw_ratio", "wall_time"),
        enums={"action": {"scale_update": NUMERICS_SCALE_ACTIONS},
               "required_dtype": NUMERICS_FORMATS,
               "current_dtype": NUMERICS_FORMATS},
        special=_numerics_special),
    "podview": ChannelSchema(
        PODVIEW_KINDS, PODVIEW_REQUIRED, PODVIEW_NULLABLE,
        counters=("rank", "reference", "n_shared", "step",
                  "occurrence", "n_ranks", "blamed_rank", "hop"),
        nonneg=("residual_ms", "skew_ms", "wire_ms", "predicted_ms",
                "measured_ms", "wall_time"),
        enums={"link": PODVIEW_LINKS},
        special=_podview_special),
    "sharding": ChannelSchema(
        SHARDING_KINDS, SHARDING_REQUIRED, SHARDING_NULLABLE,
        counters=("rank", "step", "hbm_sharded_bytes",
                  "hbm_replicated_bytes", "wire_bytes"),
        nonneg=("predicted_s", "wall_time"),
        special=_sharding_special),
    "dynamics": ChannelSchema(
        DYNAMICS_KINDS, DYNAMICS_REQUIRED, DYNAMICS_NULLABLE,
        counters=("rank", "step", "check_count", "n_sites",
                  "local_batch", "n_flagged", "n_steps",
                  "first_flag_step"),
        nonneg=("eff_lr", "uw_ratio", "world", "local_sq",
                "pooled_sq", "max_gap", "band_z", "wall_time"),
        enums={"verdict": DYNAMICS_VERDICTS},
        special=_dynamics_special),
}


# --- metrics schema (the buffered stream — its own wire format) ---------------

def check_lines(lines) -> List[str]:
    """All metrics-schema violations in an iterable of JSONL lines
    (empty = ok)."""
    errors: List[str] = []
    prev_step = None
    n_records = 0
    for i, rec in _iter_objects(lines, errors):
        n_records += 1
        for key in REQUIRED:
            if key not in rec:
                errors.append(f"line {i}: missing required key {key!r}")
        for key, v in rec.items():
            if v is None and key not in NULLABLE:
                errors.append(f"line {i}: {key!r} is null "
                              f"(only {NULLABLE} may be)")
        _check_finite_numbers(i, rec, errors)
        for key in COUNTERS:
            _check_counter(i, rec, key, errors)
        step = rec.get("step")
        if isinstance(step, int) and not isinstance(step, bool):
            if prev_step is not None and step <= prev_step:
                errors.append(f"line {i}: step {step} not greater than "
                              f"previous step {prev_step}")
            prev_step = step
    if n_records == 0:
        errors.append("no records found")
    return errors


# channel checkers, by their historical names (audit scripts and tests
# import these directly; each is the registry row's checker)
check_trace_lines = _make_checker(SCHEMAS["trace"])
check_memory_lines = _make_checker(SCHEMAS["memory"])
check_lint_lines = _make_checker(SCHEMAS["lint"])
check_ckpt_lines = _make_checker(SCHEMAS["ckpt"])
check_guard_lines = _make_checker(SCHEMAS["guard"])
check_goodput_lines = _make_checker(SCHEMAS["goodput"])
check_roofline_lines = _make_checker(SCHEMAS["roofline"])
check_cluster_lines = _make_checker(SCHEMAS["cluster"])
check_integrity_lines = _make_checker(SCHEMAS["integrity"])
check_numerics_lines = _make_checker(SCHEMAS["numerics"])
check_podview_lines = _make_checker(SCHEMAS["podview"])
check_sharding_lines = _make_checker(SCHEMAS["sharding"])
check_dynamics_lines = _make_checker(SCHEMAS["dynamics"])

CHECKERS = {"metrics": check_lines, "trace": check_trace_lines,
            "memory": check_memory_lines, "lint": check_lint_lines,
            "ckpt": check_ckpt_lines, "guard": check_guard_lines,
            "goodput": check_goodput_lines,
            "roofline": check_roofline_lines,
            "cluster": check_cluster_lines,
            "integrity": check_integrity_lines,
            "numerics": check_numerics_lines,
            "podview": check_podview_lines,
            "sharding": check_sharding_lines,
            "dynamics": check_dynamics_lines}


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    kind = "metrics"
    files: List[str] = []
    it = iter(argv)
    for a in it:
        if a in ("-h", "--help"):
            print(__doc__)
            return 2
        if a == "--kind":
            kind = next(it, "")
        elif a.startswith("--kind="):
            kind = a.split("=", 1)[1]
        else:
            files.append(a)
    if kind not in CHECKERS or len(files) != 1:
        print(__doc__)
        return 2
    try:
        with open(files[0]) as f:
            errors = CHECKERS[kind](f)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        print(f"{files[0]}: INVALID ({len(errors)} violations)",
              file=sys.stderr)
        return 1
    print(f"{files[0]}: ok ({kind})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
