"""Donation-safe async device→host snapshots — the step-path half.

A checkpoint of a *donated* train state cannot simply hold references:
the next dispatch invalidates the input buffers ("Array has been
deleted" — the failure mode :func:`apex_tpu.monitor.metrics_snapshot`
exists for). And a checkpoint that ``device_get``s synchronously stalls
the step loop for the full HBM→host transfer. This module does neither:

1. **Capture** (:func:`device_snapshot`): every leaf is copied into a
   fresh device buffer (``a.copy()`` — async dispatch, exactly the
   PR-5 donation-safety machinery generalized from Metrics scalars to
   the whole training tuple), then ``copy_to_host_async`` starts the
   D2H transfer in the background. The step path pays only the copy
   dispatch — the bounded stall the bench row measures.
2. **Materialize** (worker thread inside :class:`Snapshotter`): the
   host numpy arrays land off the step path; the finished
   :class:`HostSnapshot` becomes :attr:`Snapshotter.last` — the state
   an escalation policy can persist *without touching the (possibly
   wedged) device*.

Double-buffered: at most one capture is in flight. Starting a new one
while the previous is still materializing waits for it first, so HBM
holds at most one extra copy of the snapshot tree and the stall stays
bounded instead of queueing unboundedly behind a slow disk.

Typed PRNG keys (``jax.random.key``) are snapshotted as their raw
``key_data`` plus the impl name, and re-wrapped on restore — numpy
cannot hold an opaque key dtype.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

__all__ = ["HostSnapshot", "ShardChunks", "Snapshotter",
           "device_snapshot", "tree_paths", "is_prng_key"]


class ShardChunks:
    """This process's pieces of a multi-process sharded array.

    ``chunks`` is ``[(index, np.ndarray)]`` where ``index`` is a tuple
    of ``(start, stop)`` pairs per dim into the global ``shape``. A
    fully-addressable array never becomes one of these (it materializes
    as a plain numpy array); the format layer writes each chunk with its
    global index so restore can gather by manifest.
    """

    __slots__ = ("shape", "dtype", "chunks")

    def __init__(self, shape, dtype, chunks):
        self.shape = tuple(int(d) for d in shape)
        self.dtype = np.dtype(dtype)
        self.chunks = list(chunks)


def is_prng_key(x) -> bool:
    """True for typed PRNG key arrays (opaque dtype, needs unwrapping)."""
    try:
        return isinstance(x, jax.Array) and jax.dtypes.issubdtype(
            x.dtype, jax.dtypes.prng_key)
    except Exception:
        return False


def tree_paths(tree) -> list:
    """``(path_str, leaf)`` pairs in flatten order — the stable leaf
    addressing shared by capture (here) and the on-disk format
    (:mod:`apex_tpu.ckpt.format`). Path strings come from
    ``jax.tree_util.keystr``, so two trees with the same structure
    always produce the same names."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


@jax.jit
def _copy_leaves(leaves):
    """One program copying every leaf: N fresh buffers for ONE dispatch
    (per-leaf ``.copy()`` costs a dispatch each — measured 100s of ms
    of step stall on wide trees; this is the bounded-stall half of the
    <5%-of-step claim). Without donation XLA cannot alias inputs to
    outputs, so the results are genuinely fresh, donation-safe buffers.
    """
    return [jax.numpy.asarray(l).copy() for l in leaves]


def device_snapshot(tree):
    """Donation-safe device copy of a pytree + async D2H start.

    Returns ``(copies, keys)``: ``copies`` mirrors ``tree`` with every
    device leaf replaced by a fresh buffer (typed PRNG keys by their
    uint32 ``key_data``); ``keys`` maps path→impl-name for the key
    leaves so a restore can re-wrap them. Host leaves (ints, numpy)
    pass through untouched.
    """
    keys: Dict[str, str] = {}
    flat = jax.tree_util.tree_flatten_with_path(tree)
    out_leaves = []
    to_copy = []                   # (position, leaf) device leaves
    for path, leaf in flat[0]:
        if is_prng_key(leaf):
            keys[jax.tree_util.keystr(path)] = str(
                jax.random.key_impl(leaf))
            leaf = jax.random.key_data(leaf)
        if isinstance(leaf, jax.Array):
            to_copy.append((len(out_leaves), leaf))
        out_leaves.append(leaf)
    if to_copy:
        copied = _copy_leaves([l for _, l in to_copy])
        for (pos, _), c in zip(to_copy, copied):
            try:
                c.copy_to_host_async()
            except Exception:
                pass          # backends without async D2H still work
            out_leaves[pos] = c
    copies = jax.tree_util.tree_unflatten(flat[1], out_leaves)
    return copies, keys


class HostSnapshot:
    """One fully-materialized host-side snapshot of the training tuple.

    ``tree`` holds numpy leaves (typed keys already unwrapped —
    ``prng_impls`` carries their impl names); ``extra`` is the host-side
    side-channel (data-pipeline cursor, user tags) captured atomically
    with the device state.
    """

    __slots__ = ("step", "tree", "prng_impls", "extra", "wall_time",
                 "stall_ms", "persist")

    def __init__(self, step: int, tree, prng_impls: Dict[str, str],
                 extra: Optional[Dict[str, Any]], stall_ms: float,
                 persist: bool = True):
        self.step = int(step)
        self.tree = tree
        self.prng_impls = dict(prng_impls)
        self.extra = dict(extra) if extra else {}
        self.wall_time = time.time()
        self.stall_ms = float(stall_ms)
        #: False = capture-only (kept as ``Snapshotter.last`` for an
        #: escalation to persist on demand; nothing written eagerly)
        self.persist = persist


def _materialize_leaf(a):
    if not isinstance(a, jax.Array):
        return a
    if a.is_fully_addressable:
        return np.asarray(a)
    # multi-process: this host only sees its shards — keep each distinct
    # addressable shard with its global index (gather happens at restore,
    # by manifest)
    seen, chunks = set(), []
    for sh in a.addressable_shards:
        idx = tuple(
            (0 if s.start is None else int(s.start),
             int(d) if s.stop is None else int(s.stop))
            for s, d in zip(sh.index, a.shape))
        if not a.shape:
            idx = ()
        if idx in seen:
            continue
        seen.add(idx)
        chunks.append((idx, np.asarray(sh.data)))
    return ShardChunks(a.shape, a.dtype, chunks)


def _materialize(copies):
    """Device copies → numpy leaves (the worker-thread fetch)."""
    return jax.tree_util.tree_map(_materialize_leaf, copies)


class Snapshotter:
    """Double-buffered async snapshot pipeline.

    ::

        snap = ckpt.Snapshotter()
        for i, batch in enumerate(data):
            state = train_step(state, batch)       # donated
            if i % 100 == 0:
                snap.capture(i, state, extra={"cursor": src.state()})
        snap.wait()
        snap.last                                  # newest HostSnapshot

    ``capture`` is the only call on the step path; its wall time is the
    snapshot's step stall (recorded as ``HostSnapshot.stall_ms``).
    ``on_ready`` fires on the worker thread with each finished snapshot
    — the CheckpointManager's async-write hook.
    """

    def __init__(self, on_ready: Optional[Callable[[HostSnapshot],
                                                   None]] = None):
        self.on_ready = on_ready
        self.last: Optional[HostSnapshot] = None
        #: first error the worker thread hit (materialization OR
        #: on_ready); re-raised by the next capture()/wait() so a dead
        #: snapshot pipeline can never silently stop checkpointing
        self.error: Optional[BaseException] = None
        self._pending: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def capture(self, step: int, tree, *,
                extra: Optional[Dict[str, Any]] = None,
                block: bool = False, persist: bool = True) -> float:
        """Snapshot ``tree`` (donation-safe). Returns the step-path
        stall in milliseconds. ``block=True`` waits for the host copy
        and any ``on_ready`` work before returning (the sync-save
        comparison mode in the bench). ``persist=False`` marks the
        snapshot capture-only — ``on_ready`` consumers that write to
        disk honor the flag (``CheckpointManager.snapshot``)."""
        t0 = time.perf_counter()
        self.wait()                      # double-buffer: one in flight
        copies, keys = device_snapshot(tree)
        stall_ms = (time.perf_counter() - t0) * 1e3

        def work():
            try:
                host = _materialize(copies)
                snap = HostSnapshot(step, host, keys, extra, stall_ms,
                                    persist=persist)
                with self._lock:
                    self.last = snap
                if self.on_ready is not None:
                    self.on_ready(snap)
            except BaseException as e:   # surfaced on next capture/wait
                with self._lock:
                    if self.error is None:
                        self.error = e

        t = threading.Thread(target=work, daemon=True,
                             name="apex_tpu.ckpt.snapshot")
        self._pending = t
        t.start()
        if block:
            self.wait()
            stall_ms = (time.perf_counter() - t0) * 1e3
        return stall_ms

    def wait(self, timeout: Optional[float] = None) -> None:
        """Drain the in-flight materialization (no-op when idle);
        re-raises any error the worker hit."""
        t = self._pending
        if t is not None and t.is_alive():
            t.join(timeout)
        if t is not None and not t.is_alive():
            self._pending = None
        self.raise_pending()

    def raise_pending(self) -> None:
        with self._lock:
            err, self.error = self.error, None
        if err is not None:
            raise err
