"""Noise-calibrated convergence A/B harness: the trajectory comparator.

Items 4 and 5's done-bars ("O4/fp8 converges within tolerance of O2",
"Adasum matches the baseline at a higher effective batch") need a
machine answer to *"does trajectory B match trajectory A?"* — and a
hand-picked ``rtol`` is exactly the wrong instrument, because the right
tolerance IS the run-to-run seed noise, which varies by model, batch
size and step count. This module calibrates the band instead of
guessing it:

- **calibrate** (:func:`calibrate_band`): run the SAME config twice (or
  more) with paired seeds — same data order, different init/dropout
  streams — and apply the perf_sentinel robust statistics
  (median + z·1.4826·MAD, :mod:`apex_tpu.prof.sentinel`) over the
  pooled per-step loss-gap trajectory. The result is a :class:`Band`:
  "two runs that differ only by seed noise stay within THIS loss gap";
- **compare** (:func:`convergence_report`): walk two trajectories
  step-aligned and emit a pass/flag :class:`ConvergenceVerdict` naming
  the FIRST step outside the band (``grace`` early steps are exempt —
  warmup gaps before the trajectories lock in are seed noise by
  construction). The verdict serializes as the dynamics channel's
  ``kind="convergence_verdict"`` event (``check_metrics_schema.py
  --kind dynamics`` validates).

Pure host-side numpy — trajectories are lists of floats (the logged
per-step losses), so the comparator runs anywhere the metrics stream
lands, devices long gone. Workflow + worked example:
docs/dynamics.md#convergence. The asserted CI exercise is
``scripts/dynamics_audit.py --cpu8`` (a seeded too-high-LR divergence
flagged at the right step; a paired-seed twin staying quiet).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence

__all__ = ["Band", "ConvergenceVerdict", "calibrate_band",
           "convergence_report"]

#: MAD → sigma under normality (the perf_sentinel constant)
_MAD_SIGMA = 1.4826


@dataclasses.dataclass(frozen=True)
class Band:
    """A calibrated loss-gap tolerance: ``|loss_a[t] − loss_b[t]| ≤
    threshold`` is "within seed noise"."""

    threshold: float     #: the absolute per-step loss-gap bound
    median_gap: float    #: median |gap| of the calibration trajectory
    mad_gap: float       #: MAD of the calibration |gap|s
    z: float             #: robust-sigma multiplier used
    n_pairs: int         #: calibration run pairs pooled
    n_steps: int         #: calibration steps pooled per pair
    floor: float         #: absolute floor applied

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: Dict) -> "Band":
        return cls(**{f.name: d[f.name]
                      for f in dataclasses.fields(cls)})


def _gaps(a: Sequence[float], b: Sequence[float]) -> List[float]:
    n = min(len(a), len(b))
    return [abs(float(a[t]) - float(b[t])) for t in range(n)]


def _median(xs: List[float]) -> float:
    ys = sorted(xs)
    n = len(ys)
    mid = n // 2
    return ys[mid] if n % 2 else 0.5 * (ys[mid - 1] + ys[mid])


def calibrate_band(runs: Sequence[Sequence[float]], *, z: float = 6.0,
                   floor: float = 1e-9) -> Band:
    """Calibrate the seed-noise band from ≥ 2 paired-seed loss
    trajectories of the SAME config: every pair's per-step |gap|s pool
    into one sample, and the band is ``median + z·1.4826·MAD`` (the
    perf_sentinel statistics applied to convergence), floored at
    ``floor``.

    ``z`` defaults to 6 — deliberately generous: the comparator's job
    is to catch *divergence* (a trajectory leaving the noise cone and
    not coming back), not to referee ULP-level wobble; docs/dynamics.md
    discusses tightening it when more calibration pairs are pooled."""
    runs = [list(map(float, r)) for r in runs]
    if len(runs) < 2:
        raise ValueError(f"calibrate_band needs >= 2 paired-seed runs, "
                         f"got {len(runs)}")
    for i, r in enumerate(runs):
        if len(r) < 2:
            raise ValueError(f"calibration run {i} has {len(r)} steps; "
                             f"need >= 2")
        bad = [v for v in r if not math.isfinite(v)]
        if bad:
            raise ValueError(f"calibration run {i} contains nonfinite "
                             f"losses — calibrate on healthy runs only")
    pooled: List[float] = []
    n_pairs = 0
    n_steps = min(len(r) for r in runs)
    for i in range(len(runs)):
        for j in range(i + 1, len(runs)):
            pooled.extend(_gaps(runs[i], runs[j]))
            n_pairs += 1
    med = _median(pooled)
    mad = _median([abs(g - med) for g in pooled])
    threshold = max(med + z * _MAD_SIGMA * mad, float(floor))
    return Band(threshold=threshold, median_gap=med, mad_gap=mad,
                z=float(z), n_pairs=n_pairs, n_steps=n_steps,
                floor=float(floor))


@dataclasses.dataclass
class ConvergenceVerdict:
    """The A/B comparator's machine-readable answer."""

    verdict: str                    #: "pass" | "flag"
    first_flag_step: Optional[int]  #: first step outside the band
    n_flagged: int                  #: steps outside the band
    n_steps: int                    #: steps compared (min of the two)
    max_gap: float                  #: worst |loss_a − loss_b| seen
    max_gap_step: int               #: where the worst gap sat
    band: Band                      #: the tolerance applied
    grace: int                      #: warmup steps exempted

    @property
    def ok(self) -> bool:
        return self.verdict == "pass"

    @property
    def fingerprint(self) -> str:
        """Stable ``dynamics|convergence|loss`` key — the waiver/pin
        identity (never includes measured numbers or the verdict)."""
        return "dynamics|convergence|loss"

    def to_event(self, rank: int = 0) -> Dict:
        """``kind="convergence_verdict"`` event for the dynamics
        channel (``check_metrics_schema.py --kind dynamics``
        validates)."""
        return {"kind": "convergence_verdict", "rank": rank,
                "step": self.first_flag_step,
                "verdict": self.verdict,
                "first_flag_step": self.first_flag_step,
                "n_flagged": self.n_flagged,
                "n_steps": self.n_steps,
                "max_gap": (self.max_gap
                            if math.isfinite(self.max_gap) else None),
                "band_threshold": self.band.threshold,
                "band_z": self.band.z,
                "fingerprint": self.fingerprint}

    def summary(self) -> str:
        if self.ok:
            return (f"convergence PASS: {self.n_steps} steps within "
                    f"band {self.band.threshold:.4g} (max gap "
                    f"{self.max_gap:.4g} @ step {self.max_gap_step})")
        return (f"convergence FLAG: first excursion at step "
                f"{self.first_flag_step} ({self.n_flagged}/"
                f"{self.n_steps} steps outside band "
                f"{self.band.threshold:.4g}; max gap "
                f"{self.max_gap:.4g} @ step {self.max_gap_step})")


def convergence_report(run_a: Sequence[float],
                       run_b: Sequence[float], *,
                       band: Optional[Band] = None,
                       calibration: Optional[
                           Sequence[Sequence[float]]] = None,
                       z: float = 6.0, floor: float = 1e-9,
                       grace: int = 0) -> ConvergenceVerdict:
    """Compare loss trajectory B against reference A under a
    noise-calibrated band; flag the FIRST step whose |gap| leaves it.

    Pass either ``band`` (a pre-calibrated :class:`Band` — calibrate
    once per config, reuse across comparisons) or ``calibration`` (≥ 2
    paired-seed trajectories; :func:`calibrate_band` runs inline with
    ``z``/``floor``). A nonfinite loss in either run flags immediately
    at that step — divergence to inf/nan is never inside any band.
    ``grace`` exempts the first N steps (warmup, before paired-seed
    trajectories decouple from init noise).
    """
    if band is None:
        if calibration is None:
            raise ValueError("convergence_report needs band= or "
                             "calibration= (>= 2 paired-seed runs)")
        band = calibrate_band(calibration, z=z, floor=floor)
    a = list(map(float, run_a))
    b = list(map(float, run_b))
    n = min(len(a), len(b))
    if n < 1:
        raise ValueError("convergence_report needs non-empty runs")
    first: Optional[int] = None
    n_flagged = 0
    max_gap, max_gap_step = 0.0, 0
    for t in range(n):
        if not (math.isfinite(a[t]) and math.isfinite(b[t])):
            gap = math.inf
        else:
            gap = abs(a[t] - b[t])
        if gap > max_gap:
            max_gap, max_gap_step = gap, t
        if t < grace:
            continue
        if gap > band.threshold:
            n_flagged += 1
            if first is None:
                first = t
    return ConvergenceVerdict(
        verdict="pass" if first is None else "flag",
        first_flag_step=first, n_flagged=n_flagged, n_steps=n,
        max_gap=max_gap, max_gap_step=max_gap_step, band=band,
        grace=int(grace))
