#!/usr/bin/env python
"""link_probe — measure link α–β budgets and emit a MeshModel JSON.

Sweeps all-reduce / reduce-scatter / all-gather across message sizes
per mesh axis (:mod:`apex_tpu.monitor.linkbench`), fits latency +
inverse-bandwidth per axis, and writes a
:class:`apex_tpu.lint.mesh_model.MeshModel` JSON whose
``link_bytes_per_s`` is MEASURED (with the fit provenance in its
``calibration`` block). The output is directly consumable by:

- ``scripts/apexlint.py --mesh <out.json>`` — APX203's
  flat-vs-hierarchical DCN milliseconds are then computed from the
  measured budgets, not the ``DEFAULT_LINK_BYTES_PER_S`` constants;
- ``scripts/pod_comm_budget.py --mesh <out.json>`` — the weak-scaling
  ICI budget uses the measured bytes/s.

Usage:
  python scripts/link_probe.py --cpu8 [--out FILE] [--jsonl FILE]
      # 8 virtual CPU devices factored dp2x4 (2 modeled "slices" over
      # DCN x 4 chips over ICI) — the CI pipeline proof
      # (run_tier1.sh --smoke); measures XLA:CPU's collective
      # emulation, structurally identical to the on-chip run
  python scripts/link_probe.py --tpu [--spec dpAxB|iciN] [--out FILE]
      # the local accelerator mesh; default spec ici<N> over all
      # local devices
Options:
  --out FILE     MeshModel JSON path (default MESH_MEASURED.json)
  --jsonl FILE   also stream kind="linkfit" events (goodput channel;
                 validate with check_metrics_schema.py --kind goodput)
  --sizes a,b,c  message-size ladder in bytes
  --iters N      best-of iterations per point (default 3)
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    mode = None
    out_path, jsonl_path, spec = "MESH_MEASURED.json", None, None
    sizes, iters = None, 3
    it = iter(argv)
    for a in it:
        if a in ("-h", "--help"):
            print(__doc__)
            return 2
        if a == "--cpu8":
            mode = "cpu8"
        elif a == "--tpu":
            mode = "tpu"
        elif a in ("--out", "--jsonl", "--sizes", "--iters", "--spec"):
            val = next(it, None)
            if val is None:
                print(f"{a} requires a value", file=sys.stderr)
                return 2
            if a == "--out":
                out_path = val
            elif a == "--jsonl":
                jsonl_path = val
            elif a == "--spec":
                spec = val
            elif a == "--sizes":
                sizes = tuple(int(s) for s in val.split(","))
            else:
                iters = int(val)
        else:
            print(f"unknown arg {a!r}\n{__doc__}", file=sys.stderr)
            return 2
    if mode is None:
        print(__doc__, file=sys.stderr)
        return 2

    if mode == "cpu8":
        import jax
        jax.config.update("jax_platforms", "cpu")
        from apex_tpu import _compat
        _compat.request_cpu_devices(8)
        spec = spec or "dp2x4"
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from apex_tpu.lint.mesh_model import parse_mesh_spec
    from apex_tpu.monitor import linkbench

    devs = jax.devices()
    if spec is None:
        spec = f"ici{len(devs)}"
    template = parse_mesh_spec(spec, n_devices=len(devs))
    if template.n_devices > len(devs):
        print(f"spec {spec!r} wants {template.n_devices} devices, "
              f"have {len(devs)}", file=sys.stderr)
        return 2
    shape = tuple(a.size for a in template.axes)
    mesh = Mesh(np.array(devs[:template.n_devices]).reshape(shape),
                tuple(a.name for a in template.axes))
    print(f"link_probe: {jax.default_backend()} mesh "
          f"{dict(zip(mesh.axis_names, shape))} (spec {spec})")

    kwargs = dict(iters=iters)
    if sizes:
        kwargs["sizes"] = sizes
    model, fits, samples = linkbench.calibrate(mesh, template, **kwargs)
    print(linkbench.fit_table(fits, samples))
    for link, bps in sorted(model.link_bytes_per_s.items()):
        cal = model.calibration.get(link)
        src = (f"measured on axis {cal['axis']}" if cal
               else "default (no axis of this class swept)")
        print(f"  link {link}: {bps / 1e9:.3f} GB/s ({src})")

    with open(out_path, "w") as f:
        json.dump(model.to_json(), f, indent=2)
        f.write("\n")
    print(f"wrote {out_path} (consume: scripts/apexlint.py --mesh "
          f"{out_path} | scripts/pod_comm_budget.py --mesh {out_path})")

    if jsonl_path:
        from apex_tpu import monitor
        logger = monitor.MetricsLogger(
            sinks=[], goodput_sink=monitor.JSONLSink(jsonl_path))
        for ev in linkbench.linkfit_events(model):
            logger.record_goodput(ev)
        logger.close()
        print(f"wrote {jsonl_path} (validate: python "
              f"scripts/check_metrics_schema.py --kind goodput "
              f"{jsonl_path})")

    # sanity the emitted artifact: the written file must round-trip as
    # a MeshModel (whatever the --out name — the spec grammar only
    # file-loads *.json paths) with measured provenance on every
    # fitted link
    from apex_tpu.lint.mesh_model import MeshModel
    with open(out_path) as f:
        rt = MeshModel.from_json(json.load(f))
    assert rt.measured and rt.calibration == model.calibration
    for link in model.calibration:
        assert rt.link_bytes_per_s[link] > 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
