"""The cross-rank half of apexlint: SPMD congruence + topology rules.

The jaxpr and HLO passes audit one program; the failures that cost a
*pod* are cross-rank: replica groups that disagree between two ranks'
programs deadlock every chip in the group, sharding propagation
silently materializes a replicated operand with a full all-gather, a
flat all-reduce spans a DCN boundary that wanted a hierarchical
schedule, and a nondeterministic draw breaks guard's bitwise-rewind
oracle only after the rewind. All four are statically visible — this
pass is strictly AOT like the other two (trace + compile, never a
dispatch).

- **spmd-divergence** (APX201): extract each rank's collective
  *schedule* (ordered collectives with channel ids, replica groups,
  dtypes, wire bytes) and walk all ranks in lockstep — every
  collective must appear in identical order with matching channel id,
  replica groups and dtype across all participants. The first
  diverging op is reported with the rank pair; a rank whose schedule
  runs dry while a peer still waits is the deadlock shape. One SPMD
  module is congruent by construction, but its groups are still
  checked for well-formedness (disjoint, covering); per-rank compiled
  modules (MPMD, elastic restarts on mixed binaries) get the full
  cross-program check.
- **implicit-full-gather** (APX202): an ``all-gather`` whose stripped
  scope matches no row of the collective-scope registry
  (:mod:`apex_tpu.parallel.registry`) — sharding propagation
  materializing a replicated operand the user never asked for, with
  the wire bytes and the materialized HBM bytes as evidence.
- **dcn-flat-collective** (APX203): a reduction collective whose
  replica group crosses a slice (DCN) boundary *and* keeps more than
  one member inside some slice — the flat one-hop shape. A
  hierarchical schedule reduces within-slice over ICI first, so its
  DCN hop carries 1/local_size of the bytes. Fires on planned scopes
  too: topology, not attribution. Wire-byte evidence uses the same
  result-shape accounting as ``monitor.wire_report``.
- **nondeterminism** (APX204): the static complement to guard's
  bitwise-rewind guarantee — ``rng_bit_generator`` whose key is a
  baked-in constant or whose updated state is dropped (not threaded
  through carried state: a rewind cannot replay the stream),
  ``pure_callback``/``io_callback`` results feeding the committed
  outputs (host values on the commit path re-run differently), and
  float scatter-adds with ``unique_indices=False`` (order-sensitive
  accumulation under SPMD repartitioning; warning severity).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

import numpy as np

from apex_tpu.lint.findings import Finding
from apex_tpu.lint.mesh_model import MeshModel
from apex_tpu.prof import memory as _mem
from apex_tpu.prof.xplane import COLLECTIVE_PREFIXES, strip_scope

__all__ = ["CollectiveInstr", "extract_collective_schedule",
           "parse_replica_groups", "rank_schedule",
           "congruence_findings", "full_gather_findings",
           "dcn_flat_findings", "nondeterminism_jaxpr_findings",
           "lint_spmd_text"]


# -- collective-schedule extraction -------------------------------------------

@dataclasses.dataclass(frozen=True)
class CollectiveInstr:
    """One collective in a module's schedule, with its match identity."""

    index: int                       # position among the module's collectives
    name: str                        # HLO instruction name
    opcode: str                      # normalized ("all-reduce", ...)
    channel_id: Optional[int]
    replica_groups: Tuple[Tuple[int, ...], ...]  # () = one implicit
                                                 # all-devices group
    dtypes: Tuple[str, ...]          # result dtypes
    bytes: int                       # wire bytes — result-shape
                                     # accounting, = monitor.wire_report
    scope: str                       # stripped named-scope path
    use_global_ids: bool = True

    def identity(self) -> Tuple:
        """The congruence-match key: what every participant must agree
        on for the collective to complete (wire bytes included — a
        dtype-matched but size-mismatched pair still hangs)."""
        return (self.opcode, self.channel_id, self.replica_groups,
                self.dtypes, self.bytes)

    def describe(self) -> str:
        groups = ("all-devices" if not self.replica_groups else
                  "{" + ",".join(
                      "{" + ",".join(map(str, g)) + "}"
                      for g in self.replica_groups[:4])
                  + (",..." if len(self.replica_groups) > 4 else "")
                  + "}")
        return (f"{self.opcode}(channel={self.channel_id}, "
                f"groups={groups}, {'+'.join(self.dtypes)}, "
                f"{self.bytes}B)")


_CHANNEL_RE = re.compile(r"channel_id=(\d+)")
_GROUPS_RE = re.compile(
    r"replica_groups=(\{(?:[^{}]|\{[^{}]*\})*\}"
    r"|\[[\d,]+\]<=\[[\d,]+\](?:T\([\d,]+\))?)")
_IOTA_RE = re.compile(
    r"^\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?$")


def parse_replica_groups(text: str) -> Tuple[Tuple[int, ...], ...]:
    """Parse either replica-group syntax XLA prints:

    explicit ``{{0,1},{2,3}}`` (also ``{}``), or iota(-v2)
    ``[G,S]<=[d0,d1,...]`` with optional transpose ``T(p...)`` —
    ``arange(prod(d)).reshape(d).transpose(p).reshape(G, S)``.
    """
    text = text.strip()
    m = _IOTA_RE.match(text)
    if m:
        gshape = [int(x) for x in m.group(1).split(",") if x]
        dims = [int(x) for x in m.group(2).split(",") if x]
        arr = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(3):
            perm = [int(x) for x in m.group(3).split(",") if x]
            arr = arr.transpose(perm)
        arr = arr.reshape(gshape)
        return tuple(tuple(int(v) for v in row) for row in arr)
    if not (text.startswith("{") and text.endswith("}")):
        raise ValueError(f"unrecognized replica_groups {text!r}")
    groups = []
    for gm in re.finditer(r"\{([\d, ]*)\}", text[1:-1]):
        ids = [int(x) for x in gm.group(1).replace(" ", "").split(",")
               if x]
        if ids:
            groups.append(tuple(ids))
    return tuple(groups)


def _split_top_level(s: str) -> List[str]:
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "({[":
            depth += 1
        elif ch in ")}]":
            depth -= 1
        if ch == "," and depth == 1 and s[0] == "(":
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return [p.strip() for p in parts if p.strip()]


def _result_shape_of_start(shape: str) -> str:
    """The result half of an async ``-start`` tuple shape: the tuple is
    ``(operands..., results...)``, so the trailing half is the payload
    the matching ``-done`` returns."""
    if not shape.startswith("("):
        return shape
    parts = _split_top_level(shape)
    if parts and parts[0].startswith("("):
        parts[0] = parts[0][1:]
    if len(parts) >= 2 and len(parts) % 2 == 0:
        return " ".join(parts[len(parts) // 2:])
    return shape  # odd arity — caller falls back to halved bytes


def _dtypes_of(shape: str) -> Tuple[str, ...]:
    return tuple(sorted({dt for dt, _ in _mem._SHAPE_RE.findall(shape)
                         if dt in _mem._DTYPE_BYTES}))


def extract_collective_schedule(hlo_text: str) -> List[CollectiveInstr]:
    """Ordered collectives of an optimized module (entry + nested
    computations, textual schedule order). Async pairs are recorded at
    the ``-start`` (the issue point a deadlock hangs at, and the line
    carrying channel id + replica groups); their ``-done`` halves are
    skipped. Wire bytes use the result shape — identical accounting to
    ``monitor.collective_bytes_by_dtype``, so topology findings agree
    with ``monitor.wire_report`` by construction."""
    out: List[CollectiveInstr] = []
    for raw in hlo_text.splitlines():
        line = raw.strip()
        m = _mem._INSTR_RE.match(line)
        if not m:
            continue
        op = m.group("op")
        prefix = next((p for p in COLLECTIVE_PREFIXES
                       if op.startswith(p)), None)
        if prefix is None:
            continue
        if op == prefix + "-done":
            continue                       # recorded at the -start
        shape = m.group("shape")
        if op == prefix + "-start":
            result_shape = _result_shape_of_start(shape)
            nbytes = (_mem.shape_bytes(result_shape)
                      if result_shape != shape
                      else _mem.shape_bytes(shape) // 2)
        else:
            result_shape = shape
            nbytes = _mem.shape_bytes(shape)
        cm = _CHANNEL_RE.search(line)
        gm = _GROUPS_RE.search(line)
        sm = _mem._OP_NAME_RE.search(line)
        out.append(CollectiveInstr(
            index=len(out),
            name=m.group("n").lstrip("%"),
            opcode=prefix,
            channel_id=int(cm.group(1)) if cm else None,
            replica_groups=(parse_replica_groups(gm.group(1))
                            if gm else ()),
            dtypes=_dtypes_of(result_shape),
            bytes=nbytes,
            scope=strip_scope(sm.group(1)) if sm else "",
            use_global_ids="use_global_device_ids=true" in line))
    return out


# -- APX201: cross-rank congruence --------------------------------------------

def _participants(instr: CollectiveInstr, all_ranks: Sequence[int]
                  ) -> List[int]:
    if not instr.replica_groups:
        return list(all_ranks)
    members = {m for g in instr.replica_groups for m in g}
    return [r for r in all_ranks if r in members]


def rank_schedule(schedule: Sequence[CollectiveInstr],
                  rank: int) -> List[CollectiveInstr]:
    """The subsequence of a module's collectives ``rank`` participates
    in (member of some replica group, or every collective when groups
    are implicit)."""
    out = []
    for instr in schedule:
        if not instr.replica_groups or any(
                rank in g for g in instr.replica_groups):
            out.append(instr)
    return out


def _group_shape_findings(rank: int, schedule: Sequence[CollectiveInstr],
                          n_ranks: int) -> List[Finding]:
    """Per-module well-formedness: groups must be disjoint and (with
    global device ids) cover every rank — a rank left out of all groups
    of an instruction it executes never joins the rendezvous."""
    out: List[Finding] = []
    chan_groups: Dict[int, Tuple[Tuple[Tuple[int, ...], ...], str]] = {}
    for instr in schedule:
        seen: Set[int] = set()
        dup = [m for g in instr.replica_groups for m in g
               if m in seen or seen.add(m)]
        if dup:
            d = sorted(set(dup))
            out.append(Finding(
                rule="spmd-divergence",
                message=f"{instr.describe()} lists rank(s) {d} in "
                        f"more than one replica group — the groups "
                        f"must partition the mesh",
                op=instr.opcode, scope=instr.scope or instr.name,
                # ranks is a PAIR in the event schema; a single
                # double-listed rank carries its evidence in the message
                ranks=d[:2] if len(d) >= 2 else None))
        elif (instr.replica_groups and instr.use_global_ids
                and len(seen) not in (0, n_ranks)):
            missing = sorted(set(range(n_ranks)) - seen)
            out.append(Finding(
                rule="spmd-divergence",
                message=f"rank {rank}: {instr.describe()} covers only "
                        f"{len(seen)}/{n_ranks} ranks — rank(s) "
                        f"{missing[:4]} execute the op but belong to "
                        f"no group",
                op=instr.opcode, scope=instr.scope or instr.name,
                ranks=[rank, missing[0]] if missing else None))
        if instr.channel_id is not None and instr.replica_groups:
            prev = chan_groups.get(instr.channel_id)
            if prev is not None and prev[0] != instr.replica_groups:
                out.append(Finding(
                    rule="spmd-divergence",
                    message=f"channel {instr.channel_id} is used with "
                            f"two different replica-group sets "
                            f"({prev[1]} vs {instr.name}) in one "
                            f"module",
                    op=instr.opcode,
                    scope=instr.scope or instr.name))
            else:
                chan_groups[instr.channel_id] = (instr.replica_groups,
                                                 instr.name)
    return out


def congruence_findings(modules, n_ranks: Optional[int] = None,
                        mesh_model: Optional[MeshModel] = None
                        ) -> List[Finding]:
    """APX201 over one SPMD module or per-rank modules.

    ``modules``: optimized-HLO text or a pre-extracted schedule (one
    SPMD program — every rank runs the same schedule; group
    well-formedness is still audited), or ``{rank: hlo_text}`` for
    per-rank-compiled programs (the MPMD / mixed-binary case the
    lockstep walk exists for). ``n_ranks`` defaults to the mesh
    model's device count, the dict's size, or the highest rank any
    replica group mentions + 1.
    """
    if isinstance(modules, (str, list)):
        schedule = (modules if isinstance(modules, list)
                    else extract_collective_schedule(modules))
        if n_ranks is None:
            n_ranks = _infer_n_ranks(schedule, mesh_model)
        per_rank = {r: schedule for r in range(n_ranks)}
    else:
        texts = dict(modules)
        # one schedule object per distinct module text: parse once, and
        # the identity-based well-formedness dedupe below sees through
        # N ranks sharing one binary
        by_text: Dict[str, List[CollectiveInstr]] = {}
        schedules = {}
        for r, t in texts.items():
            if t not in by_text:
                by_text[t] = extract_collective_schedule(t)
            schedules[r] = by_text[t]
        if n_ranks is None:
            n_ranks = (mesh_model.n_devices if mesh_model is not None
                       else max(max(texts, default=0) + 1,
                                max((m for s in schedules.values()
                                     for i in s
                                     for g in i.replica_groups
                                     for m in g), default=-1) + 1))
        per_rank = {r: schedules[r] if r in schedules else None
                    for r in range(n_ranks)}
        # ranks without a module of their own run rank 0's (the common
        # "one binary, is it safe?" case degenerates to SPMD)
        base = schedules.get(min(schedules, default=0), [])
        per_rank = {r: (s if s is not None else base)
                    for r, s in per_rank.items()}

    out: List[Finding] = []
    seen_mods: Set[int] = set()
    for r in sorted(per_rank):
        if id(per_rank[r]) in seen_mods:
            continue
        seen_mods.add(id(per_rank[r]))
        out += _group_shape_findings(r, per_rank[r], n_ranks)
    if out:
        return out     # malformed groups make the lockstep walk moot

    ranks = sorted(per_rank)
    queues = {r: list(rank_schedule(per_rank[r], r)) for r in ranks}
    heads = {r: 0 for r in ranks}

    def head(r):
        q = queues[r]
        return q[heads[r]] if heads[r] < len(q) else None

    while True:
        live = [r for r in ranks if head(r) is not None]
        if not live:
            break
        r0 = live[0]
        ref = head(r0)
        participants = _participants(ref, ranks)
        diverged = False
        for p in participants:
            if p == r0:
                continue
            other = head(p)
            if other is None:
                out.append(Finding(
                    rule="spmd-divergence",
                    message=f"deadlock: rank {r0} waits in "
                            f"{ref.describe()} at schedule position "
                            f"{heads[r0]} but rank {p}'s collective "
                            f"schedule is exhausted — rank {p} never "
                            f"joins",
                    op=ref.opcode, scope=ref.scope or ref.name,
                    bytes=ref.bytes, ranks=[r0, p],
                    axes=(mesh_model.group_axes(participants)
                          if mesh_model is not None else None)))
                diverged = True
                break
            if other.identity() != ref.identity():
                field = _first_mismatch(ref, other)
                out.append(Finding(
                    rule="spmd-divergence",
                    message=f"first diverging op at schedule position "
                            f"{heads[r0]}: rank {r0} issues "
                            f"{ref.describe()} but rank {p} issues "
                            f"{other.describe()} — {field} mismatch "
                            f"deadlocks every rank in the group",
                    op=ref.opcode, scope=ref.scope or ref.name,
                    bytes=ref.bytes, ranks=[r0, p],
                    axes=(mesh_model.group_axes(participants)
                          if mesh_model is not None else None)))
                diverged = True
                break
        if diverged:
            break      # everything after the first divergence is noise
        for p in participants:
            heads[p] += 1
    return out


def _first_mismatch(a: CollectiveInstr, b: CollectiveInstr) -> str:
    if a.opcode != b.opcode:
        return f"opcode ({a.opcode} vs {b.opcode})"
    if a.channel_id != b.channel_id:
        return f"channel id ({a.channel_id} vs {b.channel_id})"
    if a.replica_groups != b.replica_groups:
        return "replica groups"
    if a.dtypes != b.dtypes:
        return f"dtype ({'+'.join(a.dtypes)} vs {'+'.join(b.dtypes)})"
    return f"payload bytes ({a.bytes} vs {b.bytes})"


def _infer_n_ranks(schedule: Sequence[CollectiveInstr],
                   mesh_model: Optional[MeshModel]) -> int:
    if mesh_model is not None:
        return mesh_model.n_devices
    return max((m for i in schedule for g in i.replica_groups
                for m in g), default=0) + 1


# -- APX202: implicit full gather ---------------------------------------------

def _scope_known(scope: str, extra: Sequence[str]):
    from apex_tpu.parallel import registry
    return registry.scope_entry(scope, extra=extra)


def full_gather_findings(hlo_text_or_schedule, *,
                         mesh_model: Optional[MeshModel] = None,
                         known_scopes: Sequence[str] = ()
                         ) -> List[Finding]:
    """APX202: ``all-gather`` ops outside every registered collective
    scope — the gather sharding propagation inserted to materialize a
    replicated operand. Evidence bytes are the gathered result (wire
    accounting = ``monitor.wire_report``); the message carries the HBM
    bytes the replication costs every participant."""
    schedule = (hlo_text_or_schedule
                if isinstance(hlo_text_or_schedule, list)
                else extract_collective_schedule(hlo_text_or_schedule))
    agg: Dict[Tuple[str, str], List[CollectiveInstr]] = {}
    for instr in schedule:
        if instr.opcode != "all-gather":
            continue
        if _scope_known(instr.scope, known_scopes) is not None:
            continue
        agg.setdefault((instr.opcode, instr.scope), []).append(instr)
    out: List[Finding] = []
    for (op, scope), instrs in sorted(agg.items()):
        nbytes = sum(i.bytes for i in instrs)
        first = instrs[0]
        axes = hop = None
        where = ""
        if mesh_model is not None and first.replica_groups:
            g = first.replica_groups[0]
            axes = mesh_model.group_axes(g) or None
            hop = mesh_model.group_hop(
                {m for gg in first.replica_groups for m in gg})
            full = (len(first.replica_groups) == 1
                    and len(g) == mesh_model.n_devices)
            where = (" across the whole mesh" if full else
                     f" over axes {axes}" if axes else "")
        out.append(Finding(
            rule="implicit-full-gather",
            message=f"{len(instrs)} unplanned all-gather(s){where} "
                    f"materialize a replicated operand the program "
                    f"never names — {nbytes} wire bytes/step and "
                    f"{nbytes} bytes of HBM per participant",
            op=op, scope=scope or "<unscoped>", bytes=nbytes,
            count=len(instrs), axes=axes, hop=hop))
    return out


# -- APX203: DCN-crossing flat collective -------------------------------------

_REDUCE_OPS = ("all-reduce", "reduce-scatter")


def dcn_flat_findings(hlo_text_or_schedule, mesh_model: MeshModel,
                      ) -> List[Finding]:
    """APX203: reduction collectives whose replica groups cross a DCN
    (slice) boundary while keeping >1 member inside some slice — the
    flat one-hop reduce that wanted a hierarchical schedule. Fires on
    planned (scoped) collectives too: this is a topology property, not
    an attribution one."""
    schedule = (hlo_text_or_schedule
                if isinstance(hlo_text_or_schedule, list)
                else extract_collective_schedule(hlo_text_or_schedule))
    agg: Dict[Tuple[str, str], List[CollectiveInstr]] = {}
    for instr in schedule:
        if instr.opcode not in _REDUCE_OPS:
            continue
        groups = instr.replica_groups or (
            tuple(range(mesh_model.n_devices)),)
        if any(mesh_model.is_flat_dcn_group(g) for g in groups):
            agg.setdefault((instr.opcode, instr.scope),
                           []).append(instr)
    out: List[Finding] = []
    local = 1
    for a in mesh_model.axes:
        if a.link == "ici":
            local *= a.size
    for (op, scope), instrs in sorted(agg.items()):
        nbytes = sum(i.bytes for i in instrs)
        g0 = (instrs[0].replica_groups or
              (tuple(range(mesh_model.n_devices)),))[0]
        axes = mesh_model.group_axes(g0) or None
        flat_ms = mesh_model.hop_seconds(nbytes, "dcn") * 1e3
        hier_ms = mesh_model.hop_seconds(
            max(nbytes // max(local, 1), 1), "dcn") * 1e3
        out.append(Finding(
            rule="dcn-flat-collective",
            message=f"{len(instrs)} flat {op}(s) cross a DCN boundary "
                    f"with whole-slice groups — {nbytes} wire bytes "
                    f"ride DCN (~{flat_ms:.2f} ms); a hierarchical "
                    f"schedule (ICI reduce within-slice first) sends "
                    f"~1/{local} of that (~{hier_ms:.2f} ms)",
            op=op, scope=scope or "<unscoped>", bytes=nbytes,
            count=len(instrs), axes=axes, hop="dcn"))
    return out


# -- APX204: nondeterminism ----------------------------------------------------

_COMMIT_CALLBACK_PRIMS = ("pure_callback", "io_callback")


def _is_literal(v) -> bool:
    return not hasattr(v, "count") and hasattr(v, "val")


def _is_float_dtype(aval) -> bool:
    dt = getattr(aval, "dtype", None)
    if dt is None:
        return False
    try:
        return np.issubdtype(np.dtype(dt), np.floating)
    except TypeError:
        return False


def nondeterminism_jaxpr_findings(jaxpr) -> List[Finding]:
    """APX204 over a (Closed)Jaxpr — see the module docstring for the
    three detector classes. Recurses into every sub-jaxpr; each level's
    commit path is that level's outvars (conservative for nested
    calls, whose results flow outward opaquely)."""
    from apex_tpu.lint.jaxpr_pass import _closed_to_jaxpr, _sub_jaxprs
    out: List[Finding] = []
    _nondet_walk(_closed_to_jaxpr(jaxpr), (), out,
                 _closed_to_jaxpr, _sub_jaxprs)
    return out


def _nondet_walk(jaxpr, path, out, _closed, _subs) -> None:
    used: Set = set()
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if not _is_literal(v):
                used.add(v)
    for v in jaxpr.outvars:
        if not _is_literal(v):
            used.add(v)

    # commit-path reachability: vars that (transitively, treating each
    # eqn as opaque) feed this jaxpr's outputs
    needed: Set = {v for v in jaxpr.outvars if not _is_literal(v)}
    for eqn in reversed(jaxpr.eqns):
        if any(v in needed for v in eqn.outvars):
            for v in eqn.invars:
                if not _is_literal(v):
                    needed.add(v)

    where = "/".join(path) or None
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "rng_bit_generator":
            key_literal = eqn.invars and _is_literal(eqn.invars[0])
            state_out = eqn.outvars[0] if eqn.outvars else None
            state_dropped = (state_out is None or state_out not in used)
            # a threaded, argument-derived key replays bitwise — clean
            if key_literal or state_dropped:
                why = ("a baked-in constant key" if key_literal else
                       "a dropped output state (the updated key never "
                       "threads back into carried state)")
                out.append(Finding(
                    rule="nondeterminism",
                    message=f"rng_bit_generator with {why} — the "
                            "stream cannot be replayed after a "
                            "guard rewind",
                    op=name, scope=where))
        elif name in _COMMIT_CALLBACK_PRIMS:
            if any(v in needed for v in eqn.outvars):
                out.append(Finding(
                    rule="nondeterminism",
                    message=f"{name} result feeds the committed step "
                            "outputs — host values on the commit path "
                            "re-run differently on rewind/replay",
                    op=name, scope=where))
        elif name == "scatter-add":
            if (not eqn.params.get("unique_indices", False)
                    and eqn.invars
                    and _is_float_dtype(getattr(eqn.invars[0], "aval",
                                                None))):
                out.append(Finding(
                    rule="nondeterminism", severity="warning",
                    message="float scatter-add with unique_indices="
                            "False — duplicate-index accumulation "
                            "order is not stable under SPMD "
                            "repartitioning",
                    op=name, scope=where))
        sub_path = path + ((str(eqn.params.get("name")),)
                           if eqn.params.get("name") else ())
        for sub in _subs(eqn):
            _nondet_walk(_closed(sub), sub_path, out, _closed, _subs)


# -- entry point --------------------------------------------------------------

def lint_spmd_text(modules, *, mesh_model: Optional[MeshModel] = None,
                   known_scopes: Sequence[str] = (),
                   n_ranks: Optional[int] = None,
                   rules: Optional[Sequence[str]] = None
                   ) -> List[Finding]:
    """Run the cross-rank HLO rules over one SPMD module (text) or
    per-rank modules (``{rank: text}``). APX203 needs a mesh model
    with a DCN axis; APX202 uses it for axis/hop evidence when given.
    The jaxpr-side APX204 detectors live in
    :func:`nondeterminism_jaxpr_findings` (``lint_step`` runs them off
    its one trace)."""
    run = set(rules) if rules is not None else None

    def on(slug: str) -> bool:
        return run is None or slug in run

    out: List[Finding] = []
    if on("spmd-divergence"):
        out += congruence_findings(modules, n_ranks=n_ranks,
                                   mesh_model=mesh_model)
    # APX202/203 audit every DISTINCT module (a rank-local gather or
    # flat reduce in an MPMD peer's program is just as real); identical
    # texts are parsed and reported once
    texts = ([modules] if isinstance(modules, str)
             else list(dict.fromkeys(modules.values())))
    for text in texts:
        schedule = extract_collective_schedule(text)
        if on("implicit-full-gather"):
            out += full_gather_findings(schedule, mesh_model=mesh_model,
                                        known_scopes=known_scopes)
        if on("dcn-flat-collective") and mesh_model is not None:
            out += dcn_flat_findings(schedule, mesh_model)
    return out
