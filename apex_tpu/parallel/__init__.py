"""apex_tpu.parallel — distributed training over device meshes.

TPU-native rebuild of `apex/parallel` (`apex/parallel/__init__.py:10-95`):
data parallelism, synced batch norm, LARC, plus the mesh construction
helpers that replace `torch.distributed` process groups. Sequence/context
parallelism (ring attention) lives in :mod:`apex_tpu.parallel.ring` — a
capability the reference lacks but a TPU framework owes its users.
"""

from apex_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, SEQ_AXIS, PIPE_AXIS, EXPERT_AXIS,
    DATA_INTER_AXIS, DATA_INTRA_AXIS,
    make_mesh, data_parallel_mesh, hierarchical_data_mesh,
    replicated, batch_sharding, axis_size, local_batch,
)
from apex_tpu.parallel.comm import (
    bucket_plan, bucket_table, bucketed_all_reduce, init_residual,
    wire_bytes,
)
from apex_tpu.parallel.hierarchy import (
    CommPlan, Hop, plan_comm, hierarchical_sync, hierarchical_pmean,
)
from apex_tpu.parallel.distributed import (
    DistributedDataParallel, Reducer, sync_gradients, flat_all_reduce,
    flat_tree_all_reduce,
    replicate, replica_broadcast,
)
from apex_tpu.parallel.larc import LARC, larc_rewrite_grads
from apex_tpu.parallel.registry import (
    CollectiveScope, COLLECTIVE_SCOPES, known_patterns, scope_axis,
    scope_entry,
)
from apex_tpu.parallel.launch import (
    distributed_init, elastic_run, enable_crash_dumps, is_distributed,
    process_index, process_count, maybe_print, shrink_schedule,
)
from apex_tpu.parallel.ring import ring_attention, ulysses_attention
from apex_tpu.parallel.sync_batchnorm import (
    SyncBatchNorm, sync_batch_norm, sync_moments, syncbn_stats_groups,
    convert_sync_batchnorm,
)

__all__ = [
    "DATA_AXIS", "MODEL_AXIS", "SEQ_AXIS", "PIPE_AXIS", "EXPERT_AXIS",
    "make_mesh", "data_parallel_mesh", "hierarchical_data_mesh",
    "replicated", "batch_sharding", "axis_size", "local_batch",
    "DistributedDataParallel", "Reducer", "sync_gradients",
    "flat_all_reduce", "flat_tree_all_reduce", "replicate",
    "replica_broadcast",
    "DATA_INTER_AXIS", "DATA_INTRA_AXIS",
    "bucket_plan", "bucket_table", "bucketed_all_reduce",
    "init_residual", "wire_bytes",
    "CommPlan", "Hop", "plan_comm", "hierarchical_sync",
    "hierarchical_pmean",
    "LARC", "larc_rewrite_grads",
    "CollectiveScope", "COLLECTIVE_SCOPES", "known_patterns",
    "scope_axis", "scope_entry",
    "distributed_init", "elastic_run", "enable_crash_dumps",
    "is_distributed", "process_index", "process_count", "maybe_print",
    "shrink_schedule",
    "ring_attention", "ulysses_attention",
    "SyncBatchNorm", "sync_batch_norm", "sync_moments",
    "syncbn_stats_groups", "convert_sync_batchnorm",
]
