"""Memory-budget audit: assert the HBM attribution story on the
flagship ResNet step over the 8-device virtual mesh.

The asserting sibling of ``pod_comm_budget.py --cpu8`` for the memory
axis (``run_tier1.sh --smoke`` runs it; exit status is the verdict).
Three claims, each printed and asserted:

(a) **attribution closes** — the :class:`apex_tpu.prof.MemoryReport`
    class table (params / optimizer_state / activations / comm / inputs
    / outputs) sums to the XLA ``memory_analysis()`` total within 1%,
    for BOTH the replicated-DDP and the ZeRO-sharded flagship step;
(b) **ZeRO shard savings are visible in the report, not folklore** —
    ``DistributedFusedAdam`` optimizer-state bytes shrink ~1/N vs the
    replicated optimizer (slot-normalized; shard-alignment padding is
    the stated slack) and match the analytic
    ``DistributedFusedAdam.state_bytes`` table within 2%;
(c) **compile_watch sees exactly what happened** — one trace/compile
    for the steady-state step across repeated calls, and a
    shape-perturbed batch forces a retrace whose report names the
    changed argument.

Model: ResNet [2,2,2,2] (14.2M params — flagship-class structure at a
CPU-compilable size; shard padding < 4% so the 1/N claim is clean),
image 64, batch 8/device — the same downscaling convention as the pod
comm audit's ``--cpu8`` structural variant.

Usage: python scripts/memory_budget.py --cpu8
       python scripts/memory_budget.py           # same audit, local devices
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

PER_CHIP_BATCH = 8
IMAGE = 64


def build_programs(mesh, n):
    """(zero, repl) — each a dict with the shard_mapped step, example
    args, and builder metadata, sharing ONE model/amp definition."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu import amp, models, ops, parallel
    from apex_tpu.optim import DistributedFusedAdam, FusedAdam

    model = models.ResNet(stage_sizes=[2, 2, 2, 2], num_classes=1000,
                          dtype=jnp.bfloat16)
    rng = np.random.RandomState(0)
    batch = PER_CHIP_BATCH * n
    x = jnp.asarray(rng.rand(batch, IMAGE, IMAGE, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 1000, batch), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    policy = amp.Policy.from_opt_level("O2")

    def make(label, tx, ddp):
        amp_opt = amp.Amp(policy, tx)

        def step(state, bs, xb, yb):
            def loss_fn(mp):
                logits, mut = model.apply(
                    {"params": mp, "batch_stats": bs}, xb, train=True,
                    mutable=["batch_stats"])
                loss = jnp.mean(ops.softmax_cross_entropy_loss(logits, yb))
                return jax.lax.pmean(loss, parallel.DATA_AXIS), \
                    mut["batch_stats"]

            (loss, new_bs), grads, state, finite = amp_opt.backward(
                state, loss_fn, has_aux=True)
            if ddp is not None:
                grads = ddp.sync(grads)
            state = amp_opt.apply_gradients(state, grads, finite)
            return state, new_bs, loss

        state = jax.jit(jax.shard_map(
            lambda p: amp_opt.init(p), mesh=mesh, in_specs=(P(),),
            out_specs=P(), check_vma=False))(params)
        mapped = jax.shard_map(
            step, mesh=mesh,
            in_specs=(P(), P(), P(parallel.DATA_AXIS),
                      P(parallel.DATA_AXIS)),
            out_specs=(P(), P(), P()), check_vma=False)
        # commit every arg to its mesh sharding up front: an
        # UNcommitted first call followed by committed step outputs
        # would itself retrace (a real cache key change — exactly the
        # class of silent retrace compile_watch exists to catch; here
        # the audit wants a clean steady state)
        from jax.sharding import NamedSharding
        args = (state,
                jax.device_put(batch_stats, NamedSharding(mesh, P())),
                jax.device_put(x, NamedSharding(
                    mesh, P(parallel.DATA_AXIS))),
                jax.device_put(y, NamedSharding(
                    mesh, P(parallel.DATA_AXIS))))
        return {"label": label, "fn": mapped, "tx": tx,
                "args": args, "step": step}

    zero = make("ZeRO DistributedFusedAdam",
                DistributedFusedAdam(lr=1e-3, axis_name=parallel.DATA_AXIS),
                None)
    repl = make("replicated FusedAdam + bucketed DDP",
                FusedAdam(lr=1e-3),
                parallel.DistributedDataParallel(
                    mesh, bucket_allreduce=True, message_size=2_000_000))
    return zero, repl, params


def audit_reports(zero, repl, params, n):
    """Claims (a) + (b): build both MemoryReports, print, assert."""
    from apex_tpu import prof

    reports = {}
    for prog in (zero, repl):
        compiled = jax.jit(prog["fn"]).lower(*prog["args"]).compile()
        rep = prof.memory_report(compiled, batch_size=PER_CHIP_BATCH)
        reports[prog["label"]] = rep
        print(f"\n== {prog['label']}")
        print(rep.table(top=6))
        total, attr = rep.total_bytes, rep.attributed_total()
        rel = abs(attr - total) / max(total, 1)
        print(f"  (a) attributed {attr} vs memory_analysis {total} "
              f"(rel err {rel:.4%})")
        assert rel < 0.01, (
            f"{prog['label']}: class attribution {attr} deviates "
            f"{rel:.2%} from memory_analysis total {total}")

    rz = reports[zero["label"]]
    rr = reports[repl["label"]]
    opt_z, opt_r = (rz.classes["optimizer_state"],
                    rr.classes["optimizer_state"])
    # slot-normalized 1/N: DistributedFusedAdam shards 3 fp32 slots
    # (master/m/v), FusedAdam replicates 2 (m/v; masters sit in
    # state.params under amp O2 for both programs)
    n_slots_z = len(zero["tx"].slot_names)
    n_slots_r = len(repl["tx"].slot_names)
    ratio = (opt_z / n_slots_z) / (opt_r / n_slots_r)
    analytic = zero["tx"].state_bytes(params, world=n)
    print(f"\n(b) optimizer-state bytes: sharded {opt_z} "
          f"({n_slots_z} slots) vs replicated {opt_r} "
          f"({n_slots_r} slots) -> per-slot ratio {ratio:.4f} "
          f"(1/N = {1 / n:.4f}, padding slack "
          f"{analytic['ratio'] * n:.3f}x)")
    assert 0.8 / n <= ratio <= 1.5 / n, (
        f"ZeRO optimizer state not ~1/{n} of replicated: per-slot "
        f"ratio {ratio:.4f}")
    rel = abs(opt_z - analytic["sharded_bytes"]) / analytic["sharded_bytes"]
    print(f"    report {opt_z} vs analytic state_bytes "
          f"{analytic['sharded_bytes']} (rel err {rel:.4%})")
    assert rel < 0.02, (
        f"report optimizer_state {opt_z} deviates {rel:.2%} from "
        f"analytic {analytic['sharded_bytes']}")
    # the bucketed-DDP program must show its comm buffers
    assert rr.classes["comm"] > 0, "bucketed DDP report shows no comm bytes"
    return reports


def audit_compile_watch(zero):
    """Claim (c): steady state = exactly 1 trace; a shape-perturbed
    batch retraces and the detector names the changed argument."""
    from apex_tpu import prof

    watcher = prof.CompileWatcher(warn_after=10)
    jstep = watcher.watch(jax.jit(zero["fn"]), name="flagship_step")
    state, bs, x, y = zero["args"]
    out = jstep(state, bs, x, y)
    out = jstep(out[0], out[1], x, y)          # steady state, same shapes
    rec = watcher["flagship_step"]
    print(f"\n(c) steady state: {rec.n_calls} calls -> {rec.n_traces} "
          f"trace(s), {rec.n_retraces} retrace(s)")
    assert rec.n_traces == 1, (
        f"steady-state step traced {rec.n_traces} times")

    half = x.shape[0] // 2
    jstep(out[0], out[1], x[:half], y[:half])  # shape-perturbed step
    assert rec.n_retraces == 1, rec.n_retraces
    changed = rec.retraces[0]["changed"]
    print(f"    retrace change report: {changed[:160]}")
    assert f"({x.shape[0]}," in changed and f"({half}," in changed, (
        f"retrace report does not name the perturbed batch argument: "
        f"{changed}")
    print(watcher.report())
    return watcher


def main_audit():
    from jax.sharding import Mesh

    from apex_tpu import parallel

    devs = jax.devices()
    if len(devs) < 2:
        print("need >= 2 devices for the sharded audit — run with "
              "--cpu8 for the 8-device virtual mesh")
        return 2
    n = len(devs)
    mesh = Mesh(np.array(devs), (parallel.DATA_AXIS,))
    print(f"memory-budget audit: flagship ResNet step, {n}-device mesh "
          f"({jax.default_backend()}), b={PER_CHIP_BATCH}/device "
          f"@ {IMAGE}px")

    zero, repl, params = build_programs(mesh, n)
    audit_reports(zero, repl, params, n)
    audit_compile_watch(zero)
    print("\nmemory budget audit ok")
    return 0


def main():
    if "--cpu8" in sys.argv:
        jax.config.update("jax_platforms", "cpu")
        from apex_tpu import _compat
        _compat.request_cpu_devices(8)
    return main_audit()


if __name__ == "__main__":
    sys.exit(main())
