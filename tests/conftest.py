"""Test configuration: run everything on a simulated 8-device CPU mesh.

The reference's test tiers all require real GPUs (SURVEY.md §4). We do better:
JAX can expose N virtual CPU devices, so every distributed-semantics test in
this suite runs hostside in CI with no accelerator. Pallas kernels detect the
CPU backend and fall back to interpreter mode (see apex_tpu.ops._dispatch).

This must run before any other module initialises a JAX backend. The image's
sitecustomize force-registers the 'axon' TPU platform, so selecting CPU via
environment variables is not enough — we override the config directly.
"""

import jax
import pytest

from apex_tpu import _compat  # noqa: F401  (jax API shims for older releases)

jax.config.update("jax_platforms", "cpu")
_compat.request_cpu_devices(8)
# Tests compare against fp32 references; keep matmuls at full fp32 precision.
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    import numpy as np
    from jax.sharding import Mesh
    return Mesh(np.array(devices), ("data",))


@pytest.fixture(scope="session")
def mesh4x2(devices):
    import numpy as np
    from jax.sharding import Mesh
    return Mesh(np.array(devices).reshape(4, 2), ("data", "model"))


@pytest.fixture(scope="session")
def mesh2x4(devices):
    """The factored data mesh of the hierarchical gradient sync:
    2 slices over (modeled) DCN x 4 chips over ICI — the dp2x4 mesh
    model's axis names, so plans/models/meshes line up."""
    import numpy as np
    from jax.sharding import Mesh

    from apex_tpu.parallel import DATA_INTER_AXIS, DATA_INTRA_AXIS
    return Mesh(np.array(devices).reshape(2, 4),
                (DATA_INTER_AXIS, DATA_INTRA_AXIS))
