"""Fused optimizer parity vs reference implementations.

Mirrors `tests/L0/run_optimizers/test_adam.py`, `test_lamb.py` (RefLAMB
comparison), `test_adagrad.py`, and the fused-vs-reference trajectory
equality of `tests/L0/run_amp/test_fused_sgd.py` — with optax/jnp as the
reference instead of torch.optim.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import amp
from apex_tpu.optim import (FusedAdagrad, FusedAdam, FusedLAMB,
                            FusedNovoGrad, FusedSGD)


def _params(key=0, mixed=False):
    ks = jax.random.split(jax.random.PRNGKey(key), 3)
    p = {"w1": jax.random.normal(ks[0], (33, 17)),
         "b": jax.random.normal(ks[1], (17,)),
         "w2": jax.random.normal(ks[2], (129,))}
    if mixed:
        p["w2"] = p["w2"].astype(jnp.bfloat16)
    return p


def _grads(params, key=1):
    ks = jax.random.split(jax.random.PRNGKey(key), len(jax.tree_util.tree_leaves(params)))
    leaves, treedef = jax.tree_util.tree_flatten(params)
    gs = [jax.random.normal(k, x.shape).astype(x.dtype)
          for k, x in zip(ks, leaves)]
    return jax.tree_util.tree_unflatten(treedef, gs)


def _run(opt, params, steps=5, seed=1):
    state = opt.init(params)
    for i in range(steps):
        grads = _grads(params, key=seed + i)
        params, state = jax.jit(opt.step)(grads, state, params)
    return params


def _assert_close(a, b, rtol=2e-5, atol=2e-6):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x, np.float32), np.asarray(y, np.float32),
            rtol=rtol, atol=atol), a, b)


class TestFusedAdam:
    def test_matches_optax_adamw(self):
        params = _params()
        got = _run(FusedAdam(lr=1e-2, weight_decay=0.01), params)
        tx = optax.adamw(1e-2, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01)
        st = tx.init(params)
        ref = params
        for i in range(5):
            g = _grads(ref, key=1 + i)
            u, st = tx.update(g, st, ref)
            ref = optax.apply_updates(ref, u)
        _assert_close(got, ref)

    def test_l2_mode_matches_optax_adam_with_l2(self):
        params = _params()
        got = _run(FusedAdam(lr=1e-2, weight_decay=0.1, adam_w_mode=False),
                   params)
        tx = optax.chain(optax.add_decayed_weights(0.1),
                         optax.scale_by_adam(b1=0.9, b2=0.999, eps=1e-8),
                         optax.scale(-1e-2))
        st = tx.init(params)
        ref = params
        for i in range(5):
            g = _grads(ref, key=1 + i)
            u, st = tx.update(g, st, ref)
            ref = optax.apply_updates(ref, u)
        # optax scale_by_adam applies eps after bias-corrected sqrt, same as
        # ours; add_decayed_weights injects wd before adam like L2 mode
        _assert_close(got, ref)

    def test_mixed_dtype_partitions(self):
        params = _params(mixed=True)
        out = _run(FusedAdam(lr=1e-3), params, steps=3)
        assert out["w2"].dtype == jnp.bfloat16
        assert out["w1"].dtype == jnp.float32
        # moved
        assert not np.allclose(np.asarray(out["w1"]),
                               np.asarray(params["w1"]))

    def test_lr_schedule_callable(self):
        calls = []

        def sched(count):
            calls.append(1)
            return 1e-2 / count

        params = _params()
        _run(FusedAdam(lr=sched), params, steps=2)
        assert calls  # schedule consulted


class TestFusedSGD:
    def test_matches_optax_sgd_momentum(self):
        params = _params()
        got = _run(FusedSGD(lr=0.1, momentum=0.9), params)
        # pytorch/apex momentum: m1 = g; optax trace with
        # init zero gives m1 = g as well (t=0: m = g + 0*decay)
        tx = optax.sgd(0.1, momentum=0.9)
        st = tx.init(params)
        ref = params
        for i in range(5):
            g = _grads(ref, key=1 + i)
            u, st = tx.update(g, st, ref)
            ref = optax.apply_updates(ref, u)
        _assert_close(got, ref)

    def test_weight_decay_before_momentum(self):
        params = {"w": jnp.ones((8,))}
        opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=0.5)
        state = opt.init(params)
        g = {"w": jnp.zeros((8,))}
        new_p, _ = opt.step(g, state, params)
        # g_eff = 0 + 0.5*1 = 0.5; m = g_eff; p = 1 - 0.1*0.5
        np.testing.assert_allclose(np.asarray(new_p["w"]), 0.95, rtol=1e-6)

    def test_nesterov(self):
        params = {"w": jnp.full((4,), 2.0)}
        opt = FusedSGD(lr=0.1, momentum=0.5, nesterov=True)
        state = opt.init(params)
        g = {"w": jnp.ones((4,))}
        p1, state = opt.step(g, state, params)
        # m=g=1; upd = g + 0.5*1 = 1.5; p = 2 - .15
        np.testing.assert_allclose(np.asarray(p1["w"]), 1.85, rtol=1e-6)

    def test_nesterov_validation(self):
        with pytest.raises(ValueError):
            FusedSGD(lr=0.1, nesterov=True)


class TestFusedAdagrad:
    def test_matches_manual(self):
        params = {"w": jnp.full((16,), 2.0)}
        opt = FusedAdagrad(lr=0.5, eps=1e-10)
        state = opt.init(params)
        g = {"w": jnp.full((16,), 3.0)}
        p1, state = opt.step(g, state, params)
        # h = 9; p = 2 - 0.5*3/(3+eps) ~ 1.5
        np.testing.assert_allclose(np.asarray(p1["w"]), 1.5, rtol=1e-5)
        p2, state = opt.step(g, state, params=p1)
        # h = 18; p = 1.5 - 0.5*3/sqrt(18)
        np.testing.assert_allclose(np.asarray(p2["w"]),
                                   1.5 - 1.5 / np.sqrt(18), rtol=1e-5)


class TestFusedLAMB:
    def _ref_lamb(self, params, steps, lr=1e-2, b1=0.9, b2=0.999, eps=1e-6,
                  wd=0.01, max_norm=1.0):
        """Pure-jnp RefLAMB (the `test_lamb.py:1-259` comparator), applied
        per-tensor."""
        m = jax.tree_util.tree_map(jnp.zeros_like, params)
        v = jax.tree_util.tree_map(jnp.zeros_like, params)
        p = params
        for i in range(steps):
            g = _grads(p, key=1 + i)
            gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(x))
                                 for x in jax.tree_util.tree_leaves(g)))
            clip = jnp.where(gnorm > max_norm, max_norm / gnorm, 1.0)
            g = jax.tree_util.tree_map(lambda x: x * clip, g)
            t = i + 1
            bc1 = 1 - b1 ** t
            bc2 = 1 - b2 ** t

            def upd(pp, gg, mm, vv):
                mm = b1 * mm + (1 - b1) * gg
                vv = b2 * vv + (1 - b2) * gg * gg
                u = (mm / bc1) / (jnp.sqrt(vv / bc2) + eps) + wd * pp
                pn = jnp.sqrt(jnp.sum(jnp.square(pp)))
                un = jnp.sqrt(jnp.sum(jnp.square(u)))
                ratio = jnp.where((pn > 0) & (un > 0), pn / un, 1.0)
                return pp - lr * ratio * u, mm, vv

            out = jax.tree_util.tree_map(upd, p, g, m, v)
            p = jax.tree_util.tree_map(lambda o: o[0], out,
                                       is_leaf=lambda x: isinstance(x, tuple))
            m = jax.tree_util.tree_map(lambda o: o[1], out,
                                       is_leaf=lambda x: isinstance(x, tuple))
            v = jax.tree_util.tree_map(lambda o: o[2], out,
                                       is_leaf=lambda x: isinstance(x, tuple))
        return p

    def test_matches_ref_lamb(self):
        params = _params()
        got = _run(FusedLAMB(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0),
                   params, steps=4)
        ref = self._ref_lamb(params, steps=4)
        _assert_close(got, ref, rtol=1e-4, atol=1e-5)


class TestFusedNovoGrad:
    def test_matches_reference_semantics(self):
        """Reference defaults: per-tensor *norm* EMA (not squared,
        `fused_novograd.py:157-158`), first-step norm init, bias correction
        with bc2=sqrt(1-b2^t), decoupled decay (MOMENT_MODE_1,
        `multi_tensor_novograd.cu:107-112`)."""
        b1, b2, lr, eps = 0.9, 0.99, 0.1, 1e-8
        params = {"w": jnp.full((32,), 1.0)}
        opt = FusedNovoGrad(lr=lr, betas=(b1, b2), weight_decay=0.0, eps=eps)
        state = opt.init(params)
        g = {"w": jnp.full((32,), 2.0)}

        gnorm = 2 * np.sqrt(32)
        # step 1: v1 = ||g|| (init with first-step norm)
        p1, state = opt.step(g, state, params)
        m1 = (1 - b1) * 2.0
        bc1, bc2 = 1 - b1, np.sqrt(1 - b2)
        upd1 = (m1 / bc1) / (gnorm / bc2 + eps)
        np.testing.assert_allclose(np.asarray(p1["w"]), 1.0 - lr * upd1,
                                   rtol=1e-5)
        # step 2: v2 = b2*v1 + (1-b2)*||g|| = ||g|| (same grad)
        p2, state = opt.step(g, state, p1)
        m2 = b1 * m1 + (1 - b1) * 2.0
        bc1, bc2 = 1 - b1 ** 2, np.sqrt(1 - b2 ** 2)
        upd2 = (m2 / bc1) / (gnorm / bc2 + eps)
        np.testing.assert_allclose(np.asarray(p2["w"]),
                                   np.asarray(p1["w"]) - lr * upd2, rtol=1e-5)

    def test_weight_decay_decoupled_vs_inside(self):
        params = {"w": jnp.full((16,), 1.0)}
        g = {"w": jnp.full((16,), 1.0)}
        out = {}
        for mode in (False, True):
            opt = FusedNovoGrad(lr=0.1, weight_decay=0.1,
                                reg_inside_moment=mode)
            st = opt.init(params)
            p = params
            # modes coincide on step 1 (bc1 cancels); diverge from step 2
            for _ in range(3):
                p, st = opt.step(g, st, p)
            out[mode] = np.asarray(p["w"])
        assert not np.allclose(out[False], out[True])

    def test_inf_norm_mode(self):
        params = {"w": jnp.full((8,), 1.0)}
        g = {"w": jnp.arange(8.0)}
        opt = FusedNovoGrad(lr=0.1, norm_type=0, weight_decay=0.0)
        st = opt.init(params)
        p1, st = opt.step(g, st, params)
        assert np.isfinite(np.asarray(p1["w"])).all()


class TestAmpIntegration:
    def test_amp_o2_with_fused_adam(self):
        """Full pipeline: O2 masters + fused optimizer + overflow skip."""
        params = _params()
        # static scale small enough that fp16 grads of the scaled loss fit
        # (2^16 would overflow on the first steps and back off — correct
        # dynamic behavior, but a static scale keeps this test deterministic)
        amp_opt, state = amp.initialize(
            params, FusedAdam(lr=1e-2), "O2", half_dtype=jnp.float16,
            loss_scale=128.0)

        def loss_fn(mp, x):
            return jnp.mean(jnp.square(x @ mp["w1"] + mp["b"]))

        x = jax.random.normal(jax.random.PRNGKey(9), (4, 33))
        step = jax.jit(lambda s: amp_opt.step(s, loss_fn, x))
        losses = [None] * 3
        for i in range(3):
            state, losses[i], finite = step(state)
            assert bool(finite)
        assert float(losses[2]) < float(losses[0])
        assert int(state.opt_state.count) == 3

        # overflow: fused state (count + slots) must not advance
        bad = jax.jit(lambda s: amp_opt.step(
            s, lambda mp, x: loss_fn(mp, x) * jnp.inf, x))
        w_before = np.asarray(state.params["w1"])
        state, _, finite = bad(state)
        assert not bool(finite)
        assert int(state.opt_state.count) == 3
        np.testing.assert_array_equal(np.asarray(state.params["w1"]),
                                      w_before)

    def test_dynamic_scale_backs_off_until_trainable(self):
        """With init scale 2^16, early fp16 steps overflow and the scaler
        backs off until updates commit — the reference's intended dynamic
        behavior (`scaler.py:197-215`)."""
        params = _params()
        amp_opt, state = amp.initialize(
            params, FusedAdam(lr=1e-2), "O2", half_dtype=jnp.float16)

        def loss_fn(mp, x):
            return jnp.mean(jnp.square(x @ mp["w1"] + mp["b"]))

        x = jax.random.normal(jax.random.PRNGKey(9), (4, 33))
        step = jax.jit(lambda s: amp_opt.step(s, loss_fn, x))
        committed = 0
        for _ in range(20):
            state, _, finite = step(state)
            committed += int(bool(finite))
        assert committed > 0
        assert int(state.opt_state.count) == committed
        assert float(state.scalers[0].loss_scale) < 2.0 ** 16


class TestTreeStrategy:
    """strategy='tree' (per-tensor jnp updates) must match the arena
    kernels' math across several steps for every optimizer."""

    def _params(self):
        rng = np.random.RandomState(0)
        return {
            "w": jnp.asarray(rng.randn(64, 33).astype(np.float32)),
            "b": jnp.asarray(rng.randn(17).astype(np.float32)),
        }

    def _grads(self, i):
        rng = np.random.RandomState(100 + i)
        return {
            "w": jnp.asarray(rng.randn(64, 33).astype(np.float32) * 0.1),
            "b": jnp.asarray(rng.randn(17).astype(np.float32) * 0.1),
        }

    @pytest.mark.parametrize("ctor,kw", [
        (FusedAdam, dict(lr=1e-2, weight_decay=0.01)),
        (FusedAdam, dict(lr=1e-2, weight_decay=0.01, adam_w_mode=False)),
        (FusedSGD, dict(lr=0.1, momentum=0.9, weight_decay=1e-4,
                        nesterov=True)),
        (FusedAdagrad, dict(lr=0.05, weight_decay=1e-3)),
        (FusedLAMB, dict(lr=1e-2, weight_decay=0.01)),
        (FusedLAMB, dict(lr=1e-2, weight_decay=0.0, use_nvlamb=True)),
        (FusedNovoGrad, dict(lr=1e-2, weight_decay=1e-3)),
    ])
    def test_matches_arena(self, ctor, kw):
        params = self._params()
        tree_opt = ctor(strategy="tree", **kw)
        arena_opt = ctor(strategy="arena", **kw)
        pt, pa = params, params
        st, sa = tree_opt.init(params), arena_opt.init(params)
        for i in range(3):
            g = self._grads(i)
            pt, st = jax.jit(tree_opt.step)(g, st, pt)
            pa, sa = jax.jit(arena_opt.step)(g, sa, pa)
        for k in params:
            np.testing.assert_allclose(pt[k], pa[k], atol=1e-6,
                                       rtol=1e-6)

    def test_auto_picks_tree_for_big_models(self):
        opt = FusedAdam()
        small = {"w": jnp.zeros((10, 10))}
        assert not opt._use_tree(small)
        big = {"w": jnp.zeros((4096, 2048)), "v": jnp.zeros((1024,))}
        assert opt._use_tree(big)

    def test_tree_strategy_tuple_structured_params(self):
        """Structural tuples in the params pytree must not be mistaken
        for per-leaf output bundles (round-3 review finding)."""
        rng = np.random.RandomState(0)
        params = ({"w": jnp.asarray(rng.randn(8, 4), jnp.float32)},
                  (jnp.asarray(rng.randn(6), jnp.float32),
                   jnp.asarray(rng.randn(3), jnp.float32)))
        grads = jax.tree_util.tree_map(lambda p: p * 0.1, params)
        tree_opt = FusedAdam(lr=1e-2, strategy="tree")
        arena_opt = FusedAdam(lr=1e-2, strategy="arena")
        pt, st = tree_opt.step(grads, tree_opt.init(params), params)
        pa, sa = arena_opt.step(grads, arena_opt.init(params), params)
        assert jax.tree_util.tree_structure(pt) == \
            jax.tree_util.tree_structure(params)
        for a, b in zip(jax.tree_util.tree_leaves(pt),
                        jax.tree_util.tree_leaves(pa)):
            np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-6)
