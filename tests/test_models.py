"""Model family smoke + driver artifact tests (CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import models


class TestResNet:
    def test_resnet18_forward(self):
        model = models.ResNet18(num_classes=10, width=16)
        x = jnp.ones((2, 64, 64, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        y = model.apply(variables, x, train=False)
        assert y.shape == (2, 10)

    def test_resnet_train_updates_stats(self):
        model = models.ResNet(stage_sizes=[1], num_classes=4, width=8)
        x = jnp.asarray(np.random.RandomState(0)
                        .randn(2, 32, 32, 3).astype(np.float32))
        variables = model.init(jax.random.PRNGKey(0), x, train=True)
        y, mut = model.apply(variables, x, train=True,
                             mutable=["batch_stats"])
        assert y.shape == (2, 4)
        assert "batch_stats" in mut

    def test_resnet50_param_count(self):
        model = models.ResNet50(num_classes=1000)
        x = jnp.ones((1, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        n = sum(int(np.prod(p.shape)) for p in
                jax.tree_util.tree_leaves(variables["params"]))
        # torchvision resnet50: 25.56M params
        assert 25e6 < n < 26e6, n


class TestTransformer:
    def test_encoder_forward(self):
        enc = models.BertEncoder(vocab_size=100, hidden=64, layers=2,
                                 heads=4, max_len=32)
        toks = jnp.ones((2, 16), jnp.int32)
        variables = enc.init(jax.random.PRNGKey(0), toks)
        y = enc.apply(variables, toks)
        assert y.shape == (2, 16, 64)

    def test_mlm_loss(self):
        enc = models.BertEncoder(vocab_size=50, hidden=32, layers=1,
                                 heads=2, max_len=16)
        toks = jnp.ones((2, 8), jnp.int32)
        variables = enc.init(jax.random.PRNGKey(0), toks)
        labels = jnp.full((2, 8), -1, jnp.int32).at[0, 2].set(5)
        loss = models.mlm_loss(enc, variables, toks, labels)
        assert np.isfinite(float(loss))

    def test_attention_mask(self):
        enc = models.BertEncoder(vocab_size=50, hidden=32, layers=1,
                                 heads=2, max_len=16)
        toks = jnp.ones((1, 8), jnp.int32)
        variables = enc.init(jax.random.PRNGKey(0), toks)
        mask = jnp.asarray([[1, 1, 1, 1, 0, 0, 0, 0]])
        y = enc.apply(variables, toks, attn_mask=mask)
        assert y.shape == (1, 8, 32)


class TestDCGAN:
    def test_generator_shapes(self):
        g = models.Generator(nz=16, ngf=8, nc=3)
        z = jnp.ones((2, 1, 1, 16))
        variables = g.init(jax.random.PRNGKey(0), z, train=False)
        img = g.apply(variables, z, train=False)
        assert img.shape == (2, 64, 64, 3)
        assert bool(jnp.all(jnp.abs(img) <= 1.0))

    def test_discriminator_shapes(self):
        d = models.Discriminator(ndf=8, nc=3)
        x = jnp.ones((2, 64, 64, 3))
        variables = d.init(jax.random.PRNGKey(0), x, train=False)
        logit = d.apply(variables, x, train=False)
        assert logit.shape == (2,)


class TestGraftEntry:
    @pytest.mark.slow       # ~21s on CPU CI: full multichip dryrun
    def test_dryrun_multichip_8(self):
        """The driver contract: 8-virtual-device full training step."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "graft_entry", "__graft_entry__.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.dryrun_multichip(8)


class TestStemConv:
    def test_s2d_matches_plain_conv(self):
        from apex_tpu.models.resnet import _StemConv
        rng = np.random.RandomState(20)
        x = jnp.asarray(rng.randn(2, 32, 32, 3).astype(np.float32))
        s2d = _StemConv(16, space_to_depth=True)
        ref = _StemConv(16, space_to_depth=False)
        v = s2d.init(jax.random.PRNGKey(0), x)
        np.testing.assert_allclose(
            np.asarray(s2d.apply(v, x)), np.asarray(ref.apply(v, x)),
            atol=2e-5)
        g1 = jax.grad(lambda v_: jnp.sum(jnp.sin(s2d.apply(v_, x))))(v)
        g0 = jax.grad(lambda v_: jnp.sum(jnp.sin(ref.apply(v_, x))))(v)
        np.testing.assert_allclose(
            np.asarray(g1["params"]["kernel"]),
            np.asarray(g0["params"]["kernel"]), atol=2e-4)

    def test_stem_half_under_auto_cast(self):
        """The custom stem must be on the O1 whitelist like nn.Conv —
        auto_cast runs it in the half dtype."""
        from apex_tpu import amp
        from apex_tpu.models.resnet import _StemConv
        x = jnp.ones((1, 8, 8, 3), jnp.float32)
        m = _StemConv(4)
        v = m.init(jax.random.PRNGKey(0), x)
        policy = amp.Policy.from_opt_level("O1")
        with amp.auto_cast(policy):
            y = m.apply(v, x)
        assert y.dtype == jnp.bfloat16
        assert m.apply(v, x).dtype == jnp.float32  # outside: fp32
