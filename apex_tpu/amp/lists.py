"""Operation cast-policy tables for the precision engine.

TPU-native redesign of the reference's cast lists
(`apex/amp/lists/functional_overrides.py:18-80`,
`apex/amp/lists/torch_overrides.py:7-117`): instead of monkey-patching a
framework namespace, these tables classify *our* library ops (and flax module
classes) into three groups, applied at the library boundary by
``apex_tpu.amp.policy_scope`` / the flax interceptor:

- HALF  ("whitelist"): tensor-core/MXU ops — run in the policy's half dtype.
- FLOAT ("blacklist"): reductions, norms, losses, transcendentals — fp32.
- PROMOTE: multi-input elementwise ops — widest input dtype wins.

Users can extend the tables with :func:`register_half_op`,
:func:`register_float_op`, :func:`register_promote_op` (the analogue of
``amp.register_half_function`` etc., `apex/amp/amp.py:30-64`).
"""

from __future__ import annotations

# --- Op-name tables (consulted by apex_tpu.ops / apex_tpu.layers) -----------

# MXU-bound ops: large matmuls/convs want bf16 on TPU
# (reference whitelist: conv*, linear, matmul/bmm/addmm..., rnn cells)
HALF_OPS = {
    "conv", "conv1d", "conv2d", "conv3d", "conv_transpose",
    "dense", "linear", "matmul", "einsum", "dot_general",
    "attention", "mlp", "rnn_cell", "lstm_cell", "gru_cell",
}

# Precision-sensitive ops: keep fp32
# (reference blacklist: softmax/log_softmax, norms, losses, exp/pow/sum...)
FLOAT_OPS = {
    "softmax", "log_softmax", "layer_norm", "group_norm", "batch_norm",
    "rms_norm", "weight_norm", "cross_entropy", "softmax_cross_entropy",
    "nll_loss", "mse_loss", "l1_loss", "cosine_similarity",
    "exp", "expm1", "log", "log1p", "log2", "log10", "pow", "erf", "erfinv",
    "sum", "mean", "prod", "cumsum", "cumprod", "var", "std", "norm",
    "sigmoid_focal_loss", "renorm", "softplus", "gelu_exact",
}

# Multi-arg elementwise ops: promote to the widest floating dtype
PROMOTE_OPS = {
    "add", "sub", "mul", "div", "addcmul", "addcdiv",
    "concatenate", "stack", "where", "equal", "maximum", "minimum",
    "atan2", "cross", "bilinear", "dot",
}

# Ops that must never see low precision (reference: binary_cross_entropy is
# *banned* under amp with a fix-it message, `functional_overrides.py:73-80`)
BANNED_HALF_OPS = {
    "binary_cross_entropy",
}

BANNED_MESSAGE = (
    "{name} is numerically unsafe in {dtype}. Compute it in float32 — e.g. "
    "use apex_tpu.ops.softmax_cross_entropy (fused, fp32 internals) or pass "
    "logits and use a *_with_logits loss, which is stable in mixed precision."
)


def classify(op_name: str) -> str:
    """Return 'half' | 'float' | 'promote' | 'neutral' for an op name."""
    if op_name in BANNED_HALF_OPS:
        return "banned"
    if op_name in HALF_OPS:
        return "half"
    if op_name in FLOAT_OPS:
        return "float"
    if op_name in PROMOTE_OPS:
        return "promote"
    return "neutral"


def register_half_op(name) -> None:
    """Classify op ``name`` (str) as half, or — given a ``(module,
    attr)`` pair — give a user-owned *raw function* the O1
    functional-patch half treatment (the reference's arbitrary-function
    registration, `apex/amp/amp.py:30-64`)."""
    if not isinstance(name, str):
        from apex_tpu.amp.functional_patch import register_raw_target
        register_raw_target(name[0], name[1], "half")
        return
    FLOAT_OPS.discard(name)
    PROMOTE_OPS.discard(name)
    HALF_OPS.add(name)


def register_float_op(name) -> None:
    """Classify op ``name`` (str) as fp32, or register a raw ``(module,
    attr)`` target for the fp32 functional patch."""
    if not isinstance(name, str):
        from apex_tpu.amp.functional_patch import register_raw_target
        register_raw_target(name[0], name[1], "float")
        return
    HALF_OPS.discard(name)
    PROMOTE_OPS.discard(name)
    FLOAT_OPS.add(name)


def register_promote_op(name: str) -> None:
    HALF_OPS.discard(name)
    FLOAT_OPS.discard(name)
    PROMOTE_OPS.add(name)


def unregister_op(name) -> None:
    """Remove an op-name (str) from every classification table, or —
    given a ``(module, attr)`` pair — drop a raw functional-patch
    registration (restoring the original immediately if a scope is
    live). Idempotent."""
    if not isinstance(name, str):
        from apex_tpu.amp.functional_patch import unregister_raw_target
        unregister_raw_target(name[0], name[1])
        return
    HALF_OPS.discard(name)
    FLOAT_OPS.discard(name)
    PROMOTE_OPS.discard(name)


# --- Flax module-class tables (consulted by the interceptor) ----------------

# user-registered module classes (the module-level analogue of
# register_half_function/register_float_function, `apex/amp/amp.py:30-64`;
# user registrations out-prioritise the built-in tables)
_EXTRA_HALF_MODULES: list = []
_EXTRA_FLOAT_MODULES: list = []


def register_half_module(cls) -> None:
    """Intercepted calls of ``cls`` run in the policy half dtype."""
    if cls in _EXTRA_FLOAT_MODULES:
        _EXTRA_FLOAT_MODULES.remove(cls)
    if cls not in _EXTRA_HALF_MODULES:
        _EXTRA_HALF_MODULES.append(cls)


def register_float_module(cls) -> None:
    """Intercepted calls of ``cls`` run in fp32."""
    if cls in _EXTRA_HALF_MODULES:
        _EXTRA_HALF_MODULES.remove(cls)
    if cls not in _EXTRA_FLOAT_MODULES:
        _EXTRA_FLOAT_MODULES.append(cls)


def _flax_module_tables():
    """Lazily build (HALF_MODULES, FLOAT_MODULES) tuples of flax classes.

    Mirrors the op surface of the reference O1 whitelist/blacklist
    (`functional_overrides.py:18-80`) at module granularity: everything
    MXU-bound (dense/conv/attention/embedding lookups feeding matmuls)
    goes half; statistics/norm modules stay fp32. User registrations are
    placed FIRST so they win isinstance checks over the built-ins.
    """
    import flax.linen as nn

    half = [nn.Dense, nn.DenseGeneral, nn.Conv, nn.ConvTranspose,
            nn.Einsum, nn.ConvLocal, nn.Embed,
            nn.MultiHeadDotProductAttention, nn.SelfAttention]
    flt = [nn.LayerNorm, nn.BatchNorm, nn.GroupNorm, nn.RMSNorm]
    # aliases present only in some flax versions
    for name, dest in (("MultiHeadAttention", half),
                       ("InstanceNorm", flt)):
        cls = getattr(nn, name, None)
        if cls is not None and cls not in dest:
            dest.append(cls)
    # the interceptor consults the user registries BEFORE these, so a
    # user re-registration (or registered subclass) of a built-in wins
    return tuple(half), tuple(flt)
