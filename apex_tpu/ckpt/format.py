"""Durable sharded checkpoint format: one file per process + a manifest.

The commit protocol is the whole point (veScale-style save-where-it-
lives, crash-safe like a WAL):

1. every process writes ``proc{rank:05d}.npz`` to a ``*.tmp`` path,
   fsyncs, then renames — a crash mid-write leaves only a ``.tmp``
   orphan;
2. each process then writes ``proc{rank:05d}.files.json`` (content
   hash + per-array chunk metadata) the same way — the data file is
   now durable and described;
3. rank 0 waits for every rank's files.json (shared-filesystem
   barrier), then writes ``manifest.json`` **last** — again
   temp-then-rename.

``manifest.json`` IS the commit record: a checkpoint directory without
one does not exist as far as :func:`latest_checkpoint` is concerned, so
a crash at ANY point of a save leaves the previous committed checkpoint
untouched and loadable (the mid-save-kill test in tests/test_ckpt.py
proves it at every crash point).

Arrays are addressed by pytree path string (``jax.tree_util.keystr``)
and stored as **chunks**: a fully-addressable array is one whole-array
chunk; a multi-process sharded array contributes each of this process's
distinct addressable shards with its global index slices. Restore
gathers chunks by manifest (any file layout → the full logical array)
and re-scatters to the *target* sharding — which is how a checkpoint
saved on one mesh loads onto another (see :mod:`apex_tpu.ckpt.elastic`
for the ZeRO re-partitioning).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import signal
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["write_process_file", "commit_manifest", "read_manifest",
           "assemble_arrays", "latest_checkpoint", "committed_steps",
           "gc_checkpoints", "step_dir", "MANIFEST", "CheckpointError",
           "checkpoint_in_use", "checkpoint_is_in_use", "INUSE_PREFIX"]

MANIFEST = "manifest.json"
FORMAT_VERSION = 1
#: in-use marker files (``inuse.rank00000.12345.json``): a restore in
#: progress pins its directory against a concurrent ``gc_checkpoints``
#: on another rank — see :func:`checkpoint_in_use`
INUSE_PREFIX = "inuse."

#: test hook: crash the process (SIGKILL — no handlers, no atexit) at a
#: named point of the save. Points: "before_data_rename" (data tmp
#: written, not committed), "before_manifest" (data committed, manifest
#: not). Used by the crash-consistency tests to prove every crash point
#: leaves the previous checkpoint loadable.
_CRASH_ENV = "APEX_TPU_CKPT_TEST_CRASH"


class CheckpointError(RuntimeError):
    """A checkpoint could not be written or read consistently."""


# shared-filesystem polls (the rank barrier, manifest reads, restore
# gathers) MUST NOT poll in lockstep: N ranks hammering one metadata
# server at a fixed 50 ms phase is exactly the thundering herd that
# turns a slow NFS into a stalled commit — hence the de-phased,
# seed-independent jittered backoff (see apex_tpu/utils/backoff.py)
from apex_tpu.utils.backoff import backoff_sleep as _backoff_sleep
from apex_tpu.utils.fsio import write_atomic


def _test_crash(point: str) -> None:
    if os.environ.get(_CRASH_ENV) == point:
        os.kill(os.getpid(), signal.SIGKILL)


def _check_fence(fence, what: str, *, path: Optional[str] = None,
                 step: Optional[int] = None) -> None:
    """Validate a generation fence token before mutating shared state.

    ``fence`` is any object with ``check(what, *, path, step)`` —
    in practice an :class:`apex_tpu.cluster.ClusterMembership`. The
    check re-reads the cluster's COMMITTED generation and raises
    ``StaleGenerationError`` (after emitting the ``cluster_fence``
    refusal event) when this process's token is stale — the zombie
    fence: a rank resumed from a pause/preemption must not write into
    a checkpoint tree a newer generation already owns. ``fence=None``
    keeps the whole path unconditional (single-incarnation runs)."""
    if fence is not None:
        fence.check(what, path=path, step=step)


def tag_generation(event: Dict, fence) -> Dict:
    """Stamp the fence token on a checkpoint-layer event (in place) —
    the forensic half of generation fencing: a refused zombie's
    save/escalation record names the stale epoch it acted FROM. One
    helper so every emitter (CheckpointManager, EscalationPolicy)
    tags identically and a change to the contract lands once."""
    if fence is not None and "generation" not in event:
        event["generation"] = int(getattr(fence, "generation", 0))
    return event


def step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"step_{int(step):08d}")


def _write_atomic(path: str, data: bytes, crash_point: str = "") -> None:
    """temp → fsync → rename; durable against crash at any instant."""
    write_atomic(path, data,
                 before_rename=((lambda: _test_crash(crash_point))
                                if crash_point else None))


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


# --- chunk extraction ---------------------------------------------------------

def _chunks_of(leaf, rank: int) -> Optional[List[Tuple[Optional[List],
                                                       np.ndarray]]]:
    """This process's chunks of one leaf: ``[(index, array), ...]``.

    ``index`` is ``None`` for a whole-array chunk, else
    ``[[start, stop], ...]`` per dim. Whole-array chunks are written by
    rank 0 only (replicated leaves exist everywhere; N identical copies
    on disk would be waste, and restore dedupes by index anyway).
    Returns None when this rank has nothing to write for the leaf.
    """
    from apex_tpu.ckpt.snapshot import ShardChunks  # circular-free: late

    if isinstance(leaf, ShardChunks):
        out = []
        for idx, arr in leaf.chunks:
            whole = all(a == 0 and b == d
                        for (a, b), d in zip(idx, leaf.shape))
            if whole:
                if rank == 0:
                    out.append((None, arr))
            else:
                out.append(([list(p) for p in idx], arr))
        return out or None
    if rank != 0:
        return None              # plain host array == replicated
    arr = np.asarray(leaf)
    return [(None, arr)]


def write_process_file(ckpt_dir: str, rank: int,
                       leaves: Sequence[Tuple[str, Any]], *,
                       fence=None) -> Dict:
    """Write this process's data file + its files.json piece.

    ``leaves`` is ``[(path_str, leaf)]`` where a leaf is a numpy array,
    a scalar, or a :class:`~apex_tpu.ckpt.snapshot.ShardChunks`. Returns
    the files.json record (also written to disk, atomically, after the
    data file commits). ``fence`` refuses the write when this process's
    generation token is stale — checked BEFORE the first byte lands: a
    zombie overwriting ``proc{rank}.npz`` under an already-committed
    manifest would otherwise break that manifest's content hash.
    """
    _check_fence(fence, "write", path=ckpt_dir)
    os.makedirs(ckpt_dir, exist_ok=True)
    fname = f"proc{rank:05d}.npz"
    arrays: List[Dict] = []
    payload: Dict[str, np.ndarray] = {}
    key_i = 0
    for path, leaf in leaves:
        chunks = _chunks_of(leaf, rank)
        if not chunks:
            continue
        for idx, arr in chunks:
            arr = np.asarray(arr)
            key = f"a{key_i:05d}"
            key_i += 1
            payload[key] = arr
            # global shape: the chunk's own shape for whole-array
            # chunks; recorded so assembly can allocate without a like
            gshape = (list(arr.shape) if idx is None
                      else [d for d in _global_shape_of(leaf)])
            arrays.append({
                "path": path, "key": key, "index": idx,
                "shape": list(arr.shape), "dtype": str(arr.dtype),
                "global_shape": gshape,
            })
    buf = io.BytesIO()
    np.savez(buf, **payload)
    data = buf.getvalue()
    _write_atomic(os.path.join(ckpt_dir, fname), data,
                  crash_point="before_data_rename")
    record = {"rank": rank, "file": fname, "sha256": _sha256(data),
              "bytes": len(data), "arrays": arrays}
    _write_atomic(os.path.join(ckpt_dir, f"proc{rank:05d}.files.json"),
                  json.dumps(record).encode())
    return record


def _global_shape_of(leaf):
    from apex_tpu.ckpt.snapshot import ShardChunks
    if isinstance(leaf, ShardChunks):
        return leaf.shape
    return np.asarray(leaf).shape


# --- commit -------------------------------------------------------------------

def commit_manifest(ckpt_dir: str, *, step: int, process_count: int,
                    meta: Optional[Dict] = None,
                    zero: Optional[Dict[str, int]] = None,
                    extra: Optional[Dict] = None,
                    prng_impls: Optional[Dict[str, str]] = None,
                    wait_for_ranks: bool = True,
                    barrier_timeout_s: float = 120.0,
                    fence=None,
                    generation: Optional[int] = None) -> str:
    """Rank 0's commit: gather every rank's files.json, write the
    manifest LAST. ``wait_for_ranks=False`` (the escalation path — dead
    peers will never write theirs) commits with whatever files exist;
    restore's coverage check decides whether the result is usable.

    ``fence`` re-validates the generation token immediately before the
    manifest rename (the commit point — a zombie that passed the write
    fence but was lapped during the rank barrier is still refused
    here); ``generation`` (defaulting to ``fence.generation``) is
    recorded in the manifest, so every committed checkpoint names the
    epoch that produced it.
    """
    deadline = time.monotonic() + barrier_timeout_s
    if generation is None and fence is not None:
        generation = int(getattr(fence, "generation", 0))
    files: List[Dict] = []
    attempt = 0
    while True:
        files = []
        missing = []
        for r in range(process_count):
            p = os.path.join(ckpt_dir, f"proc{r:05d}.files.json")
            if os.path.exists(p):
                with open(p) as f:
                    files.append(json.load(f))
            else:
                missing.append(r)
        if not missing or not wait_for_ranks:
            break
        if time.monotonic() > deadline:
            raise CheckpointError(
                f"checkpoint barrier timed out after {barrier_timeout_s}s"
                f" waiting for ranks {missing} (have "
                f"{sorted(f['rank'] for f in files)}) under {ckpt_dir} "
                f"— NOT committing (the previous checkpoint stays the "
                f"latest); the named ranks never wrote their files.json "
                f"(dead, preempted, or a shared-fs visibility lag "
                f"longer than the timeout)")
        # jittered exponential poll: fast while peers are mid-write,
        # backed off once something is clearly slow — and never in
        # phase across waiters. Cap 0.2 s: a blocking save's commit
        # barrier can sit on the MAIN thread (save(block=True)) where
        # every extra poll latency is step-heartbeat latency a
        # HangWatchdog with a tight deadline would misread as a stall
        _backoff_sleep(attempt, cap_s=0.2)
        attempt += 1
    manifest = {
        "format": FORMAT_VERSION, "step": int(step),
        "wall_time": time.time(), "process_count": int(process_count),
        "n_files": len(files),
        "complete_barrier": len(files) == process_count,
        "generation": (int(generation) if generation is not None
                       else None),
        "meta": dict(meta or {}),
        "zero": dict(zero or {}),
        "extra": dict(extra or {}),
        "prng_impls": dict(prng_impls or {}),
        "files": files,
    }
    # the fence is re-validated at the COMMIT POINT, after the (possibly
    # long) rank barrier: a generation bump that landed while this rank
    # waited means the cluster moved on — committing now would publish
    # a stale epoch's state as the newest checkpoint
    _check_fence(fence, "commit", path=ckpt_dir, step=int(step))
    path = os.path.join(ckpt_dir, MANIFEST)
    _write_atomic(path, json.dumps(manifest, indent=1).encode(),
                  crash_point="before_manifest")
    return path


# --- read side ----------------------------------------------------------------

def read_manifest(ckpt_dir: str, *, attempts: int = 3) -> Dict:
    """Read the commit record, retrying transient shared-fs failures.

    A manifest is written atomically (temp → fsync → rename), but on a
    networked filesystem a reader racing the rename — or a brief NFS
    staleness window — can see ENOENT/EIO/short-read for a file that is
    durably there. Bounded jittered retries absorb that; a manifest
    still unreadable after ``attempts`` is genuinely absent or broken.
    """
    path = os.path.join(ckpt_dir, MANIFEST)
    last: Optional[Exception] = None
    for k in range(max(int(attempts), 1)):
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            last = e
            if k + 1 < attempts:
                _backoff_sleep(k, base_s=0.05)
    raise CheckpointError(f"no committed checkpoint at {ckpt_dir}: "
                          f"{last} (after {attempts} attempts)") from last


def _read_file_deadline(fpath: str, deadline_s: float) -> bytes:
    """Read a checkpoint data file with jittered retries under one
    overall deadline — the timeout on the elastic-restore *gather*: a
    multi-rank restore pulling dozens of shard files over a shared fs
    must degrade to an actionable refusal naming the file, never hang
    a whole relaunch on one stuck read."""
    t0 = time.monotonic()
    attempt = 0
    not_found = 0
    last: Optional[Exception] = None
    while True:
        try:
            with open(fpath, "rb") as f:
                return f.read()
        except FileNotFoundError as e:
            # absence is retried only briefly (rename-visibility lag);
            # a file still absent after that is deleted/never-written —
            # fail fast with the actionable "missing" message instead
            # of burning the whole gather deadline on it
            last = e
            not_found += 1
            if not_found >= 3:
                raise CheckpointError(
                    f"checkpoint data file missing: {fpath} ({e})"
                ) from e
        except OSError as e:
            last = e
        if time.monotonic() - t0 >= deadline_s:
            raise CheckpointError(
                f"checkpoint data file unreadable within "
                f"{deadline_s:.0f}s: {fpath} ({last}) — the restore "
                f"gather timed out; restore from another checkpoint or "
                f"raise io_deadline_s if the filesystem is just slow"
            ) from last
        _backoff_sleep(attempt, base_s=0.05)
        attempt += 1


def assemble_arrays(ckpt_dir: str, manifest: Dict, *,
                    paths: Optional[Sequence[str]] = None,
                    verify: bool = True,
                    io_deadline_s: float = 30.0) -> Dict[str, np.ndarray]:
    """Gather-by-manifest: read every referenced data file and assemble
    each leaf's full logical array from its chunks.

    ``paths`` restricts assembly (restore only pulls what the like-tree
    needs); ``verify`` checks each data file's sha256 against the
    manifest before trusting it; ``io_deadline_s`` bounds each file
    read (transient shared-fs errors are retried with jittered backoff
    inside the deadline). Raises :class:`CheckpointError` on a hash
    mismatch, a read timeout, or a leaf whose chunks do not cover the
    full array (e.g. a lone-rank escalation save of ZeRO-sharded state
    — the actionable message names the uncovered leaf).
    """
    want = set(paths) if paths is not None else None
    loaded: Dict[str, Any] = {}
    per_path: Dict[str, List[Tuple[Optional[Tuple], np.ndarray,
                                   List[int], str]]] = {}
    for frec in manifest.get("files", []):
        if want is not None and not any(a["path"] in want
                                        for a in frec["arrays"]):
            continue
        fpath = os.path.join(ckpt_dir, frec["file"])
        data = _read_file_deadline(fpath, io_deadline_s)
        if verify and _sha256(data) != frec["sha256"]:
            raise CheckpointError(
                f"content hash mismatch for {fpath} — the file does not "
                f"match the committed manifest (corruption or a mixed-up "
                f"directory); refusing to load")
        npz = np.load(io.BytesIO(data))
        for arec in frec["arrays"]:
            p = arec["path"]
            if want is not None and p not in want:
                continue
            idx = (None if arec["index"] is None else
                   tuple(tuple(pair) for pair in arec["index"]))
            arr = npz[arec["key"]]
            want_dt = np.dtype(arec["dtype"])
            if arr.dtype != want_dt:
                # npz round-trips extension dtypes (bfloat16, fp8) as
                # raw void records; the manifest's dtype restores them
                if arr.dtype.itemsize != want_dt.itemsize:
                    raise CheckpointError(
                        f"stored dtype {arr.dtype} for {p!r} cannot "
                        f"reinterpret as recorded {want_dt}")
                arr = arr.view(want_dt)
            per_path.setdefault(p, []).append(
                (idx, arr, arec["global_shape"], str(want_dt)))
    for p, chunks in per_path.items():
        # dedupe identical indices (replicated shards saved by several
        # ranks); distinct addressable shards of one array never overlap
        seen = {}
        for idx, arr, gshape, dt in chunks:
            seen.setdefault(idx, (arr, gshape, dt))
        whole = seen.pop(None, None)
        if whole is not None:
            loaded[p] = whole[0]          # whole copy wins; parts agree
            continue
        gshape = tuple(next(iter(seen.values()))[1])
        dt = next(iter(seen.values()))[2]
        out = np.zeros(gshape, dtype=dt)
        covered = 0
        for idx, (arr, _, _) in seen.items():
            sl = tuple(slice(a, b) for a, b in idx)
            out[sl] = arr
            covered += int(np.prod([b - a for a, b in idx]))
        total = int(np.prod(gshape)) if gshape else 1
        if covered < total:
            raise CheckpointError(
                f"leaf {p!r} is only partially covered by the saved "
                f"chunks ({covered}/{total} elements) — this manifest "
                f"was committed without all ranks (a lone-rank "
                f"escalation save of sharded state); restore from the "
                f"previous fully-committed checkpoint instead")
        loaded[p] = out
    if want is not None:
        missing = want - set(loaded)
        if missing:
            raise CheckpointError(
                "checkpoint is missing required leaves: "
                + ", ".join(sorted(missing)[:8])
                + (" …" if len(missing) > 8 else "")
                + " — was it saved from a state with a different "
                  "structure?")
    return loaded


# --- discovery / retention ----------------------------------------------------

def committed_steps(root: str) -> List[int]:
    """Steps with a committed (manifest-bearing) checkpoint, ascending."""
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        if not name.startswith("step_"):
            continue
        if os.path.exists(os.path.join(root, name, MANIFEST)):
            try:
                out.append(int(name[len("step_"):]))
            except ValueError:
                continue
    return sorted(out)


def latest_checkpoint(root: str) -> Optional[str]:
    """Newest committed checkpoint directory under ``root`` (None when
    nothing has ever committed). Partial directories — a crash mid-save
    — have no manifest and are invisible here by construction."""
    steps = committed_steps(root)
    return step_dir(root, steps[-1]) if steps else None


@contextmanager
def checkpoint_in_use(ckpt_dir: str, rank: int = 0, *,
                      refresh_s: float = 60.0):
    """Pin a checkpoint directory against concurrent retention.

    ``gc_checkpoints(keep=N)`` on one rank can race a ``restore`` on
    another and delete the directory mid-read — the reader then fails
    its gather (or worse, its hash check) on a checkpoint that was
    committed and healthy. A restore wraps its gather in this context
    manager: it drops an ``inuse.rank{r}.{pid}.json`` marker
    (atomically) that :func:`gc_checkpoints` honors, and removes it on
    exit. The marker is advisory and TTL'd (``gc``'s ``inuse_ttl_s``)
    so a reader that died mid-restore cannot pin a directory forever —
    a LIVE reader re-stamps it every ``refresh_s`` (<< the ttl) on a
    daemon thread, so a legitimately slow gather on a degraded fs
    stays pinned however long it runs. A marker write that fails must
    never block the restore itself (``refresh_s=0`` disables the
    refresher).
    """
    path = os.path.join(
        ckpt_dir, f"{INUSE_PREFIX}rank{int(rank):05d}.{os.getpid()}.json")

    def _stamp() -> None:
        _write_atomic(path, json.dumps(
            {"rank": int(rank), "pid": os.getpid(),
             "wall_time": time.time()}).encode())

    try:
        _stamp()
    except OSError:
        path = None
    stop = thread = None
    if path is not None and refresh_s > 0:
        stop = threading.Event()

        def _refresh() -> None:
            while not stop.wait(refresh_s):
                try:
                    _stamp()
                except OSError:
                    pass       # a lost re-stamp falls back to the ttl
                if stop.is_set():
                    # the owner may have removed the marker while our
                    # stamp was in flight on a stalled fs — a re-stamp
                    # landing AFTER that removal would pin a finished
                    # restore's directory against gc for a full ttl
                    try:
                        os.remove(path)
                    except OSError:
                        pass
        thread = threading.Thread(target=_refresh,
                                  name="apex_tpu.ckpt.inuse",
                                  daemon=True)
        thread.start()
    try:
        yield
    finally:
        if stop is not None:
            stop.set()
            thread.join(timeout=1.0)
        if path is not None:
            try:
                os.remove(path)
            except OSError:
                pass


def checkpoint_is_in_use(ckpt_dir: str, *,
                         ttl_s: float = 300.0) -> bool:
    """True when the directory carries a live in-use marker (younger
    than ``ttl_s``). A torn/unreadable marker counts as live — it is
    probably a reader racing its own marker write, and skipping one gc
    round is cheaper than deleting under a reader."""
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return False
    now = time.time()
    for name in names:
        if not (name.startswith(INUSE_PREFIX)
                and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(ckpt_dir, name)) as f:
                rec = json.load(f)
            if now - float(rec.get("wall_time", 0)) < ttl_s:
                return True
        except (OSError, ValueError, TypeError):
            return True
    return False


def gc_checkpoints(root: str, keep: int, *, fence=None,
                   inuse_ttl_s: float = 300.0) -> List[str]:
    """Delete committed checkpoints beyond the newest ``keep`` (and any
    uncommitted partial dirs older than the newest committed one).
    Returns the removed directory paths.

    Two guards make retention safe at pod scale: ``fence`` refuses the
    whole pass when the caller's generation token is stale (a zombie
    must not delete checkpoints the new epoch may still restore from),
    and directories pinned by a live :func:`checkpoint_in_use` marker
    (a concurrent restore on another rank) are skipped this round —
    they fall to a later pass once the reader finishes or its marker
    ages past ``inuse_ttl_s``.
    """
    import shutil
    _check_fence(fence, "delete", path=root)
    steps = committed_steps(root)
    removed = []
    for s in steps[:-keep] if keep > 0 else []:
        d = step_dir(root, s)
        if checkpoint_is_in_use(d, ttl_s=inuse_ttl_s):
            continue               # a reader holds it; next round's job
        shutil.rmtree(d, ignore_errors=True)
        removed.append(d)
    if steps:
        newest = step_dir(root, steps[-1])
        try:
            names = os.listdir(root)
        except OSError:
            names = []
        for name in names:
            d = os.path.join(root, name)
            if (name.startswith("step_") and d != newest
                    and not os.path.exists(os.path.join(d, MANIFEST))
                    and d < newest
                    and not checkpoint_is_in_use(d, ttl_s=inuse_ttl_s)):
                shutil.rmtree(d, ignore_errors=True)
                removed.append(d)
    return removed
