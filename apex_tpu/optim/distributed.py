"""ZeRO-style sharded distributed optimizers.

TPU-native rebuild of `apex.contrib.optimizers.DistributedFusedAdam` /
`DistributedFusedLAMB` (`distributed_fused_adam.py:7-564`,
`distributed_fused_lamb.py:7-607`): gradients are **reduce-scattered** over
the data axis, the fused update runs on each device's **shard** of the
fp32 master/momentum arena, and the new parameters come back with one
**all-gather** — optionally in a compressed dtype (the reference's e5m2
all-gather; here any jnp dtype incl. ``float8_e5m2``/``bfloat16``).
The inbound reduce-scatter can be compressed the same way
(``grad_scatter_dtype=jnp.bfloat16`` halves the grad-side ICI bytes).

What the reference engineers by hand maps to mesh/XLA machinery:

- block×chunk×shard layout with 128-byte alignment
  (`distributed_fused_adam.py:99-148`) → the flat arena + shard padding;
  a shard is a contiguous slice.
- per-param backward hooks flushing blocks through round-robin CUDA
  streams (`:303-353`) → XLA overlaps the reduce-scatter with remaining
  backward compute under one jit.
- two-level intra/inter-node reduction (`:250-290,319-341`) → pass
  ``axis_name=("data_inter", "data_intra")`` over a factorized mesh: the
  scatter applies per axis in order (shard index = axis-major
  linearization), and XLA routes each hop on the right interconnect.
- reversible update / fp32 double-buffer overflow revert (`:75-80,
  446-533`) → unnecessary: the functional state simply isn't committed on
  overflow (``amp.apply_gradients`` selects the old state) — a genuine
  simplification the SURVEY calls out.
- the v2/v3 iterations of the reference are earlier drafts of the same
  pipeline; this one implementation covers their capability surface.

Used inside ``shard_map``: ``init`` slices this device's shard, ``step``
issues the collectives. The class speaks the fused-optimizer protocol
(``step``/``update``), so ``apex_tpu.amp.Amp`` drives it unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import arena
from apex_tpu.optim.fused import FusedOptimizer
from apex_tpu.ops import optim_kernels as K
from apex_tpu.ops import multi_tensor as MT
from apex_tpu.ops._dispatch import BLOCK_ROWS, LANES

Axis = Union[str, Tuple[str, ...]]

_SHARD_ALIGN = BLOCK_ROWS * LANES  # per-shard length stays Pallas-tileable


class ShardedOptState(NamedTuple):
    """count + per-partition sharded slots. ``slots["master"][dt]`` is this
    device's fp32 master shard for the ``dt`` param partition."""
    count: jax.Array
    slots: Dict[str, Dict[str, jax.Array]]


def _axes(axis_name: Axis) -> Tuple[str, ...]:
    return (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)


def _world(axis_name: Axis) -> int:
    n = 1
    for a in _axes(axis_name):
        n *= jax.lax.axis_size(a)
    return n


def _my_rank(axis_name: Axis) -> jax.Array:
    """Linearized rank, axis-major in axis order — matches the tile order
    produced by scattering over each axis in sequence."""
    r = jnp.int32(0)
    for a in _axes(axis_name):
        r = r * jax.lax.axis_size(a) + jax.lax.axis_index(a)
    return r


def _padded_len(n: int, world: int) -> int:
    per = -(-n // world)
    per = -(-per // _SHARD_ALIGN) * _SHARD_ALIGN
    return per * world


def _reduce_scatter_mean(buf, axis_name: Axis, world: int,
                         wire_dtype=None):
    """Mean-reducing scatter over (possibly nested) axes: scatter each
    axis in order, so device (i0, i1, ...) ends with tile
    i0·n1·… + i1·… (axis-major) — the intra/inter-group pipeline of
    `_pipeline_block_reductions` (`distributed_fused_adam.py:319-341`).

    ``wire_dtype`` compresses the scatter's wire format (e.g.
    ``jnp.bfloat16`` halves ICI bytes, the grad-side sibling of the
    ``param_gather_dtype`` compressed all-gather); the result is cast
    back to the input dtype before the mean division.

    Runs under the ``zero/grad_scatter`` span so the reduce-scatters
    are attributable in xplane traces and HLO dumps — the scope
    apexlint's implicit-resharding rule recognizes as planned."""
    from apex_tpu.trace.spans import span as _span
    out = buf if wire_dtype is None else buf.astype(wire_dtype)
    with _span("zero/grad_scatter", kind="collective"):
        for a in _axes(axis_name):
            out = jax.lax.psum_scatter(out, a, scatter_dimension=0,
                                       tiled=True)
    if wire_dtype is not None:
        out = out.astype(buf.dtype)
    return out / world


def _all_gather_shard(shard, axis_name: Axis):
    """Exact inverse of :func:`_reduce_scatter_mean`'s tiling: gather the
    axes in reverse order — under the ``zero/param_gather`` span (same
    attribution contract as ``zero/grad_scatter``)."""
    from apex_tpu.trace.spans import span as _span
    out = shard
    with _span("zero/param_gather", kind="collective"):
        for a in reversed(_axes(axis_name)):
            out = jax.lax.all_gather(out, a, axis=0, tiled=True)
    return out


class DistributedFusedAdam(FusedOptimizer):
    """Sharded Adam/AdamW over a mesh axis (or axis tuple).

    Constructor mirrors `distributed_fused_adam.py:30-95`'s semantic knobs;
    ``param_gather_dtype`` is the compressed all-gather
    (``e5m2_allgather``): new params travel in this dtype and are cast to
    the param dtype on arrival. ``update`` (optax protocol) is inherited
    from :class:`FusedOptimizer`.
    """

    slot_names = ("master", "m", "v")

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adam_w_mode=True, bias_correction=True,
                 axis_name: Axis = "data", max_grad_norm: float = 0.0,
                 param_gather_dtype=None, grad_scatter_dtype=None):
        super().__init__(lr)
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.bias_correction = bias_correction
        self.axis_name = axis_name
        self.max_grad_norm = max_grad_norm
        self.param_gather_dtype = param_gather_dtype
        #: wire dtype of the grad reduce-scatter (e.g. ``jnp.bfloat16``
        #: halves the inbound ICI bytes; masters/moments stay fp32). No
        #: error feedback on this path — the fp32 master update absorbs
        #: the per-step rounding like any bf16-grad training run.
        self.grad_scatter_dtype = grad_scatter_dtype

    # -- sharding helpers ----------------------------------------------------

    def _pad_full(self, buf, buffer_len: int, world: int):
        total = _padded_len(buffer_len, world)
        return jnp.pad(buf, (0, total - buffer_len))

    def _scatter_grads(self, spec, grads, world: int):
        """flatten + pad + reduce-scatter every partition (one flatten for
        the whole tree)."""
        g_bufs = arena.flatten(grads, spec, cast=jnp.float32)
        out = {}
        for part in spec.partitions:
            g = self._pad_full(g_bufs[part.dtype], part.buffer_len, world)
            out[part.dtype] = _reduce_scatter_mean(
                g, self.axis_name, world,
                wire_dtype=self.grad_scatter_dtype)
        return out

    # -- state ---------------------------------------------------------------

    def init(self, params) -> ShardedOptState:
        """Build this device's shard of master + moment state. Must run
        inside shard_map over ``axis_name``."""
        spec = arena.plan(params)
        world = _world(self.axis_name)
        rank = _my_rank(self.axis_name)
        full_bufs = arena.flatten(params, spec, cast=jnp.float32)
        slots = {name: {} for name in self.slot_names}
        for part in spec.partitions:
            dt = part.dtype
            full = self._pad_full(full_bufs[dt], part.buffer_len, world)
            per = full.shape[0] // world
            shard = jax.lax.dynamic_slice_in_dim(full, rank * per, per)
            slots["master"][dt] = shard
            slots["m"][dt] = jnp.zeros_like(shard)
            slots["v"][dt] = jnp.zeros_like(shard)
        return ShardedOptState(count=jnp.int32(0), slots=slots)

    # -- checkpoint layout ---------------------------------------------------

    def checkpoint_layout(self, params) -> Dict[str, int]:
        """``dtype → logical buffer length`` of this optimizer's slot
        shards — the numbers an elastic checkpoint records so a restore
        can re-partition to a different ``zero_size``
        (:mod:`apex_tpu.ckpt.elastic`; docs/checkpointing.md). The
        logical content of every slot buffer is its first
        ``buffer_len`` elements: the arena pads with zeros, zero grads
        keep zero moments at zero, and a zero master under AdamW decay
        stays zero — so truncate + re-pad across world sizes is
        bitwise. Host-side shape arithmetic only; delegates to the one
        shared derivation (``ckpt.elastic.partition_lengths``) that
        ``ckpt.zero_layout`` also uses for the manifest's per-leaf
        map, so the two can never drift."""
        from apex_tpu.ckpt.elastic import partition_lengths
        return partition_lengths(arena.plan(params))

    # -- memory accounting ---------------------------------------------------

    def state_bytes(self, params, world: Optional[int] = None) -> Dict:
        """Analytic per-device optimizer-state bytes — the ZeRO claim as
        arithmetic, cross-checkable against the compiled-step
        :class:`apex_tpu.prof.MemoryReport` (``optimizer_state`` class;
        ``scripts/memory_budget.py`` asserts the two agree and that
        ``ratio`` ≈ 1/world).

        Host-side only (shapes, no device work): per fp32 slot, a
        replicated optimizer holds the full flattened partition while
        this one holds ``padded_len/world`` (shard-alignment padding is
        why ``ratio`` sits slightly above 1/world on small models).
        Returns ``{"per_slot_sharded", "per_slot_replicated",
        "sharded_bytes", "replicated_bytes", "ratio", "world",
        "n_slots"}``.
        """
        if world is None:
            try:
                import jax as _jax
                world = len(_jax.devices())
            except Exception:
                world = 1
        spec = arena.plan(params)
        per_slot_rep = sum(p.buffer_len for p in spec.partitions) * 4
        per_slot_shard = sum(
            _padded_len(p.buffer_len, world) // world
            for p in spec.partitions) * 4                   # fp32 slots
        n = len(self.slot_names)
        return {
            "world": world, "n_slots": n,
            "per_slot_sharded": per_slot_shard,
            "per_slot_replicated": per_slot_rep,
            "sharded_bytes": n * per_slot_shard,
            "replicated_bytes": n * per_slot_rep,
            "ratio": (n * per_slot_shard) / max(n * per_slot_rep, 1),
        }

    # -- update --------------------------------------------------------------

    def _grad_clip_scale(self, g_shards):
        """Global grad-norm clip from sharded pieces: local shard sq-sums
        psum to the exact global norm (the async L2-norm pipeline,
        `distributed_fused_adam.py:343-353`)."""
        if not self.max_grad_norm:
            return 1.0
        sq = sum(jnp.square(MT.multi_tensor_l2norm(g))
                 for g in g_shards.values())
        for a in _axes(self.axis_name):
            sq = jax.lax.psum(sq, a)
        gnorm = jnp.sqrt(sq)
        return jnp.where(gnorm > self.max_grad_norm,
                         self.max_grad_norm / gnorm, 1.0)

    def _shard_update(self, spec, part, g, slots, count, lr, clip, world):
        """Per-partition sharded update → (state slot updates, wire buf)."""
        dt = part.dtype
        res = K.adam_update(
            slots["master"][dt], g, slots["m"][dt], slots["v"][dt],
            lr=lr, beta1=self.beta1, beta2=self.beta2, eps=self.eps,
            weight_decay=self.weight_decay, step=count,
            adam_w_mode=self.adam_w_mode,
            bias_correction=self.bias_correction, grad_scale=clip,
            param_copy_dtype=self.param_gather_dtype)
        if self.param_gather_dtype is not None:
            p_shard, m2, v2, wire = res
        else:
            p_shard, m2, v2 = res
            wire = p_shard
        return {"master": p_shard, "m": m2, "v": v2}, wire

    def step(self, grads, state: ShardedOptState, params):
        spec = arena.plan(params)
        world = _world(self.axis_name)
        count = state.count + 1
        lr = self.lr(count) if callable(self.lr) else self.lr

        g_shards = self._scatter_grads(spec, grads, world)
        clip = self._grad_clip_scale(g_shards)

        new_p, new_slots = {}, {name: {} for name in self.slot_names}
        for part in spec.partitions:
            dt = part.dtype
            slot_updates, wire = self._shard_update(
                spec, part, g_shards[dt], state.slots, count, lr, clip,
                world)
            for name, val in slot_updates.items():
                new_slots[name][dt] = val
            gathered = _all_gather_shard(wire, self.axis_name)
            new_p[dt] = gathered[:part.buffer_len].astype(jnp.dtype(dt))
        return (arena.unflatten(new_p, spec),
                ShardedOptState(count=count, slots=new_slots))


class DistributedFusedLAMB(DistributedFusedAdam):
    """Sharded LAMB (`distributed_fused_lamb.py:7-607`): the Adam pipeline
    plus per-tensor trust ratios computed from *sharded* param/update
    norms — local segment sq-sums over the shard, psum'd to exact
    per-tensor norms (`__compute_contrib_param_norm`,
    `distributed_fused_lamb.py:453-472`)."""

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-6,
                 weight_decay=0.01, adam_w_mode=True, bias_correction=True,
                 axis_name: Axis = "data", max_grad_norm: float = 1.0,
                 use_nvlamb: bool = False, param_gather_dtype=None,
                 grad_scatter_dtype=None):
        super().__init__(lr=lr, betas=betas, eps=eps,
                         weight_decay=weight_decay, adam_w_mode=adam_w_mode,
                         bias_correction=bias_correction,
                         axis_name=axis_name, max_grad_norm=max_grad_norm,
                         param_gather_dtype=param_gather_dtype,
                         grad_scatter_dtype=grad_scatter_dtype)
        self.use_nvlamb = use_nvlamb

    def _per_tensor_sq(self, buf, part, world):
        """Exact global per-tensor sq-sums from this shard's slice: local
        block-decomposed partials (scatter-free — TPU serializes
        segment_sum's scatter) psum'd across the axis."""
        per = _padded_len(part.buffer_len, world) // world
        start = _my_rank(self.axis_name) * per
        out = MT.per_tensor_sq_shard(buf, part.offsets, part.sizes, start)
        for a in _axes(self.axis_name):
            out = jax.lax.psum(out, a)
        return out

    def _shard_update(self, spec, part, g, slots, count, lr, clip, world):
        dt = part.dtype
        master = slots["master"][dt]

        u, m2, v2 = K.lamb_stage1(
            master, g, slots["m"][dt], slots["v"][dt],
            beta1=self.beta1, beta2=self.beta2, eps=self.eps,
            weight_decay=self.weight_decay, step=count,
            bias_correction=self.bias_correction,
            adam_w_mode=self.adam_w_mode, clip_scale=clip)

        p_norms = jnp.sqrt(self._per_tensor_sq(master, part, world))
        u_norms = jnp.sqrt(self._per_tensor_sq(u, part, world))
        ratio = jnp.where((p_norms > 0) & (u_norms > 0),
                          p_norms / u_norms, 1.0)
        if not self.use_nvlamb and self.weight_decay == 0.0:
            ratio = jnp.ones_like(ratio)
        # scatter-free spread over the shard (the traced segment-id
        # gather this replaces serializes on TPU, like the segment_sum
        # the norms above avoid)
        per = _padded_len(part.buffer_len, world) // world
        start = _my_rank(self.axis_name) * per
        ratio_pos = MT.spread_per_tensor_shard(
            ratio, part.offsets, part.sizes, start, per)
        p_shard = K.lamb_stage2(master, u, ratio_pos, lr=lr)

        if self.param_gather_dtype is not None:
            wire = p_shard.astype(jnp.dtype(self.param_gather_dtype))
        else:
            wire = p_shard
        return {"master": p_shard, "m": m2, "v": v2}, wire
