"""Data-parallel gradient synchronisation — the DDP equivalent.

The reference's ``DistributedDataParallel`` (`apex/parallel/distributed.py:
129-639`) is ~600 lines of machinery whose entire job is to make NCCL
allreduce overlap backward: per-param grad hooks, arrival-order bucket
construction broadcast from rank 0, flatten/unflatten, side CUDA streams,
epilogue callbacks. On TPU the same capability is a *program property*:
gradients computed under ``shard_map`` over a ``data`` mesh axis are synced
with ``psum``, and XLA's latency-hiding scheduler overlaps the collectives
with remaining backward compute — the bucket/stream machinery is the
compiler's job. What survives as API are the *semantic* knobs:

- ``gradient_average`` / ``gradient_predivide_factor``
  (`distributed.py:144-148,442-451`): pre/post division around the reduce.
- ``allreduce_always_fp32`` (`distributed.py:140-143,455-459`): reduce half
  grads in fp32.
- ``delay_allreduce`` (`distributed.py:168,491-510`): the reference's
  ``allreduce_fallback`` — skip overlapped per-bucket reduction, sync
  everything in one flat fused all-reduce per dtype at the end. Here it
  switches ``sync`` to :func:`flat_tree_all_reduce` (one ``psum`` of a
  concatenated buffer per dtype instead of per-tensor ``psum``s).
- ``no_sync`` / ``_disable_allreduce`` (`distributed.py:566-570`): gradient
  accumulation without communication.
- ``message_size`` (`distributed.py:165`): the bucket-combine threshold,
  forwarded to XLA's collective-combiner via jit ``compiler_options``
  (``xla_gpu_all_reduce_combine_threshold_bytes`` — the DebugOptions field
  is shared across backends). Backends whose compile service rejects
  option overrides (e.g. the axon tunnel) fall back to default combining
  with a one-time warning. With ``bucket_allreduce=True`` the same knob
  sizes *explicit* buckets instead: ``allreduce_bucket`` parity
  (`distributed.py:425-475`) — one psum per reverse-parameter-order
  bucket, chained so the latency-hiding scheduler overlaps each bucket's
  all-reduce with the remaining backward (see
  :mod:`apex_tpu.parallel.comm`).
- ``compress`` (``"bf16"`` / ``"int8"``): compressed collectives with an
  optional error-feedback residual — capability the reference never had
  (EQuARX/DynamiQ lineage), see :func:`comm.bucketed_all_reduce`.

``Reducer`` (`distributed.py:89-126`) survives as the manual-trigger
average; ``flat_dist_call`` (`distributed.py:26-49`) as ``flat_all_reduce``
over an arena buffer.
"""

from __future__ import annotations

import functools
import warnings
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.parallel.mesh import DATA_AXIS

#: named-scope patterns (regex fragments) under which this package
#: deliberately emits collectives — the allowlist apexlint's
#: implicit-resharding rules (APX102/APX202) check compiled collectives
#: against. The canonical table now lives in
#: :mod:`apex_tpu.parallel.registry` — one declarative row per planned
#: collective family, carrying the mesh axis it communicates over (the
#: mesh model / topology rules consume the same rows); this name is the
#: backward-compatible flat view.
from apex_tpu.parallel.registry import known_patterns as _known_patterns

KNOWN_COLLECTIVE_SCOPES = _known_patterns()


def _is_float(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def sync_gradients(grads, axis_name: str = DATA_AXIS, *,
                   gradient_average: bool = True,
                   gradient_predivide_factor: float = 1.0,
                   allreduce_always_fp32: bool = False):
    """All-reduce a gradient pytree across ``axis_name`` (inside shard_map).

    Implements the arithmetic of ``allreduce_bucket``
    (`apex/parallel/distributed.py:425-475`): optionally cast to fp32,
    divide by ``predivide_factor`` before the reduce, ``psum``, then divide
    by ``world/predivide`` after (or not at all when ``gradient_average``
    is off), casting back to the gradient dtype at the end.
    """
    world = jax.lax.axis_size(axis_name)

    def _sync(g):
        if not _is_float(g):
            return g
        orig = g.dtype
        if allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if gradient_predivide_factor != 1.0:
            g = g / gradient_predivide_factor
        g = jax.lax.psum(g, axis_name)
        if gradient_average:
            post = world / gradient_predivide_factor
            if post != 1.0:
                g = g / post
        return g.astype(orig)

    return jax.tree_util.tree_map(_sync, grads)


def flat_all_reduce(buf: jax.Array, axis_name: str = DATA_AXIS, *,
                    average: bool = True) -> jax.Array:
    """One fused all-reduce of a flat arena buffer — ``flat_dist_call``
    (`apex/parallel/distributed.py:26-49`) with the flatten already done by
    the arena. Also the ``delay_allreduce`` fallback path
    (`distributed.py:491-510`)."""
    out = jax.lax.psum(buf, axis_name)
    if average:
        out = out / jax.lax.axis_size(axis_name)
    return out


def flat_tree_all_reduce(grads, axis_name: str = DATA_AXIS, *,
                         gradient_average: bool = True,
                         gradient_predivide_factor: float = 1.0,
                         allreduce_always_fp32: bool = False):
    """``allreduce_fallback`` (`apex/parallel/distributed.py:491-510`):
    concatenate all floating gradients into one flat buffer *per dtype*
    (the reference's type-bucketed ``flat_dist_call``), one ``psum`` per
    buffer, then split back. Same arithmetic knobs as
    :func:`sync_gradients`."""
    world = jax.lax.axis_size(axis_name)
    leaves, treedef = jax.tree_util.tree_flatten(grads)

    groups = {}
    for i, leaf in enumerate(leaves):
        if _is_float(leaf):
            groups.setdefault(jnp.asarray(leaf).dtype, []).append(i)

    out = list(leaves)
    for dtype, idxs in groups.items():
        flat = jnp.concatenate(
            [jnp.ravel(leaves[i]) for i in idxs])
        if allreduce_always_fp32:
            flat = flat.astype(jnp.float32)
        if gradient_predivide_factor != 1.0:
            flat = flat / gradient_predivide_factor
        flat = jax.lax.psum(flat, axis_name)
        if gradient_average:
            post = world / gradient_predivide_factor
            if post != 1.0:
                flat = flat / post
        flat = flat.astype(dtype)
        off = 0
        for i in idxs:
            n = leaves[i].size
            out[i] = flat[off:off + n].reshape(leaves[i].shape)
            off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def dynamics_probe(local_grads, synced_grads,
                   axis_name: str = DATA_AXIS):
    """The training-dynamics observatory's per-step gradient scalars
    (:class:`apex_tpu.monitor.dynamics.DynamicsProbe`): call between
    ``sync`` and ``apply_gradients`` with the replica-LOCAL gradient
    tree and the synced (averaged) tree — both already in hand there —
    and feed the result to the ``Amp.step(dynamics=…)`` hook or
    :func:`~apex_tpu.monitor.dynamics.dynamics_observe` directly.

    Wire cost is two scalar-class collectives riding the existing sync
    dispatch, each under its own registered scope
    (:mod:`apex_tpu.parallel.registry` — APX102/APX202 and the per-axis
    byte split resolve them):

    - ``ddp/dynamics_gns``: ONE scalar psum of the per-replica squared
      grad norm → the mean ``|G_local|²`` the gradient-noise-scale
      estimator pairs against the pooled ``|G_big|²`` (computed
      locally — the synced tree is replicated after the sync);
    - ``ddp/dynamics_geom``: one all-gather of the per-replica
      ``[|g_i|², g_i·g̅]`` scalar pair → the cosine spectrum and the
      Adasum projection coefficients (arXiv 2006.02924), ``2·world``
      floats on the wire.

    ``synced_grads`` must be the *averaged* sync output (the
    ``gradient_average=True`` default): the GNS algebra reads it as the
    pooled-mean gradient. Non-floating leaves are ignored, mirroring
    ``sync``. Works under any mapping that binds ``axis_name``
    (shard_map/pmap); the probe itself adds no host ops.
    """
    from apex_tpu.monitor.dynamics import DynamicsProbe
    from apex_tpu.trace.spans import span as _span

    def _sq(tree):
        acc = jnp.float32(0)
        for leaf in jax.tree_util.tree_leaves(tree):
            if _is_float(leaf):
                acc = acc + jnp.sum(
                    jnp.square(jnp.asarray(leaf).astype(jnp.float32)))
        return acc

    local_sq = _sq(local_grads)
    pooled_sq = _sq(synced_grads)
    dot = jnp.float32(0)
    for g, s in zip(jax.tree_util.tree_leaves(local_grads),
                    jax.tree_util.tree_leaves(synced_grads)):
        if _is_float(g) and _is_float(s):
            dot = dot + jnp.sum(
                jnp.asarray(g).astype(jnp.float32)
                * jnp.asarray(s).astype(jnp.float32))
    world = jax.lax.axis_size(axis_name)
    with _span("ddp/dynamics_gns", kind="collective"):
        local_sq_mean = jax.lax.pmean(local_sq, axis_name)
    with _span("ddp/dynamics_geom", kind="collective"):
        pairs = jax.lax.all_gather(jnp.stack([local_sq, dot]),
                                   axis_name)
    return DynamicsProbe(local_sq_mean=local_sq_mean,
                         pooled_sq=pooled_sq,
                         local_sqs=pairs[:, 0], dots=pairs[:, 1],
                         world=jnp.float32(world))


class Reducer:
    """Manual-trigger parameter/gradient averaging
    (`apex/parallel/distributed.py:89-126`): construction-time broadcast is
    replaced by ``replicate`` (params placed with a replicated sharding are
    identical on all devices by construction); ``reduce`` averages a pytree
    across the data axis whenever the user calls it."""

    def __init__(self, axis_name: str = DATA_AXIS):
        self.axis_name = axis_name

    def reduce(self, tree):
        world = jax.lax.axis_size(self.axis_name)
        return jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, self.axis_name) / world
            if _is_float(x) else x, tree)


def replica_broadcast(tree, axis_name=DATA_AXIS, *, source=0):
    """Bit-exact re-broadcast of a pytree from one replica of
    ``axis_name`` to all of them (inside shard_map) — the in-place
    repair collective of the silent-divergence defense
    (:mod:`apex_tpu.guard.integrity`).

    Every replica receives the ``source`` replica's **exact bits**: the
    broadcast is a ``psum`` of the where-selected *bit pattern*
    (integer addition against zeros is exact), never of the float
    values — a float psum would already lose ``-0.0`` signs, and
    bit-exactness is the whole point (the repaired replica must equal
    the majority bitwise, or the fingerprint re-verification fails).
    ``source`` may be a traced scalar (the quorum vote's choice is a
    runtime value). Call under a registered collective scope
    (``guard/integrity_repair``) so apexlint APX102/APX202 stay clean.
    """
    me = jax.lax.axis_index(axis_name)
    src = jnp.asarray(source, me.dtype)

    def _one(x):
        from apex_tpu.utils import uint_view_dtype
        x = jnp.asarray(x)
        if jnp.issubdtype(x.dtype, jnp.floating):
            bits = jax.lax.bitcast_convert_type(
                x, uint_view_dtype(x.dtype))
            sel = jnp.where(me == src, bits, jnp.zeros_like(bits))
            return jax.lax.bitcast_convert_type(
                jax.lax.psum(sel, axis_name), x.dtype)
        if x.dtype == jnp.bool_:
            sel = jnp.where(me == src, x.astype(jnp.int32), 0)
            return jax.lax.psum(sel, axis_name) != 0
        if jnp.issubdtype(x.dtype, jnp.integer):
            sel = jnp.where(me == src, x, jnp.zeros_like(x))
            return jax.lax.psum(sel, axis_name)
        # passing an uncovered dtype through unrepaired would silently
        # leave the divergence in place — refuse loudly (mirrors
        # guard.integrity's fold, which refuses to fingerprint it)
        raise TypeError(
            f"replica_broadcast cannot re-broadcast dtype {x.dtype} "
            f"bit-exactly — exclude the leaf from the repaired "
            f"subtree explicitly")

    return jax.tree_util.tree_map(_one, tree)


def replicate(tree, mesh: Mesh):
    """Place a pytree replicated on every device of ``mesh`` — the
    construction-time rank-0 broadcast of the reference DDP
    (`apex/parallel/distributed.py:253`), done by sharding instead of
    communication."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


class DistributedDataParallel:
    """Data-parallel train-step transform.

    ``ddp = DistributedDataParallel(mesh)`` then ``ddp.wrap(step)`` turns a
    per-device step ``(state, batch) -> (state, metrics)`` whose gradients
    are produced locally into a jitted SPMD program: the batch is split over
    the data axis, the step runs per shard, and every gradient the step
    syncs through ``ddp.sync_gradients`` (or the wrapper's automatic sync if
    the step returns raw grads) is all-reduced.

    The constructor flags mirror `apex/parallel/distributed.py:129-191`.
    """

    def __init__(self, mesh: Mesh, axis_name: str = DATA_AXIS, *,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 allreduce_always_fp32: bool = False,
                 delay_allreduce: bool = False,
                 message_size: Optional[int] = None,
                 grad_dtype=None,
                 bucket_allreduce: bool = False,
                 compress: Optional[str] = None,
                 compress_block: Optional[int] = None,
                 comm_plan=None):
        from apex_tpu.parallel import comm as _comm
        if comm_plan is not None:
            # a hierarchy.CommPlan IS the compression + topology spec:
            # its axes replace axis_name, its per-hop dtypes replace
            # compress, and delay_allreduce's one terminal flat reduce
            # is the exact shape it exists to remove
            if compress is not None or allreduce_always_fp32 or \
                    delay_allreduce or compress_block is not None:
                raise ValueError(
                    "comm_plan fixes the per-hop wire dtypes, the "
                    "quantization block and the topology; it does not "
                    "compose with compress, compress_block, "
                    "allreduce_always_fp32 or delay_allreduce (set "
                    "compress_block via plan_comm)")
            for ax in comm_plan.axis_names:
                if ax not in mesh.axis_names:
                    raise ValueError(
                        f"comm_plan axis {ax!r} not in mesh "
                        f"{mesh.axis_names} — build the mesh with "
                        "hierarchical_data_mesh (or matching axis "
                        "names) for a hierarchical plan")
            for hop in comm_plan.hops:
                if mesh.shape[hop.axis] != hop.size:
                    raise ValueError(
                        f"comm_plan axis {hop.axis!r} has size "
                        f"{hop.size} but the mesh has "
                        f"{mesh.shape[hop.axis]}")
            axis_name = (comm_plan.axis_names[0]
                         if len(comm_plan.axis_names) == 1
                         else tuple(comm_plan.axis_names))
        elif axis_name not in mesh.axis_names:
            raise ValueError(f"axis {axis_name!r} not in mesh "
                             f"{mesh.axis_names}")
        if compress not in _comm.COMPRESS_MODES:
            raise ValueError(f"compress must be one of "
                             f"{_comm.COMPRESS_MODES}, got {compress!r}")
        if compress is not None and allreduce_always_fp32:
            raise ValueError("compress fixes the wire dtype; it does not "
                             "compose with allreduce_always_fp32")
        if bucket_allreduce and delay_allreduce:
            raise ValueError("bucket_allreduce (overlapped per-bucket "
                             "reduction) and delay_allreduce (one "
                             "terminal flat reduce) are opposite modes")
        self.mesh = mesh
        self.axis_name = axis_name
        #: None | hierarchy.CommPlan — the topology-aware hierarchical
        #: schedule (int8 ICI reduce-scatter / bf16-or-int8 DCN reduce /
        #: ICI all-gather, planner-chosen per hop; see
        #: apex_tpu.parallel.hierarchy). Strictly opt-in: comm_plan=None
        #: leaves every existing path untouched.
        self.comm_plan = comm_plan
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.delay_allreduce = delay_allreduce
        self.message_size = message_size
        #: explicit message_size-bounded buckets in reverse-parameter
        #: order, one chained psum each — the apex ``allreduce_bucket``
        #: overlap structure (see apex_tpu.parallel.comm)
        self.bucket_allreduce = bucket_allreduce
        #: None | "bf16" | "int8" — compressed collectives with optional
        #: error feedback (pass ``residual=`` to :meth:`sync`)
        self.compress = compress
        self.compress_block = (compress_block if compress_block
                               else _comm.DEFAULT_COMPRESS_BLOCK)
        #: dtype the gradients ARRIVE in — used only to size the
        #: message_size → combine-threshold conversion (bf16 grads halve
        #: the byte threshold). It does NOT cast the reduction: grads
        #: reduce in their incoming dtype (upcast via
        #: allreduce_always_fp32 if wanted). Defaults to fp32 sizing
        #: (the reference counts fp32 elements,
        #: `apex/parallel/distributed.py:165`); allreduce_always_fp32
        #: forces fp32 sizing regardless.
        self.grad_dtype = grad_dtype
        self._sync_enabled = True

    @property
    def world_size(self) -> int:
        if isinstance(self.axis_name, tuple):
            n = 1
            for a in self.axis_name:
                n *= self.mesh.shape[a]
            return n
        return self.mesh.shape[self.axis_name]

    # -- in-step API ---------------------------------------------------------

    def sync(self, grads, residual=None):
        """Sync a gradient pytree (call inside the wrapped step). Honors
        ``no_sync`` — the `_disable_allreduce` flag
        (`apex/parallel/distributed.py:566-570`) — and ``delay_allreduce``
        (one flat fused reduce per dtype, the `allreduce_fallback` path).
        With ``bucket_allreduce`` or ``compress`` set, the sync runs
        through :func:`comm.bucketed_all_reduce` (per-bucket chained
        psums / compressed collectives).

        ``residual`` enables error feedback for the compressed modes:
        pass the previous step's residual (seed with
        :meth:`init_residual`) and the return value becomes
        ``(synced_grads, new_residual)`` — thread it through your step
        state with a per-device sharding (docs/parallel.md). Without
        ``residual`` the return value stays a bare pytree.

        Runs under a ``kind="collective"`` trace span so the psums are
        scoped ``ddp/sync_gradients`` in xplane traces and HLO dumps
        (per-bucket sub-spans ``bucket00``… nest inside it) — that
        attribution is what survives into the compiled program.
        The span itself executes at trace time (this code runs inside
        the user's jitted step), so *runtime* in-flight-collective
        forensics come from host-side collective spans around the
        blocking point, e.g. ``with trace.span("allreduce-wait",
        kind="collective"): jax.block_until_ready(grads)`` — see
        docs/tracing.md."""
        if not self._sync_enabled:
            return grads if residual is None else (grads, residual)
        from apex_tpu.parallel import comm as _comm
        from apex_tpu.trace.spans import span as _span
        if self.comm_plan is not None:
            from apex_tpu.parallel import hierarchy as _hier
            msg = self.message_size if self.message_size else (
                _comm.DEFAULT_MESSAGE_SIZE if self.bucket_allreduce
                else None)
            with _span("ddp/sync_gradients", kind="collective"):
                if self.comm_plan.is_hierarchical:
                    return _hier.hierarchical_sync(
                        grads, self.comm_plan, message_size=msg,
                        gradient_average=self.gradient_average,
                        gradient_predivide_factor=self
                        .gradient_predivide_factor,
                        residual=residual)
                # a flat (single-slice) plan is the planner-chosen
                # compress mode over one axis — the existing machinery
                return _comm.bucketed_all_reduce(
                    grads, self.axis_name, message_size=msg,
                    gradient_average=self.gradient_average,
                    gradient_predivide_factor=self
                    .gradient_predivide_factor,
                    compress=self.comm_plan.hops[0].dtype,
                    residual=residual,
                    compress_block=self.comm_plan.compress_block)
        if self.bucket_allreduce or self.compress is not None:
            # compress without bucketing = one bucket per dtype
            msg = self.message_size if self.message_size else (
                _comm.DEFAULT_MESSAGE_SIZE if self.bucket_allreduce
                else None)
            with _span("ddp/sync_gradients", kind="collective"):
                return _comm.bucketed_all_reduce(
                    grads, self.axis_name, message_size=msg,
                    gradient_average=self.gradient_average,
                    gradient_predivide_factor=self
                    .gradient_predivide_factor,
                    allreduce_always_fp32=self.allreduce_always_fp32,
                    compress=self.compress, residual=residual,
                    compress_block=self.compress_block)
        fn = flat_tree_all_reduce if self.delay_allreduce else \
            sync_gradients
        with _span("ddp/sync_gradients", kind="collective"):
            synced = fn(
                grads, self.axis_name,
                gradient_average=self.gradient_average,
                gradient_predivide_factor=self.gradient_predivide_factor,
                allreduce_always_fp32=self.allreduce_always_fp32)
        # exact modes have no compression error: the residual passes
        # through unchanged so callers can keep one code shape
        return synced if residual is None else (synced, residual)

    def init_residual(self, grads):
        """Zeroed error-feedback residual matching a gradient pytree —
        see :func:`apex_tpu.parallel.comm.init_residual`."""
        from apex_tpu.parallel import comm as _comm
        return _comm.init_residual(grads)

    def pmean(self, x):
        """Cross-replica mean over this DDP's topology (use for the
        logged loss). Matters with a hierarchical ``comm_plan``: a
        ``jax.lax.pmean`` over the axis *tuple* lowers to one flat
        whole-mesh all-reduce — the DCN-crossing shape APX203 flags —
        while this emits one psum per axis (within-slice, then
        one-member-per-slice across). Call it inside a registered
        collective span (``ddp/loss_pmean``)."""
        if self.comm_plan is not None and self.comm_plan.is_hierarchical:
            from apex_tpu.parallel import hierarchy as _hier
            return _hier.hierarchical_pmean(x, self.comm_plan)
        return jax.lax.pmean(x, self.axis_name)

    def no_sync(self):
        """Context manager: steps wrapped while active skip gradient
        all-reduce (gradient accumulation across microbatches)."""
        ddp = self

        class _NoSync:
            def __enter__(self):
                self._prev = ddp._sync_enabled
                ddp._sync_enabled = False

            def __exit__(self, *exc):
                ddp._sync_enabled = self._prev

        return _NoSync()

    # -- step transform ------------------------------------------------------

    def wrap(self, step_fn: Callable, *,
             state_specs=P(), batch_specs=None, out_specs=None,
             donate_state: bool = True) -> Callable:
        """shard_map ``step_fn(state, batch) -> (state, aux)`` over the mesh.

        ``state`` is replicated (every device holds identical params, like
        DDP's broadcast invariant), ``batch`` is split on its leading dim.
        ``step_fn`` must call ``self.sync`` on its gradients (or use
        ``wrap_grad_fn``). Donation keeps the replicated state update
        in-place.
        """
        batch_specs = batch_specs if batch_specs is not None else \
            P(self.axis_name)
        out_specs = out_specs if out_specs is not None else \
            (state_specs, P())

        jit_kwargs = {}
        if donate_state:
            jit_kwargs["donate_argnums"] = (0,)

        # ``self._sync_enabled`` is read inside step_fn at *trace* time, so
        # a single compiled program would bake in whichever value was active
        # at the first call — breaking no_sync for already-compiled steps.
        # Build one program per flag value, each from a distinct closure
        # (distinct trace caches) that pins the flag while tracing.
        def _build(sync_on: bool):
            def pinned(*args, **kwargs):
                prev = self._sync_enabled
                self._sync_enabled = sync_on
                try:
                    return step_fn(*args, **kwargs)
                finally:
                    self._sync_enabled = prev
            mapped = jax.shard_map(
                pinned, mesh=self.mesh,
                in_specs=(state_specs, batch_specs),
                out_specs=out_specs,
                check_vma=False)
            opts = self._compiler_options()
            if opts:
                return jax.jit(mapped, compiler_options=opts,
                               **jit_kwargs)
            return jax.jit(mapped, **jit_kwargs)

        programs = {}

        @functools.wraps(step_fn)
        def dispatch(*args, **kwargs):
            key = self._sync_enabled
            if key not in programs:
                programs[key] = _build(key)
            return programs[key](*args, **kwargs)

        return dispatch

    # None = not probed yet; set process-wide by the first probe
    _options_supported: Optional[bool] = None

    @staticmethod
    def _probe_compiler_options() -> bool:
        """Whether this backend's compile service accepts DebugOptions
        overrides (the axon remote-compile tunnel rejects them all).
        Probed once per process with a trivial program so a user step
        failure can never be misattributed to option rejection."""
        cls = DistributedDataParallel
        if cls._options_supported is None:
            try:
                jax.jit(lambda x: x + 1, compiler_options={
                    "xla_gpu_all_reduce_combine_threshold_bytes":
                    "10485760"})(jnp.zeros(1))
                cls._options_supported = True
            except Exception:
                cls._options_supported = False
                warnings.warn(
                    "backend rejects compiler options; the message_size "
                    "combine hint will be ignored", RuntimeWarning)
        return cls._options_supported

    def _compiler_options(self) -> Optional[dict]:
        """``message_size`` (elements; the reference default is 1e7 ≈
        40 MB of fp32, `apex/parallel/distributed.py:165`) → the XLA
        collective-combiner threshold, scaled by the reduction dtype's
        itemsize. ``None`` lets XLA choose.

        Best-effort contract: the DebugOptions field is shared across
        backends despite the gpu prefix and demonstrably reaches the
        compiled executable where options are accepted (pinned by
        tests/test_parallel.py), but whether a given TPU runtime's
        combiner honors it is backend-version dependent — treat it as a
        hint, exactly like the reference's bucketing heuristic."""
        if self.message_size is None:
            return None
        if not self._probe_compiler_options():
            return None
        import jax.numpy as jnp
        dt = jnp.float32 if (self.allreduce_always_fp32 or
                             self.grad_dtype is None) else self.grad_dtype
        itemsize = jnp.dtype(dt).itemsize
        return {"xla_gpu_all_reduce_combine_threshold_bytes":
                str(int(self.message_size) * itemsize)}

    # -- telemetry -----------------------------------------------------------

    def collective_bytes(self, step_fn: Callable, *args, **kwargs) -> dict:
        """Static per-step collective traffic of a (wrapped) step, by
        opcode, from the compiled HLO — ``{"all-reduce": bytes, ...,
        "total": bytes}``.

        The accounting the reference could only approximate from its own
        bucket bookkeeping (`apex/parallel/distributed.py:425-475`); here
        the compiled program is the ground truth. Compile-time constant:
        feed it to ``MetricsLogger(collective_bytes_per_step=...)`` (or
        let ``MetricsLogger.attach`` derive it) so every logged record
        carries the step's communication volume.
        """
        from apex_tpu.monitor.collectives import collective_bytes as _cb
        return _cb(step_fn, *args, **kwargs)

    def memory_report(self, step_fn: Callable, *args,
                      batch_size: Optional[int] = None, **kwargs):
        """Static per-device HBM footprint of a (wrapped) step — a
        :class:`apex_tpu.prof.MemoryReport` whose class table attributes
        every byte to params / optimizer state / activations / **comm**
        (the ``bucket_plan`` buffers and compressed-collective wire
        staging show up under ``comm``, scoped ``ddp/sync_gradients``).
        AOT-only like :meth:`collective_bytes`: one compile, never a
        dispatch. ``batch_size`` is the PER-DEVICE batch dimension (the
        post-shard_map leading dim, i.e. global batch / world_size) and
        enables the what-if batch forecast. See docs/memory.md."""
        from apex_tpu.prof.memory import memory_report as _mr
        if batch_size is None:
            # infer: the common leading dim of the batch-side args
            # (everything after state), divided over the data axis the
            # wrapper splits it on. Ambiguity (leaves disagreeing on a
            # world-divisible leading dim — e.g. a batch_stats vector
            # riding along) leaves batch_size None: no forecast beats a
            # silently wrong one.
            dims = {l.shape[0] for a in args[1:]
                    for l in jax.tree_util.tree_leaves(a)
                    if getattr(l, "shape", ())}
            cands = {d for d in dims if d % self.world_size == 0}
            if len(cands) == 1:
                batch_size = cands.pop() // self.world_size
        return _mr(step_fn, *args, batch_size=batch_size, **kwargs)

    def wrap_grad_fn(self, grad_fn: Callable) -> Callable:
        """Wrap ``grad_fn(*a, **k) -> (value, grads)`` so grads come back
        synced — the "model wrapper" usage of the reference where backward
        itself triggers the reduction."""
        @functools.wraps(grad_fn)
        def wrapped(*args, **kwargs):
            value, grads = grad_fn(*args, **kwargs)
            return value, self.sync(grads)
        return wrapped
