"""apex_tpu.ops — fused Pallas kernels (SURVEY.md §2.10).

Every native module of the reference maps here: the `amp_C` multi-tensor
family (multi_tensor.py), the optimizer functors (optim_kernels.py), fused
LayerNorm / MLP / softmax-CE / NHWC BatchNorm / attention (their own
modules). All kernels run compiled on TPU and in interpret mode elsewhere.
"""

from apex_tpu.ops.multi_tensor import (
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_maxnorm,
    multi_tensor_scale,
    per_tensor_l2norm,
)
from apex_tpu.ops import optim_kernels

__all__ = [
    "multi_tensor_axpby", "multi_tensor_l2norm", "multi_tensor_maxnorm",
    "multi_tensor_scale", "per_tensor_l2norm", "optim_kernels",
]
