"""Pallas multi-tensor kernel parity tests.

Mirrors `tests/L0/run_amp/test_multi_tensor_scale.py`, `_l2norm`, `_axpby`:
overflow-flag propagation, dtype cross-products, numeric parity vs jnp.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import arena
from apex_tpu.ops import (multi_tensor_axpby, multi_tensor_l2norm,
                          multi_tensor_maxnorm, multi_tensor_scale,
                          per_tensor_l2norm)

N = 512 * 128  # one kernel block


def _buf(dtype=jnp.float32, fill=None, n=N):
    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, (n,), jnp.float32)
    if fill is not None:
        x = x.at[1234].set(fill)
    return x.astype(dtype)


class TestScale:
    @pytest.mark.parametrize("in_dt,out_dt", [
        (jnp.float32, jnp.float32), (jnp.float32, jnp.float16),
        (jnp.float16, jnp.float32), (jnp.bfloat16, jnp.float32),
    ])
    def test_parity(self, in_dt, out_dt):
        x = _buf(in_dt)
        out, finite = multi_tensor_scale(x, 4.0, out_dtype=out_dt)
        assert out.dtype == out_dt
        assert bool(finite)
        ref = (x.astype(jnp.float32) * 4.0).astype(out_dt)
        np.testing.assert_allclose(np.asarray(out, jnp.float32),
                                   np.asarray(ref, jnp.float32), rtol=1e-6)

    @pytest.mark.parametrize("bad", [np.inf, -np.inf, np.nan])
    def test_overflow_flag(self, bad):
        x = _buf(fill=bad)
        _, finite = multi_tensor_scale(x, 1.0)
        assert not bool(finite)

    def test_overflow_from_downcast(self):
        # 1e30 * 1e10 overflows fp32 during scaling -> flag set
        x = _buf().at[7].set(1e30)
        _, finite = multi_tensor_scale(x, 1e10)
        assert not bool(finite)

    def test_multiblock(self):
        x = _buf(n=4 * N)
        out, finite = multi_tensor_scale(x, 0.5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 0.5,
                                   rtol=1e-6)
        assert bool(finite)

    def test_under_jit(self):
        f = jax.jit(lambda x, s: multi_tensor_scale(x, s))
        out, finite = f(_buf(), jnp.float32(2.0))
        np.testing.assert_allclose(np.asarray(out), np.asarray(_buf()) * 2,
                                   rtol=1e-6)


class TestAxpby:
    def test_parity(self):
        x, y = _buf(), _buf() * 2
        out, finite = multi_tensor_axpby(2.0, x, -3.0, y)
        np.testing.assert_allclose(
            np.asarray(out), 2 * np.asarray(x) - 3 * np.asarray(y),
            rtol=1e-5)
        assert bool(finite)

    def test_flag_on_nan_either_input(self):
        x, y = _buf(fill=np.nan), _buf()
        _, finite = multi_tensor_axpby(1.0, x, 1.0, y)
        assert not bool(finite)
        _, finite = multi_tensor_axpby(1.0, y, 1.0, x)
        assert not bool(finite)


class TestNorms:
    def test_l2_parity(self):
        x = _buf(n=2 * N)
        got = multi_tensor_l2norm(x)
        ref = jnp.sqrt(jnp.sum(jnp.square(x)))
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)

    def test_maxnorm(self):
        x = _buf().at[99].set(-123.0)
        assert abs(float(multi_tensor_maxnorm(x)) - 123.0) < 1e-6

    def test_per_tensor_l2norm(self):
        tree = {"a": jnp.full((10,), 2.0), "b": jnp.full((7,), 3.0)}
        spec = arena.plan(tree)
        flat = arena.flatten(tree, spec)["float32"]
        seg = jnp.asarray(arena.segment_ids(spec, jnp.float32))
        norms = per_tensor_l2norm(flat, seg, 2)
        np.testing.assert_allclose(
            np.asarray(norms),
            [np.sqrt(10 * 4.0), np.sqrt(7 * 9.0)], rtol=1e-6)


class TestPerTensorShardSums:
    """per_tensor_sq_shard vs a segment-sum oracle over every shard
    offset alignment case (tensor fully inside / straddling / outside the
    shard; boundaries on and off block edges)."""

    def _oracle(self, full, offsets, sizes, lo, hi):
        out = []
        for off, sz in zip(offsets, sizes):
            a, b = max(off, lo), min(off + sz, hi)
            seg = full[a:b] if b > a else np.zeros(0, np.float32)
            out.append(np.sum(np.square(seg.astype(np.float64))))
        return np.array(out)

    @pytest.mark.parametrize("shard_start,shard_len", [
        (0, 700), (300, 700), (650, 700), (1024, 700), (0, 2048)])
    def test_matches_oracle(self, shard_start, shard_len):
        from apex_tpu.ops.multi_tensor import per_tensor_sq_shard
        rng = np.random.RandomState(0)
        offsets = (0, 140, 300, 1000, 1500)
        sizes = (130, 150, 700, 500, 400)
        full = rng.randn(2048).astype(np.float32)
        shard = jnp.asarray(
            full[shard_start:shard_start + shard_len]
            if shard_start < 2048 else np.zeros(shard_len, np.float32))
        got = per_tensor_sq_shard(shard, offsets, sizes,
                                  jnp.int32(shard_start), block=256)
        ref = self._oracle(full, offsets, sizes, shard_start,
                           min(shard_start + shard_len, 2048))
        np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5,
                                   atol=1e-5)

    def test_jittable_with_traced_start(self):
        from apex_tpu.ops.multi_tensor import per_tensor_sq_shard
        rng = np.random.RandomState(1)
        buf = jnp.asarray(rng.randn(512).astype(np.float32))
        f = jax.jit(lambda b, s: per_tensor_sq_shard(
            b, (0, 200), (180, 300), s, block=128))
        a = f(buf, jnp.int32(0))
        b = f(buf, jnp.int32(128))
        assert a.shape == (2,) and not np.allclose(a, b)

    def test_no_scatter_in_jaxpr(self):
        from apex_tpu.ops.multi_tensor import per_tensor_sq_shard
        buf = jnp.ones(1024)
        jaxpr = str(jax.make_jaxpr(lambda b, s: per_tensor_sq_shard(
            b, (0, 512), (512, 512), s))(buf, jnp.int32(0)))
        assert "scatter" not in jaxpr


class TestSpreadPerTensorShard:
    @pytest.mark.parametrize("shard_start", [0, 300, 650, 1024])
    def test_matches_gather_oracle(self, shard_start):
        from apex_tpu.ops.multi_tensor import spread_per_tensor_shard
        offsets = (0, 140, 300, 1000, 1500)
        sizes = (130, 150, 700, 500, 400)
        per = 700
        vals = jnp.asarray([1.0, 2.0, 3.0, 4.0, 5.0])
        got = np.asarray(spread_per_tensor_shard(
            vals, offsets, sizes, jnp.int32(shard_start), per))
        ref = np.zeros(per, np.float32)
        for j, (off, sz) in enumerate(zip(offsets, sizes)):
            for pos in range(per):
                gp = shard_start + pos
                if off <= gp < off + sz:
                    ref[pos] = float(vals[j])
        np.testing.assert_array_equal(got, ref)

    def test_big_tensor_spans_whole_shard(self):
        from apex_tpu.ops.multi_tensor import spread_per_tensor_shard
        got = np.asarray(spread_per_tensor_shard(
            jnp.asarray([7.0]), (0,), (4096,), jnp.int32(1024), 512))
        np.testing.assert_array_equal(got, np.full(512, 7.0, np.float32))

    def test_no_gather_scatter_in_jaxpr(self):
        from apex_tpu.ops.multi_tensor import spread_per_tensor_shard
        jaxpr = str(jax.make_jaxpr(lambda v, s: spread_per_tensor_shard(
            v, (0, 256), (256, 256), s, 256))(jnp.ones(2), jnp.int32(0)))
        assert "scatter" not in jaxpr and "gather[" not in jaxpr
