"""apex_tpu.data — input pipeline (decode → augment → device prefetch).

The reference trains from real data through either torch's DataLoader or
DALI (`examples/imagenet/main_amp.py:28-57`, `:264-317` data_prefetcher).
This package is the TPU-side equivalent: a threaded JPEG decode+augment
source over an ImageFolder tree, a bounded device-put prefetcher that
overlaps host→device transfer with compute, and measurement helpers that
report whether a config is input-bound or compute-bound.
"""

from apex_tpu.data.pipeline import (
    DevicePrefetcher,
    ImageFolderSource,
    make_fake_imagefolder,
    measure_source,
    synthetic_source,
)
from apex_tpu.data.packed import PackedSource, build_cache

__all__ = [
    "DevicePrefetcher", "ImageFolderSource", "make_fake_imagefolder",
    "measure_source", "synthetic_source",
    "PackedSource", "build_cache",
]
