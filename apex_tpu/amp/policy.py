"""Precision policy: the TPU-native replacement for amp opt-levels.

The reference configures precision with an ``amp.initialize(...,
opt_level="O1")`` call that validates a ``Properties`` struct and then rewires
the interpreter (`apex/amp/frontend.py:7-358`). Here the same knobs live in an
immutable :class:`Policy` value:

- O0: pure fp32 (`frontend.py:166-191`)
- O1: fp32 params, per-op cast policy (MXU ops in half, reductions/losses in
  fp32), dynamic loss scaling (`frontend.py:144-163`)
- O2: half model + fp32 batchnorm + fp32 master weights, dynamic loss scaling
  (`frontend.py:122-141`)
- O3: pure half, no master weights (`frontend.py:102-119`)

On TPU the half dtype defaults to **bfloat16**, which shares fp32's exponent
range — loss scaling then becomes optional (``loss_scale=None``) and O1/O2
degenerate to cheap dtype policies. ``half_dtype=jnp.float16`` restores exact
reference semantics (with the scaler) for parity testing.

A policy is *applied*, never patched in: ``policy_scope(policy)`` sets the
ambient policy consulted by ``apex_tpu.ops``/``apex_tpu.layers`` at trace
time, and :func:`cast_params` / :func:`cast_to_compute` do the explicit
casts. Because tracing happens once under ``jax.jit``, the "patching" cost the
reference pays per call (`apex/amp/wrap.py:10-29`) is paid once at compile
time here.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

from apex_tpu.amp import lists
from apex_tpu.utils import tree_cast

_DtypeLike = Any


def _canon(dt):
    return jnp.dtype(dt) if dt is not None else None


@dataclasses.dataclass(frozen=True)
class Policy:
    """Immutable precision policy (the reference's validated ``Properties``).

    Attributes:
      opt_level: "O0" | "O1" | "O2" | "O3" (or None for a custom policy).
      enabled: master switch; disabled == O0 semantics.
      half_dtype: the low precision dtype (bfloat16 on TPU; float16 for
        reference-parity runs).
      cast_model_type: dtype model params are cast to for the forward pass
        (None = leave fp32; O2/O3 set this to half_dtype).
      patch_ops: per-op cast policy active (O1) — ops listed HALF compute in
        half_dtype, FLOAT ops in fp32, PROMOTE ops widen.
      keep_batchnorm_fp32: exempt batch/layer-norm scale/offset (and their
        statistics) from model casts (O2).
      master_weights: optimizer holds fp32 masters and the update runs in
        fp32 regardless of model dtype (O1/O2).
      loss_scale: "dynamic", a float (static), or None (no scaling).
      output_dtype: dtype model outputs are cast back to (fp32 by default,
        mirroring the patched-forward output cast, `_initialize.py:194-201`).
    """

    opt_level: Optional[str] = None
    enabled: bool = True
    half_dtype: _DtypeLike = jnp.bfloat16
    cast_model_type: Optional[_DtypeLike] = None
    patch_ops: bool = False
    keep_batchnorm_fp32: bool = False
    master_weights: bool = True
    loss_scale: Union[str, float, None] = None
    output_dtype: Optional[_DtypeLike] = jnp.float32

    # ---- construction ------------------------------------------------------

    @classmethod
    def from_opt_level(cls, opt_level: str, *, half_dtype=jnp.bfloat16,
                       **overrides) -> "Policy":
        """Build a preset policy then apply per-field overrides.

        Mirrors ``amp.initialize``'s "apply opt_level, then explicit kwargs
        win" contract (`frontend.py:308-356`).
        """
        half_dtype = _canon(half_dtype)
        # fp16 needs loss scaling; bf16 has fp32's range so scaling is
        # unnecessary unless explicitly requested.
        default_scale = "dynamic" if half_dtype == jnp.float16 else None
        presets = {
            "O0": dict(enabled=True, cast_model_type=None, patch_ops=False,
                       keep_batchnorm_fp32=False, master_weights=False,
                       loss_scale=None, half_dtype=half_dtype),
            "O1": dict(enabled=True, cast_model_type=None, patch_ops=True,
                       keep_batchnorm_fp32=False, master_weights=False,
                       loss_scale=default_scale, half_dtype=half_dtype),
            "O2": dict(enabled=True, cast_model_type=half_dtype,
                       patch_ops=False, keep_batchnorm_fp32=True,
                       master_weights=True, loss_scale=default_scale,
                       half_dtype=half_dtype),
            "O3": dict(enabled=True, cast_model_type=half_dtype,
                       patch_ops=False, keep_batchnorm_fp32=False,
                       master_weights=False, loss_scale=1.0,
                       half_dtype=half_dtype),
        }
        if opt_level not in presets:
            raise ValueError(
                f"Unexpected optimization level {opt_level!r}; options are "
                "'O0', 'O1', 'O2', 'O3'.")
        kwargs = presets[opt_level]
        kwargs.update(overrides)
        policy = cls(opt_level=opt_level, **kwargs)
        policy.validate()
        return policy

    def replace(self, **overrides) -> "Policy":
        p = dataclasses.replace(self, **overrides)
        p.validate()
        return p

    def validate(self) -> None:
        """Cross-field consistency checks (`Properties.__setattr__` checks,
        `frontend.py:51-97`)."""
        half = _canon(self.half_dtype)
        if half not in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
            raise ValueError(f"half_dtype must be bfloat16 or float16, got {half}")
        cm = _canon(self.cast_model_type)
        if cm is not None and jnp.issubdtype(cm, jnp.floating) is False:
            raise ValueError(f"cast_model_type must be a float dtype, got {cm}")
        if self.patch_ops and cm is not None and cm != jnp.dtype(jnp.float32):
            raise ValueError(
                "patch_ops (O1-style op policy) expects fp32 params; "
                "combining it with a cast model is not supported "
                "(matches the reference's O1/O2 exclusivity).")
        if isinstance(self.loss_scale, str) and self.loss_scale != "dynamic":
            raise ValueError("loss_scale must be a float, 'dynamic', or None")
        fp16_compute = (cm == jnp.dtype(jnp.float16)
                        or (self.patch_ops and half == jnp.dtype(jnp.float16)))
        if fp16_compute and self.loss_scale is None and self.enabled:
            raise ValueError(
                "float16 compute without loss scaling will underflow; pass "
                "loss_scale='dynamic' (or a static scale).")

    # ---- dtype queries -----------------------------------------------------

    @property
    def compute_dtype(self):
        """Dtype MXU-class ops run in under this policy."""
        if not self.enabled:
            return jnp.dtype(jnp.float32)
        if self.cast_model_type is not None:
            return _canon(self.cast_model_type)
        if self.patch_ops:
            return _canon(self.half_dtype)
        return jnp.dtype(jnp.float32)

    @property
    def param_dtype(self):
        """Storage dtype of the params the *model* consumes."""
        if self.enabled and self.cast_model_type is not None:
            return _canon(self.cast_model_type)
        return jnp.dtype(jnp.float32)

    @property
    def uses_loss_scaling(self) -> bool:
        return self.enabled and self.loss_scale is not None

    def op_dtype(self, op_name: str, *input_dtypes):
        """Resolve the compute dtype for a named op under this policy.

        This is the runtime of the reference's wrapped-namespace dispatch
        (`apex/amp/wrap.py`): HALF ops get ``half_dtype``, FLOAT ops fp32,
        PROMOTE ops the widest input dtype, neutral ops the first input
        dtype. Raises for banned ops in half precision.
        """
        if not self.enabled:
            return jnp.dtype(jnp.float32)
        kind = lists.classify(op_name)
        if kind == "banned":
            if self.patch_ops or self.cast_model_type is not None:
                raise TypeError(lists.BANNED_MESSAGE.format(
                    name=op_name, dtype=self.half_dtype))
            return jnp.dtype(jnp.float32)
        if not self.patch_ops and self.cast_model_type is None:
            # O0-style: no op policy, respect inputs
            return _promote(input_dtypes) if input_dtypes else jnp.float32
        if kind == "half":
            return _canon(self.half_dtype)
        if kind == "float":
            return jnp.dtype(jnp.float32)
        if kind == "promote":
            return _promote(input_dtypes)
        # neutral: match first floating input, else fp32
        return _promote(input_dtypes[:1]) if input_dtypes else jnp.float32

    # ---- casting helpers ---------------------------------------------------

    def _bn_exempt(self, path, _leaf) -> bool:
        """True if a param at ``path`` should stay fp32 (batchnorm-style).

        Matched per path *component* against normalization-layer naming
        conventions (flax's ``BatchNorm_0``/``LayerNorm_0``, our
        ``batch_norm``/``sync_batch_norm``, and the ``batch_stats``
        collection) — not by substring over the whole path, so e.g. a module
        named ``subnet`` is not exempted.
        """
        names = [str(getattr(k, "key", getattr(k, "name", k))).lower()
                 for k in path]
        return any(_NORM_COMPONENT_RE.match(n) for n in names)

    def cast_params(self, params):
        """Cast a param tree to the model dtype (``convert_network``,
        `apex/fp16_utils/fp16util.py:75-100`). Norm params stay fp32 when
        ``keep_batchnorm_fp32``."""
        if not self.enabled or self.cast_model_type is None:
            return params
        pred = ((lambda p, x: not self._bn_exempt(p, x))
                if self.keep_batchnorm_fp32 else None)
        return tree_cast(params, _canon(self.cast_model_type), predicate=pred)

    def cast_inputs(self, tree):
        """Cast floating inputs to the model compute dtype (the patched
        ``model.forward`` input cast, `_initialize.py:194-198`)."""
        if not self.enabled or self.cast_model_type is None:
            return tree
        return tree_cast(tree, _canon(self.cast_model_type))

    def cast_outputs(self, tree):
        """Cast floating outputs to ``output_dtype`` (fp32 by default)."""
        if not self.enabled or self.output_dtype is None:
            return tree
        return tree_cast(tree, _canon(self.output_dtype))

    def cast_to_compute(self, tree):
        """Cast floating leaves to :attr:`compute_dtype`."""
        return tree_cast(tree, self.compute_dtype)


import re

_NORM_COMPONENT_RE = re.compile(
    r"^(bn\d*"                                    # bn, bn1 ...
    r"|batch_?norm.*|sync_?batch_?norm.*"          # batch_norm*, syncbn modules
    r"|(layer|group|rms|instance)_?norm.*"         # other norm layers
    r"|norm(_\d+)?"                                # bare norm / norm_0
    r"|batch_stats)$"                              # flax BN statistics
)


def _promote(dtypes):
    dts = [jnp.dtype(d) for d in dtypes if d is not None]
    dts = [d for d in dts if jnp.issubdtype(d, jnp.floating)]
    if not dts:
        return jnp.dtype(jnp.float32)
    out = dts[0]
    for d in dts[1:]:
        out = jnp.promote_types(out, d)
    return out


# --- Ambient policy ---------------------------------------------------------
#
# The policy consulted by apex_tpu.ops / apex_tpu.layers when none is passed
# explicitly. Thread-local because tracing is thread-confined; inside a jitted
# function the scope binds at trace time, so there is zero runtime cost.

class _PolicyState(threading.local):
    def __init__(self):
        self.stack = []


_state = _PolicyState()

_DEFAULT_POLICY = Policy(opt_level="O0", enabled=False)


def current_policy() -> Policy:
    return _state.stack[-1] if _state.stack else _DEFAULT_POLICY


@contextlib.contextmanager
def policy_scope(policy: Policy):
    """Bind ``policy`` as the ambient policy for ops built while tracing."""
    _state.stack.append(policy)
    try:
        yield policy
    finally:
        _state.stack.pop()
