#!/usr/bin/env bash
# Tier-1 verify — the ROADMAP.md command, encapsulated.
#
#   scripts/run_tier1.sh            # full tier-1 pytest run (870s budget)
#   scripts/run_tier1.sh --smoke    # fast pre-flight: schema validators
#                                   # + a 3-step traced bench.py --trace run
#                                   # + the DDP overlap audit (8-device
#                                   #   CPU variant of pod_comm_budget,
#                                   #   incl. the hierarchical-schedule
#                                   #   gate: one-member-per-slice DCN
#                                   #   groups, per-hop dtype split,
#                                   #   APX203 ABSENT + the flat
#                                   #   negative twin still firing)
#                                   # + the memory-budget audit (--cpu8)
#                                   # + the ckpt save->kill->elastic-
#                                   #   restore roundtrip (--cpu8)
#                                   # + the guard chaos audit (--cpu8):
#                                   #   clean-run zero interventions,
#                                   #   NaN-spike rewind bitwise vs a
#                                   #   fault-free oracle, skip-class
#                                   #   convergence, guard schema
#                                   # + the integrity audit (--cpu8):
#                                   #   silent mantissa-bitflip caught
#                                   #   by cross-replica fingerprints,
#                                   #   minority named by quorum vote,
#                                   #   repaired in place bitwise vs
#                                   #   oracle; no-majority falls
#                                   #   through to coordinated rewind;
#                                   #   EF-int8 hierarchical sync
#                                   #   fingerprint-clean
#                                   # + the cluster control-plane audit
#                                   #   (--cpu8): zombie write/delete
#                                   #   fenced after a generation bump,
#                                   #   coordinated cross-rank rewind
#                                   #   bitwise vs oracle with exactly
#                                   #   one bump, split-brain intent +
#                                   #   CAS refused, hung collective
#                                   #   named, cluster schema
#                                   # + apexlint on the flagship steps
#                                   #   incl. the guarded/ckpt
#                                   #   self-audit targets (asserts
#                                   #   zero error findings)
#                                   # + the cross-rank SPMD congruence
#                                   #   audit (--mesh dp2x4 on the
#                                   #   cpu8 mesh, --fail-on error)
#                                   # + the link probe (--cpu8): sweep
#                                   #   collectives per mesh axis, fit
#                                   #   alpha-beta, emit a MEASURED
#                                   #   MeshModel JSON
#                                   # + the goodput audit (--cpu8):
#                                   #   per-step bucket attribution
#                                   #   closes over wall time within
#                                   #   5%, a seeded synthetic slow
#                                   #   rank is named with its slowest
#                                   #   span class, and the measured
#                                   #   model round-trips through
#                                   #   apexlint --mesh with APX203 hop
#                                   #   evidence from the measured
#                                   #   bytes/s
#                                   # + the pod observatory audit
#                                   #   (--cpu8): cross-rank timeline
#                                   #   merge recovers injected clock
#                                   #   offsets, collective skew blamed
#                                   #   on the seeded (rank, span),
#                                   #   goodput comm_skew/comm_wire
#                                   #   split still closes, 4-process
#                                   #   merge on real clocks, measured
#                                   #   hop wire time vs plan within
#                                   #   the stated band + staled-model
#                                   #   negative twin, podview schema
#                                   #   incl. the committed fixture
#                                   # + the numerics observatory audit
#                                   #   (--cpu8): per-tensor dynamic-
#                                   #   range fold zero-dispatch on the
#                                   #   BERT step, e4m3-boundary tensor
#                                   #   flagged at the right site with
#                                   #   a scale that fixes it,
#                                   #   ScaleHistory bitwise vs oracle,
#                                   #   numerics schema
#                                   # + the roofline observatory audit
#                                   #   (--cpu8): per-op attribution
#                                   #   closure on the committed BERT
#                                   #   fixture, the known fused-
#                                   #   backward gap named, AOT-only
#                                   #   path, sentinel seeded positive
#                                   #   + negative twin
#                                   # + the mesh pre-flight explainer
#                                   #   (--cpu8): per-axis HBM closure
#                                   #   + ZeRO ~1/N declared shards,
#                                   #   wire pricing vs the alpha-beta
#                                   #   plan within band, flat ranked
#                                   #   below hierarchical with APX203
#                                   #   attached, sharding schema
#                                   # + the kernel autotuner audit
#                                   #   (--cpu8 --interpret): block-
#                                   #   shape sweep accounted compile-
#                                   #   exact under autotune_scope,
#                                   #   DB round-trip + loud stale
#                                   #   refusal, committed DB exact-key
#                                   #   hits on every family, zero
#                                   #   steady-state autotune compiles,
#                                   #   tune_report covers the fused-
#                                   #   backward roofline candidate
#                                   # + the perf sentinel gate over the
#                                   #   committed BENCH_r0*.json
#                                   #   trajectory (exit 1 on unwaived
#                                   #   regression)
#
# Exit status is pytest's (or the first failing smoke step). The full
# run prints DOTS_PASSED=<n> — the count of passing-test dots the driver
# tracks — whether or not the run hit the timeout.

set -u -o pipefail
cd "$(dirname "$0")/.."

TIMEOUT="${TIER1_TIMEOUT:-870}"
LOG="${TIER1_LOG:-/tmp/_t1.log}"

if [[ "${1:-}" == "--smoke" ]]; then
    set -e
    tmp="$(mktemp -d)"
    trap 'rm -rf "$tmp"' EXIT

    echo "== smoke: metrics schema validator (self-test stream)"
    JAX_PLATFORMS=cpu python - "$tmp/metrics.jsonl" <<'EOF'
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from apex_tpu import monitor
logger = monitor.MetricsLogger(
    sinks=[monitor.JSONLSink(sys.argv[1])], flush_every=2)
m = monitor.metrics_init()
for i in range(4):
    m = m.count_step(jnp.bool_(True)).record_loss(float(i))
    logger.record(m)
logger.close()
EOF
    python scripts/check_metrics_schema.py --kind metrics "$tmp/metrics.jsonl"

    echo "== smoke: 3-step traced bench (bench.py --trace)"
    # run inside $tmp so TRACE*.json(l) artifacts never land in the tree
    repo="$(pwd)"
    (cd "$tmp" && JAX_PLATFORMS=cpu python "$repo/bench.py" --trace)

    echo "== smoke: trace schema validator on the bench event stream"
    python scripts/check_metrics_schema.py --kind trace \
        "$tmp/TRACE_EVENTS.jsonl"

    echo "== smoke: Chrome trace is valid JSON with traceEvents"
    python - "$tmp/TRACE.json" <<'EOF'
import json, sys
ct = json.load(open(sys.argv[1]))
assert isinstance(ct.get("traceEvents"), list) and ct["traceEvents"], \
    "TRACE.json has no traceEvents"
EOF

    echo "== smoke: DDP overlap audit (8-device CPU variant)"
    # includes the hierarchical compressed-sync gate: the factored
    # (2-slice x 4) mesh compiles int8 ICI reduce-scatter ->
    # one-member-per-slice DCN reduce -> ICI all-gather, APX203 stays
    # ABSENT on that module (exit 1 on reappearance), and the flat
    # negative twin still fires it — the ROADMAP item-2 done-state.
    JAX_PLATFORMS=cpu python scripts/pod_comm_budget.py --cpu8

    echo "== smoke: memory-budget audit (8-device CPU variant)"
    # asserts: (a) class attribution == memory_analysis within 1%,
    # (b) ZeRO optimizer state ~1/N vs replicated, (c) compile_watch
    # 1 steady-state compile + named changed arg on a forced retrace
    JAX_PLATFORMS=cpu python scripts/memory_budget.py --cpu8

    echo "== smoke: checkpoint save->kill->elastic-restore roundtrip"
    # asserts: (a) SIGKILL mid-save (both crash points) leaves the
    # previous committed checkpoint as latest + hash-verified loadable,
    # (b) ZeRO run saved on the 8-mesh resumes on a 4-mesh bitwise vs
    # an uninterrupted 4-mesh run, (c) async capture stall bounded by
    # the full save, (d) the ckpt event stream passes --kind ckpt
    JAX_PLATFORMS=cpu python scripts/ckpt_roundtrip.py --cpu8

    echo "== smoke: guard chaos audit (8-device CPU mesh)"
    # asserts: (a) a fault-free guarded run triggers ZERO guard events
    # and compiles bit-identical HLO under observation, (b) an injected
    # param-NaN spike rewinds (rejecting the corrupted newer ckpt) and
    # its post-rewind losses + final params bitwise-match an oracle
    # that never saw the poison window, (c) grad-NaN/Inf + corrupt-
    # batch faults are skipped in-graph and still converge, (d) the
    # guard event stream passes --kind guard
    JAX_PLATFORMS=cpu python scripts/chaos_audit.py --cpu8

    echo "== smoke: integrity silent-divergence audit (8-device CPU mesh)"
    # asserts: (a) a fault-free fingerprinted run logs ZERO integrity
    # events with bit-identical HLO under host polling, (b) a seeded
    # FINITE mantissa bitflip on replica 1 (silent to the NaN/spike
    # probes) is detected within check_every steps, the minority named
    # by quorum vote, and repaired IN PLACE (no rewind, cursor
    # untouched) bitwise vs a fault-free oracle, (c) a 2-of-2
    # no-majority divergence falls through to the coordinated-rewind
    # path with exactly one generation bump, (d) the EF-int8
    # hierarchical sync runs fingerprint-clean (the collectives-v2
    # runtime proof), (e) every stream passes --kind integrity
    JAX_PLATFORMS=cpu python scripts/integrity_audit.py --cpu8

    echo "== smoke: cluster control-plane audit (8-device CPU mesh)"
    # asserts: (a) a rank paused through an escalation + relaunch has
    # its late checkpoint write AND retention delete refused by the
    # generation fence (latest_checkpoint untouched), (b) rank-
    # asymmetric param corruption resolves to ONE agreed rewind target
    # (oldest good step wins) with exactly one generation bump and
    # post-rewind losses + params bitwise vs a fault-free oracle on
    # both ranks, (c) a split-brain generation claim is refused at
    # intent verification and at the CAS bump, (d) a hung collective
    # is named + escalated and every stream passes --kind cluster
    JAX_PLATFORMS=cpu python scripts/cluster_audit.py --cpu8

    echo "== smoke: apexlint flagship steps (--fail-on error)"
    # lints the flagship ResNet-O2 and BERT-LAMB steps (CPU structural
    # downscalings) PLUS the guard-instrumented step, the ckpt
    # snapshot copy program and the dynamics-instrumented step (the
    # self-audit targets) against the committed baseline — which
    # starts EMPTY, so any new error-severity finding (donation miss,
    # host transfer, f64 creep, RNG reuse, non-replayable randomness,
    # unscaled narrow cast, scale leak) breaks this gate
    JAX_PLATFORMS=cpu python scripts/apexlint.py --flagship all \
        --baseline scripts/apexlint_baseline.json --fail-on error \
        --jsonl "$tmp/lint.jsonl"

    echo "== smoke: lint schema validator on the apexlint event stream"
    python scripts/check_metrics_schema.py --kind lint "$tmp/lint.jsonl"

    echo "== smoke: apexlint precision certification sweep (O0-O3)"
    # the precision pass (APX3xx, docs/linting.md#apx3xx) over both
    # flagships REBUILT at every amp opt level: the amp machinery's
    # scale/unscale/cast structure must certify statically at each
    # level — an unscaled narrow cast, a scale leaking past the
    # unscale, or a master-weight violation is an error against the
    # same empty baseline
    JAX_PLATFORMS=cpu python scripts/apexlint.py --flagship both \
        --opt-level all --baseline scripts/apexlint_baseline.json \
        --fail-on error --jsonl "$tmp/lint_precision.jsonl"

    echo "== smoke: lint schema validator on the precision stream"
    python scripts/check_metrics_schema.py --kind lint \
        "$tmp/lint_precision.jsonl"

    echo "== smoke: apexlint cross-rank congruence audit (cpu8, dp2x4)"
    # the SPMD pass over the DDP flagship steps compiled on the
    # FACTORED 2-slice x 4-chip mesh with the hierarchical comm_plan
    # (collectives v2): asserts zero APX201 deadlock/divergence and
    # zero error-severity findings. APX203-clean is now the EXPECTED
    # flagship state (docs/linting.md) — the flat negative twin that
    # proves the rule still fires lives in pod_comm_budget --cpu8 and
    # tests/test_pod_hlo.py.
    JAX_PLATFORMS=cpu python scripts/apexlint.py --flagship both \
        --mesh dp2x4 --baseline scripts/apexlint_baseline.json \
        --fail-on error --jsonl "$tmp/lint_mesh.jsonl"

    echo "== smoke: lint schema validator on the cross-rank stream"
    python scripts/check_metrics_schema.py --kind lint \
        "$tmp/lint_mesh.jsonl"

    echo "== smoke: link probe (8-device CPU mesh, measured MeshModel)"
    # sweeps all-reduce/reduce-scatter/all-gather per mesh axis, fits
    # alpha-beta, and emits a MeshModel JSON with MEASURED
    # link_bytes_per_s + calibration provenance; the emitted stream
    # validates under --kind goodput and the artifact self-checks its
    # round-trip through parse_mesh_spec
    JAX_PLATFORMS=cpu python scripts/link_probe.py --cpu8 \
        --out "$tmp/mesh_measured.json" --jsonl "$tmp/linkfit.jsonl"
    python scripts/check_metrics_schema.py --kind goodput \
        "$tmp/linkfit.jsonl"

    echo "== smoke: goodput attribution + straggler + calibration audit"
    # asserts: (a) the goodput ledger's bucket sum closes over each
    # step's measured wall time within 5% (recompile bucket present on
    # step 0 only; injected input-wait and joined ckpt stall land in
    # their buckets), (b) a seeded synthetic slow rank is flagged with
    # hysteresis and named with its slowest span class, feeding the
    # watchdog's early-warning tier, (c) link_probe's measured
    # MeshModel round-trips through apexlint --mesh with APX203 hop
    # milliseconds computed from the MEASURED bytes/s, (d) every
    # stream passes --kind goodput
    JAX_PLATFORMS=cpu python scripts/goodput_audit.py --cpu8

    echo "== smoke: pod observatory audit (--cpu8)"
    # asserts: (a) the synthetic 4-rank merge recovers injected clock
    # offsets to sub-us residual and blames EVERY collective on the
    # seeded (rank 2, data/load) with the exact skew/wire split, the
    # critical path chains wait->wire, and the podview stream + the
    # committed fixture validate under --kind podview, (b) a
    # pod-measured skew joins OUT of comm_wire into comm_skew with the
    # bucket closure intact (oversized claims clamped), (c) 4 real
    # processes with unrelated perf_counter origins merge through
    # barrier-released collective spans and blame the seeded slow
    # rank, (d) measured per-hop wire time agrees with plan_comm's
    # hop_seconds within the stated band on the calibrated dp2x4 mesh
    # AND the deliberately staled model fires the drift flag with
    # link_probe advice
    JAX_PLATFORMS=cpu python scripts/pod_audit.py --cpu8

    echo "== smoke: numerics observatory audit (--cpu8)"
    # asserts: (a) the instrumented structural BERT step (numerics
    # fold + grad-site ScaleHistory through Amp.step) emits ZERO
    # surprise verdicts with compiled HLO bit-identical under per-step
    # host polling and no host ops, (b) a seeded tensor straddling the
    # e4m3 underflow boundary is flagged at the correct site with a
    # verdict naming the minimum safe format and a recommended_scale
    # that, applied, drives the measured underflow below threshold,
    # (c) ScaleHistory tracks a synthetic amax ramp matching a
    # pure-numpy oracle bitwise through grow/shrink/backoff, (d) the
    # stream passes --kind numerics with all three kinds present
    JAX_PLATFORMS=cpu python scripts/numerics_audit.py --cpu8

    echo "== smoke: training-dynamics observatory audit (--cpu8)"
    # asserts: (a) the GNS/B_crit estimator recovers a KNOWN injected
    # gradient noise scale within 25% through the real pipeline
    # (8-replica shard_map, the registered ddp/dynamics_* collectives,
    # the EMA fold), with the G2/S intermediates matching their
    # analytic values, (b) bit-replicated gradients measure cosine and
    # Adasum projection = 1 while a seeded-decorrelation twin drops to
    # the analytic ~1/sqrt(world) cosine regime, (c) the
    # noise-calibrated convergence comparator flags a too-high-LR
    # trajectory at the seeded divergence step under a band calibrated
    # from paired-seed runs AND stays quiet on a paired-seed twin,
    # (d) Amp.step(dynamics=...) leaves losses and params bitwise
    # identical observed-vs-not at O0-O3, (e) the stream passes
    # --kind dynamics with all three kinds and the
    # dynamics/no-extra-dispatch compile-check case is green
    JAX_PLATFORMS=cpu python scripts/dynamics_audit.py --cpu8

    echo "== smoke: roofline observatory audit (--cpu8)"
    # asserts: (a) the per-op roofline join over the committed
    # BERT-layer fixture closes over the trace's module device time
    # within 5%, classifies attention compute-bound / LayerNorm
    # memory-bound, and worst_gaps names the PERF.md round-5 fused-
    # backward attention gap (~549 us measured vs its ~436 us d=64 MXU
    # floor), (b) an AOT-only report carries measured_us=null analytic
    # rows with dot FLOPs folded into calling fusions, (c) the
    # sentinel flags a seeded 45% MFU drop on the committed r01–r05
    # trajectory AND passes clean on the unmodified trajectory (the
    # negative twin), (d) every stream passes --kind roofline
    JAX_PLATFORMS=cpu python scripts/roofline_audit.py --cpu8

    echo "== smoke: mesh pre-flight explainer (--cpu8)"
    # asserts: (a) per-axis HBM closes over the memory report's class
    # totals and the ZeRO candidate's declared opt-state shards show
    # the ~1/N local/global ratio, (b) per-axis wire pricing agrees
    # with the alpha-beta comm plan within the stated band on both
    # hops, (c) the flat candidate is ranked below the hierarchical
    # one WITH an APX203 verdict attached while the hierarchical one
    # is clean, (d) the emitted stream passes --kind sharding
    JAX_PLATFORMS=cpu python scripts/mesh_explain.py --cpu8

    echo "== smoke: kernel autotuner audit (sweep -> DB -> dispatch, --cpu8)"
    # asserts: (a) the interpret-mode block-shape sweep over all five
    # kernel families accounts for EXACTLY its candidate count in
    # compile_watch's autotune_scope and shows a measurable best-vs-
    # worst spread on >=1 family, while a steady-state tuned dispatch
    # re-jit adds ZERO autotune compiles and records an exact-key DB
    # hit, (b) the tuning DB round-trips save->load->exact-key-hit,
    # nearest-miss shapes return None (defaults), and a seeded stale
    # entry is refused LOUDLY naming its fingerprint, (c) the committed
    # scripts/kernel_tuning_db.json loads with a winner for every
    # family and 5/5 exact-key hits on the sweep shapes, (d)
    # tune_report joins the DB against the roofline fixture's
    # worst_gaps — the PERF.md fused-backward attention candidate
    # (~549 us vs ~436 us) shows as COVERED — and both tune-event
    # streams pass --kind roofline
    JAX_PLATFORMS=cpu python scripts/kernel_tune.py --cpu8 --interpret

    echo "== smoke: perf sentinel gate over the committed trajectory"
    # the noise-aware regression gate (robust median/MAD baselines,
    # direction-aware thresholds, fingerprinted waivers in
    # scripts/perf_baseline.json) judging the newest committed bench
    # row — exit 1 here means a landed change regressed a judged
    # column (ms/step, MFU, peak HBM, wire ratio, goodput_frac, lint
    # counts) without an explicit waiver
    python scripts/perf_sentinel.py --check BENCH_r0*.json \
        --baseline scripts/perf_baseline.json \
        --jsonl "$tmp/sentinel.jsonl"
    python scripts/check_metrics_schema.py --kind roofline \
        "$tmp/sentinel.jsonl"

    echo "smoke ok"
    exit 0
fi

rm -f "$LOG"
timeout -k 10 "$TIMEOUT" env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
# ROADMAP's class plus X: a progress line containing an xpassed test
# must not drop its passing dots from the count
echo "DOTS_PASSED=$(grep -aE '^[.FEsxX]+( *\[ *[0-9]+%\])?$' "$LOG" \
    | tr -cd . | wc -c)"
exit "$rc"
