"""Per-op profile of the BERT-Large LAMB bench step (VERDICT r2 item 3).

Usage: python scripts/prof_bert.py [--batch N] [--seq N] [--top N]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    batch = 16
    seq = 512
    top = 30
    argv = sys.argv
    if "--batch" in argv:
        batch = int(argv[argv.index("--batch") + 1])
    if "--seq" in argv:
        seq = int(argv[argv.index("--seq") + 1])
    if "--top" in argv:
        top = int(argv[argv.index("--top") + 1])

    from apex_tpu import amp, models, prof
    from apex_tpu.optim import FusedLAMB

    policy = amp.Policy.from_opt_level("O1")
    enc = models.BertLarge()
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 30000, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, 30000, (batch, seq)), jnp.int32)
    variables = enc.init(jax.random.PRNGKey(0), toks[:1])
    amp_opt = amp.Amp(policy, FusedLAMB(lr=1e-3))
    state = amp_opt.init(variables["params"])

    def step(state, toks, labels):
        def loss_fn(mp):
            with amp.auto_cast(policy):
                return models.mlm_loss(enc, {"params": mp}, toks, labels)
        loss, grads, state, finite = amp_opt.backward(state, loss_fn)
        return amp_opt.apply_gradients(state, grads, finite), loss

    import tempfile
    import time

    jstep = jax.jit(step, donate_argnums=(0,))
    from apex_tpu.prof import hlo as _hlo
    cost = _hlo.cost_analysis(jstep, state, toks, labels)
    for _ in range(3):
        state, loss = jstep(state, toks, labels)
    float(loss)

    iters = 5
    logdir = tempfile.mkdtemp(prefix="apex_tpu_prof_bert_")
    t0 = time.perf_counter()
    with prof.trace(logdir):
        for _ in range(iters):
            state, loss = jstep(state, toks, labels)
        float(loss)
    wall = (time.perf_counter() - t0) / iters

    from apex_tpu.prof import xplane as _xplane
    profile = _xplane.parse_trace(logdir)
    dev_us = (profile.module_total_us / profile.module_runs
              if profile.module_runs else wall * 1e6)
    n_params = sum(int(np.prod(l.shape)) for l in
                   jax.tree_util.tree_leaves(variables["params"]))
    model_flops = 6.0 * n_params * batch * seq
    print(f"batch={batch} seq={seq} params={n_params/1e6:.1f}M")
    print(f"wall/iter={wall*1e6:.0f}us device/iter={dev_us:.0f}us "
          f"xla_flops={cost['flops']:.3g} "
          f"model_flops={model_flops:.3g} "
          f"bytes={cost['bytes_accessed']:.3g}")
    cats = "  ".join(f"{k}={v:.0f}us"
                     for k, v in list(profile.by_category().items())[:8])
    print(cats)
    print(profile.table(top=top))
    peak = prof.device_peak_flops() or float("inf")
    print("model-flops MFU:", model_flops / (dev_us * 1e-6) / peak)
    print("seq/s:", batch / (dev_us * 1e-6))


if __name__ == "__main__":
    main()
