"""Data-parallel engine tests on the 8-device CPU mesh.

Mirrors the reference's 2-GPU semantics tests
(`tests/distributed/DDP/ddp_race_condition_test.py`: exactly-known grads
checked after sync) and the allreduce-arithmetic flags of
`apex/parallel/distributed.py:425-475`.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.parallel import mesh as mesh_lib


def _shard_eval(mesh, fn, *args, in_specs=P("data"), out_specs=P()):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)(*args)


class TestMesh:
    def test_make_mesh_infer(self, devices):
        m = mesh_lib.make_mesh([("data", -1)])
        assert m.shape["data"] == 8

    def test_make_mesh_2d(self, devices):
        m = mesh_lib.make_mesh([("data", 4), ("model", 2)])
        assert m.shape == {"data": 4, "model": 2}

    def test_make_mesh_bad_size(self, devices):
        with pytest.raises(ValueError):
            mesh_lib.make_mesh([("data", 3), ("model", 2)])

    def test_hierarchical(self, devices):
        m = mesh_lib.hierarchical_data_mesh(local_size=4)
        assert m.shape == {"data_inter": 2, "data_intra": 4}

    def test_local_batch(self, mesh8):
        assert mesh_lib.local_batch(64, mesh8) == 8
        with pytest.raises(ValueError):
            mesh_lib.local_batch(63, mesh8)


class TestSyncGradients:
    """The ddp_race_condition contract: after backward+sync every device
    holds the average of per-device grads, for exactly-known values."""

    def test_known_grad_average(self, mesh8):
        # per-device grad = rank+1  =>  synced = mean = 4.5
        def step(x):
            g = {"w": x * jnp.ones((4, 128))}
            return parallel.sync_gradients(g, "data")["w"]

        x = jnp.arange(1.0, 9.0)
        out = _shard_eval(mesh8, step, x, in_specs=P("data"),
                          out_specs=P())
        np.testing.assert_allclose(out, 4.5 * np.ones((4, 128)), rtol=1e-6)

    def test_predivide_factor(self, mesh8):
        # predivide: sum(g/f)/(world/f) == mean — same result, different
        # intermediate scaling (`distributed.py:442-451`)
        def step(x):
            g = parallel.sync_gradients(
                {"w": x}, "data", gradient_predivide_factor=8.0)
            return g["w"]

        x = jnp.arange(1.0, 9.0)
        out = _shard_eval(mesh8, step, x)
        np.testing.assert_allclose(out, 4.5, rtol=1e-6)

    def test_no_average(self, mesh8):
        def step(x):
            return parallel.sync_gradients(
                {"w": x}, "data", gradient_average=False)["w"]

        out = _shard_eval(mesh8, step, jnp.ones(8))
        np.testing.assert_allclose(out, 8.0)

    def test_fp32_allreduce_of_bf16(self, mesh8):
        # bf16 grads reduced in fp32 keep more precision than bf16 psum
        def step(x):
            g = x.astype(jnp.bfloat16)
            synced = parallel.sync_gradients(
                {"w": g}, "data", allreduce_always_fp32=True)["w"]
            assert synced.dtype == jnp.bfloat16
            return synced.astype(jnp.float32)

        vals = jnp.float32([1.0, 1 + 1/256, 1 - 1/256, 1.0,
                            1.0, 1.0, 1.0, 1.0])
        out = _shard_eval(mesh8, step, vals)
        expect = np.mean([float(jnp.bfloat16(v)) for v in vals])
        np.testing.assert_allclose(float(out[0]), expect, rtol=1e-2)

    def test_int_leaves_untouched(self, mesh8):
        def step(x):
            g = {"w": x, "count": jnp.int32(3)}
            s = parallel.sync_gradients(g, "data")
            return s["count"]

        out = _shard_eval(mesh8, step, jnp.ones(8))
        assert int(out) == 3


class TestReducer:
    def test_manual_reduce(self, mesh8):
        red = parallel.Reducer("data")

        def step(x):
            return red.reduce({"p": x})["p"]

        out = _shard_eval(mesh8, step, jnp.arange(8.0))
        np.testing.assert_allclose(out, 3.5)


class TestDDP:
    def test_wrapped_training_matches_single_device(self, mesh8):
        """A DDP step on 8 shards == single-device step on the full batch
        (the fundamental DDP equivalence the reference race test checks)."""
        ddp = parallel.DistributedDataParallel(mesh8)
        w0 = jnp.ones((128,)) * 0.5
        x = jnp.arange(64.0 * 128).reshape(64, 128) / 1e4
        lr = 0.1

        def loss_fn(w, xb):
            return jnp.mean(jnp.square(xb @ w))

        def step(w, xb):
            loss, g = jax.value_and_grad(loss_fn)(w, xb)
            g = ddp.sync({"w": g})["w"]
            return w - lr * g, jax.lax.pmean(loss, "data")

        stepped = ddp.wrap(step, donate_state=False)
        w_ddp, loss_ddp = stepped(w0, x)

        loss_ref, g_ref = jax.value_and_grad(loss_fn)(w0, x)
        w_ref = w0 - lr * g_ref
        np.testing.assert_allclose(np.asarray(w_ddp), np.asarray(w_ref),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(loss_ddp), float(loss_ref),
                                   rtol=1e-5)

    def test_no_sync_accumulation(self, mesh8):
        ddp = parallel.DistributedDataParallel(mesh8)

        def step(x):
            return ddp.sync({"g": x})["g"]

        with ddp.no_sync():
            out = _shard_eval(mesh8, step, jnp.arange(8.0),
                              out_specs=P("data"))
        np.testing.assert_allclose(out, np.arange(8.0))  # untouched
        out = _shard_eval(mesh8, step, jnp.arange(8.0))
        np.testing.assert_allclose(out, 3.5)

    def test_no_sync_after_compile(self, mesh8):
        """no_sync must affect a step already compiled by ddp.wrap (the flag
        is trace-time state; wrap keeps one program per flag value)."""
        ddp = parallel.DistributedDataParallel(mesh8)

        def step(state, x):
            return state, ddp.sync({"g": x})["g"]

        stepped = ddp.wrap(step, donate_state=False,
                           out_specs=(P(), P("data")))
        s = jnp.float32(0.0)
        _, synced = stepped(s, jnp.arange(8.0))
        np.testing.assert_allclose(np.unique(np.asarray(synced)), [3.5])
        with ddp.no_sync():
            _, raw = stepped(s, jnp.arange(8.0))
        np.testing.assert_allclose(np.asarray(raw), np.arange(8.0))
        _, synced2 = stepped(s, jnp.arange(8.0))
        np.testing.assert_allclose(np.unique(np.asarray(synced2)), [3.5])

    def test_flat_all_reduce(self, mesh8):
        def step(b):
            return parallel.flat_all_reduce(b, "data")

        buf = jnp.ones((8 * 65536,))
        out = _shard_eval(mesh8, step, buf, in_specs=P("data"),
                          out_specs=P())
        np.testing.assert_allclose(out, np.ones(65536))

    def test_replicate(self, mesh8):
        p = parallel.replicate({"w": jnp.arange(4.0)}, mesh8)
        assert p["w"].sharding.is_fully_replicated


class TestDDPKnobs:
    def test_delay_allreduce_matches_overlapped(self, mesh8):
        """delay_allreduce (one flat fused reduce per dtype) must produce
        the same synced grads as the per-tensor path — the semantics of
        the reference's allreduce_fallback (distributed.py:491-510)."""
        ddp_d = parallel.DistributedDataParallel(mesh8,
                                                 delay_allreduce=True)
        ddp_n = parallel.DistributedDataParallel(mesh8)
        tree = {"a": jnp.arange(24.0).reshape(3, 8),
                "b": jnp.ones((5,), jnp.bfloat16),
                "n": jnp.arange(3)}          # int leaf passes through

        def mk(ddp):
            def step(x):
                shard = jax.lax.axis_index("data").astype(jnp.float32)
                g = {"a": tree["a"] * (shard + 1),
                     "b": tree["b"] * (shard + 1).astype(jnp.bfloat16),
                     "n": tree["n"]}
                return ddp.sync(g)
            return step

        out_d = _shard_eval(mesh8, mk(ddp_d), jnp.zeros(8))
        out_n = _shard_eval(mesh8, mk(ddp_n), jnp.zeros(8))
        for k in ("a", "b", "n"):
            np.testing.assert_allclose(
                np.asarray(out_d[k], np.float32),
                np.asarray(out_n[k], np.float32), rtol=1e-6,
                err_msg=k)

    def test_delay_allreduce_single_psum_per_dtype(self, mesh8):
        """The flat path must actually fuse: exactly one psum per dtype
        group, not one per tensor."""
        grads = {"a": jnp.ones((4, 8)), "b": jnp.ones((16,)),
                 "c": jnp.ones((2, 2))}
        jaxpr = jax.make_jaxpr(
            lambda g: jax.shard_map(
                lambda g_: parallel.flat_tree_all_reduce(g_, "data"),
                mesh=mesh8, in_specs=P(), out_specs=P())(g))(grads)
        # count psum primitives
        n_psum = str(jaxpr).count("psum")
        assert n_psum == 1, f"expected 1 fused psum, found {n_psum}"

    def test_message_size_sets_compiler_option(self, mesh8):
        """Non-default message_size must reach XLA as a combine-threshold
        compiler option (accepted by the CPU/GPU compile path) and the
        program must still run correctly."""
        ddp = parallel.DistributedDataParallel(mesh8,
                                               message_size=250_000)
        opts = ddp._compiler_options()
        assert opts == {"xla_gpu_all_reduce_combine_threshold_bytes":
                        "1000000"}

        def step(w, xb):
            g = ddp.sync({"w": xb.sum(0)})["w"]
            return w - g, jax.lax.pmean(xb.sum(), "data")

        stepped = ddp.wrap(step, donate_state=False)
        w, _ = stepped(jnp.zeros(8), jnp.ones((8, 8)))
        # each shard holds 1 row of ones -> per-shard g = ones(8),
        # averaged across 8 shards it stays ones(8)
        np.testing.assert_allclose(np.asarray(w), -1.0)

    def test_default_message_size_no_options(self, mesh8):
        ddp = parallel.DistributedDataParallel(mesh8)
        assert ddp._compiler_options() is None

    def test_message_size_scales_by_grad_dtype(self, mesh8):
        """bf16 reductions halve the byte threshold; allreduce_always_fp32
        overrides back to 4 bytes/element (ADVICE round-2)."""
        ddp = parallel.DistributedDataParallel(
            mesh8, message_size=250_000, grad_dtype=jnp.bfloat16)
        assert ddp._compiler_options() == {
            "xla_gpu_all_reduce_combine_threshold_bytes": "500000"}
        ddp32 = parallel.DistributedDataParallel(
            mesh8, message_size=250_000, grad_dtype=jnp.bfloat16,
            allreduce_always_fp32=True)
        assert ddp32._compiler_options() == {
            "xla_gpu_all_reduce_combine_threshold_bytes": "1000000"}

    def test_combine_threshold_option_reaches_compiler(self):
        """The observable contract for the message_size knob: the
        DebugOptions field is actually parsed by XLA's compile path (an
        unparseable value errors), not silently dropped — so a valid
        threshold demonstrably reaches the executable build. Gated on
        the same probe production uses: backends that reject compiler
        options wholesale (the axon tunnel) skip, mirroring the knob's
        documented best-effort degradation."""
        import pytest

        if not parallel.DistributedDataParallel._probe_compiler_options():
            pytest.skip("backend rejects compiler options entirely")
        jax.jit(lambda x: x + 1, compiler_options={
            "xla_gpu_all_reduce_combine_threshold_bytes": "12345"})(
                jnp.zeros(4))
        with pytest.raises(Exception):
            jax.jit(lambda x: x + 2, compiler_options={
                "xla_gpu_all_reduce_combine_threshold_bytes":
                "not-a-number"})(jnp.zeros(4))

    def test_combine_threshold_observed_or_documented_ignored(self,
                                                              mesh8):
        """Does the threshold change COMBINING BEHAVIOR (VERDICT r3 weak
        4)? Compile the same independent-psum program under a 1-byte and
        a 1-GiB threshold and count all-reduce ops in the optimized HLO.
        Where the backend's combiner honors the flag the counts must
        differ; where it does not (measured: the CPU pipeline combines
        independent psums even at threshold=1), skip with the explicit
        observation — the knob's documented best-effort contract, now
        backed by a measurement instead of silence."""
        import pytest

        if not parallel.DistributedDataParallel._probe_compiler_options():
            pytest.skip("backend rejects compiler options entirely")

        def step(gs):
            return [jax.lax.psum(g, "data") for g in gs]

        gs = [jnp.ones((64, 64)) for _ in range(8)]
        m = jax.shard_map(step, mesh=mesh8, in_specs=(P(),),
                          out_specs=P(), check_vma=False)

        def n_allreduce(thresh):
            opts = {"xla_gpu_all_reduce_combine_threshold_bytes": thresh}
            txt = jax.jit(m, compiler_options=opts).lower(
                gs).compile().as_text()
            return txt.count("all-reduce(") + txt.count(
                "all-reduce-start(")

        lo, hi = n_allreduce("1"), n_allreduce("1073741824")
        if lo == hi:
            pytest.skip(
                f"this backend's combiner ignores the threshold "
                f"(all-reduce count {lo} at both extremes) — the knob "
                f"degrades to XLA's default combining, as documented")
        assert lo > hi, (lo, hi)


class TestLARC:
    def test_rewrite_matches_reference_formula(self):
        """Leaf-wise trust ratio per `apex/parallel/LARC.py:78-105`."""
        p = jnp.float32(np.random.RandomState(0).randn(16, 8))
        g = jnp.float32(np.random.RandomState(1).randn(16, 8)) * 0.01
        lr, trust, wd, eps = 0.1, 0.02, 1e-4, 1e-8

        out = parallel.larc_rewrite_grads(
            {"w": g}, {"w": p}, lr=lr, trust_coefficient=trust,
            weight_decay=wd, eps=eps)["w"]

        pn = np.linalg.norm(np.asarray(p))
        gn = np.linalg.norm(np.asarray(g))
        adaptive = trust * pn / (gn + pn * wd + eps)
        adaptive = min(adaptive / lr, 1.0)
        expect = (np.asarray(g) + wd * np.asarray(p)) * adaptive
        np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5)

    def test_scale_mode(self):
        p = jnp.ones((4,)) * 2.0
        g = jnp.ones((4,)) * 1.0
        out = parallel.larc_rewrite_grads(
            {"w": g}, {"w": p}, lr=None, clip=False,
            trust_coefficient=0.01)["w"]
        # adaptive = 0.01 * |p|/|g| = 0.02
        np.testing.assert_allclose(np.asarray(out), 0.02 * np.ones(4),
                                   rtol=1e-5)

    def test_zero_grad_passthrough(self):
        # zero grad norm leaves the gradient COMPLETELY untouched — no wd
        # fold either (`LARC.py:88` skips the whole rewrite)
        p = jnp.ones((4,))
        g = jnp.zeros((4,))
        out = parallel.larc_rewrite_grads(
            {"w": g}, {"w": p}, lr=0.1, weight_decay=0.01)["w"]
        np.testing.assert_allclose(np.asarray(out), 0.0)

    def test_wrapper_with_fused_sgd(self):
        from apex_tpu.optim import FusedSGD
        larc = parallel.LARC(FusedSGD(lr=0.1), trust_coefficient=0.02)
        params = {"w": jnp.ones((256,))}
        state = larc.init(params)
        g = {"w": jnp.ones((256,)) * 0.5}
        new_p, _ = larc.step(g, state, params)
        assert not np.allclose(np.asarray(new_p["w"]), 1.0)
