"""apex_tpu.prof — profiling/tracing subsystem (the pyprof equivalent).

The reference's pyprof pipeline is three offline stages
(`apex/pyprof/nvtx/nvmarker.py` annotate → nvprof → `parse/` → `prof/`
FLOPs analyzers). TPU-native, the same capability is:

- :mod:`~apex_tpu.prof.annotate` — ``scope``/``annotate`` named-scope
  helpers + ``annotate_modules`` flax interceptor (arg shapes/dtypes per
  module call, reversible, no monkey-patching);
- :mod:`~apex_tpu.prof.xplane` — parse ``jax.profiler`` xplane.pb traces
  into per-HLO-op timing records;
- :mod:`~apex_tpu.prof.hlo` — XLA cost analysis + per-instruction
  FLOPs/bytes estimates from optimized HLO;
- :mod:`~apex_tpu.prof.report` — ``profile_step`` one-stop capture →
  parse → MFU report;
- :mod:`~apex_tpu.prof.memory` — HBM footprint reports: per-buffer
  attribution (params / optimizer state / activations / comm) from the
  optimized HLO + ``memory_analysis()``, peak-live estimate, what-if
  batch scaler vs HBM capacity (docs/memory.md);
- :mod:`~apex_tpu.prof.compile_watch` — trace/lower/compile counters +
  retrace detector naming the argument whose shape changed (autotune-
  origin compiles tagged separately via ``autotune_scope``);
- :mod:`~apex_tpu.prof.roofline` — per-op efficiency attribution:
  measured device time joined with analytic FLOPs/bytes against the
  chip's peak table, compute/memory bound classes, per-family
  aggregation, and the fingerprinted ``worst_gaps`` autotuner feed
  (docs/profiling.md#roofline);
- :mod:`~apex_tpu.prof.sentinel` — noise-aware perf-regression gate
  over bench JSON trajectories (robust median/MAD, direction-aware,
  fingerprinted waivers; ``scripts/perf_sentinel.py``);
- :mod:`~apex_tpu.prof.sharding` — per-mesh-axis HBM attribution from
  the compiled module's HloSharding annotations: sharded-by vs
  replicated-over per axis, closure over ``memory_report``'s class
  totals, what-if ``forecast_axes`` shrink pricing
  (``scripts/mesh_explain.py``; docs/memory.md#shard-report).
"""

from apex_tpu.prof.annotate import (CallRecord, annotate, annotate_modules,
                                    scope)
from apex_tpu.prof.compile_watch import (CompileWatcher, FunctionWatch,
                                         autotune_scope, global_counters)
from apex_tpu.prof.hlo import (OpEstimate, compiled_hlo, cost_analysis,
                               op_estimates, op_estimates_from_text)
from apex_tpu.prof.memory import (BufferRecord, MemoryReport,
                                  device_memory_sample, hbm_capacity,
                                  memory_report)
from apex_tpu.prof.report import (PEAK_FLOPS, PEAK_HBM_BW, StepReport,
                                  device_peak_flops, device_peak_hbm_bw,
                                  profile_step, trace)
from apex_tpu.prof.roofline import (RooflineReport, RooflineRow,
                                    roofline_report)
from apex_tpu.prof.sharding import (ShardRecord, ShardReport,
                                    shard_report)
from apex_tpu.prof.xplane import OpRecord, TraceProfile, parse_trace

__all__ = [
    "CallRecord", "annotate", "annotate_modules", "scope",
    "OpEstimate", "compiled_hlo", "cost_analysis", "op_estimates",
    "op_estimates_from_text",
    "PEAK_FLOPS", "PEAK_HBM_BW", "StepReport", "device_peak_flops",
    "device_peak_hbm_bw", "profile_step", "trace",
    "OpRecord", "TraceProfile", "parse_trace",
    "MemoryReport", "BufferRecord", "memory_report", "hbm_capacity",
    "device_memory_sample",
    "CompileWatcher", "FunctionWatch", "autotune_scope",
    "global_counters",
    "RooflineReport", "RooflineRow", "roofline_report",
    "ShardRecord", "ShardReport", "shard_report",
]
