"""The precision half of apexlint: a dtype-provenance dataflow pass.

Abstract interpretation over ``jax.make_jaxpr`` output (sharing the
single trace :func:`apex_tpu.lint.lint_step` already makes for the
jaxpr pass and APX204) that propagates, per jaxpr var, an abstract
value::

    (dtype, scale-provenance, rounding-depth)

- **dtype** is the var's float format on the numerics ladder
  (``fp8_e4m3 < fp8_e5m2 < fp16 < bf16 < fp32 < fp64``; None for
  non-floats, which also drops every provenance bit — a finiteness
  predicate is not a scaled value).
- **scale-provenance** tracks two independent facts: whether the value
  is *dominated by a scale multiply* (``x * s`` with a scalar ``s`` —
  the site-scaled shape O4's scaled casts and ``ScaleHistory`` emit),
  and which *loss-scale tokens* taint it. A token is minted at the
  ``scale_loss`` signature — a scalar carried input multiplying a
  computed scalar (the loss) — and is cancelled by a multiply with
  that token's reciprocal (``g * (1/s)``, the ``unscale_grads``
  shape) or a divide by the token's source. Taint joins as union, so
  a select between a scaled and an unscaled path stays tainted: the
  unscale must happen on **every** path.
- **rounding-depth** counts chained narrowing casts (reset by
  arithmetic — a sum of rounded values is a new quantity; preserved
  through transposes/reshapes/converts).

Rules (docs/linting.md#apx3xx):

- **APX301** unscaled-narrow-cast: a ``convert_element_type`` to fp8
  whose operand is neither site-scaled nor loss-scale-tainted (to
  fp16: only when no loss-scaling policy protects the program —
  warning). bf16 keeps the f32 exponent range and is exempt.
- **APX302** double-rounding: a narrowing cast whose operand was
  already narrowed and whose target is narrower than every format the
  value passed through (f32→bf16→fp8); bf16→f32→bf16 round-trips are
  exempt (nothing new is destroyed).
- **APX303** scale-leak: loss-scale taint reaching a non-scalar
  program output (the committed params / optimizer state). Scalar
  outputs are exempt — the scaler's own state update legitimately
  derives from the scale.
- **APX304** master-weight violation: an add/sub in the half dtype
  whose operand chain reaches a same-shaped half-dtype carried input
  and whose result is committed, under a policy that promises f32
  masters (``master_weights=True`` → error; O3-style pure-half
  policies → info, the by-design advisory).
- **APX305** half-accumulation: a dot/conv with fp16/fp8 operands and
  no widened accumulator (``preferred_element_type``), or a
  sum/psum/cumsum reducing half operands directly (bf16 reductions
  are info — the MXU widens bf16 *dots* in hardware, but a plain
  ``reduce_sum`` does accumulate in bf16).
- **APX306** wire-dtype-unsafe (:func:`wire_dtype_findings`): the
  static × measured join — a reduction collective's wire dtype,
  attributed to a subsystem via :mod:`apex_tpu.parallel.registry`,
  narrower than the matching per-site ``precision_report`` verdicts
  in a committed fixture. Non-float wires are exempt (the int8
  hierarchical sync carries error feedback by design).

:func:`precision_preflight` joins the two worlds the other way round:
the fixture's measured fp8-safe sites filtered by the program's static
verdict — the ranked "statically castable ∩ measured-safe" list that
is the fp8/O4 pre-flight (``mesh_explain``-style table via
``PreflightResult.table()``).

Everything here is strictly AOT — a pure walk over an already-made
jaxpr (and, for APX306, already-compiled HLO text); the
``lint/precision-no-extra-dispatch`` compile-check case pins that the
pass leaves compiled programs bit-identical.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.lint.findings import Finding
from apex_tpu.lint.jaxpr_pass import (_closed_to_jaxpr, _is_literal,
                                      _np_dtype, _sub_jaxprs,
                                      MATMUL_PRIMS)

__all__ = ["precision_findings", "analyze_jaxpr", "PrecisionAnalysis",
           "wire_dtype_findings", "precision_preflight",
           "PreflightResult", "LADDER", "MANTISSA_BITS"]

#: narrow → wide; numerics.FORMAT_LADDER plus fp64
LADDER: Tuple[str, ...] = ("fp8_e4m3", "fp8_e5m2", "fp16", "bf16",
                           "fp32", "fp64")
_RANK = {name: i for i, name in enumerate(LADDER)}
MANTISSA_BITS = {"fp8_e4m3": 3, "fp8_e5m2": 2, "fp16": 10, "bf16": 7,
                 "fp32": 23, "fp64": 52}
_FP8 = ("fp8_e4m3", "fp8_e5m2")

#: numpy dtype name → ladder name (fp8 dtypes via jax.numpy; their
#: numpy names are ml_dtypes')
_NP_TO_LADDER = {"float8_e4m3fn": "fp8_e4m3", "float8_e4m3": "fp8_e4m3",
                 "float8_e5m2": "fp8_e5m2", "float16": "fp16",
                 "bfloat16": "bf16", "float32": "fp32",
                 "float64": "fp64"}
#: HLO dtype token → ladder name (the SPMD schedule's wire dtypes)
_HLO_TO_LADDER = {"f8e4m3fn": "fp8_e4m3", "f8e4m3": "fp8_e4m3",
                  "f8e5m2": "fp8_e5m2", "f16": "fp16", "bf16": "bf16",
                  "f32": "fp32", "f64": "fp64"}

#: ops that carry a value through unchanged (modulo layout): every
#: provenance bit survives them
_PRESERVING = frozenset({
    "convert_element_type", "reshape", "transpose", "broadcast_in_dim",
    "squeeze", "slice", "dynamic_slice", "rev", "copy", "neg",
    "stop_gradient", "expand_dims", "reduce_precision", "optimization_barrier",
})
#: reductions that ACCUMULATE (max/min/and/or don't lose mantissa)
_SUM_REDUCTIONS = frozenset({"reduce_sum", "cumsum", "reduce_window_sum",
                             "psum", "add_any"})
_REDUCTION_COLLECTIVES = ("all-reduce", "reduce-scatter")


def _fmt_of_aval(aval) -> Optional[str]:
    dt = _np_dtype(getattr(aval, "dtype", None))
    if dt is None:
        return None
    return _NP_TO_LADDER.get(dt.name)


def _is_scalar(aval) -> bool:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return False
    return int(np.prod(shape, dtype=np.int64)) == 1 if shape else True


def hlo_dtype_format(tok: str) -> Optional[str]:
    """Ladder name of an HLO dtype token, None for non-floats."""
    return _HLO_TO_LADDER.get(str(tok).lower())


@dataclasses.dataclass
class _AbsVal:
    """Per-var abstract value: dtype + scale provenance + rounding."""

    fmt: Optional[str] = None       # ladder name, None = non-float
    taint: frozenset = frozenset()  # live loss-scale tokens
    inv_of: frozenset = frozenset()  # tokens this value is 1/s of
    scale_src: Optional[int] = None  # token if this IS a scale scalar
    site_scaled: bool = False       # dominated by a scale multiply
    depth: int = 0                  # chained-narrowing-cast count
    min_mant: int = 52              # narrowest mantissa passed through
    carry_shape: Optional[Tuple[int, ...]] = None  # half carried input
    upd_candidate: bool = False     # half update add on a half carry

    def drop_if_nonfloat(self) -> "_AbsVal":
        if self.fmt is None:
            return _AbsVal(fmt=None)
        return self


def _join(a: _AbsVal, b: _AbsVal) -> _AbsVal:
    """Path join (cond branches / scan fixpoint): taint is a union —
    an unscale must happen on every path."""
    return _AbsVal(
        fmt=a.fmt if a.fmt == b.fmt else (a.fmt or b.fmt),
        taint=a.taint | b.taint,
        inv_of=a.inv_of & b.inv_of,
        scale_src=a.scale_src if a.scale_src == b.scale_src else None,
        site_scaled=a.site_scaled and b.site_scaled,
        depth=max(a.depth, b.depth),
        min_mant=min(a.min_mant, b.min_mant),
        carry_shape=(a.carry_shape
                     if a.carry_shape == b.carry_shape else None),
        upd_candidate=a.upd_candidate or b.upd_candidate)


def _same(a: _AbsVal, b: _AbsVal) -> bool:
    return (a.taint == b.taint and a.site_scaled == b.site_scaled
            and a.depth == b.depth and a.min_mant == b.min_mant
            and a.upd_candidate == b.upd_candidate)


@dataclasses.dataclass
class PrecisionAnalysis:
    """Result of one precision-pass run over a jaxpr."""

    findings: List[Finding]
    n_cast_sites: int = 0        # float→float convert_element_type eqns
    n_matmul_sites: int = 0      # dot_general / conv eqns
    n_reduction_sites: int = 0   # accumulating reductions / psums

    @property
    def n_sites(self) -> int:
        return (self.n_cast_sites + self.n_matmul_sites
                + self.n_reduction_sites)


class _Interp:
    """The abstract interpreter. One instance per analyze_jaxpr call."""

    def __init__(self, policy=None):
        self.policy = policy
        self.findings: List[Finding] = []
        self._seen = set()          # (rule, id(eqn), path) dedup
        self._next_token = 0
        self._active = set()        # tokens minted at a scale_loss mul
        self.n_cast_sites = 0
        self.n_matmul_sites = 0
        self.n_reduction_sites = 0
        # policy facts the rules key on
        self.uses_loss_scaling = bool(
            getattr(policy, "uses_loss_scaling", False))
        enabled = bool(getattr(policy, "enabled", False))
        self.master_weights = enabled and bool(
            getattr(policy, "master_weights", False))
        self.pure_half = (enabled and not self.master_weights
                          and getattr(policy, "cast_model_type", None)
                          is not None)
        self.apx304_active = self.master_weights or self.pure_half

    # -- finding emission -----------------------------------------------------

    def _emit(self, eqn, path, emit: bool, **kw) -> None:
        if not emit:
            return
        key = (kw.get("rule"), id(eqn), path)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(Finding(**kw))

    # -- abstract eval --------------------------------------------------------

    def run(self, jaxpr, emit: bool = True) -> None:
        jaxpr = _closed_to_jaxpr(jaxpr)
        env: Dict = {}
        for v in jaxpr.invars + jaxpr.constvars:
            val = _AbsVal(fmt=_fmt_of_aval(getattr(v, "aval", None)))
            if val.fmt is not None:
                if _is_scalar(v.aval):
                    # a scalar carried input is a scale *candidate*:
                    # a token is minted only if it multiplies the loss
                    val.scale_src = self._next_token
                    self._next_token += 1
                elif (self.apx304_active and val.fmt in
                      ("fp16", "bf16") + _FP8):
                    # half-dtype carried input: the APX304 source
                    val.carry_shape = tuple(
                        getattr(v.aval, "shape", ()) or ())
            env[v] = val
        self._eval_jaxpr(jaxpr, env, (), emit)
        # -- APX303 / APX304: what reaches the committed outputs
        for i, v in enumerate(jaxpr.outvars):
            if _is_literal(v) or v not in env:
                continue
            val = env[v]
            if val.fmt is None:
                continue
            scalar = _is_scalar(getattr(v, "aval", None))
            if val.taint and not scalar:
                self._emit(
                    v, ("outputs",), emit, rule="scale-leak",
                    message=(f"loss-scaled taint reaches committed "
                             f"output #{i} ({val.fmt}) — no unscale "
                             "on at least one path"),
                    op="output", scope=f"outputs[{i}]",
                    dtype_from=val.fmt if val.fmt in LADDER else None,
                    scale_provenance="loss-scaled")
            if val.upd_candidate and not scalar:
                sev = "error" if self.master_weights else "info"
                self._emit(
                    v, ("outputs", "apx304"), emit,
                    rule="master-weight-violation", severity=sev,
                    message=(f"committed output #{i} is a {val.fmt} "
                             "update of a same-shaped half carried "
                             "input — no f32 master in the chain"
                             + ("" if self.master_weights else
                                " (pure-half policy: by design)")),
                    op="output", scope=f"outputs[{i}]",
                    dtype_from=val.fmt if val.fmt in LADDER else None,
                    dtype_to="fp32",
                    scale_provenance=None)

    def _read(self, env, v) -> _AbsVal:
        if _is_literal(v):
            return _AbsVal(fmt=_fmt_of_aval(getattr(v, "aval", None)))
        return env.get(v) or _AbsVal(
            fmt=_fmt_of_aval(getattr(v, "aval", None)))

    def _eval_jaxpr(self, jaxpr, env: Dict, path: Tuple[str, ...],
                    emit: bool) -> None:
        jaxpr = _closed_to_jaxpr(jaxpr)
        for eqn in jaxpr.eqns:
            self._eval_eqn(eqn, env, path, emit)

    # -- sub-jaxpr plumbing ---------------------------------------------------

    def _call_sub(self, sub, in_vals: Sequence[_AbsVal],
                  path: Tuple[str, ...], emit: bool) -> List[_AbsVal]:
        """Evaluate a sub-jaxpr with the caller's abstract values bound
        to its invars; returns the body's outvar values."""
        closed_consts = getattr(sub, "consts", None)
        sub = _closed_to_jaxpr(sub)
        env: Dict = {}
        for cv in sub.constvars:
            env[cv] = _AbsVal(fmt=_fmt_of_aval(getattr(cv, "aval",
                                                       None)))
        n = min(len(sub.invars), len(in_vals))
        for v, val in zip(sub.invars[:n], in_vals[:n]):
            env[v] = val
        for v in sub.invars[n:]:
            env[v] = _AbsVal(fmt=_fmt_of_aval(getattr(v, "aval", None)))
        self._eval_jaxpr(sub, env, path, emit)
        return [self._read(env, v) for v in sub.outvars]

    def _sub_path(self, eqn, path):
        name = eqn.params.get("name")
        return path + ((str(name),) if name else (eqn.primitive.name,))

    # -- the transfer function ------------------------------------------------

    def _eval_eqn(self, eqn, env: Dict, path: Tuple[str, ...],
                  emit: bool) -> None:
        prim = eqn.primitive.name
        ins = [self._read(env, v) for v in eqn.invars]

        # ---- structured control flow / calls
        if prim == "scan":
            self._eval_scan(eqn, env, ins, path, emit)
            return
        if prim == "while":
            self._eval_while(eqn, env, ins, path, emit)
            return
        if prim == "cond":
            self._eval_cond(eqn, env, ins, path, emit)
            return
        subs = list(_sub_jaxprs(eqn))
        if subs:
            sub_path = self._sub_path(eqn, path)
            if len(subs) == 1 and len(_closed_to_jaxpr(
                    subs[0]).invars) == len(eqn.invars):
                outs = self._call_sub(subs[0], ins, sub_path, emit)
                for v, val in zip(eqn.outvars,
                                  outs + [None] * len(eqn.outvars)):
                    env[v] = (val or _AbsVal(fmt=_fmt_of_aval(
                        getattr(v, "aval", None)))).drop_if_nonfloat()
                return
            # opaque multi-jaxpr call (custom_vjp with bwd, pallas):
            # walk every body for rule hits; outputs get the
            # conservative union taint so leaks flow through
            for sub in subs:
                body = _closed_to_jaxpr(sub)
                benv: Dict = {}
                bn = min(len(body.invars), len(ins))
                for v, val in zip(body.invars[:bn], ins[:bn]):
                    benv[v] = val
                for v in list(body.invars[bn:]) + list(body.constvars):
                    benv[v] = _AbsVal(fmt=_fmt_of_aval(
                        getattr(v, "aval", None)))
                self._eval_jaxpr(body, benv, sub_path, emit)
            taint = frozenset().union(*(i.taint for i in ins)) \
                if ins else frozenset()
            for v in eqn.outvars:
                env[v] = _AbsVal(
                    fmt=_fmt_of_aval(getattr(v, "aval", None)),
                    taint=taint).drop_if_nonfloat()
            return

        # ---- leaf primitives
        out = self._leaf(eqn, prim, ins, path, emit)
        for v in eqn.outvars:
            o = dataclasses.replace(
                out, fmt=_fmt_of_aval(getattr(v, "aval", None)))
            env[v] = o.drop_if_nonfloat()

    def _eval_scan(self, eqn, env, ins, path, emit):
        sub = eqn.params.get("jaxpr")
        if sub is None:
            self._default_out(eqn, env, ins)
            return
        n_consts = int(eqn.params.get("num_consts", 0))
        n_carry = int(eqn.params.get("num_carry", 0))
        sub_path = self._sub_path(eqn, path)
        carry = list(ins[n_consts:n_consts + n_carry])
        # fixpoint over the carry: taint grows monotonically
        for it in range(4):
            outs = self._call_sub(
                sub, list(ins[:n_consts]) + carry
                + list(ins[n_consts + n_carry:]), sub_path, emit=False)
            new_carry = [_join(c, o) for c, o in
                         zip(carry, outs[:n_carry])]
            if all(_same(c, n) for c, n in zip(carry, new_carry)):
                break
            carry = new_carry
        outs = self._call_sub(
            sub, list(ins[:n_consts]) + carry
            + list(ins[n_consts + n_carry:]), sub_path, emit)
        for v, val in zip(eqn.outvars,
                          outs + [None] * len(eqn.outvars)):
            env[v] = (val or _AbsVal(fmt=_fmt_of_aval(
                getattr(v, "aval", None)))).drop_if_nonfloat()

    def _eval_while(self, eqn, env, ins, path, emit):
        cond = eqn.params.get("cond_jaxpr")
        body = eqn.params.get("body_jaxpr")
        cn = int(eqn.params.get("cond_nconsts", 0))
        bn = int(eqn.params.get("body_nconsts", 0))
        sub_path = self._sub_path(eqn, path)
        carry = list(ins[cn + bn:])
        body_consts = list(ins[cn:cn + bn])
        for it in range(4):
            if body is None:
                break
            outs = self._call_sub(body, body_consts + carry, sub_path,
                                  emit=False)
            new_carry = [_join(c, o) for c, o in zip(carry, outs)]
            if all(_same(c, n) for c, n in zip(carry, new_carry)):
                break
            carry = new_carry
        if cond is not None:
            self._call_sub(cond, list(ins[:cn]) + carry, sub_path,
                           emit)
        if body is not None:
            outs = self._call_sub(body, body_consts + carry, sub_path,
                                  emit)
            carry = [_join(c, o) for c, o in zip(carry, outs)]
        for v, val in zip(eqn.outvars,
                          carry + [None] * len(eqn.outvars)):
            env[v] = (val or _AbsVal(fmt=_fmt_of_aval(
                getattr(v, "aval", None)))).drop_if_nonfloat()

    def _eval_cond(self, eqn, env, ins, path, emit):
        branches = eqn.params.get("branches") or ()
        sub_path = self._sub_path(eqn, path)
        operands = ins[1:]
        joined: Optional[List[_AbsVal]] = None
        for br in branches:
            outs = self._call_sub(br, operands, sub_path, emit)
            joined = outs if joined is None else [
                _join(a, b) for a, b in zip(joined, outs)]
        joined = joined or []
        for v, val in zip(eqn.outvars,
                          joined + [None] * len(eqn.outvars)):
            env[v] = (val or _AbsVal(fmt=_fmt_of_aval(
                getattr(v, "aval", None)))).drop_if_nonfloat()

    def _default_out(self, eqn, env, ins):
        taint = frozenset().union(*(i.taint for i in ins)) \
            if ins else frozenset()
        for v in eqn.outvars:
            env[v] = _AbsVal(
                fmt=_fmt_of_aval(getattr(v, "aval", None)),
                taint=taint).drop_if_nonfloat()

    # -- leaf transfer --------------------------------------------------------

    def _leaf(self, eqn, prim, ins: List[_AbsVal], path, emit) -> _AbsVal:
        union_taint = frozenset().union(*(i.taint for i in ins)) \
            if ins else frozenset()

        if prim == "convert_element_type":
            return self._convert(eqn, ins[0] if ins else _AbsVal(),
                                 path, emit)

        if prim in _PRESERVING:
            src = ins[0] if ins else _AbsVal()
            out = dataclasses.replace(src)
            # shape-changing preservers drop the carried-input shape
            # (scale_src survives a broadcast: a broadcast scalar
            # scale is still the scale, pointwise)
            out_aval = getattr(eqn.outvars[0], "aval", None) \
                if eqn.outvars else None
            if (src.carry_shape is not None and out_aval is not None
                    and tuple(getattr(out_aval, "shape", ()) or ())
                    != src.carry_shape):
                out.carry_shape = None
            return out

        if prim in ("mul", "div"):
            return self._mul_div(eqn, prim, ins, path, emit)

        if prim in ("add", "sub"):
            return self._add_sub(eqn, ins, path, emit)

        if prim == "select_n":
            # predicate (operand 0) is control, not a scaled value
            cases = ins[1:] or [_AbsVal()]
            out = cases[0]
            for c in cases[1:]:
                out = _join(out, c)
            return out

        if prim == "clamp":
            # clamp(lo, x, hi): a clamped scaled value is still scaled
            # (the scaled-cast recipe saturates before narrowing)
            mid = ins[1] if len(ins) >= 2 else _AbsVal()
            return dataclasses.replace(mid, scale_src=None)

        if prim in ("max", "min"):
            arr = [i for i in ins if i.fmt is not None]
            if len(ins) == 2 and any(
                    _is_literal(v) or _is_scalar(getattr(v, "aval",
                                                         None))
                    for v in eqn.invars):
                keep = ins[0] if (_is_literal(eqn.invars[1]) or
                                  _is_scalar(getattr(eqn.invars[1],
                                                     "aval", None))) \
                    else ins[1]
                return dataclasses.replace(keep, scale_src=None)
            out = arr[0] if arr else _AbsVal()
            for c in arr[1:]:
                out = _join(out, c)
            return dataclasses.replace(out, site_scaled=False,
                                       scale_src=None)

        if prim in MATMUL_PRIMS:
            self.n_matmul_sites += 1
            self._check_matmul(eqn, ins, path, emit)
            return _AbsVal(taint=union_taint)

        if prim in _SUM_REDUCTIONS:
            self.n_reduction_sites += 1
            self._check_reduction(eqn, prim, ins, path, emit)
            return _AbsVal(taint=union_taint)

        # generic: taint flows through, domination/rounding reset
        return _AbsVal(taint=union_taint)

    def _convert(self, eqn, src: _AbsVal, path, emit) -> _AbsVal:
        dst_fmt = _fmt_of_aval(getattr(eqn.outvars[0], "aval", None)) \
            if eqn.outvars else None
        out = dataclasses.replace(src)
        if src.fmt is None or dst_fmt is None:
            # int→float / float→int: fresh value
            return _AbsVal(fmt=dst_fmt)
        self.n_cast_sites += 1
        src_m = MANTISSA_BITS.get(src.fmt, 52)
        dst_m = MANTISSA_BITS.get(dst_fmt, 52)
        scope = "/".join(path) or None
        if dst_m < src_m:                      # narrowing
            provenance = ("loss-scaled" if src.taint else
                          "site-scaled" if src.site_scaled else
                          "unscaled-after-narrow" if src.depth else
                          "unscaled")
            if src.depth >= 1 and dst_m < src.min_mant:
                self._emit(
                    eqn, path, emit, rule="double-rounding",
                    message=(f"{src.fmt}→{dst_fmt} narrows a value "
                             f"already rounded {src.depth}x (narrowest "
                             f"format seen: {src.min_mant}-bit "
                             "mantissa) — cast once from the wide "
                             "source instead"),
                    op="convert_element_type", scope=scope,
                    dtype_from=src.fmt, dtype_to=dst_fmt,
                    scale_provenance=provenance)
            # fp8 needs a *per-site* scale — a global loss scale is
            # not enough (its magnitude is tuned for fp16 grad
            # exponents, not this site's distribution)
            if dst_fmt in _FP8 and not src.site_scaled:
                self._emit(
                    eqn, path, emit, rule="unscaled-narrow-cast",
                    message=(f"{src.fmt}→{dst_fmt} cast with no "
                             "dominating per-site scale multiply"
                             + (" (loss scale alone does not place "
                                "this site's exponents)"
                                if src.taint else "")
                             + " — the cast O4 must never emit"),
                    op="convert_element_type", scope=scope,
                    dtype_from=src.fmt, dtype_to=dst_fmt,
                    scale_provenance=provenance)
            elif (dst_fmt == "fp16"
                  and not (src.site_scaled or src.taint)
                  and not self.uses_loss_scaling):
                self._emit(
                    eqn, path, emit, rule="unscaled-narrow-cast",
                    severity="warning",
                    message=(f"{src.fmt}→fp16 cast with no scale "
                             "multiply and no loss-scaling policy — "
                             "fp16's 5-bit exponent underflows "
                             "unprotected gradients"),
                    op="convert_element_type", scope=scope,
                    dtype_from=src.fmt, dtype_to="fp16",
                    scale_provenance=provenance)
            out.depth = src.depth + 1
            out.min_mant = min(src.min_mant, dst_m)
        out.fmt = dst_fmt
        if out.carry_shape is not None and eqn.outvars:
            # a widened copy of a half carried input is no longer the
            # half carry (an f32 master path exists from here on)
            if dst_m > src_m:
                out.carry_shape = None
        return out

    def _mul_div(self, eqn, prim, ins: List[_AbsVal], path,
                 emit) -> _AbsVal:
        if len(ins) != 2:
            return _AbsVal(taint=frozenset().union(
                *(i.taint for i in ins)) if ins else frozenset())
        a, b = ins
        av, bv = eqn.invars
        a_lit = _is_literal(av)
        b_lit = _is_literal(bv)
        a_scalar = a_lit or _is_scalar(getattr(av, "aval", None))
        b_scalar = b_lit or _is_scalar(getattr(bv, "aval", None))
        taint = a.taint | b.taint
        inv_of: frozenset = frozenset()
        scale_src: Optional[int] = None
        site_scaled = False
        if prim == "mul":
            for x, y, y_lit, y_scalar in ((a, b, b_lit, b_scalar),
                                          (b, a, a_lit, a_scalar)):
                if x.scale_src is None:
                    continue
                if (not y_lit and y_scalar and y.scale_src is None
                        and y.fmt is not None):
                    # token minting: the scale_loss signature — the
                    # scale (a scalar carried input) times a
                    # *computed* scalar (the loss). From here on the
                    # token is live: scalars derived from the scale
                    # taint everything they multiply (the autodiff
                    # backward multiplies cotangents by the scale).
                    self._active.add(x.scale_src)
                    taint = taint | {x.scale_src}
                elif x.scale_src in self._active:
                    taint = taint | {x.scale_src}
                else:
                    # scaled copy of an un-activated scale candidate
                    # (mul 1.0 c in a grad jaxpr) is still the scale
                    scale_src = x.scale_src
            # cancellation: multiply by the reciprocal of a live token
            if a.inv_of & b.taint:
                taint = taint - a.inv_of
            if b.inv_of & a.taint:
                taint = taint - b.inv_of
            site_scaled = a_scalar or b_scalar
        else:                                   # div
            # numerator / scale-source: the unscale-by-division shape
            if b.scale_src is not None and b.scale_src in a.taint:
                taint = frozenset(t for t in taint
                                  if t != b.scale_src)
            # literal / scale-source: a reciprocal of the scale
            if (b.scale_src is not None and a_scalar and not a.taint
                    and a.scale_src is None):
                inv_of = frozenset({b.scale_src})
            # scale / literal is still scale-derived
            if a.scale_src is not None and b_lit:
                scale_src = a.scale_src
                if a.scale_src in self._active:
                    taint = taint | {a.scale_src}
            site_scaled = b_scalar
        out = _AbsVal(taint=taint, inv_of=inv_of, scale_src=scale_src,
                      site_scaled=site_scaled)
        # a scaled copy of a value keeps its rounding history
        arr = a if not a_scalar else b
        out.depth = arr.depth
        out.min_mant = arr.min_mant
        return out

    def _add_sub(self, eqn, ins: List[_AbsVal], path, emit) -> _AbsVal:
        taint = frozenset().union(*(i.taint for i in ins)) \
            if ins else frozenset()
        out = _AbsVal(taint=taint)
        if not self.apx304_active or len(ins) != 2:
            return out
        a, b = ins
        out_aval = getattr(eqn.outvars[0], "aval", None) \
            if eqn.outvars else None
        if out_aval is None or _is_scalar(out_aval):
            return out
        shape = tuple(getattr(out_aval, "shape", ()) or ())
        halfs = {"fp16", "bf16"} | set(_FP8)
        if (a.fmt in halfs and b.fmt in halfs
                and (a.carry_shape == shape or b.carry_shape == shape)):
            out.upd_candidate = True
            out.carry_shape = shape    # chains of half update arith
        out.upd_candidate = out.upd_candidate or a.upd_candidate \
            or b.upd_candidate
        return out

    def _check_matmul(self, eqn, ins: List[_AbsVal], path, emit):
        in_fmts = [_fmt_of_aval(getattr(v, "aval", None))
                   for v in eqn.invars]
        in_fmts = [f for f in in_fmts if f is not None]
        out_fmt = _fmt_of_aval(getattr(eqn.outvars[0], "aval", None)) \
            if eqn.outvars else None
        if not in_fmts or out_fmt is None:
            return
        narrow = set(in_fmts) <= {"fp16"} | set(_FP8)
        widened = _RANK.get(out_fmt, 9) > max(
            _RANK.get(f, 0) for f in in_fmts)
        if narrow and not widened:
            self._emit(
                eqn, path, emit, rule="half-accumulation",
                message=(f"{eqn.primitive.name} with "
                         f"{'/'.join(sorted(set(in_fmts)))} operands "
                         f"accumulates in {out_fmt} — pass "
                         "preferred_element_type=jnp.float32"),
                op=eqn.primitive.name, scope="/".join(path) or None,
                dtype_from=sorted(in_fmts, key=lambda f:
                                  _RANK.get(f, 9))[0],
                dtype_to=out_fmt)

    def _check_reduction(self, eqn, prim, ins: List[_AbsVal], path,
                         emit):
        in_fmts = [_fmt_of_aval(getattr(v, "aval", None))
                   for v in eqn.invars]
        in_fmts = [f for f in in_fmts if f is not None]
        if not in_fmts:
            return
        narrowest = sorted(in_fmts, key=lambda f: _RANK.get(f, 9))[0]
        if narrowest in ("fp16",) + _FP8:
            sev = "warning"
        elif narrowest == "bf16":
            sev = "info"        # bf16 sums do accumulate in bf16 —
            # advisory (bf16 *dots* widen in MXU hardware and are not
            # flagged)
        else:
            return
        out_fmt = _fmt_of_aval(getattr(eqn.outvars[0], "aval", None)) \
            if eqn.outvars else None
        if out_fmt is not None and _RANK.get(out_fmt, 0) > \
                _RANK.get(narrowest, 0):
            return              # widened accumulator
        self._emit(
            eqn, path, emit, rule="half-accumulation", severity=sev,
            message=(f"{prim} reduces {narrowest} operands directly — "
                     "the accumulator keeps the narrow mantissa"),
            op=prim, scope="/".join(path) or None,
            dtype_from=narrowest, dtype_to=out_fmt or narrowest)


# -- entry points -------------------------------------------------------------

def analyze_jaxpr(fn_or_jaxpr, *args, policy=None,
                  **kwargs) -> PrecisionAnalysis:
    """Run the precision dataflow pass; returns findings + site
    counts. ``fn_or_jaxpr`` is a callable (traced here) or an
    already-made (Closed)Jaxpr — :func:`apex_tpu.lint.lint_step`
    passes its single shared trace."""
    if hasattr(fn_or_jaxpr, "eqns") or hasattr(fn_or_jaxpr, "jaxpr"):
        jaxpr = fn_or_jaxpr
    else:
        import jax
        jaxpr = jax.make_jaxpr(fn_or_jaxpr)(*args, **kwargs)
    interp = _Interp(policy=policy)
    interp.run(jaxpr)
    return PrecisionAnalysis(
        findings=_fold(interp.findings),
        n_cast_sites=interp.n_cast_sites,
        n_matmul_sites=interp.n_matmul_sites,
        n_reduction_sites=interp.n_reduction_sites)


def precision_findings(fn_or_jaxpr, *args, policy=None,
                       **kwargs) -> List[Finding]:
    """The findings-only view of :func:`analyze_jaxpr`."""
    return analyze_jaxpr(fn_or_jaxpr, *args, policy=policy,
                         **kwargs).findings


def _fold(findings: List[Finding]) -> List[Finding]:
    """Fold same-fingerprint findings into one with a count (the
    fingerprint excludes dtype evidence, so the fold keeps the first
    occurrence's pair — the baseline workflow stays one line per
    site)."""
    by_fp: Dict[str, Finding] = {}
    for f in findings:
        fp = f.fingerprint() + f"|{f.severity}"
        if fp in by_fp:
            by_fp[fp].count += f.count
        else:
            by_fp[fp] = f
    return list(by_fp.values())


# -- APX306: the static x measured wire-dtype join ----------------------------

def _as_report(report_or_stats):
    from apex_tpu.monitor import numerics as nx
    if hasattr(report_or_stats, "rows"):
        return report_or_stats
    return nx.precision_report(report_or_stats)


def wire_dtype_findings(schedule: Iterable, report_or_stats, *,
                        extra_scopes: Sequence[str] = ()
                        ) -> List[Finding]:
    """APX306: reduction collectives whose float wire dtype is
    narrower than the committed ``precision_report`` verdicts for
    their subsystem (scope → subsystem via
    :mod:`apex_tpu.parallel.registry`; rows of the matching kind, all
    rows when none match). Non-float wires (the int8 error-feedback
    sync) are exempt."""
    from apex_tpu.parallel import registry
    report = _as_report(report_or_stats)
    if not report.rows:
        return []
    out: List[Finding] = []
    for instr in schedule or ():
        if instr.opcode not in _REDUCTION_COLLECTIVES:
            continue
        wire_fmts = [hlo_dtype_format(d) for d in instr.dtypes]
        wire_fmts = [f for f in wire_fmts if f is not None]
        if not wire_fmts:
            continue                       # int/pred wire: exempt
        wire = min(wire_fmts, key=lambda f: _RANK[f])
        ent = registry.scope_entry(instr.scope,
                                   tuple(extra_scopes)) \
            if instr.scope else None
        subsystem = getattr(ent, "subsystem", None)
        rows = [r for r in report.rows if r.kind == subsystem] \
            or list(report.rows)
        unsafe = [r for r in rows
                  if _RANK.get(r.required_dtype, 99) > _RANK[wire]]
        if not unsafe:
            continue
        widest = max((r.required_dtype for r in unsafe),
                     key=lambda f: _RANK.get(f, 0))
        out.append(Finding(
            rule="wire-dtype-unsafe",
            message=(f"{instr.opcode} wire dtype {wire} is narrower "
                     f"than the measured verdict for {len(unsafe)} "
                     f"site(s) (widest required: {widest}"
                     + (f", subsystem {subsystem}" if subsystem
                        else "") + ")"),
            op=instr.opcode, scope=instr.scope or instr.name,
            bytes=instr.bytes, count=len(unsafe),
            dtype_from=wire if wire in LADDER else None,
            dtype_to=widest if widest in LADDER else None,
            scale_provenance="unscaled"))
    return out


# -- the fp8/O4 pre-flight ----------------------------------------------------

@dataclasses.dataclass
class PreflightResult:
    """The ranked "statically castable ∩ measured-safe" site list."""

    rows: List[Dict]            # one per measured fp8-safe site
    blocking: List[str]         # APX3xx error ids blocking the program
    n_sites: int                # static sites the pass examined
    n_measured_sites: int       # verdict rows in the report

    @property
    def candidates(self) -> List[Dict]:
        return [r for r in self.rows if r["castable"]]

    def table(self) -> str:
        head = (f"precision preflight: {len(self.candidates)}/"
                f"{len(self.rows)} measured fp8-safe site(s) "
                f"statically castable ({self.n_sites} static sites "
                "examined)")
        lines = [head]
        if self.blocking:
            lines.append("  blocked by: " + ", ".join(self.blocking)
                         + " — fix the static findings first")
        if not self.rows:
            lines.append("  no measured fp8 candidates.")
            return "\n".join(lines)
        lines.append(f"  {'#':>3} {'ok':<3} {'format':<9} "
                     f"{'scale':>10}  site")
        for i, r in enumerate(self.rows):
            lines.append(
                f"  {i + 1:>3} {'y' if r['castable'] else 'n':<3} "
                f"{r['required_dtype']:<9} "
                f"{r['recommended_scale']:>10.3g}  "
                f"{r['site'][:60]}")
        return "\n".join(lines)


def precision_preflight(fn_or_jaxpr, *args, stats=None, report=None,
                        policy=None, hlo_text=None, k=None,
                        known_scopes: Sequence[str] = (),
                        **kwargs) -> PreflightResult:
    """Join the program's static precision verdict with a measured
    ``precision_report`` (a :class:`NumericsReport`, or ``stats=`` a
    committed stats dict / ``stats_to_json`` fixture): every measured
    fp8-safe site, ranked narrowest-format-first, carrying whether the
    program as compiled is statically safe to start casting
    (no APX3xx errors — including APX306 when ``hlo_text`` supplies
    the collective schedule)."""
    from apex_tpu.monitor import numerics as nx
    if report is None:
        if stats is None:
            raise ValueError("precision_preflight needs report= or "
                             "stats=")
        report = nx.precision_report(stats)
    analysis = analyze_jaxpr(fn_or_jaxpr, *args, policy=policy,
                             **kwargs)
    findings = list(analysis.findings)
    if hlo_text:
        from apex_tpu.lint.spmd_pass import extract_collective_schedule
        findings += wire_dtype_findings(
            extract_collective_schedule(hlo_text), report,
            extra_scopes=known_scopes)
    blocking = sorted({f.id for f in findings
                       if f.severity == "error"})
    rows = []
    for cand in report.fp8_candidates(k):
        rows.append({**cand, "castable": not blocking,
                     "blocking": blocking})
    rows.sort(key=lambda r: (_RANK.get(r["required_dtype"], 9),
                             r.get("predicted_underflow_frac", 0.0)
                             + r.get("predicted_saturation_frac", 0.0),
                             r["site"]))
    return PreflightResult(rows=rows, blocking=blocking,
                           n_sites=analysis.n_sites,
                           n_measured_sites=len(report.rows))
