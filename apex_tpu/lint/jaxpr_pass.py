"""The jaxpr half of apexlint: trace-time semantic rules.

Walks ``jax.make_jaxpr`` output (recursing into every sub-jaxpr — pjit
bodies, scan/while bodies, cond branches, custom_vjp calls) and checks
properties that are visible *before* XLA ever runs:

- **rng-key-reuse** (APX001): the same key variable consumed by more
  than one random primitive (directly, via ``random_wrap`` of a raw
  uint32 key, or as the key operand of a call whose body draws
  randomness) — correlated draws, the classic silent-statistics bug.
- **f64-creep** (APX002): any float64 value in the step — a numpy
  scalar or ``.astype`` that promoted the graph.
- **fp32-matmul-in-amp** (APX003): an all-fp32 ``dot_general``/
  ``conv_general_dilated`` while the supplied amp policy computes in
  bf16/fp16 (a bf16-in/f32-out accumulating dot is fine and not
  flagged).
- **host-callback-in-step** (APX004): ``jax.debug.print``/
  ``pure_callback``/``io_callback`` traced into the step.

Everything here is AOT: ``make_jaxpr`` traces but never compiles or
dispatches (the ``lint/no-extra-dispatch`` compile-check case pins
that linting leaves the step's compiled HLO bit-identical).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from apex_tpu.lint.findings import Finding

__all__ = ["lint_jaxpr", "iter_eqns"]

#: primitives that CONSUME a key to draw bits / derive keys
RANDOM_PRIMS = frozenset({
    "random_bits", "random_split", "random_fold_in", "random_gamma",
    "threefry2x32", "rng_bit_generator",
})
#: primitives that wrap a raw uint32 buffer into a typed key — their
#: operand IS the key material, so two wraps of one buffer is reuse
KEY_WRAP_PRIMS = frozenset({"random_wrap"})

CALLBACK_PRIMS = frozenset({
    "debug_callback", "pure_callback", "io_callback", "outside_call",
    "host_callback",
})

MATMUL_PRIMS = frozenset({"dot_general", "conv_general_dilated"})

_F64 = np.dtype(np.float64)
_F32 = np.dtype(np.float32)


def _np_dtype(dt) -> Optional[np.dtype]:
    """np.dtype of an aval dtype, or None for extended dtypes (typed
    PRNG keys) that numpy cannot interpret."""
    if dt is None:
        return None
    try:
        return np.dtype(dt)
    except TypeError:
        return None


def _closed_to_jaxpr(j):
    return j.jaxpr if hasattr(j, "jaxpr") else j


def _sub_jaxprs(eqn):
    """Every Jaxpr nested in an eqn's params (call/control-flow bodies)."""
    for v in eqn.params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if hasattr(x, "eqns"):            # Jaxpr
                yield x
            elif hasattr(x, "jaxpr"):          # ClosedJaxpr
                yield x.jaxpr


def iter_eqns(jaxpr, path: Tuple[str, ...] = ()):
    """Yield ``(eqn, path)`` over a jaxpr and all nested jaxprs; path
    accumulates call names (``pjit[name=...]``, scan, while, ...)."""
    jaxpr = _closed_to_jaxpr(jaxpr)
    for eqn in jaxpr.eqns:
        yield eqn, path
        name = eqn.params.get("name")
        sub_path = path + ((str(name),) if name
                           else (eqn.primitive.name,))
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, sub_path)


def _contains_random(jaxpr, memo: Dict[int, bool]) -> bool:
    jaxpr = _closed_to_jaxpr(jaxpr)
    key = id(jaxpr)
    if key in memo:
        return memo[key]
    memo[key] = False        # cycle guard (jaxprs are acyclic anyway)
    found = False
    for eqn in jaxpr.eqns:
        if (eqn.primitive.name in RANDOM_PRIMS
                or eqn.primitive.name in KEY_WRAP_PRIMS):
            found = True
            break
        if any(_contains_random(s, memo) for s in _sub_jaxprs(eqn)):
            found = True
            break
    memo[key] = found
    return found


def _is_key_aval(aval) -> bool:
    """True for typed PRNG keys and raw uint32 key buffers."""
    dt = getattr(aval, "dtype", None)
    if dt is None:
        return False
    try:
        if jax.dtypes.issubdtype(dt, jax.dtypes.prng_key):
            return True
    except Exception:
        pass
    shape = getattr(aval, "shape", ())
    nd = _np_dtype(dt)
    return (nd is not None and nd == np.dtype(np.uint32)
            and len(shape) >= 1 and shape[-1] in (2, 4))


def _is_literal(v) -> bool:
    return not hasattr(v, "count") and hasattr(v, "val")


# -- rules --------------------------------------------------------------------

def _rng_reuse(jaxpr, memo, out: List[Finding],
               path: Tuple[str, ...] = ()) -> None:
    """Per jaxpr level: key vars consumed by >= 2 random consumers."""
    jaxpr = _closed_to_jaxpr(jaxpr)
    consumers: Dict[Any, List[str]] = {}
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        is_random = (name in RANDOM_PRIMS or name in KEY_WRAP_PRIMS
                     or any(_contains_random(s, memo)
                            for s in _sub_jaxprs(eqn)))
        if is_random:
            label = str(eqn.params.get("name") or name)
            for v in eqn.invars:
                if _is_literal(v):
                    continue
                if name in KEY_WRAP_PRIMS or _is_key_aval(v.aval):
                    consumers.setdefault(v, []).append(label)
        # recurse: reuse inside a call body is a violation at that level
        sub_path = path + ((str(eqn.params.get("name")),)
                           if eqn.params.get("name") else ())
        for sub in _sub_jaxprs(eqn):
            _rng_reuse(sub, memo, out, sub_path)
    for var, who in consumers.items():
        if len(who) >= 2:
            aval = getattr(var, "aval", None)
            out.append(Finding(
                rule="rng-key-reuse",
                message=f"key {aval} feeds {len(who)} random consumers: "
                        f"{', '.join(who[:4])}",
                op="/".join(who[:4]), scope="/".join(path),
                count=len(who)))


def _f64_creep(jaxpr, out: List[Finding]) -> None:
    hits: Dict[str, int] = {}
    top = _closed_to_jaxpr(jaxpr)
    for v in top.invars:
        dt = _np_dtype(getattr(getattr(v, "aval", None), "dtype", None))
        # NB: "dt == _F64" without the None guard would be True — numpy
        # coerces None to the default dtype, which IS float64
        if dt is not None and dt == _F64:
            hits["<argument>"] = hits.get("<argument>", 0) + 1
    for eqn, _path in iter_eqns(jaxpr):
        for v in eqn.outvars:
            dt = _np_dtype(getattr(getattr(v, "aval", None), "dtype",
                                   None))
            if dt is not None and dt == _F64:
                hits[eqn.primitive.name] = \
                    hits.get(eqn.primitive.name, 0) + 1
                break
    if hits:
        n = sum(hits.values())
        prims = ", ".join(sorted(hits)[:5])
        out.append(Finding(
            rule="f64-creep",
            message=f"{n} f64-producing equation(s) in the step "
                    f"(primitives: {prims})",
            op=prims, count=n))


def _fp32_matmul(jaxpr, policy, out: List[Finding]) -> None:
    if policy is None or not getattr(policy, "enabled", False):
        return
    half = (np.dtype(np.float16), np.dtype(np.dtype("bfloat16")))
    try:
        compute = np.dtype(policy.compute_dtype)
    except Exception:
        return
    if compute not in half:
        return
    hits: Dict[str, int] = {}
    for eqn, path in iter_eqns(jaxpr):
        if eqn.primitive.name not in MATMUL_PRIMS:
            continue
        in_all = [_np_dtype(getattr(getattr(v, "aval", None), "dtype",
                                    None)) for v in eqn.invars]
        in_dts = [d for d in in_all
                  if d is not None and np.issubdtype(d, np.floating)]
        out_dts = [d for d in (
            _np_dtype(getattr(getattr(v, "aval", None), "dtype", None))
            for v in eqn.outvars) if d is not None]
        # bf16-in/f32-out accumulation is the *wanted* shape; only an
        # all-fp32 matmul is creep
        if in_dts and all(d == _F32 for d in in_dts) \
                and all(d == _F32 for d in out_dts):
            key = "/".join(path + (eqn.primitive.name,)) or \
                eqn.primitive.name
            hits[key] = hits.get(key, 0) + 1
    for where, n in sorted(hits.items()):
        out.append(Finding(
            rule="fp32-matmul-in-amp",
            message=f"{n} all-fp32 matmul(s) under an active "
                    f"{compute} policy at {where}",
            op=where.rsplit("/", 1)[-1], scope=where, count=n))


def _callbacks(jaxpr, out: List[Finding]) -> None:
    hits: Dict[str, Tuple[int, str]] = {}
    for eqn, path in iter_eqns(jaxpr):
        if eqn.primitive.name in CALLBACK_PRIMS:
            n, p = hits.get(eqn.primitive.name, (0, "/".join(path)))
            hits[eqn.primitive.name] = (n + 1, p)
    for prim, (n, p) in sorted(hits.items()):
        out.append(Finding(
            rule="host-callback-in-step",
            message=f"{n} {prim} call(s) traced into the step",
            op=prim, scope=p or None, count=n))


# -- entry point --------------------------------------------------------------

def lint_jaxpr(fn_or_jaxpr, *args, policy=None, **kwargs) -> List[Finding]:
    """Run the jaxpr rules.

    ``fn_or_jaxpr`` is either a (possibly jitted) callable — traced here
    via ``jax.make_jaxpr(fn)(*args, **kwargs)``, no compile, no dispatch
    — or an already-made (Closed)Jaxpr (then pass no args). ``policy``
    is the :class:`apex_tpu.amp.Policy` the step runs under; the
    fp32-matmul rule only activates for a half-precision policy.
    """
    if hasattr(fn_or_jaxpr, "eqns") or hasattr(fn_or_jaxpr, "jaxpr"):
        jaxpr = fn_or_jaxpr
    else:
        jaxpr = jax.make_jaxpr(fn_or_jaxpr)(*args, **kwargs)
    out: List[Finding] = []
    _rng_reuse(jaxpr, {}, out)
    _f64_creep(jaxpr, out)
    _fp32_matmul(jaxpr, policy, out)
    _callbacks(jaxpr, out)
    return out
