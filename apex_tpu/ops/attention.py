"""Fused multihead attention — blockwise (flash) Pallas kernels.

TPU-native rebuild of the `fast_multihead_attn` family
(`apex/contrib/csrc/multihead_attn/*`: fused QKV GEMM → CUTLASS strided-
batched GEMM → warp softmax(+mask)(+dropout) → batched GEMM, headers
`softmax.h`, `strided_batched_gemm.h`). Those kernels materialize the full
(S, S) attention matrix per head and run fixed-max-seq warp softmax; the
TPU design is strictly stronger: **blockwise softmax with online
renormalization** (flash attention), so the score matrix never exists in
HBM, memory is O(S·D) instead of O(S²), and long sequences are natural —
which is exactly why it also becomes the per-shard compute of ring
sequence parallelism (apex_tpu.parallel.ring).

Layout: (B, S, H, D) inputs, kernel works on (B·H, S, D). Forward saves
(out, lse) residuals; backward recomputes probabilities blockwise (two
kernels: dq over q-blocks, dk/dv over k-blocks), the standard
recompute-over-store trade that wins on HBM bandwidth.

Additive bias (the reference's additive-mask variants), causal masking,
and softmax dropout all run inside the kernel. Dropout — fused in the
reference via in-kernel Philox (`dropout.h`) — uses a counter-based hash
RNG (see ``_keep_mask``): the mask is a pure function of the score
element's coordinates, so forward and backward regenerate it exactly and
no mask tensor ever exists in HBM.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import use_interpret

LANES = 128
# Grid-step overhead on TPU dwarfs the per-tile MXU work at 128-blocks
# (a 128x128x64 tile is ~4 MFLOP ≈ 20 ns of MXU time). Sequences at or
# below the default clamp to a single block (so S=512 behaves exactly
# as the round-2 512-tile default, measured 13x faster backward than
# 128); longer sequences run 1024-tiles — the PERF.md sweep measured
# S=8192/d=64 fwd 27.3 → 51.6 TFLOP/s and fwd+bwd 1.35x vs 512-tiles
# (a 1024² fp32 score tile is 4 MiB, still VMEM-comfortable on the
# plain path). Long sequences stream blockwise — this only sets the
# tile, not the memory complexity.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
NEG_INF = -1e30
#: tile cap for bias/dropout-carrying kernels. ONE definition: the
#: dropout keep-mask hash is a function of block coordinates, so
#: _block_cap, the dense replica, AND ring_attention's shard-alignment
#: check + block-offset units must all agree on this number.
DROPOUT_TILE = 512


def _block_cap(block_q, block_k, has_bias, dropout_rate):
    """Tile cap for kernel paths that hold extra full-tile temporaries.

    A (bq, bk) fp32 bias block at 1024-tiles is 4 MiB (double-buffered:
    8), and dropout adds keep-mask/hash uint32 temporaries of the same
    footprint — either pushes the kernels past the 16 MiB scoped VMEM
    on long sequences, so both paths stay on the proven 512 tile.

    ONE definition, used by the forward wrapper, the backward wrapper
    AND the dense dropout-mask replica (`_bias_grad`): the counter-based
    mask is a function of block coordinates, so any divergence in the
    cap silently changes the dropout mask between kernels and the dense
    replica."""
    if has_bias or dropout_rate > 0.0:
        return min(block_q, DROPOUT_TILE), min(block_k, DROPOUT_TILE)
    return block_q, block_k


def _choose_block(pref, s, lane: bool = False):
    """Tile size for a sequence dim: clamp to the sequence, keep it
    8-sublane aligned (the lse output block `(bq, LANES)` tiles a
    `(B·H·nq·bq, LANES)` buffer, so bq must be a multiple of 8 whenever
    there is more than one block — interpret mode does not check this),
    and halve while padding waste exceeds half a tile (a 520-long
    sequence should pad to 640, not 1024).

    ``lane=True`` marks the key dimension, which lands in the *lane*
    position of the bias block: with more than one block Mosaic requires
    a multiple of 128 there, so halved/odd preferences (e.g. block_k=384
    over Sk=400 halving to 96) are rounded back up to 128-multiples; a
    single block covering the whole padded dim is always legal."""
    b = -(-min(pref, max(16, s)) // 8) * 8
    while b > 128 and (-(-s // b)) * b - s > b // 2:
        b //= 2
    if lane and -(-s // b) > 1 and b % LANES:
        b = -(-b // LANES) * LANES
    return b


def _g_pack(bh, nq, has_bias, dropout_rate, bq, bk, dp, itemsize=2):
    """Batch·head rows per grid step for the flash kernels.

    One-row steps leave the core waiting on per-step DMA setup (~2.3us
    measured vs ~0.7us of MXU work at S=512, D=64); packing g rows
    amortizes it. Only on the fast path: per-row bias blocks and the
    dropout hash's program_id coordinates assume one row per step, and
    the lse block layout needs a single q-block — so bias/dropout/nq>1
    keep g=1. Bounded by a ~9 MiB VMEM estimate (in-blocks double-
    buffered + f32 accumulators); ``itemsize`` is the q/k/v element
    size — the kernels keep inputs in their native dtype, so fp32
    inputs halve the attainable packing (ADVICE r3 item 1)."""
    if has_bias or dropout_rate > 0.0 or nq != 1:
        return 1
    for g in (4, 2):
        if bh % g:
            continue
        half_bufs = g * (bq + 2 * bk) * dp * 2 * itemsize
        scratch = g * bq * (2 * LANES + 2 * dp) * 4
        if half_bufs + scratch <= 9 * 2 ** 20:
            return g
    return 1


def _causal_mask(iq, ik, bq, bk, offset):
    """Bottom-right-aligned causal mask: query i attends keys
    0..i+(Sk-Sq), matching the oracle's tril(k=sk-sq) for cross lengths."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
    cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
    return rows + offset >= cols


def _kv_valid(ik, bk, kv_len, bq):
    cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
    return cols < kv_len


def _tile_valid(iq, ik, bq, bk, kv_len, q_len, causal, off, *,
                need_rows):
    """Validity mask for one (bq, bk) score tile, or None when it is
    statically all-true. kv_len/q_len/bq/bk are Python ints, so each
    term elides independently at trace time: the kv-pad term exists iff
    ``kv_len % bk``, the q-row-pad term iff ``need_rows and q_len %
    bq``; only the causal frontier is inherently dynamic. ONE
    definition for all four native-layout kernels — each retained term
    costs a full-tile iota/compare/AND VPU sweep."""
    valid = None

    def land(a, b):
        return b if a is None else jnp.logical_and(a, b)

    if kv_len % bk:
        valid = land(valid, _kv_valid(ik, bk, kv_len, bq))
    if causal:
        valid = land(valid, _causal_mask(iq, ik, bq, bk, off))
    if need_rows and q_len % bq:
        rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
        valid = land(valid, rows < q_len)
    return valid


def _keep_mask(seed, iq, ik, bq, bk, rate, gb=None):
    """In-kernel softmax-dropout keep mask — the TPU analogue of the
    reference's Philox dropout fused into the softmax kernel
    (`apex/contrib/csrc/multihead_attn/dropout.h:1-308`).

    Counter-based (lowbias32 avalanche over the score element's grid
    coordinates), so it is a pure function of (seed, batch·head, q-block,
    k-block, row, col): the forward and both backward kernels regenerate
    bitwise-identical masks regardless of grid iteration order, and
    compiled/interpret modes agree exactly (unlike ``pltpu.prng_*``,
    which has no interpret lowering). ``gb`` overrides the batch·head
    coordinate for kernels whose grid packs several heads per step (the
    native-layout path); default is one bh-row per step.
    """
    if gb is None:
        gb = pl.program_id(0)
    rows = jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 0)
    cols = jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 1)
    return _mix_keep(seed, gb, iq, ik, rows, cols, rate)


def _dbo_shift(iq, ik, dbo_ref, has_dbo):
    """Apply the traced (q-block, k-block) dropout offsets — ONE
    definition for all four kernels: a drifted copy would silently
    change the mask in exactly one of fwd/bwd."""
    if not has_dbo:
        return iq, ik
    return iq + dbo_ref[0], ik + dbo_ref[1]


def _mix_keep(seed, gb, iq, ik, rows, cols, rate):
    """The shared coordinate hash: block seed + per-element lowbias32
    avalanche → keep bool. ONE definition used by both the kernels and
    the dense replica (`_keep_mask_dense`) — their bitwise agreement is
    what makes the bias-gradient dropout mask exact."""
    x = (seed.astype(jnp.uint32)
         + jnp.asarray(gb).astype(jnp.uint32) * np.uint32(0x9E3779B9)
         + jnp.asarray(iq).astype(jnp.uint32) * np.uint32(0x85EBCA6B)
         + jnp.asarray(ik).astype(jnp.uint32) * np.uint32(0xC2B2AE35)
         + rows * np.uint32(0x27D4EB2F) + cols * np.uint32(0x165667B1))
    x = x ^ (x >> 16)
    x = x * np.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * np.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    # drop iff x < rate·2^32 ⇒ P(keep) = 1 - rate
    return x >= np.uint32(int(rate * 4294967296.0))


# --- forward ----------------------------------------------------------------

def _fwd_kernel(scale, causal, kv_len, q_len, has_bias, dropout_rate,
                g_pack, refs):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    pos = 3
    b_ref = None
    if has_bias:
        b_ref = refs[pos]
        pos += 1
    seed_ref = None
    if dropout_rate > 0.0:
        seed_ref = refs[pos]
        pos += 1
    o_ref, lse_ref, m_scr, l_scr, acc = refs[pos:]
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc[:] = jnp.zeros_like(acc)

    # ``g_pack`` batch·head rows per grid step (statically unrolled):
    # one-head steps at S=512 measured ~2.3us against ~0.7us of MXU
    # work — per-step DMA setup dominates; packing amortizes it. Only
    # used on the no-bias/no-dropout/single-q-block path (wrapper
    # gates), so bias/dropout below always see g_pack == 1.
    for h in range(g_pack):
        # dot operands stay in the INPUT dtype (bf16 multiplies + f32
        # MXU accumulate via preferred_element_type); softmax stays f32
        q, k, v = q_ref[h], k_ref[h], v_ref[h]
        bq, bk = q.shape[0], k.shape[0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if has_bias:
            s = s + b_ref[0].astype(jnp.float32)
        valid = _kv_valid(ik, bk, kv_len, bq)
        if causal:
            valid = jnp.logical_and(
                valid, _causal_mask(iq, ik, bq, bk, kv_len - q_len))
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_scr[h][:, :1]
        l_prev = l_scr[h][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        # softmax dropout: the normalizer l uses the *undropped* sum
        # (dropout acts on the normalized probabilities, after the
        # softmax), so only the accumulator sees the mask
        pd = p
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref[0], iq, ik, bq, bk, dropout_rate)
            pd = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
        acc[h] = acc[h] * alpha + jax.lax.dot_general(
            pd.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[h] = jnp.broadcast_to(m_new, m_scr.shape[1:])
        l_scr[h] = jnp.broadcast_to(l_new, l_scr.shape[1:])

        @pl.when(ik == nk - 1)
        def _(h=h):
            l = l_scr[h][:, :1]
            safe_l = jnp.where(l == 0.0, 1.0, l)
            o_ref[h] = (acc[h] / safe_l).astype(o_ref.dtype)
            # lse = m + log l; fully-masked rows get -inf-ish lse →
            # p=0 in bwd
            bq_ = o_ref.shape[1]
            lse_ref[h * bq_:(h + 1) * bq_] = \
                (m_scr[h][:, :1] + jnp.log(safe_l)) \
                + jnp.zeros((bq_, lse_ref.shape[1]), jnp.float32)


def _flash_fwd(q3, k3, v3, bias_g, bidx, scale, causal, block_q, block_k,
               dropout_rate=0.0, seed=None):
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    dp = -(-d // LANES) * LANES
    block_q, block_k = _block_cap(block_q, block_k, bias_g is not None,
                                  dropout_rate)
    bq = _choose_block(block_q, sq)
    bk = _choose_block(block_k, sk, lane=True)
    sqp = -(-sq // bq) * bq
    skp = -(-sk // bk) * bk

    pad3 = lambda t, s_, d_: jnp.pad(
        t, ((0, 0), (0, s_ - t.shape[1]), (0, d_ - t.shape[2])))
    qp, kp, vp = pad3(q3, sqp, dp), pad3(k3, skp, dp), pad3(v3, skp, dp)
    nq, nk = sqp // bq, skp // bk

    has_bias = bias_g is not None
    g = _g_pack(bh, nq, has_bias, dropout_rate, bq, bk, dp,
                q3.dtype.itemsize)
    in_specs = [
        pl.BlockSpec((g, bq, dp), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((g, bk, dp), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((g, bk, dp), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [qp, kp, vp]
    if has_bias:
        bias_p = jnp.pad(bias_g, ((0, 0), (0, sqp - sq), (0, skp - sk)))
        in_specs.append(pl.BlockSpec(
            (1, bq, bk), lambda b, i, j: (bidx(b), i, j),
            memory_space=pltpu.VMEM))
        args.append(bias_p)
    if dropout_rate > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)

    kernel = functools.partial(_fwd_kernel, scale, causal, sk, sq,
                               has_bias, dropout_rate, g)
    o, lse = pl.pallas_call(
        lambda *refs: kernel(refs),
        grid=(bh // g, nq, nk),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((g, bq, dp), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((g * bq, LANES), lambda b, i, j: (b * nq + i, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, sqp, dp), q3.dtype),
            jax.ShapeDtypeStruct((bh * nq * bq, LANES), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((g, bq, LANES), jnp.float32),
            pltpu.VMEM((g, bq, LANES), jnp.float32),
            pltpu.VMEM((g, bq, dp), jnp.float32),
        ],
        interpret=use_interpret(),
    )(*args)
    lse = lse[:, 0].reshape(bh, sqp)[:, :sq]
    return o[:, :sq, :d], lse


# --- backward ---------------------------------------------------------------

def _bwd_dq_kernel(scale, causal, kv_len, q_len, has_bias, dropout_rate,
                   g_pack, refs):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    pos = 3
    b_ref = None
    if has_bias:
        b_ref = refs[pos]
        pos += 1
    seed_ref = None
    if dropout_rate > 0.0:
        seed_ref = refs[pos]
        pos += 1
    do_ref, lse_ref, dl_ref, dq_ref, dq_acc = refs[pos:]
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    for h in range(g_pack):
        # dots in input dtype + f32 accumulate (see _fwd_kernel note)
        q, k, v, do = q_ref[h], k_ref[h], v_ref[h], do_ref[h]
        bq, bk = q.shape[0], k.shape[0]
        lse = lse_ref[h * bq:(h + 1) * bq, :1]
        delta = dl_ref[h * bq:(h + 1) * bq, :1]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if has_bias:
            s = s + b_ref[0].astype(jnp.float32)
        valid = _kv_valid(ik, bk, kv_len, bq)
        if causal:
            valid = jnp.logical_and(
                valid, _causal_mask(iq, ik, bq, bk, kv_len - q_len))
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            # gradient flows only through kept entries: dP = mask·dp̃/
            # keep. delta = rowsum(do·o) already equals Σ_j dp̃_j·P̃_j
            # (see _flash_bwd), so only dp needs the mask applied here
            keep = _keep_mask(seed_ref[0], iq, ik, bq, bk, dropout_rate)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        ds = (p * (dp - delta)).astype(q.dtype)
        dq_acc[h] = dq_acc[h] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

        @pl.when(ik == nk - 1)
        def _(h=h):
            dq_ref[h] = dq_acc[h].astype(dq_ref.dtype)


def _bwd_dkv_kernel(scale, causal, kv_len, q_len, has_bias, dropout_rate,
                    g_pack, refs):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    pos = 3
    b_ref = None
    if has_bias:
        b_ref = refs[pos]
        pos += 1
    seed_ref = None
    if dropout_rate > 0.0:
        seed_ref = refs[pos]
        pos += 1
    do_ref, lse_ref, dl_ref, dk_ref, dv_ref, dk_acc, dv_acc = refs[pos:]
    ik, iq = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    for h in range(g_pack):
        # dots in input dtype + f32 accumulate (see _fwd_kernel note)
        q, k, v, do = q_ref[h], k_ref[h], v_ref[h], do_ref[h]
        bq, bk = q.shape[0], k.shape[0]
        lse = lse_ref[h * bq:(h + 1) * bq, :1]
        delta = dl_ref[h * bq:(h + 1) * bq, :1]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if has_bias:
            s = s + b_ref[0].astype(jnp.float32)
        valid = _kv_valid(ik, bk, kv_len, bq)
        if causal:
            valid = jnp.logical_and(
                valid, _causal_mask(iq, ik, bq, bk, kv_len - q_len))
        # also mask padded query rows
        rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
        valid = jnp.logical_and(valid, rows < q_len)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)

        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        pv = p
        if dropout_rate > 0.0:
            # dv sees the dropped probabilities p̃ = mask·p/keep; dp gets
            # the same mask (gradient only through kept entries) —
            # identical mask to the forward because _keep_mask is
            # counter-based on (iq, ik)
            keep = _keep_mask(seed_ref[0], iq, ik, bq, bk, dropout_rate)
            inv_keep = 1.0 / (1.0 - dropout_rate)
            pv = jnp.where(keep, p * inv_keep, 0.0)
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        dv_acc[h] = dv_acc[h] + jax.lax.dot_general(
            pv.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc[h] = dk_acc[h] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(iq == nq - 1)
    def _():
        for h in range(g_pack):
            dk_ref[h] = dk_acc[h].astype(dk_ref.dtype)
            dv_ref[h] = dv_acc[h].astype(dv_ref.dtype)


def _flash_bwd(q3, k3, v3, bias_g, bidx, o3, lse, do3, scale, causal,
               block_q, block_k, delta_shift=None, dropout_rate=0.0,
               seed=None):
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    dp = -(-d // LANES) * LANES
    block_q, block_k = _block_cap(block_q, block_k, bias_g is not None,
                                  dropout_rate)
    bq = _choose_block(block_q, sq)
    bk = _choose_block(block_k, sk, lane=True)
    sqp = -(-sq // bq) * bq
    skp = -(-sk // bk) * bk
    nq, nk = sqp // bq, skp // bk

    pad3 = lambda t, s_, d_: jnp.pad(
        t, ((0, 0), (0, s_ - t.shape[1]), (0, d_ - t.shape[2])))
    qp, kp, vp = pad3(q3, sqp, dp), pad3(k3, skp, dp), pad3(v3, skp, dp)
    dop = pad3(do3, sqp, dp)

    # delta_i = rowsum(do * o) — flash backward's precomputed correction;
    # an lse cotangent shifts it (ds = p*(dp - delta) + p*dlse)
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)
    if delta_shift is not None:
        delta = delta - delta_shift.astype(jnp.float32)
    # lay lse/delta out as (bh*nq*bq, LANES) lane-broadcast rows
    def lanes(x):
        xpad = jnp.pad(x, ((0, 0), (0, sqp - sq)))
        return jnp.broadcast_to(
            xpad.reshape(bh * sqp, 1), (bh * sqp, LANES))

    lse_l, delta_l = lanes(lse), lanes(delta)

    has_bias = bias_g is not None
    bias_p = None
    if has_bias:
        bias_p = jnp.pad(bias_g, ((0, 0), (0, sqp - sq), (0, skp - sk)))

    g = _g_pack(bh, nq, has_bias, dropout_rate, bq, bk, dp,
                q3.dtype.itemsize)
    q_spec_q = pl.BlockSpec((g, bq, dp), lambda b, i, j: (b, i, 0),
                            memory_space=pltpu.VMEM)
    k_spec_q = pl.BlockSpec((g, bk, dp), lambda b, i, j: (b, j, 0),
                            memory_space=pltpu.VMEM)
    lane_spec_q = pl.BlockSpec((g * bq, LANES),
                               lambda b, i, j: (b * nq + i, 0),
                               memory_space=pltpu.VMEM)

    in_specs = [q_spec_q, k_spec_q, k_spec_q]
    args = [qp, kp, vp]
    if has_bias:
        in_specs.append(pl.BlockSpec(
            (1, bq, bk), lambda b, i, j: (bidx(b), i, j),
            memory_space=pltpu.VMEM))
        args.append(bias_p)
    if dropout_rate > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    in_specs += [q_spec_q, lane_spec_q, lane_spec_q]
    args += [dop, lse_l, delta_l]

    dq = pl.pallas_call(
        lambda *refs: functools.partial(
            _bwd_dq_kernel, scale, causal, sk, sq, has_bias,
            dropout_rate, g)(refs),
        grid=(bh // g, nq, nk),
        in_specs=in_specs,
        out_specs=q_spec_q,
        out_shape=jax.ShapeDtypeStruct((bh, sqp, dp), q3.dtype),
        scratch_shapes=[pltpu.VMEM((g, bq, dp), jnp.float32)],
        interpret=use_interpret(),
    )(*args)

    # dk/dv: grid loops q innermost
    q_spec_k = pl.BlockSpec((g, bq, dp), lambda b, j, i: (b, i, 0),
                            memory_space=pltpu.VMEM)
    k_spec_k = pl.BlockSpec((g, bk, dp), lambda b, j, i: (b, j, 0),
                            memory_space=pltpu.VMEM)
    lane_spec_k = pl.BlockSpec((g * bq, LANES),
                               lambda b, j, i: (b * nq + i, 0),
                               memory_space=pltpu.VMEM)
    in_specs2 = [q_spec_k, k_spec_k, k_spec_k]
    args2 = [qp, kp, vp]
    if has_bias:
        in_specs2.append(pl.BlockSpec(
            (1, bq, bk), lambda b, j, i: (bidx(b), i, j),
            memory_space=pltpu.VMEM))
        args2.append(bias_p)
    if dropout_rate > 0.0:
        in_specs2.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args2.append(seed)
    in_specs2 += [q_spec_k, lane_spec_k, lane_spec_k]
    args2 += [dop, lse_l, delta_l]

    dk, dv = pl.pallas_call(
        lambda *refs: functools.partial(
            _bwd_dkv_kernel, scale, causal, sk, sq, has_bias,
            dropout_rate, g)(refs),
        grid=(bh // g, nk, nq),
        in_specs=in_specs2,
        out_specs=(k_spec_k, k_spec_k),
        out_shape=(jax.ShapeDtypeStruct((bh, skp, dp), k3.dtype),) * 2,
        scratch_shapes=[pltpu.VMEM((g, bk, dp), jnp.float32)] * 2,
        interpret=use_interpret(),
    )(*args2)

    return dq[:, :sq, :d], dk[:, :sk, :d], dv[:, :sk, :d]


# --- native-layout kernels ---------------------------------------------------
#
# The wrappers above take (B·H, S, D) operands, which costs a transpose
# copy per tensor at the custom-call boundary (measured 10.6 ms/step on
# the BERT bench — `{3,0,2,1}`-style relayouts XLA cannot fuse into a
# pallas call) plus a zero-pad of D up to the 128-lane tile. The
# native-layout path keeps the model's (B, S, H) activations AS the
# kernel operands: the grid still enumerates batch·head rows, but the
# BlockSpec index maps slice each head's D columns out of the lane axis
# (g heads per step so the block width g·D is lane-aligned — for the
# ubiquitous D=64 two heads share one 128-lane tile, removing the
# zero-pad too). Dropout stays bitwise-identical: the hash's batch·head
# coordinate is reconstructed as ``t·g + h``, exactly the bh-row the
# transposed path would have used.


def _native_g0(nh: int, d: int) -> Optional[int]:
    """Smallest head-group g with (g·d) lane-aligned; None = no native
    path (heads not groupable into a lane-aligned block)."""
    if d <= 0:
        return None
    g0 = 128 // int(np.gcd(d, 128))
    if nh % g0:
        return None
    return g0


def _native_g(nh, d, dropout_rate, bq, bk, itemsize, *, bias_isz=0,
              bias_per_head=False, carry_scratch=True):
    """Heads per grid step on the native path: at least g0 (lane
    alignment), more when the forward kernel's VMEM ledger fits the
    16 MiB scoped budget (in-blocks, scratch, score tile, out-blocks;
    packing amortizes per-step DMA setup). Dropout adds a (bq, bk)
    keep-mask/hash temporary; a bias adds its double-buffered
    (g|1, bq, bk) in-block. ``APEX_TPU_NATIVE_G`` overrides for perf
    experiments."""
    g0 = _native_g0(nh, d)
    forced = os.environ.get("APEX_TPU_NATIVE_G")
    if forced:
        try:
            g = int(forced)
        except ValueError:
            raise ValueError(
                f"APEX_TPU_NATIVE_G={forced!r} is not an integer; it "
                "must be a multiple of the lane-alignment group "
                f"g0={g0} that divides nh={nh}") from None
        if g > 0 and g % g0 == 0 and nh % g == 0:
            return g
        import warnings
        warnings.warn(
            f"APEX_TPU_NATIVE_G={g} ignored: must be a positive multiple of "
            f"g0={g0} (lane alignment for d={d}) and divide nh={nh}; "
            "using the VMEM-ledger choice instead", stacklevel=3)
    # full ledger of what the fwd kernel keeps in scoped VMEM: the
    # double-buffered q/k/v in-blocks, the m/l/acc scratch, the f32
    # score tile, the o and lse out-blocks (also double-buffered), and
    # dropout's keep-mask temporary. Calibrated against the measured
    # ceiling: S=2048 nh=16 OOM'd at g=4 (17.9 MiB actual) while
    # S=512 g=8 and fp32 S=1024 g=2 compile.
    mask_tmp = bq * bk * 8 if dropout_rate > 0.0 else 0
    for mult in (4, 2, 1):
        g = g0 * mult
        if nh % g:
            continue
        gd = g * d
        half_bufs = (bq + 2 * bk) * gd * itemsize * 2
        # single-k (no carry): the m/l/acc scratch disappears, but the
        # fp32 PV result and its divided copy live as stack temps (the
        # acc role, twice) and up to three score-class tiles coexist
        # (s, p, and the masked/dropout product). Calibrated against a
        # measured 16.73 MiB OOM at fp32 S=512 g=8 (the ledger must
        # reject g=8 there — a single-temp estimate lands at exactly
        # the 16 MiB boundary and slips through; g=4 fits).
        scratch = (g * bq * 2 * LANES * 4 + bq * gd * 4
                   if carry_scratch else 2 * bq * gd * 4)
        score = bq * bk * 4 * (1 if carry_scratch else 3)
        outs = bq * gd * itemsize * 2 + g * bq * LANES * 4 * 2
        bias_buf = ((g if bias_per_head else 1) * bq * bk * bias_isz * 2
                    if bias_isz else 0)
        if (half_bufs + scratch + score + outs + mask_tmp + bias_buf
                <= 16 * 2 ** 20):
            return g
    return g0


def _unpack_common(refs, pos, has_bias, dropout_rate, has_dbo,
                   has_off):
    """ONE optional-operand order for every native kernel:
    bias, dropout seed, dropout block offsets, causal offset. The
    wrapper-side mirror is :func:`_append_common` — a new optional
    operand is added in exactly these two places."""
    b_ref = seed_ref = dbo_ref = off_ref = None
    if has_bias:
        b_ref = refs[pos]
        pos += 1
    if dropout_rate > 0.0:
        seed_ref = refs[pos]
        pos += 1
    if has_dbo:
        dbo_ref = refs[pos]
        pos += 1
    if has_off:
        off_ref = refs[pos]
        pos += 1
    return b_ref, seed_ref, dbo_ref, off_ref, pos


def _append_common(in_specs, args, *, bias_p, bias_mode, g, hg,
                   bias_dims, bias_idx, dropout_rate, seed, dbo,
                   causal_off):
    """Wrapper-side mirror of :func:`_unpack_common`: appends the
    optional operands' specs/args in the shared order. ``bias_idx``
    maps the dim-0 row function to this grid's index map."""
    if bias_p is not None:
        blk0, row = _bias_blk_nl(bias_mode, g, hg)
        in_specs.append(pl.BlockSpec((blk0,) + tuple(bias_dims),
                                     bias_idx(row),
                                     memory_space=pltpu.VMEM))
        args.append(bias_p)
    if dropout_rate > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    if dbo is not None:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(dbo)
    if causal_off is not None:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(causal_off)


def _fwd_kernel_nl(scale, causal, kv_len, q_len, dropout_rate, d, g,
                   has_off, has_bias, bias_per_head, has_dbo, refs):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    b_ref, seed_ref, dbo_ref, off_ref, pos = _unpack_common(
        refs, 3, has_bias, dropout_rate, has_dbo, has_off)
    # single k-block (kv fits one tile, the S<=1024 regime): the online
    # running-max carry is dead weight — the wrapper passes no scratch,
    # and there is no init, no alpha rescale, no carry broadcasts, no
    # separate epilogue division pass
    single_k = kv_len <= k_ref.shape[1]
    if single_k:
        o_ref, lse_ref = refs[pos:]
        m_scr = l_scr = acc = None
    else:
        o_ref, lse_ref, m_scr, l_scr, acc = refs[pos:]
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    if not single_k:
        @pl.when(ik == 0)
        def _():
            m_scr[:] = jnp.full_like(m_scr, NEG_INF)
            l_scr[:] = jnp.zeros_like(l_scr)
            acc[:] = jnp.zeros_like(acc)

    for h in range(g):
        sl = slice(h * d, (h + 1) * d)
        q, k, v = q_ref[0][:, sl], k_ref[0][:, sl], v_ref[0][:, sl]
        bq, bk = q.shape[0], k.shape[0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if has_bias:
            s = s + b_ref[h if bias_per_head else 0].astype(jnp.float32)
        off = ((off_ref[0] if has_off else kv_len - q_len)
               if causal else None)
        valid = _tile_valid(iq, ik, bq, bk, kv_len, q_len, causal, off,
                            need_rows=False)
        masked = valid is not None
        if masked:
            s = jnp.where(valid, s, NEG_INF)

        if single_k:
            m_new = jnp.max(s, axis=1, keepdims=True)
            p = jnp.exp(s - m_new)
            if masked:
                # fully-masked rows: m == NEG_INF ⇒ p rows of exp(0)=1
                # garbage; zero them so l lands at 0 (ring contract)
                p = jnp.where(valid, p, 0.0)
            l = jnp.sum(p, axis=1, keepdims=True)
            pd = p
            if dropout_rate > 0.0:
                iqo, iko = _dbo_shift(iq, ik, dbo_ref, has_dbo)
                keep = _keep_mask(seed_ref[0], iqo, iko, bq, bk,
                                  dropout_rate,
                                  gb=pl.program_id(0) * g + h)
                pd = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)),
                               0.0)
            safe_l = jnp.where(l == 0.0, 1.0, l) if masked else l
            o_ref[0, :, sl] = (jax.lax.dot_general(
                pd.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) / safe_l).astype(
                    o_ref.dtype)
            lse_ref[h * bq:(h + 1) * bq] = \
                (m_new + jnp.log(safe_l)) \
                + jnp.zeros((bq, lse_ref.shape[1]), jnp.float32)
            continue

        m_prev = m_scr[h][:, :1]
        l_prev = l_scr[h][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        pd = p
        if dropout_rate > 0.0:
            iqo, iko = _dbo_shift(iq, ik, dbo_ref, has_dbo)
            keep = _keep_mask(seed_ref[0], iqo, iko, bq, bk, dropout_rate,
                              gb=pl.program_id(0) * g + h)
            pd = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
        acc[0, :, sl] = acc[0][:, sl] * alpha + jax.lax.dot_general(
            pd.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[h] = jnp.broadcast_to(m_new, m_scr.shape[1:])
        l_scr[h] = jnp.broadcast_to(l_new, l_scr.shape[1:])

        @pl.when(ik == nk - 1)
        def _(h=h, sl=sl):
            l = l_scr[h][:, :1]
            safe_l = jnp.where(l == 0.0, 1.0, l)
            bq_ = o_ref.shape[1]
            o_ref[0, :, sl] = (acc[0][:, sl] / safe_l).astype(o_ref.dtype)
            lse_ref[h * bq_:(h + 1) * bq_] = \
                (m_scr[h][:, :1] + jnp.log(safe_l)) \
                + jnp.zeros((bq_, lse_ref.shape[1]), jnp.float32)


def _head_specs(nh, g, bq, bk, gd):
    """(q, k) BlockSpecs over (B, S, H) with head columns in the lane
    axis; grid dim 0 enumerates (batch, head-group) pairs group-minor."""
    hg = nh // g
    q_spec = pl.BlockSpec((1, bq, gd),
                          lambda t, i, j: (t // hg, i, t % hg),
                          memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, bk, gd),
                          lambda t, i, j: (t // hg, j, t % hg),
                          memory_space=pltpu.VMEM)
    return q_spec, k_spec


def _lse_reorder(lse_rows, bh, g, nq, bq):
    """Kernel lse row order [group][q-block][head][row] → (bh, sqp).
    With nq == 1 or g == 1 the orders already coincide."""
    x = lse_rows.reshape(bh // g, nq, g, bq)
    if g > 1 and nq > 1:
        x = x.transpose(0, 2, 1, 3)
    return x.reshape(bh, nq * bq)


def _lanes_nl(x, bh, g, nq, bq, sq):
    """(bh, sq) per-row scalars → (bh·sqp, LANES) in the kernels' block
    row order [group][q-block][head][row]."""
    sqp = nq * bq
    xp = jnp.pad(x, ((0, 0), (0, sqp - sq)))
    xp = xp.reshape(bh // g, g, nq, bq)
    if g > 1 and nq > 1:
        xp = xp.transpose(0, 2, 1, 3)
    xp = xp.reshape(bh * sqp, 1)
    return jnp.broadcast_to(xp, (bh * sqp, LANES))


def _bias_group_nl(bias, b, nh, sq, sk):
    """(B|1, H|1, Sq|1, Sk|1) bias → ((G, Sq, Sk), mode) for the native
    grid whose dim 0 enumerates (batch, head-group) pairs group-minor.
    mode picks the dim-0 block shape and index map: 'shared' (G=1) and
    'batch' (G=B) ride (1, bq, bk) blocks; 'head' (G=H) and 'full'
    (G=B·H) need the step's g per-head slabs, (g, bq, bk) blocks.
    The reference's additive-mask MHA variants
    (`setup.py:295-320`, `self_multihead_attn_bias_additive_mask_cuda.cu`)
    are the per-batch/per-head cases."""
    if bias is None:
        return None, None
    bias_g, bb, bh_ = _bias_flat(bias, b, nh, sq, sk)
    mode = {(True, True): "shared", (False, True): "batch",
            (True, False): "head", (False, False): "full"}[
                (bb == 1, bh_ == 1)]
    return bias_g, mode


def _bias_blk_nl(mode, g, hg):
    """(dim-0 block size, grid-step → dim-0 block index) for a native
    bias spec; block index is in units of the block size."""
    return {
        "shared": (1, lambda t: 0),
        "batch": (1, lambda t: t // hg),
        "head": (g, lambda t: t % hg),
        "full": (g, lambda t: t),
    }[mode]


def _pad_bias_nl(bias_g, sqp, skp):
    G, sq, sk = bias_g.shape
    if sq == sqp and sk == skp:
        return bias_g
    return jnp.pad(bias_g, ((0, 0), (0, sqp - sq), (0, skp - sk)))


def _flash_fwd_nl(q2, k2, v2, nh, d, scale, causal, block_q, block_k,
                  dropout_rate=0.0, seed=None, causal_off=None,
                  bias_g=None, bias_mode=None, dbo=None):
    b, sq, H = q2.shape
    sk = k2.shape[1]
    bh = b * nh
    block_q, block_k = _block_cap(block_q, block_k, bias_g is not None,
                                  dropout_rate)
    bq = _choose_block(block_q, sq)
    bk = _choose_block(block_k, sk, lane=True)
    sqp = -(-sq // bq) * bq
    skp = -(-sk // bk) * bk
    nq, nk = sqp // bq, skp // bk

    pad_s = lambda t, s_: t if t.shape[1] == s_ else jnp.pad(
        t, ((0, 0), (0, s_ - t.shape[1]), (0, 0)))
    qp, kp, vp = pad_s(q2, sqp), pad_s(k2, skp), pad_s(v2, skp)

    bias_per_head = bias_mode in ("head", "full")
    g = _native_g(nh, d, dropout_rate, bq, bk, q2.dtype.itemsize,
                  bias_isz=(bias_g.dtype.itemsize if bias_g is not None
                            else 0),
                  bias_per_head=bias_per_head, carry_scratch=nk > 1)
    gd = g * d
    hg = nh // g
    q_spec, k_spec = _head_specs(nh, g, bq, bk, gd)
    in_specs = [q_spec, k_spec, k_spec]
    args = [qp, kp, vp]
    _append_common(
        in_specs, args,
        bias_p=(None if bias_g is None
                else _pad_bias_nl(bias_g, sqp, skp)),
        bias_mode=bias_mode, g=g, hg=hg, bias_dims=(bq, bk),
        bias_idx=lambda row: lambda t, i, j: (row(t), i, j),
        dropout_rate=dropout_rate, seed=seed, dbo=dbo,
        causal_off=causal_off)

    kernel = functools.partial(_fwd_kernel_nl, scale, causal, sk, sq,
                               dropout_rate, d, g,
                               causal_off is not None,
                               bias_g is not None, bias_per_head,
                               dbo is not None)
    o, lse = pl.pallas_call(
        lambda *refs: kernel(refs),
        grid=(bh // g, nq, nk),
        in_specs=in_specs,
        out_specs=(
            q_spec,
            pl.BlockSpec((g * bq, LANES), lambda t, i, j: (t * nq + i, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b, sqp, H), q2.dtype),
            jax.ShapeDtypeStruct((bh * nq * bq, LANES), jnp.float32),
        ),
        scratch_shapes=([] if nk == 1 else [
            pltpu.VMEM((g, bq, LANES), jnp.float32),
            pltpu.VMEM((g, bq, LANES), jnp.float32),
            pltpu.VMEM((1, bq, gd), jnp.float32),
        ]),
        interpret=use_interpret(),
    )(*args)
    lse = _lse_reorder(lse[:, 0], bh, g, nq, bq)[:, :sq]
    return o[:, :sq, :], lse


def _bwd_dq_kernel_nl(scale, causal, kv_len, q_len, dropout_rate, d, g,
                      has_off, has_bias, bias_per_head, has_dbo, refs):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    b_ref, seed_ref, dbo_ref, off_ref, pos = _unpack_common(
        refs, 3, has_bias, dropout_rate, has_dbo, has_off)
    do_ref, lse_ref, dl_ref, dq_ref, dq_acc = refs[pos:]
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    for h in range(g):
        sl = slice(h * d, (h + 1) * d)
        q, k, v = q_ref[0][:, sl], k_ref[0][:, sl], v_ref[0][:, sl]
        do = do_ref[0][:, sl]
        bq, bk = q.shape[0], k.shape[0]
        lse = lse_ref[h * bq:(h + 1) * bq, :1]
        delta = dl_ref[h * bq:(h + 1) * bq, :1]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if has_bias:
            s = s + b_ref[h if bias_per_head else 0].astype(jnp.float32)
        p = jnp.exp(s - lse)
        off = ((off_ref[0] if has_off else kv_len - q_len)
               if causal else None)
        valid = _tile_valid(iq, ik, bq, bk, kv_len, q_len, causal, off,
                            need_rows=False)
        if valid is not None:
            p = jnp.where(valid, p, 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            iqo, iko = _dbo_shift(iq, ik, dbo_ref, has_dbo)
            keep = _keep_mask(seed_ref[0], iqo, iko, bq, bk, dropout_rate,
                              gb=pl.program_id(0) * g + h)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        ds = (p * (dp - delta)).astype(q.dtype)
        dq_acc[0, :, sl] = dq_acc[0][:, sl] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

        @pl.when(ik == nk - 1)
        def _(sl=sl):
            dq_ref[0, :, sl] = dq_acc[0][:, sl].astype(dq_ref.dtype)


def _bwd_dkv_kernel_nl(scale, causal, kv_len, q_len, dropout_rate, d, g,
                       has_off, has_bias, bias_per_head, has_dbo, refs):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    b_ref, seed_ref, dbo_ref, off_ref, pos = _unpack_common(
        refs, 3, has_bias, dropout_rate, has_dbo, has_off)
    do_ref, lse_ref, dl_ref, dk_ref, dv_ref, dk_acc, dv_acc = refs[pos:]
    ik, iq = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    for h in range(g):
        sl = slice(h * d, (h + 1) * d)
        q, k, v = q_ref[0][:, sl], k_ref[0][:, sl], v_ref[0][:, sl]
        do = do_ref[0][:, sl]
        bq, bk = q.shape[0], k.shape[0]
        lse = lse_ref[h * bq:(h + 1) * bq, :1]
        delta = dl_ref[h * bq:(h + 1) * bq, :1]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if has_bias:
            s = s + b_ref[h if bias_per_head else 0].astype(jnp.float32)
        p = jnp.exp(s - lse)
        off = ((off_ref[0] if has_off else kv_len - q_len)
               if causal else None)
        valid = _tile_valid(iq, ik, bq, bk, kv_len, q_len, causal, off,
                            need_rows=True)
        if valid is not None:
            p = jnp.where(valid, p, 0.0)

        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        pv = p
        if dropout_rate > 0.0:
            iqo, iko = _dbo_shift(iq, ik, dbo_ref, has_dbo)
            keep = _keep_mask(seed_ref[0], iqo, iko, bq, bk, dropout_rate,
                              gb=pl.program_id(0) * g + h)
            inv_keep = 1.0 / (1.0 - dropout_rate)
            pv = jnp.where(keep, p * inv_keep, 0.0)
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        dv_acc[0, :, sl] = dv_acc[0][:, sl] + jax.lax.dot_general(
            pv.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc[0, :, sl] = dk_acc[0][:, sl] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(iq == nq - 1)
    def _():
        dk_ref[:] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_fused_kernel_nl(scale, causal, kv_len, q_len, dropout_rate, d,
                         g, has_off, self_delta, has_bias,
                         bias_per_head, has_dbo, refs):
    """Single-sweep backward for single-block grids (Sq, Sk each one
    tile): s and p are computed ONCE per head and all three gradients
    come out of the same sweep — the two-kernel split pays a redundant
    QKᵀ and exp pass per kernel, which at short sequence lengths is the
    dominant backward cost (BERT-Large: ~0.8 ms/layer two-kernel vs the
    fused sweep).

    ``self_delta``: with the full row in the tile, the kernel needs NO
    lse/delta operands at all — the softmax normalizer is recomputed
    from ``s`` (same max/sum the forward took) and
    ``delta = Σⱼ dp̃ⱼ·pⱼ ≡ Σⱼ doⱼ·oⱼ`` falls out of the dp tile the
    sweep already holds (with dropout: the masked dp̃, since
    o = (keep⊙p/(1−r))@v makes both sums run over the same terms).
    The lane-broadcast (g·bq, 128) f32 operand layout those inputs
    needed was a 128× HBM inflation — 2×64 MB per BERT-Large layer for
    512 KB of data, ~40% of the backward kernel's input bytes plus a
    materialized broadcast per layer (round-5 profile). Only the
    lse-cotangent path (`_fal_bwd`, the ring merge) still feeds an
    externally shifted delta."""
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    b_ref, seed_ref, dbo_ref, off_ref, pos = _unpack_common(
        refs, 3, has_bias, dropout_rate, has_dbo, has_off)
    if self_delta:
        do_ref, dq_ref, dk_ref, dv_ref = refs[pos:]
        lse_ref = dl_ref = None
    else:
        do_ref, lse_ref, dl_ref, dq_ref, dk_ref, dv_ref = refs[pos:]

    for h in range(g):
        sl = slice(h * d, (h + 1) * d)
        q, k, v = q_ref[0][:, sl], k_ref[0][:, sl], v_ref[0][:, sl]
        do = do_ref[0][:, sl]
        bq, bk = q.shape[0], k.shape[0]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if has_bias:
            s = s + b_ref[h if bias_per_head else 0].astype(jnp.float32)
        off = ((off_ref[0] if has_off else kv_len - q_len)
               if causal else None)
        valid = _tile_valid(0, 0, bq, bk, kv_len, q_len, causal, off,
                            need_rows=True)
        masked = valid is not None
        if self_delta:
            if masked:
                m = jnp.max(jnp.where(valid, s, NEG_INF), axis=1,
                            keepdims=True)
                e = jnp.where(valid, jnp.exp(s - m), 0.0)
                l = jnp.sum(e, axis=1, keepdims=True)
                # fully-masked rows (ring causal hops): l == 0 ⇒ p ≡ 0
                p = e * jnp.where(l > 0.0, 1.0 / l, 0.0)
            else:
                m = jnp.max(s, axis=1, keepdims=True)
                e = jnp.exp(s - m)
                l = jnp.sum(e, axis=1, keepdims=True)
                p = e * (1.0 / l)
        else:
            lse = lse_ref[h * bq:(h + 1) * bq, :1]
            p = jnp.exp(s - lse)
            if masked:
                p = jnp.where(valid, p, 0.0)

        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        pv = p
        if dropout_rate > 0.0:
            iqo, iko = _dbo_shift(0, 0, dbo_ref, has_dbo)
            keep = _keep_mask(seed_ref[0], iqo, iko, bq, bk, dropout_rate,
                              gb=pl.program_id(0) * g + h)
            inv_keep = 1.0 / (1.0 - dropout_rate)
            pv = jnp.where(keep, p * inv_keep, 0.0)
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        if self_delta:
            delta = jnp.sum(dp * p, axis=1, keepdims=True)
        else:
            delta = dl_ref[h * bq:(h + 1) * bq, :1]
        ds = (p * (dp - delta)).astype(q.dtype)
        dq_ref[0, :, sl] = (jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale).astype(
                dq_ref.dtype)
        dk_ref[0, :, sl] = (jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale).astype(
                dk_ref.dtype)
        dv_ref[0, :, sl] = jax.lax.dot_general(
            pv.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)


def _flash_bwd_fused_nl(qp, kp, vp, dop, lse_l, delta_l, nh, d, g,
                        scale, causal, sq, sk, sqp, skp, bq, bk, seed,
                        dropout_rate, causal_off=None, bias_p=None,
                        bias_mode=None, dbo=None):
    """``lse_l``/``delta_l`` None ⇒ the kernel self-computes the
    normalizer and delta (the single-block identity, no lane operands)."""
    self_delta = lse_l is None
    b = qp.shape[0]
    H = qp.shape[2]
    bh = b * nh
    gd = g * d
    hg = nh // g
    bias_per_head = bias_mode in ("head", "full")
    q_spec = pl.BlockSpec((1, sqp, gd), lambda t: (t // hg, 0, t % hg),
                          memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, skp, gd), lambda t: (t // hg, 0, t % hg),
                          memory_space=pltpu.VMEM)
    in_specs = [q_spec, k_spec, k_spec]
    args = [qp, kp, vp]
    _append_common(
        in_specs, args, bias_p=bias_p, bias_mode=bias_mode, g=g, hg=hg,
        bias_dims=(sqp, skp),
        bias_idx=lambda row: lambda t: (row(t), 0, 0),
        dropout_rate=dropout_rate, seed=seed, dbo=dbo,
        causal_off=causal_off)
    if self_delta:
        in_specs += [q_spec]
        args += [dop]
    else:
        lane_spec = pl.BlockSpec((g * bq, LANES), lambda t: (t, 0),
                                 memory_space=pltpu.VMEM)
        in_specs += [q_spec, lane_spec, lane_spec]
        args += [dop, lse_l, delta_l]

    dq, dk, dv = pl.pallas_call(
        lambda *refs: functools.partial(
            _bwd_fused_kernel_nl, scale, causal, sk, sq, dropout_rate,
            d, g, causal_off is not None, self_delta,
            bias_p is not None, bias_per_head, dbo is not None)(refs),
        grid=(bh // g,),
        in_specs=in_specs,
        out_specs=(q_spec, k_spec, k_spec),
        out_shape=(
            jax.ShapeDtypeStruct((b, sqp, H), qp.dtype),
            jax.ShapeDtypeStruct((b, skp, H), kp.dtype),
            jax.ShapeDtypeStruct((b, skp, H), kp.dtype),
        ),
        interpret=use_interpret(),
    )(*args)
    return dq[:, :sq, :], dk[:, :sk, :], dv[:, :sk, :]


def _flash_bwd_nl(q2, k2, v2, nh, d, lse, delta, do2, scale, causal,
                  block_q, block_k, dropout_rate=0.0, seed=None,
                  causal_off=None, delta_shifted=False, bias_g=None,
                  bias_mode=None, dbo=None):
    """Native-layout backward: operands/outputs (B, S, H); ``lse`` and
    ``delta`` arrive (B·H, Sq).

    ``delta_shifted``: the caller folded an lse cotangent into delta
    (`_fal_bwd`), so the single-block fused kernel may NOT self-compute
    it and must take the lane operands. In the default unshifted case
    the fused path drops lse/delta entirely (their producing graphs are
    dead-code-eliminated by XLA)."""
    b, sq, H = q2.shape
    sk = k2.shape[1]
    bh = b * nh
    block_q, block_k = _block_cap(block_q, block_k, bias_g is not None,
                                  dropout_rate)
    bq = _choose_block(block_q, sq)
    bk = _choose_block(block_k, sk, lane=True)
    bias_isz = bias_g.dtype.itemsize if bias_g is not None else 0
    bias_per_head = bias_mode in ("head", "full")
    g = _native_g(nh, d, dropout_rate, bq, bk, q2.dtype.itemsize,
                  bias_isz=bias_isz, bias_per_head=bias_per_head)
    bwd_vmem = None
    if (sq > bq or sk > bk) and bq * bk * 4 >= (1 << 22) and bh > g:
        # multi-block two-kernel path with 1024²-class f32 score tiles:
        # Mosaic multi-buffers the streamed blocks across head-group
        # boundaries when more groups follow (measured: the identical
        # kernel compiles at bh == g and OOMs at 19.6 MiB with 64
        # groups). The 16 MiB scoped-VMEM ceiling is a compiler
        # default, not the hardware's (v5e carries 128 MiB): raise the
        # limit for these two kernels instead of shrinking the tile —
        # the 1024-tile bwd measured 27% faster with serialized grads
        # (APEX_TPU_BWD_512=1 restores the capped-tile behavior), and
        # the raised path falls back to the cap whenever its own bwd
        # ledger — in/out blocks with cross-group triple-buffering,
        # both lane arrays, accumulators, and the live f32 score
        # temporaries — would exceed the raised limit.
        gd_ = g * d
        isz = q2.dtype.itemsize
        bwd_est = ((2 * bq + 2 * bk) * gd_ * isz * 3
                   + 2 * g * bq * LANES * 4 * 3
                   + 2 * bk * gd_ * isz * 2 + 2 * bk * gd_ * 4
                   + 3 * bq * bk * 4
                   + ((g if bias_per_head else 1) * bq * bk
                      * bias_isz * 3 if bias_isz else 0))
        if (os.environ.get("APEX_TPU_BWD_512") == "1"
                or bwd_est > 32 * 2 ** 20):
            bq = _choose_block(min(block_q, 512), sq)
            bk = _choose_block(min(block_k, 512), sk, lane=True)
            g = _native_g(nh, d, dropout_rate, bq, bk,
                          q2.dtype.itemsize, bias_isz=bias_isz,
                          bias_per_head=bias_per_head)
            g0_ = _native_g0(nh, d)
            while g > 2 * g0_ or (nh % g) or (g % g0_):
                nxt = g // 2
                if nxt < g0_ or nxt % g0_ or nh % nxt:
                    nxt = g0_
                g = nxt
        else:
            bwd_vmem = 32 * 2 ** 20  # est 24.1 MiB at the 1024² point
    sqp = -(-sq // bq) * bq
    skp = -(-sk // bk) * bk
    nq, nk = sqp // bq, skp // bk

    pad_s = lambda t, s_: t if t.shape[1] == s_ else jnp.pad(
        t, ((0, 0), (0, s_ - t.shape[1]), (0, 0)))
    qp, kp, vp = pad_s(q2, sqp), pad_s(k2, skp), pad_s(v2, skp)
    dop = pad_s(do2, sqp)

    if nq == 1 and nk == 1:
        # single-block grids: one fused sweep computes dq/dk/dv from a
        # single s/p evaluation. Its VMEM budget carries all seven
        # blocks + f32 score temporaries — shrink g until it fits, and
        # fall back to the two-kernel split when even the minimum
        # lane-aligned group does not (large-S fp32 shapes).
        isz = q2.dtype.itemsize

        def fused_est(g_):
            gd_ = g_ * d
            lanes = (2 * g_ * bq * LANES * 4 * 2 if delta_shifted
                     else bq * bk * 4)   # self-delta: one extra f32 tile
            bias_buf = ((g_ if bias_per_head else 1) * bq * bk
                        * bias_isz * 2 if bias_isz else 0)
            return ((2 * sqp + 2 * skp) * gd_ * isz * 2
                    + (sqp + 2 * skp) * gd_ * isz * 2
                    + bq * bk * 4 * 3 + lanes + bias_buf)

        g0 = _native_g0(nh, d)
        gf = g
        while gf > g0 and fused_est(gf) > 13 * 2 ** 20:
            # halve while staying a lane-aligned group that divides the
            # head count (an APEX_TPU_NATIVE_G override may start on a
            # non-power-of-two multiple of g0)
            nxt = gf // 2
            if nxt % g0 or nh % nxt:
                nxt = g0
            gf = nxt
        if fused_est(gf) <= 13 * 2 ** 20:
            if delta_shifted:
                lse_f = _lanes_nl(lse, bh, gf, 1, bq, sq)
                delta_f = _lanes_nl(delta, bh, gf, 1, bq, sq)
            else:
                lse_f = delta_f = None
            bias_p = (None if bias_g is None
                      else _pad_bias_nl(bias_g, sqp, skp))
            return _flash_bwd_fused_nl(qp, kp, vp, dop, lse_f, delta_f,
                                       nh, d, gf, scale, causal, sq, sk,
                                       sqp, skp, bq, bk, seed,
                                       dropout_rate, causal_off,
                                       bias_p=bias_p,
                                       bias_mode=bias_mode, dbo=dbo)

    gd = g * d
    lse_l = _lanes_nl(lse, bh, g, nq, bq, sq)
    delta_l = _lanes_nl(delta, bh, g, nq, bq, sq)

    hg = nh // g
    q_spec, k_spec = _head_specs(nh, g, bq, bk, gd)
    lane_spec = pl.BlockSpec((g * bq, LANES),
                             lambda t, i, j: (t * nq + i, 0),
                             memory_space=pltpu.VMEM)

    bias_p = None if bias_g is None else _pad_bias_nl(bias_g, sqp, skp)
    in_specs = [q_spec, k_spec, k_spec]
    args = [qp, kp, vp]
    _append_common(
        in_specs, args, bias_p=bias_p, bias_mode=bias_mode, g=g, hg=hg,
        bias_dims=(bq, bk),
        bias_idx=lambda row: lambda t, i, j: (row(t), i, j),
        dropout_rate=dropout_rate, seed=seed, dbo=dbo,
        causal_off=causal_off)
    in_specs += [q_spec, lane_spec, lane_spec]
    args += [dop, lse_l, delta_l]

    interp = use_interpret()
    extra = {}
    if bwd_vmem is not None and not interp:
        extra["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=bwd_vmem)
    dq = pl.pallas_call(
        lambda *refs: functools.partial(
            _bwd_dq_kernel_nl, scale, causal, sk, sq, dropout_rate, d,
            g, causal_off is not None, bias_p is not None,
            bias_per_head, dbo is not None)(refs),
        grid=(bh // g, nq, nk),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, sqp, H), q2.dtype),
        scratch_shapes=[pltpu.VMEM((1, bq, gd), jnp.float32)],
        interpret=interp,
        **extra,
    )(*args)

    # dk/dv: grid loops q innermost
    q_spec_k = pl.BlockSpec((1, bq, gd),
                            lambda t, j, i: (t // hg, i, t % hg),
                            memory_space=pltpu.VMEM)
    k_spec_k = pl.BlockSpec((1, bk, gd),
                            lambda t, j, i: (t // hg, j, t % hg),
                            memory_space=pltpu.VMEM)
    lane_spec_k = pl.BlockSpec((g * bq, LANES),
                               lambda t, j, i: (t * nq + i, 0),
                               memory_space=pltpu.VMEM)
    in_specs2 = [q_spec_k, k_spec_k, k_spec_k]
    args2 = [qp, kp, vp]
    _append_common(
        in_specs2, args2, bias_p=bias_p, bias_mode=bias_mode, g=g,
        hg=hg, bias_dims=(bq, bk),
        bias_idx=lambda row: lambda t, j, i: (row(t), i, j),
        dropout_rate=dropout_rate, seed=seed, dbo=dbo,
        causal_off=causal_off)
    in_specs2 += [q_spec_k, lane_spec_k, lane_spec_k]
    args2 += [dop, lse_l, delta_l]

    dk, dv = pl.pallas_call(
        lambda *refs: functools.partial(
            _bwd_dkv_kernel_nl, scale, causal, sk, sq, dropout_rate, d,
            g, causal_off is not None, bias_p is not None,
            bias_per_head, dbo is not None)(refs),
        grid=(bh // g, nk, nq),
        in_specs=in_specs2,
        out_specs=(k_spec_k, k_spec_k),
        out_shape=(jax.ShapeDtypeStruct((b, skp, H), k2.dtype),) * 2,
        scratch_shapes=[pltpu.VMEM((1, bk, gd), jnp.float32)] * 2,
        interpret=interp,
        **extra,
    )(*args2)

    return dq[:, :sq, :], dk[:, :sk, :], dv[:, :sk, :]


# --- public op --------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention(q, k, v, bias=None, scale=None, causal=False,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    dropout_rate=0.0, dropout_seed=None,
                    causal_offset=None):
    """Blockwise softmax attention.

    q: (B, Sq, H, D); k/v: (B, Sk, H, D); bias: optional additive
    (B|1, H|1, Sq|1, Sk|1) — the additive-mask variants of the reference
    (`self_multihead_attn_func.py` additive mask path). Returns
    (B, Sq, H, D). ``bias`` is differentiable (learned relative-position
    biases work); its gradient path materializes O(S²) scores, computed
    only when actually requested (see ``_bias_grad``).

    ``dropout_rate > 0`` applies *softmax* dropout (on the normalized
    probabilities) inside the kernel — the fused Philox dropout of the
    reference (`dropout.h:1-308`) — seeded by ``dropout_seed`` (int32
    scalar, typically drawn fresh per step from the training rng). The
    backward kernels regenerate the identical mask from the same seed;
    no mask tensor ever exists in HBM.

    ``causal_offset`` (int32 scalar, may be TRACED — e.g. derived from
    ``axis_index`` inside a ring hop) shifts the causal frontier: query
    i attends key j iff ``i + causal_offset >= j``. With ``None`` the
    frontier is bottom-right aligned (``Sk − Sq``). On the native-layout
    path the offset rides SMEM into the kernels so ring hops need no
    O(S²) additive bias; geometries that fall back to the bias path
    build the mask from the offset internally.
    """
    o, _ = _flash_attention_fwd_res(q, k, v, bias, dropout_seed, scale,
                                    causal, block_q, block_k,
                                    dropout_rate, causal_offset)
    return o


def _to3(q, k, v):
    b, sq, h, d = q.shape
    tr = lambda t: jnp.swapaxes(t, 1, 2).reshape(b * h, t.shape[1], d)
    return tr(q), tr(k), tr(v)


def _bias_flat(bias, b, h, sq, sk):
    """Shared validate/broadcast/flatten for both bias groupings:
    (B|1, H|1, Sq|1, Sk|1) → ((bb·bh, Sq, Sk), bb, bh). Size-1
    *sequence* dims can't ride the index map (blocks tile them) and
    are materialized to (Sq, Sk)."""
    bb, bh_ = bias.shape[0], bias.shape[1]
    if bb not in (1, b) or bh_ not in (1, h):
        raise ValueError(f"bias dims {bias.shape[:2]} must broadcast "
                         f"against (B={b}, H={h})")
    if bias.shape[2] not in (1, sq) or bias.shape[3] not in (1, sk):
        raise ValueError(f"bias dims {bias.shape[2:]} must broadcast "
                         f"against (Sq={sq}, Sk={sk})")
    bias = jnp.broadcast_to(bias, (bb, bh_, sq, sk))
    return bias.reshape(bb * bh_, sq, sk), bb, bh_


def _bias_group(bias, b, h, sq, sk):
    """(B|1, H|1, Sq|1, Sk|1) bias → ((G, Sq, Sk), idx_fn).

    The kernels index the bias through ``idx_fn(grid_b)`` in their
    BlockSpecs, so a (1, 1, Sq, Sk) causal bias (the ring-attention
    per-hop case) occupies exactly one copy in HBM instead of B·H
    score-sized buffers.
    """
    if bias is None:
        return None, None
    bias_g, bb, bh_ = _bias_flat(bias, b, h, sq, sk)
    if bb == 1 and bh_ == 1:
        idx = lambda g: 0
    elif bb == 1:                       # (1, H, ...) — per-head bias
        idx = lambda g: g % h
    elif bh_ == 1:                      # (B, 1, ...) — per-batch mask
        idx = lambda g: g // h
    else:
        idx = lambda g: g
    return bias_g, idx


def _seed_arr(dropout_seed, dropout_rate):
    if dropout_rate == 0.0:
        return None
    if not 0.0 < dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got "
                         f"{dropout_rate}")
    if dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    return jnp.asarray(dropout_seed, jnp.int32).reshape(-1)[:1]


def _off_arr(causal_offset, causal):
    if causal_offset is None:
        return None
    if not causal:
        raise ValueError("causal_offset requires causal=True")
    return jnp.asarray(causal_offset, jnp.int32).reshape(-1)[:1]


def _offset_bias(off_arr, sq, sk):
    """Fallback additive mask for geometries off the native path:
    built from the (possibly traced) offset scalar."""
    rows = jnp.arange(sq, dtype=jnp.int32)[:, None]
    cols = jnp.arange(sk, dtype=jnp.int32)[None, :]
    return jnp.where(rows + off_arr[0] >= cols, 0.0,
                     NEG_INF).reshape(1, 1, sq, sk)


def _tuned_qk(q, k, block_q, block_k, dropout_rate):
    """Trace-time tuning-DB consult for the attention family.

    Applies only when the caller left (block_q, block_k) at the
    defaults — an explicit override always wins — and never under
    dropout (the Philox mask hash is a function of block coordinates;
    tuned blocks would be a *different* mask than the one the dropout
    contract documents). Called identically from the fwd residual path
    and ``_fa_bwd`` so both directions realize the same tuned blocks.
    Exact-key miss returns the defaults untouched: bit-identical HLO,
    pinned by the ``autotune/no-extra-dispatch`` compile-check case.
    """
    if (block_q, block_k) != (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K):
        return block_q, block_k
    if dropout_rate > 0.0:
        return block_q, block_k
    from apex_tpu.ops import autotune
    b, sq, h, d = q.shape
    blocks = autotune.lookup_blocks(
        "attention", (b, sq, k.shape[1], h, d), q.dtype)
    if not blocks:
        return block_q, block_k
    return (int(blocks.get("block_q", block_q)),
            int(blocks.get("block_k", block_k)))


def _flash_attention_fwd_res(q, k, v, bias, dropout_seed, scale, causal,
                             block_q, block_k, dropout_rate,
                             causal_offset=None, dbo=None):
    block_q, block_k = _tuned_qk(q, k, block_q, block_k, dropout_rate)
    b, sq, h, d = q.shape
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    seed = _seed_arr(dropout_seed, dropout_rate)
    off = _off_arr(causal_offset, causal)
    if off is not None and bias is not None:
        raise ValueError("causal_offset cannot combine with a bias")
    if dbo is not None:
        if bias is not None or _native_g0(h, d) is None:
            # block offsets shift the dropout hash's global
            # coordinates; the dense bias-grad replica and the
            # transposed fallback do not reconstruct them — fail
            # loudly rather than silently diverge from the
            # single-device mask (docs/parallel.md)
            raise ValueError("dropout_block_offset requires the "
                             "native attention path and no bias")
        cq, ck = _block_cap(block_q, block_k, False, dropout_rate)
        bq_r = _choose_block(cq, sq)
        bk_r = _choose_block(ck, k.shape[1], lane=True)
        if (bq_r, bk_r) != (DROPOUT_TILE, DROPOUT_TILE):
            # the offsets are expressed in DROPOUT_TILE units; a
            # geometry whose realized blocks differ (short shards,
            # overridden block sizes) would apply them in the wrong
            # units and silently draw a different mask
            raise ValueError(
                f"dropout_block_offset requires {DROPOUT_TILE}-sized "
                f"kernel blocks; this geometry realizes "
                f"({bq_r}, {bk_r}) — shard lengths must be multiples "
                f"of {DROPOUT_TILE}")
    if _native_g0(h, d) is not None:
        # native-layout path: (B, S, H) operands straight through — no
        # transpose copies, no D zero-pad (see the native-kernel block).
        # An additive bias rides the native grid as (g|1, bq, bk)
        # blocks (round-5; biased MHA no longer pays the transpose tax)
        bias_nl, bias_mode = _bias_group_nl(bias, b, h, sq, k.shape[1])
        q2 = q.reshape(b, sq, h * d)
        k2 = k.reshape(b, k.shape[1], h * d)
        v2 = v.reshape(b, v.shape[1], h * d)
        o2, lse = _flash_fwd_nl(q2, k2, v2, h, d, scale, causal,
                                block_q, block_k, dropout_rate, seed,
                                causal_off=off, bias_g=bias_nl,
                                bias_mode=bias_mode, dbo=dbo)
        o = o2.reshape(b, sq, h, d)
        return o, (q, k, v, bias, dropout_seed, o, lse, causal_offset)
    eff_bias, eff_causal = bias, causal
    if off is not None:
        # no native path for this geometry: the offset becomes an
        # additive mask (exactly what a caller would have built)
        eff_bias, eff_causal = _offset_bias(off, sq, k.shape[1]), False
    q3, k3, v3 = _to3(q, k, v)
    bias_g, bidx = _bias_group(eff_bias, b, h, sq, k.shape[1])
    o3, lse = _flash_fwd(q3, k3, v3, bias_g, bidx, scale, eff_causal,
                         block_q, block_k, dropout_rate, seed)
    o = jnp.swapaxes(o3.reshape(b, h, sq, d), 1, 2)
    return o, (q, k, v, bias, dropout_seed, o, lse, causal_offset)


def _fa_fwd(q, k, v, bias, scale, causal, block_q, block_k, dropout_rate,
            dropout_seed, causal_offset):
    o, res = _flash_attention_fwd_res(q, k, v, bias, dropout_seed, scale,
                                      causal, block_q, block_k,
                                      dropout_rate, causal_offset)
    return o, res


def _fa_bwd(scale, causal, block_q, block_k, dropout_rate, res, do):
    q, k, v, bias, dropout_seed, o, lse, causal_offset = res
    block_q, block_k = _tuned_qk(q, k, block_q, block_k, dropout_rate)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale_ = scale if scale is not None else 1.0 / np.sqrt(d)
    seed = _seed_arr(dropout_seed, dropout_rate)
    if _native_g0(h, d) is not None:
        bias_nl, bias_mode = _bias_group_nl(bias, b, h, sq, sk)
        q2 = q.reshape(b, sq, h * d)
        k2 = k.reshape(b, sk, h * d)
        v2 = v.reshape(b, sk, h * d)
        do2 = do.reshape(b, sq, h * d)
        # delta = rowsum(do·o) per head, straight from the (B, S, H)
        # layout: only the tiny (B, S, nh) per-head sums transpose
        delta = jnp.sum(
            (do.astype(jnp.float32) * o.astype(jnp.float32)), axis=-1)
        delta = jnp.swapaxes(delta, 1, 2).reshape(b * h, sq)
        dq2, dk2, dv2 = _flash_bwd_nl(
            q2, k2, v2, h, d, lse, delta, do2, scale_, causal,
            block_q, block_k, dropout_rate=dropout_rate, seed=seed,
            causal_off=_off_arr(causal_offset, causal),
            bias_g=bias_nl, bias_mode=bias_mode)
        dbias = None if bias is None else _bias_grad(
            q, k, v, bias, o, lse, do, scale_, causal,
            dropout_rate=dropout_rate, seed=seed,
            block_q=block_q, block_k=block_k)
        return (dq2.reshape(b, sq, h, d), dk2.reshape(b, sk, h, d),
                dv2.reshape(b, sk, h, d), dbias, None, None)
    eff_bias, eff_causal = bias, causal
    off = _off_arr(causal_offset, causal)
    if off is not None:
        eff_bias, eff_causal = _offset_bias(off, sq, sk), False
    q3, k3, v3 = _to3(q, k, v)
    bias_g, bidx = _bias_group(eff_bias, b, h, sq, k.shape[1])
    o3 = jnp.swapaxes(o, 1, 2).reshape(b * h, sq, d)
    do3 = jnp.swapaxes(do, 1, 2).reshape(b * h, sq, d)
    dq3, dk3, dv3 = _flash_bwd(q3, k3, v3, bias_g, bidx, o3, lse, do3,
                               scale_, eff_causal, block_q, block_k,
                               dropout_rate=dropout_rate, seed=seed)
    un = lambda t, s_: jnp.swapaxes(t.reshape(b, h, s_, d), 1, 2)
    dbias = None if bias is None else _bias_grad(
        q, k, v, bias, o, lse, do, scale_, causal,
        dropout_rate=dropout_rate, seed=seed,
        block_q=block_q, block_k=block_k)
    return un(dq3, sq), un(dk3, sk), un(dv3, sk), dbias, None, None


def _keep_mask_dense(seed, b, h, sq, sk, bq, bk, rate):
    """Host-side (dense) replica of :func:`_keep_mask` over the full
    (B·H, Sq, Sk) score tensor — bitwise identical to what the kernels
    generate, reconstructed from global coordinates via the block
    decomposition. Only used by the bias-gradient path, which is dense
    anyway."""
    gb = jax.lax.broadcasted_iota(jnp.uint32, (b * h, sq, sk), 0)
    qr = jax.lax.broadcasted_iota(jnp.uint32, (b * h, sq, sk), 1)
    kc = jax.lax.broadcasted_iota(jnp.uint32, (b * h, sq, sk), 2)
    return _mix_keep(seed, gb, qr // bq, kc // bk, qr % bq, kc % bk, rate)


def _bias_grad(q, k, v, bias, o, lse, do, scale, causal, *,
               dropout_rate=0.0, seed=None, block_q=DEFAULT_BLOCK_Q,
               block_k=DEFAULT_BLOCK_K, delta_shift=None):
    """Cotangent for a learned additive bias (e.g. relative-position
    biases): ds = p * (dp - delta), reduced to the bias's broadcast
    shape. Recomputes p from the saved lse so no extra softmax pass is
    needed — but it DOES materialize the (B, H, Sq, Sk) score matrix, the
    very thing flash attention avoids. That is inherent to producing a
    dense dbias; XLA dead-code-eliminates this whole computation whenever
    the caller does not differentiate w.r.t. the bias, so pure-mask users
    pay nothing."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s = s + bias.astype(jnp.float32)
    if causal:
        mask = np.tril(np.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse.reshape(b, h, sq)[..., None])
    dp = jnp.einsum("bqhd,bkhd->bhqk", do.astype(jnp.float32),
                    v.astype(jnp.float32))
    if dropout_rate > 0.0:
        # mirror the kernels' block choice exactly via the SHARED cap
        # (the mask hash is a function of block coordinates — a
        # different bq/bk is a different mask)
        cq, ck = _block_cap(block_q, block_k, True, dropout_rate)
        bq = _choose_block(cq, sq)
        bk = _choose_block(ck, sk, lane=True)
        keep = _keep_mask_dense(seed[0], b, h, sq, sk, bq, bk,
                                dropout_rate).reshape(b, h, sq, sk)
        dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                       # (b, sq, h)
    delta = jnp.swapaxes(delta, 1, 2)              # (b, h, sq)
    if delta_shift is not None:
        # the lse-cotangent fold: ds = p*(dp - (delta - dlse))
        delta = delta - delta_shift.astype(jnp.float32)
    ds = p * (dp - delta[..., None])
    for axis in range(4):
        if bias.shape[axis] == 1:
            ds = jnp.sum(ds, axis=axis, keepdims=True)
    return ds.astype(bias.dtype)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def attention_reference(q, k, v, bias=None, scale=None, causal=False):
    """Pure-jnp oracle — the reference's ``impl='default'`` python path
    (`self_multihead_attn_func.py:6-232`). Runs with the O1 raw-op patch
    suspended: its fp32 einsums are the point of the oracle."""
    from apex_tpu.amp.functional_patch import suspend
    with suspend():
        return _attention_reference(q, k, v, bias, scale, causal)


def _attention_reference(q, k, v, bias, scale, causal):
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        sq, sk = s.shape[-2:]
        mask = np.tril(np.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)


def mask_softmax_dropout(scores, mask=None, dropout_rate=0.0,
                         rng=None, deterministic=True):
    """Standalone (masked) softmax(+dropout) on explicit scores —
    ``fast_mask_softmax_dropout_func``
    (`apex/contrib/multihead_attn/fast_mask_softmax_dropout_func.py`)."""
    s = scores.astype(jnp.float32)
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(rng, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    return p.astype(scores.dtype)


# --- lse-returning variant (sequence-parallel building block) ---------------

def flash_attention_lse(q, k, v, bias=None, scale=None, causal=False,
                        block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                        *, dropout_rate=0.0, dropout_seed=None,
                        causal_offset=None, dropout_block_offset=None):
    """Like :func:`flash_attention` but returns ``(out, lse)`` with
    ``lse`` (B, H, Sq) differentiable — the building block ring attention
    needs to merge partial results across sequence shards.
    ``causal_offset`` shifts the causal frontier like
    :func:`flash_attention`'s (ring hops pass their traced global
    offset so no O(S²) hop bias is ever built on the native path).
    ``dropout_block_offset`` — a traced (2,) int32 of global
    (q-block, k-block) offsets — shifts the counter-based dropout
    hash's block coordinates, so a sequence shard reproduces exactly
    the keep mask the single-device call would have generated at the
    same global coordinates (ring hops pass their ring position; the
    reference's fused dropout has no distributed counterpart,
    `apex/contrib/csrc/multihead_attn/dropout.h:1-308`).

    ``dropout_rate``/``dropout_seed``/``causal_offset``/
    ``dropout_block_offset`` are keyword-only: they were inserted ahead
    of ``causal_offset`` historically, so a positional caller would
    silently bind an offset to ``dropout_rate`` — now it fails loudly
    at the call site (ADVICE r5).
    """
    return _flash_attention_lse(q, k, v, bias, scale, causal, block_q,
                                block_k, dropout_rate, dropout_seed,
                                causal_offset, dropout_block_offset)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attention_lse(q, k, v, bias, scale, causal, block_q, block_k,
                         dropout_rate, dropout_seed, causal_offset,
                         dropout_block_offset):
    # positional custom_vjp core — custom_vjp cannot resolve
    # keyword-only parameters, hence the public wrapper above
    (o, lse), _ = _fal_fwd(q, k, v, bias, scale, causal, block_q,
                           block_k, dropout_rate, dropout_seed,
                           causal_offset, dropout_block_offset)
    return o, lse


def _fal_fwd(q, k, v, bias, scale, causal, block_q, block_k,
             dropout_rate, dropout_seed, causal_offset,
             dropout_block_offset):
    if dropout_rate > 0.0 and _native_g0(q.shape[2], q.shape[3]) is None:
        # the lse variant's backward has no transposed dropout path —
        # fail at trace time, not at the first jax.grad deep in a step
        raise NotImplementedError(
            "flash_attention_lse dropout requires the native attention "
            "path (lane-groupable heads)")
    dbo = (None if dropout_block_offset is None
           else jnp.asarray(dropout_block_offset, jnp.int32).reshape(2))
    o, res = _flash_attention_fwd_res(q, k, v, bias, dropout_seed,
                                      scale, causal, block_q, block_k,
                                      dropout_rate, causal_offset, dbo)
    b, sq, h, _ = q.shape
    return (o, res[6].reshape(b, h, sq)), res + (dbo,)


def _fal_bwd(scale, causal, block_q, block_k, dropout_rate, res, cot):
    do, dlse = cot
    q, k, v, bias, dropout_seed, o, lse, causal_offset, dbo = res
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale_ = scale if scale is not None else 1.0 / np.sqrt(d)
    seed = _seed_arr(dropout_seed, dropout_rate)
    # d lse/d s = p, so the lse cotangent folds into the delta term:
    # ds = p*(dp - delta) + p*dlse = p*(dp - (delta - dlse))
    if _native_g0(h, d) is not None:
        bias_nl, bias_mode = _bias_group_nl(bias, b, h, sq, sk)
        q2 = q.reshape(b, sq, h * d)
        k2 = k.reshape(b, sk, h * d)
        v2 = v.reshape(b, sk, h * d)
        do2 = do.reshape(b, sq, h * d)
        delta = jnp.sum(
            (do.astype(jnp.float32) * o.astype(jnp.float32)), axis=-1)
        delta = jnp.swapaxes(delta, 1, 2).reshape(b * h, sq)
        delta = delta - dlse.reshape(b * h, sq).astype(jnp.float32)
        dq2, dk2, dv2 = _flash_bwd_nl(
            q2, k2, v2, h, d, lse, delta, do2, scale_, causal,
            block_q, block_k, dropout_rate=dropout_rate, seed=seed,
            causal_off=_off_arr(causal_offset, causal),
            delta_shifted=True, bias_g=bias_nl, bias_mode=bias_mode,
            dbo=dbo)
        dbias = None if bias is None else _bias_grad(
            q, k, v, bias, o, lse, do, scale_, causal,
            dropout_rate=dropout_rate, seed=seed,
            block_q=block_q, block_k=block_k,
            delta_shift=dlse.reshape(b, h, sq))
        return (dq2.reshape(b, sq, h, d), dk2.reshape(b, sk, h, d),
                dv2.reshape(b, sk, h, d), dbias, None, None, None)
    if dropout_rate > 0.0:
        raise NotImplementedError(
            "flash_attention_lse dropout requires the native attention "
            "path (lane-groupable heads); this geometry fell back to "
            "the transposed kernels")
    eff_bias, eff_causal = bias, causal
    off = _off_arr(causal_offset, causal)
    if off is not None:
        eff_bias, eff_causal = _offset_bias(off, sq, sk), False
    q3, k3, v3 = _to3(q, k, v)
    bias_g, bidx = _bias_group(eff_bias, b, h, sq, k.shape[1])
    o3 = jnp.swapaxes(o, 1, 2).reshape(b * h, sq, d)
    do3 = jnp.swapaxes(do, 1, 2).reshape(b * h, sq, d)
    dlse3 = dlse.reshape(b * h, sq)
    dq3, dk3, dv3 = _flash_bwd(q3, k3, v3, bias_g, bidx, o3, lse, do3,
                               scale_, eff_causal, block_q, block_k,
                               delta_shift=dlse3)
    un = lambda t, s_: jnp.swapaxes(t.reshape(b, h, s_, d), 1, 2)
    dbias = None if bias is None else _bias_grad(
        q, k, v, bias, o, lse, do, scale_, causal,
        block_q=block_q, block_k=block_k,
        delta_shift=dlse.reshape(b, h, sq))
    return (un(dq3, sq), un(dk3, sk), un(dv3, sk), dbias, None, None,
            None)


_flash_attention_lse.defvjp(_fal_fwd, _fal_bwd)
