#!/usr/bin/env python
"""Cluster control-plane audit: fence zombies, coordinate rewinds.

The asserting sibling of ``chaos_audit.py --cpu8`` for the cluster axis
(``run_tier1.sh --smoke`` runs it; exit status is the verdict). Four
claims over :mod:`apex_tpu.cluster`, each printed and asserted — the
in-process twins of the two multi-process acceptance tests in
``tests/test_cluster.py`` (TestZombieAcceptance /
TestCoordinatedRewindAcceptance, ``-m slow``):

(a) **zombies are fenced** — a rank paused through an escalation +
    relaunch (its lease expired, the generation bumped past it) has
    BOTH its late checkpoint write and its retention delete refused
    by the generation fence, leaving the new epoch's
    ``latest_checkpoint`` untouched and the refusals on the cluster
    event stream;
(b) **coordinated rewind is bitwise** — chaos poisons rank 1's
    committed params (rank 0 stays clean); rank 1's guard detects and
    posts a signed intent, rank 0 joins the round, both resolve to the
    SAME target (oldest good step wins — rank 0 honors the cluster
    verdict over its own newer good checkpoint), the generation bumps
    EXACTLY once, and both ranks' post-rewind losses and final params
    are **bitwise-equal** to a fault-free oracle pair that never saw
    the poison window — the chaos_audit claim (b), now cross-rank;
(c) **split-brain is refused** — a rank claiming a generation the
    cluster never committed (the ``cluster:split_brain`` chaos site)
    has its intent refused at verification, its checkpoint fence
    checks refused (a future claim is split-brain, not seniority) and
    its CAS bump refused at commit, with the evidence on the stream;
(d) **the event stream validates** — every emitted event passes
    ``check_metrics_schema.py --kind cluster`` and all four event
    kinds are present (a hung-collective probe supplies the
    ``collective_hang`` edge).

Usage: python scripts/cluster_audit.py --cpu8
       python scripts/cluster_audit.py        # same audit, local devs
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from scripts.chaos_audit import (BATCH, LR, SEED, _init_params,  # noqa: E402
                                 _make_cfg, _make_step)

N_STEPS = 14
SAVE_EVERY = 2
POISON_STEP = 7


def _logger(path):
    from apex_tpu import monitor
    return monitor.MetricsLogger(sinks=[],
                                 cluster_sink=monitor.JSONLSink(path))


# --- (a) zombie fence ---------------------------------------------------------

def audit_zombie(tmp, events_path):
    import jax.numpy as jnp

    from apex_tpu import ckpt, cluster

    cdir = os.path.join(tmp, "cluster_a")
    root = os.path.join(tmp, "ck_a")
    logger = _logger(events_path)

    zombie = cluster.ClusterMembership(cdir, rank=1, ttl_s=60.0,
                                       event_sink=logger.record_cluster)
    assert zombie.join() == 0
    mgr_z = ckpt.CheckpointManager(root, fence=zombie, rank=0,
                                   process_count=1, keep=0)
    mgr_z.save(3, {"w": jnp.full((8,), 3.0)}, block=True)
    mgr_z.wait()
    assert ckpt.read_manifest(
        ckpt.latest_checkpoint(root))["generation"] == 0

    # the rank pauses (a long SIGSTOP / VM migration, seen from
    # outside): its lease expires — the dead-member signal the
    # coordinated shrink acts on
    zombie.lease.expire_now()
    watcher = cluster.ClusterMembership(cdir, rank=0)
    watcher.join()
    assert watcher.expired_ranks() == [1]

    # escalation + relaunch: the controller fences out generation 0
    gen = cluster.relaunch(cdir, reason="elastic_restart:1",
                           event_sink=logger.record_cluster)
    assert gen == 1
    fresh = cluster.ClusterMembership(cdir, rank=0,
                                      event_sink=logger.record_cluster)
    assert fresh.join() == 1
    mgr_f = ckpt.CheckpointManager(root, fence=fresh, rank=0,
                                   process_count=1, keep=0)
    mgr_f.save(5, {"w": jnp.full((8,), 5.0)}, block=True)
    mgr_f.wait()
    latest_before = ckpt.latest_checkpoint(root)
    assert latest_before == ckpt.step_dir(root, 5)

    # ---- the zombie resumes and tries to write / gc ----
    refused = 0
    mgr_z.save(99, {"w": jnp.full((8,), 99.0)}, block=True)
    try:
        mgr_z.wait()
    except cluster.StaleGenerationError:
        refused += 1
    try:
        ckpt.gc_checkpoints(root, keep=1, fence=zombie)
    except cluster.StaleGenerationError:
        refused += 1
    assert refused == 2, f"zombie mutations not fenced ({refused}/2)"
    assert not os.path.exists(ckpt.step_dir(root, 99)), \
        "the refused write left debris"
    assert ckpt.latest_checkpoint(root) == latest_before
    assert len(ckpt.committed_steps(root)) == 2, \
        "the refused gc deleted checkpoints"
    logger.close()
    with open(events_path) as f:
        acts = [json.loads(l).get("action") for l in f if l.strip()]
    assert "refused_write" in acts and "refused_delete" in acts, acts
    print("  (a) zombie fenced: lease expiry detected, write + delete "
          "both refused after the generation bump; "
          "latest_checkpoint untouched")


# --- (b) coordinated rewind ---------------------------------------------------

def _run_pair(imgroot, workdir, cluster_dir, jstep, cfg, mesh, *,
              n_steps, poison_step=None, oracle_skip=None, tag,
              event_sink=None):
    """Two logical ranks trained in lockstep in ONE process: per-rank
    data shard, checkpoint tree, guard policy, and cluster membership
    over a SHARED cluster directory. Deterministic resolution makes
    the sequential drive equivalent to the concurrent one — every
    rank computes the same decision from the same intent files (the
    multi-process version is the ``-m slow`` acceptance test)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import ckpt, cluster, guard
    from apex_tpu.data.pipeline import ImageFolderSource

    shd = NamedSharding(mesh, P("data"))
    members, coords, mgrs, policies, srcs = [], [], [], [], []
    for r in (0, 1):
        m = cluster.ClusterMembership(cluster_dir, rank=r,
                                      event_sink=event_sink)
        m.join()
        members.append(m)
        coords.append(cluster.RecoveryCoordinator(
            m, barrier_timeout_s=30.0, event_sink=event_sink))
        mgr = ckpt.CheckpointManager(
            os.path.join(workdir, f"ck_{tag}_r{r}"), fence=m, rank=0,
            process_count=1, keep=0)
        mgrs.append(mgr)
        policies.append(guard.GuardPolicy(manager=mgr,
                                          rewind_budget=2))
        srcs.append(ImageFolderSource(imgroot, batch=BATCH, size=16,
                                      seed=SEED, workers=2,
                                      process_index=r,
                                      process_count=2))
    plan = None
    if poison_step is not None:
        plan = guard.FaultPlan(seed=1).add(poison_step, "params",
                                           "nan", rank=1)
    harness = guard.ChaosHarness(plan, rank=1) if plan else None

    params = [_init_params(mesh) for _ in (0, 1)]
    gs = [guard.guard_init(cfg) for _ in (0, 1)]
    its = [None, None]

    def pull(r):
        while True:
            if its[r] is None:
                its[r] = srcs[r].epoch()
            try:
                return next(its[r])
            except StopIteration:
                its[r] = None

    losses = [[], []]
    rewound = [[], []]
    for step in range(n_steps):
        for r in (0, 1):
            if oracle_skip and srcs[r].cursor_index() == oracle_skip[0]:
                srcs[r].skip_batches(oracle_skip[1])
                its[r] = None
            x, y = pull(r)
            xd = jax.device_put(x, shd)
            yd = jax.device_put(np.asarray(y, np.int32), shd)
            params[r], gs[r], loss = jstep(params[r], gs[r], xd, yd,
                                           jax.numpy.int32(0))
            losses[r].append(np.float32(np.asarray(loss)))
            if step % SAVE_EVERY == 0:
                mgrs[r].save(step, {"params": params[r], "gs": gs[r]},
                             extra={"cursor": srcs[r].state()})
                mgrs[r].wait()
            members[r].heartbeat()
            if harness is not None and r == 1:
                params[r] = harness.post_step(step, params[r])
        # --- the coordination sweep (both ranks crossed the step) ---
        needs = [policies[r].update(step, gs[r]).kind == "rewind"
                 for r in (0, 1)]
        if any(needs):
            likes = [{"params": params[r], "gs": gs[r]}
                     for r in (0, 1)]
            for r in (0, 1):
                if needs[r]:
                    coords[r].propose(
                        action="rewind", step=step,
                        good_step=policies[r].probe_good_step(
                            likes[r]))
            for r in (0, 1):
                if not needs[r]:
                    assert coords[r].peer_requested(), \
                        "healthy rank failed to notice the intent"
            for r in (0, 1):
                dec, restored = coords[r].run_round(
                    policies[r], step, likes[r], srcs[r],
                    expect_ranks=[0, 1])
                tree, manifest = restored
                params[r], gs[r] = tree["params"], tree["gs"]
                its[r] = None
                rewound[r].append((step, dec.target_step,
                                   dec.generation,
                                   dec.new_generation))
    for s in srcs:
        s.close()
    return {"losses": losses, "params": params, "rewound": rewound,
            "final_cursors": [s.cursor_index() for s in srcs],
            "members": members}


def audit_coordinated_rewind(tmp, imgroot, jstep, cfg, mesh,
                             events_path):
    import numpy as np

    from apex_tpu import cluster

    logger = _logger(events_path)
    cdir_f = os.path.join(tmp, "cluster_b")
    faulted = _run_pair(imgroot, tmp, cdir_f, jstep, cfg, mesh,
                        n_steps=N_STEPS, poison_step=POISON_STEP,
                        tag="faulted",
                        event_sink=logger.record_cluster)
    oracle = _run_pair(imgroot, tmp, os.path.join(tmp, "cluster_bo"),
                       jstep, cfg, mesh, n_steps=N_STEPS - 2,
                       oracle_skip=(POISON_STEP, 2), tag="oracle")

    # both ranks agreed on one round: detect at 8, target 6 (rank 1's
    # ckpt@8 captured the corruption; rank 0 honors the cluster
    # verdict over its own good step 8), generation 0 -> 1
    for r in (0, 1):
        assert faulted["rewound"][r] == [(POISON_STEP + 1,
                                          POISON_STEP - 1, 0, 1)], \
            (r, faulted["rewound"][r])
        assert oracle["rewound"][r] == []
        f_tail = [l.tobytes().hex()
                  for l in faulted["losses"][r][POISON_STEP + 2:]]
        o_tail = [l.tobytes().hex()
                  for l in oracle["losses"][r][POISON_STEP:]]
        assert f_tail == o_tail, (
            f"rank {r} post-rewind losses diverge from the oracle")
        for k in ("w", "b"):
            a = np.asarray(faulted["params"][r][k])
            b = np.asarray(oracle["params"][r][k])
            assert np.array_equal(a, b), \
                f"rank {r} final params[{k}] not bitwise vs oracle"
        assert (faulted["final_cursors"][r]
                == oracle["final_cursors"][r])
    assert cluster.read_generation(cdir_f) == 1
    logger.close()
    with open(events_path) as f:
        evs = [json.loads(l) for l in f if l.strip()]
    bumps = [e for e in evs if e["kind"] == "cluster_generation"
             and e["action"] == "bump"]
    assert len(bumps) == 1, "the leader alone commits the bump"
    resolves = [e for e in evs if e.get("action") == "resolve"]
    assert len(resolves) == 2
    assert all(e["decided"] == "rewind"
               and e["target_step"] == POISON_STEP - 1
               for e in resolves)
    print(f"  (b) coordinated rewind: rank 1 poisoned at step "
          f"{POISON_STEP}, detected at {POISON_STEP + 1}; both ranks "
          f"resolved to target {POISON_STEP - 1} (oldest good wins), "
          f"generation bumped exactly once, post-rewind losses + "
          f"final params BITWISE == fault-free oracle on both ranks")


# --- (c) split-brain refused --------------------------------------------------

def audit_split_brain(tmp, events_path):
    from apex_tpu import cluster, guard

    cdir = os.path.join(tmp, "cluster_c")
    logger = _logger(events_path)
    honest = cluster.ClusterMembership(cdir, rank=0,
                                       event_sink=logger.record_cluster)
    honest.join()
    rogue = cluster.ClusterMembership(cdir, rank=1)
    rogue.join()

    # the chaos site: rank 1 claims an epoch the cluster never agreed
    plan = guard.FaultPlan(seed=2).add(3, "cluster", "split_brain",
                                       rank=1, arg=2.0)
    guard.ChaosHarness(plan, rank=1).post_step(3, {}, membership=rogue)
    assert rogue.generation == 2
    assert cluster.read_generation(cdir) == 0

    c_honest = cluster.RecoveryCoordinator(
        honest, barrier_timeout_s=1.0,
        event_sink=logger.record_cluster)
    c_rogue = cluster.RecoveryCoordinator(rogue, barrier_timeout_s=1.0)
    c_rogue.propose(action="rewind", step=5, good_step=4)
    # the claimed epoch's intent never counts at the committed one —
    # forged down to the committed prefix it is refused with evidence
    assert not c_honest.peer_requested()
    src = cluster.intent_path(cdir, 2, 1)
    dst = cluster.intent_path(cdir, 0, 1)
    os.replace(src, dst)
    assert c_honest.pending() == {}
    assert c_honest.last_refused == (1,)
    # the checkpoint fence refuses the claim too — a never-committed
    # generation is split-brain, not seniority
    try:
        rogue.check("commit")
        raise AssertionError("split-brain fence check was not refused")
    except cluster.StaleGenerationError:
        pass
    # and the claim cannot commit itself: the CAS bump refuses it
    try:
        rogue.bump("split-brain")
        raise AssertionError("split-brain bump was not refused")
    except cluster.StaleGenerationError:
        pass
    assert cluster.read_generation(cdir) == 0
    logger.close()
    with open(events_path) as f:
        evs = [json.loads(l) for l in f if l.strip()]
    refusals = [e for e in evs if e.get("action") == "refused_intent"]
    assert refusals and "claims generation 2" in refusals[-1]["reason"]
    print("  (c) split-brain refused: the claimed generation's intent "
          "failed verification, its fence check and CAS bump were "
          "refused; the committed epoch never moved")


# --- (d) collective deadline + stream validation ------------------------------

def audit_stream(tmp, event_paths):
    from apex_tpu import cluster, trace

    # a hung collective (span instance open past the deadline) is
    # named and escalated — the missing cluster_coord edge
    events_path = os.path.join(tmp, "cluster_d.jsonl")
    logger = _logger(events_path)
    tracer = trace.Tracer()
    trips = []

    class _Trip:
        def trip(self, reason):
            trips.append(reason)

    cd = cluster.CollectiveDeadline(tracer, deadline_s=0.05,
                                    escalation=_Trip(),
                                    event_sink=logger.record_cluster)
    with tracer:
        with trace.step(0):
            with trace.span("ddp/sync_gradients", kind="collective"):
                time.sleep(0.12)
                ev = cd.poll_once()
                assert ev is not None and ev["action"] == \
                    "collective_hang"
            assert cd.poll_once() is None   # it closed: not hung
    assert trips == ["collective:ddp/sync_gradients"]
    logger.close()
    event_paths = list(event_paths) + [events_path]

    from scripts.check_metrics_schema import check_cluster_lines
    kinds, n = set(), 0
    for path in event_paths:
        with open(path) as f:
            lines = [l for l in f if l.strip()]
        errors = check_cluster_lines(lines)
        assert not errors, (f"{path} schema violations:\n"
                            + "\n".join(errors))
        n += len(lines)
        kinds |= {json.loads(l)["kind"] for l in lines}
    want = {"cluster_lease", "cluster_generation", "cluster_fence",
            "cluster_coord"}
    assert kinds >= want, f"missing event kinds: {want - kinds}"
    print(f"  (d) hung collective named + escalated; {n} cluster "
          f"events across {len(event_paths)} streams validate "
          f"(--kind cluster), all 4 kinds present")


def main_audit():
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from apex_tpu.data.pipeline import make_fake_imagefolder

    devs = jax.devices()
    if len(devs) < 8:
        raise SystemExit("audit needs 8 devices — pass --cpu8 for the "
                         "8-device virtual mesh")
    mesh = Mesh(np.array(devs[:8]), ("data",))
    cfg = _make_cfg()
    jstep = _make_step(cfg)

    tmp = tempfile.mkdtemp(prefix="apex_cluster_audit_")
    imgroot = make_fake_imagefolder(os.path.join(tmp, "imgs"),
                                    n_classes=4, per_class=8, size=64,
                                    seed=0)
    ev_a = os.path.join(tmp, "cluster_a.jsonl")
    ev_b = os.path.join(tmp, "cluster_b.jsonl")
    ev_c = os.path.join(tmp, "cluster_c.jsonl")
    audit_zombie(tmp, ev_a)
    audit_coordinated_rewind(tmp, imgroot, jstep, cfg, mesh, ev_b)
    audit_split_brain(tmp, ev_c)
    audit_stream(tmp, [ev_a, ev_b, ev_c])
    print("cluster audit ok")


def main():
    if "--cpu8" in sys.argv:
        import jax
        from apex_tpu import _compat
        jax.config.update("jax_platforms", "cpu")
        _compat.request_cpu_devices(8)
    main_audit()


if __name__ == "__main__":
    main()
