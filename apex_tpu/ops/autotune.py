"""Kernel autotuner: Pallas block-shape search + the committed tuning DB.

ROADMAP item 3. The repo *measures* its kernel debt precisely —
``prof.roofline.worst_gaps()`` names the fingerprinted candidates,
APX104 flags the statically-provable tile padding — and this module
*closes* gaps: a sweep engine enumerates a per-family grid of Pallas
block shapes, times each candidate best-of-N under
``compile_watch.autotune_scope()`` (sweep compiles are accounted, never
mistaken for steady-state retraces), and persists winners in a committed
JSON tuning DB (``scripts/kernel_tuning_db.json``) that every kernel
family's dispatch path consults at trace time.

Fingerprint key (apexlint-style — stable identity, never measured
numbers)::

    family|dims|dtype|chip        e.g.  attention|2x256x256x4x64|float32|cpu

``dims`` is the family's *logical* problem shape, joined with ``x``:

- ``attention``  — (B, Sq, Sk, H, D) of :func:`~apex_tpu.ops.flash_attention`
- ``mlp``        — (rows, d0, d1, ..., dN) of :func:`~apex_tpu.ops.fused_mlp`
- ``layer_norm`` — (rows, H) of the flattened input
- ``xentropy``   — (rows, V) of the flattened logits
- ``optimizer``  — (n_elements,) of the arena flat buffer fed to
  ``ops/_dispatch.launch`` (the multi-tensor optimizer launcher)

Consultation contract (pinned by the ``autotune/no-extra-dispatch``
compile-check case): **exact-key hit → tuned blocks; miss → the current
hardcoded defaults, bit-identical HLO**. Nearest-miss never matches. A
DB entry whose recorded identity no longer re-fingerprints to its key is
*stale* and is refused loudly at load time (:class:`StaleTuningEntry`)
instead of silently applied.

Env control: ``APEX_TPU_AUTOTUNE=off|db|sweep`` (default ``db``).
``off`` skips the DB entirely (trajectory bitwise-identical to the
pre-tuner code); ``db`` consults the committed DB; ``sweep`` behaves
like ``db`` and additionally marks the process as a sweep run for
``scripts/kernel_tune.py``. See docs/profiling.md#autotuner.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "FAMILIES", "MODES", "StaleTuningEntry", "TuningEntry", "TuningDB",
    "fingerprint", "chip_kind", "mode", "default_db_path", "active_db",
    "set_db", "use_db", "reload_db", "lookup_blocks", "tuned_rows",
    "counters", "reset_counters", "recent_consults", "db_stats",
    "candidate_grid", "measure_candidate", "sweep_entry", "tune_report",
    "tuned_lint_shapes",
]

#: the five fused-op families apex_tpu ships tunable kernels for — the
#: same family names prof.roofline.classify_family assigns, so
#: worst_gaps rows join DB entries without a translation table
FAMILIES = ("attention", "mlp", "layer_norm", "xentropy", "optimizer")

MODES = ("off", "db", "sweep")

_ENV = "APEX_TPU_AUTOTUNE"
_ENV_DB = "APEX_TPU_AUTOTUNE_DB"

#: sublane multiple per itemsize — the TPU tile grid (same table the
#: APX104 lint rule uses); tuned row blocks must sit on it
_SUBLANE = {4: 8, 2: 16, 1: 32}


class StaleTuningEntry(ValueError):
    """A tuning-DB entry whose recorded identity does not re-fingerprint
    to its key — refused loudly instead of silently applied."""


def mode() -> str:
    """The autotune mode from ``APEX_TPU_AUTOTUNE`` (default ``db``)."""
    m = os.environ.get(_ENV, "db")
    if m not in MODES:
        raise ValueError(
            f"{_ENV}={m!r} is not one of {MODES} — refusing to guess")
    return m


def chip_kind() -> str:
    """Canonical chip key for fingerprints: the attached device kind
    lowercased with spaces collapsed (``tpu_v5_lite``), or ``cpu`` off
    TPU — so interpret-mode sweep artifacts never shadow on-chip ones."""
    import jax
    if jax.default_backend() != "tpu":
        return "cpu"
    kind = getattr(jax.devices()[0], "device_kind", "tpu")
    return str(kind).strip().lower().replace(" ", "_")


def _dtype_name(dtype) -> str:
    """Canonical dtype token — derived from the dtype object itself
    (``float32``/``bfloat16``/...), never a jax-version-dependent repr,
    so the same logical shape fingerprints identically across jax
    versions."""
    import numpy as np
    try:
        return np.dtype(dtype).name
    except TypeError:
        return str(dtype)


def fingerprint(family: str, dims: Sequence[int], dtype,
                chip: Optional[str] = None) -> str:
    """``family|dims|dtype|chip`` — the DB key. Pure arithmetic on
    python ints + the canonical dtype name: stable across processes,
    jax versions, and reruns."""
    if family not in FAMILIES:
        raise ValueError(f"unknown kernel family {family!r} "
                         f"(one of {FAMILIES})")
    dims_s = "x".join(str(int(d)) for d in dims)
    return (f"{family}|{dims_s}|{_dtype_name(dtype)}"
            f"|{chip if chip is not None else chip_kind()}")


# --- the tuning DB -----------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TuningEntry:
    """One committed winner: identity + block decision + sweep
    provenance (the measured evidence; never part of the key)."""

    family: str
    dims: Tuple[int, ...]
    dtype: str
    chip: str
    block: Dict[str, int]              # e.g. {"block_rows": 256} or
                                       # {"block_q": 512, "block_k": 512}
    sweep: Dict[str, Any] = dataclasses.field(default_factory=dict)
    #: normalized dot-shape signatures (APX104's ``scope`` strings) this
    #: tuned kernel covers — lets the lint pass keep a DB-satisfied
    #: shape at info severity instead of escalating
    lint_sigs: Tuple[str, ...] = ()

    @property
    def fingerprint(self) -> str:
        return fingerprint(self.family, self.dims, self.dtype, self.chip)

    def to_json(self) -> Dict[str, Any]:
        out = {"family": self.family, "dims": list(self.dims),
               "dtype": self.dtype, "chip": self.chip,
               "block": dict(self.block)}
        if self.sweep:
            out["sweep"] = dict(self.sweep)
        if self.lint_sigs:
            out["lint_sigs"] = list(self.lint_sigs)
        return out

    @classmethod
    def from_json(cls, rec: Dict[str, Any]) -> "TuningEntry":
        return cls(family=str(rec["family"]),
                   dims=tuple(int(d) for d in rec["dims"]),
                   dtype=str(rec["dtype"]), chip=str(rec["chip"]),
                   block={str(k): int(v)
                          for k, v in rec["block"].items()},
                   sweep=dict(rec.get("sweep", {})),
                   lint_sigs=tuple(rec.get("lint_sigs", ())))


def default_db_path() -> str:
    """``scripts/kernel_tuning_db.json`` next to the package root, or
    the ``APEX_TPU_AUTOTUNE_DB`` override."""
    env = os.environ.get(_ENV_DB)
    if env:
        return env
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "scripts", "kernel_tuning_db.json")


class TuningDB:
    """Exact-key fingerprint → :class:`TuningEntry` map with committed
    JSON round-trip. Load validates every entry's recorded identity
    against its key — a mismatch (hand-edited shape, renamed family, a
    key from an older dims convention) raises :class:`StaleTuningEntry`
    naming the offending key."""

    def __init__(self, entries: Optional[Dict[str, TuningEntry]] = None,
                 path: Optional[str] = None):
        self.entries: Dict[str, TuningEntry] = dict(entries or {})
        self.path = path

    def __len__(self) -> int:
        return len(self.entries)

    @classmethod
    def load(cls, path: str) -> "TuningDB":
        """Parse + validate a DB file. A missing file is an empty DB
        (the consult path must work on a fresh clone); a *corrupt or
        stale* file is an error — tuned block shapes silently falling
        back would regress perf with no signal."""
        try:
            with open(path) as f:
                data = json.load(f)
        except FileNotFoundError:
            return cls(path=path)
        if not isinstance(data, dict) or not isinstance(
                data.get("entries"), dict):
            raise ValueError(
                f"{path}: not a tuning DB (expected "
                '{"version": 1, "entries": {fingerprint: {...}}})')
        entries: Dict[str, TuningEntry] = {}
        for key, rec in data["entries"].items():
            try:
                e = TuningEntry.from_json(rec)
            except (KeyError, TypeError, ValueError) as err:
                raise StaleTuningEntry(
                    f"{path}: entry {key!r} is malformed ({err}) — "
                    f"re-run scripts/kernel_tune.py to regenerate it"
                ) from err
            if e.fingerprint != key:
                raise StaleTuningEntry(
                    f"{path}: stale tuning entry {key!r}: its recorded "
                    f"identity re-fingerprints to {e.fingerprint!r} — "
                    f"the entry predates a shape/dims convention change "
                    f"and is refused, not silently applied; re-run "
                    f"scripts/kernel_tune.py --update-db to re-measure")
            entries[key] = e
        return cls(entries, path=path)

    def save(self, path: Optional[str] = None) -> str:
        path = path or self.path
        if not path:
            raise ValueError("no path to save the tuning DB to")
        body = {"version": 1,
                "entries": {k: self.entries[k].to_json()
                            for k in sorted(self.entries)}}
        with open(path, "w") as f:
            json.dump(body, f, indent=1, sort_keys=False)
            f.write("\n")
        self.path = path
        return path

    def lookup(self, fp: str) -> Optional[TuningEntry]:
        """EXACT-key lookup — a nearest miss (off-by-one dim, other
        dtype, other chip) returns None and takes the default path."""
        return self.entries.get(fp)

    def add(self, entry: TuningEntry) -> None:
        self.entries[entry.fingerprint] = entry

    def families(self) -> List[str]:
        return sorted({e.family for e in self.entries.values()})

    def stats(self) -> Dict[str, Any]:
        return {"entries": len(self.entries),
                "tuned_families": self.families(),
                "path": self.path}


# --- runtime consult state ---------------------------------------------------

_lock = threading.Lock()
_state: Dict[str, Any] = {"db": None}
_counters = {"hits": 0, "misses": 0}
_recent: List[Tuple[str, bool]] = []      # last consults, for audits
_RECENT_CAP = 256


def active_db() -> TuningDB:
    """The DB consulted at trace time — lazily loaded from
    :func:`default_db_path` (committed repo DB) unless a test installed
    one via :func:`set_db`/:func:`use_db`."""
    with _lock:
        db = _state["db"]
        if db is None:
            db = _state["db"] = TuningDB.load(default_db_path())
        return db


def set_db(db: Optional[TuningDB]) -> None:
    """Install ``db`` as the active DB (None → lazy-reload the default
    on next consult)."""
    with _lock:
        _state["db"] = db


def reload_db(path: Optional[str] = None) -> TuningDB:
    db = TuningDB.load(path or default_db_path())
    set_db(db)
    return db


@contextlib.contextmanager
def use_db(db_or_path):
    """Temporarily consult a specific DB (tests, sweep verification)."""
    db = (db_or_path if isinstance(db_or_path, TuningDB)
          else TuningDB.load(db_or_path))
    with _lock:
        prev = _state["db"]
        _state["db"] = db
    try:
        yield db
    finally:
        with _lock:
            _state["db"] = prev


def lookup_blocks(family: str, dims: Sequence[int],
                  dtype) -> Optional[Dict[str, int]]:
    """The trace-time consult every dispatch seam calls: exact-key DB
    hit → the tuned block dict; miss (or ``APEX_TPU_AUTOTUNE=off``) →
    None, leaving the call site's hardcoded default untouched so the
    compiled HLO is bit-identical to the pre-tuner program."""
    if mode() == "off":
        return None
    fp = fingerprint(family, dims, dtype)
    entry = active_db().lookup(fp)
    with _lock:
        _counters["hits" if entry else "misses"] += 1
        _recent.append((fp, entry is not None))
        del _recent[:-_RECENT_CAP]
    return dict(entry.block) if entry else None


def tuned_rows(family: str, dims: Sequence[int], dtype, *,
               key: str = "block_rows", lo: int = 16, hi: int = 1024,
               multiple: Optional[int] = None) -> Optional[int]:
    """Consult + validate a tuned row-block value. An entry carrying an
    illegal value (off the dtype's sublane grid, outside [lo, hi])
    warns naming the offending fingerprint and returns None — the call
    site keeps its heuristic, loudly, never a Mosaic tiling crash."""
    import numpy as np
    blocks = lookup_blocks(family, dims, dtype)
    if not blocks or key not in blocks:
        return None
    r = int(blocks[key])
    if multiple is None:
        try:
            itemsize = np.dtype(dtype).itemsize
        except TypeError:
            itemsize = 4
        # at least 16: the row-block kernels size for the widest (bf16)
        # tiling whatever the array dtype (see layer_norm._row_block)
        multiple = max(16, _SUBLANE.get(itemsize, 8))
    if r < lo or r > hi or r % multiple:
        warnings.warn(
            f"tuning entry {fingerprint(family, dims, dtype)}: "
            f"{key}={r} is off the legal grid (multiple of {multiple} "
            f"in [{lo}, {hi}]) — falling back to the built-in "
            f"heuristic; re-run scripts/kernel_tune.py --update-db",
            RuntimeWarning, stacklevel=3)
        return None
    return r


def counters() -> Dict[str, int]:
    with _lock:
        return dict(_counters)


def reset_counters() -> None:
    with _lock:
        _counters["hits"] = _counters["misses"] = 0
        del _recent[:]


def recent_consults() -> List[Tuple[str, bool]]:
    """(fingerprint, hit) of recent :func:`lookup_blocks` calls — the
    kernel_tune audit asserts the exact-key hit landed."""
    with _lock:
        return list(_recent)


def db_stats() -> Dict[str, Any]:
    """The bench columns, off the already-loaded DB + this process's
    consult counters — zero compiles, zero device access."""
    st = active_db().stats()
    st.update(counters())
    return st


# --- candidate grids ---------------------------------------------------------

def candidate_grid(family: str, dims: Sequence[int],
                   dtype) -> List[Dict[str, int]]:
    """The block-shape candidates the sweep times for one problem
    shape. Defaults lead each grid (the sweep must always be able to
    fall back to "keep the default"); every candidate is legal for the
    family's Mosaic tiling rules at that shape."""
    import numpy as np
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        itemsize = 4
    sub = _SUBLANE.get(itemsize, 8)

    if family == "attention":
        # (block_q, block_k) over the proven tile range; 1024x1024 is
        # the current DEFAULT_BLOCK_Q/K. Clamp to the sequence dims —
        # a block larger than the (8-aligned) sequence is just the
        # whole-sequence block the dispatch would pick anyway.
        _, sq, sk = int(dims[0]), int(dims[1]), int(dims[2])
        qs = sorted({min(b, -(-sq // 8) * 8)
                     for b in (256, 512, 1024)}, reverse=True)
        ks = sorted({min(b, -(-sk // 8) * 8)
                     for b in (256, 512, 1024)}, reverse=True)
        out = [{"block_q": 1024, "block_k": 1024}]
        out += [{"block_q": q, "block_k": k} for q in qs for k in ks
                if {"block_q": q, "block_k": k} not in out]
        return out

    if family == "optimizer":
        # BLOCK_ROWS of the _dispatch launcher. Arena buffers are
        # padded to BUFFER_MULTIPLE = 512*128 elements, so rows is
        # always a multiple of 512 — every candidate here divides it.
        # All are multiples of the widest (int8: 32) sublane grid.
        return [{"block_rows": r} for r in (512, 256, 128, 64)]

    # the row-block families: rows multiples of the dtype's sublane
    # grid (16 covers fp32+bf16 — the grid the in-tree heuristics use)
    step = max(16, sub)
    rows = [r for r in (256, 128, 64, 32, 16) if r % step == 0]
    return [{"block_rows": r} for r in rows]


# --- the sweep engine --------------------------------------------------------

def measure_candidate(build: Callable[[], Any], args: Sequence[Any], *,
                      iters: int = 3, warmup: int = 1) -> float:
    """Compile (under ``autotune_scope`` — accounted, never a retrace)
    and time one candidate best-of-``iters``; returns microseconds."""
    import jax
    from apex_tpu.prof import compile_watch

    jitted = jax.jit(build())
    with compile_watch.autotune_scope():
        compiled = jitted.lower(*args).compile()
    for _ in range(max(warmup, 0)):
        jax.block_until_ready(compiled(*args))
    best = float("inf")
    for _ in range(max(iters, 1)):
        t0 = time.perf_counter()
        jax.block_until_ready(compiled(*args))
        best = min(best, time.perf_counter() - t0)
    return best * 1e6


def sweep_entry(family: str, dims: Sequence[int], dtype,
                build: Callable[[Dict[str, int]], Tuple[Callable, Sequence]],
                *, candidates: Optional[List[Dict[str, int]]] = None,
                iters: int = 3, warmup: int = 1,
                on_candidate: Optional[Callable[[Dict, float], None]] = None,
                ) -> TuningEntry:
    """Time every candidate of :func:`candidate_grid` (best-of-N each)
    and return the winner as a :class:`TuningEntry` with full sweep
    provenance. ``build(block)`` returns ``(fn, args)`` for one
    candidate; the first grid entry is the current default (its time is
    recorded as ``default_us``)."""
    grid = candidates if candidates is not None else candidate_grid(
        family, dims, dtype)
    if not grid:
        raise ValueError(f"empty candidate grid for {family} {dims}")
    timed: List[Tuple[Dict[str, int], float]] = []
    for block in grid:
        fn, args = build(dict(block))
        us = measure_candidate(lambda: fn, args, iters=iters,
                               warmup=warmup)
        timed.append((dict(block), us))
        if on_candidate is not None:
            on_candidate(dict(block), us)
    default_us = timed[0][1]
    best_block, best_us = min(timed, key=lambda t: t[1])
    import jax
    return TuningEntry(
        family=family, dims=tuple(int(d) for d in dims),
        dtype=_dtype_name(dtype), chip=chip_kind(),
        block=best_block,
        sweep={"mode": ("compiled" if jax.default_backend() == "tpu"
                        else "interpret"),
               "iters": int(iters), "n_candidates": len(timed),
               "best_us": round(best_us, 3),
               "default_us": round(default_us, 3),
               "candidates": [{"block": b, "us": round(us, 3)}
                              for b, us in timed]})


# --- joins: roofline worst_gaps + APX104 -------------------------------------

def tuned_lint_shapes(db: Optional[TuningDB] = None) -> List[str]:
    """Normalized dot-shape signatures covered by DB entries — the
    ``tuned_shapes`` input of ``lint.hlo_pass.tile_findings`` (a
    DB-satisfied shape stays at info severity instead of escalating)."""
    db = db if db is not None else active_db()
    out: List[str] = []
    for e in db.entries.values():
        out.extend(s for s in e.lint_sigs if s not in out)
    return out


def tune_report(db: Optional[TuningDB] = None,
                worst_gaps: Optional[Sequence[Dict[str, Any]]] = None,
                tile_findings: Optional[Sequence[Any]] = None,
                ) -> Dict[str, Any]:
    """Join DB entries against ``roofline_report.worst_gaps()`` rows
    and APX104 tile-padding findings: per roofline candidate, is its
    family covered by a committed tuning entry, and what closure did
    the sweep predict (``default_us - best_us`` per occurrence)?

    ``worst_gaps`` rows are the dicts ``RooflineReport.worst_gaps()``
    returns (fingerprint ``family|opcode|scope|shape``); coverage joins
    on the shared ``family`` axis — the DB key's dims are logical call
    shapes, the roofline's are compiled-op shapes, so family (+ the
    per-entry predicted closure) is the honest join, not a fake
    exact-key equality between two different key spaces.
    """
    db = db if db is not None else active_db()
    by_family: Dict[str, List[TuningEntry]] = {}
    for e in db.entries.values():
        by_family.setdefault(e.family, []).append(e)

    candidates = []
    for g in (worst_gaps or ()):
        fam = g.get("family")
        entries = by_family.get(fam, [])
        predicted = sum(
            max(0.0, float(e.sweep.get("default_us", 0.0))
                - float(e.sweep.get("best_us", 0.0)))
            for e in entries)
        candidates.append({
            "fingerprint": g.get("fingerprint"),
            "family": fam, "op": g.get("op"),
            "measured_us": g.get("measured_us"),
            "attainable_us": g.get("attainable_us"),
            "gap_us": g.get("gap_us"),
            "covered": bool(entries),
            "db_entries": sorted(e.fingerprint for e in entries),
            "predicted_closure_us": round(predicted, 3),
        })

    apx104 = []
    sigs = set(tuned_lint_shapes(db))
    for f in (tile_findings or ()):
        scope = getattr(f, "scope", None) or (
            f.get("scope") if isinstance(f, dict) else None)
        apx104.append({"scope": scope,
                       "bytes": getattr(f, "bytes", None) if not
                       isinstance(f, dict) else f.get("bytes"),
                       "db_satisfied": scope in sigs})

    covered = [c for c in candidates if c["covered"]]
    return {
        "tuned_families": db.families(),
        "n_entries": len(db.entries),
        "candidates": candidates,
        "n_candidates": len(candidates),
        "n_covered": len(covered),
        "uncovered_families": sorted(
            {c["family"] for c in candidates if not c["covered"]}),
        "apx104": apx104,
        "workflow": ("scripts/kernel_tune.py --update-db sweeps the "
                     "grid and commits winners to "
                     "scripts/kernel_tuning_db.json"),
    }


def tune_event(action: str, fp: str, family: str, *,
               rank: int = 0, **extra) -> Dict[str, Any]:
    """``kind="tune"`` event for the roofline channel
    (``check_metrics_schema.py --kind roofline`` validates)."""
    ev = {"kind": "tune", "rank": rank, "action": action,
          "fingerprint": fp, "family": family}
    ev.update(extra)
    return ev
