"""Fault escalation: silent rank → checkpoint-save → crash-dump → exit.

The failure mode this closes (ROADMAP item 5a): a peer host dies, this
rank wedges inside a collective, and the job burns chips silently
forever — no exception, no SIGTERM, nothing for the flight recorder to
hook. The :class:`~apex_tpu.trace.HangWatchdog` already *detects* that
state; an :class:`EscalationPolicy` wired into its ``on_stall`` turns
detection into recovery:

1. **checkpoint**: durably commit the newest already-fetched host
   snapshot (``CheckpointManager.save_last_snapshot`` — zero device
   interaction, so the wedged runtime cannot block it);
2. **crash dump**: the flight-recorder forensics, metrics not fetched
   (same hung-runtime rule the watchdog applies);
3. **exit nonzero**: ``os._exit(exit_code)`` — deliberately not
   ``sys.exit``: a normal interpreter teardown would block on the
   wedged runtime's atexit hooks, which is exactly the hang being
   escaped. Default code 75 (``EX_TEMPFAIL``: transient, retry) is what
   :func:`apex_tpu.parallel.launch.elastic_run` recognizes as
   shrink-and-continue.

The same policy handles *graceful* preemption: wire it as
``FlightRecorder(escalation=...)`` and the SIGTERM handler saves the
snapshot checkpoint before the crash dump — a managed-cluster
preemption becomes a committed checkpoint instead of lost work.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, Optional

__all__ = ["EscalationPolicy", "PreemptionError", "ESCALATION_EXIT_CODE"]

#: EX_TEMPFAIL — the "transient failure, please retry" convention; the
#: elastic_run restart loop treats this exit as shrink-and-continue.
ESCALATION_EXIT_CODE = 75


class PreemptionError(RuntimeError):
    """Raised (instead of exiting) by a ``mode="raise"`` policy — the
    in-process signal :func:`apex_tpu.parallel.launch.elastic_run`
    catches to restart on a smaller mesh."""

    def __init__(self, reason: str, ckpt_path: Optional[str] = None):
        super().__init__(f"escalated ({reason}); "
                         f"checkpoint={ckpt_path or 'none'}")
        self.reason = reason
        self.ckpt_path = ckpt_path


class EscalationPolicy:
    """checkpoint-save → crash-dump → nonzero exit, as one callable.

    ::

        policy = ckpt.EscalationPolicy(mgr, recorder=recorder)
        wd = trace.HangWatchdog(30.0, recorder=recorder, tracer=tracer,
                                on_stall=policy)
        recorder.escalation = policy      # SIGTERM → save-then-dump

    ``mode="exit"`` (default) hard-exits with ``exit_code`` — correct
    for a wedged rank (see module docstring), and the only mode that
    can actually interrupt one: use it for ``HangWatchdog(on_stall=)``.
    ``mode="raise"`` raises :class:`PreemptionError` — for *main-thread*
    call sites (SIGTERM handlers, manual invocation, an in-process
    ``elastic_run`` train loop that calls the policy itself). Invoked
    from a non-main thread (the watchdog daemon), a raise could not
    unwind the wedged main thread and would be swallowed by the
    watchdog loop's guard — so there the raise-mode policy completes
    the checkpoint+dump, records :attr:`tripped`, and returns; polling
    drivers observe ``tripped`` (the unit tests use exactly this).
    """

    def __init__(self, manager, *, recorder=None,
                 exit_code: int = ESCALATION_EXIT_CODE,
                 mode: str = "exit",
                 event_sink: Optional[Callable[[Dict], None]] = None):
        if mode not in ("exit", "raise"):
            raise ValueError(f"mode must be 'exit' or 'raise', "
                             f"got {mode!r}")
        self.manager = manager
        self.recorder = recorder
        self.exit_code = int(exit_code)
        self.mode = mode
        self.event_sink = event_sink or getattr(manager, "event_sink",
                                                None)
        #: set to the escalation reason once tripped (observable by
        #: polling drivers even in exit mode, for tests)
        self.tripped: Optional[str] = None

    def _emit(self, event: Dict) -> None:
        if self.event_sink is None:
            return
        try:
            from apex_tpu.ckpt.format import tag_generation
            rank = getattr(self.manager, "rank", 0)
            ev = tag_generation(
                dict(event, rank=rank, wall_time=time.time()),
                getattr(self.manager, "fence", None))
            self.event_sink(ev)
        except Exception:
            pass

    def _escalate(self, reason: str, *, exit_after: bool,
                  dump: bool = True) -> Optional[str]:
        self.tripped = reason
        path = None
        try:
            path = self.manager.save_last_snapshot(reason)
        except Exception:
            path = None
        snap = getattr(self.manager, "last_host_snapshot", None)
        self._emit({
            "kind": "ckpt_escalation", "reason": reason,
            "path": path, "step": (snap.step if snap else None),
            "exit_code": self.exit_code if exit_after else None,
            "action": ("checkpoint+dump+exit" if exit_after
                       else "checkpoint+dump"),
        })
        if dump and self.recorder is not None:
            try:
                self.recorder.dump(reason=f"escalation:{reason}")
            except Exception:
                pass
        return path

    # -- hooks -----------------------------------------------------------------

    def trip(self, reason: str) -> None:
        """Escalate NOW with a caller-supplied reason — the public entry
        point for in-band policies (:class:`apex_tpu.guard.GuardPolicy`
        trips it with ``"guard:..."`` when the rewind budget runs out).
        Same ladder as the watchdog path: checkpoint-save → crash-dump →
        ``os._exit`` / :class:`PreemptionError` per ``mode``."""
        import threading
        exit_after = self.mode == "exit"
        path = self._escalate(reason, exit_after=exit_after)
        if exit_after:
            os._exit(self.exit_code)
        if threading.current_thread() is not threading.main_thread():
            # raise-mode off the main thread: a raise here could not
            # unwind the wedged main thread (and the watchdog loop
            # would swallow it) — checkpoint+dump are done, `tripped`
            # is the observable (see class docstring)
            return
        raise PreemptionError(reason, path)

    def on_stall(self, event: Optional[Dict] = None) -> None:
        """HangWatchdog ``on_stall`` hook: the silent-rank path."""
        return self.trip("stall")

    def on_preempt(self) -> Optional[str]:
        """FlightRecorder SIGTERM hook: graceful preemption. Saves the
        snapshot checkpoint and returns (the recorder's own handler
        dumps + chains afterwards — so no dump here) — never exits:
        SIGTERM delivery already has an exit path."""
        return self._escalate("preempt", exit_after=False, dump=False)

    # the policy object itself is the on_stall callable
    __call__ = on_stall
