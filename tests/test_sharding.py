"""Per-axis sharding observatory: HloSharding parsing, axis
disposition, the declared-override escape hatch, HBM closure, the
what-if forecaster, and the sharding event channel with its schema
negative twins (ISSUE-17).
"""

import io
import json
import os
import sys

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import monitor
from apex_tpu.lint.mesh_model import parse_mesh_spec
from apex_tpu.prof.sharding import (parameter_shardings,
                                    parse_hlo_sharding, shard_report)

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _schema():
    from scripts.check_metrics_schema import check_sharding_lines
    return check_sharding_lines


# --- HloSharding text parsing ------------------------------------------------

class TestParseHloSharding:
    def test_replicated(self):
        tiles, form = parse_hlo_sharding("replicated", 8)
        assert form == "replicated" and tiles == [0] * 8

    def test_maximal(self):
        tiles, form = parse_hlo_sharding("maximal device=3", 8)
        assert form == "maximal" and len(set(tiles)) == 1

    def test_iota(self):
        tiles, form = parse_hlo_sharding("devices=[8,1]<=[8]", 8)
        assert form == "tiled" and tiles == list(range(8))

    def test_iota_transpose(self):
        # arange(8).reshape(2,4).T ravels to [0,4,1,5,2,6,3,7]: device
        # order in tile sequence — tiles[device] inverts it
        tiles, form = parse_hlo_sharding("devices=[4,2]<=[2,4]T(1,0)", 8)
        assert form == "tiled"
        order = [0, 4, 1, 5, 2, 6, 3, 7]
        assert tiles == [order.index(d) for d in range(8)]

    def test_explicit_device_list(self):
        tiles, form = parse_hlo_sharding("devices=[8,1]0,1,2,3,4,5,6,7", 8)
        assert form == "tiled" and tiles == list(range(8))

    def test_last_tile_dim_replicate(self):
        # 2 tiles x 4-way replication: devices 0-3 share tile 0
        tiles, form = parse_hlo_sharding(
            "devices=[2,1,4]<=[8] last_tile_dim_replicate", 8)
        assert form == "tiled"
        assert tiles == [0, 0, 0, 0, 1, 1, 1, 1]
        assert len(set(tiles)) == 2

    def test_unparsed_is_conservative(self):
        tiles, form = parse_hlo_sharding("devices=[4,1]<=[4]", 8)
        assert tiles is None and form == "unparsed"
        tiles, form = parse_hlo_sharding("gibberish", 8)
        assert tiles is None and form == "unparsed"


# --- disposition + report on a real compiled program -------------------------

def _compile_flat(mesh):
    from apex_tpu.trace.spans import span

    init = {"w": jnp.ones((32, 8), jnp.float32)}
    batch = jnp.ones((16 * 8, 32), jnp.float32)

    def step(params, x):
        h = x @ params["w"]
        loss = jnp.sum(h * h)
        with span("ddp/sync_gradients", kind="collective"):
            loss = jax.lax.pmean(loss, "data")
        return loss

    mapped = jax.shard_map(step, mesh=mesh,
                           in_specs=(P(), P("data")), out_specs=P(),
                           check_vma=False)
    return jax.jit(mapped).lower(init, batch).compile()


class TestShardReport:
    @pytest.fixture(scope="class")
    def flat_report(self, mesh8):
        compiled = _compile_flat(mesh8)
        return shard_report(compiled, parse_mesh_spec("ici8"))

    def test_annotation_disposition(self, flat_report):
        sr = flat_report
        by_path = {r.path or r.name: r for r in sr.records}
        batch = [r for r in sr.records if r.shard_factor == 8]
        assert batch, f"no x8-sharded arg found: {list(by_path)}"
        assert all(r.sharded_by("data") for r in batch)
        weights = [r for r in sr.records
                   if r.shard_factor == 1 and r.bytes >= 32 * 8 * 4]
        assert weights and not any(r.sharded_by("data") for r in weights)
        assert all(r.source == "annotation" for r in batch)
        assert all(r.source in ("annotation", "none") for r in weights)

    def test_closure_and_axis_bytes(self, flat_report):
        sr = flat_report
        ok, worst = sr.closure()
        assert ok, f"per-axis table does not close: {worst:.4f}"
        b = sr.axis_bytes("data")
        assert b["sharded_bytes"] > 0 and b["replicated_bytes"] > 0
        assert (b["sharded_bytes"] + b["replicated_bytes"]
                == sr.attributed_total())
        with pytest.raises(KeyError):
            sr.axis_bytes("no_such_axis")

    def test_declared_override(self, mesh8):
        """The ZeRO escape hatch: an override re-declares an
        annotation-replicated arg as sharded; the row carries
        source="declared" and the global bytes scale by the axis size."""
        compiled = _compile_flat(mesh8)
        mm = parse_mesh_spec("ici8")
        plain = shard_report(compiled, mm)
        ovr = shard_report(compiled, mm, overrides={r"w": ("data",)})
        declared = [r for r in ovr.records if r.source == "declared"]
        assert declared and all(r.sharded_by("data") for r in declared)
        assert all(r.shard_factor == 8 for r in declared)
        # the ratio drops: declared shards divide their global bytes
        ratio_p = plain.class_shard_ratio("params")
        ratio_o = ovr.class_shard_ratio("params")
        assert ratio_o < ratio_p <= 1.0

    def test_forecast_axes(self, flat_report):
        sr = flat_report
        fc = sr.forecast_axes({"tp": 2, "pp": 2})
        assert fc["total_forecast"] <= fc["total_now"]
        pc = fc["per_class"]["params"]
        # params are fully replicated on the flat mesh: eligible > 0
        # and the forecast shrinks them by the factor product (ceil)
        assert pc["eligible"] > 0
        assert pc["forecast"] == (pc["now"] - pc["eligible"]
                                  + (pc["eligible"] + 3) // 4)
        with pytest.raises(ValueError):
            sr.forecast_axes({"tp": 0})

    def test_factored_mesh_disposition(self, mesh2x4):
        """On the dp2x4 mesh a batch arg sharded over both data axes is
        sharded-by both; a synthetic 2-way tile assignment is sharded
        by data_inter only."""
        from apex_tpu.prof.sharding import _axis_disposition

        mm = parse_mesh_spec("dp2x4")
        axes = _axis_disposition([0, 0, 0, 0, 1, 1, 1, 1], mm)
        assert axes == {"data_inter": "sharded",
                        "data_intra": "replicated"}
        axes = _axis_disposition(list(range(8)), mm)
        assert axes == {"data_inter": "sharded", "data_intra": "sharded"}
        axes = _axis_disposition([0] * 8, mm)
        assert axes == {"data_inter": "replicated",
                        "data_intra": "replicated"}

    def test_parameter_shardings_scan(self, mesh8):
        hlo = _compile_flat(mesh8).as_text()
        ann = parameter_shardings(hlo)
        assert ann, "no sharding annotations found in the module"
        assert any("devices=" in b for b in ann.values())


# --- the sharding event channel + schema negative twins ----------------------

class TestShardingEvents:
    @pytest.fixture(scope="class")
    def events(self, mesh8):
        compiled = _compile_flat(mesh8)
        sr = shard_report(compiled, parse_mesh_spec("ici8"))
        return sr.to_events(candidate="ici8",
                            wire_by_axis={"data": 4096, "unknown": 64},
                            predicted_s={"data": 1.2e-6})

    def test_stream_validates(self, events):
        check = _schema()
        lines = [json.dumps(e) for e in events]
        assert check(lines) == []
        # header + one row per axis + the explicit unknown row
        assert events[0]["kind"] == "sharding_mesh"
        rows = {e["axis"]: e for e in events[1:]}
        assert set(rows) == {"data", "unknown"}
        assert rows["unknown"]["wire_bytes"] == 64
        assert rows["unknown"]["hbm_sharded_bytes"] == 0
        assert rows["data"]["predicted_s"] == pytest.approx(1.2e-6)

    def test_logger_channel(self, mesh8):
        compiled = _compile_flat(mesh8)
        sr = shard_report(compiled, parse_mesh_spec("ici8"))
        buf = io.StringIO()
        logger = monitor.MetricsLogger(
            sinks=[], sharding_sink=monitor.JSONLSink(buf))
        logger.attach_shard_report(sr, candidate="ici8",
                                   wire_by_axis={"data": 4096})
        logger.close()
        lines = buf.getvalue().splitlines()
        assert len(lines) >= 2
        assert _schema()(lines) == []
        assert logger.shard_report is sr

    def test_negative_twin_bad_axis(self, events):
        check = _schema()
        bad = [dict(e) for e in events]
        bad[1]["axis"] = "bogus_axis"
        errors = check([json.dumps(e) for e in bad])
        assert errors and "bogus_axis" in errors[0]

    def test_negative_twin_negative_bytes(self, events):
        check = _schema()
        bad = [dict(e) for e in events]
        bad[1]["hbm_sharded_bytes"] = -1
        assert check([json.dumps(e) for e in bad])

    def test_negative_twin_missing_header_axes(self, events):
        check = _schema()
        hdr = dict(events[0])
        del hdr["axes"]
        assert check([json.dumps(hdr)])

    def test_extra_axes_declaration(self, events):
        """A composite attribution row (the registry's flat ``data``
        over a factored mesh) passes only when the header declares it
        in ``extra_axes`` — undeclared it is a schema violation."""
        check = _schema()
        hdr = dict(events[0], axes=["data_inter", "data_intra"],
                   axis_sizes={"data_inter": 2, "data_intra": 4},
                   extra_axes=["data"])
        row = dict(events[1], axis="data")
        assert check([json.dumps(hdr), json.dumps(row)]) == []
        hdr_undeclared = dict(hdr, extra_axes=None)
        errors = check([json.dumps(hdr_undeclared), json.dumps(row)])
        assert errors and "'data'" in errors[0]

    def test_composite_wire_row_declared_automatically(self, mesh2x4):
        """to_events on a factored mesh with composite-axis wire (the
        zero/ddp flat 'data' traffic) declares the extra row in the
        header so the stream stays schema-valid."""
        from apex_tpu.trace.spans import span

        params = {"w": jnp.ones((16, 4), jnp.float32)}
        x = jnp.ones((8 * 4, 16), jnp.float32)

        def step(p, xb):
            loss = jnp.sum(xb @ p["w"])
            with span("ddp/loss_pmean", kind="collective"):
                for ax in ("data_inter", "data_intra"):
                    loss = jax.lax.pmean(loss, ax)
            return loss

        mapped = jax.shard_map(
            step, mesh=mesh2x4,
            in_specs=(P(), P(("data_inter", "data_intra"))),
            out_specs=P(), check_vma=False)
        compiled = jax.jit(mapped).lower(params, x).compile()
        mm = parse_mesh_spec("dp2x4")
        sr = shard_report(compiled, mm)
        wire = {ax: sum(per.values()) for ax, per in
                monitor.collective_bytes_by_axis(
                    compiled.as_text()).items()}
        assert wire.get("data", 0) > 0, wire
        evs = sr.to_events(wire_by_axis=wire)
        assert "data" in (evs[0]["extra_axes"] or [])
        assert _schema()([json.dumps(e) for e in evs]) == []


# --- mesh_explain pricing (pure function, no compile) ------------------------

def test_price_candidate_pure():
    """price_candidate is text+model arithmetic: per-axis wire joined
    through the registry, predicted seconds from the model's link
    budgets, unknown priced to None."""
    import importlib.util as _util

    path = os.path.join(_REPO_ROOT, "scripts", "mesh_explain.py")
    spec = _util.spec_from_file_location("mesh_explain", path)
    me = _util.module_from_spec(spec)
    spec.loader.exec_module(me)

    hlo = """
HloModule m
ENTRY e {
  p0 = f32[1024]{0} parameter(0)
  a1 = f32[1024]{0} all-reduce(p0), replica_groups={{0,1,2,3},{4,5,6,7}}, metadata={op_name="jit(f)/ddp/sync_gradients/bucket00/ici/psum"}
  a2 = f32[256]{0} all-reduce(a1), replica_groups={{0,4},{1,5},{2,6},{3,7}}, metadata={op_name="jit(f)/ddp/sync_gradients/bucket00/dcn/psum"}
  ROOT a3 = f32[64]{0} all-reduce(a2), metadata={op_name="jit(f)/nobody/planned"}
}
"""
    mm = parse_mesh_spec("dp2x4")
    out = me.price_candidate(hlo, mm)
    assert out["wire_by_axis"]["data_intra"] == 1024 * 4
    assert out["wire_by_axis"]["data_inter"] == 256 * 4
    assert out["wire_by_axis"]["unknown"] == 64 * 4
    assert out["predicted_s"]["unknown"] is None
    assert out["predicted_s"]["data_intra"] == pytest.approx(
        1024 * 4 / mm.link_bytes_per_s["ici"])
    assert out["predicted_s"]["data_inter"] == pytest.approx(
        256 * 4 / mm.link_bytes_per_s["dcn"])
    assert out["predicted_total_s"] > 0
