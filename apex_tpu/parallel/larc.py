"""LARC — layer-wise adaptive rate control.

Rebuild of `apex/parallel/LARC.py:5-107`: an optimizer *wrapper* that
rewrites each parameter's gradient with a locally-adaptive trust ratio
before delegating to the inner optimizer. Functional form: a gradient
transform applied leaf-wise (each leaf = one "layer" parameter, matching
the reference's per-param loop at `LARC.py:78-105`).

    larc = LARC(inner_tx, trust_coefficient=0.02, clip=True)
    state = larc.init(params)
    params, state = larc.step(grads, state, params, lr=lr)
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


def larc_rewrite_grads(grads, params, *, lr, trust_coefficient: float = 0.02,
                       clip: bool = True, eps: float = 1e-8,
                       weight_decay: float = 0.0):
    """Per-leaf LARC gradient rewrite (`apex/parallel/LARC.py:78-105`).

    adaptive_lr = trust * ||p|| / (||g|| + wd * ||p|| + eps); in ``clip``
    mode the ratio is capped at 1 relative to the global lr
    (`LARC.py:94-96`), otherwise it scales the gradient directly. Weight
    decay is folded into the gradient exactly like the reference
    (`LARC.py:100-103`) so the inner optimizer must not re-apply it.
    Zero-norm params or grads leave the gradient untouched (`LARC.py:88`).
    """
    if clip and lr is None:
        raise ValueError("clip mode requires lr")

    def _rewrite(g, p):
        if not jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating):
            return g
        pn = jnp.linalg.norm(p.astype(jnp.float32).reshape(-1))
        gn = jnp.linalg.norm(g.astype(jnp.float32).reshape(-1))
        adaptive = trust_coefficient * pn / (gn + pn * weight_decay + eps)
        if clip:
            adaptive = jnp.minimum(adaptive / lr, 1.0)
        new_g = (g.astype(jnp.float32) + weight_decay * p.astype(jnp.float32)
                 ) * adaptive
        # zero param or grad norm leaves the gradient *completely* untouched
        # (no wd fold either) — `LARC.py:88` gates the whole rewrite
        active = (pn != 0.0) & (gn != 0.0)
        return jnp.where(active, new_g, g.astype(jnp.float32)).astype(g.dtype)

    return jax.tree_util.tree_map(_rewrite, grads, params)


class LARC:
    """Optimizer wrapper with the reference's constructor signature
    (`LARC.py:55-63`). Works with any inner optimizer exposing
    ``init`` + (``step`` or ``update``) — fused apex_tpu optimizers or
    optax transforms."""

    def __init__(self, optimizer, trust_coefficient: float = 0.02,
                 clip: bool = True, eps: float = 1e-8,
                 weight_decay: float = 0.0):
        self.inner = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps
        self.weight_decay = weight_decay

    def init(self, params):
        return self.inner.init(params)

    def _lr(self, lr):
        if lr is not None:
            return lr
        lr = getattr(self.inner, "lr", None)
        if lr is None:
            raise ValueError("clip mode needs lr: pass lr= or use an inner "
                             "optimizer with a .lr attribute")
        return lr

    def step(self, grads, state, params, *, lr=None):
        grads = larc_rewrite_grads(
            grads, params, lr=self._lr(lr) if self.clip else None,
            trust_coefficient=self.trust_coefficient, clip=self.clip,
            eps=self.eps, weight_decay=self.weight_decay)
        if hasattr(self.inner, "step"):
            return self.inner.step(grads, state, params)
        updates, state = self.inner.update(grads, state, params)
        params = jax.tree_util.tree_map(
            lambda p, u: p + u.astype(p.dtype), params, updates)
        return params, state

    def update(self, grads, state, params, *, lr=None):
        grads = larc_rewrite_grads(
            grads, params, lr=self._lr(lr) if self.clip else None,
            trust_coefficient=self.trust_coefficient, clip=self.clip,
            eps=self.eps, weight_decay=self.weight_decay)
        return self.inner.update(grads, state, params)
