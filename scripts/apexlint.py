#!/usr/bin/env python
"""apexlint CLI — lint compiled training steps before they cost a run.

Three ways to name the step:

``--flagship resnet|bert|both`` (default: both)
    The BASELINE.md flagship steps, built exactly as ``bench.py`` runs
    them (ResNet-50 amp O2 + FusedSGD; BERT LAMB amp O1), jitted WITH
    their donation so the donation rule audits the real program. On an
    accelerator the full-size configs are used; on CPU the structural
    downscalings (the same convention as ``pod_comm_budget --cpu8`` /
    ``memory_budget --cpu8``: ResNet at 64px/b8, a 4-layer BERT at
    seq 128) — same step structure, CPU-compilable.

``--import pkg.mod:builder``
    ``builder()`` must return ``(step_fn, args)`` or
    ``(step_fn, args, policy)``; ``step_fn`` may be jitted (pass your
    real ``donate_argnums``).

``--hlo FILE``
    HLO-pass-only lint of a dumped optimized-HLO text file
    (``scripts/dump_hlo.py`` output or an XLA dump).

Output: the finding table on stdout; ``--jsonl FILE`` streams
``lint_report``/``lint_finding`` events through the
``MetricsLogger(lint_sink=...)`` channel (validate with
``check_metrics_schema.py --kind lint``); ``--json`` prints a summary
object. ``--baseline FILE`` suppresses previously-accepted findings
(``--write-baseline`` records the current findings as that file);
``--fail-on error|warning|never`` (default error) sets the exit gate —
``run_tier1.sh --smoke`` runs the flagship lint with the committed
(empty) ``scripts/apexlint_baseline.json`` so any new error-severity
finding breaks CI. Everything is AOT: trace + compile, zero dispatches.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _build_flagship_resnet():
    """The headline ResNet-50 amp O2 step, donated as bench measures it."""
    import jax
    import bench
    from apex_tpu import amp
    on_tpu = jax.default_backend() == "tpu"
    batch, size = (256, 224) if on_tpu else (8, 64)
    step, (state, batch_stats), (x, y) = bench._resnet_step_builder(
        batch, size, "O2")
    jstep = jax.jit(step, donate_argnums=(0, 1))
    return (jstep, (state, batch_stats, x, y),
            amp.Policy.from_opt_level("O2"), "resnet50_o2_step")


def _build_flagship_bert():
    """The BERT LAMB step, built by bench's own `_bert_step_builder`
    (the lint gate audits the program the bench measures), donated. CPU
    uses a 4-layer structural downscale — XLA:CPU takes minutes just to
    compile the 24-layer BertLarge module (see bench._bert_row)."""
    import jax
    import bench
    from apex_tpu import models

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        enc, batch, seq = None, 16, 512      # None -> full BertLarge
    else:
        enc = models.BertEncoder(30000, hidden=256, layers=4, heads=4,
                                 max_len=128)
        batch, seq = 2, 128
    step, state, (toks, labels), policy, _enc, _vars = \
        bench._bert_step_builder(batch, seq, encoder=enc)
    jstep = jax.jit(step, donate_argnums=(0,))
    return jstep, (state, toks, labels), policy, "bert_lamb_step"


FLAGSHIPS = {"resnet": _build_flagship_resnet,
             "bert": _build_flagship_bert}


def _import_builder(spec):
    mod_name, _, fn_name = spec.partition(":")
    if not fn_name:
        raise SystemExit(f"--import wants pkg.mod:builder, got {spec!r}")
    import importlib
    built = getattr(importlib.import_module(mod_name), fn_name)()
    if len(built) == 2:
        fn, args = built
        policy = None
    else:
        fn, args, policy = built[:3]
    return fn, args, policy, spec


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    flagship = None
    imports, hlo_files = [], []
    baseline_path = write_baseline = jsonl_path = None
    fail_on = "error"
    as_json = False
    it = iter(argv)
    for a in it:
        if a in ("-h", "--help"):
            print(__doc__)
            return 2
        elif a == "--json":
            as_json = True
            continue
        elif a not in ("--flagship", "--import", "--hlo", "--baseline",
                       "--write-baseline", "--jsonl", "--fail-on"):
            print(f"unknown arg {a!r}\n{__doc__}", file=sys.stderr)
            return 2
        val = next(it, None)
        if val is None:
            print(f"{a} requires a value\n{__doc__}", file=sys.stderr)
            return 2
        if a == "--flagship":
            flagship = val
        elif a == "--import":
            imports.append(val)
        elif a == "--hlo":
            hlo_files.append(val)
        elif a == "--baseline":
            baseline_path = val
        elif a == "--write-baseline":
            write_baseline = val
        elif a == "--jsonl":
            jsonl_path = val
        elif a == "--fail-on":
            fail_on = val
    if fail_on not in ("error", "warning", "never"):
        print(f"--fail-on must be error|warning|never, got {fail_on!r}",
              file=sys.stderr)
        return 2
    if flagship is None and not imports and not hlo_files:
        flagship = "both"
    targets = []
    if flagship:
        names = list(FLAGSHIPS) if flagship == "both" else [flagship]
        for n in names:
            if n not in FLAGSHIPS:
                print(f"unknown flagship {n!r} (choices: "
                      f"{', '.join(FLAGSHIPS)}, both)", file=sys.stderr)
                return 2
            targets.append(("flagship", n))
    targets += [("import", s) for s in imports]
    targets += [("hlo", p) for p in hlo_files]

    from apex_tpu import lint
    baseline = lint.load_baseline(baseline_path) if baseline_path else []

    logger = None
    if jsonl_path:
        from apex_tpu import monitor
        logger = monitor.MetricsLogger(
            sinks=[], lint_sink=monitor.JSONLSink(jsonl_path))

    reports, raw_findings = [], []
    for kind, what in targets:
        if kind == "hlo":
            report = lint.lint_hlo_file(what)
        else:
            fn, args, policy, name = (FLAGSHIPS[what]()
                                      if kind == "flagship"
                                      else _import_builder(what))
            report = lint.lint_step(fn, *args, policy=policy,
                                    fn_name=name)
        # the written baseline must cover EVERYTHING that fired —
        # including findings the read baseline suppresses, or a
        # --baseline X --write-baseline X refresh would drop still-live
        # accepted debt and resurface it as new failures
        raw_findings += report.findings
        report = report.apply_baseline(baseline)
        reports.append(report)
        if as_json:
            out = {"fn": report.fn_name}
            out.update(report.summary())
            print(json.dumps(out))
        else:
            print(report.table())
        if logger is not None:
            logger.attach_lint_report(report)
    if logger is not None:
        logger.close()

    if write_baseline:
        n = lint.save_baseline(write_baseline, lint.Report(raw_findings))
        print(f"wrote {write_baseline} ({n} suppressions)")

    # severity rank comes from the one canonical ordering (index =
    # sort key) in apex_tpu.lint.SEVERITIES
    sev_rank = {s: i for i, s in enumerate(lint.SEVERITIES)}
    worst = min((sev_rank[r.max_severity()] for r in reports
                 if r.max_severity()), default=99)
    if fail_on != "never" and worst <= sev_rank[fail_on]:
        print(f"apexlint: failing (findings at or above "
              f"--fail-on {fail_on})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
