"""The HLO half of apexlint: rules over what XLA actually compiled.

Operates on optimized (scheduled) HLO text — from a compiled
executable's ``as_text()`` or a ``scripts/dump_hlo.py`` dump — reusing
:func:`apex_tpu.prof.memory.parse_entry` (the scheduled-HLO buffer
parser) and the collective opcode list shared with
:mod:`apex_tpu.monitor.collectives`:

- **donation-miss** (APX101): an entry argument whose path classifies
  as params/optimizer_state (carried training state) that is not in
  the module's ``input_output_alias`` map *and* has a matching
  un-aliased output to donate into — XLA double-allocates it every
  step; the wasted-bytes estimate is the buffer size.
- **implicit-resharding** (APX102): a compiled collective whose
  named-scope path matches none of the known collective scopes
  (``ddp/sync_gradients``, per-bucket spans, SyncBN, ZeRO
  scatter/gather, ...) — the reshard XLA inserted that nobody planned,
  with its wire-byte cost.
- **host-transfer** (APX103): infeed/outfeed/send/recv/python-callback
  custom calls in the steady-state step (same markers
  :mod:`apex_tpu.monitor.check` pins for the telemetry contract).
- **tile-padding** (APX104): ``dot`` operand/result dims off the TPU
  (sublane, 128) tile grid, with a padding-waste byte estimate
  (sublane 8 for 4-byte dtypes, 16 for 2-byte, 32 for 1-byte).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from apex_tpu.lint.findings import Finding
from apex_tpu.prof import hlo as _hlo
from apex_tpu.prof import memory as _mem
from apex_tpu.prof.xplane import COLLECTIVE_PREFIXES, strip_scope

__all__ = ["lint_hlo_text", "parse_input_output_alias",
           "parse_entry_output_shapes", "donation_findings",
           "resharding_findings", "host_transfer_findings",
           "tile_findings"]

#: carried-state classes the donation rule expects to be aliased
_CARRIED_CLASSES = ("params", "optimizer_state")

#: minimal fallback when apex_tpu.parallel cannot be imported — the ONE
#: canonical allowlist is the declarative per-axis registry
#: :mod:`apex_tpu.parallel.registry` (kept next to the code that emits
#: the collectives, so a new planned collective scope is registered in
#: exactly one place; the SPMD pass and the mesh model consume the
#: same rows)
_FALLBACK_KNOWN_SCOPES = (r"ddp/sync_gradients",)

_COMMENT_RE = re.compile(r"/\*.*?\*/")
_LAYOUT_RE = re.compile(r"\{[^{}]*\}")


def _known_scope_patterns(extra: Sequence[str] = ()) -> List[re.Pattern]:
    try:
        from apex_tpu.parallel.registry import known_patterns
        pats = list(known_patterns())
    except Exception:
        pats = list(_FALLBACK_KNOWN_SCOPES)
    pats += list(extra)
    return [re.compile(p) for p in dict.fromkeys(pats)]


def _normalize_shape(shape_text: str) -> str:
    """Layout/comment-free canonical shape for alias matching:
    ``f32[64,64]{1,0:T(8,128)}`` -> ``f32[64,64]``."""
    s = _COMMENT_RE.sub("", shape_text)
    while _LAYOUT_RE.search(s):
        s = _LAYOUT_RE.sub("", s)
    return s.replace(" ", "")


# -- module-header parsing ----------------------------------------------------

_ALIAS_BLOCK_RE = re.compile(
    r"input_output_alias=\{((?:[^{}]|\{[^{}]*\})*)\}")
_ALIAS_ENTRY_RE = re.compile(r"\{([\d, ]*)\}:\s*\((\d+)")


def parse_input_output_alias(hlo_text: str) -> Tuple[Set[int], Set[int]]:
    """(aliased parameter numbers, aliased output top-level indices)
    from the module header's ``input_output_alias`` map. Both empty
    when the module declares no aliasing (nothing donated)."""
    head = hlo_text[:hlo_text.find("ENTRY")] if "ENTRY" in hlo_text \
        else hlo_text
    m = _ALIAS_BLOCK_RE.search(head)
    if not m:
        return set(), set()
    params: Set[int] = set()
    outs: Set[int] = set()
    for out_idx, pnum in _ALIAS_ENTRY_RE.findall(m.group(1)):
        params.add(int(pnum))
        first = out_idx.replace(" ", "").split(",")[0]
        outs.add(int(first) if first else 0)
    return params, outs


def _split_top_level(s: str) -> List[str]:
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "({[":
            depth += 1
        elif ch in ")}]":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return [p.strip() for p in parts if p.strip()]


def parse_entry_output_shapes(hlo_text: str) -> List[str]:
    """Normalized result shapes of the entry computation, in output
    order, from ``entry_computation_layout={(...)->RESULT}``."""
    marker = "entry_computation_layout={"
    i = hlo_text.find(marker)
    if i < 0:
        return []
    j, depth = i + len(marker) - 1, 0
    for j in range(i + len(marker) - 1, len(hlo_text)):
        if hlo_text[j] == "{":
            depth += 1
        elif hlo_text[j] == "}":
            depth -= 1
            if depth == 0:
                break
    layout = hlo_text[i + len(marker):j]
    if "->" not in layout:
        return []
    result = layout.split("->", 1)[1].strip()
    if result.startswith("("):
        # find the matching close paren of the result tuple
        depth = 0
        for k, ch in enumerate(result):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    result = result[1:k]
                    break
        return [_normalize_shape(p) for p in _split_top_level(result)]
    return [_normalize_shape(result)]


# -- rules --------------------------------------------------------------------

def donation_findings(hlo_text: str, *,
                      min_bytes: int = 4096) -> List[Finding]:
    """Carried-state inputs (params / optimizer-state argument paths)
    not aliased to any output, where an un-aliased output of the same
    shape exists (so donation WOULD have worked — inference-style
    programs whose params never come back out are not flagged)."""
    aliased_params, aliased_outs = parse_input_output_alias(hlo_text)
    out_shapes = parse_entry_output_shapes(hlo_text)
    avail: Dict[str, int] = {}
    for idx, s in enumerate(out_shapes):
        if idx not in aliased_outs:
            avail[s] = avail.get(s, 0) + 1
    args_meta, _instrs, _root = _mem.parse_entry(hlo_text)
    findings: List[Finding] = []
    for name, shape, path, pnum in args_meta:
        cls = _mem.classify_arg_path(path or name)
        if cls not in _CARRIED_CLASSES:
            continue
        nbytes = _mem.shape_bytes(shape)
        if nbytes < min_bytes or pnum in aliased_params:
            continue
        norm = _normalize_shape(shape)
        if avail.get(norm, 0) <= 0:
            continue          # no matching output — not carried state
        avail[norm] -= 1
        findings.append(Finding(
            rule="donation-miss",
            message=f"{cls} input #{pnum} ({norm}) is not donated — "
                    f"{nbytes} bytes double-allocated every step",
            op=name, scope=path or None, bytes=nbytes))
    return findings


def resharding_findings(hlo_text: str,
                        known_scopes: Sequence[str] = ()) -> List[Finding]:
    """Collectives whose named-scope path matches no known pattern."""
    pats = _known_scope_patterns(known_scopes)
    agg: Dict[Tuple[str, str], Tuple[int, int]] = {}
    for raw in hlo_text.splitlines():
        line = raw.strip()
        m = _mem._INSTR_RE.match(line)
        if not m:
            continue
        op = m.group("op")
        for prefix in COLLECTIVE_PREFIXES:
            if not op.startswith(prefix):
                continue
            if op.endswith("-start"):
                break          # counted at the matching -done
            sm = _mem._OP_NAME_RE.search(line)
            scope = strip_scope(sm.group(1)) if sm else ""
            if any(p.search(scope) for p in pats):
                break
            nbytes = _mem.shape_bytes(m.group("shape"))
            n, b = agg.get((prefix, scope), (0, 0))
            agg[(prefix, scope)] = (n + 1, b + nbytes)
            break
    return [Finding(
        rule="implicit-resharding",
        message=f"{n} {prefix} op(s) outside any known collective "
                f"scope ({b} wire bytes/step)",
        op=prefix, scope=scope or "<unscoped>", bytes=b, count=n)
        for (prefix, scope), (n, b) in sorted(agg.items())]


def host_transfer_findings(hlo_text: str) -> List[Finding]:
    """Device↔host traffic compiled into the step (the same markers the
    monitor/trace zero-dispatch compile checks pin)."""
    from apex_tpu.monitor.check import HOST_TRAFFIC_MARKERS
    agg: Dict[str, int] = {}
    for raw in hlo_text.splitlines():
        for marker in HOST_TRAFFIC_MARKERS:
            if marker in raw:
                key = marker.strip().rstrip("(")
                agg[key] = agg.get(key, 0) + 1
                break
    return [Finding(
        rule="host-transfer",
        message=f"{n} {kind} instruction(s) in the compiled step",
        op=kind, count=n) for kind, n in sorted(agg.items())]


def _sublane(itemsize: int) -> int:
    return {4: 8, 2: 16, 1: 32}.get(itemsize, 8)


def _pad_waste(shape_text: str) -> Tuple[int, int]:
    """(logical_bytes, padded_bytes) of one typed shape under the TPU
    (sublane, 128) tile grid."""
    logical = padded = 0
    for dt, dims_s in _mem._SHAPE_RE.findall(shape_text):
        if dt not in _hlo._DTYPE_BYTES:
            continue
        isize = _hlo._DTYPE_BYTES[dt]
        dims = [int(d) for d in dims_s.split(",") if d]
        if not dims:
            continue
        elems = 1
        for d in dims:
            elems *= d
        pdims = list(dims)
        pdims[-1] = -(-pdims[-1] // 128) * 128
        if len(pdims) >= 2:
            sl = _sublane(isize)
            pdims[-2] = -(-pdims[-2] // sl) * sl
        pelems = 1
        for d in pdims:
            pelems *= d
        logical += elems * isize
        padded += pelems * isize
    return logical, padded


def tile_findings(hlo_text: str, *, min_waste_frac: float = 0.01,
                  min_waste_bytes: int = 1 << 16,
                  tuned_shapes: Sequence[str] = ()) -> List[Finding]:
    """``dot`` instructions whose operand/result dims are off the
    (sublane, 128) tile grid, with the padding-waste estimate. Sub-1%
    AND sub-64KiB waste is rounding residue, not a finding — the floor
    keeps ``bench.py``'s ``lint_findings`` count meaningful.

    ``tuned_shapes``: normalized shape signatures a committed tuning-DB
    entry covers (``apex_tpu.ops.autotune.tuned_lint_shapes()``). A
    matching signature stays at info severity with the fix-it naming
    the DB entry — the shape is model-fixed and the kernel block was
    tuned around it, so escalation would only nag."""
    shapes: Dict[str, str] = {}
    dots: List[Tuple[str, str, List[str]]] = []
    for name, shape, op, operands, _line in _hlo.iter_instructions(
            hlo_text):
        shapes[name] = shape
        if op == "dot":
            dots.append((name, shape, operands))
    agg: Dict[str, Tuple[int, int, int]] = {}
    for name, out_shape, operands in dots:
        sig_parts, logical, padded = [], 0, 0
        for s in [shapes.get(o, "") for o in operands[:2]] + [out_shape]:
            lg, pd = _pad_waste(s)
            logical += lg
            padded += pd
            sig_parts.append(_normalize_shape(s))
        waste = padded - logical
        if logical == 0 or waste <= 0:
            continue
        if waste / logical < min_waste_frac and waste < min_waste_bytes:
            continue
        sig = " x ".join(p for p in sig_parts if p)
        n, w, lg = agg.get(sig, (0, 0, 0))
        agg[sig] = (n + 1, w + waste, lg + logical)
    findings = []
    tuned = set(tuned_shapes)
    for sig, (n, waste, logical) in sorted(agg.items()):
        frac = waste / max(logical, 1)
        db_satisfied = sig in tuned
        severity = ("warning" if (frac >= 0.25 and waste >= 1 << 20
                                  and not db_satisfied) else "info")
        msg = (f"{n} dot(s) {sig} pad {frac:.1%} off the "
               f"(sublane,128) grid")
        if db_satisfied:
            msg += (" [covered by a scripts/kernel_tuning_db.json "
                    "entry — block shapes tuned around this padding]")
        findings.append(Finding(
            rule="tile-padding", severity=severity, message=msg,
            op="dot", scope=sig, bytes=waste, count=n))
    return findings


# -- entry point --------------------------------------------------------------

def lint_hlo_text(hlo_text: str, *, known_scopes: Sequence[str] = (),
                  min_donation_bytes: int = 4096,
                  rules: Optional[Sequence[str]] = None,
                  tuned_shapes: Sequence[str] = ()) -> List[Finding]:
    """Run the HLO rules over optimized-HLO text. ``rules`` restricts
    to a subset of slugs (default: all four); ``tuned_shapes`` feeds
    the APX104 tuning-DB exemption (see :func:`tile_findings`)."""
    run = set(rules) if rules is not None else None

    def on(slug: str) -> bool:
        return run is None or slug in run

    out: List[Finding] = []
    if on("donation-miss"):
        out += donation_findings(hlo_text, min_bytes=min_donation_bytes)
    if on("implicit-resharding"):
        out += resharding_findings(hlo_text, known_scopes)
    if on("host-transfer"):
        out += host_transfer_findings(hlo_text)
    if on("tile-padding"):
        out += tile_findings(hlo_text, tuned_shapes=tuned_shapes)
    return out
