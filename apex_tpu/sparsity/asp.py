"""ASP — automatic sparsity: 2:4 masks woven into training.

Rebuild of `apex/contrib/sparsity/asp.py:23-217`. The reference mutates:
it registers mask buffers on whitelisted modules
(`init_model_for_pruning`, `:30`), then monkey-patches ``optimizer.step``
to re-prune grads before and weights after every update
(`init_optimizer_for_pruning`, `:127`). Functionally that's a *gradient/
parameter transform*: masks are state, pruning is two tree_maps around the
inner optimizer — checkpointing the (masks, inner state) tuple round-trips
everything the reference saves through module/optimizer state dicts.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.sparsity import masklib


def default_whitelist(path: Tuple = (), leaf=None) -> bool:
    """Mirror of the reference's whitelist (torch.nn.Linear/Conv kernels,
    `asp.py:30-60`): prune 2-D+ kernels, skip biases/scales/embeddings
    named like norms."""
    names = [str(p).lower() for p in path]
    if leaf is None or getattr(leaf, "ndim", 0) < 2:
        return False
    banned = ("bias", "scale", "embedding", "norm", "bn")
    return not any(b in n for n in names for b in banned)


class ASPState(NamedTuple):
    masks: Any            # pytree of bool masks (None = dense leaf)
    inner: Any            # wrapped optimizer state


def compute_sparse_masks(params, pattern: str = "m4n2_1d",
                         whitelist: Optional[Callable] = None):
    """Mask pytree for ``params`` (`compute_sparse_masks`, `asp.py:155`).
    Non-whitelisted leaves get ``None`` (dense)."""
    whitelist = whitelist or default_whitelist
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    masks = []
    for path, leaf in flat:
        keys = tuple(getattr(p, "key", getattr(p, "idx", p)) for p in path)
        masks.append(masklib.create_mask(leaf, pattern)
                     if whitelist(keys, leaf) else None)
    return jax.tree_util.tree_unflatten(treedef, masks)


def prune(tree, masks):
    """Apply masks leaf-wise (None = identity)."""
    return jax.tree_util.tree_map(
        lambda x, m: x if m is None else jnp.where(m, x, 0).astype(x.dtype),
        tree, masks, is_leaf=lambda x: x is None)


class ASP:
    """Optimizer wrapper: prune grads before and params after the inner
    update — the semantics of the patched ``optimizer.step``
    (`asp.py:127-153`). Works with fused (step) and optax (update)
    optimizers.

    Usage::

        asp = ASP(FusedSGD(lr=0.1), pattern="m4n2_1d")
        state = asp.init(params)              # masks computed here
        params, state = asp.step(grads, state, params)
    """

    def __init__(self, optimizer, pattern: str = "m4n2_1d",
                 whitelist: Optional[Callable] = None):
        self.inner = optimizer
        self.pattern = pattern
        self.whitelist = whitelist

    def init(self, params) -> ASPState:
        masks = compute_sparse_masks(params, self.pattern, self.whitelist)
        return ASPState(masks=masks,
                        inner=self.inner.init(prune(params, masks)))

    def recompute_masks(self, state: ASPState, params) -> ASPState:
        """Refresh masks from current weights (the reference recomputes on
        demand, e.g. after loading a dense checkpoint)."""
        return state._replace(masks=compute_sparse_masks(
            params, self.pattern, self.whitelist))

    def step(self, grads, state: ASPState, params):
        grads = prune(grads, state.masks)
        if hasattr(self.inner, "step"):
            new_params, inner = self.inner.step(grads, state.inner, params)
        else:
            updates, inner = self.inner.update(grads, state.inner, params)
            new_params = jax.tree_util.tree_map(
                lambda p, u: p + u.astype(p.dtype), params, updates)
        new_params = prune(new_params, state.masks)
        return new_params, ASPState(masks=state.masks, inner=inner)

    def update(self, grads, state: ASPState, params):
        new_params, new_state = self.step(grads, state, params)
        updates = jax.tree_util.tree_map(
            lambda n, o: (n.astype(jnp.float32)
                          - o.astype(jnp.float32)).astype(o.dtype),
            new_params, params)
        return updates, new_state
