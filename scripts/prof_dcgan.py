"""Per-op profile of the DCGAN multi-loss bench step (VERDICT r4
item 8; PERF.md round-5 DCGAN section).

Traces the bench's own run — which includes compile, cost analysis,
init, and warmup dispatches — so ABSOLUTE totals span more dispatches
than the timed loop. Everything printed is therefore normalized per
scanned step: the per-op ``avg_us`` column is per occurrence (one per
scanned step for loop-body ops), and category totals divide by the
max op-occurrence count (the number of scanned steps actually traced,
derived from the trace itself).

Usage: python scripts/prof_dcgan.py [--batch N] [--top N]
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    batch, top = 128, 20
    argv = sys.argv
    if "--batch" in argv:
        batch = int(argv[argv.index("--batch") + 1])
    if "--top" in argv:
        top = int(argv[argv.index("--top") + 1])

    import bench as B
    from apex_tpu import prof

    logdir = tempfile.mkdtemp(prefix="apex_tpu_prof_dcgan_")
    with prof.trace(logdir):
        img_s, dt, flops_s = B._bench_dcgan(batch, iters=3)
    peak = prof.device_peak_flops() or float("inf")
    print(f"batch={batch} img/s={img_s:.0f} ms/step={dt * 1e3:.3f} "
          f"MFU={flops_s / peak:.3f}")

    from apex_tpu.prof import xplane
    p = xplane.parse_trace(logdir)
    cats = p.by_category()
    tot = sum(cats.values())
    # steps executed = the max op occurrence count: a loop-body op runs
    # once per scanned step, so this needs no knowledge of the bench's
    # scan length and is immune to init/warmup dispatches in the trace
    steps = max((o.occurrences for o in p.ops), default=1)
    print(f"~{steps} scanned steps traced; per-step category times "
          f"(init-dispatch time included in totals/percentages):")
    for k, v in list(cats.items())[:8]:
        print(f"  {k:20s} {v / steps:9.1f} us/step  "
              f"{100 * v / tot:5.1f}%")
    print(p.table(top=top))


if __name__ == "__main__":
    main()
