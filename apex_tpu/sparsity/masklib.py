"""2:4 structured sparsity mask computation.

Rebuild of `apex/contrib/sparsity/sparse_masklib.py:25-160`: for every
contiguous group of 4 elements along the last (reduction) dimension keep
the 2 with the pattern maximizing preserved magnitude. ``m4n2_1d`` is the
exhaustive 6-pattern search (`create_mask`'s "1d best"); ``m4n2_2d_greedy``
approximates the 4x4 block variant by row-wise 1d on permuted layouts.

Everything is pure tensor math (the reference computes masks in torch on
device, `sparse_masklib.py:145-160`) — jit/vmap friendly, no host loops
over elements.
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np

# all C(4,2)=6 binary patterns with exactly 2 of 4 kept
_PATTERNS_4C2 = np.array(
    [p for p in itertools.product((0, 1), repeat=4) if sum(p) == 2],
    np.float32)                                    # (6, 4)


def m4n2_1d(w) -> jax.Array:
    """Boolean mask, groups of 4 along the last dim, keep best 2.

    Tail elements (last dim % 4) are always kept — same behavior as the
    reference's padding treatment.
    """
    shape = w.shape
    n = shape[-1]
    ngroups = n // 4
    body_len = ngroups * 4
    body = jnp.abs(w[..., :body_len].astype(jnp.float32))
    body = body.reshape(*shape[:-1], ngroups, 4)
    patterns = jnp.asarray(_PATTERNS_4C2)          # (6, 4)
    scores = jnp.einsum("...gi,pi->...gp", body, patterns)
    best = jnp.argmax(scores, axis=-1)             # (..., g)
    mask_body = patterns[best]                     # (..., g, 4)
    mask_body = mask_body.reshape(*shape[:-1], body_len) > 0.5
    if body_len < n:
        tail = jnp.ones((*shape[:-1], n - body_len), bool)
        return jnp.concatenate([mask_body, tail], axis=-1)
    return mask_body


def m4n2_2d_greedy(w) -> jax.Array:
    """Greedy 4x4-block variant (`sparse_masklib.py` "2d greedy"): 2:4
    along the last dim computed on the transposed view as well; keep the
    better-scoring orientation per tensor."""
    if w.ndim < 2:
        return m4n2_1d(w)
    m_row = m4n2_1d(w)
    wt = jnp.swapaxes(w, -1, -2)
    m_col = jnp.swapaxes(m4n2_1d(wt), -1, -2)
    w32 = jnp.abs(w.astype(jnp.float32))
    keep = (jnp.sum(w32 * m_row) >= jnp.sum(w32 * m_col))
    return jnp.where(keep, m_row, m_col)


_PATTERNS = {
    "m4n2_1d": m4n2_1d,
    "m4n2_2d_greedy": m4n2_2d_greedy,
}


def create_mask(w, pattern: str = "m4n2_1d") -> jax.Array:
    """Mask for one tensor (`sparse_masklib.py:145-160`). Tensors with
    fewer than 4 elements in the last dim are left dense."""
    if pattern not in _PATTERNS:
        raise ValueError(f"unknown pattern {pattern!r}; "
                         f"have {sorted(_PATTERNS)}")
    if w.shape[-1] < 4:
        return jnp.ones(w.shape, bool)
    return _PATTERNS[pattern](w)


def density(mask) -> float:
    return float(jnp.mean(mask.astype(jnp.float32)))
