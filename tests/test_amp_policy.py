"""Policy preset & cast-table semantics.

Mirrors the reference's opt-level/property checks (`apex/amp/frontend.py`)
and cast-list classification tests (`tests/L0/run_amp/test_basic_casts.py`,
`test_promotion.py`) at the policy level.
"""

import jax.numpy as jnp
import pytest

from apex_tpu import amp


class TestPresets:
    def test_o0_is_pure_fp32(self):
        p = amp.Policy.from_opt_level("O0")
        assert p.compute_dtype == jnp.float32
        assert p.param_dtype == jnp.float32
        assert p.cast_model_type is None
        assert not p.uses_loss_scaling

    def test_o1_patches_ops_keeps_fp32_params(self):
        p = amp.Policy.from_opt_level("O1")
        assert p.patch_ops
        assert p.param_dtype == jnp.float32
        assert p.compute_dtype == jnp.bfloat16

    def test_o2_half_model_fp32_bn_masters(self):
        p = amp.Policy.from_opt_level("O2")
        assert p.cast_model_type == jnp.bfloat16
        assert p.keep_batchnorm_fp32
        assert p.master_weights

    def test_o3_pure_half(self):
        p = amp.Policy.from_opt_level("O3")
        assert p.cast_model_type == jnp.bfloat16
        assert not p.keep_batchnorm_fp32
        assert not p.master_weights

    def test_fp16_presets_get_dynamic_scaling(self):
        # fp16 needs a scaler; bf16 defaults to none (full exponent range)
        p16 = amp.Policy.from_opt_level("O2", half_dtype=jnp.float16)
        assert p16.loss_scale == "dynamic"
        pbf = amp.Policy.from_opt_level("O2")
        assert pbf.loss_scale is None

    def test_overrides_win(self):
        # explicit kwargs beat the preset, as in amp.initialize
        p = amp.Policy.from_opt_level("O2", keep_batchnorm_fp32=False,
                                      loss_scale=128.0)
        assert not p.keep_batchnorm_fp32
        assert p.loss_scale == 128.0

    def test_bad_opt_level_raises(self):
        with pytest.raises(ValueError):
            amp.Policy.from_opt_level("O4")

    def test_fp16_without_scaler_rejected(self):
        with pytest.raises(ValueError):
            amp.Policy.from_opt_level("O2", half_dtype=jnp.float16,
                                      loss_scale=None)


class TestOpPolicy:
    """Dtype propagation tables (`test_basic_casts.py` semantics)."""

    def test_o1_half_ops(self):
        p = amp.Policy.from_opt_level("O1")
        for op in ("conv2d", "dense", "matmul", "attention"):
            assert p.op_dtype(op, jnp.float32) == jnp.bfloat16

    def test_o1_float_ops(self):
        p = amp.Policy.from_opt_level("O1")
        for op in ("softmax", "layer_norm", "cross_entropy", "exp", "sum"):
            assert p.op_dtype(op, jnp.bfloat16) == jnp.float32

    def test_o1_promote_ops_widen(self):
        p = amp.Policy.from_opt_level("O1")
        assert p.op_dtype("add", jnp.bfloat16, jnp.float32) == jnp.float32
        assert p.op_dtype("add", jnp.bfloat16, jnp.bfloat16) == jnp.bfloat16

    def test_o0_respects_inputs(self):
        p = amp.Policy.from_opt_level("O0")
        assert p.op_dtype("dense", jnp.float32) == jnp.float32

    def test_banned_op_raises_under_half(self):
        p = amp.Policy.from_opt_level("O1")
        with pytest.raises(TypeError):
            p.op_dtype("binary_cross_entropy", jnp.bfloat16)

    def test_registration(self):
        amp.register_half_op("my_custom_gemm")
        p = amp.Policy.from_opt_level("O1")
        assert p.op_dtype("my_custom_gemm") == jnp.bfloat16
        amp.register_float_op("my_custom_gemm")
        assert p.op_dtype("my_custom_gemm") == jnp.float32


class TestCasting:
    def _params(self):
        return {
            "conv": {"kernel": jnp.ones((3, 3, 4, 8), jnp.float32)},
            "batch_norm": {"scale": jnp.ones((8,), jnp.float32),
                           "bias": jnp.zeros((8,), jnp.float32)},
            "step": jnp.int32(3),
        }

    def test_o2_cast_keeps_bn_fp32(self):
        p = amp.Policy.from_opt_level("O2")
        cast = p.cast_params(self._params())
        assert cast["conv"]["kernel"].dtype == jnp.bfloat16
        assert cast["batch_norm"]["scale"].dtype == jnp.float32
        assert cast["step"].dtype == jnp.int32  # non-float untouched

    def test_o3_casts_everything_floating(self):
        p = amp.Policy.from_opt_level("O3")
        cast = p.cast_params(self._params())
        assert cast["batch_norm"]["scale"].dtype == jnp.bfloat16

    def test_output_cast_is_fp32(self):
        p = amp.Policy.from_opt_level("O2")
        out = p.cast_outputs({"logits": jnp.ones((2,), jnp.bfloat16)})
        assert out["logits"].dtype == jnp.float32

    def test_policy_scope_ambient(self):
        p = amp.Policy.from_opt_level("O1")
        assert not amp.current_policy().enabled
        with amp.policy_scope(p):
            assert amp.current_policy() is p
        assert not amp.current_policy().enabled


class TestReviewRegressions:
    """Regressions from code review: substring exemption, numpy leaves,
    O1-fp16 validation."""

    def test_subnet_is_not_bn_exempt(self):
        p = amp.Policy.from_opt_level("O2")
        params = {"subnet": {"kernel": jnp.ones((2, 2), jnp.float32)},
                  "enormous": {"kernel": jnp.ones((2,), jnp.float32)},
                  "BatchNorm_0": {"scale": jnp.ones((2,), jnp.float32)},
                  "LayerNorm_3": {"scale": jnp.ones((2,), jnp.float32)},
                  "bn1": {"scale": jnp.ones((2,), jnp.float32)}}
        cast = p.cast_params(params)
        assert cast["subnet"]["kernel"].dtype == jnp.bfloat16
        assert cast["enormous"]["kernel"].dtype == jnp.bfloat16
        assert cast["BatchNorm_0"]["scale"].dtype == jnp.float32
        assert cast["LayerNorm_3"]["scale"].dtype == jnp.float32
        assert cast["bn1"]["scale"].dtype == jnp.float32

    def test_numpy_leaves_are_cast(self):
        import numpy as np
        from apex_tpu.utils import tree_cast
        out = tree_cast({"x": np.ones((3,), np.float32)}, jnp.bfloat16)
        assert out["x"].dtype == jnp.bfloat16

    def test_o1_fp16_without_scaler_rejected(self):
        with pytest.raises(ValueError):
            amp.Policy.from_opt_level("O1", half_dtype=jnp.float16,
                                      loss_scale=None)

    def test_amp_step_binds_ambient_policy(self):
        import optax
        from apex_tpu.amp.api import Amp
        policy = amp.Policy.from_opt_level("O1")
        amp_opt = Amp(policy, optax.sgd(0.1))
        state = amp_opt.init({"w": jnp.ones((2,))})
        seen = {}

        def loss_fn(mp, x):
            seen["policy"] = amp.current_policy()
            return jnp.sum(mp["w"] * x)

        amp_opt.backward(state, loss_fn, jnp.ones((2,)))
        assert seen["policy"] is policy

    def test_unscale_preserves_dtype_when_upcast_none(self):
        cfg = amp.LossScaleConfig(init_scale=4.0)
        st = amp.loss_scale_init(cfg)
        g = {"w": jnp.ones((2,), jnp.bfloat16) * 4}
        out, _ = amp.unscale_grads(g, st, upcast_to=None)
        assert out["w"].dtype == jnp.bfloat16

    def test_stashed_unscale_skips_int_leaves(self):
        cfg = amp.LossScaleConfig(init_scale=2.0)
        st = amp.loss_scale_init(cfg)
        g = {"w": jnp.ones((2,)) * 2, "count": jnp.int32(5)}
        s = {"w": jnp.ones((2,)), "count": jnp.int32(7)}
        out, _ = amp.unscale_grads_with_stashed(g, s, st)
        assert out["count"].dtype == jnp.int32
