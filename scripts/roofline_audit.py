#!/usr/bin/env python
"""roofline_audit — the asserting CI audit of the roofline observatory
+ perf sentinel (run by ``run_tier1.sh --smoke``; exit status is the
verdict).

Four asserted legs, CPU-only off committed artifacts (live capture
happens on TPU; the committed ``tests/fixtures/*.xplane.pb`` and
``BENCH_r0*.json`` make the whole loop regression-testable tf-free):

(a) **attribution closure + the known gap**: the BERT-layer fixture's
    per-op roofline join must close over the trace's module device
    time within 5%, classify the attention kernels compute-bound and
    the LayerNorm fusions memory-bound, and ``worst_gaps`` must name
    the fused backward-attention kernel at ~549 us measured vs its
    ~436 us d=64 MXU floor — the PERF.md round-5 "550 vs ~440"
    ledger line, reproduced by the tool.

(b) **AOT-only path**: a compiled (never dispatched) step yields
    analytic rows with ``measured_us=None`` and populated bound
    classes; the attention-free toy attributes its dot FLOPs into the
    calling fusion.

(c) **sentinel replay, seeded positive + negative twin**: the
    committed BENCH_r01→r05 trajectory replays CLEAN through
    ``scripts/perf_sentinel.py`` (exit 0 — the r05 failed-bench row is
    skipped with a note, not flagged), and the same trajectory with a
    seeded 45% MFU/throughput drop appended exits 1 naming ``mfu``.

(d) every emitted stream validates under
    ``check_metrics_schema.py --kind roofline``.

Usage: JAX_PLATFORMS=cpu python scripts/roofline_audit.py --cpu8
"""

import glob
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURES = os.path.join(_REPO, "tests", "fixtures")


def _run_schema(path: str, kind: str = "roofline") -> None:
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts",
                                      "check_metrics_schema.py"),
         "--kind", kind, path],
        capture_output=True, text=True)
    assert r.returncode == 0, (
        f"schema validation failed for {path}:\n{r.stdout}{r.stderr}")


def audit_fixture_join(tmp: str) -> None:
    from apex_tpu import monitor
    from apex_tpu.prof import roofline, xplane

    print("== roofline join on the committed BERT-layer fixture")
    os.environ["APEX_TPU_XPLANE_PURE"] = "1"     # tf-free decode path
    tp = xplane.parse_trace(os.path.join(_FIXTURES,
                                         "bert_layer.xplane.pb"))
    rep = roofline.roofline_report(profile=tp,
                                   device_kind="TPU v5 lite")
    print(rep.table(top=8))

    # (a) attribution closes over the module's device time within 5%
    ok, err = rep.check_closure(tolerance=0.05)
    assert ok, f"per-op attribution does not close over device time: " \
               f"relative error {err:.4f} > 0.05"
    print(f"  closure over module device time: {err:.2%} (<= 5%)")

    # bound classes: attention kernels compute-bound (the d=64 MXU
    # cap), LayerNorm fusions memory-bound (HBM roofline)
    by_name = {r.name: r for r in rep.rows}
    for name in ("custom-call.201", "custom-call.202"):
        assert by_name[name].family == "attention", by_name[name]
        assert by_name[name].bound == "compute", by_name[name]
        assert by_name[name].mxu_cap == 0.5, by_name[name]
    for name in ("fusion.210", "fusion.211"):
        assert by_name[name].family == "layer_norm", by_name[name]
        assert by_name[name].bound == "memory", by_name[name]
    fams = rep.by_family()
    assert set(fams) == {"attention", "layer_norm", "mlp"}, fams
    for r in rep.rows:
        assert r.efficiency is not None and 0.0 <= r.efficiency <= 1.0, r

    # the known gap: PERF round-5's "fused backward at ~550 us vs its
    # ~440 us roofline" — worst_gaps must name the bwd attention
    # kernel with the tool reproducing both numbers
    gaps = rep.worst_gaps(3)
    bwd = [g for g in gaps if g["op"] == "custom-call.202"]
    assert bwd, f"worst_gaps(3) does not name the fused backward " \
                f"attention kernel: {[g['op'] for g in gaps]}"
    g = bwd[0]
    assert g["family"] == "attention" and g["bound"] == "compute", g
    assert 540.0 <= g["measured_us"] <= 560.0, g
    assert 420.0 <= g["attainable_us"] <= 450.0, g
    assert g["gap_us"] > 80.0, g
    assert g["fingerprint"].startswith("attention|custom-call|"), g
    print(f"  worst_gaps names the fused-backward gap: "
          f"{g['measured_us']:.0f} us measured vs "
          f"{g['attainable_us']:.0f} us d=64 MXU floor "
          f"(eff {g['efficiency']:.0%}) — the PERF round-5 ledger line")

    # resnet fixture still joins (families/categories; its op set is a
    # 2-step sub-sample, so closure is not asserted there)
    tp2 = xplane.parse_trace(os.path.join(_FIXTURES,
                                          "resnet_step.xplane.pb"))
    rep2 = roofline.roofline_report(profile=tp2,
                                    device_kind="TPU v5 lite")
    fams2 = rep2.by_family()
    assert "bn_act" in fams2 and "conv" in fams2, fams2
    conv = [r for r in rep2.rows if r.opcode == "convolution"][0]
    assert conv.flops > 0 and conv.bound == "compute", conv

    # (d) the event stream validates
    events_path = os.path.join(tmp, "roofline.jsonl")
    logger = monitor.MetricsLogger(
        sinks=[], roofline_sink=monitor.JSONLSink(events_path))
    logger.attach_roofline_report(rep)
    logger.close()
    _run_schema(events_path)
    print(f"  events validate (--kind roofline): {events_path}")


def audit_aot_only(tmp: str) -> None:
    import jax
    import jax.numpy as jnp

    from apex_tpu import monitor
    from apex_tpu.prof import roofline

    print("== AOT-only roofline (compiled module, zero dispatches)")

    def step(x, w1, w2):
        return jnp.tanh(jnp.tanh(x @ w1) @ w2).sum()

    avals = (jax.ShapeDtypeStruct((256, 512), jnp.float32),
             jax.ShapeDtypeStruct((512, 512), jnp.float32),
             jax.ShapeDtypeStruct((512, 128), jnp.float32))
    compiled = jax.jit(step).lower(*avals).compile()
    rep = roofline.roofline_report(compiled=compiled,
                                   device_kind="TPU v5 lite")
    assert rep.rows and not rep.measured
    assert all(r.measured_us is None and r.gap_us is None
               and r.efficiency is None for r in rep.rows)
    total_flops = sum(r.flops for r in rep.rows)
    want = 2 * 256 * 512 * 512 + 2 * 256 * 512 * 128
    assert abs(total_flops - want) / want < 0.01, (total_flops, want)
    assert any(r.bound in ("compute", "memory") for r in rep.rows)
    ok, err = rep.check_closure()
    assert ok and err == 0.0            # nothing measured -> trivially ok
    assert rep.worst_gaps(5) == []      # gaps need measurements
    events_path = os.path.join(tmp, "roofline_aot.jsonl")
    logger = monitor.MetricsLogger(
        sinks=[], roofline_sink=monitor.JSONLSink(events_path))
    logger.attach_roofline_report(rep)
    logger.close()
    _run_schema(events_path)
    print(f"  {len(rep.rows)} analytic rows, dot FLOPs fold into the "
          f"calling fusion ({total_flops:.3g} == {want:.3g}), "
          f"measured_us null on every row, events validate")


def audit_sentinel(tmp: str) -> None:
    print("== perf sentinel: committed trajectory clean, seeded "
          "regression fires")
    traj = sorted(glob.glob(os.path.join(_REPO, "BENCH_r0*.json")))
    assert len(traj) >= 4, f"expected the committed r01.. trajectory, " \
                           f"got {traj}"
    baseline = os.path.join(_REPO, "scripts", "perf_baseline.json")

    def run_sentinel(files, jsonl):
        return subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "scripts", "perf_sentinel.py"),
             "--check", *files, "--baseline", baseline,
             "--jsonl", jsonl],
            capture_output=True, text=True)

    # negative twin: the unmodified trajectory must pass clean
    clean_events = os.path.join(tmp, "sentinel_clean.jsonl")
    r = run_sentinel(traj, clean_events)
    assert r.returncode == 0, (
        f"sentinel flagged the UNMODIFIED committed trajectory:\n"
        f"{r.stdout}{r.stderr}")
    assert "skipped" in r.stdout, (
        "the failed r05 row should be skipped with a note:\n" + r.stdout)
    _run_schema(clean_events)
    print("  unmodified r01->r05 trajectory: clean (exit 0, failed "
          "r05 row skipped with a note)")

    # seeded positive: last good row degraded 45% in MFU + throughput
    last_good = None
    for p in reversed(traj):
        obj = json.load(open(p))
        if obj.get("parsed"):
            last_good = obj["parsed"]
            break
    assert last_good is not None
    seeded = json.loads(json.dumps(last_good))
    seeded["value"] *= 0.55
    seeded["extra"]["mfu"] *= 0.55
    seeded_path = os.path.join(tmp, "BENCH_seeded.json")
    json.dump({"n": 99, "rc": 0, "parsed": seeded},
              open(seeded_path, "w"))
    seed_events = os.path.join(tmp, "sentinel_seeded.jsonl")
    r = run_sentinel(traj + [seeded_path], seed_events)
    assert r.returncode == 1, (
        f"sentinel MISSED the seeded 45% MFU regression:\n"
        f"{r.stdout}{r.stderr}")
    assert "mfu" in r.stdout and "REGRESSED" in r.stdout, r.stdout
    _run_schema(seed_events)
    regressed = [json.loads(l) for l in open(seed_events)
                 if json.loads(l).get("regressed")]
    assert {"mfu", "device_img_s"} <= {e["metric"] for e in regressed}, \
        regressed
    print("  seeded 45% MFU drop: flagged (exit 1; mfu + device_img_s "
          "regressed, direction-aware median/MAD baseline)")

    # library-level replay backtest: every committed row judged
    # against its prefix stays quiet
    from apex_tpu.prof import sentinel as sn
    rows = sn.load_rows(traj)
    reports = sn.replay_trajectory(rows)
    assert reports and all(rep.ok for rep in reports), \
        [(rep.subject, [v.metric for v in rep.regressions])
         for rep in reports]
    print(f"  replay backtest: {len(reports)} judged rows, all quiet")


def main_cpu8() -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    from apex_tpu import _compat
    _compat.request_cpu_devices(8)

    with tempfile.TemporaryDirectory() as tmp:
        audit_fixture_join(tmp)
        audit_aot_only(tmp)
        audit_sentinel(tmp)
    print("\nroofline audit ok")


if __name__ == "__main__":
    if "--cpu8" in sys.argv:
        main_cpu8()
    else:
        print(__doc__)
        sys.exit(2)
