"""Measured link calibration: sweep collectives, fit α–β, emit a MeshModel.

The mesh model (:mod:`apex_tpu.lint.mesh_model`) judges every
cross-rank finding against per-link byte budgets — but until something
*measures* them, ``link_bytes_per_s`` is an assumed constant
(``DEFAULT_LINK_BYTES_PER_S``), and ROADMAP item 2's per-hop dtype
choices (DynamiQ-style routing, EQuARX-class quantized all-reduce)
would be scheduled against a guess. This module closes that loop:

1. **sweep** (:func:`sweep_axis`): time all-reduce / reduce-scatter /
   all-gather over ONE mesh axis across a ladder of message sizes —
   each point is a jitted ``shard_map`` collective, warmed once, then
   best-of-``iters`` wall time at a host sync (best-of, like the bench
   harness: the floor is the hardware, the jitter is the host);
2. **fit** (:func:`fit_alpha_beta`): least-squares ``t = α + β·bytes``
   over the ring-model wire bytes per chip (all-reduce moves
   ``2(N−1)/N`` of the buffer, reduce-scatter / all-gather
   ``(N−1)/N`` — the same factors ``scripts/pod_comm_budget.py``
   budgets with), yielding the latency intercept α, the measured
   bandwidth ``1/β``, and the relative fit residual;
3. **emit** (:func:`calibrate`): one fit per mesh axis, folded per
   link class (the slowest axis of a class bounds it), into a
   :class:`~apex_tpu.lint.mesh_model.MeshModel` whose
   ``link_bytes_per_s`` is **measured** and whose ``calibration``
   block records the provenance (α, bytes/s, residual, sample count,
   source axis) — JSON-committable, and ingested unchanged by
   ``apexlint --mesh model.json`` and ``pod_comm_budget --mesh``, so
   APX203's flat-vs-hierarchical DCN milliseconds rest on
   measurements.

``kind="linkfit"`` events for the goodput channel come from
:func:`linkfit_events` (``check_metrics_schema.py --kind goodput``
validates). The CLI is ``scripts/link_probe.py --cpu8|--tpu``.

Caveat stated up front: on a shared-memory CPU "mesh" the numbers
characterize XLA:CPU's collective emulation, not a fabric — the
``--cpu8`` path exists so the *pipeline* (sweep → fit → MeshModel →
apexlint) is CI-proven end to end; on-chip runs produce the numbers
that matter.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.lint.mesh_model import MeshAxis, MeshModel

__all__ = ["LinkSample", "LinkFit", "sweep_axis", "fit_alpha_beta",
           "calibrate", "linkfit_events", "fit_table",
           "DEFAULT_SIZES", "OPS"]

#: message-size ladder (bytes of the logical buffer) — spans the
#: latency-dominated and bandwidth-dominated regimes
DEFAULT_SIZES = (1 << 14, 1 << 17, 1 << 20)

#: collective families swept per axis
OPS = ("all_reduce", "reduce_scatter", "all_gather")

#: ring-model wire-byte factor per chip, as a function of axis size N
_RING_FACTOR = {
    "all_reduce": lambda n: 2.0 * (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "all_gather": lambda n: (n - 1) / n,
}


@dataclasses.dataclass(frozen=True)
class LinkSample:
    """One timed point: op, axis, logical bytes, ring wire bytes/chip,
    best-of seconds."""

    op: str
    axis: str
    size_bytes: int
    wire_bytes: float
    seconds: float


@dataclasses.dataclass(frozen=True)
class LinkFit:
    """α–β fit of one axis (or link class): ``t = alpha_s +
    wire_bytes / bytes_per_s``."""

    axis: str
    alpha_s: float
    bytes_per_s: float
    residual: float               # relative RMS of the fit
    n_samples: int

    def seconds(self, wire_bytes: float) -> float:
        return self.alpha_s + wire_bytes / self.bytes_per_s

    def to_json(self) -> Dict:
        return {"axis": self.axis,
                "alpha_us": round(self.alpha_s * 1e6, 3),
                "bytes_per_s": float(self.bytes_per_s),
                "residual": round(float(self.residual), 6),
                "n_samples": self.n_samples}


def _collective(op: str, mesh, axis: str):
    """The jitted shard_map collective for one (op, axis) pair. Input
    is the replicated logical buffer (each chip holds it whole), so
    the swept ``size_bytes`` is exactly the collective's payload."""
    import jax
    from jax.sharding import PartitionSpec as P

    if op == "all_reduce":
        body, out_spec = (lambda x: jax.lax.psum(x, axis)), P()
    elif op == "reduce_scatter":
        body, out_spec = (lambda x: jax.lax.psum_scatter(
            x, axis, tiled=True)), P(axis)
    elif op == "all_gather":
        # gather back a buffer sharded over the axis: the GLOBAL input
        # is the full logical buffer (in_specs=P(axis) hands each chip
        # its 1/N shard), the gathered output is the whole buffer again
        # — so the swept size is exactly the bytes the gather rebuilds
        def body(x):
            return jax.lax.all_gather(x, axis, tiled=True)
        return jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=P(axis), out_specs=P(),
            check_vma=False))
    else:
        raise ValueError(f"unknown op {op!r} (want one of {OPS})")
    return jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=P(), out_specs=out_spec,
        check_vma=False))


def sweep_axis(mesh, axis: str, *, sizes: Sequence[int] = DEFAULT_SIZES,
               ops: Sequence[str] = OPS, iters: int = 3,
               ) -> List[LinkSample]:
    """Time each (op, size) point over ``axis`` of ``mesh``; returns
    one :class:`LinkSample` per point. Buffers are f32; sizes are
    rounded up so every op's sharding divides evenly."""
    import jax
    import jax.numpy as jnp

    n = int(mesh.shape[axis])
    if n < 2:
        raise ValueError(f"axis {axis!r} has size {n}; calibrating a "
                         "link needs >= 2 participants")
    out: List[LinkSample] = []
    for op in ops:
        fn = _collective(op, mesh, axis)
        for size in sizes:
            elems = max(int(size) // 4, n)
            elems += (-elems) % n            # divisible by the axis
            # every op takes the full logical buffer as its GLOBAL
            # input (shard_map's in_specs do any sharding), so
            # size_bytes below is the payload each op logically moves
            x = jnp.arange(elems, dtype=jnp.float32)
            jax.block_until_ready(fn(x))     # warm: compile + first run
            best = float("inf")
            for _ in range(max(int(iters), 1)):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(x))
                best = min(best, time.perf_counter() - t0)
            nbytes = elems * 4
            out.append(LinkSample(
                op=op, axis=axis, size_bytes=nbytes,
                wire_bytes=_RING_FACTOR[op](n) * nbytes,
                seconds=best))
    return out


def fit_alpha_beta(samples: Sequence[LinkSample],
                   axis: Optional[str] = None) -> LinkFit:
    """Least-squares ``t = α + β·wire_bytes`` over the samples.

    α is clamped non-negative and β strictly positive: a noisy sweep
    (CPU emulation, tiny messages) can fit a negative slope, and a
    negative "bandwidth" would poison every downstream ``hop_seconds``
    — the fallback slope is the largest sample's aggregate rate, the
    conservative measurable bound."""
    if not samples:
        raise ValueError("no samples to fit")
    b = np.array([s.wire_bytes for s in samples], np.float64)
    t = np.array([s.seconds for s in samples], np.float64)
    a_mat = np.stack([np.ones_like(b), b], axis=1)
    (alpha, beta), *_ = np.linalg.lstsq(a_mat, t, rcond=None)
    alpha = max(float(alpha), 0.0)
    if beta <= 0:
        i = int(np.argmax(b))
        beta = max(float(t[i] / b[i]), 1e-15)
        alpha = 0.0
    pred = alpha + beta * b
    residual = float(np.sqrt(np.mean((pred - t) ** 2)) / max(
        float(np.mean(t)), 1e-12))
    return LinkFit(axis=axis or samples[0].axis, alpha_s=alpha,
                   bytes_per_s=1.0 / float(beta), residual=residual,
                   n_samples=len(samples))


def calibrate(mesh, template: MeshModel, *,
              sizes: Sequence[int] = DEFAULT_SIZES,
              ops: Sequence[str] = OPS, iters: int = 3,
              name: Optional[str] = None,
              ) -> Tuple[MeshModel, Dict[str, LinkFit],
                         List[LinkSample]]:
    """Sweep every size->1 axis of ``template`` over the matching
    ``mesh`` axis and emit ``(measured_model, per_axis_fits,
    samples)``.

    ``template`` declares the topology (axis names/sizes/link classes
    — e.g. ``parse_mesh_spec("dp2x4")``); ``mesh`` must carry the same
    axis names with the same sizes. Each link class's
    ``link_bytes_per_s`` becomes the MINIMUM fitted bandwidth over its
    axes (the slowest member bounds the class), and the model's
    ``calibration`` block records each class's winning fit."""
    for ax in template.axes:
        if ax.size > 1 and ax.name not in mesh.shape:
            raise ValueError(f"template axis {ax.name!r} missing from "
                             f"mesh axes {tuple(mesh.shape)}")
        if ax.size > 1 and int(mesh.shape[ax.name]) != ax.size:
            raise ValueError(
                f"axis {ax.name!r}: template size {ax.size} != mesh "
                f"size {int(mesh.shape[ax.name])}")
    fits: Dict[str, LinkFit] = {}
    samples: List[LinkSample] = []
    for ax in template.axes:
        if ax.size < 2:
            continue
        ss = sweep_axis(mesh, ax.name, sizes=sizes, ops=ops,
                        iters=iters)
        samples.extend(ss)
        fits[ax.name] = fit_alpha_beta(ss, axis=ax.name)
    if not fits:
        raise ValueError("template has no axis of size >= 2 to "
                         "calibrate")
    link_bps: Dict[str, float] = {}
    calibration: Dict[str, Dict] = {}
    for ax in template.axes:
        fit = fits.get(ax.name)
        if fit is None:
            continue
        cur = link_bps.get(ax.link)
        if cur is None or fit.bytes_per_s < cur:
            link_bps[ax.link] = fit.bytes_per_s
            calibration[ax.link] = fit.to_json()
    model = MeshModel(
        [MeshAxis(a.name, a.size, a.link) for a in template.axes],
        link_bytes_per_s=link_bps,
        budget_bytes_per_step=template.budget_bytes_per_step,
        name=name or (f"{template.name}-measured" if template.name
                      else "measured"),
        calibration=calibration)
    return model, fits, samples


def linkfit_events(model: MeshModel,
                   rank: Optional[int] = None) -> List[Dict]:
    """``kind="linkfit"`` events (goodput channel) for a calibrated
    model — one per measured link class."""
    if rank is None:
        try:
            import jax
            rank = jax.process_index()
        except Exception:
            rank = 0
    out: List[Dict] = []
    for link, cal in sorted(model.calibration.items()):
        out.append({"kind": "linkfit", "link": link,
                    "axis": cal.get("axis"),
                    "alpha_us": cal.get("alpha_us"),
                    "bytes_per_s": cal.get("bytes_per_s"),
                    "residual": cal.get("residual"),
                    "n_samples": cal.get("n_samples"),
                    "rank": rank, "wall_time": time.time()})
    return out


def fit_table(fits: Dict[str, LinkFit],
              samples: Sequence[LinkSample] = ()) -> str:
    """Aligned per-axis fit summary (plus the sample count per op)."""
    lines = [f"{'axis':<14} {'alpha_us':>10} {'GB/s':>10} "
             f"{'residual':>9} {'samples':>8}"]
    for axis, fit in sorted(fits.items()):
        lines.append(f"{axis:<14} {fit.alpha_s * 1e6:>10.1f} "
                     f"{fit.bytes_per_s / 1e9:>10.3f} "
                     f"{fit.residual:>9.4f} {fit.n_samples:>8}")
    if samples:
        by_op: Dict[str, int] = {}
        for s in samples:
            by_op[s.op] = by_op.get(s.op, 0) + 1
        lines.append("  swept: " + ", ".join(
            f"{op} x{n}" for op, n in sorted(by_op.items())))
    return "\n".join(lines)
