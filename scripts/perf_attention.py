"""Flash-attention perf sweep — the `perf_test_multihead_attn.py` mirror.

The reference's headline artifact is its fused-MHA fwd/bwd timing chart
(`apex/contrib/multihead_attn/README.md`,
`perf_test_multihead_attn.py:9-16`: TitanV, 18 layers, hidden 1024,
16 heads). This sweeps the TPU kernels across sequence lengths at
constant token count and prints achieved TFLOP/s for forward and
forward+backward. K scanned steps per dispatch amortize tunnel
dispatch overhead (device wall ≈ K·step).

Usage: python scripts/perf_attention.py [--tokens 16384] [--causal]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import time

import jax
import jax.numpy as jnp
import numpy as np


def attn_flops(b, s, h, d):
    # QK^T + PV, fwd; bwd ≈ 2.5x fwd (dq,dk,dv recompute scores once)
    return 2.0 * b * h * s * s * d * 2


def measure(fn, args, iters=3, K=20):
    """Mean step time over K scanned steps per dispatch.

    The carry feeds each step's first input (scaled to ~0 so numerics
    are unchanged) and the output collapses to a scalar — a genuine
    loop dependence, so XLA can neither hoist the body out of the loop
    nor stack K full-size outputs (bench.py's scan threads state for
    the same reason)."""
    q0, rest = args[0], args[1:]

    def scanned(q0, rest):
        def body(c, _):
            out = fn(q0 + c, *rest)
            s = sum(jnp.sum(l.astype(jnp.float32))
                    for l in jax.tree_util.tree_leaves(out))
            return (s * 1e-30).astype(q0.dtype), None
        c, _ = jax.lax.scan(body, jnp.zeros((), q0.dtype), None, length=K)
        return c

    jf = jax.jit(scanned)
    out = jf(q0, rest)
    np.asarray(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jf(q0, rest)
    np.asarray(out)
    return (time.perf_counter() - t0) / (iters * K)


def main():
    from apex_tpu.ops import flash_attention

    tokens = 16384
    if "--tokens" in sys.argv:
        tokens = int(sys.argv[sys.argv.index("--tokens") + 1])
    causal = "--causal" in sys.argv
    h, d = 16, 64                       # BERT-Large head geometry
    dtype = jnp.bfloat16

    print(f"| Seq | Batch | fwd ms | fwd TFLOP/s | fwd+bwd ms | "
          f"eff. TFLOP/s |")
    print("|---|---|---|---|---|---|")
    for s in (128, 512, 2048, 8192):
        b = max(1, tokens // s)
        rng = np.random.RandomState(0)
        mk = lambda i: jnp.asarray(
            rng.randn(b, s, h, d).astype(np.float32) * 0.3, dtype)
        q, k, v = mk(0), mk(1), mk(2)

        fwd = lambda q, k, v: flash_attention(q, k, v, causal=causal)
        t_f = measure(fwd, (q, k, v))

        def fwdbwd(q, k, v):
            def loss(q, k, v):
                o = flash_attention(q, k, v, causal=causal)
                return jnp.sum(o.astype(jnp.float32) ** 2)
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        t_fb = measure(fwdbwd, (q, k, v))

        fl = attn_flops(b, s, h, d) * (0.5 if causal else 1.0)
        print(f"| {s} | {b} | {t_f*1e3:.2f} | {fl/t_f/1e12:.1f} | "
              f"{t_fb*1e3:.2f} | {fl*3.5/t_fb/1e12:.1f} |")


if __name__ == "__main__":
    main()
