#!/usr/bin/env python
"""Validate apex_tpu observability JSONL streams.

Two wire formats share a validator core (finite-or-null numbers, typed
counters, JSON-object-per-line):

``--kind metrics`` (default) — the stream emitted by
``apex_tpu.monitor.JSONLSink`` (keep in lockstep with
``apex_tpu/monitor/sinks.py`` / ``logger.py``):

- every line is a standalone JSON object;
- the REQUIRED keys are present on every line;
- ``step`` is a strictly increasing integer (the in-graph counter
  counts *attempted* steps, so the stream is monotonic even across
  overflow-skipped updates);
- counters are non-negative integers;
- every numeric value is finite — Infinity/NaN never reach the wire
  (the logger nulls non-finite gauges); ``null`` is allowed only for
  the NULLABLE gauges (first-record step time, unknown-chip MFU, ...).

``--kind trace`` — the trace-event / crash-dump / watchdog stream from
``apex_tpu.trace`` (keep in lockstep with ``apex_tpu/trace/spans.py``,
``recorder.py``, ``watchdog.py``): every line is an object with a
``kind`` in {span, step, crash, watchdog}; per-kind REQUIRED keys below;
``rank`` is a non-negative int everywhere; durations are finite,
non-negative numbers; a crash/watchdog header names the last-completed
span (string or null) and lists in-flight spans.

``--kind memory`` — the memory/compile event channel
(``MetricsLogger(memory_sink=...)``; keep in lockstep with
``apex_tpu/prof/memory.py`` and ``compile_watch.py``): ``kind`` in
{memory, memory_report, retrace, compile}. A ``memory`` event is one
runtime allocator sample (bytes in use / peak / limit, null off-TPU);
``memory_report`` carries the compiled step's footprint (total + peak
bytes, the per-class breakdown, top buffers); ``retrace``/``compile``
are the retrace-detector warnings naming the function and the changed
argument.

``--kind lint`` — the apexlint event channel
(``MetricsLogger(lint_sink=...)``; keep in lockstep with
``apex_tpu/lint/findings.py``): ``kind`` in {lint_report,
lint_finding}. A ``lint_report`` header carries the finding count and
per-severity breakdown; each ``lint_finding`` names its rule (stable
id), severity in {error, warning, info}, message, fix-it hint, and
evidence (op / scope / bytes).

``--kind guard`` — the self-healing guard event channel
(``MetricsLogger(guard_sink=...)``; keep in lockstep with
``apex_tpu/guard/policy.py``): ``kind`` in {guard_anomaly,
guard_action, guard_rewind}. A ``guard_anomaly`` names the anomaly
classes the in-graph detectors flagged (with the robust z-score,
nullable — a NaN-loss step has no finite z); a ``guard_action``
records the ladder's decision (action in {skip, rewind, escalate,
observe}); a ``guard_rewind`` records a restore-and-fast-forward
(from_step/to_step, checkpoint root, how many batches the data
cursor skipped, how many corrupt/nonfinite candidates were rejected).

``--kind goodput`` — the runtime performance-observatory channel
(``MetricsLogger(goodput_sink=...)``; keep in lockstep with
``apex_tpu/monitor/goodput.py``, ``trace/straggler.py`` and
``monitor/linkbench.py``): ``kind`` in {goodput, straggler, linkfit}.
A ``goodput`` event is one step's wall-time decomposition (wall_ms +
the per-bucket breakdown, the goodput fraction, and the attribution
closure error); a ``straggler`` names a persistent laggard rank (lag
vs the median rank, robust z, consecutive flagged steps, and the
slowest span class on the lagging rank); a ``linkfit`` records one
link class's measured α–β calibration (latency, bytes/s, fit
residual).

``--kind roofline`` — the roofline-observatory channel
(``MetricsLogger(roofline_sink=...)``; keep in lockstep with
``apex_tpu/prof/roofline.py`` and ``prof/sentinel.py``): ``kind`` in
{roofline, regress}. A ``roofline`` event is one op's
measured-vs-attainable verdict (bound class in {compute, memory,
unknown}, efficiency ∈ [0, 1] or null, ``measured_us`` nullable — an
AOT-only audit has analytic rows with no trace); a ``regress`` event is
one perf-sentinel verdict (direction in {higher, lower}, robust
baseline/MAD/threshold, the regressed/waived booleans and the waiver
fingerprint).

``--kind cluster`` — the cluster-control-plane channel
(``MetricsLogger(cluster_sink=...)``; keep in lockstep with
``apex_tpu/cluster/membership.py`` and ``coordinator.py``): ``kind``
in {cluster_lease, cluster_generation, cluster_fence, cluster_coord}.
A ``cluster_lease`` records a membership edge (action in {acquire,
release, expire, gc}); a ``cluster_generation`` records an epoch
commit or observation (action in {bump, observe} — a bump's
``generation`` must exceed its ``prev_generation``, and bumps are
monotone non-decreasing across the stream); a ``cluster_fence`` is a
REFUSAL (action in {refused_commit, refused_write, refused_delete,
refused_intent}) naming the stale token and the committed generation
it lost to; a ``cluster_coord`` is one recovery-round edge (action in
{propose, resolve, barrier_timeout, collective_hang}) — deadline and
target fields are nullable (a resolve that escalated has no rewind
target).

``--kind integrity`` — the silent-divergence-defense channel
(``MetricsLogger(integrity_sink=...)``; keep in lockstep with
``apex_tpu/guard/integrity.py`` and ``guard/policy.py``): ``kind`` in
{integrity_check, integrity_vote, integrity_repair}. An
``integrity_check`` records one detected cross-replica fingerprint
mismatch (the in-graph pmin/pmax disagreed — fp_min/fp_max and the
cumulative mismatch counter); an ``integrity_vote`` records the quorum
verdict (action in {repair, rewind, escalate, observe}, the named
minority rank list, and the broadcast source — nullable, a no-majority
vote has none); an ``integrity_repair`` records the in-place
re-broadcast (action in {repair, repair_failed}, the re-verification
verdict). Every event carries a nullable ``generation`` — the cluster
fence token when a membership is wired.

``--kind ckpt`` — the checkpoint event channel
(``MetricsLogger(ckpt_sink=...)``; keep in lockstep with
``apex_tpu/ckpt/manager.py`` and ``escalate.py``): ``kind`` in
{ckpt_save, ckpt_restore, ckpt_escalation}. A ``ckpt_save`` names the
committed directory with its step, payload bytes, the step-path stall
(``stall_ms`` — the async-save overhead the bench row tracks) and the
write duration; a ``ckpt_restore`` carries the restored step and how
many leaves were elastically re-partitioned (``resharded``); a
``ckpt_escalation`` records the stall/preempt reason, the action taken
and the (nullable — no snapshot may exist yet) checkpoint path.

Pure stdlib on purpose: CI and log-shipping hosts can run it without
jax. Exit status 0 = valid, 1 = violations (printed one per line),
2 = usage/IO error.

Usage: python scripts/check_metrics_schema.py
           [--kind metrics|trace|memory|lint|ckpt|guard|goodput|roofline
                   |cluster|integrity]
           FILE
"""

from __future__ import annotations

import json
import math
import sys
from typing import Dict, List, Optional

REQUIRED = (
    "step", "loss", "loss_scale", "grad_norm", "param_norm",
    "overflow_count", "skip_count", "growth_count", "backoff_count",
    "step_time_ms", "throughput_steps_per_s", "mfu",
)
COUNTERS = ("step", "overflow_count", "skip_count", "growth_count",
            "backoff_count")
NULLABLE = ("step_time_ms", "throughput_steps_per_s", "mfu",
            "collective_bytes", "loss", "grad_norm", "param_norm",
            "wire_by_dtype", "logical_bytes", "wire_to_logical")

# --- trace-event / crash-dump schema -----------------------------------------

TRACE_KINDS = ("span", "step", "crash", "watchdog")
#: required keys per trace-event kind (beyond "kind" itself)
TRACE_REQUIRED = {
    "span": ("name", "dur_ms"),
    "step": ("step", "spans"),
    "crash": ("reason", "rank", "last_completed_span", "in_flight_spans"),
    "watchdog": ("reason", "rank", "seconds_since_last_step", "stacks",
                 "silent_ranks"),
}
#: keys that may be null per kind (everything else non-null when present)
TRACE_NULLABLE = {
    "span": ("step",),
    "step": ("step", "dur_ms", "metrics", "loss_scale"),
    "crash": ("last_completed_span", "in_flight_collective"),
    "watchdog": ("last_step", "last_completed_span",
                 "in_flight_collective"),
}


# --- memory / compile channel schema -----------------------------------------

MEMORY_KINDS = ("memory", "memory_report", "retrace", "compile")
#: required keys per memory-event kind (beyond "kind" itself)
MEMORY_REQUIRED = {
    "memory": ("rank",),
    "memory_report": ("rank", "total_bytes", "peak_live_bytes",
                      "classes"),
    "retrace": ("fn", "changed"),
    "compile": ("fn", "dur_ms"),
}
#: keys that may be null per kind (everything else non-null when present)
MEMORY_NULLABLE = {
    "memory": ("step", "bytes_in_use", "peak_bytes_in_use",
               "bytes_limit"),
    "memory_report": ("step", "hbm_limit", "batch_size"),
    "retrace": ("step",),
    "compile": ("step", "changed"),
}
#: byte-count fields that must be non-negative integers when present
MEMORY_BYTE_FIELDS = ("total_bytes", "attributed_bytes",
                      "peak_live_bytes", "batch_bytes", "bytes_in_use",
                      "peak_bytes_in_use", "bytes_limit", "hbm_limit")


# --- lint channel schema ------------------------------------------------------

LINT_KINDS = ("lint_report", "lint_finding")
LINT_SEVERITIES = ("error", "warning", "info")
#: link classes a cross-rank (APX2xx) finding's bytes ride
LINT_HOPS = ("ici", "dcn")
#: required keys per lint-event kind (beyond "kind" itself)
LINT_REQUIRED = {
    "lint_report": ("n_findings", "by_severity"),
    "lint_finding": ("rule", "id", "severity", "message"),
}
#: keys that may be null per kind (everything else non-null when
#: present; axes/ranks/hop are the SPMD-pass evidence — null on
#: single-program findings)
LINT_NULLABLE = {
    "lint_report": ("step", "fn"),
    "lint_finding": ("step", "fn", "op", "scope", "bytes", "fix",
                     "axes", "ranks", "hop"),
}


# --- ckpt channel schema ------------------------------------------------------

CKPT_KINDS = ("ckpt_save", "ckpt_restore", "ckpt_escalation")
CKPT_ACTIONS = ("checkpoint+dump+exit", "checkpoint+dump")
#: required keys per ckpt-event kind (beyond "kind" itself)
CKPT_REQUIRED = {
    "ckpt_save": ("step", "path", "bytes", "stall_ms", "dur_ms"),
    "ckpt_restore": ("step", "path", "dur_ms"),
    "ckpt_escalation": ("reason", "action"),
}
#: keys that may be null per kind (everything else non-null when present)
CKPT_NULLABLE = {
    "ckpt_save": (),
    "ckpt_restore": (),
    "ckpt_escalation": ("path", "step", "exit_code"),
}


# --- cluster control-plane channel schema -------------------------------------

CLUSTER_KINDS = ("cluster_lease", "cluster_generation", "cluster_fence",
                 "cluster_coord")
#: action enums per cluster-event kind (keep in lockstep with
#: apex_tpu/cluster/membership.py / coordinator.py emitters)
CLUSTER_ACTIONS = {
    "cluster_lease": ("acquire", "release", "expire", "gc"),
    "cluster_generation": ("bump", "observe"),
    "cluster_fence": ("refused_commit", "refused_write",
                      "refused_delete", "refused_intent"),
    "cluster_coord": ("propose", "resolve", "barrier_timeout",
                      "collective_hang"),
}
#: required keys per cluster-event kind (beyond "kind" itself)
CLUSTER_REQUIRED = {
    "cluster_lease": ("action", "generation"),
    "cluster_generation": ("action", "generation"),
    "cluster_fence": ("action", "generation", "current_generation"),
    "cluster_coord": ("action", "generation"),
}
#: keys that may be null per kind (everything else non-null when
#: present) — deadline/target fields are nullable by design: an
#: escalate-resolve has no rewind target, an unreadable lease no
#: expires_at, and a rejoin-observe no prev epoch
CLUSTER_NULLABLE = {
    "cluster_lease": ("expires_at", "reason"),
    "cluster_generation": ("reason", "prev_generation"),
    "cluster_fence": ("path", "step", "reason"),
    "cluster_coord": ("good_step", "target_step", "deadline_s",
                      "reason"),
}


def check_cluster_lines(lines) -> List[str]:
    """All cluster-channel violations in an iterable of JSONL lines
    (empty = ok). Validates membership-lease edges, generation
    commits (monotone, non-negative), fence refusals and
    recovery-coordination rounds."""
    errors: List[str] = []
    n_records = 0
    last_bump: Optional[int] = None
    for i, rec in _iter_objects(lines, errors):
        n_records += 1
        kind = rec.get("kind")
        if kind not in CLUSTER_KINDS:
            errors.append(f"line {i}: 'kind' must be one of "
                          f"{CLUSTER_KINDS}, got {kind!r}")
            continue
        for key in CLUSTER_REQUIRED[kind]:
            if key not in rec:
                errors.append(f"line {i}: {kind} event missing required "
                              f"key {key!r}")
        nullable = CLUSTER_NULLABLE[kind]
        for key, v in rec.items():
            if v is None and key not in nullable:
                errors.append(f"line {i}: {kind} key {key!r} is null "
                              f"(only {nullable} may be)")
        _check_finite_numbers(i, rec, errors)
        _check_counter(i, rec, "rank", errors, what="field")
        for key in ("generation", "current_generation",
                    "prev_generation", "new_generation", "good_step",
                    "target_step", "step", "expired_rank", "leader",
                    "n_removed", "n_refused", "n_intents"):
            _check_counter(i, rec, key, errors, what="field")
        act = rec.get("action")
        if act is not None and act not in CLUSTER_ACTIONS[kind]:
            errors.append(f"line {i}: {kind} 'action' must be one of "
                          f"{CLUSTER_ACTIONS[kind]}, got {act!r}")
        for dk in ("ttl_s", "deadline_s", "age_s", "wall_time",
                   "expires_at"):
            v = rec.get(dk)
            if dk not in rec or v is None:
                continue
            if not _is_number(v) or (v < 0 and dk != "expires_at"):
                errors.append(f"line {i}: {dk!r} must be a non-negative "
                              f"number, got {v!r}")
        if kind == "cluster_generation" and act == "bump":
            gen, prev = rec.get("generation"), rec.get("prev_generation")
            if (isinstance(gen, int) and isinstance(prev, int)
                    and not isinstance(gen, bool)
                    and not isinstance(prev, bool) and gen <= prev):
                errors.append(f"line {i}: generation bump goes backwards "
                              f"({prev} -> {gen})")
            if isinstance(gen, int) and not isinstance(gen, bool):
                if last_bump is not None and gen < last_bump:
                    errors.append(f"line {i}: bump generation {gen} "
                                  f"below an earlier bump {last_bump} — "
                                  "epochs must be monotone")
                last_bump = gen
        if kind == "cluster_fence":
            what = rec.get("what")
            if what is not None and not isinstance(what, str):
                errors.append(f"line {i}: 'what' must be a string")
        if kind == "cluster_coord":
            for lk in ("ranks", "missing"):
                v = rec.get(lk)
                if v is not None and lk in rec and not (
                        isinstance(v, list)
                        and all(isinstance(r, int)
                                and not isinstance(r, bool)
                                and r >= 0 for r in v)):
                    errors.append(f"line {i}: {lk!r} must be a list of "
                                  "non-negative rank ids")
            for sk in ("proposed", "decided", "collective", "what"):
                v = rec.get(sk)
                if v is not None and sk in rec and not isinstance(v, str):
                    errors.append(f"line {i}: {sk!r} must be a string")
    if n_records == 0:
        errors.append("no records found")
    return errors


# --- goodput / straggler / linkfit channel schema -----------------------------

GOODPUT_KINDS = ("goodput", "straggler", "linkfit")
#: the ledger's bucket names (keep in lockstep with
#: apex_tpu/monitor/goodput.py BUCKETS)
GOODPUT_BUCKETS = ("compute", "exposed_comm", "input_wait",
                   "host_callback", "ckpt_stall", "recompile",
                   "guard_rewind", "other")
#: link classes a linkfit may calibrate (mesh-model LINK_CLASSES)
GOODPUT_LINKS = ("ici", "dcn")
#: required keys per goodput-event kind (beyond "kind" itself)
GOODPUT_REQUIRED = {
    "goodput": ("rank", "wall_ms", "buckets_ms", "closure_err"),
    "straggler": ("step", "rank", "lag_ms", "z", "consecutive",
                  "n_ranks"),
    "linkfit": ("link", "bytes_per_s", "residual", "n_samples"),
}
#: keys that may be null per kind (everything else non-null when present)
GOODPUT_NULLABLE = {
    "goodput": ("step", "goodput_frac"),
    "straggler": ("slowest_span", "span_class", "slowest_span_ms"),
    "linkfit": ("axis", "alpha_us"),
}


def check_goodput_lines(lines) -> List[str]:
    """All goodput-channel violations in an iterable of JSONL lines
    (empty = ok). Validates per-step wall-time decompositions,
    straggler warnings, and link-calibration fits."""
    errors: List[str] = []
    n_records = 0
    for i, rec in _iter_objects(lines, errors):
        n_records += 1
        kind = rec.get("kind")
        if kind not in GOODPUT_KINDS:
            errors.append(f"line {i}: 'kind' must be one of "
                          f"{GOODPUT_KINDS}, got {kind!r}")
            continue
        for key in GOODPUT_REQUIRED[kind]:
            if key not in rec:
                errors.append(f"line {i}: {kind} event missing required "
                              f"key {key!r}")
        nullable = GOODPUT_NULLABLE[kind]
        for key, v in rec.items():
            if v is None and key not in nullable:
                errors.append(f"line {i}: {kind} key {key!r} is null "
                              f"(only {nullable} may be)")
        _check_finite_numbers(i, rec, errors)
        _check_counter(i, rec, "rank", errors, what="field")
        for key in ("step", "consecutive", "n_ranks", "n_samples"):
            _check_counter(i, rec, key, errors, what="field")
        for dk in ("wall_ms", "closure_err", "slowest_span_ms",
                   "wall_time", "residual", "alpha_us"):
            v = rec.get(dk)
            if dk not in rec or v is None:
                continue
            if not _is_number(v) or v < 0:
                errors.append(f"line {i}: {dk!r} must be a non-negative "
                              f"number, got {v!r}")
        if kind == "goodput":
            buckets = rec.get("buckets_ms")
            if not isinstance(buckets, dict):
                errors.append(f"line {i}: 'buckets_ms' must be an object")
            else:
                for bk, bv in buckets.items():
                    if bk not in GOODPUT_BUCKETS:
                        errors.append(f"line {i}: buckets_ms key {bk!r} "
                                      f"not in {GOODPUT_BUCKETS}")
                    if not _is_number(bv) or bv < 0:
                        errors.append(
                            f"line {i}: buckets_ms[{bk!r}] must be a "
                            f"non-negative number, got {bv!r}")
            gf = rec.get("goodput_frac")
            if gf is not None and "goodput_frac" in rec and (
                    not _is_number(gf) or gf < 0):
                errors.append(f"line {i}: 'goodput_frac' must be a "
                              f"non-negative number, got {gf!r}")
        if kind == "straggler":
            for dk in ("lag_ms", "z"):
                v = rec.get(dk)
                if v is not None and dk in rec and not _is_number(v):
                    errors.append(f"line {i}: {dk!r} must be a number, "
                                  f"got {v!r}")
            for sk in ("slowest_span", "span_class"):
                v = rec.get(sk)
                if v is not None and sk in rec and not isinstance(v, str):
                    errors.append(f"line {i}: {sk!r} must be a string "
                                  f"or null, got {v!r}")
        if kind == "linkfit":
            link = rec.get("link")
            if link is not None and link not in GOODPUT_LINKS:
                errors.append(f"line {i}: 'link' must be one of "
                              f"{GOODPUT_LINKS}, got {link!r}")
            bps = rec.get("bytes_per_s")
            if bps is not None and "bytes_per_s" in rec and (
                    not _is_number(bps) or bps <= 0):
                errors.append(f"line {i}: 'bytes_per_s' must be a "
                              f"positive number, got {bps!r}")
    if n_records == 0:
        errors.append("no records found")
    return errors


# --- roofline / sentinel channel schema ---------------------------------------

ROOFLINE_KINDS = ("roofline", "regress")
#: roofline bound classes (keep in lockstep with
#: apex_tpu/prof/roofline.py BOUND_CLASSES)
ROOFLINE_BOUNDS = ("compute", "memory", "unknown")
#: sentinel degradation directions (prof/sentinel.py DIRECTIONS)
REGRESS_DIRECTIONS = ("higher", "lower")
#: required keys per roofline-event kind (beyond "kind" itself)
ROOFLINE_REQUIRED = {
    "roofline": ("op", "family", "bound", "flops", "bytes",
                 "attainable_us", "fingerprint"),
    "regress": ("metric", "direction", "regressed", "n_history",
                "fingerprint"),
}
#: keys that may be null per kind (everything else non-null when
#: present); measured_us/efficiency/gap_us are null on AOT-only rows,
#: the regress baselines on insufficient-history verdicts
ROOFLINE_NULLABLE = {
    "roofline": ("step", "measured_us", "efficiency", "gap_us",
                 "scope", "dtype"),
    "regress": ("latest", "baseline", "mad", "threshold",
                "degradation"),
}


def check_roofline_lines(lines) -> List[str]:
    """All roofline-channel violations in an iterable of JSONL lines
    (empty = ok). Validates per-op roofline verdicts and perf-sentinel
    regression verdicts."""
    errors: List[str] = []
    n_records = 0
    for i, rec in _iter_objects(lines, errors):
        n_records += 1
        kind = rec.get("kind")
        if kind not in ROOFLINE_KINDS:
            errors.append(f"line {i}: 'kind' must be one of "
                          f"{ROOFLINE_KINDS}, got {kind!r}")
            continue
        for key in ROOFLINE_REQUIRED[kind]:
            if key not in rec:
                errors.append(f"line {i}: {kind} event missing required "
                              f"key {key!r}")
        nullable = ROOFLINE_NULLABLE[kind]
        for key, v in rec.items():
            if v is None and key not in nullable:
                errors.append(f"line {i}: {kind} key {key!r} is null "
                              f"(only {nullable} may be)")
        _check_finite_numbers(i, rec, errors)
        _check_counter(i, rec, "rank", errors, what="field")
        for key in ("step", "occurrences", "n_history"):
            _check_counter(i, rec, key, errors, what="field")
        if "fingerprint" in rec and not isinstance(
                rec.get("fingerprint"), str):
            errors.append(f"line {i}: 'fingerprint' must be a string")
        if kind == "roofline":
            bound = rec.get("bound")
            if bound is not None and bound not in ROOFLINE_BOUNDS:
                errors.append(f"line {i}: 'bound' must be one of "
                              f"{ROOFLINE_BOUNDS}, got {bound!r}")
            eff = rec.get("efficiency")
            if eff is not None and "efficiency" in rec:
                if not _is_number(eff) or not 0.0 <= eff <= 1.0:
                    errors.append(f"line {i}: 'efficiency' must be in "
                                  f"[0, 1] or null, got {eff!r}")
            for dk in ("flops", "bytes", "attainable_us", "measured_us",
                       "gap_us"):
                v = rec.get(dk)
                if dk not in rec or v is None:
                    continue
                if not _is_number(v) or v < 0:
                    errors.append(f"line {i}: {dk!r} must be a "
                                  f"non-negative number, got {v!r}")
            for sk in ("op", "family"):
                if sk in rec and not isinstance(rec.get(sk), str):
                    errors.append(f"line {i}: {sk!r} must be a string")
        if kind == "regress":
            d = rec.get("direction")
            if d is not None and d not in REGRESS_DIRECTIONS:
                errors.append(f"line {i}: 'direction' must be one of "
                              f"{REGRESS_DIRECTIONS}, got {d!r}")
            if not isinstance(rec.get("metric"), str):
                errors.append(f"line {i}: 'metric' must be a string")
            for bk in ("regressed", "waived"):
                v = rec.get(bk)
                if v is not None and bk in rec and not isinstance(v,
                                                                  bool):
                    errors.append(f"line {i}: {bk!r} must be a boolean")
            for dk in ("mad", "threshold"):
                v = rec.get(dk)
                if v is not None and dk in rec and (
                        not _is_number(v) or v < 0):
                    errors.append(f"line {i}: {dk!r} must be a "
                                  f"non-negative number, got {v!r}")
    if n_records == 0:
        errors.append("no records found")
    return errors


# --- guard channel schema -----------------------------------------------------

GUARD_KINDS = ("guard_anomaly", "guard_action", "guard_rewind")
GUARD_ACTIONS = ("skip", "rewind", "escalate", "observe")
GUARD_CLASSES = ("loss_spike", "grad_explosion", "nonfinite_grad",
                 "nonfinite_loss", "nonfinite_param",
                 "replica_divergence")
#: required keys per guard-event kind (beyond "kind" itself)
GUARD_REQUIRED = {
    "guard_anomaly": ("step", "classes"),
    "guard_action": ("step", "action"),
    "guard_rewind": ("step", "from_step", "to_step", "path",
                     "skipped_batches"),
}
#: keys that may be null per kind (everything else non-null when present)
GUARD_NULLABLE = {
    "guard_anomaly": ("z",),
    "guard_action": ("reason",),
    "guard_rewind": ("reason",),
}


def check_guard_lines(lines) -> List[str]:
    """All guard-channel violations in an iterable of JSONL lines
    (empty = ok). Validates anomaly reports, ladder decisions and
    rewind records."""
    errors: List[str] = []
    n_records = 0
    for i, rec in _iter_objects(lines, errors):
        n_records += 1
        kind = rec.get("kind")
        if kind not in GUARD_KINDS:
            errors.append(f"line {i}: 'kind' must be one of "
                          f"{GUARD_KINDS}, got {kind!r}")
            continue
        for key in GUARD_REQUIRED[kind]:
            if key not in rec:
                errors.append(f"line {i}: {kind} event missing required "
                              f"key {key!r}")
        nullable = GUARD_NULLABLE[kind]
        for key, v in rec.items():
            if v is None and key not in nullable:
                errors.append(f"line {i}: {kind} key {key!r} is null "
                              f"(only {nullable} may be)")
        _check_finite_numbers(i, rec, errors)
        _check_counter(i, rec, "rank", errors, what="field")
        for key in ("step", "from_step", "to_step", "skipped_batches",
                    "fallbacks", "consecutive", "skip_count"):
            _check_counter(i, rec, key, errors, what="field")
        classes = rec.get("classes")
        if classes is not None:
            if not isinstance(classes, list):
                errors.append(f"line {i}: 'classes' must be a list")
            else:
                for c in classes:
                    if c not in GUARD_CLASSES:
                        errors.append(f"line {i}: classes entry {c!r} "
                                      f"not in {GUARD_CLASSES}")
        if kind == "guard_action":
            act = rec.get("action")
            if act is not None and act not in GUARD_ACTIONS:
                errors.append(f"line {i}: 'action' must be one of "
                              f"{GUARD_ACTIONS}, got {act!r}")
        if kind == "guard_rewind":
            p = rec.get("path")
            if "path" in rec and not isinstance(p, str):
                errors.append(f"line {i}: 'path' must be a string, "
                              f"got {p!r}")
            fs, ts = rec.get("from_step"), rec.get("to_step")
            if (isinstance(fs, int) and isinstance(ts, int)
                    and not isinstance(fs, bool)
                    and not isinstance(ts, bool) and ts > fs):
                errors.append(f"line {i}: rewind goes forwards "
                              f"(to_step {ts} > from_step {fs})")
    if n_records == 0:
        errors.append("no records found")
    return errors


# --- integrity channel schema -------------------------------------------------

INTEGRITY_KINDS = ("integrity_check", "integrity_vote",
                   "integrity_repair")
INTEGRITY_VOTE_ACTIONS = ("repair", "rewind", "escalate", "observe")
INTEGRITY_REPAIR_ACTIONS = ("repair", "repair_failed")
#: required keys per integrity-event kind (beyond "kind" itself)
INTEGRITY_REQUIRED = {
    "integrity_check": ("step", "check_step", "n_ranks",
                        "mismatch_count"),
    "integrity_vote": ("step", "action", "n_ranks", "minority"),
    "integrity_repair": ("step", "action", "source_rank", "minority",
                         "verified"),
}
#: keys that may be null per kind (everything else non-null when
#: present). "generation" is the cluster fence token — null until a
#: membership is wired; a no-majority vote has no source/majority_fp.
INTEGRITY_NULLABLE = {
    # check_step is null when the counter moved but no check ran under
    # THIS electorate (the integrity_resize elastic-resume sentinel)
    "integrity_check": ("generation", "check_step"),
    "integrity_vote": ("generation", "reason", "source_rank",
                       "majority_fp"),
    "integrity_repair": ("generation", "reason"),
}


def check_integrity_lines(lines) -> List[str]:
    """All integrity-channel violations in an iterable of JSONL lines
    (empty = ok). Validates mismatch reports, quorum votes (with their
    minority rank lists) and repair records."""
    errors: List[str] = []
    n_records = 0
    for i, rec in _iter_objects(lines, errors):
        n_records += 1
        kind = rec.get("kind")
        if kind not in INTEGRITY_KINDS:
            errors.append(f"line {i}: 'kind' must be one of "
                          f"{INTEGRITY_KINDS}, got {kind!r}")
            continue
        for key in INTEGRITY_REQUIRED[kind]:
            if key not in rec:
                errors.append(f"line {i}: {kind} event missing required "
                              f"key {key!r}")
        nullable = INTEGRITY_NULLABLE[kind]
        for key, v in rec.items():
            if v is None and key not in nullable:
                errors.append(f"line {i}: {kind} key {key!r} is null "
                              f"(only {nullable} may be)")
        _check_finite_numbers(i, rec, errors)
        for key in ("rank", "step", "check_step", "n_ranks",
                    "mismatch_count", "new_mismatches", "fp_min",
                    "fp_max", "source_rank", "majority_fp",
                    "generation"):
            _check_counter(i, rec, key, errors, what="field")
        minority = rec.get("minority")
        if minority is not None:
            if not (isinstance(minority, list)
                    and all(isinstance(r, int)
                            and not isinstance(r, bool)
                            and r >= 0 for r in minority)):
                errors.append(f"line {i}: 'minority' must be a list of "
                              f"non-negative replica ranks, got "
                              f"{minority!r}")
        if kind == "integrity_check":
            h = rec.get("healed")
            if "healed" in rec and not isinstance(h, bool):
                errors.append(f"line {i}: 'healed' must be a boolean, "
                              f"got {h!r}")
        if kind == "integrity_vote":
            act = rec.get("action")
            if act is not None and act not in INTEGRITY_VOTE_ACTIONS:
                errors.append(f"line {i}: 'action' must be one of "
                              f"{INTEGRITY_VOTE_ACTIONS}, got {act!r}")
        if kind == "integrity_repair":
            act = rec.get("action")
            if act is not None and act not in INTEGRITY_REPAIR_ACTIONS:
                errors.append(f"line {i}: 'action' must be one of "
                              f"{INTEGRITY_REPAIR_ACTIONS}, got "
                              f"{act!r}")
            ver = rec.get("verified")
            if "verified" in rec and not isinstance(ver, bool):
                errors.append(f"line {i}: 'verified' must be a "
                              f"boolean, got {ver!r}")
            if (isinstance(ver, bool) and isinstance(act, str)
                    and (act == "repair") != ver):
                errors.append(f"line {i}: action {act!r} contradicts "
                              f"verified={ver}")
    if n_records == 0:
        errors.append("no records found")
    return errors


def check_ckpt_lines(lines) -> List[str]:
    """All ckpt-channel violations in an iterable of JSONL lines
    (empty = ok). Validates save commits, (elastic) restores, and
    escalation records."""
    errors: List[str] = []
    n_records = 0
    for i, rec in _iter_objects(lines, errors):
        n_records += 1
        kind = rec.get("kind")
        if kind not in CKPT_KINDS:
            errors.append(f"line {i}: 'kind' must be one of "
                          f"{CKPT_KINDS}, got {kind!r}")
            continue
        for key in CKPT_REQUIRED[kind]:
            if key not in rec:
                errors.append(f"line {i}: {kind} event missing required "
                              f"key {key!r}")
        nullable = CKPT_NULLABLE[kind]
        for key, v in rec.items():
            if v is None and key not in nullable:
                errors.append(f"line {i}: {kind} key {key!r} is null "
                              f"(only {nullable} may be)")
        _check_finite_numbers(i, rec, errors)
        _check_counter(i, rec, "rank", errors, what="field")
        _check_counter(i, rec, "step", errors, what="field")
        _check_counter(i, rec, "bytes", errors, what="byte field")
        for key in ("n_arrays", "resharded", "from_processes",
                    "exit_code"):
            _check_counter(i, rec, key, errors, what="field")
        for dk in ("stall_ms", "dur_ms", "wall_time"):
            v = rec.get(dk)
            if dk not in rec or v is None:
                continue
            if not _is_number(v) or v < 0:
                errors.append(f"line {i}: {dk!r} must be a non-negative "
                              f"number, got {v!r}")
        if kind != "ckpt_escalation":
            p = rec.get("path")
            if "path" in rec and not isinstance(p, str):
                errors.append(f"line {i}: 'path' must be a string, "
                              f"got {p!r}")
        if kind == "ckpt_escalation":
            if not isinstance(rec.get("reason"), str):
                errors.append(f"line {i}: escalation 'reason' must be a "
                              "string")
            act = rec.get("action")
            if act is not None and act not in CKPT_ACTIONS:
                errors.append(f"line {i}: 'action' must be one of "
                              f"{CKPT_ACTIONS}, got {act!r}")
    if n_records == 0:
        errors.append("no records found")
    return errors


# --- shared core -------------------------------------------------------------

def _iter_objects(lines, errors: List[str]):
    """Parse JSONL, reporting bad lines; yields (lineno, dict)."""
    for i, raw in enumerate(lines, start=1):
        line = raw.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError as e:
            errors.append(f"line {i}: not valid JSON ({e})")
            continue
        if not isinstance(rec, dict):
            errors.append(f"line {i}: not a JSON object")
            continue
        yield i, rec


def _is_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _check_finite_numbers(i: int, rec: Dict, errors: List[str],
                          prefix: str = "") -> None:
    """Every numeric value (one level of nesting included) is finite —
    Infinity/NaN are not strict JSON and never belong on the wire."""
    for k, v in rec.items():
        if _is_number(v) and not math.isfinite(v):
            errors.append(f"line {i}: {prefix}{k!r} is non-finite ({v!r})")
        elif isinstance(v, dict):
            _check_finite_numbers(i, v, errors, prefix=f"{k}.")
        elif isinstance(v, list):
            for j, item in enumerate(v):
                if isinstance(item, dict):
                    _check_finite_numbers(i, item, errors,
                                          prefix=f"{k}[{j}].")
                elif _is_number(item) and not math.isfinite(item):
                    errors.append(f"line {i}: {k}[{j}] is non-finite "
                                  f"({item!r})")


def _check_counter(i: int, rec: Dict, key: str, errors: List[str],
                   what: str = "counter") -> None:
    v = rec.get(key)
    if v is None or key not in rec:
        return
    if not isinstance(v, int) or isinstance(v, bool) or v < 0:
        errors.append(f"line {i}: {what} {key!r} must be a "
                      f"non-negative int, got {v!r}")


# --- metrics schema ----------------------------------------------------------

def check_lines(lines) -> List[str]:
    """All metrics-schema violations in an iterable of JSONL lines
    (empty = ok)."""
    errors: List[str] = []
    prev_step = None
    n_records = 0
    for i, rec in _iter_objects(lines, errors):
        n_records += 1
        for key in REQUIRED:
            if key not in rec:
                errors.append(f"line {i}: missing required key {key!r}")
        for key, v in rec.items():
            if v is None and key not in NULLABLE:
                errors.append(f"line {i}: {key!r} is null "
                              f"(only {NULLABLE} may be)")
        _check_finite_numbers(i, rec, errors)
        for key in COUNTERS:
            _check_counter(i, rec, key, errors)
        step = rec.get("step")
        if isinstance(step, int) and not isinstance(step, bool):
            if prev_step is not None and step <= prev_step:
                errors.append(f"line {i}: step {step} not greater than "
                              f"previous step {prev_step}")
            prev_step = step
    if n_records == 0:
        errors.append("no records found")
    return errors


# --- trace schema ------------------------------------------------------------

def check_trace_lines(lines) -> List[str]:
    """All trace-schema violations in an iterable of JSONL lines
    (empty = ok). Validates span/step timeline events, flight-recorder
    crash dumps, and watchdog hang dumps."""
    errors: List[str] = []
    n_records = 0
    for i, rec in _iter_objects(lines, errors):
        n_records += 1
        kind = rec.get("kind")
        if kind not in TRACE_KINDS:
            errors.append(f"line {i}: 'kind' must be one of "
                          f"{TRACE_KINDS}, got {kind!r}")
            continue
        for key in TRACE_REQUIRED[kind]:
            if key not in rec:
                errors.append(f"line {i}: {kind} event missing required "
                              f"key {key!r}")
        nullable = TRACE_NULLABLE[kind]
        for key, v in rec.items():
            if v is None and key not in nullable:
                errors.append(f"line {i}: {kind} key {key!r} is null "
                              f"(only {nullable} may be)")
        _check_finite_numbers(i, rec, errors)
        _check_counter(i, rec, "rank", errors, what="field")
        _check_counter(i, rec, "pid", errors, what="field")
        for dk in ("dur_ms", "t_ms", "wall_time",
                   "seconds_since_last_step", "deadline_s"):
            if dk not in rec or rec[dk] is None:
                continue
            v = rec[dk]
            if not _is_number(v):
                errors.append(f"line {i}: {dk!r} must be a number, "
                              f"got {v!r}")
            elif v < 0 and dk != "t_ms":
                errors.append(f"line {i}: {dk!r} must be >= 0, got {v!r}")
        if kind == "span" and not isinstance(rec.get("name"), str):
            errors.append(f"line {i}: span 'name' must be a string")
        if kind == "step":
            spans = rec.get("spans")
            if not isinstance(spans, list):
                errors.append(f"line {i}: step 'spans' must be a list")
            else:
                for j, s in enumerate(spans):
                    if (not isinstance(s, dict)
                            or not isinstance(s.get("name"), str)
                            or not _is_number(s.get("dur_ms"))):
                        errors.append(f"line {i}: spans[{j}] must be "
                                      "{name: str, dur_ms: number}")
            _check_counter(i, rec, "step", errors, what="field")
        if kind in ("crash", "watchdog"):
            if not isinstance(rec.get("reason"), str):
                errors.append(f"line {i}: {kind} 'reason' must be a "
                              "string")
            lcs = rec.get("last_completed_span")
            if lcs is not None and not isinstance(lcs, str):
                errors.append(f"line {i}: 'last_completed_span' must be "
                              "a string or null")
            ifs = rec.get("in_flight_spans")
            if ifs is not None and not isinstance(ifs, list):
                errors.append(f"line {i}: 'in_flight_spans' must be a "
                              "list")
        if kind == "watchdog":
            if not isinstance(rec.get("stacks"), dict):
                errors.append(f"line {i}: watchdog 'stacks' must be an "
                              "object")
            sr = rec.get("silent_ranks")
            if not (isinstance(sr, list)
                    and all(isinstance(r, int) and not isinstance(r, bool)
                            and r >= 0 for r in sr)):
                errors.append(f"line {i}: 'silent_ranks' must be a list "
                              "of non-negative ints")
    if n_records == 0:
        errors.append("no records found")
    return errors


# --- memory schema -----------------------------------------------------------

def check_memory_lines(lines) -> List[str]:
    """All memory-channel violations in an iterable of JSONL lines
    (empty = ok). Validates runtime allocator samples, compiled-step
    memory reports, and retrace-detector events."""
    errors: List[str] = []
    n_records = 0
    for i, rec in _iter_objects(lines, errors):
        n_records += 1
        kind = rec.get("kind")
        if kind not in MEMORY_KINDS:
            errors.append(f"line {i}: 'kind' must be one of "
                          f"{MEMORY_KINDS}, got {kind!r}")
            continue
        for key in MEMORY_REQUIRED[kind]:
            if key not in rec:
                errors.append(f"line {i}: {kind} event missing required "
                              f"key {key!r}")
        nullable = MEMORY_NULLABLE[kind]
        for key, v in rec.items():
            if v is None and key not in nullable:
                errors.append(f"line {i}: {kind} key {key!r} is null "
                              f"(only {nullable} may be)")
        _check_finite_numbers(i, rec, errors)
        _check_counter(i, rec, "rank", errors, what="field")
        for key in MEMORY_BYTE_FIELDS:
            _check_counter(i, rec, key, errors, what="byte field")
        if kind in ("retrace", "compile"):
            if not isinstance(rec.get("fn"), str):
                errors.append(f"line {i}: {kind} 'fn' must be a string")
            dm = rec.get("dur_ms")
            if dm is not None and "dur_ms" in rec and (
                    not _is_number(dm) or dm < 0):
                errors.append(f"line {i}: 'dur_ms' must be a "
                              f"non-negative number, got {dm!r}")
        if kind == "memory_report":
            classes = rec.get("classes")
            if not isinstance(classes, dict):
                errors.append(f"line {i}: 'classes' must be an object")
            else:
                for ck, cv in classes.items():
                    if (not isinstance(cv, int) or isinstance(cv, bool)
                            or cv < 0):
                        errors.append(
                            f"line {i}: classes[{ck!r}] must be a "
                            f"non-negative int, got {cv!r}")
            tb = rec.get("top_buffers")
            if tb is not None and not (
                    isinstance(tb, list)
                    and all(isinstance(b, dict)
                            and isinstance(b.get("name"), str)
                            and isinstance(b.get("bytes"), int)
                            for b in tb)):
                errors.append(f"line {i}: 'top_buffers' must be a list "
                              "of {name: str, bytes: int, ...}")
    if n_records == 0:
        errors.append("no records found")
    return errors


# --- lint schema --------------------------------------------------------------

def check_lint_lines(lines) -> List[str]:
    """All lint-channel violations in an iterable of JSONL lines
    (empty = ok). Validates apexlint report headers and findings."""
    errors: List[str] = []
    n_records = 0
    for i, rec in _iter_objects(lines, errors):
        n_records += 1
        kind = rec.get("kind")
        if kind not in LINT_KINDS:
            errors.append(f"line {i}: 'kind' must be one of "
                          f"{LINT_KINDS}, got {kind!r}")
            continue
        for key in LINT_REQUIRED[kind]:
            if key not in rec:
                errors.append(f"line {i}: {kind} event missing required "
                              f"key {key!r}")
        nullable = LINT_NULLABLE[kind]
        for key, v in rec.items():
            if v is None and key not in nullable:
                errors.append(f"line {i}: {kind} key {key!r} is null "
                              f"(only {nullable} may be)")
        _check_finite_numbers(i, rec, errors)
        _check_counter(i, rec, "bytes", errors, what="byte field")
        _check_counter(i, rec, "count", errors, what="field")
        _check_counter(i, rec, "step", errors, what="field")
        if kind == "lint_report":
            _check_counter(i, rec, "n_findings", errors, what="field")
            _check_counter(i, rec, "suppressed", errors, what="field")
            sev = rec.get("by_severity")
            if not isinstance(sev, dict):
                errors.append(f"line {i}: 'by_severity' must be an "
                              "object")
            else:
                for sk, sv in sev.items():
                    if sk not in LINT_SEVERITIES:
                        errors.append(f"line {i}: by_severity key "
                                      f"{sk!r} not in {LINT_SEVERITIES}")
                    if (not isinstance(sv, int) or isinstance(sv, bool)
                            or sv < 0):
                        errors.append(f"line {i}: by_severity[{sk!r}] "
                                      f"must be a non-negative int, got "
                                      f"{sv!r}")
        if kind == "lint_finding":
            for key in ("rule", "id", "message"):
                if key in rec and not isinstance(rec.get(key), str):
                    errors.append(f"line {i}: {key!r} must be a string")
            sev = rec.get("severity")
            if sev is not None and sev not in LINT_SEVERITIES:
                errors.append(f"line {i}: 'severity' must be one of "
                              f"{LINT_SEVERITIES}, got {sev!r}")
            axes = rec.get("axes")
            if axes is not None and not (
                    isinstance(axes, list)
                    and all(isinstance(a, str) for a in axes)):
                errors.append(f"line {i}: 'axes' must be a list of "
                              "mesh-axis names")
            ranks = rec.get("ranks")
            if ranks is not None and not (
                    isinstance(ranks, list) and len(ranks) == 2
                    and all(isinstance(r, int)
                            and not isinstance(r, bool)
                            and r >= 0 for r in ranks)):
                errors.append(f"line {i}: 'ranks' must be a pair of "
                              "non-negative rank ids")
            hop = rec.get("hop")
            if hop is not None and hop not in LINT_HOPS:
                errors.append(f"line {i}: 'hop' must be one of "
                              f"{LINT_HOPS}, got {hop!r}")
    if n_records == 0:
        errors.append("no records found")
    return errors


CHECKERS = {"metrics": check_lines, "trace": check_trace_lines,
            "memory": check_memory_lines, "lint": check_lint_lines,
            "ckpt": check_ckpt_lines, "guard": check_guard_lines,
            "goodput": check_goodput_lines,
            "roofline": check_roofline_lines,
            "cluster": check_cluster_lines,
            "integrity": check_integrity_lines}


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    kind = "metrics"
    files: List[str] = []
    it = iter(argv)
    for a in it:
        if a in ("-h", "--help"):
            print(__doc__)
            return 2
        if a == "--kind":
            kind = next(it, "")
        elif a.startswith("--kind="):
            kind = a.split("=", 1)[1]
        else:
            files.append(a)
    if kind not in CHECKERS or len(files) != 1:
        print(__doc__)
        return 2
    try:
        with open(files[0]) as f:
            errors = CHECKERS[kind](f)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        print(f"{files[0]}: INVALID ({len(errors)} violations)",
              file=sys.stderr)
        return 1
    print(f"{files[0]}: ok ({kind})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
