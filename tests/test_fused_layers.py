"""Fused LayerNorm / MLP / xentropy kernels vs jnp oracles.

Mirrors `tests/L0/run_fused_layer_norm/test_fused_layer_norm.py` (fused vs
torch.nn.LayerNorm fwd+bwd), `tests/L0/run_mlp/test_mlp.py` (MLP vs
nn.Sequential fwd+bwd), and `apex/contrib/test/test_label_smoothing.py`.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import ops


class TestFusedLayerNorm:
    @pytest.mark.parametrize("shape", [(16, 32, 64), (8, 768), (4, 7, 129),
                                       (3, 50)])
    def test_forward_affine(self, shape):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(*shape).astype(np.float32))
        w = jnp.asarray(rng.rand(shape[-1]).astype(np.float32) + 0.5)
        b = jnp.asarray(rng.randn(shape[-1]).astype(np.float32))
        got = ops.fused_layer_norm_affine(x, w, b, 1e-5)
        ref = ops.layer_norm_reference(x, w, b, 1e-5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-5)

    def test_forward_no_affine(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(6, 200).astype(np.float32))
        got = ops.fused_layer_norm(x, 1e-5)
        ref = ops.layer_norm_reference(x, None, None, 1e-5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-5)

    @pytest.mark.parametrize("shape", [(8, 768), (4, 7, 129)])
    def test_backward_matches_autodiff(self, shape):
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(*shape).astype(np.float32))
        w = jnp.asarray(rng.rand(shape[-1]).astype(np.float32) + 0.5)
        b = jnp.asarray(rng.randn(shape[-1]).astype(np.float32))

        def loss_fused(x_, w_, b_):
            return jnp.sum(jnp.square(
                ops.fused_layer_norm_affine(x_, w_, b_, 1e-5)))

        def loss_ref(x_, w_, b_):
            return jnp.sum(jnp.square(
                ops.layer_norm_reference(x_, w_, b_, 1e-5)))

        gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, w, b)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
        for a, e, name in zip(gf, gr, "xwb"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), atol=2e-4,
                err_msg=f"grad {name}")

    def test_bf16_io(self):
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(8, 256).astype(np.float32)
                        ).astype(jnp.bfloat16)
        w = jnp.ones((256,), jnp.float32)
        b = jnp.zeros((256,), jnp.float32)
        got = ops.fused_layer_norm_affine(x, w, b, 1e-5)
        assert got.dtype == jnp.bfloat16
        ref = ops.layer_norm_reference(x, w, b, 1e-5)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(ref, np.float32),
            atol=2e-2)

    def test_module(self):
        ln = ops.FusedLayerNorm(64)
        x = jnp.ones((4, 64))
        variables = ln.init(jax.random.PRNGKey(0), x)
        y = ln.apply(variables, x)
        assert y.shape == (4, 64)
        np.testing.assert_allclose(np.asarray(y), 0.0, atol=1e-5)


class TestFusedMLP:
    @pytest.mark.parametrize("activation", ["relu", "sigmoid", "none"])
    def test_forward(self, activation):
        rng = np.random.RandomState(4)
        sizes = [39, 128, 57]
        x = jnp.asarray(rng.randn(10, sizes[0]).astype(np.float32))
        ws = tuple(jnp.asarray(
            (rng.randn(sizes[i], sizes[i + 1]) / np.sqrt(sizes[i]))
            .astype(np.float32)) for i in range(len(sizes) - 1))
        bs = tuple(jnp.asarray(rng.randn(sizes[i + 1]).astype(np.float32)
                               * 0.1) for i in range(len(sizes) - 1))
        got = ops.fused_mlp(x, ws, bs, activation)
        ref = ops.mlp_reference(x, ws, bs, activation)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-4)

    def test_no_bias(self):
        rng = np.random.RandomState(5)
        x = jnp.asarray(rng.randn(6, 16).astype(np.float32))
        ws = (jnp.asarray(rng.randn(16, 24).astype(np.float32)),)
        got = ops.fused_mlp(x, ws, None, "relu")
        ref = ops.mlp_reference(x, ws, None, "relu")
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-5)

    def test_backward(self):
        rng = np.random.RandomState(6)
        sizes = [20, 64, 12]
        x = jnp.asarray(rng.randn(8, sizes[0]).astype(np.float32))
        ws = tuple(jnp.asarray(
            (rng.randn(sizes[i], sizes[i + 1]) / np.sqrt(sizes[i]))
            .astype(np.float32)) for i in range(2))
        bs = tuple(jnp.asarray(rng.randn(sizes[i + 1]).astype(np.float32)
                               * 0.1) for i in range(2))

        def lf(x_, ws_, bs_):
            return jnp.sum(jnp.sin(ops.fused_mlp(x_, ws_, bs_, "relu")))

        def lr(x_, ws_, bs_):
            return jnp.sum(jnp.sin(ops.mlp_reference(x_, ws_, bs_, "relu")))

        gf = jax.grad(lf, argnums=(0, 1, 2))(x, ws, bs)
        gr = jax.grad(lr, argnums=(0, 1, 2))(x, ws, bs)
        for a, e in zip(jax.tree_util.tree_leaves(gf),
                        jax.tree_util.tree_leaves(gr)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                       atol=1e-4)

    def test_module_params(self):
        mlp = ops.MLP([10, 20, 5], bias=True, activation="relu")
        x = jnp.ones((3, 10))
        variables = mlp.init(jax.random.PRNGKey(0), x)
        names = set(variables["params"].keys())
        assert names == {"weight_0", "weight_1", "bias_0", "bias_1"}
        y = mlp.apply(variables, x)
        assert y.shape == (3, 5)
        assert bool(jnp.all(y >= 0))  # trailing relu, like the reference


class TestSoftmaxCrossEntropy:
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    @pytest.mark.parametrize("vocab", [100, 128, 1000])
    def test_forward(self, smoothing, vocab):
        rng = np.random.RandomState(7)
        x = jnp.asarray(rng.randn(12, vocab).astype(np.float32) * 3)
        labels = jnp.asarray(rng.randint(0, vocab, 12), jnp.int32)
        got = ops.softmax_cross_entropy_loss(x, labels, smoothing)
        ref = ops.softmax_cross_entropy_reference(x, labels, smoothing)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("smoothing", [0.0, 0.15])
    def test_backward(self, smoothing):
        rng = np.random.RandomState(8)
        x = jnp.asarray(rng.randn(9, 257).astype(np.float32))
        labels = jnp.asarray(rng.randint(0, 257, 9), jnp.int32)

        def lf(x_):
            return jnp.sum(ops.softmax_cross_entropy_loss(
                x_, labels, smoothing) * 1.7)

        def lr(x_):
            return jnp.sum(ops.softmax_cross_entropy_reference(
                x_, labels, smoothing) * 1.7)

        gf = jax.grad(lf)(x)
        gr = jax.grad(lr)(x)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=1e-5)

    def test_ignored_labels(self):
        rng = np.random.RandomState(9)
        x = jnp.asarray(rng.randn(4, 50).astype(np.float32))
        labels = jnp.asarray([3, -1, 7, -1], jnp.int32)
        loss = ops.softmax_cross_entropy_loss(x, labels, 0.0)
        assert float(loss[1]) == 0.0 and float(loss[3]) == 0.0
        g = jax.grad(lambda x_: jnp.sum(
            ops.softmax_cross_entropy_loss(x_, labels, 0.0)))(x)
        np.testing.assert_allclose(np.asarray(g)[1], 0.0)
        np.testing.assert_allclose(np.asarray(g)[3], 0.0)

    def test_batched_shape(self):
        rng = np.random.RandomState(10)
        x = jnp.asarray(rng.randn(2, 5, 64).astype(np.float32))
        labels = jnp.asarray(rng.randint(0, 64, (2, 5)), jnp.int32)
        loss = ops.softmax_cross_entropy_loss(x, labels, 0.1)
        assert loss.shape == (2, 5)


class TestGroupBN:
    def test_single_device_module(self):
        bn = ops.BatchNorm2d_NHWC(8, fuse_relu=True)
        rng = np.random.RandomState(11)
        x = jnp.asarray(rng.randn(4, 6, 6, 8).astype(np.float32))
        variables = bn.init(jax.random.PRNGKey(0), x)
        y, mut = bn.apply(variables, x, mutable=["batch_stats"])
        assert bool(jnp.all(y >= 0))
        mean = np.asarray(x).mean(axis=(0, 1, 2))
        np.testing.assert_allclose(
            np.asarray(mut["batch_stats"]["mean"]), 0.1 * mean, atol=1e-5)

    def test_bn_group_spec(self):
        assert ops.bn_group_spec(8, 2) == [[0, 1], [2, 3], [4, 5], [6, 7]]
        # group of 1 = per-device stats, NOT None (None = whole axis)
        assert ops.bn_group_spec(8, 1) == [[i] for i in range(8)]
        assert ops.bn_group_spec(8, 8) is None
