"""On-device compile validation for every Pallas kernel.

The CI suite runs all kernels in interpret mode on a CPU mesh (see
tests/conftest.py) — semantically exact, but Mosaic's block-shape/tiling
rules are only enforced when a kernel actually *compiles* for a TPU. The
reference never had this gap (every test tier runs on real GPUs,
SURVEY.md §4); this module closes it: ``python -m apex_tpu.ops`` compiles
and runs every kernel family across the shape grid the tests use — plus
the known-nasty shapes (short multi-head sequences, odd hidden widths,
non-power-of-two block preferences, tail partitions) — on the attached
accelerator, checking outputs against interpret-mode or jnp oracles.

The driver-visible artifact is ``COMPILECHECK.json`` (written by
``--json``; bench.py also triggers this after the headline metric).
"""

from __future__ import annotations

import contextlib
import functools
import json
import os
import sys
import traceback
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

CASES: List[Tuple[str, Callable[[], None]]] = []


def case(name: str):
    def reg(fn):
        CASES.append((name, fn))
        return fn
    return reg


def _rand(shape, seed=0, dtype=jnp.float32, scale=1.0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape).astype(np.float32) * scale, dtype)


@contextlib.contextmanager
def _interpret_oracle():
    """Trace kernels in interpret mode (the CI-validated semantics) —
    the oracle for kernels without a standalone jnp reference."""
    old = os.environ.get("APEX_TPU_FORCE_INTERPRET")
    os.environ["APEX_TPU_FORCE_INTERPRET"] = "1"
    try:
        yield
    finally:
        if old is None:
            del os.environ["APEX_TPU_FORCE_INTERPRET"]
        else:
            os.environ["APEX_TPU_FORCE_INTERPRET"] = old


def _check(label, got, want, atol, rtol=1e-5):
    got = np.asarray(got, np.float32)
    want = np.asarray(want, np.float32)
    np.testing.assert_allclose(got, want, atol=atol, rtol=rtol,
                               err_msg=label)


# --- flash attention ---------------------------------------------------------

def _attn_case(b, sq, sk, h, d, *, causal=False, with_bias=False,
               block_q=None, block_k=None, dtype=jnp.float32,
               seed=0, atol=2e-2):
    from apex_tpu.ops.attention import attention_reference, flash_attention
    q = _rand((b, sq, h, d), seed, dtype, 0.5)
    k = _rand((b, sk, h, d), seed + 1, dtype, 0.5)
    v = _rand((b, sk, h, d), seed + 2, dtype, 0.5)
    bias = _rand((b, h, sq, sk), seed + 3, dtype, 0.5) if with_bias else None
    kw = {}
    if block_q:
        kw["block_q"] = block_q
    if block_k:
        kw["block_k"] = block_k

    def fwd(q, k, v, bias):
        return flash_attention(q, k, v, bias=bias, causal=causal, **kw)

    got = jax.jit(fwd)(q, k, v, bias)
    want = attention_reference(q.astype(jnp.float32),
                               k.astype(jnp.float32),
                               v.astype(jnp.float32), bias=bias,
                               causal=causal)
    _check("attention fwd", got, want, atol)

    g = _rand((b, sq, h, d), seed + 4, dtype, 0.5)

    def loss(q, k, v, bias):
        return jnp.sum(fwd(q, k, v, bias).astype(jnp.float32) * g)

    def loss_ref(q, k, v, bias):
        return jnp.sum(attention_reference(q, k, v, bias=bias,
                                           causal=causal) * g)

    argn = (0, 1, 2, 3) if with_bias else (0, 1, 2)
    got_g = jax.jit(jax.grad(loss, argnums=argn))(q, k, v, bias)
    want_g = jax.grad(loss_ref, argnums=argn)(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32), bias)
    for name, gg, ww in zip("qkvb", got_g, want_g):
        _check(f"attention d{name}", gg, ww, atol * 4, rtol=1e-3)


@case("attention/basic-256")
def _():
    _attn_case(2, 256, 256, 4, 64)


@case("attention/causal-384")
def _():
    _attn_case(1, 384, 384, 2, 128, causal=True)


@case("attention/bias-256")
def _():
    _attn_case(2, 256, 256, 2, 64, with_bias=True)


@case("attention/bias-native-no-transpose")
def _():
    # round-5: per-head additive bias rides the native-layout grid —
    # the compiled fwd+bwd graph must contain NO transpose ops (the
    # 10.6 ms/step-class tax the (B·H,S,D) wrappers paid) and exactly
    # the two native custom-calls
    import jax
    import numpy as np
    from apex_tpu.ops.attention import flash_attention

    B, S, H, D = 2, 256, 4, 64
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
    bias = jnp.asarray(rng.randn(1, H, S, S), jnp.float32)

    def f(q, k, v, bias):
        return jnp.sum(flash_attention(q, k, v, bias)
                       .astype(jnp.float32) ** 2)

    g = jax.jit(jax.grad(f, argnums=(0, 1, 2)))
    from apex_tpu.ops._dispatch import use_interpret
    if use_interpret():
        # interpret mode lowers pallas_call to plain HLO — the
        # custom-call/transpose structure only exists on the chip;
        # still drive the compiled grads (same condition the kernel
        # dispatch uses, incl. APEX_TPU_FORCE_INTERPRET on a TPU host)
        jax.block_until_ready(g(q, k, v, bias))
        return
    hlo = g.lower(q, k, v, bias).compile().as_text()
    n_tr = sum(1 for l in hlo.splitlines() if " transpose(" in l)
    assert n_tr == 0, f"biased attention compiled {n_tr} transposes"
    assert hlo.count("tpu_custom_call") == 2, "expected fwd + fused bwd"


@case("attention/short-seq-multihead")
def _():
    # sq < 128 with several heads — the round-2 lse-alignment bug shape
    _attn_case(2, 64, 64, 4, 64)


@case("attention/cross-200x112")
def _():
    # ragged cross-attention lengths exercise tail masking
    _attn_case(1, 200, 112, 3, 64)


@case("attention/nonpow2-block-pref")
def _():
    # ADVICE round-2: block_k=384 over sk=400 must not produce an
    # unaligned multi-block tile when a bias is present
    _attn_case(1, 256, 400, 2, 64, with_bias=True, block_k=384)


@case("attention/bf16-512")
def _():
    _attn_case(1, 512, 512, 4, 64, dtype=jnp.bfloat16, atol=5e-2)


@case("attention/long-2048-1024tiles")
def _():
    # multi-block grids at the 1024-tile default (the long-sequence
    # fast path; also the causal multi-block masking)
    _attn_case(1, 2048, 2048, 2, 64, causal=True, dtype=jnp.bfloat16,
               atol=5e-2)


@case("attention/long-bias-2048")
def _():
    # the ring causal-hop shape: long sequence WITH an additive bias —
    # the path the 512-tile bias cap protects (a 1024-tile fp32 bias
    # block would blow the scoped VMEM); grads included
    _attn_case(1, 2048, 2048, 1, 64, with_bias=True,
               dtype=jnp.bfloat16, atol=5e-2)


@case("attention/dropout-runs-finite")
def _():
    from apex_tpu.ops.attention import flash_attention
    # S=256 (single block) and S=2048 (multi-block at the capped 512
    # dropout tile — the VMEM-sensitive combination)
    for s in (256, 2048):
        q = _rand((1, s, 2, 64), 0)
        k = _rand((1, s, 2, 64), 1)
        v = _rand((1, s, 2, 64), 2)

        def loss(q, k, v):
            o = flash_attention(q, k, v, dropout_rate=0.1,
                                dropout_seed=7)
            return jnp.sum(o * o)

        val, grads = jax.jit(jax.value_and_grad(
            loss, argnums=(0, 1, 2)))(q, k, v)
        assert np.isfinite(float(val))
        for g in grads:
            assert np.all(np.isfinite(np.asarray(g, np.float32)))


def _dense_dropout_oracle(q, k, v, bias, seed, rate, causal,
                          block_q, block_k):
    """Dense attention applying the EXACT keep mask the kernels
    generate (same hash, same block decomposition via the shared cap) —
    the on-chip value oracle for the compiled dropout paths
    (VERDICT r3 item 3: the bitwise mask agreement across the fwd
    kernel, both bwd kernels, and the dense `_bias_grad` replica was
    previously validated only in interpret mode)."""
    from apex_tpu.ops.attention import (
        NEG_INF, _block_cap, _choose_block, _keep_mask_dense)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    cq, ck = _block_cap(block_q, block_k, bias is not None, rate)
    bq = _choose_block(cq, sq)
    bk = _choose_block(ck, sk, lane=True)
    seed_arr = jnp.asarray(seed, jnp.int32).reshape(-1)[:1]
    keep = _keep_mask_dense(seed_arr[0], b, h, sq, sk, bq, bk,
                            rate).reshape(b, h, sq, sk)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(d)
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        mask = np.tril(np.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    pd = jnp.where(keep, p / (1.0 - rate), 0.0)
    o = jnp.einsum("bhqk,bkhd->bqhd", pd, v.astype(jnp.float32))
    return o.astype(q.dtype)


def _dropout_equiv_case(s, *, with_bias=False, causal=False, seed=11,
                        rate=0.3):
    """Elementwise compiled-vs-dense dropout equivalence under HIGHEST
    matmul precision: f32 dots on the MXU default to bf16 passes
    (~1e-3 relative noise — larger than a long-sequence mask-flip's
    ~p-sized signal), so the mask certification needs the fp32-exact
    passes. At highest precision the fp noise floor is ~1e-6 while a
    single flipped keep bit moves affected o/grad elements by
    ≥ ~1/(2s) through the 1/(1-rate) scale — cleanly detectable at the
    tolerances below."""
    from apex_tpu.ops.attention import (DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q,
                                        flash_attention)
    q = _rand((1, s, 2, 64), 0)
    k = _rand((1, s, 2, 64), 1)
    v = _rand((1, s, 2, 64), 2)
    bias = _rand((1, 2, s, s), 3, scale=0.5) if with_bias else None
    g = _rand((1, s, 2, 64), 4)

    def fwd(q, k, v, bias):
        return flash_attention(q, k, v, bias=bias, causal=causal,
                               dropout_rate=rate, dropout_seed=seed)

    def fwd_ref(q, k, v, bias):
        return _dense_dropout_oracle(q, k, v, bias, seed, rate, causal,
                                     DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)

    def loss(q, k, v, bias):
        return jnp.sum(fwd(q, k, v, bias).astype(jnp.float32) * g)

    def loss_ref(q, k, v, bias):
        return jnp.sum(fwd_ref(q, k, v, bias).astype(jnp.float32) * g)

    argn = (0, 1, 2, 3) if with_bias else (0, 1, 2)
    with jax.default_matmul_precision("highest"):
        got_o = jax.jit(fwd)(q, k, v, bias)
        want_o = jax.jit(fwd_ref)(q, k, v, bias)
        got_g = jax.jit(jax.grad(loss, argnums=argn))(q, k, v, bias)
        want_g = jax.jit(jax.grad(loss_ref, argnums=argn))(q, k, v, bias)
    _check("dropout o", got_o, want_o, 5e-5, rtol=1e-4)
    for name, gg, ww in zip("qkvb", got_g, want_g):
        _check(f"dropout d{name}", gg, ww, 2e-4, rtol=1e-3)


@case("attention/dropout-mask-equivalence-256")
def _():
    # single-block grid: compiled fwd + both bwd kernels must regenerate
    # the dense replica's mask bit-for-bit (values asserted, not
    # finiteness)
    _dropout_equiv_case(256)


@case("attention/dropout-mask-equivalence-2048")
def _():
    # multi-block grid at the capped 512 dropout tile: the block-
    # coordinate hash must agree across a non-trivial decomposition
    _dropout_equiv_case(2048, causal=True)


@case("attention/dropout-bias-grad-equivalence")
def _():
    # the learned-bias cotangent path (`_bias_grad`) shares the dense
    # mask with the kernels: dbias values must match the oracle too
    _dropout_equiv_case(384, with_bias=True)


@case("attention/fp32-1024-gpack-vmem")
def _():
    # fp32 inputs double the g-pack VMEM estimate (ADVICE r3 item 1):
    # the largest single-q-block fp32 shape must stay Mosaic-compilable
    # with the itemsize-aware packing
    _attn_case(4, 1024, 1024, 4, 64, dtype=jnp.float32, atol=2e-2)


@case("attention/lse-dropout-block-offset")
def _():
    # ring-hop dropout on the chip: the lse variant with a traced
    # (q-block, k-block) offset must equal a dense replica hashed at
    # the SHIFTED global coordinates — bitwise mask, fp-tolerance
    # values (round-5 ring dropout machinery)
    import numpy as np
    from apex_tpu.ops import attention as A

    B, S, H, D = 1, 512, 2, 64
    rate, seed = 0.3, 9
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    dbo = jnp.asarray([2, 3], jnp.int32)

    def dense(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
        p = jax.nn.softmax(s, axis=-1)
        gb = jax.lax.broadcasted_iota(jnp.uint32, (B * H, S, S), 0)
        rows = jax.lax.broadcasted_iota(jnp.uint32, (B * H, S, S), 1)
        cols = jax.lax.broadcasted_iota(jnp.uint32, (B * H, S, S), 2)
        keep = A._mix_keep(jnp.uint32(seed), gb, jnp.uint32(2),
                           jnp.uint32(3), rows, cols, rate)
        pk = jnp.where(keep.reshape(B, H, S, S), p / (1.0 - rate), 0.0)
        return jnp.einsum("bhqk,bkhd->bqhd", pk, v)

    # highest precision: default lowers f32 dots to bf16 passes whose
    # ~1e-3 noise would swamp a flipped-keep signal (see
    # _dropout_equiv_case)
    with jax.default_matmul_precision("highest"):
        o, lse = jax.jit(lambda q, k, v: A.flash_attention_lse(
            q, k, v, dropout_rate=rate, dropout_seed=seed,
            dropout_block_offset=dbo))(q, k, v)
        ref = jax.jit(dense)(q, k, v)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               atol=5e-5)


@case("attention/ring-hop-shapes")
def _():
    # the ring per-hop call: flash_attention_lse under the TRACED
    # causal_offset (the native-path hop — no O(S²) bias), grads
    # through (o, lse) both, PLUS the (1,1,sq,sk) additive-bias form
    # it replaced (still the fallback for non-native geometries) —
    # Mosaic legality on the chip for the hop kernels the CPU-mesh
    # dryrun exercises only in interpret mode
    from apex_tpu.ops.attention import (attention_reference,
                                        flash_attention_lse)
    sq = sk = 1024
    q = _rand((1, sq, 2, 64), 0, jnp.bfloat16, 0.5)
    k = _rand((1, sk, 2, 64), 1, jnp.bfloat16, 0.5)
    v = _rand((1, sk, 2, 64), 2, jnp.bfloat16, 0.5)
    # hop: query global offset sq (second shard), key offset 0
    rows = np.arange(sq)[:, None] + sq
    cols = np.arange(sk)[None, :]
    bias = jnp.asarray(np.where(rows >= cols, 0.0, -1e9),
                       jnp.float32).reshape(1, 1, sq, sk)
    g = _rand((1, sq, 2, 64), 3)

    def loss_off(q, k, v, off):
        o, lse = flash_attention_lse(q, k, v, causal=True,
                                     causal_offset=off)
        return jnp.sum(o.astype(jnp.float32) * g) \
            + 1e-3 * jnp.sum(lse.astype(jnp.float32))

    def loss_bias(q, k, v):
        o, lse = flash_attention_lse(q, k, v, bias=bias)
        return jnp.sum(o.astype(jnp.float32) * g) \
            + 1e-3 * jnp.sum(lse.astype(jnp.float32))

    off = jnp.int32(sq)
    want_o = attention_reference(q.astype(jnp.float32),
                                 k.astype(jnp.float32),
                                 v.astype(jnp.float32), bias=bias)
    o_off, _ = jax.jit(lambda q, k, v, s: flash_attention_lse(
        q, k, v, causal=True, causal_offset=s))(q, k, v, off)
    _check("ring hop fwd (offset)", o_off, want_o, 5e-2)
    o_b, _ = jax.jit(flash_attention_lse)(q, k, v, bias=bias)
    _check("ring hop fwd (bias)", o_b, want_o, 5e-2)

    for lossfn, args in ((loss_off, (q, k, v, off)),
                         (loss_bias, (q, k, v))):
        got = jax.jit(jax.value_and_grad(
            lossfn, argnums=(0, 1, 2)))(*args)
        assert np.isfinite(float(got[0]))
        for gg in got[1]:
            assert np.all(np.isfinite(np.asarray(gg, np.float32)))


@case("attention/ulysses-resharded")
def _():
    # the Ulysses all-to-all re-shard: long local sequence, few local
    # heads (16 heads over an 8-way axis -> 2), causal, bf16 — the
    # 1024-tile multi-block causal path at the resharded geometry
    _attn_case(2, 2048, 2048, 2, 64, causal=True, dtype=jnp.bfloat16,
               atol=5e-2)


# --- layer norm --------------------------------------------------------------

def _ln_case(n, h, dtype=jnp.float32, atol=1e-4):
    from apex_tpu.ops.layer_norm import (fused_layer_norm_affine,
                                         layer_norm_reference)
    x = _rand((n, h), 0, dtype)
    w = _rand((h,), 1) * 0.5 + 1.0
    b = _rand((h,), 2) * 0.1
    g = _rand((n, h), 3, dtype)

    got = jax.jit(fused_layer_norm_affine)(x, w, b)
    want = layer_norm_reference(x, w, b)
    _check("ln fwd", got, want, atol)

    def loss(x, w, b):
        return jnp.sum(fused_layer_norm_affine(x, w, b).astype(jnp.float32)
                       * g.astype(jnp.float32))

    def loss_ref(x, w, b):
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, -1, keepdims=True)
        var = jnp.var(x32, -1, keepdims=True)
        y = (x32 - mean) * jax.lax.rsqrt(var + 1e-5) * w + b
        return jnp.sum(y * g.astype(jnp.float32))

    got_g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(x, w, b)
    want_g = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    for name, gg, ww in zip(["dx", "dw", "db"], got_g, want_g):
        _check(f"ln {name}", gg, ww, atol * 20, rtol=1e-3)


@case("layer_norm/1024")
def _():
    _ln_case(257, 1024)


@case("layer_norm/odd-769")
def _():
    _ln_case(64, 769)


@case("layer_norm/narrow-48")
def _():
    _ln_case(33, 48)


@case("layer_norm/bf16-1024")
def _():
    _ln_case(128, 1024, dtype=jnp.bfloat16, atol=2e-2)


# --- MLP ---------------------------------------------------------------------

@case("mlp/3-layer-odd-widths")
def _():
    from apex_tpu.ops.mlp import fused_mlp, mlp_reference
    x = _rand((96, 224), 0)
    ws = [_rand((224, 200), 1, scale=0.1), _rand((200, 136), 2, scale=0.1),
          _rand((136, 10), 3, scale=0.1)]
    bs = [_rand((200,), 4, scale=0.1), _rand((136,), 5, scale=0.1),
          _rand((10,), 6, scale=0.1)]
    got = jax.jit(functools.partial(fused_mlp, activation="relu"))(x, ws, bs)
    want = mlp_reference(x, ws, bs, activation="relu")
    _check("mlp fwd", got, want, 1e-4)

    g = _rand((96, 10), 7)

    def loss(x, ws, bs):
        return jnp.sum(fused_mlp(x, ws, bs, activation="relu") * g)

    def loss_ref(x, ws, bs):
        return jnp.sum(mlp_reference(x, ws, bs, activation="relu") * g)

    got_g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(x, ws, bs)
    want_g = jax.grad(loss_ref, argnums=(0, 1, 2))(x, ws, bs)
    for gg, ww in zip(jax.tree_util.tree_leaves(got_g),
                      jax.tree_util.tree_leaves(want_g)):
        _check("mlp grad", gg, ww, 1e-3, rtol=1e-3)


# --- xentropy ----------------------------------------------------------------

def _xent_case(n, v, smoothing):
    from apex_tpu.ops.xentropy import (softmax_cross_entropy_loss,
                                       softmax_cross_entropy_reference)
    x = _rand((n, v), 0, scale=2.0)
    labels = jnp.asarray(np.random.RandomState(1).randint(0, v, n),
                         jnp.int32)
    f = functools.partial(softmax_cross_entropy_loss, smoothing=smoothing)
    got = jax.jit(f)(x, labels)
    want = softmax_cross_entropy_reference(x, labels, smoothing=smoothing)
    _check("xent fwd", got, want, 1e-4)

    g = _rand((n,), 2)

    def loss(x):
        return jnp.sum(f(x, labels) * g)

    def loss_ref(x):
        return jnp.sum(softmax_cross_entropy_reference(
            x, labels, smoothing=smoothing) * g)

    got_g = jax.jit(jax.grad(loss))(x)
    want_g = jax.grad(loss_ref)(x)
    _check("xent dx", got_g, want_g, 1e-4, rtol=1e-4)


@case("xentropy/odd-vocab-1003")
def _():
    _xent_case(37, 1003, 0.0)


@case("xentropy/bert-vocab-smoothing")
def _():
    _xent_case(64, 30528, 0.1)


# --- multi-tensor arena kernels ---------------------------------------------

def _arena_buf(n_logical, seed, dtype=jnp.float32):
    """A flat arena buffer: n_logical live values, zero tail padding up
    to the launcher's 64Ki multiple (the tail-partition case)."""
    from apex_tpu.ops._dispatch import BLOCK_ROWS, LANES
    mult = BLOCK_ROWS * LANES
    n = -(-n_logical // mult) * mult
    vals = np.zeros(n, np.float32)
    vals[:n_logical] = np.random.RandomState(seed).randn(n_logical)
    return jnp.asarray(vals, dtype)


@case("multi_tensor/scale-axpby-norms")
def _():
    from apex_tpu.ops.multi_tensor import (
        multi_tensor_axpby, multi_tensor_l2norm, multi_tensor_maxnorm,
        multi_tensor_scale)
    x = _arena_buf(100_003, 0)
    y = _arena_buf(100_003, 1)
    out, finite = jax.jit(lambda x: multi_tensor_scale(x, 0.25))(x)
    _check("scale", out, np.asarray(x) * 0.25, 1e-6)
    assert bool(finite)
    out, finite = jax.jit(
        lambda x, y: multi_tensor_axpby(2.0, x, -0.5, y))(x, y)
    _check("axpby", out, 2.0 * np.asarray(x) - 0.5 * np.asarray(y), 1e-5)
    nrm = jax.jit(multi_tensor_l2norm)(x)
    _check("l2norm", nrm, np.linalg.norm(np.asarray(x)), 1e-2)
    mx = jax.jit(multi_tensor_maxnorm)(x)
    _check("maxnorm", mx, np.abs(np.asarray(x)).max(), 1e-6)
    # overflow flag fires on inf
    bad = x.at[17].set(jnp.inf)
    _, finite = jax.jit(lambda b: multi_tensor_scale(b, 1.0))(bad)
    assert not bool(finite)


# --- fused optimizer kernels -------------------------------------------------

def _vs_interpret(fn, *args):
    """Run ``fn`` compiled and in interpret mode; compare all outputs."""
    got = jax.jit(fn)(*args)
    with _interpret_oracle():
        want = jax.jit(fn).lower(*args).compile()(*args)
    for i, (gg, ww) in enumerate(zip(jax.tree_util.tree_leaves(got),
                                     jax.tree_util.tree_leaves(want))):
        _check(f"out[{i}]", gg, ww, 1e-5, rtol=1e-5)


@case("optim/adam")
def _():
    from apex_tpu.ops.optim_kernels import adam_update
    p, g = _arena_buf(70_001, 0), _arena_buf(70_001, 1)
    m, v = _arena_buf(70_001, 2) * 0.1, jnp.abs(_arena_buf(70_001, 3)) * 0.1

    def step(p, g, m, v):
        return adam_update(p, g, m, v, lr=1e-3, beta1=0.9, beta2=0.999,
                           eps=1e-8, weight_decay=0.01, step=3,
                           param_copy_dtype=jnp.bfloat16)

    _vs_interpret(step, p, g, m, v)


@case("optim/sgd-nesterov-copy")
def _():
    from apex_tpu.ops.optim_kernels import sgd_update
    p, g, m = _arena_buf(70_001, 0), _arena_buf(70_001, 1), \
        _arena_buf(70_001, 2) * 0.1

    def step(p, g, m):
        return sgd_update(p, g, m, lr=0.1, momentum=0.9, weight_decay=1e-4,
                          nesterov=True, param_copy_dtype=jnp.bfloat16)

    _vs_interpret(step, p, g, m)


@case("optim/adagrad")
def _():
    from apex_tpu.ops.optim_kernels import adagrad_update
    p, g = _arena_buf(70_001, 0), _arena_buf(70_001, 1)
    h = jnp.abs(_arena_buf(70_001, 2)) * 0.1

    def step(p, g, h):
        return adagrad_update(p, g, h, lr=0.01, weight_decay=1e-4)

    _vs_interpret(step, p, g, h)


@case("optim/lamb-two-stage")
def _():
    from apex_tpu.ops.optim_kernels import lamb_stage1, lamb_stage2
    p, g = _arena_buf(70_001, 0), _arena_buf(70_001, 1)
    m, v = _arena_buf(70_001, 2) * 0.1, jnp.abs(_arena_buf(70_001, 3)) * 0.1
    ratio = jnp.abs(_arena_buf(70_001, 4)) * 0.01 + 1.0

    def step(p, g, m, v, ratio):
        u, m2, v2 = lamb_stage1(p, g, m, v, beta1=0.9, beta2=0.999,
                                eps=1e-6, weight_decay=0.01, step=2)
        return lamb_stage2(p, u, ratio, lr=1e-3), m2, v2

    _vs_interpret(step, p, g, m, v, ratio)


@case("optim/novograd")
def _():
    from apex_tpu.ops.optim_kernels import novograd_update
    p, g = _arena_buf(70_001, 0), _arena_buf(70_001, 1)
    m = _arena_buf(70_001, 2) * 0.1
    vnorm = jnp.abs(_arena_buf(70_001, 3)) + 0.1

    def step(p, g, m, vnorm):
        return novograd_update(p, g, m, vnorm, lr=1e-3, beta1=0.95,
                               beta2=0.98, eps=1e-8, weight_decay=1e-3,
                               step=2)

    _vs_interpret(step, p, g, m, vnorm)


# --- fused BN unit (Pallas two-pass backward) --------------------------------

@case("bn_act/relu-grads")
def _():
    from apex_tpu.ops.bn_act import (bn_act_reference, bn_act_train,
                                     make_cfg)
    # odd spatial (14x14) and the C=64 sub-lane channel case
    x = _rand((16, 14, 14, 64), 0, jnp.bfloat16)
    s = _rand((64,), 2) * 0.5 + 1.0
    b = _rand((64,), 3) * 0.1
    g = _rand((16, 14, 14, 64), 4)
    cfg = make_cfg(relu=True)

    def loss(x, s, b):
        z, *_ = bn_act_train(x, s, b, cfg)
        return jnp.sum(z.astype(jnp.float32) * g)

    def loss_ref(x, s, b):
        z, _, _ = bn_act_reference(x, s, b, relu=True)
        return jnp.sum(z.astype(jnp.float32) * g)

    got = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(x, s, b)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(x, s, b)
    for gg, ww in zip(got, want):
        _check("bn_act relu grad", gg, ww, 5e-2, rtol=2e-2)


@case("bn_act/add-relu-grads")
def _():
    from apex_tpu.ops.bn_act import (bn_act_reference, bn_add_act_train,
                                     make_cfg)
    x = _rand((8, 14, 14, 64), 0, jnp.bfloat16)
    r = _rand((8, 14, 14, 64), 1, jnp.bfloat16)
    s = _rand((64,), 2) * 0.5 + 1.0
    b = _rand((64,), 3) * 0.1
    g = _rand((8, 14, 14, 64), 4)
    cfg = make_cfg(relu=True)

    def loss(x, r, s, b):
        z, *_ = bn_add_act_train(x, r, s, b, cfg)
        return jnp.sum(z.astype(jnp.float32) * g)

    def loss_ref(x, r, s, b):
        z, _, _ = bn_act_reference(x, s, b, residual=r, relu=True)
        return jnp.sum(z.astype(jnp.float32) * g)

    got = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3)))(x, r, s, b)
    want = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, r, s, b)
    for gg, ww in zip(got, want):
        _check("bn_act grad", gg, ww, 5e-2, rtol=2e-2)


@case("bn_act/pallas-bwd-variant")
def _():
    """The opt-in Pallas two-pass backward (APEX_TPU_BN_PALLAS_BWD=1):
    not the default path (it loses to XLA on layout copies — PERF.md),
    but it must stay Mosaic-legal across the channel grid since it is
    the shipped fallback-free kernel surface."""
    from apex_tpu.ops.bn_act import (bn_act_reference, bn_act_train,
                                     bn_add_act_train, make_cfg)
    old = os.environ.get("APEX_TPU_BN_PALLAS_BWD")
    os.environ["APEX_TPU_BN_PALLAS_BWD"] = "1"
    try:
        cfg = make_cfg(relu=True)
        for c, with_res in ((64, False), (256, True), (2048, True)):
            x = _rand((8, 7, 7, c), 0, jnp.bfloat16)
            r = _rand((8, 7, 7, c), 1, jnp.bfloat16)
            s = _rand((c,), 2) * 0.5 + 1.0
            b = _rand((c,), 3) * 0.1
            g = _rand((8, 7, 7, c), 4)

            if with_res:
                def loss(x, r, s, b):
                    z, *_ = bn_add_act_train(x, r, s, b, cfg)
                    return jnp.sum(z.astype(jnp.float32) * g)

                def loss_ref(x, r, s, b):
                    z, _, _ = bn_act_reference(x, s, b, residual=r,
                                               relu=True)
                    return jnp.sum(z.astype(jnp.float32) * g)

                got = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3)))(
                    x, r, s, b)
                want = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(
                    x, r, s, b)
            else:
                def loss(x, s, b):
                    z, *_ = bn_act_train(x, s, b, cfg)
                    return jnp.sum(z.astype(jnp.float32) * g)

                def loss_ref(x, s, b):
                    z, _, _ = bn_act_reference(x, s, b, relu=True)
                    return jnp.sum(z.astype(jnp.float32) * g)

                got = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(x, s, b)
                want = jax.grad(loss_ref, argnums=(0, 1, 2))(x, s, b)
            for gg, ww in zip(got, want):
                _check(f"bn_act pallas c={c}", gg, ww, 5e-2, rtol=2e-2)
    finally:
        if old is None:
            del os.environ["APEX_TPU_BN_PALLAS_BWD"]
        else:
            os.environ["APEX_TPU_BN_PALLAS_BWD"] = old


# --- monitor: zero-dispatch telemetry contract -------------------------------

@case("monitor/no-extra-dispatch")
def _():
    """The in-graph Metrics pytree must ride the existing step program:
    monitored and unmonitored toy train steps compile to the same number
    of HLO modules (one executable each), and the monitored module
    contains no host traffic (outfeed/infeed/host callbacks) — telemetry
    leaves the device only when the host logger flushes."""
    from apex_tpu import amp
    from apex_tpu.monitor.check import module_count_and_host_ops
    from apex_tpu.optim import FusedSGD

    x = _rand((16, 32), 0)
    y = _rand((16, 8), 1)
    params = {"w": _rand((32, 8), 2, scale=0.1),
              "b": jnp.zeros((8,), jnp.float32)}

    def build(monitored):
        amp_opt, state = amp.initialize(
            params, FusedSGD(lr=0.1), "O2", half_dtype=jnp.float16,
            verbosity=0, monitor=monitored)

        def train_step(state, x, y):
            def loss_fn(p):
                return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))
            state, loss, _ = amp_opt.step(state, loss_fn)
            return state, loss

        return jax.jit(train_step), state

    mon_step, mon_state = build(True)
    plain_step, plain_state = build(False)
    n_mon, host_mon = module_count_and_host_ops(mon_step, mon_state, x, y)
    n_plain, _ = module_count_and_host_ops(plain_step, plain_state, x, y)
    assert n_mon == n_plain, (n_mon, n_plain)
    assert not host_mon, f"monitored step compiled host traffic: {host_mon}"


# --- trace: span/probe zero-dispatch contract --------------------------------

@case("trace/no-extra-dispatch")
def _():
    """Spans and NaN probes with trace.debug_nans OFF must leave the
    compiled program identical to an unannotated twin: same HLO module
    count, no host traffic. With the mode ON the probes must actually
    appear (host callbacks in the HLO) — proving the guard flips real
    dispatch structure, not a no-op."""
    from apex_tpu import trace
    from apex_tpu.monitor.check import module_count_and_host_ops

    x = _rand((16, 32), 0)
    w = _rand((32, 8), 1, scale=0.1)

    def plain(w, x):
        h = jnp.tanh(x @ w)
        return jnp.sum(h * h)

    def traced(w, x):
        with trace.span("fwd"):
            h = jnp.tanh(x @ w)
        h = trace.nan_probe("fwd", h)
        with trace.span("loss"):
            return trace.nan_probe("loss", jnp.sum(h * h))

    n_t, host_t = module_count_and_host_ops(jax.jit(traced), w, x)
    n_p, _ = module_count_and_host_ops(jax.jit(plain), w, x)
    assert n_t == n_p, (n_t, n_p)
    assert not host_t, f"passive spans compiled host traffic: {host_t}"

    with trace.debug_nans():
        # the flag is trace-time and jax caches traces per function
        # object — drop the off-mode trace before recompiling
        jax.clear_caches()
        _, host_on = module_count_and_host_ops(jax.jit(traced), w, x)
    assert host_on, "debug_nans probes missing from the compiled HLO"
    trace.reset_nan_state()
    jax.clear_caches()


# --- memory/compile observability: zero-dispatch contract --------------------

@case("memory/no-extra-dispatch")
def _():
    """Memory sampling + compile_watch are pure host-side observers: a
    step driven under a CompileWatcher with allocator sampling and an
    attached MemoryReport must compile BIT-IDENTICAL HLO to an
    unwatched twin (same guarantee monitor/trace already pin), with no
    host traffic and exactly one trace in steady state."""
    import io

    from apex_tpu import monitor, prof
    from apex_tpu.monitor.check import module_count_and_host_ops

    x = _rand((16, 32), 0)
    y = _rand((16, 8), 1)
    params = {"w": _rand((32, 8), 2, scale=0.1),
              "b": jnp.zeros((8,), jnp.float32)}

    def train_step(p, x, y):
        def loss_fn(p):
            return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))
        g = jax.grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)

    plain = jax.jit(train_step)
    hlo_plain = plain.lower(params, x, y).compile().as_text()

    watcher = prof.CompileWatcher()
    logger = monitor.MetricsLogger(
        sinks=[], memory_sink=monitor.JSONLSink(io.StringIO()))
    watcher.subscribe(logger.record_memory)
    watched = watcher.watch(train_step, name="train_step")

    watched(params, x, y)
    logger.sample_memory(step=0)
    rep = prof.memory_report(watched.jitted, params, x, y)
    logger.attach_memory_report(rep)
    watched(params, x, y)                      # steady state
    logger.close()

    hlo_watched = watched.jitted.lower(params, x, y).compile().as_text()
    assert hlo_watched == hlo_plain, \
        "watching/sampling changed the compiled program"
    assert watcher["train_step"].n_traces == 1, \
        watcher["train_step"].n_traces
    _n, host = module_count_and_host_ops(watched.jitted, params, x, y)
    assert not host, f"observed step compiled host traffic: {host}"
    assert rep.total_bytes > 0


# --- apexlint: strictly-AOT contract + kernel sweep --------------------------

@case("lint/no-extra-dispatch")
def _():
    """Linting a step is pure observation: a step compiled under
    apexlint (jaxpr trace + AOT compile inside lint_step) must leave
    the step's own compiled HLO BIT-IDENTICAL to the unobserved twin —
    lint never mutates the function, the trace cache, or compiler
    flags. Donated and undonated twins both pinned (the donation rule
    reads aliasing, it must not create it)."""
    from apex_tpu import lint

    x = _rand((16, 32), 0)
    y = _rand((16, 8), 1)
    params = {"w": _rand((32, 8), 2, scale=0.1),
              "b": jnp.zeros((8,), jnp.float32)}

    def train_step(p, x, y):
        def loss_fn(p):
            return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))
        g = jax.grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)

    for donate in ((), (0,)):
        jitted = jax.jit(train_step, donate_argnums=donate)
        before = jitted.lower(params, x, y).compile().as_text()
        rep = lint.lint_step(jax.jit(train_step, donate_argnums=donate),
                             params, x, y)
        after = jitted.lower(params, x, y).compile().as_text()
        assert after == before, \
            f"lint observation changed the compiled program (donate=" \
            f"{donate})"
        # the lint itself must see a host-clean program
        assert not rep.by_rule("host-transfer"), rep.table()


@case("lint/precision-no-extra-dispatch")
def _():
    """The precision pass (APX3xx) is strictly AOT like its siblings:
    running it — default trace-side rules AND the APX306 fixture join
    (``precision=`` a measured stats dict) — leaves the step's own
    compiled HLO BIT-IDENTICAL, donated and undonated. A scale/unscale
    pair is built into the step so the taint machinery actually
    executes (the pin covers the analysis, not a vacuous walk)."""
    from apex_tpu import lint

    x = _rand((16, 32), 0)
    y = _rand((16, 8), 1)
    scale = jnp.float32(1024.0)
    params = {"w": _rand((32, 8), 2, scale=0.1),
              "b": jnp.zeros((8,), jnp.float32)}

    def train_step(p, x, y, s):
        def loss_fn(p):
            return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y)) * s
        g = jax.grad(loss_fn)(p)
        inv = (1.0 / s).astype(jnp.float32)
        g = jax.tree_util.tree_map(lambda a: a * inv, g)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)

    # a tiny synthetic measured fixture (the columnar
    # numerics.stats_from_json layout): one well-behaved site whose
    # exponents sit in a single mid-range binade — fp8-safe
    import numpy as _np
    from apex_tpu.monitor import numerics as nx
    hist = _np.zeros((1, nx.HIST_BINS))
    hist[0, nx.HIST_BINS // 2] = 1.0
    stats = {"sites": ("amp/cast/['w']",),
             "amax": [1.0], "amax_ema": [1.0],
             "amin": [0.5], "amin_ema": [0.5],
             "exp_hist": hist, "zero_frac": [0.0],
             "nonfinite_frac": [0.0], "uw_ratio": [-1.0]}
    assert nx.precision_report(stats).rows, "synthetic fixture invalid"

    for donate in ((), (0,)):
        jitted = jax.jit(train_step, donate_argnums=donate)
        before = jitted.lower(params, x, y, scale).compile().as_text()
        for precision in (None, stats):
            rep = lint.lint_step(
                jax.jit(train_step, donate_argnums=donate),
                params, x, y, scale, precision=precision)
            assert not [f for f in rep.findings
                        if f.rule in ("unscaled-narrow-cast",
                                      "scale-leak")], rep.table()
        after = jitted.lower(params, x, y, scale).compile().as_text()
        assert after == before, \
            f"precision pass changed the compiled program (donate=" \
            f"{donate})"


@case("lint/kernel-sweep")
def _():
    """apexlint HLO sweep over the kernel families the pinned cases
    above compile: every family's compiled module must carry zero
    error-severity findings (no host callbacks, no stray collectives,
    no un-aliased carried state) — the kernels are lint-clean by
    construction, and a regression that compiles host traffic into a
    kernel fails here before it costs a run."""
    from apex_tpu import lint
    from apex_tpu.ops.layer_norm import fused_layer_norm_affine
    from apex_tpu.ops.mlp import fused_mlp
    from apex_tpu.ops.multi_tensor import multi_tensor_scale
    from apex_tpu.ops.optim_kernels import adam_update
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
    from apex_tpu.prof import hlo as _hlo

    x = _rand((64, 256), 0)
    w = _rand((256,), 1) * 0.5 + 1.0
    b = _rand((256,), 2) * 0.1
    buf = _arena_buf(70_001, 3)
    labels = jnp.asarray(np.random.RandomState(4).randint(0, 1000, 64),
                         jnp.int32)
    sweep = {
        "layer_norm": (fused_layer_norm_affine, (x, w, b)),
        "mlp": (lambda a: fused_mlp(a, [_rand((256, 128), 5, scale=0.1)],
                                    [_rand((128,), 6, scale=0.1)]), (x,)),
        "xentropy": (lambda a: softmax_cross_entropy_loss(
            a, labels), (_rand((64, 1000), 7),)),
        "multi_tensor": (lambda v: multi_tensor_scale(v, 0.5), (buf,)),
        "optim_adam": (lambda p, g, m, v: adam_update(
            p, g, m, v, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
            weight_decay=0.0, step=2),
            (buf, _arena_buf(70_001, 8), _arena_buf(70_001, 9) * 0.1,
             jnp.abs(_arena_buf(70_001, 10)) * 0.1)),
    }
    for name, (fn, args) in sweep.items():
        text = _hlo.compiled_hlo(fn, *args)
        findings = lint.lint_hlo_text(text)
        errors = [f for f in findings if f.severity == "error"]
        assert not errors, (
            f"kernel family {name} has error-severity lint findings: "
            + "; ".join(f"{f.rule}: {f.message}" for f in errors))
        print(f"  lint-swept {name}: {len(findings)} finding(s), "
              f"0 errors")


# --- ckpt: host-side-only snapshot contract ----------------------------------

@case("ckpt/no-extra-dispatch")
def _():
    """Checkpointing attached to a train loop must leave the step's
    compiled HLO BIT-IDENTICAL — donated and undonated: the snapshot is
    device copies + host-side writes BETWEEN dispatches, never ops
    inside the step program (the claim behind the <5%-of-step async
    overhead bound: only the copy dispatch rides the step path). Also
    pins the donation-safety contract itself: the state saved right
    before a donating dispatch restores bitwise after that dispatch
    invalidated the original buffers."""
    import tempfile

    from apex_tpu import amp, ckpt
    from apex_tpu.monitor.check import module_count_and_host_ops
    from apex_tpu.optim import FusedSGD

    x = _rand((16, 32), 0)
    y = _rand((16, 8), 1)
    params = {"w": _rand((32, 8), 2, scale=0.1),
              "b": jnp.zeros((8,), jnp.float32)}
    amp_opt, state0 = amp.initialize(
        params, FusedSGD(lr=0.1), "O2", half_dtype=jnp.float16,
        verbosity=0)

    def train_step(state, x, y):
        def loss_fn(p):
            return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))
        state, loss, _ = amp_opt.step(state, loss_fn)
        return state, loss

    for donate in ((), (0,)):
        jitted = jax.jit(train_step, donate_argnums=donate)
        before = jitted.lower(state0, x, y).compile().as_text()
        with tempfile.TemporaryDirectory() as tmp:
            mgr = ckpt.CheckpointManager(tmp)
            state = state0
            for i in range(3):
                state, loss = jitted(state, x, y)
                if i == 1:
                    mgr.save(i, state)      # the NEXT dispatch donates
            mgr.wait()                      # `state`'s buffers away
            after = jitted.lower(state0, x, y).compile().as_text()
            assert after == before, \
                f"checkpointing changed the compiled step (donate=" \
                f"{donate})"
            _n, host = module_count_and_host_ops(
                jax.jit(train_step, donate_argnums=donate), state0, x, y)
            assert not host, f"step compiled host traffic: {host}"
            if donate:
                # the donation-safety half: the original `saved` buffers
                # were invalidated by the i=2 dispatch, yet the
                # checkpoint restores the step-1 state bitwise
                restored, _m = mgr.restore(state0)
                rs = jax.tree_util.tree_leaves(restored.params)
                assert all(np.isfinite(np.asarray(l)).all() for l in rs)
                assert int(restored.step) == 2, int(restored.step)

# --- guard: in-graph detection zero-dispatch contract -------------------------

@case("guard/no-extra-dispatch")
def _():
    """Two halves of the guard's observability contract: (1) the
    in-graph detectors ride the existing step program — a guarded step
    compiles to the same number of HLO modules as its unguarded twin
    (one executable) with no host traffic (detection costs no extra
    dispatches); (2) attaching the HOST side — an observe-only
    GuardPolicy polling every step into a guard_sink — leaves the
    guarded step's compiled HLO BIT-IDENTICAL: observation is pure
    host-side reads, never ops."""
    import io

    from apex_tpu import guard, monitor
    from apex_tpu.monitor.check import module_count_and_host_ops

    x = _rand((16, 32), 0)
    y = _rand((16, 8), 1)
    params = {"w": _rand((32, 8), 2, scale=0.1),
              "b": jnp.zeros((8,), jnp.float32)}
    cfg = guard.GuardConfig(window=8, min_history=3)

    def loss_fn(p):
        return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))

    def plain_step(p):
        loss, g = jax.value_and_grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g), \
            loss

    def guarded_step(p, gs):
        loss, g = jax.value_and_grad(loss_fn)(p)
        gs = guard.guard_observe(gs, cfg, loss=loss, grads=g, params=p)
        new_p = jax.tree_util.tree_map(
            lambda a, b: a - 0.1 * gs.lr_scale * b, p, g)
        return guard.guard_commit(gs, new_p, p, cfg), gs, loss

    gs0 = guard.guard_init(cfg)
    n_g, host_g = module_count_and_host_ops(jax.jit(guarded_step),
                                            params, gs0)
    n_p, _ = module_count_and_host_ops(jax.jit(plain_step), params)
    assert n_g == n_p, (n_g, n_p)
    assert not host_g, f"guarded step compiled host traffic: {host_g}"

    # half 2: observe-only host policy + sink attached — bit-identical
    jitted = jax.jit(guarded_step)
    before = jitted.lower(params, gs0).compile().as_text()
    logger = monitor.MetricsLogger(
        sinks=[], guard_sink=monitor.JSONLSink(io.StringIO()))
    policy = guard.GuardPolicy(observe_only=True,
                               event_sink=logger.record_guard)
    p, gs = params, gs0
    for i in range(3):
        p, gs, loss = jitted(p, gs)
        act = policy.update(i, gs)
        assert act.kind == "none", act
    logger.close()
    after = jitted.lower(params, gs0).compile().as_text()
    assert after == before, \
        "observe-only guard observation changed the compiled program"


@case("integrity/no-extra-dispatch")
def _():
    """The silent-divergence defense's observability contract: (1) the
    fingerprint fold + cross-replica compare ride the existing step
    program — the instrumented step compiles to ONE executable with no
    host traffic (off-steps take the empty ``lax.cond`` branch: no
    fold, no collective, and the host polls only cumulative counters);
    (2) attaching the HOST side — a GuardPolicy polling GuardState AND
    IntegrityState every step into guard/integrity sinks — leaves the
    compiled HLO BIT-IDENTICAL, donated and undonated (observation is
    pure host-side reads, never ops). Same guarantee the
    monitor/guard/goodput/cluster cases pin for their layers."""
    import io

    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu import guard, monitor, parallel
    from apex_tpu.monitor.check import module_count_and_host_ops
    from apex_tpu.trace.spans import span

    devs = jax.devices()
    if len(devs) < 2:
        print("  (skip: <2 local devices — no dp axis to fingerprint "
              "across)")
        return
    mesh = Mesh(np.array(devs), ("data",))
    world = len(devs)
    cfg = guard.GuardConfig(window=8, min_history=3)
    icfg = guard.IntegrityConfig(check_every=4)   # steps 1-3 are OFF

    n = 16 * world
    x = _rand((n, 32), 0)
    y = _rand((n, 8), 1)
    params = {"w": _rand((32, 8), 2, scale=0.1),
              "b": jnp.zeros((8,), jnp.float32)}

    def body(p, gs, ist, x, y, fingerprinted):
        if fingerprinted:
            ist = guard.integrity_check(ist, icfg, p, axis_name="data")

        def loss_fn(p):
            return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))

        loss, g = jax.value_and_grad(loss_fn)(p)
        with span("ddp/sync_gradients", kind="collective"):
            g = parallel.sync_gradients(g, "data")
        with span("ddp/loss_pmean", kind="collective"):
            loss = jax.lax.pmean(loss, "data")
        gs = guard.guard_observe(
            gs, cfg, loss=loss, grads=g, params=p,
            replica_ok=guard.integrity_ok(ist) if fingerprinted
            else None)
        new_p = jax.tree_util.tree_map(
            lambda a, b: a - 0.1 * gs.lr_scale * b, p, g)
        return guard.guard_commit(gs, new_p, p, cfg), gs, ist, loss

    def build(fingerprinted, donate):
        fn = functools.partial(body, fingerprinted=fingerprinted)
        mapped = jax.shard_map(
            fn, mesh=mesh,
            in_specs=(P(), P(), P(), P("data"), P("data")),
            out_specs=(P(), P(), P(), P()), check_vma=False)
        kw = {"donate_argnums": (0, 1, 2)} if donate else {}
        return jax.jit(mapped, **kw)

    gs0 = guard.guard_init(cfg)
    ist0 = guard.integrity_init(icfg, world=world)

    # half 1: one executable, no host ops (module-count parity with
    # the fingerprint-less guarded twin)
    n_i, host_i = module_count_and_host_ops(build(True, False),
                                            params, gs0, ist0, x, y)
    n_g, _ = module_count_and_host_ops(build(False, False),
                                       params, gs0, ist0, x, y)
    assert n_i == n_g, (n_i, n_g)
    assert not host_i, \
        f"fingerprinted step compiled host traffic: {host_i}"

    # half 2: host polling (guard + integrity, every step — three of
    # four being off-steps) leaves the program bit-identical, donated
    # and undonated
    for donate in (False, True):
        jitted = build(True, donate)
        before = jitted.lower(params, gs0, ist0, x, y) \
            .compile().as_text()
        logger = monitor.MetricsLogger(
            sinks=[], guard_sink=monitor.JSONLSink(io.StringIO()),
            integrity_sink=monitor.JSONLSink(io.StringIO()))
        policy = guard.GuardPolicy(
            observe_only=True, event_sink=logger.record_guard,
            integrity_sink=logger.record_integrity)
        # fresh unaliased buffers: the zero-scalar counters of a
        # freshly-init'd GuardState/IntegrityState share one cached
        # device constant, which a donating jit would refuse to donate
        # twice
        p, gs, ist = jax.tree_util.tree_map(
            lambda a: jnp.array(a, copy=True), (params, gs0, ist0))
        for i in range(4):
            p, gs, ist, loss = jitted(p, gs, ist, x, y)
            act = policy.update(i, gs)
            iact = policy.update_integrity(i, ist)
            assert act.kind == "none" and iact.kind == "none"
        logger.close()
        after = jitted.lower(params, gs0, ist0, x, y) \
            .compile().as_text()
        assert after == before, (
            f"integrity observation changed the compiled program "
            f"(donate={donate})")


@case("goodput/no-extra-dispatch")
def _():
    """The goodput observatory is pure host-side observation: a step
    driven under a Tracer with per-phase spans, a GoodputLedger folding
    every step, a heartbeat writer beating the shared-fs straggler
    files, and a compile watcher feeding recompile spans must compile
    BIT-IDENTICAL HLO to the unobserved twin (same guarantee the
    monitor/trace/memory/guard cases pin), with no host traffic — and
    the ledger's bucket sum must close over each step's measured wall
    time (the attribution-closure contract
    ``scripts/goodput_audit.py --cpu8`` pins at 5%)."""
    import io
    import tempfile

    from apex_tpu import monitor, prof, trace
    from apex_tpu.monitor.check import module_count_and_host_ops

    x = _rand((16, 32), 0)
    y = _rand((16, 8), 1)
    params = {"w": _rand((32, 8), 2, scale=0.1),
              "b": jnp.zeros((8,), jnp.float32)}

    def train_step(p, x, y):
        def loss_fn(p):
            return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))
        g = jax.grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)

    plain = jax.jit(train_step)
    hlo_plain = plain.lower(params, x, y).compile().as_text()

    watcher = prof.CompileWatcher()
    logger = monitor.MetricsLogger(
        sinks=[], goodput_sink=monitor.JSONLSink(io.StringIO()))
    tracer = trace.Tracer()
    ledger = monitor.GoodputLedger(tracer, tolerance=0.05)
    ledger.subscribe(logger.record_goodput)
    watched = watcher.watch(train_step, name="train_step")
    p = params
    with tempfile.TemporaryDirectory() as tmp:
        hb = trace.HeartbeatWriter(tmp, rank=0)
        tracer.subscribe(hb.on_step)
        with tracer:
            for i in range(4):
                with trace.step(i):
                    with trace.span("dispatch"):
                        p = watched(p, x, y)
                    with trace.span("fetch"):
                        jax.block_until_ready(p)
        assert hb.n_written == 4 and hb.n_dropped == 0
    logger.close()

    hlo_obs = watched.jitted.lower(params, x, y).compile().as_text()
    assert hlo_obs == hlo_plain, \
        "goodput observation changed the compiled program"
    _n, host = module_count_and_host_ops(watched.jitted, params, x, y)
    assert not host, f"observed step compiled host traffic: {host}"
    assert len(ledger.steps) == 4
    ok, worst = ledger.check_closure()
    assert ok, f"attribution closure broke: worst error {worst:.4f}"
    # step 0 folded the trace+compile: its back-dated compile span must
    # land in the recompile bucket, and steady state must not
    assert ledger.steps[0].buckets["recompile"] > 0
    assert ledger.steps[-1].buckets["recompile"] == 0


@case("sharding/no-extra-dispatch")
def _():
    """Per-axis sharding attribution is pure AOT observation: building
    the :func:`apex_tpu.prof.shard_report` (HLO sharding annotations +
    memory report join) and the per-axis wire split
    (:func:`apex_tpu.monitor.collective_bytes_by_axis`) off a compiled
    step, then attaching both to the sharding event channel, must
    leave the compiled HLO BIT-IDENTICAL — donated and undonated —
    with zero host ops in the observed module (same guarantee the
    monitor/memory/goodput cases pin for their layers). The grad
    sync's wire bytes must land on the ``data`` axis row (the registry
    join), never silently in ``unknown``."""
    import io

    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu import monitor, parallel, prof
    from apex_tpu.lint.mesh_model import parse_mesh_spec
    from apex_tpu.monitor.check import module_count_and_host_ops
    from apex_tpu.trace.spans import span

    devs = jax.devices()
    if len(devs) < 2:
        print("  (skip: <2 local devices — no data axis to attribute)")
        return
    world = len(devs)
    mesh = Mesh(np.array(devs), ("data",))
    mm = parse_mesh_spec(f"ici{world}")

    n = 16 * world
    x = _rand((n, 32), 0)
    y = _rand((n, 8), 1)
    params = {"w": _rand((32, 8), 2, scale=0.1),
              "b": jnp.zeros((8,), jnp.float32)}

    def train_step(p, x, y):
        def loss_fn(p):
            return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))
        loss, g = jax.value_and_grad(loss_fn)(p)
        with span("ddp/sync_gradients", kind="collective"):
            g = parallel.sync_gradients(g, "data")
        new_p = jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)
        return new_p, loss

    def build(donate):
        mapped = jax.shard_map(
            train_step, mesh=mesh,
            in_specs=(P(), P("data"), P("data")),
            out_specs=(P(), P()), check_vma=False)
        kw = {"donate_argnums": (0,)} if donate else {}
        return jax.jit(mapped, **kw)

    for donate in (False, True):
        jitted = build(donate)
        compiled = jitted.lower(params, x, y).compile()
        before = compiled.as_text()
        sr = prof.shard_report(compiled, mm)
        wire = {ax: sum(per.values()) for ax, per in
                monitor.collective_bytes_by_axis(before).items()}
        logger = monitor.MetricsLogger(
            sinks=[], sharding_sink=monitor.JSONLSink(io.StringIO()))
        logger.attach_shard_report(sr, wire_by_axis=wire)
        logger.close()
        after = jitted.lower(params, x, y).compile().as_text()
        assert after == before, (
            f"sharding attribution changed the compiled program "
            f"(donate={donate})")
        assert wire.get("data", 0) > 0, (
            f"grad sync not attributed to the data axis: {wire}")
        ok, worst = sr.closure()
        assert ok, f"per-axis HBM closure broke: {worst:.4f}"
        assert sr.axis_bytes("data")["sharded_bytes"] > 0, (
            "nothing attributed sharded over the data axis")
    _n, host = module_count_and_host_ops(build(False), params, x, y)
    assert not host, f"observed step compiled host traffic: {host}"


@case("roofline/no-extra-dispatch")
def _():
    """Roofline observation is AOT + offline: compiling the step for
    the analytic side, capturing a profiler trace around it, parsing
    the xplane, building the roofline report, and attaching it to a
    logger must leave the compiled HLO BIT-IDENTICAL (donated and
    undonated) — the report reads the module and the trace, never the
    program. Same guarantee the monitor/trace/memory/goodput cases
    pin."""
    import io
    import tempfile

    from apex_tpu import monitor, prof

    x = _rand((16, 32), 0)
    y = _rand((16, 8), 1)
    params = {"w": _rand((32, 8), 2, scale=0.1),
              "b": jnp.zeros((8,), jnp.float32)}

    def train_step(p, x, y):
        def loss_fn(p):
            return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))
        g = jax.grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)

    for donate in (False, True):
        kw = {"donate_argnums": (0,)} if donate else {}
        plain = jax.jit(train_step, **kw)
        hlo_plain = plain.lower(params, x, y).compile().as_text()

        observed = jax.jit(train_step, **kw)
        compiled = observed.lower(params, x, y).compile()
        with tempfile.TemporaryDirectory() as tmp:
            with prof.trace(tmp):
                p2 = observed(params, x, y)
                jax.block_until_ready(p2)
            profile = prof.parse_trace(tmp)     # no device plane on CPU
        rep = prof.roofline_report(compiled=compiled, profile=profile
                                   if profile.ops else None)
        logger = monitor.MetricsLogger(
            sinks=[], roofline_sink=monitor.JSONLSink(io.StringIO()))
        logger.attach_roofline_report(rep)
        logger.close()
        assert rep.rows, "roofline report attributed no ops"

        hlo_obs = observed.lower(params, x, y).compile().as_text()
        assert hlo_obs == hlo_plain, (
            f"roofline observation changed the compiled program "
            f"(donate={donate})")


@case("cluster/no-extra-dispatch")
def _():
    """The cluster control plane is host-side only: a step driven
    under full membership instrumentation — a joined
    ClusterMembership renewing its lease every step, a
    generation-fenced CheckpointManager saving mid-loop, a
    RecoveryCoordinator polling for peer intents, and a
    CollectiveDeadline watching the tracer's collective spans — must
    compile BIT-IDENTICAL HLO to the uninstrumented twin, donated and
    undonated (membership is lease files + fence checks BETWEEN
    dispatches, never ops). Same guarantee the ckpt/guard/goodput
    cases pin for their layers."""
    import tempfile

    from apex_tpu import ckpt, cluster, trace

    x = _rand((16, 32), 0)
    y = _rand((16, 8), 1)
    params = {"w": _rand((32, 8), 2, scale=0.1),
              "b": jnp.zeros((8,), jnp.float32)}

    def train_step(p, x, y):
        def loss_fn(p):
            return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))
        g = jax.grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)

    for donate in ((), (0,)):
        plain = jax.jit(train_step, donate_argnums=donate)
        hlo_plain = plain.lower(params, x, y).compile().as_text()

        jitted = jax.jit(train_step, donate_argnums=donate)
        tracer = trace.Tracer()
        with tempfile.TemporaryDirectory() as tmp:
            member = cluster.ClusterMembership(
                os.path.join(tmp, "cluster"), rank=0)
            member.join()
            coord = cluster.RecoveryCoordinator(member,
                                                barrier_timeout_s=0.2)
            deadline = cluster.CollectiveDeadline(
                tracer, deadline_s=60.0, generation=member.refresh)
            mgr = ckpt.CheckpointManager(os.path.join(tmp, "ck"),
                                         fence=member, rank=0,
                                         process_count=1)
            p = params
            with tracer:
                for i in range(3):
                    with trace.step(i):
                        p = jitted(p, x, y)
                        jax.block_until_ready(p)
                    member.heartbeat()
                    assert deadline.poll_once() is None
                    assert not coord.peer_requested()
                    if i == 1:
                        mgr.save(i, p)
            mgr.wait()
            assert member.check("commit") == 0    # fence valid: gen 0
            member.leave()
        hlo_obs = jitted.lower(params, x, y).compile().as_text()
        assert hlo_obs == hlo_plain, (
            f"cluster membership instrumentation changed the compiled "
            f"step (donate={donate})")


@case("numerics/no-extra-dispatch")
def _():
    """The numerics observatory's observability contract: (1) the
    per-site fold (amax/amin EMAs, exponent histograms, uw ratios) and
    the in-graph ScaleHistory update ride the existing step program —
    the instrumented step compiles to ONE executable with no host
    traffic (off-steps take the empty ``lax.cond`` branch: no fold, no
    scatter-add); (2) the HOST side — polling NumericsState into
    ``check_events`` / ``precision_report`` / ``scale_update_events``
    through a ``numerics_sink`` every step — leaves the compiled HLO
    BIT-IDENTICAL, donated and undonated (observation is pure
    host-side reads, never ops). Same guarantee the
    monitor/guard/integrity cases pin for their layers."""
    import io

    from apex_tpu import amp, monitor
    from apex_tpu.monitor import numerics as _nx
    from apex_tpu.monitor.check import module_count_and_host_ops

    x = _rand((16, 32), 0)
    y = _rand((16, 8), 1)
    params = {"w": _rand((32, 8), 2, scale=0.1),
              "b": jnp.zeros((8,), jnp.float32)}
    ncfg = _nx.NumericsConfig(check_every=4)   # steps 1-3 are OFF
    scfg = amp.ScaleHistoryConfig(window=4)
    sites = _nx.site_names({"grads": params, "params": params})
    n_sites = len(sites)
    grad_rows = [i for i, s in enumerate(sites)
                 if s.startswith("grads/")]

    def body(p, ns, sh, x, y, observed):
        def loss_fn(p):
            return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))
        g = jax.grad(loss_fn)(p)
        if observed:
            ns = _nx.numerics_observe(ns, ncfg,
                                      {"grads": g, "params": p})
            sh = amp.scale_history_update(
                sh, scfg, _nx.scale_amax(ns, grad_rows))
        new_p = jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)
        return new_p, ns, sh, jnp.float32(0)

    def build(observed, donate):
        fn = functools.partial(body, observed=observed)
        kw = {"donate_argnums": (0, 1, 2)} if donate else {}
        return jax.jit(fn, **kw)

    ns0 = _nx.numerics_init(ncfg, sites=sites)
    sh0 = amp.scale_history_init(scfg, n_sites=len(grad_rows))

    # half 1: one executable, no host ops (module-count parity with
    # the unobserved twin)
    n_o, host_o = module_count_and_host_ops(build(True, False),
                                            params, ns0, sh0, x, y)
    n_p, _ = module_count_and_host_ops(build(False, False),
                                       params, ns0, sh0, x, y)
    assert n_o == n_p, (n_o, n_p)
    assert not host_o, \
        f"numerics-observed step compiled host traffic: {host_o}"

    # half 2: host polling every step (three of four being off-steps)
    # leaves the program bit-identical, donated and undonated
    for donate in (False, True):
        jitted = build(True, donate)
        before = jitted.lower(params, ns0, sh0, x, y) \
            .compile().as_text()
        logger = monitor.MetricsLogger(
            sinks=[], numerics_sink=monitor.JSONLSink(io.StringIO()))
        # fresh unaliased buffers: freshly-init'd states share cached
        # zero-scalar constants a donating jit would refuse to donate
        # twice
        p, ns, sh = jax.tree_util.tree_map(
            lambda a: jnp.array(a, copy=True), (params, ns0, sh0))
        for i in range(4):
            # fetch BEFORE the (possibly donating) dispatch — donation
            # invalidates the input buffers, the same hazard
            # MetricsLogger(donation_safe=) covers for metrics
            prev_sh = jax.device_get(sh)
            p, ns, sh, _loss = jitted(p, ns, sh, x, y)
            for ev in _nx.check_events(ns, sites,
                                       current_dtype="bfloat16"):
                logger.record_numerics(ev)
            for ev in amp.scale_update_events(
                    prev_sh, sh, tuple(sites[i] for i in grad_rows)):
                logger.record_numerics(ev)
            rep = _nx.precision_report(ns, sites,
                                       current_dtypes="float32")
            for ev in rep.to_events():
                logger.record_numerics(ev)
        logger.close()
        assert int(jax.device_get(ns.check_count)) == 1
        after = jitted.lower(params, ns0, sh0, x, y) \
            .compile().as_text()
        assert after == before, (
            f"numerics observation changed the compiled program "
            f"(donate={donate})")


@case("dynamics/no-extra-dispatch")
def _():
    """The training-dynamics observatory's observability contract:
    (1) the fold — GNS/geometry probe collectives included (the
    ``ddp/dynamics_gns`` psum and ``ddp/dynamics_geom`` all-gather ride
    inside the step's shard_map next to the gradient pmean) — compiles
    to ONE executable with no host traffic, module-count parity with
    the unobserved twin (off-steps take the empty ``lax.cond`` branch);
    (2) the HOST side — polling DynamicsState into ``check_events`` /
    ``dynamics_report`` through a ``dynamics_sink`` every step — leaves
    the compiled HLO BIT-IDENTICAL, donated and undonated. Same
    guarantee the monitor/guard/integrity/numerics cases pin for their
    layers."""
    import io

    from jax.sharding import PartitionSpec as P

    from apex_tpu import monitor
    from apex_tpu.monitor import dynamics as _dx
    from apex_tpu.monitor.check import module_count_and_host_ops
    from apex_tpu.parallel import distributed as _dist

    devs = jax.devices()
    world = len(devs)
    mesh = jax.sharding.Mesh(np.array(devs), ("data",))
    local_batch = 8
    x = _rand((local_batch * world, 32), 0)
    y = _rand((local_batch * world, 8), 1)
    params = {"w": _rand((32, 8), 2, scale=0.1),
              "b": jnp.zeros((8,), jnp.float32)}
    dcfg = _dx.DynamicsConfig(check_every=4,        # steps 1-3 are OFF
                              local_batch=local_batch)
    sites = _dx.site_names({"dynamics/update": params})

    def body(p, ds, x, y, observed):
        def inner(p, ds, x, y):
            def loss_fn(p):
                return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))
            g_local = jax.grad(loss_fn)(p)
            g = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, "data"), g_local)
            new_p = jax.tree_util.tree_map(
                lambda a, b: a - 0.1 * b, p, g)
            if observed:
                ds = _dx.dynamics_observe(
                    ds, dcfg,
                    lambda: {"dynamics/update": jax.tree_util.tree_map(
                        lambda n, o: n - o, new_p, p)},
                    probe=lambda: _dist.dynamics_probe(g_local, g,
                                                       "data"),
                    grads={"dynamics/update": g},
                    weights={"dynamics/update": p})
            return new_p, ds, jnp.float32(0)
        return jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P(), P(), P("data"), P("data")),
            out_specs=(P(), P(), P()),
            check_vma=False)(p, ds, x, y)

    def build(observed, donate):
        fn = functools.partial(body, observed=observed)
        kw = {"donate_argnums": (0, 1)} if donate else {}
        return jax.jit(fn, **kw)

    ds0 = _dx.dynamics_init(dcfg, sites=sites, world=world)

    # half 1: one executable, no host ops (module-count parity with
    # the unobserved twin)
    n_o, host_o = module_count_and_host_ops(build(True, False),
                                            params, ds0, x, y)
    n_p, _ = module_count_and_host_ops(build(False, False),
                                       params, ds0, x, y)
    assert n_o == n_p, (n_o, n_p)
    assert not host_o, \
        f"dynamics-observed step compiled host traffic: {host_o}"

    # half 2: host polling every step (three of four being off-steps)
    # leaves the program bit-identical, donated and undonated
    for donate in (False, True):
        jitted = build(True, donate)
        before = jitted.lower(params, ds0, x, y).compile().as_text()
        logger = monitor.MetricsLogger(
            sinks=[], dynamics_sink=monitor.JSONLSink(io.StringIO()))
        # fresh unaliased buffers: freshly-init'd states share cached
        # zero-scalar constants a donating jit would refuse to donate
        # twice
        p, ds = jax.tree_util.tree_map(
            lambda a: jnp.array(a, copy=True), (params, ds0))
        for _ in range(4):
            p, ds, _loss = jitted(p, ds, x, y)
            for ev in _dx.check_events(ds, sites,
                                       local_batch=local_batch):
                logger.record_dynamics(ev)
            _dx.dynamics_report(ds, sites, local_batch=local_batch)
        logger.close()
        assert int(jax.device_get(ds.check_count)) == 1
        after = jitted.lower(params, ds0, x, y).compile().as_text()
        assert after == before, (
            f"dynamics observation changed the compiled program "
            f"(donate={donate})")


def _pod_budget():
    """Import scripts.pod_comm_budget (the shared HLO audit helpers)
    regardless of cwd — the module lives next to the package root."""
    try:
        from scripts import pod_comm_budget
    except ImportError:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        if root not in sys.path:
            sys.path.insert(0, root)
        from scripts import pod_comm_budget
    return pod_comm_budget


def _ddp_toy_step(mesh, n, **ddp_kw):
    """A small stacked-matmul DDP step, lowered with avals (works on
    abstract AOT topology devices and real meshes alike). Returns the
    compiled HLO text and the grad-leaf avals."""
    from jax.sharding import PartitionSpec as P
    from apex_tpu import parallel

    ddp = parallel.DistributedDataParallel(mesh, **ddp_kw)
    names = [f"w{i}" for i in range(8)]

    def loss_fn(p, x):
        h = x
        for k in names:
            h = jnp.tanh(h @ p[k])
        return jnp.sum(h * h)

    def step(p, x):
        l, g = jax.value_and_grad(loss_fn)(p, x)
        g = ddp.sync(g)
        p = jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)
        return p, jax.lax.pmean(l, parallel.DATA_AXIS)

    mapped = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P(parallel.DATA_AXIS)),
        out_specs=(P(), P()), check_vma=False))
    p_s = {k: jax.ShapeDtypeStruct((128, 128), jnp.float32)
           for k in names}
    x_s = jax.ShapeDtypeStruct((4 * n, 128), jnp.float32)
    hlo = mapped.lower(p_s, x_s).compile().as_text()
    return hlo, list(p_s.values())


@case("ddp/overlap-start-done")
def _():
    """Bucketed DDP sync must compile to one all-reduce PER BUCKET (the
    chained barriers keep the combiner from re-merging them into a
    terminal collective); on a TPU-scheduled module the pairs must be
    async ``all-reduce-start``/``-done`` with real compute scheduled
    inside at least one window — the overlap the latency-hiding
    scheduler is given to exploit. Prefers a real multi-chip AOT target
    (async pairs only exist in TPU-scheduled modules); falls back to
    the local device mesh (CI: 8 virtual CPU devices) for the
    structural bucket-count half of the claim."""
    from jax.sharding import Mesh
    from apex_tpu.parallel import comm
    overlap_audit = _pod_budget().overlap_audit

    devs = None
    if jax.default_backend() == "tpu":
        # only probe AOT topologies where a TPU runtime is actually
        # attached — off-TPU the libtpu metadata fetch retries for
        # minutes before failing
        try:
            from jax.experimental import topologies
            topo = topologies.get_topology_desc(platform="tpu",
                                                topology_name="v5e:2x2")
            devs = np.array(topo.devices)
        except Exception:
            devs = None
    if devs is None:
        local = jax.devices()
        if len(local) < 2:
            print("  (skip: no AOT topology support and <2 local "
                  "devices — collectives would fold away)")
            return
        devs = np.array(local)
    n = devs.size
    mesh = Mesh(devs, ("data",))
    message_size = 40_000              # 128x128 leaves -> ~2 per bucket
    hlo, leaves = _ddp_toy_step(mesh, n, bucket_allreduce=True,
                                message_size=message_size)
    n_buckets = len(comm.bucket_plan(leaves, message_size))
    assert n_buckets >= 3, f"toy plan degenerate: {n_buckets} buckets"
    n_ar = hlo.count(" all-reduce(") + hlo.count(" all-reduce-start(")
    # the scalar loss pmean adds one small all-reduce
    assert n_ar >= n_buckets, (
        f"buckets merged: {n_ar} all-reduces < {n_buckets} buckets")
    pairs = overlap_audit(hlo)
    if pairs:   # TPU-scheduled module: the async-overlap half
        assert any(p["compute_between"] > 0 for p in pairs), (
            "no compute scheduled between any start/done pair: "
            f"{pairs}")


@case("ddp/no-compress-bitident")
def _():
    """The default (no-bucket, no-compress) DDP sync must compile to a
    program structurally identical to a direct sync_gradients call —
    same instruction opcodes in the same order, same collectives. The
    new comm modes are strictly opt-in: that includes the hierarchical
    ``comm_plan`` — ``comm_plan=None`` (explicit or defaulted) must
    leave the compiled text BIT-identical to the default path."""
    from jax.sharding import Mesh
    from apex_tpu import parallel
    collectives = _pod_budget().collectives

    local = jax.devices()
    if len(local) < 2:
        print("  (skip: <2 local devices — sync collectives fold away)")
        return
    n = len(local)
    mesh = Mesh(np.array(local), ("data",))
    hlo_ddp, _ = _ddp_toy_step(mesh, n)
    hlo_none, _ = _ddp_toy_step(mesh, n, comm_plan=None)
    assert hlo_none == hlo_ddp, (
        "comm_plan=None changed the compiled default DDP program")

    # the manual twin: same step body, sync_gradients under the same
    # collective span DDP.sync uses
    from jax.sharding import PartitionSpec as P
    from apex_tpu.trace.spans import span as _span
    names = [f"w{i}" for i in range(8)]

    def loss_fn(p, x):
        h = x
        for k in names:
            h = jnp.tanh(h @ p[k])
        return jnp.sum(h * h)

    def step(p, x):
        l, g = jax.value_and_grad(loss_fn)(p, x)
        with _span("ddp/sync_gradients", kind="collective"):
            g = parallel.sync_gradients(g, parallel.DATA_AXIS)
        p = jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)
        return p, jax.lax.pmean(l, parallel.DATA_AXIS)

    mapped = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P(parallel.DATA_AXIS)),
        out_specs=(P(), P()), check_vma=False))
    p_s = {k: jax.ShapeDtypeStruct((128, 128), jnp.float32)
           for k in names}
    x_s = jax.ShapeDtypeStruct((4 * n, 128), jnp.float32)
    hlo_ref = mapped.lower(p_s, x_s).compile().as_text()

    def _opcode_seq(hlo):
        import re
        return [m.group(1) for m in re.finditer(
            r"= (?:\(?[\w\[\]{},: ]*\)?) ([\w-]+)\(", hlo)]

    assert collectives(hlo_ddp) == collectives(hlo_ref), (
        collectives(hlo_ddp), collectives(hlo_ref))
    assert _opcode_seq(hlo_ddp) == _opcode_seq(hlo_ref), (
        "default DDP sync compiled a structurally different program")


@case("autotune/no-extra-dispatch")
def _():
    """The tuning-DB consult is a trace-time table lookup with an
    exact-key contract: a shape that MISSES the DB must compile HLO
    BIT-IDENTICAL to ``APEX_TPU_AUTOTUNE=off`` (donated and undonated)
    — no extra dispatch, no reordered ops, nothing. And the positive
    twin: an exact-key HIT on a seeded DB must actually change the
    realized block (a different grid → a different program), proving
    the consult happens at trace time rather than being dead code."""
    import os

    from apex_tpu import ops
    from apex_tpu.ops import autotune

    x = _rand((96, 72), 0)
    w = jnp.ones((72,), jnp.float32)
    b = jnp.zeros((72,), jnp.float32)
    # 2x BUFFER_MULTIPLE: a legal arena length whose fingerprint is
    # NOT in the committed DB (the committed optimizer entry is the
    # 1x-BUFFER_MULTIPLE sweep shape)
    buf = _rand((2 * 512 * 128,), 1)

    def make_step():
        # a fresh function object per compile — jit's trace cache is
        # keyed on identity, and the env/DB consult happens at trace
        # time, so a shared object would reuse the first trace
        def step(x_, w_, b_, buf_):
            y = ops.fused_layer_norm_affine(x_, w_, b_)
            scaled, ok = ops.multi_tensor_scale(buf_, 0.5)
            return y.sum() + scaled.sum() + ok.astype(jnp.float32)
        return step

    prev = os.environ.get("APEX_TPU_AUTOTUNE")

    def _set(mode):
        if mode is None:
            os.environ.pop("APEX_TPU_AUTOTUNE", None)
        else:
            os.environ["APEX_TPU_AUTOTUNE"] = mode

    try:
        for donate in (False, True):
            kw = {"donate_argnums": (0,)} if donate else {}

            _set("off")
            hlo_off = jax.jit(make_step(), **kw).lower(
                x, w, b, buf).compile().as_text()

            # db mode against the committed DB: these shapes are not
            # in it — exact-key miss, defaults, bit-identical HLO
            _set("db")
            autotune.reset_counters()
            hlo_db = jax.jit(make_step(), **kw).lower(
                x, w, b, buf).compile().as_text()
            assert hlo_db == hlo_off, (
                f"DB-miss path compiled a different program than "
                f"APEX_TPU_AUTOTUNE=off (donate={donate})")
            c = autotune.counters()
            assert c["misses"] >= 2 and c["hits"] == 0, (
                f"expected pure trace-time misses, got {c}")

            # positive twin: an exact-key hit changes the realized
            # block, hence the program
            entry = autotune.TuningEntry(
                family="layer_norm", dims=(96, 72), dtype="float32",
                chip=autotune.chip_kind(), block={"block_rows": 32})
            with autotune.use_db(autotune.TuningDB(
                    {entry.fingerprint: entry})):
                autotune.reset_counters()
                hlo_hit = jax.jit(make_step(), **kw).lower(
                    x, w, b, buf).compile().as_text()
                assert autotune.counters()["hits"] == 1, \
                    autotune.counters()
            assert hlo_hit != hlo_off, (
                "an exact-key tuned hit left the program unchanged — "
                "the consult is not reaching the dispatch seam")
    finally:
        _set(prev)


# --- driver ------------------------------------------------------------------

def run(pattern: Optional[str] = None,
        json_path: Optional[str] = None) -> bool:
    backend = jax.default_backend()
    device = getattr(jax.devices()[0], "device_kind", "?")
    results: List[Dict] = []
    ok = True
    for name, fn in CASES:
        if pattern and pattern not in name:
            continue
        try:
            fn()
            results.append({"case": name, "ok": True})
            print(f"  ok    {name}", flush=True)
        except Exception as e:
            ok = False
            err = "".join(traceback.format_exception_only(type(e), e))[:2000]
            results.append({"case": name, "ok": False, "error": err})
            print(f"  FAIL  {name}\n{traceback.format_exc()}", flush=True)
    summary = {
        "backend": backend, "device": device,
        "compiled": backend == "tpu",
        "ok": ok, "n_cases": len(results),
        "n_failed": sum(1 for r in results if not r["ok"]),
        "results": results,
    }
    if json_path:
        with open(json_path, "w") as f:
            json.dump(summary, f, indent=1)
    print(f"compile-check: {summary['n_cases'] - summary['n_failed']}/"
          f"{summary['n_cases']} ok on {device} "
          f"({'compiled' if summary['compiled'] else 'interpret'})")
    return ok


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    pattern = None
    json_path = None
    it = iter(argv)
    for a in it:
        if a == "--json":
            json_path = next(it)
        elif a in ("-k", "--filter"):
            pattern = next(it)
        elif a == "--compile-check":
            pass
        else:
            print(f"usage: python -m apex_tpu.ops [--compile-check] "
                  f"[-k PATTERN] [--json PATH]")
            return 2
    return 0 if run(pattern, json_path) else 1


if __name__ == "__main__":
    sys.exit(main())
