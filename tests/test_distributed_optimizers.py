"""Sharded (ZeRO-1) optimizers vs their unsharded fused counterparts.

The invariant (the reference validates it with `tests/distributed/
amp_master_params`-style cross-rank comparisons): a sharded step over N
devices with per-device grads g_i must produce exactly the params of the
unsharded optimizer applied to mean(g_i), identically on every device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import optim


def make_params(rng):
    """Params big enough that every one of 8 shards holds real content
    (shards are 65536-aligned; ~720k elements span 6+ shards) — small
    params would leave ranks 1-7 pure padding and mask rank-linearization
    or tile-order bugs."""
    return {
        "w1": jnp.asarray(rng.randn(600, 1200).astype(np.float32)),
        "w2": jnp.asarray(rng.randn(257).astype(np.float32)),
        "w3": jnp.asarray(rng.randn(8, 4, 2).astype(np.float32)),
    }


def per_device_grads(rng, params, world):
    """world stacked grad trees (device i gets slice i)."""
    return [
        jax.tree_util.tree_map(
            lambda p: jnp.asarray(
                rng.randn(*p.shape).astype(np.float32)) * 0.1, params)
        for _ in range(world)
    ]


def stack_grads(grads_list):
    return jax.tree_util.tree_map(lambda *g: jnp.stack(g), *grads_list)


def mean_grads(grads_list):
    return jax.tree_util.tree_map(
        lambda *g: sum(g) / len(g), *grads_list)


def run_sharded(mesh, opt, params, grads_stacked, steps):
    def prog(params, gstack):
        state = opt.init(params)
        p = params
        for _ in range(steps):
            p, state = opt.step(gstack, state, p)
        return p

    def wrapper(params, gstack):
        # each device takes its grad slice
        return prog(params, jax.tree_util.tree_map(lambda g: g[0], gstack))

    return jax.jit(jax.shard_map(
        wrapper, mesh=mesh,
        in_specs=(P(), P("data")),
        out_specs=P(),
        check_vma=False))(params, grads_stacked)


class TestDistributedFusedAdam:
    def test_matches_unsharded_adam(self, mesh8):
        rng = np.random.RandomState(0)
        params = make_params(rng)
        glist = per_device_grads(rng, params, 8)

        sharded = run_sharded(
            mesh8, optim.DistributedFusedAdam(lr=1e-2, weight_decay=0.01),
            params, stack_grads(glist), steps=3)

        ref_opt = optim.FusedAdam(lr=1e-2, weight_decay=0.01)
        state = ref_opt.init(params)
        p = params
        g = mean_grads(glist)
        for _ in range(3):
            p, state = ref_opt.step(g, state, p)

        for k in params:
            np.testing.assert_allclose(
                np.asarray(sharded[k]), np.asarray(p[k]), atol=1e-6,
                err_msg=k)

    def test_grad_norm_clip(self, mesh8):
        rng = np.random.RandomState(1)
        params = make_params(rng)
        glist = per_device_grads(rng, params, 8)

        sharded = run_sharded(
            mesh8,
            optim.DistributedFusedAdam(lr=1e-2, max_grad_norm=0.05),
            params, stack_grads(glist), steps=2)

        # unsharded reference: clip the mean grad by global norm
        g = mean_grads(glist)
        gnorm = float(jnp.sqrt(sum(
            jnp.sum(jnp.square(x))
            for x in jax.tree_util.tree_leaves(g))))
        scale = min(1.0, 0.05 / gnorm)
        g_clipped = jax.tree_util.tree_map(lambda x: x * scale, g)
        ref_opt = optim.FusedAdam(lr=1e-2)
        state = ref_opt.init(params)
        p = params
        for _ in range(2):
            p, state = ref_opt.step(g_clipped, state, p)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(sharded[k]), np.asarray(p[k]), atol=1e-6,
                err_msg=k)

    def test_compressed_allgather(self, mesh8):
        rng = np.random.RandomState(2)
        params = make_params(rng)
        glist = per_device_grads(rng, params, 8)

        sharded = run_sharded(
            mesh8,
            optim.DistributedFusedAdam(lr=1e-2,
                                       param_gather_dtype=jnp.bfloat16),
            params, stack_grads(glist), steps=1)

        ref_opt = optim.FusedAdam(lr=1e-2)
        p, _ = ref_opt.step(mean_grads(glist), ref_opt.init(params), params)
        for k in params:
            # params traveled as bf16: match to bf16 resolution
            np.testing.assert_allclose(
                np.asarray(sharded[k]), np.asarray(p[k]), atol=2e-2,
                rtol=1e-2, err_msg=k)
            assert sharded[k].dtype == params[k].dtype

    def test_hierarchical_axes(self, mesh4x2):
        rng = np.random.RandomState(3)
        params = make_params(rng)
        glist = per_device_grads(rng, params, 8)

        def wrapper(params, gstack):
            opt = optim.DistributedFusedAdam(
                lr=1e-2, axis_name=("data", "model"))
            state = opt.init(params)
            g = jax.tree_util.tree_map(lambda x: x[0, 0], gstack)
            p, _ = opt.step(g, state, params)
            return p

        gstack = jax.tree_util.tree_map(
            lambda g: g.reshape(4, 2, *g.shape[1:]), stack_grads(glist))
        sharded = jax.jit(jax.shard_map(
            wrapper, mesh=mesh4x2,
            in_specs=(P(), P("data", "model")),
            out_specs=P(),
            check_vma=False))(params, gstack)

        ref_opt = optim.FusedAdam(lr=1e-2)
        p, _ = ref_opt.step(mean_grads(glist), ref_opt.init(params), params)
        for k in params:
            # two-stage reduction reorders the float sum: tiny drift only
            np.testing.assert_allclose(
                np.asarray(sharded[k]), np.asarray(p[k]), atol=1e-4,
                err_msg=k)


class TestDistributedFusedLAMB:
    def test_matches_unsharded_lamb(self, mesh8):
        rng = np.random.RandomState(4)
        params = make_params(rng)
        glist = per_device_grads(rng, params, 8)
        kw = dict(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)

        sharded = run_sharded(
            mesh8, optim.DistributedFusedLAMB(**kw),
            params, stack_grads(glist), steps=3)

        ref_opt = optim.FusedLAMB(**kw)
        state = ref_opt.init(params)
        p = params
        g = mean_grads(glist)
        for _ in range(3):
            p, state = ref_opt.step(g, state, p)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(sharded[k]), np.asarray(p[k]), atol=1e-5,
                err_msg=k)

    def test_state_is_sharded(self, mesh8):
        """Master/moment state per device is 1/8 of the padded arena —
        the actual ZeRO memory win."""
        rng = np.random.RandomState(5)
        params = make_params(rng)

        def get_state_size(params):
            opt = optim.DistributedFusedAdam(lr=1e-2)
            state = opt.init(params)
            return jnp.int32(sum(
                x.size for x in jax.tree_util.tree_leaves(state.slots)))

        size = jax.jit(jax.shard_map(
            get_state_size, mesh=mesh8, in_specs=P(),
            out_specs=P(), check_vma=False))(params)
        from apex_tpu.optim.distributed import _padded_len
        from apex_tpu import arena
        spec = arena.plan(params)
        total = sum(_padded_len(pt.buffer_len, 8)
                    for pt in spec.partitions)
        assert int(size) == 3 * total // 8
