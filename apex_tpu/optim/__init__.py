"""apex_tpu.optim — fused optimizers (SURVEY.md §2.4, §2.6).

Single-process fused optimizers run one Pallas kernel per dtype partition
over the flat arena. ZeRO-style distributed variants (reduce-scatter →
sharded update → all-gather) land in apex_tpu.optim.distributed in the
distributed milestone.
"""

from apex_tpu.optim.fused import (
    FusedAdagrad,
    FusedAdam,
    FusedLAMB,
    FusedNovoGrad,
    FusedOptimizer,
    FusedOptState,
    FusedSGD,
)

__all__ = [
    "FusedAdagrad", "FusedAdam", "FusedLAMB", "FusedNovoGrad",
    "FusedOptimizer", "FusedOptState", "FusedSGD",
]
