"""Coordinated multi-rank recovery: local verdicts → cluster decisions.

:class:`apex_tpu.guard.GuardPolicy` decides *locally* — rank 3's
nonfinite-param probe says "rewind to my newest good checkpoint". At
pod scale that is exactly the split-brain bug: rank 3 rewinds to step 6
while rank 0 (whose checkpoint at 8 is fine) keeps training, and the
next collective silently averages two different histories. The
:class:`RecoveryCoordinator` turns the verdict into a cluster decision
over the same shared filesystem the membership layer uses:

1. every participating rank posts a **signed intent** (one file per
   rank per generation, HMAC'd with the cluster token — a torn write,
   a stray file, or a zombie claiming the wrong generation is refused,
   never miscounted);
2. ranks **resolve deterministically**: wait (jittered, deadline-
   bounded — the ckpt rank-barrier pattern) until every live rank's
   intent is present, then every rank computes the SAME decision from
   the same files — action = worst proposed (escalate > rewind),
   rewind target = *oldest good step wins* (the only step every rank
   can restore);
3. the elected leader (lowest participating rank) **bumps the
   generation** — fencing out every straggler still holding the old
   token — and the others wait to observe the bump before adopting it.

:class:`CollectiveDeadline` is the host-side watchdog on
``kind="collective"`` spans: the step-level :class:`HangWatchdog` can
only say "no step landed"; this tier names *which* collective wedged —
a collective still open after ``deadline_s`` is hung, not slow (a slow
one closes and reopens, resetting its age), and feeds
``EscalationPolicy.trip("collective:<span>")``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

from apex_tpu.cluster.membership import (INTENT_PREFIX,
                                         ClusterMembership,
                                         StaleGenerationError,
                                         cluster_token, mac_ok,
                                         sign_payload)
from apex_tpu.utils.backoff import backoff_sleep

__all__ = ["RecoveryCoordinator", "RecoveryDecision",
           "CollectiveDeadline", "CoordinationError"]

_INTENT_PREFIX = INTENT_PREFIX

#: severity order of proposable actions — resolution takes the worst
ACTIONS = ("rewind", "escalate")


class CoordinationError(RuntimeError):
    """The recovery barrier could not produce a decision (timeout with
    zero usable intents, or every posted intent was refused)."""


class RecoveryDecision(NamedTuple):
    """The cluster's verdict — identical on every resolving rank, by
    construction (a pure function of the same intent files)."""
    action: str                   # "rewind" | "escalate"
    target_step: Optional[int]    # oldest good step (rewind only)
    generation: int               # epoch the decision was made IN
    new_generation: int           # epoch after the fence bump
    ranks: Tuple[int, ...]        # participating ranks
    leader: int                   # lowest participating rank
    refused: Tuple[int, ...] = () # ranks whose intents were refused


def intent_path(directory: str, generation: int, rank: int) -> str:
    return os.path.join(
        directory, f"{_INTENT_PREFIX}{int(generation):08d}"
                   f".rank{int(rank):05d}.json")


class RecoveryCoordinator:
    """See the module docstring.

    ``membership`` is this rank's :class:`ClusterMembership` (provides
    the fence token, the lease table for liveness, and the event
    sink). One coordinator instance serves the whole run; intents are
    per-generation, so a resolved round's files are inert the moment
    the leader bumps (and :meth:`ClusterMembership.gc_stale` cleans
    them at the next relaunch).
    """

    def __init__(self, membership: ClusterMembership, *,
                 barrier_timeout_s: float = 60.0,
                 event_sink: Optional[Callable[[Dict], None]] = None):
        self.membership = membership
        self.directory = membership.directory
        self.rank = membership.rank
        self.barrier_timeout_s = float(barrier_timeout_s)
        self.event_sink = event_sink or membership.event_sink
        # the signing token is immutable after creation: cache it so
        # the per-step pending() poll never re-reads it from disk
        self._token = cluster_token(self.directory)
        #: ranks refused during the last pending()/resolve() scan
        self.last_refused: Tuple[int, ...] = ()

    # -- events ----------------------------------------------------------------

    def _emit(self, event: Dict) -> None:
        if self.event_sink is None:
            return
        try:
            self.event_sink(dict(event, rank=self.rank,
                                 wall_time=time.time()))
        except Exception:
            pass

    # -- intents ---------------------------------------------------------------

    def propose(self, *, action: str, step: int,
                good_step: Optional[int],
                what: str = "guard") -> str:
        """Post this rank's signed intent for the current generation.

        ``good_step`` is the newest checkpoint step this rank verified
        restorable (:meth:`apex_tpu.guard.GuardPolicy.probe_good_step`)
        — None when it has none, which forces the decision to
        escalate. ``what`` names the subsystem whose verdict triggered
        the round (``"guard"`` for the anomaly ladder,
        ``"integrity"`` for a silent-divergence fall-through with no
        repairable majority) — forensic attribution in the event
        stream, not part of the decision. Re-posting (a retried round)
        atomically replaces the previous intent."""
        if action not in ACTIONS:
            raise ValueError(f"action must be one of {ACTIONS}, "
                             f"got {action!r}")
        gen = self.membership.generation
        payload = {"rank": self.rank, "generation": gen,
                   "action": action, "step": int(step),
                   "good_step": (None if good_step is None
                                 else int(good_step)),
                   "what": str(what),
                   "wall_time": time.time()}
        payload["mac"] = sign_payload(self._token, payload)
        path = intent_path(self.directory, gen, self.rank)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._emit({"kind": "cluster_coord", "action": "propose",
                    "generation": gen, "proposed": action,
                    "step": int(step), "what": str(what),
                    "good_step": payload["good_step"]})
        return path

    def _verify(self, rec: Optional[Dict], *, rank: int,
                generation: int) -> Optional[Dict]:
        """One intent record, or None if it must be refused. A refusal
        emits ``cluster_fence`` ``action="refused_intent"`` — the
        split-brain evidence trail: an intent claiming a generation
        the cluster never committed, a MAC that doesn't verify (torn
        write / wrong cluster / tampering), or a rank mismatch all
        land here."""
        reason = None
        if not isinstance(rec, dict):
            reason = "unreadable"
        else:
            if not isinstance(rec.get("mac"), str) or not isinstance(
                    rec.get("generation"), int):
                reason = "malformed"
            elif not mac_ok(self._token, rec):
                reason = "bad signature"
            elif rec["generation"] != generation:
                reason = (f"claims generation {rec['generation']}, "
                          f"cluster is at {generation}")
            elif rec.get("rank") != rank:
                reason = "rank mismatch"
            elif rec.get("action") not in ACTIONS:
                reason = f"unknown action {rec.get('action')!r}"
        if reason is None:
            return rec
        self._emit({"kind": "cluster_fence", "action": "refused_intent",
                    "generation": (rec.get("generation")
                                   if isinstance(rec, dict)
                                   and isinstance(rec.get("generation"),
                                                  int) else 0),
                    "current_generation": generation,
                    "what": "intent", "step": None, "path": None,
                    "reason": f"rank {rank}: {reason}"})
        return None

    def pending(self) -> Dict[int, Dict]:
        """Verified intents posted for the CURRENT generation, by
        rank. The cheap per-step poll a *healthy* rank uses to notice
        a peer asking for recovery (one listdir; empty in steady
        state)."""
        gen = self.membership.generation
        prefix = f"{_INTENT_PREFIX}{gen:08d}.rank"
        out: Dict[int, Dict] = {}
        refused: List[int] = []
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return out
        from apex_tpu.cluster.membership import _read_json_retry
        for name in names:
            if not (name.startswith(prefix) and name.endswith(".json")):
                continue
            try:
                rank = int(name[len(prefix):-len(".json")])
            except ValueError:
                continue
            rec = self._verify(
                _read_json_retry(os.path.join(self.directory, name),
                                 attempts=1),
                rank=rank, generation=gen)
            if rec is None:
                refused.append(rank)
            else:
                out[rank] = rec
        self.last_refused = tuple(refused)
        return out

    def peer_requested(self) -> bool:
        """True when any OTHER rank has a verified intent pending —
        the signal for a locally-healthy rank to join the round."""
        return any(r != self.rank for r in self.pending())

    # -- resolution ------------------------------------------------------------

    def resolve(self, *, expect_ranks: Optional[List[int]] = None,
                bump: bool = True) -> RecoveryDecision:
        """Barrier on the live ranks' intents, decide, fence.

        ``expect_ranks`` overrides liveness (tests, or a controller
        that already decided who survives); default = the membership
        layer's :meth:`~ClusterMembership.alive_ranks` — a rank whose
        lease expired mid-round cannot block the barrier forever, its
        expiry shrinks the electorate on the next poll. On deadline,
        the round proceeds with the verified intents present (the
        missing ranks are dead or fenced; refusing to decide would
        trade a recoverable fault for a hung cluster) — with zero
        intents it raises :class:`CoordinationError`.

        Every resolving rank computes the same decision; the leader
        (lowest participating rank) commits the generation bump with
        ``expect=`` the deciding epoch, so a double-resolve cannot
        stack bumps; followers wait to observe the bump, then all
        participants re-join under the new epoch.
        """
        gen = self.membership.generation
        deadline = time.monotonic() + self.barrier_timeout_s
        attempt = 0
        timed_out = False
        while True:
            intents = self.pending()
            want = (set(int(r) for r in expect_ranks)
                    if expect_ranks is not None
                    else set(self.membership.alive_ranks()) | {self.rank})
            missing = sorted(want - set(intents))
            if not missing:
                break
            if time.monotonic() > deadline:
                timed_out = True
                self._emit({"kind": "cluster_coord",
                            "action": "barrier_timeout",
                            "generation": gen,
                            "deadline_s": self.barrier_timeout_s,
                            "missing": missing,
                            "n_intents": len(intents)})
                break
            backoff_sleep(attempt, cap_s=0.2)
            attempt += 1
        if not intents:
            raise CoordinationError(
                f"recovery round at generation {gen} produced no "
                f"verified intents within {self.barrier_timeout_s}s "
                f"(refused: {list(self.last_refused)}) — nothing to "
                f"decide with; escalate locally")

        # the decision: a pure function of the verified intents, so
        # every resolving rank lands on the SAME verdict
        ranks = tuple(sorted(intents))
        actions = {r: intents[r]["action"] for r in ranks}
        goods = [intents[r]["good_step"] for r in ranks]
        action = "escalate" if ("escalate" in actions.values()
                                or any(g is None for g in goods)) \
            else "rewind"
        target = (min(int(g) for g in goods)
                  if action == "rewind" else None)
        leader = min(ranks)

        new_gen = gen
        if bump:
            if self.rank == leader:
                try:
                    new_gen = self.membership.bump(
                        f"coordinated_{action}", expect=gen)
                except StaleGenerationError:
                    # a racing resolve already fenced this epoch —
                    # adopt its bump instead of stacking another
                    new_gen = self.membership.rejoin()
            else:
                new_gen = self._wait_for_bump(gen)
                self.membership.rejoin()
        dec = RecoveryDecision(action=action, target_step=target,
                               generation=gen, new_generation=new_gen,
                               ranks=ranks, leader=leader,
                               refused=self.last_refused)
        self._emit({"kind": "cluster_coord", "action": "resolve",
                    "generation": gen, "new_generation": new_gen,
                    "decided": action, "target_step": target,
                    "ranks": list(ranks), "leader": leader,
                    "n_refused": len(self.last_refused),
                    "timed_out": bool(timed_out)})
        return dec

    def run_round(self, policy, step: int, like, source, *,
                  action: str = "rewind",
                  expect_ranks: Optional[List[int]] = None,
                  reason: str = "", what: str = "guard"):
        """One full recovery round driven through a
        :class:`~apex_tpu.guard.GuardPolicy`: vote (this rank's newest
        restorable step), resolve, and apply the cluster decision —
        rewind to the agreed target (NOT this rank's own preference),
        or escalate. Returns ``(decision, (restored, manifest) | None)``.

        This is the loop-side glue: a rank whose own guard verdict
        fired calls it with that verdict's ``action``; a locally-
        healthy rank that noticed :meth:`peer_requested` calls it with
        the default ``action="rewind"`` — its healthy vote still
        matters, because its good step bounds the target from above.
        The integrity rung falls through here too
        (``what="integrity"``): a divergence with no repairable
        majority means no single replica can be trusted as a broadcast
        source, and the only consistent state every rank can reach is
        a committed checkpoint — the same oldest-good-step-wins
        resolution, now repairing a *silent* fault.
        """
        good = policy.probe_good_step(like)
        try:
            self.propose(action=action, step=int(step), good_step=good,
                         what=what)
            dec = self.resolve(expect_ranks=expect_ranks)
        except BaseException:
            # no rewind will consume the probe's cached restored tree
            # (a full model copy) — release it before propagating, or
            # it pins HBM the recovery retry itself needs
            policy.drop_probe_cache()
            raise
        if dec.action == "escalate":
            policy.escalate(
                f"coordinated escalate (generation {dec.generation}; "
                f"ranks {list(dec.ranks)}; {reason})")
            return dec, None           # only raise-mode off-main-thread
        restored = policy.rewind(
            int(step), like, source, target_step=dec.target_step,
            reason=(f"coordinated (generation {dec.generation}->"
                    f"{dec.new_generation}; target {dec.target_step}"
                    + (f"; {reason}" if reason else "") + ")"))
        got = restored[1].get("step")
        if dec.target_step is not None and got != dec.target_step:
            # rewind's fallback chain restored an OLDER step because
            # the agreed target was unloadable HERE (its vote was some
            # other rank's good step) — peers are at the target, this
            # rank is not, and resuming would be the exact divergence
            # the round exists to prevent; fail loudly instead
            policy.escalate(
                f"coordinated rewind diverged: cluster agreed on step "
                f"{dec.target_step} but this rank restored {got} "
                f"(generation {dec.generation}->{dec.new_generation})")
            return dec, None           # only raise-mode off-main-thread
        return dec, restored

    def _wait_for_bump(self, gen: int) -> int:
        """Follower half of the fence bump: poll until the committed
        generation moves past ``gen`` (deadline-bounded — a leader
        that died mid-bump must not hang the followers; on timeout the
        follower bumps itself, the CAS `expect=` making the race
        harmless)."""
        deadline = time.monotonic() + self.barrier_timeout_s
        attempt = 0
        while True:
            cur = self.membership.refresh()
            if cur > gen:
                return cur
            if time.monotonic() > deadline:
                try:
                    return self.membership.bump(
                        "coordinated_leader_timeout", expect=gen)
                except StaleGenerationError:
                    return self.membership.refresh()
            backoff_sleep(attempt, cap_s=0.2)
            attempt += 1


# --- collective-deadline watchdog ---------------------------------------------

class CollectiveDeadline:
    """Name the wedged collective, not just the wedged step.

    Polls ``tracer.in_flight_collective_age()`` on a daemon thread: a
    ``kind="collective"`` span still open after ``deadline_s`` is a
    *hung* collective (a peer died inside it, a deadlock, a stuck DMA)
    — as opposed to a slow one, which completes, closes its span, and
    resets the age on the next call. On fire it emits one
    ``cluster_coord`` ``action="collective_hang"`` event and feeds
    ``escalation.trip("collective:<span name>")`` — the same
    checkpoint-save → crash-dump → exit ladder the step watchdog uses,
    but with the offending collective named in the reason (and hence
    in the crash header and the elastic relaunch logs).

    Fires at most once per span instance (a new collective span re-arms
    it). ``escalation=None`` degrades to observation: events + the
    ``fired`` counter only.
    """

    def __init__(self, tracer, *, deadline_s: float = 120.0,
                 escalation=None,
                 event_sink: Optional[Callable[[Dict], None]] = None,
                 on_hang: Optional[Callable[[Dict], None]] = None,
                 poll_s: Optional[float] = None,
                 generation: Optional[Callable[[], int]] = None):
        self.tracer = tracer
        self.deadline_s = float(deadline_s)
        self.escalation = escalation
        self.event_sink = event_sink
        self.on_hang = on_hang
        self.poll_s = (poll_s if poll_s is not None
                       else max(self.deadline_s / 10.0, 0.05))
        #: callable returning the current fence token for the event
        #: (wire ``generation=member.refresh`` or leave None)
        self.generation = generation
        self.fired = 0
        self._fired_key: Optional[Tuple[str, float]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> Optional[Dict]:
        """One check (also the test entry point): returns the hang
        event when the in-flight collective exceeded the deadline,
        else None."""
        probe = self.tracer.in_flight_collective_age()
        if probe is None:
            self._fired_key = None
            return None
        name, age_s = probe[0], probe[1]
        if age_s < self.deadline_s:
            return None
        # fire once per span INSTANCE: the span's identity is its name
        # plus its (fixed) start instant — the tracer reports the
        # stable start timestamp (a re-derived now−age would drift
        # across polls and could double-fire the escalation)
        start = (probe[2] if len(probe) > 2
                 else round(time.monotonic() - age_s, 1))
        key = (name, start)
        if self._fired_key == key:
            return None
        self._fired_key = key
        self.fired += 1
        event = {"kind": "cluster_coord", "action": "collective_hang",
                 "generation": (int(self.generation())
                                if self.generation is not None else 0),
                 "collective": name, "age_s": round(age_s, 3),
                 "deadline_s": self.deadline_s,
                 "wall_time": time.time()}
        try:
            import jax
            event["rank"] = jax.process_index()
        except Exception:
            event["rank"] = int(os.environ.get("RANK", "0"))
        if self.event_sink is not None:
            try:
                self.event_sink(dict(event))
            except Exception:
                pass
        if self.on_hang is not None:
            try:
                self.on_hang(dict(event))
            except Exception:
                pass
        if self.escalation is not None:
            # same thread-safety contract as HangWatchdog.on_stall: an
            # exit-mode policy never returns; a raise-mode policy on
            # this daemon thread completes the save/dump and records
            # `tripped` (its documented polling contract)
            self.escalation.trip(f"collective:{name}")
        return event

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "CollectiveDeadline":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="apex_tpu.cluster.collective",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(self.poll_s * 2, 1.0))
        self._thread = None

    def __enter__(self) -> "CollectiveDeadline":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:
                pass          # a broken poll must not kill the daemon
