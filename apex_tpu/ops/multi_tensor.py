"""Arena-wide fused elementwise kernels: the `amp_C` trio.

TPU-native rebuild of the reference's multi-tensor-apply family
(`csrc/multi_tensor_scale_kernel.cu`, `multi_tensor_axpby_kernel.cu`,
`multi_tensor_l2norm_kernel.cu`, launcher `csrc/multi_tensor_apply.cuh`):
instead of packing ≤110 tensor pointers into kernel-arg structs per launch,
the tensors already live in one flat arena buffer (apex_tpu.arena) and a
single Pallas kernel walks it in (512, 128) VMEM blocks via the shared
launcher (apex_tpu.ops._dispatch.launch).

Every op keeps the reference's overflow-flag contract: `scale`/`axpby` also
produce a scalar "all finite" flag computed in the same pass (the CUDA
kernels write a `noop_flag` on inf/nan, `multi_tensor_scale_kernel.cu:30-70`),
except the flag stays on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._dispatch import launch


# --- multi_tensor_scale ------------------------------------------------------

def _scale_kernel(scalars, x_ref, out_ref, flag_ref):
    i = pl.program_id(0)
    scale = scalars[0]
    y = x_ref[:].astype(jnp.float32) * scale

    @pl.when(i == 0)
    def _():
        flag_ref[0, 0] = 0.0

    # inf/nan in the *output* sets the noop flag (reference checks the
    # converted value, multi_tensor_scale_kernel.cu:57-63)
    bad = jnp.logical_not(jnp.all(jnp.isfinite(y)))
    flag_ref[0, 0] = flag_ref[0, 0] + jnp.where(bad, 1.0, 0.0)
    out_ref[:] = y.astype(out_ref.dtype)


def multi_tensor_scale(buf, scale, *, out_dtype=None):
    """``out = buf * scale`` over a flat arena buffer, with overflow flag.

    Returns ``(out, all_finite)``. Used for grad unscale (model→master copy
    with 1/loss_scale) and master→model copy-back, exactly the two places
    the reference launches `amp_C.multi_tensor_scale`
    (`apex/amp/scaler.py:94-125`, `_process_optimizer.py:354-364`).
    """
    out_dtype = jnp.dtype(out_dtype) if out_dtype else buf.dtype
    out, flag = launch(
        _scale_kernel, [buf],
        outs=[("block", out_dtype), ("scalar", jnp.float32)],
        scalars=[scale])
    return out, flag[0, 0] == 0.0


# --- multi_tensor_axpby ------------------------------------------------------

def _axpby_kernel(scalars, x_ref, y_ref, out_ref, flag_ref):
    i = pl.program_id(0)
    a, b = scalars[0], scalars[1]
    r = (a * x_ref[:].astype(jnp.float32)
         + b * y_ref[:].astype(jnp.float32))

    @pl.when(i == 0)
    def _():
        flag_ref[0, 0] = 0.0

    bad = jnp.logical_not(jnp.all(jnp.isfinite(r)))
    flag_ref[0, 0] = flag_ref[0, 0] + jnp.where(bad, 1.0, 0.0)
    out_ref[:] = r.astype(out_ref.dtype)


def multi_tensor_axpby(a, x, b, y, *, out_dtype=None):
    """``out = a*x + b*y`` with overflow flag — stashed-gradient
    accumulation (`apex/amp/scaler.py:152-190`,
    `csrc/multi_tensor_axpby_kernel.cu:28-90`). Returns (out, all_finite)."""
    out_dtype = jnp.dtype(out_dtype) if out_dtype else x.dtype
    out, flag = launch(
        _axpby_kernel, [x, y],
        outs=[("block", out_dtype), ("scalar", jnp.float32)],
        scalars=[a, b])
    return out, flag[0, 0] == 0.0


# --- multi_tensor_l2norm -----------------------------------------------------

def _l2norm_kernel(x_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[0, 0] = 0.0

    x = x_ref[:].astype(jnp.float32)
    acc_ref[0, 0] = acc_ref[0, 0] + jnp.sum(x * x)


def multi_tensor_l2norm(buf):
    """Global L2 norm of a flat arena buffer (fp32 accumulate).

    One-kernel version of the two-stage partial+cleanup reduction
    (`csrc/multi_tensor_l2norm_kernel.cu:28-113`): TPU grids run
    sequentially on-core, so the partial sums accumulate in a revisited
    (1,1) SMEM scalar. Arena padding is zero, so no masking is needed.
    """
    acc = launch(_l2norm_kernel, [buf], outs=[("scalar", jnp.float32)])
    return jnp.sqrt(acc[0, 0])


def _maxnorm_kernel(x_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[0, 0] = 0.0

    acc_ref[0, 0] = jnp.maximum(
        acc_ref[0, 0], jnp.max(jnp.abs(x_ref[:].astype(jnp.float32))))


def multi_tensor_maxnorm(buf):
    """Global max-abs (Linf) — `MaxNormFunctor`
    (`multi_tensor_l2norm_kernel.cu:113-160`)."""
    acc = launch(_maxnorm_kernel, [buf], outs=[("scalar", jnp.float32)])
    return acc[0, 0]


def per_tensor_l2norm(buf, segment_ids, num_tensors):
    """Per-tensor L2 norms over the arena in one pass (`multi_tensor_l2norm`
    with ``per_tensor=True``). ``segment_ids`` maps arena position → tensor
    index (-1 padding); returns (num_tensors,) f32 norms.

    NOTE: ``segment_sum`` lowers to scatter-add, which TPU serializes
    (hundreds of ms on large arenas). When the segment layout is static —
    it always is for the arena, whose offsets are Python ints — use
    :func:`per_tensor_l2norm_ranges` instead; this traced-ids version
    remains for callers whose boundaries are genuinely dynamic (e.g.
    ZeRO shard-local spans that depend on ``axis_index``)."""
    sq = jnp.square(buf.astype(jnp.float32))
    sums = jax.ops.segment_sum(sq, jnp.maximum(segment_ids, 0),
                               num_segments=num_tensors)
    # padding contributes zeros (buf padding is 0), so no correction needed
    return jnp.sqrt(sums)


def per_tensor_l2norm_ranges(buf, offsets, sizes):
    """Per-tensor L2 norms from STATIC arena ranges — no scatter.

    ``offsets``/``sizes`` are the arena partition's Python-int tuples, so
    each tensor becomes one contiguous slice-reduce; XLA fuses the lot
    into a single pass over the buffer. This is the TPU-idiomatic form
    of ``multi_tensor_l2norm(per_tensor=True)``
    (`multi_tensor_l2norm_kernel.cu:28-113`)."""
    b32 = buf.astype(jnp.float32)
    sums = [jnp.sum(jnp.square(jax.lax.slice_in_dim(b32, off, off + sz)))
            for off, sz in zip(offsets, sizes)]
    return jnp.sqrt(jnp.stack(sums))


def per_tensor_maxnorm_ranges(buf, offsets, sizes):
    """Per-tensor max-abs (Linf) norms from static arena ranges — the
    per-tensor ``MaxNormFunctor`` without scatter."""
    b32 = jnp.abs(buf.astype(jnp.float32))
    maxs = [jnp.max(jax.lax.slice_in_dim(b32, off, off + sz))
            for off, sz in zip(offsets, sizes)]
    return jnp.stack(maxs)


def per_tensor_sq_shard(buf, offsets, sizes, shard_start,
                        block: int = 64 * 1024):
    """Per-tensor sums of squares over ONE shard of the arena — the
    sharded-norm building block of DistributedFusedLAMB
    (`distributed_fused_lamb.py:453-472`), scatter-free.

    ``buf`` is this device's contiguous shard; ``shard_start`` its
    (traced) global offset; tensor ``offsets``/``sizes`` are the static
    arena layout. Each tensor's shard-local overlap decomposes into
    whole blocks (summed from one per-block partial-sums vector) plus at
    most two masked boundary blocks, read via ``dynamic_slice`` — no
    scatter, no gather over the buffer, and no cumsum-difference
    cancellation (every element is added exactly once in fp32).
    Returns (num_tensors,) partial sq-sums; ``psum`` them across shards
    for the exact global per-tensor norms.
    """
    s = buf.shape[0]
    nb = -(-s // block)
    sq = jnp.square(buf.astype(jnp.float32))
    sqp = jnp.pad(sq, (0, nb * block - s))
    bsums = jnp.sum(sqp.reshape(nb, block), axis=1)
    ib = jax.lax.iota(jnp.int32, nb)
    lane = jax.lax.iota(jnp.int32, block)
    start = jnp.asarray(shard_start, jnp.int32)

    def one(off, sz):
        lo = jnp.clip(off - start, 0, s)
        hi = jnp.clip(off + sz - start, 0, s)
        bl = (lo + block - 1) // block      # first whole block
        bh = hi // block                    # one past last whole block
        interior = jnp.sum(jnp.where((ib >= bl) & (ib < bh), bsums, 0.0))
        # left partial: [lo, left_end) inside block lo//block
        left_end = jnp.minimum(bl * block, hi)
        lblk = jax.lax.dynamic_slice_in_dim(sqp, (lo // block) * block,
                                            block)
        lpos = (lo // block) * block + lane
        left = jnp.sum(jnp.where((lpos >= lo) & (lpos < left_end),
                                 lblk, 0.0))
        # right partial: [max(bh*block, left_end), hi)
        rstart = jnp.maximum(bh * block, left_end)
        rblk = jax.lax.dynamic_slice_in_dim(sqp, bh * block, block)
        rpos = bh * block + lane
        right = jnp.sum(jnp.where((rpos >= rstart) & (rpos < hi),
                                  rblk, 0.0))
        return interior + left + right

    return jnp.stack([one(off, sz) for off, sz in zip(offsets, sizes)])


def spread_per_tensor_shard(values, offsets, sizes, shard_start, per,
                            fill=0.0):
    """Shard-local inverse of :func:`per_tensor_sq_shard`: broadcast a
    (num_tensors,) vector over this shard's slice of the arena layout —
    ``values[segment_ids]`` without the serialized per-element gather.

    Each (static-size) tensor writes its shard overlap with one
    ``dynamic_update_slice`` of a windowed read-modify-write: the window
    of length ``min(size, per)`` always covers the overlap, and the
    ``where`` keeps existing content at window positions outside the
    tensor's span, so clamping at shard edges cannot clobber neighbours.
    One pass of reads+writes over the shard in total.
    """
    start = jnp.asarray(shard_start, jnp.int32)
    out = jnp.full((per,), fill, values.dtype)
    for j, (off, sz) in enumerate(zip(offsets, sizes)):
        ln = min(sz, per)
        cl = jnp.clip(off - start, 0, per - ln)
        cur = jax.lax.dynamic_slice_in_dim(out, cl, ln)
        gpos = cl + start + jax.lax.iota(jnp.int32, ln)
        valid = (gpos >= off) & (gpos < off + sz)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, jnp.where(valid, values[j], cur), cl, axis=0)
    return out


def spread_per_tensor(values, offsets, padded, total, fill=0.0):
    """Broadcast a (num_tensors,) vector back over the arena layout —
    the inverse gather ``values[segment_ids]`` without the 100M-index
    gather: static concatenation of broadcasts (``fill`` in alignment
    gaps and tail padding)."""
    pieces = []
    pos = 0
    for j, (off, sz_pad) in enumerate(zip(offsets, padded)):
        if off > pos:
            pieces.append(jnp.full((off - pos,), fill, values.dtype))
        pieces.append(jnp.broadcast_to(values[j], (sz_pad,)))
        pos = off + sz_pad
    if pos < total:
        pieces.append(jnp.full((total - pos,), fill, values.dtype))
    return jnp.concatenate(pieces)
