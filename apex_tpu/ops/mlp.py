"""Fused MLP — the whole Linear(+bias)(+activation) stack in one kernel.

TPU-native rebuild of `mlp_cuda` (`csrc/mlp.cpp:1-164`,
`csrc/mlp_cuda.cu:55-780`): the reference loops cuBLAS GEMMs with fused
bias/ReLU/sigmoid epilogue kernels and one shared workspace. Here a single
Pallas kernel walks row blocks of the batch with *every layer's weights
resident in VMEM*, so inter-layer activations never touch HBM — the TPU
version of the reference's workspace reuse, and strictly more fused than
its per-layer GEMM launches.

When the weights don't fit the VMEM budget the op falls back to a jnp
chain, which XLA still fuses (bias+activation ride the MXU epilogue) —
matching the reference's "no extension" fallback semantics with no
capability loss.

Backward is the XLA autodiff of the reference chain: plain GEMMs are
exactly what the MXU + XLA already schedule optimally, so a hand-written
Pallas backward would only re-derive `mlp_cuda.backward`'s dgrad/wgrad
GEMM loop (`mlp_cuda.cu:440-780`).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import use_interpret

LANES = 128
_VMEM_WEIGHT_BUDGET = 8 << 20  # bytes of fp32 weights resident per step

_ACTS = {
    "none": lambda x: x,
    "relu": lambda x: jnp.maximum(x, 0.0),
    "sigmoid": jax.nn.sigmoid,
}


def _pad_to(x, rows, cols):
    r, c = x.shape
    if r == rows and c == cols:
        return x
    return jnp.pad(x, ((0, rows - r), (0, cols - c)))


def _mlp_kernel(num_layers, activation, use_bias, x_ref, *refs):
    w_refs = refs[:num_layers]
    b_refs = refs[num_layers:2 * num_layers] if use_bias else ()
    y_ref = refs[-1]
    act = _ACTS[activation]
    h = x_ref[:].astype(jnp.float32)
    for i in range(num_layers):
        h = jnp.dot(h, w_refs[i][:].astype(jnp.float32),
                    preferred_element_type=jnp.float32)
        if use_bias:
            h = h + b_refs[i][:].astype(jnp.float32)
        if i < num_layers - 1 or activation != "none":
            h = act(h)
    y_ref[:] = h.astype(y_ref.dtype)


def mlp_reference(x, weights, biases=None, activation="relu"):
    """jnp chain oracle — `nn.Sequential(Linear...)` in the reference tests
    (`tests/L0/run_mlp/test_mlp.py`). Activation applies after every layer
    including the last, matching `mlp_cuda.forward` (`csrc/mlp.cpp:30-60`).
    """
    act = _ACTS[activation]
    h = x
    for i, w in enumerate(weights):
        h = jnp.dot(h, w, preferred_element_type=jnp.float32).astype(x.dtype)
        if biases is not None:
            h = h + biases[i].astype(h.dtype)
        h = act(h) if activation != "none" else h
    return h


def _weights_fit_vmem(weights) -> bool:
    total = sum(int(np.prod(w.shape)) * 4 for w in weights)
    return total <= _VMEM_WEIGHT_BUDGET


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_mlp(x, weights, biases, activation="relu"):
    """Whole-MLP forward: ``x @ W0 (+b0) act @ W1 (+b1) act ...``.

    ``weights``: tuple of (Din, Dout) matrices; ``biases``: matching tuple
    or None. The public mirror of ``mlp_cuda.forward`` via ``MLP``
    (`apex/mlp/mlp.py:8-58`).
    """
    return _fused_mlp_fwd_impl(x, weights, biases, activation)


def _fused_mlp_fwd_impl(x, weights, biases, activation, block_rows=None):
    use_bias = biases is not None
    if not _weights_fit_vmem(weights):
        return mlp_reference(x, weights, biases, activation)

    lead = x.shape[:-1]
    d0 = x.shape[-1]
    x2 = x.reshape(-1, d0)
    n = x2.shape[0]
    dims = [d0] + [w.shape[1] for w in weights]
    if block_rows is None:
        from apex_tpu.ops import autotune
        block_rows = autotune.tuned_rows("mlp", (n, *dims), x.dtype)
    pdims = [-(-d // LANES) * LANES for d in dims]
    widest = max(pdims)
    r = (block_rows if block_rows is not None
         else max(16, min(256, ((1 << 20) // (4 * widest) // 16) * 16)))
    npad = -(-n // r) * r

    args = [_pad_to(x2, npad, pdims[0])]
    in_specs = [pl.BlockSpec((r, pdims[0]), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)]
    for li, w in enumerate(weights):
        args.append(_pad_to(w, pdims[li], pdims[li + 1]))
        in_specs.append(pl.BlockSpec(
            (pdims[li], pdims[li + 1]), lambda i: (0, 0),
            memory_space=pltpu.VMEM))
    if use_bias:
        for li, b in enumerate(biases):
            args.append(_pad_to(b.reshape(1, -1), 1, pdims[li + 1]))
            in_specs.append(pl.BlockSpec((1, pdims[li + 1]),
                                         lambda i: (0, 0),
                                         memory_space=pltpu.VMEM))

    y = pl.pallas_call(
        functools.partial(_mlp_kernel, len(weights), activation, use_bias),
        grid=(npad // r,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((r, pdims[-1]), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((npad, pdims[-1]), x.dtype),
        interpret=use_interpret(),
    )(*args)
    return y[:n, :dims[-1]].reshape(*lead, dims[-1])


def _mlp_fwd(x, weights, biases, activation):
    return _fused_mlp_fwd_impl(x, weights, biases, activation), \
        (x, weights, biases)


def _mlp_bwd(activation, res, g):
    x, weights, biases = res
    if biases is None:
        def f(x_, w_):
            return mlp_reference(x_, w_, None, activation)
        _, vjp = jax.vjp(f, x, weights)
        dx, dw = vjp(g)
        return dx, dw, None
    def f(x_, w_, b_):
        return mlp_reference(x_, w_, b_, activation)
    _, vjp = jax.vjp(f, x, weights, biases)
    return vjp(g)


fused_mlp.defvjp(_mlp_fwd, _mlp_bwd)


class MLP:
    """flax module mirror of ``apex.mlp.MLP`` (`apex/mlp/mlp.py:8-79`):
    ``MLP([in, h1, h2, ...], bias=True, activation='relu')`` with params
    named ``weight_i`` / ``bias_i`` like the reference."""

    def __new__(cls, mlp_sizes: Sequence[int], bias: bool = True,
                activation: str = "relu"):
        import flax.linen as nn

        sizes = list(mlp_sizes)
        if len(sizes) < 2:
            raise ValueError("need at least [in, out] sizes")
        if activation not in _ACTS:
            raise ValueError(f"unknown activation {activation!r}")

        class _MLP(nn.Module):
            @nn.compact
            def __call__(self, x):
                weights, biases = [], ([] if bias else None)
                for i in range(len(sizes) - 1):
                    # reference init (`apex/mlp/mlp.py:63-72`): weights
                    # N(0, sqrt(2/(fan_in+fan_out))), biases
                    # N(0, sqrt(1/fan_out))
                    w_std = np.sqrt(2.0 / (sizes[i] + sizes[i + 1]))
                    w = self.param(
                        f"weight_{i}",
                        nn.initializers.normal(stddev=w_std),
                        (sizes[i], sizes[i + 1]), jnp.float32)
                    weights.append(w)
                    if bias:
                        b_std = np.sqrt(1.0 / sizes[i + 1])
                        b = self.param(
                            f"bias_{i}",
                            nn.initializers.normal(stddev=b_std),
                            (sizes[i + 1],), jnp.float32)
                        biases.append(b)
                return fused_mlp(x, tuple(weights),
                                 tuple(biases) if bias else None,
                                 activation)

        return _MLP()
