"""FP16_Optimizer — explicit master-weights wrapper.

Rebuild of `apex/fp16_utils/fp16_optimizer.py:13-554`: the pre-Amp API
where master weights, loss scaling, overflow skipping, and gradient
clipping are explicit user-visible operations rather than a bundled
policy. The reference mutates the wrapped ``torch.optim`` optimizer in
place; here the same lifecycle is a functional state machine:

    opt = FP16_Optimizer(FusedSGD(lr=0.1), dynamic_loss_scale=True)
    state = opt.init(model_params)                 # fp32 masters + scaler
    mp = opt.model_params(state, like=model_params)  # half view for fwd

    loss, grads, finite, state = opt.backward(state, loss_fn)
    grads, norm = opt.clip_master_grads(grads, 5.0)  # optional
    state = opt.step(state, grads, finite)

``backward`` mirrors ``FP16_Optimizer.backward(loss)`` +
``update_master_grads`` (`fp16_optimizer.py:373-491`): scale → grad →
master-fp32 cast → unscale → overflow check → scale-schedule update.
``step`` mirrors `fp16_optimizer.py:272-332`: skipped entirely when the
last backward overflowed (params, optimizer state and step counter all
hold — the bitwise-skip contract).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import (LossScaleConfig, LossScaleState,
                                 loss_scale_init, loss_scale_update,
                                 scale_loss, unscale_grads)
from apex_tpu.monitor.metrics import Metrics, metrics_init
from apex_tpu.trace.debug_nans import nan_probe
from apex_tpu.trace.spans import span as trace_span
from apex_tpu.utils import global_norm, tree_cast, tree_select


class FP16OptState(NamedTuple):
    """Masters + inner optimizer state + scaler — a checkpointable pytree.

    The reference's ``state_dict`` saves exactly this set
    (`fp16_optimizer.py:209-270`): scaler state, overflow flag, inner
    optimizer state, and the fp32 master groups. ``metrics`` is the
    opt-in telemetry pytree (``FP16_Optimizer(..., monitor=True)``;
    ``None`` adds no leaves, so existing checkpoints round-trip).
    """
    step: jax.Array
    masters: Any                       # fp32 master params
    inner_state: Any                   # wrapped optimizer state
    scaler: Optional[LossScaleState]
    metrics: Optional[Metrics] = None


class FP16_Optimizer:
    def __init__(self, init_optimizer, *, static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False,
                 dynamic_loss_args: Optional[dict] = None,
                 half_dtype=jnp.float16, verbose: bool = False,
                 monitor: bool = False):
        self.tx = init_optimizer
        self.half_dtype = jnp.dtype(half_dtype)
        if dynamic_loss_scale:
            args = dynamic_loss_args or {}
            # legacy dynamic defaults: init 2**32, window 1000
            self.cfg = LossScaleConfig(
                init_scale=args.get("init_scale", 2.0 ** 32),
                growth_interval=args.get("scale_window", 1000),
                backoff_factor=1.0 / args.get("scale_factor", 2.0),
                growth_factor=args.get("scale_factor", 2.0),
                max_loss_scale=args.get("init_scale", 2.0 ** 32),
                dynamic=True)
        else:
            self.cfg = LossScaleConfig(init_scale=static_loss_scale,
                                       dynamic=False)
        self.verbose = verbose
        self.monitor = monitor

    # -- lifecycle -----------------------------------------------------------

    def init(self, model_params) -> FP16OptState:
        """fp32 masters from (possibly half) model params —
        ``prep_param_lists`` at construction (`fp16_optimizer.py:43-95`)."""
        masters = tree_cast(model_params, jnp.float32)
        return FP16OptState(
            step=jnp.int32(0),
            masters=masters,
            inner_state=self.tx.init(masters),
            scaler=loss_scale_init(self.cfg),
            metrics=metrics_init() if self.monitor else None)

    def model_params(self, state: FP16OptState, like=None):
        """Half-precision view of the masters for the forward pass —
        ``_master_params_to_model_params`` (`fp16_optimizer.py:160-172`).
        ``like`` (a params tree) overrides the target dtype per leaf."""
        if like is not None:
            return jax.tree_util.tree_map(
                lambda m, p: m.astype(p.dtype), state.masters, like)
        return tree_cast(state.masters, self.half_dtype)

    # -- backward ------------------------------------------------------------

    def backward(self, state: FP16OptState, loss_fn: Callable, *args,
                 has_aux: bool = False, **kwargs):
        """Scaled backward + master-grad production.

        ``loss_fn(model_params, ...)`` runs at the half view of the
        masters (so grads arrive w.r.t. masters in fp32 — the reference's
        ``model_grads_to_master_grads`` copy falls out of autodiff).
        Returns ``(out, master_grads, finite, state')`` with the scale
        schedule already advanced (`backward` + ``update_master_grads``,
        `fp16_optimizer.py:373-491`).
        """
        sstate = state.scaler

        # same forensic spans/probes as amp (see docs/tracing.md):
        # no-ops unless xplane-traced / trace.debug_nans is enabled
        def scaled(masters):
            mp = tree_cast(masters, self.half_dtype)
            with trace_span("fp16/fwd"):
                out = loss_fn(mp, *args, **kwargs)
            loss = nan_probe("fp16/fwd", out[0] if has_aux else out)
            return scale_loss(loss, sstate), out

        grads, out = jax.grad(scaled, has_aux=True)(state.masters)
        grads = nan_probe("fp16/bwd", grads)
        with trace_span("fp16/unscale"):
            grads, finite = unscale_grads(grads, sstate)
        grads = nan_probe("fp16/unscale", grads)
        if state.metrics is not None:
            new_scaler, metrics = loss_scale_update(sstate, finite, self.cfg,
                                                    metrics=state.metrics)
            loss_val = out[0] if has_aux else out
            metrics = metrics.record_loss(loss_val)
        else:
            new_scaler = loss_scale_update(sstate, finite, self.cfg)
            metrics = None
        return out, grads, finite, state._replace(scaler=new_scaler,
                                                  metrics=metrics)

    # -- utilities -----------------------------------------------------------

    def clip_master_grads(self, grads, max_norm, norm_type=2):
        """Clip master grads by global norm, returning (grads, norm) —
        ``clip_master_grads`` (`fp16_optimizer.py:185-207`)."""
        total = global_norm(grads, ord=norm_type)
        scale = jnp.minimum(1.0, max_norm / (total + 1e-6))
        return jax.tree_util.tree_map(lambda g: g * scale, grads), total

    def loss_scale(self, state: FP16OptState) -> jax.Array:
        return state.scaler.loss_scale

    # -- step ----------------------------------------------------------------

    def step(self, state: FP16OptState, master_grads, finite) -> FP16OptState:
        """Inner-optimizer step on the masters, skipped on overflow
        (`fp16_optimizer.py:272-332`: "OVERFLOW! Skipping step")."""
        with trace_span("fp16/update"):
            if hasattr(self.tx, "step") and callable(self.tx.step):
                new_masters, new_inner = self.tx.step(
                    master_grads, state.inner_state, state.masters)
            else:                                 # optax transform
                updates, new_inner = self.tx.update(
                    master_grads, state.inner_state, state.masters)
                new_masters = jax.tree_util.tree_map(
                    lambda p, u: p + u.astype(p.dtype), state.masters,
                    updates)
        masters = nan_probe("fp16/update", tree_select(
            finite, new_masters, state.masters))
        inner = tree_select(finite, new_inner, state.inner_state)
        if isinstance(finite, bool):
            new_step = state.step + (1 if finite else 0)
        else:
            new_step = state.step + jnp.where(finite, 1, 0).astype(jnp.int32)
        metrics = state.metrics
        if metrics is not None:
            # telemetry counters advance even on the skipped branch —
            # kept outside the tree_select commit above; the grad-norm
            # gauge holds its last finite value across overflows
            fin = jnp.asarray(finite, jnp.bool_)
            metrics = metrics.count_step(finite).record_norms(
                grad_norm=jnp.where(fin, global_norm(master_grads),
                                    metrics.grad_norm),
                param_norm=global_norm(masters))
        return state._replace(step=new_step, masters=masters,
                              inner_state=inner, metrics=metrics)

    # -- checkpoint parity ---------------------------------------------------

    def state_dict(self, state: FP16OptState) -> dict:
        """Everything `fp16_optimizer.py:209-230` saves."""
        return {
            "loss_scaler": None if state.scaler is None else {
                "loss_scale": state.scaler.loss_scale,
                "unskipped": state.scaler.growth_tracker},
            "first_closure_call_this_step": True,   # legacy field, constant
            "optimizer_state_dict": state.inner_state,
            "fp32_from_fp16": state.masters,
            "step": state.step,
        }

    def load_state_dict(self, state: FP16OptState, sd: dict) -> FP16OptState:
        """`fp16_optimizer.py:230-270`."""
        scaler = state.scaler
        if sd.get("loss_scaler") is not None and scaler is not None:
            scaler = LossScaleState(
                loss_scale=jnp.float32(sd["loss_scaler"]["loss_scale"]),
                growth_tracker=jnp.int32(sd["loss_scaler"]["unskipped"]))
        return state._replace(
            step=jnp.int32(sd.get("step", state.step)),
            masters=sd["fp32_from_fp16"],
            inner_state=sd["optimizer_state_dict"],
            scaler=scaler)
