"""Compilation observability: trace/lower/compile counters + retrace
detector.

A silent shape-induced retrace can eat minutes per step with no signal
in any existing sink — the step "just got slow". This module watches the
compile pipeline from two directions:

- **process-wide counters** via ``jax.monitoring`` events
  (``/jax/core/compile/*_duration``): every trace, MLIR lowering, and
  backend compile in the process is counted and its wall time summed,
  whether or not the function is wrapped (:func:`install`,
  :func:`global_counters` — ``bench.py`` reports ``n_compiles`` from
  this). Builds without the monitoring API degrade to the wrapper
  fallback below.
- **per-function watch** via :meth:`CompileWatcher.watch`: wraps a
  (jitted) function and, per call, detects a new trace from the jit
  cache size (exact; signature diffing is the fallback for callables
  without a cache), records the compile wall time as a
  ``kind="compile"`` span in the active :class:`apex_tpu.trace.Tracer`,
  diffs the argument shape/dtype signature against the previous trace to
  name **which argument changed**, and — after ``warn_after`` retraces
  of the same function — warns through ``warnings`` and the registered
  monitor callbacks (``MetricsLogger.record_memory`` takes the emitted
  ``kind="retrace"`` events; ``check_metrics_schema.py --kind memory``
  validates them).

The watch wrapper never changes the compiled program — the jitted
callable, its trace cache, and its donation/sharding behavior are the
wrapped function's own (the ``memory/no-extra-dispatch`` compile-check
case pins bit-identical HLO).
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

__all__ = ["CompileWatcher", "FunctionWatch", "install", "installed",
           "global_counters", "reset_global_counters", "watch",
           "autotune_scope", "in_autotune"]

# --- process-wide jax.monitoring counters ------------------------------------

_EVENT_KEYS = {
    "/jax/core/compile/jaxpr_trace_duration": "traces",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lowerings",
    "/jax/core/compile/backend_compile_duration": "compiles",
}

_lock = threading.Lock()
_installed = False
_globals = {"traces": 0, "lowerings": 0, "compiles": 0,
            "trace_secs": 0.0, "lower_secs": 0.0, "compile_secs": 0.0,
            "autotune_compiles": 0, "autotune_secs": 0.0}
_SECS_KEY = {"traces": "trace_secs", "lowerings": "lower_secs",
             "compiles": "compile_secs"}

# innermost-last stack of FunctionWatch records whose dispatch is in
# flight on this thread — monitoring events fired during the dispatch
# are attributed to the top of the stack
_tls = threading.local()


def _stack() -> List["FunctionWatch"]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


# autotune-origin marker: compiles fired while a sweep holds this flag
# are counted separately from (and in addition to) the plain compile
# counters — so a kernel autotuner's grid sweep never reads as a
# retrace storm in n_compiles (ROADMAP item 4's compile-attribution
# note; the bench JSON splits the column)
_autotune_tls = threading.local()


def in_autotune() -> bool:
    """True while an :func:`autotune_scope` is open on this thread."""
    return getattr(_autotune_tls, "depth", 0) > 0


@contextlib.contextmanager
def autotune_scope():
    """Tag every backend compile issued inside this context as
    autotune-origin (re-entrant, per-thread). The kernel autotuner's
    sweep loop wraps each candidate compile with it::

        with compile_watch.autotune_scope():
            timed = jax.jit(candidate).lower(*avals).compile()

    ``global_counters()["autotune_compiles"]`` (a subset of
    ``"compiles"``) and ``FunctionWatch.n_autotune_compiles`` count
    them; ``bench.py`` reports the split as ``n_autotune_compiles``
    next to ``n_compiles``."""
    _autotune_tls.depth = getattr(_autotune_tls, "depth", 0) + 1
    try:
        yield
    finally:
        _autotune_tls.depth -= 1


def _on_duration(name: str, secs: float, **_kw) -> None:
    key = _EVENT_KEYS.get(name)
    if key is None:
        return
    autotune = key == "compiles" and in_autotune()
    with _lock:
        _globals[key] += 1
        _globals[_SECS_KEY[key]] += secs
        if autotune:
            _globals["autotune_compiles"] += 1
            _globals["autotune_secs"] += secs
    st = _stack()
    if st:
        st[-1]._count_event(key, secs, autotune=autotune)


def install() -> bool:
    """Register the process-wide ``jax.monitoring`` listener (idempotent;
    listeners cannot be unregistered, so a module flag guards against
    doubles). Returns False when the build has no monitoring API — the
    cache-size wrapper fallback still works."""
    global _installed
    with _lock:
        if _installed:
            return True
        try:
            jax.monitoring.register_event_duration_secs_listener(
                _on_duration)
        except Exception:
            return False
        _installed = True
        return True


def installed() -> bool:
    return _installed


def global_counters() -> Dict[str, float]:
    """Process-wide compile-pipeline counters since :func:`install` /
    the last reset: {"traces", "lowerings", "compiles", "*_secs"}."""
    with _lock:
        return dict(_globals)


def reset_global_counters() -> None:
    with _lock:
        for k in _globals:
            _globals[k] = 0 if isinstance(_globals[k], int) else 0.0


# --- argument signatures -----------------------------------------------------

def _aval_of(x) -> Tuple:
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return (tuple(x.shape), str(x.dtype))
    # static leaves retrace on VALUE change, so the value is the signature
    return ("static", repr(x)[:80])


def signature(args, kwargs) -> Tuple[Tuple[str, Tuple], ...]:
    """Hashable (path, shape/dtype) signature of a call's arguments —
    the thing a retrace means *changed*."""
    flat = jax.tree_util.tree_flatten_with_path((args, kwargs))[0]
    return tuple((jax.tree_util.keystr(path), _aval_of(leaf))
                 for path, leaf in flat)


def diff_signatures(old, new) -> str:
    """Human-readable description of what changed between two call
    signatures — names the argument(s) that forced the retrace."""
    if old is None:
        return "first call"
    old_d, new_d = dict(old), dict(new)
    changes = []
    for path, aval in new_d.items():
        prev = old_d.get(path)
        if prev is None:
            changes.append(f"{path or '<args>'}: new argument {aval}")
        elif prev != aval:
            changes.append(f"{path or '<args>'}: {prev} -> {aval}")
    for path in old_d:
        if path not in new_d:
            changes.append(f"{path or '<args>'}: removed")
    if not changes and len(old) != len(new):
        changes.append(f"argument count {len(old)} -> {len(new)}")
    return "; ".join(changes[:6]) or "unknown (same avals — static or " \
        "tracing-context change)"


# --- per-function watch ------------------------------------------------------

@dataclasses.dataclass
class FunctionWatch:
    """Counters for one watched function."""

    name: str
    n_calls: int = 0
    n_traces: int = 0            # distinct traces (jit cache growth)
    n_retraces: int = 0          # traces beyond the first
    n_lowerings: int = 0         # attributed jax.monitoring events
    n_compiles: int = 0
    n_autotune_compiles: int = 0  # subset fired under autotune_scope()
    compile_secs: float = 0.0    # attributed backend-compile seconds
    trace_secs: float = 0.0
    last_signature: Optional[Tuple] = None
    last_change: Optional[str] = None
    retraces: List[Dict] = dataclasses.field(default_factory=list)
    warned: bool = False
    # signatures already traced — the no-cache-introspection fallback's
    # dedupe, so alternating between already-compiled shapes is not
    # miscounted as retracing
    _seen: set = dataclasses.field(default_factory=set)

    def _count_event(self, key: str, secs: float,
                     autotune: bool = False) -> None:
        if key == "compiles":
            self.n_compiles += 1
            self.compile_secs += secs
            if autotune:
                self.n_autotune_compiles += 1
        elif key == "lowerings":
            self.n_lowerings += 1
        elif key == "traces":
            self.trace_secs += secs

    def to_events(self, rank: int = 0) -> List[Dict]:
        """``kind="retrace"`` events for the memory/compile channel."""
        return [dict(ev, kind="retrace", rank=rank, fn=self.name)
                for ev in self.retraces]


class CompileWatcher:
    """Watches jitted functions for traces/retraces/compiles.

    ::

        watcher = prof.CompileWatcher(warn_after=3)
        step = watcher.watch(jax.jit(step_fn), name="train_step")
        ...
        print(watcher.report())
        # steady state: watcher["train_step"].n_traces == 1

    ``warn_after``: a warning fires once when one function accumulates
    that many retraces (the classic unstable-shape bug). ``on_event``
    callbacks receive each ``kind="retrace"``/``kind="compile"`` event
    dict — wire ``MetricsLogger.record_memory`` here to stream them.
    """

    def __init__(self, *, warn_after: int = 3,
                 on_event: Optional[Callable[[Dict], None]] = None):
        self.warn_after = max(int(warn_after), 1)
        self._on_event: List[Callable[[Dict], None]] = (
            [on_event] if on_event else [])
        self.watches: Dict[str, FunctionWatch] = {}
        install()                      # best effort; fallback works without

    def subscribe(self, fn: Callable[[Dict], None]) -> None:
        self._on_event.append(fn)

    def __getitem__(self, name: str) -> FunctionWatch:
        return self.watches[name]

    def _emit(self, event: Dict) -> None:
        for fn in list(self._on_event):
            try:
                fn(dict(event))
            except Exception:
                pass               # observers never break the train loop

    # -- the wrapper ---------------------------------------------------------

    def watch(self, fn: Callable, name: Optional[str] = None) -> Callable:
        """Wrap ``fn`` (jitted if not already) so every call updates its
        :class:`FunctionWatch`. The returned wrapper carries it as
        ``.watch`` and the underlying jitted callable as ``.jitted``."""
        jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
        name = name or getattr(fn, "__name__", None) or repr(fn)[:40]
        rec = self.watches.setdefault(name, FunctionWatch(name=name))

        def cache_size() -> Optional[int]:
            try:
                return jitted._cache_size()
            except Exception:
                return None

        @functools.wraps(getattr(fn, "__wrapped__", fn))
        def wrapped(*args, **kwargs):
            sig = signature(args, kwargs)
            before = cache_size()
            st = _stack()
            st.append(rec)
            t0 = time.perf_counter()
            try:
                out = jitted(*args, **kwargs)
            finally:
                dt_ms = (time.perf_counter() - t0) * 1e3
                st.pop()
            after = cache_size()
            rec.n_calls += 1
            if after is not None and before is not None:
                traced = after > before
            else:                      # no cache introspection: fall back
                traced = sig not in rec._seen
            if traced:
                self._on_trace(rec, sig, dt_ms)
            rec._seen.add(sig)
            rec.last_signature = sig
            return out

        wrapped.watch = rec
        wrapped.jitted = jitted
        return wrapped

    def _on_trace(self, rec: FunctionWatch, sig, dt_ms: float) -> None:
        rec.n_traces += 1
        retrace = rec.n_traces > 1
        change = diff_signatures(rec.last_signature, sig)
        rec.last_change = change
        # compile wall time as a kind="compile" span on the host
        # timeline (back-dated: the duration was only known after the
        # dispatch returned). The dispatch that compiles includes the
        # compile, so dt_ms bounds it from above; the attributed
        # backend_compile seconds (rec.compile_secs) are the exact
        # compiler time when jax.monitoring is available.
        from apex_tpu.trace.spans import current_tracer
        tracer = current_tracer()
        if tracer is not None:
            tracer.add_span_event(f"compile/{rec.name}", "compile", dt_ms)
        self._emit({"kind": "compile", "fn": rec.name, "dur_ms": dt_ms,
                    "n_traces": rec.n_traces, "changed": change,
                    "retrace": retrace})
        if not retrace:
            return
        rec.n_retraces += 1
        ev = {"call": rec.n_calls, "dur_ms": round(dt_ms, 3),
              "changed": change}
        rec.retraces.append(ev)
        self._emit(dict(ev, kind="retrace", fn=rec.name,
                        n_traces=rec.n_traces))
        if rec.n_retraces >= self.warn_after and not rec.warned:
            rec.warned = True
            warnings.warn(
                f"apex_tpu.prof.compile_watch: {rec.name!r} retraced "
                f"{rec.n_retraces} times (last change: {change}). Each "
                f"retrace recompiles the program — pin the changing "
                f"argument's shape/dtype or mark it static.",
                RuntimeWarning, stacklevel=3)

    # -- renderings ----------------------------------------------------------

    def counters(self) -> Dict[str, Dict]:
        """Per-function counter dicts (JSON-able) + process totals."""
        out = {name: {
            "n_calls": r.n_calls, "n_traces": r.n_traces,
            "n_retraces": r.n_retraces, "n_compiles": r.n_compiles,
            "n_autotune_compiles": r.n_autotune_compiles,
            "compile_secs": round(r.compile_secs, 4),
            "last_change": r.last_change,
        } for name, r in self.watches.items()}
        out["_process"] = global_counters()
        return out

    def report(self) -> str:
        lines = [f"{'function':<28} {'calls':>6} {'traces':>7} "
                 f"{'retraces':>9} {'compiles':>9} {'compile_s':>10}"]
        for name, r in sorted(self.watches.items()):
            lines.append(
                f"{name[:28]:<28} {r.n_calls:>6} {r.n_traces:>7} "
                f"{r.n_retraces:>9} {r.n_compiles:>9} "
                f"{r.compile_secs:>10.3f}")
            for ev in r.retraces[-3:]:
                lines.append(f"    retrace @call {ev['call']}: "
                             f"{ev['changed'][:90]}")
        g = global_counters()
        lines.append(f"process totals: {g['traces']} traces, "
                     f"{g['lowerings']} lowerings, {g['compiles']} "
                     f"backend compiles ({g['compile_secs']:.2f}s, "
                     f"of which {g['autotune_compiles']} autotune)"
                     + ("" if _installed else
                        " [jax.monitoring unavailable — per-function "
                        "cache counts only]"))
        return "\n".join(lines)


def watch(fn: Callable, name: Optional[str] = None, *,
          warn_after: int = 3) -> Callable:
    """One-off convenience: wrap ``fn`` under a fresh
    :class:`CompileWatcher` (reachable as ``wrapped.watcher``)."""
    w = CompileWatcher(warn_after=warn_after)
    wrapped = w.watch(fn, name)
    wrapped.watcher = w
    return wrapped
