"""apex_tpu.guard: in-graph detection, the policy ladder, chaos.

The heavy end-to-end story (rewind bitwise vs a fault-free oracle on
the real ImageFolder pipeline) lives in ``scripts/chaos_audit.py``
(run by ``run_tier1.sh --smoke``); these tests pin the units: detector
semantics with negative twins, ladder decisions/budgets/hysteresis,
chaos-plan determinism, the guard event schema, and the
``guard/no-extra-dispatch`` compile-check case. The multi-fault soak
(SIGKILL + stalled-collective + random plan) is ``slow``.
"""

import json
import os
import signal
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import ckpt, guard, monitor

CFG = guard.GuardConfig(window=8, min_history=4, z_threshold=6.0,
                        grad_factor=10.0, lr_growth_interval=3)


def _observe_loop(losses, *, cfg=CFG, gs=None, gnorm=1.0, params=None):
    """Eagerly observe a loss stream; returns the final GuardState."""
    gs = guard.guard_init(cfg) if gs is None else gs
    for L in losses:
        gs = guard.guard_observe(
            gs, cfg, loss=jnp.float32(L),
            grad_norm=jnp.float32(gnorm),
            params=params)
    return gs


class StubSource:
    """Duck-typed cursor-bearing source for policy tests (the real
    pipeline is exercised by scripts/chaos_audit.py)."""

    def __init__(self, per_epoch=10):
        self.per = per_epoch
        self.e = self.b = 0

    def __len__(self):
        return self.per

    def state(self):
        return {"epoch": self.e, "batch": self.b}

    def load_state(self, c):
        self.e, self.b = int(c["epoch"]), int(c["batch"])

    def cursor_index(self):
        return self.e * self.per + self.b

    def skip_batches(self, n):
        for _ in range(int(n)):
            self.b += 1
            if self.b >= self.per:
                self.e += 1
                self.b = 0


class TestDetect:
    def test_clean_stream_stays_clean(self):
        gs = _observe_loop([1.0 - 0.01 * i + 0.005 * (i % 3)
                            for i in range(20)])
        assert int(gs.anomaly) == 0
        assert int(gs.skip_count) == 0
        assert int(gs.count) == 20
        assert float(gs.lr_scale) == 1.0

    def test_spike_detected_window_not_polluted(self):
        gs = _observe_loop([1.0, 0.99, 0.98, 0.97, 0.96])
        count_before = int(gs.count)
        gs = _observe_loop([50.0], gs=gs)
        assert int(gs.anomaly) & guard.A_LOSS_SPIKE
        assert int(gs.skip_count) == 1
        assert float(gs.z) > 6.0
        # the anomalous loss never entered the window
        assert int(gs.count) == count_before
        assert not np.any(np.asarray(gs.loss_window) == 50.0)

    def test_below_threshold_is_not_a_spike(self):
        # negative twin: a modest wobble within the z threshold
        gs = _observe_loop([1.0, 0.99, 0.98, 0.97, 0.96, 1.05])
        assert int(gs.anomaly) == 0
        assert int(gs.skip_count) == 0

    def test_unarmed_guard_never_fires(self):
        # min_history=4: the same 50x jump at step 2 is not a spike
        gs = _observe_loop([1.0, 50.0], cfg=CFG)
        assert not (int(gs.anomaly) & guard.A_LOSS_SPIKE)

    def test_grad_explosion_and_negative_twin(self):
        gs = _observe_loop([1.0] * 6, gnorm=1.0)
        g2 = guard.guard_observe(gs, CFG, loss=jnp.float32(1.0),
                                 grad_norm=jnp.float32(100.0))
        assert int(g2.anomaly) & guard.A_GRAD_EXPLOSION
        assert int(g2.skip_count) == int(gs.skip_count) + 1
        g3 = guard.guard_observe(gs, CFG, loss=jnp.float32(1.0),
                                 grad_norm=jnp.float32(5.0))
        assert int(g3.anomaly) == 0     # 5x < grad_factor=10

    def test_nonfinite_grad_loss_and_commit_veto(self):
        gs = _observe_loop([1.0] * 5)
        old = {"w": jnp.ones((3,))}
        new = {"w": jnp.zeros((3,))}
        gs = guard.guard_observe(gs, CFG, loss=jnp.float32(np.nan),
                                 grads={"w": jnp.full((3,), np.nan)})
        assert int(gs.anomaly) & guard.A_NONFINITE_LOSS
        assert int(gs.anomaly) & guard.A_NONFINITE_GRAD
        kept = guard.guard_commit(gs, new, old, CFG)
        assert np.array_equal(np.asarray(kept["w"]), np.ones((3,)))

    def test_nonfinite_param_flags_but_does_not_veto(self):
        gs = _observe_loop([1.0] * 5)
        bad = {"w": jnp.array([1.0, np.nan])}
        gs = guard.guard_observe(gs, CFG, loss=jnp.float32(1.0),
                                 grad_norm=jnp.float32(1.0),
                                 params=bad)
        assert int(gs.anomaly) == guard.A_NONFINITE_PARAM
        assert bool(np.asarray(guard.guard_ok(gs, CFG)))
        # rewind class does not skip: commit still selects the update
        new = {"w": jnp.zeros((2,))}
        got = guard.guard_commit(gs, new, bad, CFG)
        assert np.array_equal(np.asarray(got["w"]), np.zeros((2,)))

    def test_lr_backoff_and_recovery_schedule(self):
        gs = _observe_loop([1.0] * 6)
        gs = _observe_loop([50.0], gs=gs)            # spike -> 0.5
        assert float(gs.lr_scale) == 0.5
        gs = _observe_loop([50.0], gs=gs)            # again -> 0.25
        assert float(gs.lr_scale) == 0.25
        # lr_growth_interval=3 clean steps per x2 notch, capped at 1.0
        gs = _observe_loop([1.0] * 3, gs=gs)
        assert float(gs.lr_scale) == 0.5
        gs = _observe_loop([1.0] * 3, gs=gs)
        assert float(gs.lr_scale) == 1.0
        gs = _observe_loop([1.0] * 3, gs=gs)
        assert float(gs.lr_scale) == 1.0             # never above 1

    def test_lr_does_not_recover_across_skipped_steps(self):
        # a NaN storm after a backoff: nonfinite-grad steps are skipped
        # (nothing commits), so the recovery tracker must HOLD — only
        # clean steps buy the lr_scale back
        gs = _observe_loop([1.0] * 6)
        gs = _observe_loop([50.0], gs=gs)            # spike -> 0.5
        for _ in range(2 * CFG.lr_growth_interval):
            gs = guard.guard_observe(
                gs, CFG, loss=jnp.float32(1.0),
                grads={"w": jnp.full((2,), np.nan)})
        assert float(gs.lr_scale) == 0.5             # storm bought nothing
        gs = _observe_loop([1.0] * CFG.lr_growth_interval, gs=gs)
        assert float(gs.lr_scale) == 1.0             # clean steps do

    def test_nonfinite_grad_does_not_back_off_lr(self):
        # amp owns the overflow response (loss scale); the guard must
        # not double-penalize routine fp16 overflows with an LR cut
        gs = _observe_loop([1.0] * 6)
        gs = guard.guard_observe(gs, CFG, loss=jnp.float32(1.0),
                                 grads={"w": jnp.full((2,), np.inf)})
        assert int(gs.anomaly) & guard.A_NONFINITE_GRAD
        assert float(gs.lr_scale) == 1.0

    def test_skip_on_spike_off_observes_only(self):
        cfg = CFG._replace(skip_on_spike=False)
        gs = _observe_loop([1.0] * 6, cfg=cfg)
        gs = _observe_loop([50.0], gs=gs, cfg=cfg)
        assert int(gs.anomaly) & guard.A_LOSS_SPIKE
        assert int(gs.skip_count) == 0
        assert bool(np.asarray(guard.guard_ok(gs, cfg)))


class TestPolicy:
    def _trained_gs(self, n=6):
        return _observe_loop([1.0 - 0.01 * i for i in range(n)])

    def test_clean_run_emits_nothing(self):
        events = []
        pol = guard.GuardPolicy(event_sink=events.append)
        gs = guard.guard_init(CFG)
        for i in range(5):
            gs = guard.guard_observe(gs, CFG,
                                     loss=jnp.float32(1.0 - 0.01 * i))
            assert pol.update(i, gs).kind == "none"
        assert events == []

    def test_skip_event_then_budget_rewind(self):
        events = []
        pol = guard.GuardPolicy(event_sink=events.append,
                                skip_budget=2, skip_window=32)
        gs = self._trained_gs()
        kinds = []
        for i in range(4):
            gs = _observe_loop([80.0], gs=gs)
            kinds.append(pol.update(10 + i, gs).kind)
        assert kinds[:2] == ["skip", "skip"]
        assert "rewind" in kinds[2:]
        acts = [e["action"] for e in events
                if e["kind"] == "guard_action"]
        assert "skip" in acts and "rewind" in acts
        anom = [e for e in events if e["kind"] == "guard_anomaly"][0]
        assert anom["classes"] == ["loss_spike"]

    def test_rewind_restores_and_fast_forwards(self, tmp_path):
        events = []
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        pol = guard.GuardPolicy(manager=mgr, event_sink=events.append)
        src = StubSource()
        gs = self._trained_gs()
        state = {"w": jnp.arange(4.0)}
        src.skip_batches(3)
        mgr.save(2, {"s": state, "gs": gs},
                 extra={"cursor": src.state()})
        mgr.wait()
        src.skip_batches(2)                      # batches 3, 4 consumed
        bad = {"w": state["w"].at[0].set(jnp.nan)}
        gs = guard.guard_observe(gs, CFG, loss=jnp.float32(np.nan),
                                 params=bad)
        act = pol.update(4, gs)
        assert act.kind == "rewind"
        assert "nonfinite_param" in act.classes
        restored, mf = pol.rewind(4, {"s": bad, "gs": gs}, src,
                                  reason=act.reason)
        assert mf["step"] == 2
        assert src.cursor_index() == 5           # past the window
        assert np.array_equal(np.asarray(restored["s"]["w"]),
                              np.arange(4.0))
        rw = [e for e in events if e["kind"] == "guard_rewind"][0]
        assert rw["skipped_batches"] == 2 and rw["fallbacks"] == 0
        # restored guard state carries its history (windows/counters)
        assert int(restored["gs"].count) == int(self._trained_gs().count)

    def test_rewind_falls_back_past_bad_checkpoints(self, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"), keep=5)
        pol = guard.GuardPolicy(manager=mgr)
        src = StubSource()
        gs = self._trained_gs()
        good = {"s": {"w": jnp.ones((4,))}, "gs": gs}
        mgr.save(1, good, extra={"cursor": {"epoch": 0, "batch": 1}})
        mgr.wait()
        # newer ckpt with NONFINITE params -> rejected by verification
        mgr.save(3, {"s": {"w": jnp.full((4,), np.nan)}, "gs": gs},
                 extra={"cursor": {"epoch": 0, "batch": 3}})
        mgr.wait()
        # newest ckpt TRUNCATED -> rejected by the manifest hash
        mgr.save(5, good, extra={"cursor": {"epoch": 0, "batch": 5}})
        mgr.wait()
        assert guard.ChaosHarness.truncate_latest_checkpoint(mgr.root)
        src.skip_batches(7)
        restored, mf = pol.rewind(7, good, src)
        assert mf["step"] == 1
        assert src.cursor_index() == 7           # 1 -> +6 skipped

    def test_budget_exhausted_escalates_via_policy(self, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        esc = ckpt.EscalationPolicy(mgr, mode="raise")
        pol = guard.GuardPolicy(manager=mgr, escalation=esc,
                                rewind_budget=0)
        gs = self._trained_gs()
        gs = guard.guard_observe(gs, CFG, loss=jnp.float32(1.0),
                                 params={"w": jnp.float32(np.nan)})
        act = pol.update(3, gs)
        assert act.kind == "escalate"
        with pytest.raises(ckpt.PreemptionError):
            pol.escalate(act.reason)
        assert esc.tripped.startswith("guard:")

    def test_escalate_without_policy_raises_guard_escalation(self):
        pol = guard.GuardPolicy()
        with pytest.raises(guard.GuardEscalation):
            pol.escalate("no ladder below me")

    def test_observe_only_never_asks_for_action(self):
        events = []
        pol = guard.GuardPolicy(event_sink=events.append,
                                observe_only=True, skip_budget=0)
        gs = self._trained_gs()
        gs = guard.guard_observe(gs, CFG, loss=jnp.float32(np.nan),
                                 params={"w": jnp.float32(np.nan)})
        act = pol.update(7, gs)
        assert act.kind == "none"
        acts = {e["action"] for e in events
                if e["kind"] == "guard_action"}
        assert acts == {"observe"}

    def test_cooldown_suppresses_budget_rewind(self):
        pol = guard.GuardPolicy(skip_budget=0, cooldown_steps=50)
        pol.cooldown_until = 100                 # as if just rewound
        gs = self._trained_gs()
        gs = _observe_loop([80.0], gs=gs)
        assert pol.update(10, gs).kind == "skip"
        # rewind-class corruption is exempt from the cooldown
        gs = guard.guard_observe(gs, CFG, loss=jnp.float32(1.0),
                                 params={"w": jnp.float32(np.nan)})
        assert pol.update(11, gs).kind == "rewind"

    def test_cooldown_skips_do_not_bank_toward_the_budget(self):
        # skips observed DURING the cooldown must not accumulate and
        # fire a chain-rewind the moment the cooldown expires
        pol = guard.GuardPolicy(skip_budget=3, skip_window=100,
                                cooldown_steps=10)
        pol.cooldown_until = 15
        gs = self._trained_gs()
        for i in range(5, 10):                   # 5 skips in cooldown
            gs = _observe_loop([80.0], gs=gs)
            assert pol.update(i, gs).kind == "skip"
        assert pol._skip_steps == []             # nothing banked
        gs = _observe_loop([80.0], gs=gs)        # 1 skip after expiry
        assert pol.update(20, gs).kind == "skip"  # 1 <= budget 3

    def test_coarse_poll_counts_every_skip_toward_budget(self):
        # poll_every=4 with a skip on every step: each poll's counter
        # delta is 4, and all 4 must count toward the budget — one
        # entry per poll would defer the rewind indefinitely
        pol = guard.GuardPolicy(skip_budget=4, skip_window=32,
                                poll_every=4)
        gs = self._trained_gs()
        pol.update(0, gs)                            # baseline
        kinds = []
        for i in range(1, 9):
            gs = _observe_loop([80.0], gs=gs)
            kinds.append(pol.update(i, gs).kind)
        assert "rewind" in kinds, kinds              # 8 skips > budget 4

    def test_rewind_resyncs_counter_baseline(self, tmp_path):
        """The restored GuardState's cumulative counters sit BELOW the
        policy's cached high-water mark; without a resync a post-rewind
        anomaly whose counter has not yet re-crossed the stale baseline
        would difference to <= 0 and be silently missed."""
        events = []
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        pol = guard.GuardPolicy(manager=mgr, event_sink=events.append,
                                cooldown_steps=0)
        src = StubSource()
        gs = self._trained_gs()
        gs = _observe_loop([90.0, 90.0], gs=gs)  # skip_count -> 2
        pol.update(5, gs)
        src.skip_batches(6)
        mgr.save(5, {"s": {"w": jnp.ones(3)}, "gs": gs},
                 extra={"cursor": src.state()})
        mgr.wait()
        gs = _observe_loop([90.0, 90.0], gs=gs)  # baseline -> 4
        pol.update(7, gs)
        gs = guard.guard_observe(gs, CFG, loss=jnp.float32(1.0),
                                 params={"w": jnp.float32(np.nan)})
        src.skip_batches(3)
        act = pol.update(8, gs)
        assert act.kind == "rewind"
        restored, _mf = pol.rewind(8, {"s": {"w": jnp.ones(3)},
                                       "gs": gs}, src)
        gs = restored["gs"]                      # counters back at 2
        before = sum(1 for e in events
                     if e["kind"] == "guard_anomaly")
        gs = _observe_loop([90.0], gs=gs)        # counter 2 -> 3 (< 5)
        pol.update(9, gs)
        after = sum(1 for e in events if e["kind"] == "guard_anomaly")
        assert after == before + 1, "post-rewind anomaly missed"

    def test_coarse_poll_recovers_missed_events(self):
        events = []
        pol = guard.GuardPolicy(event_sink=events.append, poll_every=4)
        gs = self._trained_gs()
        assert pol.update(0, gs).kind == "none"  # baseline poll
        gs = _observe_loop([80.0], gs=gs)        # anomaly at step 1 …
        assert pol.update(1, gs).kind == "none"  # … inside the cadence
        assert events == []                      # not fetched yet
        gs = _observe_loop([1.0], gs=gs)
        pol.update(4, gs)                        # 4 - 0 >= 4: polls
        anom = [e for e in events if e["kind"] == "guard_anomaly"]
        assert len(anom) == 1                    # counter delta saw it
        assert anom[0]["classes"] == ["loss_spike"]


class TestChaos:
    def test_plan_determinism_and_json_roundtrip(self):
        rates = {"grads:nan": 0.1, "batch:corrupt": 0.08,
                 "params:bitflip": 0.02}
        p1 = guard.FaultPlan.random(11, 200, rates=rates, ranks=2)
        p2 = guard.FaultPlan.random(11, 200, rates=rates, ranks=2)
        assert p1 == p2 and len(p1) > 0
        assert guard.FaultPlan.from_json(p1.to_json()) == p1
        p3 = guard.FaultPlan.random(12, 200, rates=rates, ranks=2)
        assert p3 != p1

    def test_plan_rejects_unknown_site_kind_and_dupes(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            guard.FaultPlan().add(0, "gpu", "nan")
        with pytest.raises(ValueError, match="supports kinds"):
            guard.FaultPlan().add(0, "params", "corrupt")
        with pytest.raises(ValueError, match="duplicate"):
            guard.FaultPlan().add(0, "grads", "nan").add(
                0, "grads", "inf")
        # random() must validate too — a typo'd rate key would make a
        # chaos soak pass vacuously against a fault-free run
        with pytest.raises(ValueError, match="unknown fault rate key"):
            guard.FaultPlan.random(0, 10, rates={"gards:nan": 0.5})
        with pytest.raises(ValueError, match="unknown fault rate key"):
            guard.FaultPlan.random(0, 10, rates={"batch:bitflip": 0.5})
        # two kinds on one site would silently lose collisions (plans
        # are keyed by (step, rank, site)) — refused up front
        with pytest.raises(ValueError, match="share the site"):
            guard.FaultPlan.random(0, 10, rates={"grads:nan": 0.5,
                                                 "grads:inf": 0.5})

    def test_fault_code_and_injection(self):
        plan = (guard.FaultPlan()
                .add(2, "grads", "nan")
                .add(3, "grads", "inf")
                .add(4, "activations", "nan"))
        assert plan.fault_code(0) == 0
        assert plan.fault_code(2) == guard.chaos.C_GRAD_NAN
        assert plan.fault_code(3) == guard.chaos.C_GRAD_INF
        assert plan.fault_code(4) == guard.chaos.C_ACT_NAN
        g = {"w": jnp.ones((4,)), "n": jnp.ones((2,), jnp.int32)}
        out = guard.inject_grads(g, jnp.int32(plan.fault_code(2)))
        assert np.isnan(np.asarray(out["w"])[0])
        assert np.asarray(out["n"]).sum() == 2   # ints untouched
        out = guard.inject_grads(g, jnp.int32(plan.fault_code(3)))
        assert np.isinf(np.asarray(out["w"])[0])
        act = guard.inject_activation(jnp.ones((2, 2)),
                                      jnp.int32(plan.fault_code(4)))
        assert np.isnan(np.asarray(act).reshape(-1)[0])
        clean = guard.inject_grads(g, jnp.int32(0))
        assert np.isfinite(np.asarray(clean["w"])).all()

    def test_filter_batch_kinds_are_deterministic(self):
        x = np.zeros((4, 3), np.float32)
        y = np.arange(4, dtype=np.int32)
        h = guard.ChaosHarness(
            guard.FaultPlan(seed=5)
            .add(0, "batch", "nan").add(1, "batch", "corrupt", arg=10.0)
            .add(2, "batch", "overflow", arg=1e30))
        xn, _ = h.filter_batch(0, (x, y))
        assert np.isnan(xn.reshape(-1)[0]) and not np.isnan(x).any()
        xc1, _ = h.filter_batch(1, (x, y))
        xc2, _ = h.filter_batch(1, (x, y))
        assert np.array_equal(xc1, xc2)          # seeded, replayable
        assert np.abs(xc1).max() <= 10.0
        xo, _ = h.filter_batch(2, (x + 1.0, y))
        assert xo.max() >= 1e29                  # the overflow storm
        assert h.filter_batch(3, (x, y))[0] is x  # no fault: untouched

    def test_param_corruption_nan_and_bitflip(self):
        state = {"a": jnp.ones((3,)), "b": jnp.ones((2,))}
        h = guard.ChaosHarness(guard.FaultPlan()
                               .add(0, "params", "nan")
                               .add(1, "params", "bitflip", arg=30))
        s1 = h.post_step(0, state)
        leaves = jax.tree_util.tree_leaves(s1)
        assert any(np.isnan(np.asarray(l)).any() for l in leaves)
        s2 = h.post_step(1, state)
        first = np.asarray(jax.tree_util.tree_leaves(s2)[0])
        assert not np.isnan(first).any()
        assert np.abs(first.reshape(-1)[0]) > 1e18   # exponent bit 30
        assert h.injected == [(0, "params", "nan"),
                              (1, "params", "bitflip")]

    def test_truncate_latest_checkpoint_breaks_hash(self, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        mgr.save(1, {"w": np.arange(4000, dtype=np.float32)},
                 block=True)
        mgr.wait()
        path = guard.ChaosHarness.truncate_latest_checkpoint(mgr.root)
        assert path and path.endswith(".npz")
        with pytest.raises(ckpt.CheckpointError, match="hash mismatch"):
            mgr.restore({"w": jnp.zeros((4000,), jnp.float32)})


class TestAmpGuard:
    def test_amp_step_guard_generalizes_overflow_skip(self):
        from apex_tpu import amp
        from apex_tpu.optim import FusedSGD

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(8, 16).astype("float32"))
        y = jnp.asarray(rng.randn(8, 4).astype("float32"))
        params = {"w": jnp.asarray(
            rng.randn(16, 4).astype("float32") * 0.1)}
        amp_opt, state = amp.initialize(
            params, FusedSGD(lr=0.05), "O2", half_dtype=jnp.float16,
            verbosity=0)
        cfg = CFG

        @jax.jit
        def step(state, gs, x, y):
            def loss_fn(p):
                return jnp.mean(jnp.square(x @ p["w"] - y))
            state, loss, committed, gs = amp_opt.step(
                state, loss_fn, guard=(gs, cfg))
            return state, gs, loss

        gs = guard.guard_init(cfg)
        for i in range(6):
            state, gs, loss = step(state, gs, x, y)
        assert int(state.step) == 6 and int(gs.skip_count) == 0
        # poisoned input -> nonfinite loss/grads -> guarded skip: the
        # amp step count and params both hold still
        xp = np.asarray(x).copy()
        xp.reshape(-1)[0] = np.inf
        w_before = np.asarray(state.params["w"]).copy()
        state, gs, _loss = step(state, gs, jnp.asarray(xp), y)
        assert int(state.step) == 6
        assert int(gs.skip_count) == 1
        assert np.array_equal(np.asarray(state.params["w"]), w_before)

    def test_amp_guard_applies_lr_backoff_as_grad_scaling(self):
        # the backoff rung must actually shrink the committed update:
        # with lr_scale preset to 0.5 the SGD param delta halves
        from apex_tpu import amp
        from apex_tpu.optim import FusedSGD

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(8, 16).astype("float32"))
        y = jnp.asarray(rng.randn(8, 4).astype("float32"))
        params = {"w": jnp.asarray(
            rng.randn(16, 4).astype("float32") * 0.1)}
        amp_opt, state0 = amp.initialize(
            params, FusedSGD(lr=0.05), "O2", half_dtype=jnp.float16,
            verbosity=0)

        def loss_fn(p):
            return jnp.mean(jnp.square(x @ p["w"] - y))

        def delta(lr_scale):
            gs = guard.guard_init(CFG)._replace(
                lr_scale=jnp.float32(lr_scale))
            state, _loss, _ok, _gs = jax.jit(
                lambda s, g: amp_opt.step(s, loss_fn, guard=(g, CFG))
            )(state0, gs)
            return (np.asarray(state.params["w"])
                    - np.asarray(state0.params["w"]))
        np.testing.assert_allclose(delta(0.5), 0.5 * delta(1.0),
                                   rtol=1e-5)


class TestSchema:
    def _check(self, lines):
        from scripts.check_metrics_schema import check_guard_lines
        return check_guard_lines(lines)

    def test_valid_stream_passes(self):
        lines = [
            json.dumps({"kind": "guard_anomaly", "step": 3,
                        "classes": ["loss_spike"], "z": 9.5,
                        "rank": 0, "wall_time": 1.0}),
            json.dumps({"kind": "guard_anomaly", "step": 4,
                        "classes": ["nonfinite_loss"], "z": None,
                        "rank": 0}),
            json.dumps({"kind": "guard_action", "step": 4,
                        "action": "rewind", "classes": ["nonfinite_param"],
                        "reason": "nonfinite_param", "rank": 0}),
            json.dumps({"kind": "guard_rewind", "step": 4,
                        "from_step": 4, "to_step": 2, "path": "/ck",
                        "skipped_batches": 2, "fallbacks": 1,
                        "reason": None, "rank": 0}),
        ]
        assert self._check(lines) == []

    def test_negative_twins_fail(self):
        bad = [
            # unknown kind
            json.dumps({"kind": "guard_oops", "step": 1}),
            # unknown action enum value
            json.dumps({"kind": "guard_action", "step": 1,
                        "action": "reboot"}),
            # unknown anomaly class
            json.dumps({"kind": "guard_anomaly", "step": 1,
                        "classes": ["gremlins"]}),
            # null path on a rewind
            json.dumps({"kind": "guard_rewind", "step": 1,
                        "from_step": 1, "to_step": 0, "path": None,
                        "skipped_batches": 1}),
            # rewind that goes forwards
            json.dumps({"kind": "guard_rewind", "step": 1,
                        "from_step": 1, "to_step": 5, "path": "/ck",
                        "skipped_batches": 1}),
            # negative skip count
            json.dumps({"kind": "guard_rewind", "step": 1,
                        "from_step": 1, "to_step": 0, "path": "/ck",
                        "skipped_batches": -2}),
            # missing required key (classes)
            json.dumps({"kind": "guard_anomaly", "step": 1}),
        ]
        for line in bad:
            assert self._check([line]), f"accepted bad line: {line}"

    def test_logger_guard_channel_nulls_nonfinite(self, tmp_path):
        out = tmp_path / "g.jsonl"
        logger = monitor.MetricsLogger(
            sinks=[], guard_sink=monitor.JSONLSink(str(out)))
        logger.record_guard({"kind": "guard_anomaly", "step": 1,
                             "classes": ["nonfinite_loss"],
                             "z": float("nan"), "rank": 0})
        logger.close()
        rec = json.loads(out.read_text().strip())
        assert rec["z"] is None
        assert self._check([out.read_text().strip()]) == []


class TestCompileCheck:
    def test_guard_case_runs_green(self):
        from apex_tpu.ops import compile_check as cc
        assert cc.run(pattern="guard")


class TestRecorderIntegration:
    def test_crash_header_carries_guard_events(self, tmp_path):
        from apex_tpu import trace
        rec = trace.FlightRecorder(str(tmp_path / "crash.jsonl"))
        pol = guard.GuardPolicy(recorder=rec, observe_only=True)
        gs = _observe_loop([1.0] * 6)
        gs = _observe_loop([80.0], gs=gs)
        pol.update(6, gs)
        hdr = rec.header("test")
        assert "guard_events" in hdr
        kinds = {e["kind"] for e in hdr["guard_events"]}
        assert "guard_anomaly" in kinds
        json.dumps(hdr)          # strict-JSON serializable (z nulled)


@pytest.mark.slow
class TestSoak:
    def test_sigkill_fault_kills_the_process(self, tmp_path):
        child = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "from apex_tpu import guard\n"
            "h = guard.ChaosHarness(guard.FaultPlan()"
            ".add(2, 'proc', 'sigkill'))\n"
            "for step in range(5):\n"
            "    h.post_step(step, {'w': 1.0})\n"
            "print('UNREACHABLE')\n"
        )
        p = subprocess.run(
            [sys.executable, "-c", child],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=120,
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        assert p.returncode == -signal.SIGKILL, (p.returncode, p.stderr)
        assert "UNREACHABLE" not in p.stdout

    def test_stalled_collective_trips_watchdog(self, tmp_path):
        from apex_tpu import trace
        fired = []
        wd = trace.HangWatchdog(deadline_s=0.6, poll_s=0.1,
                                path=str(tmp_path / "hang.jsonl"),
                                on_fire=fired.append)
        wd.start()
        try:
            h = guard.ChaosHarness(
                guard.FaultPlan().add(1, "collective", "stall",
                                      arg=2.0))
            for step in range(3):
                wd.notify_step(step)
                h.post_step(step, {})
        finally:
            wd.stop()
        assert fired, "stalled-collective fault did not trip the " \
                      "watchdog"

    def test_multi_fault_random_soak_recovers_or_skips(self, tmp_path):
        """A longer randomized plan: every in-graph/batch/params fault
        class mixed, the guard must end the run with finite params,
        every injected fault accounted as a skip or a rewind, and the
        event stream schema-clean."""
        from scripts.check_metrics_schema import check_guard_lines

        cfg = guard.GuardConfig(window=16, min_history=4,
                                z_threshold=8.0, lr_growth_interval=4)
        plan = (guard.FaultPlan(seed=9)
                .add(6, "grads", "nan")
                .add(11, "batch", "corrupt", arg=50.0)
                .add(15, "params", "nan")
                .add(22, "grads", "inf")
                .add(27, "batch", "nan"))
        events = []
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"), keep=4)
        pol = guard.GuardPolicy(manager=mgr, event_sink=events.append,
                                rewind_budget=3, cooldown_steps=4)
        src = StubSource(per_epoch=8)
        harness = guard.ChaosHarness(plan)
        rng = np.random.RandomState(0)
        w_true = rng.randn(16, 2).astype("float32")

        @jax.jit
        def step(params, gs, x, y, code):
            def loss_fn(p):
                return jnp.mean(jnp.square(x @ p["w"] - y))
            loss, grads = jax.value_and_grad(loss_fn)(params)
            grads = guard.inject_grads(grads, code)
            gs = guard.guard_observe(gs, cfg, loss=loss, grads=grads,
                                     params=params)
            new_p = jax.tree_util.tree_map(
                lambda p, g: p - 0.02 * gs.lr_scale * g, params, grads)
            return guard.guard_commit(gs, new_p, params, cfg), gs, loss

        params = {"w": jnp.asarray(
            rng.randn(16, 2).astype("float32") * 0.1)}
        gs = guard.guard_init(cfg)
        n_rewinds = 0
        for i in range(32):
            xb = rng.randn(8, 16).astype("float32")
            yb = xb @ w_true
            xb, yb = harness.filter_batch(i, (xb, yb))
            code = harness.fault_code(i)
            params, gs, loss = step(params, gs, jnp.asarray(xb),
                                    jnp.asarray(yb), jnp.int32(code))
            if i % 3 == 0:
                mgr.save(i, {"p": params, "gs": gs},
                         extra={"cursor": src.state()})
                mgr.wait()
            params = harness.post_step(i, params)
            src.skip_batches(1)
            act = pol.update(i, gs)
            if act.kind == "rewind":
                restored, _mf = pol.rewind(i, {"p": params, "gs": gs},
                                           src, reason=act.reason)
                params, gs = restored["p"], restored["gs"]
                n_rewinds += 1
        assert n_rewinds >= 1                    # the params:nan fault
        assert pol.rewinds_done == n_rewinds
        for leaf in jax.tree_util.tree_leaves(params):
            assert np.isfinite(np.asarray(leaf)).all()
        # every fault either skipped in-graph or triggered the rewind
        assert int(gs.skip_count) >= 3
        errors = check_guard_lines(json.dumps(e) for e in events)
        assert not errors, errors
