"""In-graph anomaly detection: the ``GuardState`` pytree.

Apex's dynamic loss scaler is the prototype in-band anomaly policy —
detect a bad step from *inside* the program, skip it, adapt, continue
(`apex/amp/scaler.py:197-215`) — but it only covers fp16 grad overflow.
This module generalizes the pattern to the anomalies that actually cost
pod-scale wall-clock: loss spikes (poisoned batches, data corruption),
gradient-norm explosions (instability before divergence), and
non-finite *parameters* (silent state corruption — a bit flip, a bad
DMA — which the grad-overflow check can never see because the damage is
already committed).

Design rules (the :mod:`apex_tpu.monitor` zero-extra-dispatch pattern):

- ``GuardState`` is a small pytree of on-device scalars plus two fixed-
  length rolling windows, carried through the jitted train step like the
  loss-scaler state itself. Every update is pure ``jnp`` arithmetic
  riding the existing step dispatch — detection costs **no extra
  dispatches and no host syncs** (the ``guard/no-extra-dispatch``
  compile-check case pins it).
- Spike detection is a **robust z-score** against the rolling loss
  window: ``z = (loss - median) / (1.4826·MAD + floor)``. Median/MAD
  (not mean/std) so a previous outlier cannot drag the baseline, with a
  relative floor so a converged flat loss curve does not turn numerical
  jitter into anomalies. Anomalous losses are never pushed into the
  window — one poisoned step cannot poison the detector.
- The skipped step is ``jnp.where`` commit-or-keep
  (:func:`guard_commit`), exactly amp's functional overflow skip
  (:func:`apex_tpu.amp.scaler.select_if_finite`) widened to every
  skip-class anomaly.
- The LR backoff ladder rung is in-graph too: ``lr_scale`` follows the
  amp loss-scale schedule (backoff on spike/explosion, recover ×2 after
  ``lr_growth_interval`` clean steps) so transient instability is damped
  with zero host involvement. Multiply it into your update
  (``p - lr * gs.lr_scale * g``); a run that never trips keeps
  ``lr_scale == 1.0`` identically.

Escalation beyond skip/backoff (rewind to a checkpoint, hand-off to the
exit-75 path) is inherently host-side — see
:class:`apex_tpu.guard.GuardPolicy`.

Forensic cross-link: the nonfinite probes here are *tree-level* — they
answer "did anything go nonfinite" cheaply enough to run every step and
veto the commit. The numerics observatory
(:mod:`apex_tpu.monitor.numerics`) carries the per-*site* complement:
``nonfinite_frac`` per tracked tensor, with
:func:`apex_tpu.monitor.numerics.nonfinite_sites` naming WHICH tensor
(and what fraction) after the guard has already stopped the damage —
the same that-vs-where split as integrity's pmin/pmax compare vs its
gathered per-replica fingerprints (docs/resilience.md, docs/numerics.md).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils import global_norm, tree_all_finite, tree_select

__all__ = [
    "GuardConfig", "GuardState", "guard_init", "guard_observe",
    "guard_ok", "guard_commit", "anomaly_classes",
    "A_LOSS_SPIKE", "A_GRAD_EXPLOSION", "A_NONFINITE_GRAD",
    "A_NONFINITE_LOSS", "A_NONFINITE_PARAM", "A_REPLICA_DIVERGENCE",
    "SKIP_MASK", "REWIND_MASK", "LR_BACKOFF_MASK", "ANOMALY_CLASSES",
]

# -- anomaly bitmask -----------------------------------------------------------

A_LOSS_SPIKE = 1        #: finite loss, robust z-score above threshold
A_GRAD_EXPLOSION = 2    #: grad norm >> rolling median grad norm
A_NONFINITE_GRAD = 4    #: NaN/Inf gradients (amp overflow generalized)
A_NONFINITE_LOSS = 8    #: NaN/Inf loss value
A_NONFINITE_PARAM = 16  #: NaN/Inf *committed parameters* — state corruption
A_REPLICA_DIVERGENCE = 32  #: cross-replica integrity fingerprints
                           #: disagree — "replicated" state silently
                           #: diverged (guard.integrity's verdict,
                           #: fed in via ``replica_ok``)

ANOMALY_CLASSES = {
    A_LOSS_SPIKE: "loss_spike",
    A_GRAD_EXPLOSION: "grad_explosion",
    A_NONFINITE_GRAD: "nonfinite_grad",
    A_NONFINITE_LOSS: "nonfinite_loss",
    A_NONFINITE_PARAM: "nonfinite_param",
    A_REPLICA_DIVERGENCE: "replica_divergence",
}

#: classes whose step is vetoed in-graph (commit-or-keep select). Note
#: nonfinite params are NOT here: the corruption already lives in the
#: committed state, so refusing this step's update cannot help — that
#: class is the host policy's rewind trigger instead. Replica
#: divergence IS here: the diverged state predates this step too, but
#: its gradients entered the psum — the update is polluted on EVERY
#: replica and must not commit while the host decides repair vs rewind
#: (``GuardPolicy.update_integrity``).
SKIP_MASK = (A_LOSS_SPIKE | A_GRAD_EXPLOSION | A_NONFINITE_GRAD
             | A_NONFINITE_LOSS | A_REPLICA_DIVERGENCE)

#: classes that mean the committed state itself is bad — skip/backoff
#: cannot recover; the host policy rewinds to the last good snapshot.
REWIND_MASK = A_NONFINITE_PARAM

#: classes that back the in-graph lr_scale off. Deliberately excludes
#: nonfinite grads: under amp that event is fp16 overflow and the loss
#: *scale* schedule already owns the response — backing the LR off too
#: would double-penalize every routine overflow.
LR_BACKOFF_MASK = A_LOSS_SPIKE | A_GRAD_EXPLOSION


def anomaly_classes(mask: int):
    """Host-side helper: bitmask → sorted list of class names."""
    m = int(mask)
    return [name for bit, name in sorted(ANOMALY_CLASSES.items())
            if m & bit]


class GuardConfig(NamedTuple):
    """Static detector configuration (hashable; safe to close over in
    jit). Thresholds are deliberately loose by default — a guard that
    false-positives on healthy noise is worse than no guard (the chaos
    audit's clean-run check pins zero interventions)."""

    window: int = 32            #: rolling window length (losses + norms)
    min_history: int = 8        #: detections armed only after this many
                                #: accepted observations
    z_threshold: float = 8.0    #: robust z above which a loss is a spike
    z_rel_floor: float = 0.05   #: MAD floor as a fraction of |median| —
                                #: a flat converged loss curve must not
                                #: turn jitter into infinite z-scores
    grad_factor: float = 20.0   #: grad norm > factor × rolling median
                                #: grad norm = explosion
    check_params: bool = True   #: enable the nonfinite-param probe
    skip_on_spike: bool = True  #: veto the commit on a loss spike (off:
                                #: spikes are observed/counted only)
    lr_backoff: float = 0.5     #: lr_scale multiplier on backoff-class
                                #: anomalies (amp schedule, LR edition)
    lr_growth_interval: int = 50  #: clean steps before lr_scale recovers
                                  #: one ×(1/lr_backoff) notch (→ 1.0)
    min_lr_scale: float = 1.0 / 64.0


class GuardState(NamedTuple):
    """The in-graph guard: rolling windows + flags + counters.

    Every field is a device scalar except the two fixed-length windows —
    the tree is checkpointable (save it inside the training tuple so a
    rewind restores the detector's memory too), donate-able, and
    ``lax.scan``-carryable. ``anomaly``/``z`` describe the LAST observed
    step (transient, refreshed by :func:`guard_observe`); the ``*_count``
    fields are cumulative and never reset, so a host poll at any cadence
    can difference them to recover missed events.
    """

    loss_window: jax.Array        # f32[window]; NaN = empty slot
    gnorm_window: jax.Array       # f32[window]; NaN = empty slot
    pos: jax.Array                # i32 ring write position
    count: jax.Array              # i32 observations accepted into windows
    step: jax.Array               # i32 observed (attempted) steps
    anomaly: jax.Array            # i32 bitmask for the last step
    z: jax.Array                  # f32 last robust z-score (NaN when
                                  # unarmed or the loss was non-finite)
    lr_scale: jax.Array           # f32 in-graph LR backoff multiplier
    lr_tracker: jax.Array         # i32 clean steps since last backoff
    consecutive: jax.Array        # i32 consecutive anomalous steps
    spike_count: jax.Array        # i32 cumulative per-class counters…
    grad_explosion_count: jax.Array
    nonfinite_grad_count: jax.Array
    nonfinite_loss_count: jax.Array
    nonfinite_param_count: jax.Array
    replica_divergence_count: jax.Array
    skip_count: jax.Array         # i32 cumulative in-graph vetoed steps


def guard_init(cfg: GuardConfig = GuardConfig()) -> GuardState:
    """Fresh guard state — thread through the step like scaler state."""
    w = int(cfg.window)
    if w < 4:
        raise ValueError(f"GuardConfig.window must be >= 4, got {w} "
                         f"(a robust median needs history)")
    nan = jnp.full((w,), jnp.nan, jnp.float32)
    z0 = jnp.int32(0)
    return GuardState(
        loss_window=nan, gnorm_window=nan,
        pos=z0, count=z0, step=z0,
        anomaly=z0, z=jnp.float32(0.0),
        lr_scale=jnp.float32(1.0), lr_tracker=z0, consecutive=z0,
        spike_count=z0, grad_explosion_count=z0,
        nonfinite_grad_count=z0, nonfinite_loss_count=z0,
        nonfinite_param_count=z0, replica_divergence_count=z0,
        skip_count=z0,
    )


def _robust_z(loss, window, cfg: GuardConfig):
    """Signed robust z-score of ``loss`` against the rolling window.

    ``nanmedian`` treats empty (NaN) slots as missing; an all-empty
    window yields NaN which compares False against the threshold — the
    un-armed guard can never fire."""
    med = jnp.nanmedian(window)
    mad = jnp.nanmedian(jnp.abs(window - med))
    scale = (1.4826 * mad + cfg.z_rel_floor * jnp.abs(med) + 1e-12)
    return (loss - med) / scale


def guard_observe(gs: GuardState, cfg: GuardConfig, *, loss,
                  grads=None, grad_norm=None, params=None,
                  grads_finite=None, replica_ok=None) -> GuardState:
    """Observe one step: compute this step's anomaly bitmask against the
    PRE-update windows, advance windows/counters/LR schedule. Pure
    ``jnp``; rides the existing step dispatch.

    ``loss`` is required. ``grads`` (a pytree) enables the nonfinite-grad
    check and, unless ``grad_norm`` is given, the explosion check;
    ``grads_finite`` (a precomputed flag — e.g. amp's) substitutes for
    the finiteness traversal. ``params`` enables the nonfinite-param
    probe (pass the *committed* params the step started from — the probe
    exists to catch corruption that is already state). ``replica_ok``
    (the :func:`apex_tpu.guard.integrity_ok` verdict of this step's
    cross-replica fingerprint check) raises the skip-class
    ``A_REPLICA_DIVERGENCE`` anomaly when False — the polluted update
    then never commits through :func:`guard_commit`, and the anomalous
    (pmean-polluted) loss never enters the rolling window.
    """
    loss = jnp.asarray(loss, jnp.float32)
    armed = gs.count >= cfg.min_history

    # --- per-class detections ------------------------------------------------
    z = _robust_z(loss, gs.loss_window, cfg)
    loss_finite = jnp.isfinite(loss)
    spike = jnp.logical_and(jnp.logical_and(armed, loss_finite),
                            z > cfg.z_threshold)

    gnorm = None
    if grad_norm is not None:
        gnorm = jnp.asarray(grad_norm, jnp.float32)
    elif grads is not None:
        gnorm = global_norm(grads)
    if gnorm is not None:
        gmed = jnp.nanmedian(gs.gnorm_window)
        explosion = jnp.logical_and(
            armed, gnorm > cfg.grad_factor * gmed)
        # an exploding-to-inf norm belongs to the nonfinite class below
        explosion = jnp.logical_and(explosion, jnp.isfinite(gnorm))
    else:
        explosion = jnp.bool_(False)

    if grads_finite is not None:
        g_fin = jnp.asarray(grads_finite, jnp.bool_)
    elif grads is not None:
        g_fin = tree_all_finite(grads)
    elif gnorm is not None:
        g_fin = jnp.isfinite(gnorm)
    else:
        g_fin = jnp.bool_(True)

    if cfg.check_params and params is not None:
        p_fin = tree_all_finite(params)
    else:
        p_fin = jnp.bool_(True)

    if replica_ok is not None:
        r_ok = jnp.asarray(replica_ok, jnp.bool_)
    else:
        r_ok = jnp.bool_(True)

    def _bit(cond, bit):
        return jnp.where(cond, jnp.int32(bit), jnp.int32(0))

    anomaly = (_bit(spike, A_LOSS_SPIKE)
               + _bit(explosion, A_GRAD_EXPLOSION)
               + _bit(jnp.logical_not(g_fin), A_NONFINITE_GRAD)
               + _bit(jnp.logical_not(loss_finite), A_NONFINITE_LOSS)
               + _bit(jnp.logical_not(p_fin), A_NONFINITE_PARAM)
               + _bit(jnp.logical_not(r_ok), A_REPLICA_DIVERGENCE))
    if not cfg.skip_on_spike:
        skip_mask = SKIP_MASK & ~A_LOSS_SPIKE
    else:
        skip_mask = SKIP_MASK
    skipped = (anomaly & skip_mask) != 0
    anomalous = anomaly != 0

    # --- window advance (clean, finite observations only) --------------------
    accept = jnp.logical_and(jnp.logical_not(anomalous), loss_finite)
    old_l = gs.loss_window[gs.pos]
    new_lw = gs.loss_window.at[gs.pos].set(
        jnp.where(accept, loss, old_l))
    if gnorm is not None:
        old_g = gs.gnorm_window[gs.pos]
        new_gw = gs.gnorm_window.at[gs.pos].set(
            jnp.where(accept, gnorm, old_g))
    else:
        new_gw = gs.gnorm_window
    w = gs.loss_window.shape[0]
    new_pos = jnp.where(accept, (gs.pos + 1) % w, gs.pos)
    new_count = gs.count + jnp.where(accept, 1, 0).astype(jnp.int32)

    # --- LR backoff schedule (amp's loss-scale schedule, LR edition) ---------
    # the recovery tracker counts CLEAN steps only: a skipped anomaly
    # step (e.g. a NaN storm of nonfinite grads) holds the tracker —
    # lr_scale must not recover to 1.0 across a stretch in which
    # nothing actually committed
    backoff_now = (anomaly & LR_BACKOFF_MASK) != 0
    backed = jnp.maximum(gs.lr_scale * cfg.lr_backoff,
                         cfg.min_lr_scale)
    clean = anomaly == 0
    grown_tracker = gs.lr_tracker + jnp.where(clean, 1, 0)
    should_grow = jnp.logical_and(clean,
                                  grown_tracker >= cfg.lr_growth_interval)
    grown = jnp.minimum(gs.lr_scale / cfg.lr_backoff, 1.0)
    new_lr = jnp.where(backoff_now, backed,
                       jnp.where(should_grow, grown,
                                 gs.lr_scale)).astype(jnp.float32)
    new_tracker = jnp.where(backoff_now, 0,
                            jnp.where(should_grow, 0,
                                      grown_tracker)).astype(jnp.int32)

    def _cnt(cond):
        return jnp.where(cond, 1, 0).astype(jnp.int32)

    return gs._replace(
        loss_window=new_lw, gnorm_window=new_gw,
        pos=new_pos, count=new_count, step=gs.step + 1,
        anomaly=anomaly,
        # NaN z (empty window, or a NaN loss) propagates as-is: the
        # event layers null non-finite gauges on the wire (the schema's
        # nullable-z contract); clamping to 0.0 here would read as
        # "no spike" in dashboards for exactly the steps that matter
        z=jnp.asarray(z, jnp.float32),
        lr_scale=new_lr, lr_tracker=new_tracker,
        consecutive=jnp.where(anomalous, gs.consecutive + 1,
                              0).astype(jnp.int32),
        spike_count=gs.spike_count + _cnt(spike),
        grad_explosion_count=gs.grad_explosion_count + _cnt(explosion),
        nonfinite_grad_count=(gs.nonfinite_grad_count
                              + _cnt(jnp.logical_not(g_fin))),
        nonfinite_loss_count=(gs.nonfinite_loss_count
                              + _cnt(jnp.logical_not(loss_finite))),
        nonfinite_param_count=(gs.nonfinite_param_count
                               + _cnt(jnp.logical_not(p_fin))),
        replica_divergence_count=(gs.replica_divergence_count
                                  + _cnt(jnp.logical_not(r_ok))),
        skip_count=gs.skip_count + _cnt(skipped),
    )


def guard_ok(gs: GuardState, cfg: Optional[GuardConfig] = None):
    """Commit predicate for the step :func:`guard_observe` just scored:
    True when no skip-class anomaly fired. Rewind-class anomalies
    (nonfinite params) do NOT veto — the update is irrelevant there and
    the host policy owns the response."""
    mask = SKIP_MASK
    if cfg is not None and not cfg.skip_on_spike:
        mask &= ~A_LOSS_SPIKE
    return (gs.anomaly & mask) == 0


def guard_commit(gs: GuardState, new_tree, old_tree,
                 cfg: Optional[GuardConfig] = None):
    """Commit ``new_tree`` unless this step was anomalous — amp's
    functional skipped step (:func:`~apex_tpu.amp.scaler.select_if_finite`)
    generalized to every skip-class anomaly."""
    return tree_select(guard_ok(gs, cfg), new_tree, old_tree)
