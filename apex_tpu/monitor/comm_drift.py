"""Plan-vs-measured comm drift: is the link model still true?

:func:`apex_tpu.parallel.plan_comm` schedules the hierarchical sync
against an α–β :class:`~apex_tpu.lint.mesh_model.MeshModel` — measured
once by ``scripts/link_probe.py``, then committed. Fabrics drift:
congestion, a degraded optical link, a different pod slice, or simply
a model someone calibrated on other hardware. A plan optimized against
a stale model silently picks the wrong wire dtype per hop (the exact
failure DynamiQ's measured-``hop_seconds`` search and EQuARX's gated
narrowing exist to avoid). This module closes the loop:

1. **measure** (:func:`measure_hops`): time each hop of a
   :class:`~apex_tpu.parallel.CommPlan` as its own jitted collective
   (linkbench's best-of-``iters`` harness at the hop's payload bytes,
   repeated ``Hop.n_collectives()`` times so the int8 multi-collective
   decomposition is charged its α's) — or join wire times the pod
   observatory already measured (:func:`wire_from_pod`, positional
   against the ``bucketNN`` → ``ici``/``dcn`` sub-spans
   ``hierarchical_sync`` emits; host-visible spans only — under jit
   those sub-spans are trace-time, so runs that want the pod join wrap
   hops in host spans or use the harness);
2. **join** (:func:`compare`): measured seconds against the plan's
   per-hop prediction (:meth:`~apex_tpu.parallel.CommPlan.hop_seconds`)
   — one :class:`HopDrift` row per hop with the measured/predicted
   ratio;
3. **flag** (:class:`CommDriftReport`): any hop whose ratio leaves
   ``[1/tolerance, tolerance]`` marks the model **stale** and the
   report names the fix (re-run ``scripts/link_probe.py``), with a
   stable apexlint-style fingerprint per hop site
   (``comm_drift|{op}|{axis}/{link}``) so baselines and dedup key on
   *where*, not on the drifting numbers.

``kind="pod_drift"`` events ride the podview channel
(``MetricsLogger(podview_sink=...)``;
``check_metrics_schema.py --kind podview`` validates). The CI gate —
measured agrees with the plan within a stated tolerance on the cpu8
mesh, and the flag FIRES on a deliberately staled model — is
``scripts/pod_audit.py --cpu8``. Tolerances are ratios, not absolutes:
α–β models are order-of-magnitude instruments, and on the CPU mesh the
numbers characterize XLA:CPU's emulation (the linkbench caveat applies
verbatim), so CI pins the *pipeline*, on-chip runs pin the fabric.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["HopDrift", "CommDriftReport", "measure_hops",
           "wire_from_pod", "compare"]


@dataclasses.dataclass(frozen=True)
class HopDrift:
    """One hop's plan-vs-measured row."""

    hop: int                  # position in plan.hops
    op: str
    axis: str
    link: str                 # "ici" | "dcn"
    dtype: Optional[str]
    predicted_ms: float
    measured_ms: float

    @property
    def ratio(self) -> float:
        """measured / predicted (1.0 = the model holds)."""
        return self.measured_ms / max(self.predicted_ms, 1e-9)

    @property
    def fingerprint(self) -> str:
        """Stable site identity, apexlint-style (``rule|op|scope``):
        the drifting milliseconds are excluded so a baselined site
        survives re-measurement."""
        return f"comm_drift|{self.op}|{self.axis}/{self.link}"

    def stale(self, tolerance: float) -> bool:
        r = self.ratio
        return r > tolerance or r < 1.0 / tolerance

    def to_event(self, tolerance: float,
                 wall_time: Optional[float] = None) -> Dict:
        return {"kind": "pod_drift", "hop": self.hop, "op": self.op,
                "axis": self.axis, "link": self.link,
                "dtype": self.dtype,
                "predicted_ms": round(self.predicted_ms, 6),
                "measured_ms": round(self.measured_ms, 6),
                "ratio": round(self.ratio, 4),
                "stale": self.stale(tolerance),
                "fingerprint": self.fingerprint,
                "wall_time": (time.time() if wall_time is None
                              else wall_time)}


@dataclasses.dataclass
class CommDriftReport:
    """All hops' drift rows + the verdict."""

    hops: List[HopDrift]
    tolerance: float              # allowed measured/predicted ratio band
    plan_source: str              # "measured" | "defaults"
    mesh_name: Optional[str] = None

    @property
    def stale(self) -> bool:
        """True when any hop left the tolerance band — the committed
        link model no longer describes this fabric."""
        return any(h.stale(self.tolerance) for h in self.hops)

    @property
    def drift_ratio(self) -> float:
        """Worst symmetric drift over the hops: ``max(ratio, 1/ratio)``
        of the worst hop (1.0 = perfect agreement) — the bench /
        sentinel scalar."""
        worst = 1.0
        for h in self.hops:
            r = h.ratio
            worst = max(worst, r, 1.0 / max(r, 1e-9))
        return worst

    def stale_hops(self) -> List[HopDrift]:
        return [h for h in self.hops if h.stale(self.tolerance)]

    def advice(self) -> Optional[str]:
        if not self.stale:
            return None
        sites = ", ".join(h.fingerprint for h in self.stale_hops())
        return (f"link model {self.mesh_name or '(unnamed)'} is stale "
                f"at {sites}: re-calibrate with scripts/link_probe.py "
                f"and re-plan (plan_comm) against the new MeshModel")

    def to_events(self, wall_time: Optional[float] = None) -> List[Dict]:
        """``kind="pod_drift"`` events (podview channel), hop order."""
        wt = time.time() if wall_time is None else wall_time
        return [h.to_event(self.tolerance, wall_time=wt)
                for h in self.hops]

    def table(self) -> str:
        lines = [f"{'hop':<4} {'op':<15} {'axis/link':<14} "
                 f"{'dtype':<6} {'pred_ms':>10} {'meas_ms':>10} "
                 f"{'ratio':>7} {'stale':>6}"]
        for h in self.hops:
            lines.append(
                f"{h.hop:<4} {h.op:<15} "
                f"{h.axis + '/' + h.link:<14} "
                f"{h.dtype or 'fp32':<6} {h.predicted_ms:>10.4f} "
                f"{h.measured_ms:>10.4f} {h.ratio:>7.2f} "
                f"{str(h.stale(self.tolerance)):>6}")
        verdict = self.advice() or (
            f"link model holds (worst drift "
            f"{self.drift_ratio:.2f}x <= {self.tolerance:.1f}x)")
        return "\n".join(lines + [verdict])


def measure_hops(plan, mesh, grad_bytes: Optional[int] = None, *,
                 iters: int = 3) -> List[float]:
    """Best-of-``iters`` seconds per hop of ``plan``, executed on
    ``mesh`` (which must carry the plan's axis names at the plan's
    sizes). Each hop runs as its own jitted shard_map collective over
    a payload of the hop's wire bytes — the dtype compression is
    modeled by shrinking the buffer, and a multi-collective hop
    (``n_collectives() > 1``, the int8 decompositions) is timed as
    that many back-to-back issues so it pays its α's like the model
    says it does. One warm call per hop absorbs compile."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.monitor import linkbench
    from apex_tpu.parallel import comm as _comm

    nbytes = grad_bytes if grad_bytes is not None else \
        (plan.grad_bytes or 0)
    elems = max(int(nbytes) // 4, 1)
    out: List[float] = []
    for hop, hop_elems in zip(plan.hops, plan._hop_elems(elems)):
        payload = _comm.dtype_wire_bytes(hop_elems, hop.dtype,
                                         plan.compress_block)
        n = max(payload // 4, hop.size)
        n += (-n) % hop.size          # divisible by the axis
        fn = linkbench._collective(hop.op, mesh, hop.axis)
        x = jnp.arange(n, dtype=jnp.float32)
        reps = hop.n_collectives()
        jax.block_until_ready(fn(x))  # warm: compile + first run
        best = float("inf")
        for _ in range(max(int(iters), 1)):
            t0 = time.perf_counter()
            for _ in range(reps):
                jax.block_until_ready(fn(x))
            best = min(best, time.perf_counter() - t0)
        out.append(best)
    return out


def wire_from_pod(pod, plan, *,
                  min_samples: int = 1) -> Optional[List[float]]:
    """Per-hop median wire seconds joined from a
    :class:`~apex_tpu.trace.PodTimeline`, or None when the pod carries
    no matching spans (e.g. the sub-spans ran at trace time under
    jit).

    The join is positional against the span names
    ``hierarchical_sync`` emits: each bucket yields one collective
    sub-span per hop, named by link class (``ici``/``dcn``) in hop
    order, so the j-th occurrence of a name within a step maps to hop
    position ``positions[name][j % len(positions[name])]``."""
    positions: Dict[str, List[int]] = {}
    for i, hop in enumerate(plan.hops):
        positions.setdefault(hop.link, []).append(i)
    per_hop: List[List[float]] = [[] for _ in plan.hops]
    for c in pod.collective_skew():
        pos = positions.get(c.name)
        if not pos:
            continue
        hop_i = pos[c.occurrence % len(pos)]
        per_hop[hop_i].append(c.wire_ms * 1e-3)
    if any(len(v) < max(int(min_samples), 1) for v in per_hop):
        return None
    out = []
    for v in per_hop:
        s = sorted(v)
        mid = len(s) // 2
        out.append(s[mid] if len(s) % 2
                   else (s[mid - 1] + s[mid]) / 2.0)
    return out


def compare(plan, measured_s: Sequence[float], *,
            tolerance: float = 4.0,
            grad_bytes: Optional[int] = None) -> CommDriftReport:
    """Join measured per-hop seconds against the plan's predicted
    :meth:`~apex_tpu.parallel.CommPlan.hop_seconds` into a
    :class:`CommDriftReport`. ``tolerance`` is the allowed
    measured/predicted ratio band (symmetric: ``tolerance=4`` accepts
    0.25x–4x — α–β models are order-of-magnitude instruments; tighten
    it on fabrics you trust)."""
    predicted = plan.hop_seconds(grad_bytes)
    if len(measured_s) != len(plan.hops):
        raise ValueError(
            f"measured {len(measured_s)} hops, plan has "
            f"{len(plan.hops)} ({plan.describe()})")
    rows = [HopDrift(hop=i, op=h.op, axis=h.axis, link=h.link,
                     dtype=h.dtype, predicted_ms=p * 1e3,
                     measured_ms=float(m) * 1e3)
            for i, (h, p, m) in enumerate(
                zip(plan.hops, predicted, measured_s))]
    return CommDriftReport(hops=rows, tolerance=float(tolerance),
                           plan_source=plan.source,
                           mesh_name=plan.mesh_name)
