"""Cross-rank straggler detection from shared-filesystem heartbeats.

The hang watchdog (:mod:`apex_tpu.trace.watchdog`) catches the binary
failure — no step for ``deadline_s`` — but a pod burns money long
before that: one rank 30% slower than its peers gates every collective
at its pace and nothing raises. This module is the early-warning tier
below the hard stall deadline:

- each rank appends one tiny **heartbeat** record per finished step to
  its own file under a shared directory (:class:`HeartbeatWriter`,
  reusing the ckpt shared-fs rank-file pattern — one file per rank, no
  cross-rank writes — with the jittered
  :func:`apex_tpu.utils.backoff.backoff_sleep` on transient IO errors
  so N ranks never hammer the metadata server in lockstep);
- a **lockstep reader** (:class:`StragglerDetector`) aligns the ranks'
  heartbeats by step and, per common step, computes each rank's
  step-duration lag against the median rank (durations come from each
  host's own clock, so a constant cross-host clock offset cancels —
  arrival times are only the fallback for duration-less beats); a
  rank is a *persistent laggard* when its robust z-score (``lag /
  (1.4826·MAD + floor)`` — the guard's MAD recipe) exceeds the
  threshold for ``hysteresis`` consecutive newest steps (the
  guard-style debounce: one slow GC pause never flags);
- a flagged report names **the slowest span class on the lagging
  rank** from its own heartbeat's span breakdown (the flight-record
  data it already writes) — "rank 3 is 400 ms behind and spends it in
  ``data/load``" is actionable, "rank 3 is slow" is not;
- :class:`StragglerWatch` polls the detector on a daemon thread and
  feeds :meth:`apex_tpu.trace.HangWatchdog.early_warning` — the
  watchdog's alerting hook fires with a ``straggler`` warning while
  the run is still making (slow) progress, long before the stall
  deadline would.

Events are ``kind="straggler"`` JSONL on the goodput channel
(``MetricsLogger(goodput_sink=...)``;
``scripts/check_metrics_schema.py --kind goodput`` validates). Typical
wiring, per rank::

    tracer = trace.Tracer()
    hb = trace.HeartbeatWriter(shared_dir)     # rank-inferred
    tracer.subscribe(hb.on_step)
    # rank 0 (or a sidecar) additionally reads:
    det = trace.StragglerDetector(shared_dir)
    watch = trace.StragglerWatch(det, watchdog=wd,
                                 event_sink=logger.record_goodput)
    watch.start()
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from apex_tpu.utils.backoff import backoff_sleep

__all__ = ["HeartbeatWriter", "StragglerDetector", "StragglerReport",
           "StragglerWatch", "read_heartbeats", "gc_stale_heartbeats"]

_HB_PREFIX = "hb.rank"


def _rank_default() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return int(os.environ.get("RANK", "0"))


def heartbeat_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"{_HB_PREFIX}{rank:05d}.jsonl")


class HeartbeatWriter:
    """Append one heartbeat line per finished step to this rank's file.

    Subscribe :meth:`on_step` to a Tracer (or call :meth:`beat`
    manually). Each record is ``{"step", "rank", "wall_time",
    "dur_ms", "spans": {name: ms}}`` — small enough that a per-step
    append on a shared fs is noise next to the step itself. Appends
    retry ``attempts`` times through the shared jittered backoff and
    then drop the beat (a lost heartbeat must never break the train
    loop — the reader treats a silent rank as the watchdog's problem,
    not this tier's)."""

    def __init__(self, directory: str, rank: Optional[int] = None, *,
                 attempts: int = 3, generation: Optional[int] = None):
        self.rank = _rank_default() if rank is None else int(rank)
        self.directory = directory
        self.attempts = max(int(attempts), 1)
        #: cluster-epoch fence token stamped on every beat (see
        #: apex_tpu.cluster): a reader scoped to the current generation
        #: ignores a dead previous attempt's records instead of
        #: mistaking them for a silent rank. None = untagged (treated
        #: as generation 0 by generation-scoped readers).
        self.generation = generation
        os.makedirs(directory, exist_ok=True)
        self.path = heartbeat_path(directory, self.rank)
        self.n_written = 0
        self.n_dropped = 0

    def set_generation(self, generation: Optional[int]) -> None:
        """Re-tag after a coordinated bump (survivors keep their writer
        across the epoch change)."""
        self.generation = generation

    def on_step(self, st) -> None:
        """Tracer subscriber (:class:`~apex_tpu.trace.StepTrace`)."""
        spans: Dict[str, float] = {}
        for s in st.spans:
            spans[s.name] = spans.get(s.name, 0.0) + s.dur_ms
        self.beat(st.step, dur_ms=st.dur_ms, spans=spans)

    def beat(self, step: Optional[int], *, dur_ms: Optional[float] = None,
             spans: Optional[Dict[str, float]] = None,
             wall_time: Optional[float] = None) -> bool:
        rec = {"step": step, "rank": self.rank,
               "wall_time": time.time() if wall_time is None else wall_time,
               "dur_ms": round(dur_ms, 4) if dur_ms is not None else None,
               "spans": {k: round(v, 4)
                         for k, v in (spans or {}).items()}}
        if self.generation is not None:
            rec["generation"] = int(self.generation)
        line = json.dumps(rec) + "\n"
        for attempt in range(self.attempts):
            try:
                with open(self.path, "a") as f:
                    f.write(line)
                self.n_written += 1
                return True
            except OSError:
                if attempt + 1 < self.attempts:
                    backoff_sleep(attempt, cap_s=0.2)
        self.n_dropped += 1
        return False


def read_heartbeats(directory: str, *,
                    generation: Optional[int] = None
                    ) -> Dict[int, Dict[int, Dict]]:
    """``{rank: {step: record}}`` over every rank file present.

    Malformed lines (a reader racing a writer's partial append) are
    skipped; a later complete record for the same step wins.

    ``generation`` scopes the read to one cluster epoch: records whose
    ``generation`` tag differs (untagged records count as generation 0)
    are ignored, and a rank whose file carries NO current-generation
    records is omitted entirely — a dead previous attempt's heartbeats
    must not read as a live-but-silent rank of the new epoch (the
    exact bug an ``elastic_run`` restart over stale files exhibits).
    """
    out: Dict[int, Dict[int, Dict]] = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if not (name.startswith(_HB_PREFIX) and name.endswith(".jsonl")):
            continue
        try:
            rank = int(name[len(_HB_PREFIX):-len(".jsonl")])
        except ValueError:
            continue
        per: Dict[int, Dict] = {}
        try:
            with open(os.path.join(directory, name)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue           # torn tail of a live append
                    if generation is not None:
                        g = rec.get("generation")
                        g = g if isinstance(g, int) else 0
                        if g != int(generation):
                            continue       # another epoch's record
                    step = rec.get("step")
                    if isinstance(step, int):
                        per[step] = rec
        except OSError:
            continue
        if per:
            out[rank] = per
    return out


def gc_stale_heartbeats(directory: str,
                        current_generation: int) -> List[str]:
    """Delete heartbeat files whose NEWEST record belongs to an older
    generation — the ``elastic_run`` relaunch hygiene pass (see
    :func:`apex_tpu.cluster.relaunch`): without it, a rank that died
    in generation N leaves a file whose last beat reads as a "silent
    rank" to every future detector poll. A file carrying any
    current-generation record is kept (a survivor's history is still
    its history). Returns removed paths."""
    removed: List[str] = []
    cur = int(current_generation)
    for rank, per in read_heartbeats(directory).items():
        # one read serves both questions (a second generation-scoped
        # pass would double the shared-fs traffic of the restart path)
        if any((rec.get("generation") if isinstance(
                rec.get("generation"), int) else 0) == cur
               for rec in per.values()):
            continue               # a survivor's history stays
        p = heartbeat_path(directory, rank)
        try:
            os.remove(p)
            removed.append(p)
        except OSError:
            pass
    return removed


@dataclasses.dataclass
class StragglerReport:
    """One persistent laggard: who, how far behind, and where it
    spends the time."""

    rank: int
    step: int                     # newest common step analyzed
    lag_ms: float                 # arrival lag vs the median rank
    z: float                      # robust z-score of that lag
    consecutive: int              # flagged steps in a row (newest back)
    slowest_span: Optional[str]   # largest span on the laggard's beat
    span_class: Optional[str]     # its goodput bucket (classify_span)
    slowest_span_ms: Optional[float]
    n_ranks: int

    def to_event(self) -> Dict:
        return {"kind": "straggler", "step": self.step, "rank": self.rank,
                "lag_ms": round(self.lag_ms, 4), "z": round(self.z, 4),
                "consecutive": self.consecutive,
                "slowest_span": self.slowest_span,
                "span_class": self.span_class,
                "slowest_span_ms": (round(self.slowest_span_ms, 4)
                                    if self.slowest_span_ms is not None
                                    else None),
                "n_ranks": self.n_ranks, "wall_time": time.time()}


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


class StragglerDetector:
    """Lockstep reader over the heartbeat directory.

    Per common step each rank's **step duration** (the host-measured
    ``dur_ms`` in its heartbeat) is compared against the median rank:
    ``lag = dur_rank − median(dur)``, ``z = lag / (1.4826·MAD +
    lag_floor_ms)`` (MAD over the ranks' lags; the floor keeps tightly
    synchronized meshes from flagging microsecond jitter — the same
    denominator-regularization recipe as the guard's spike detector).
    Durations are measured by each host's own monotonic clock, so a
    constant cross-host wall-clock offset — indistinguishable from a
    laggard if arrival times were compared — cancels entirely; the
    ``wall_time`` arrival comparison is only the fallback for beats
    that carry no ``dur_ms``. A rank is reported only after
    ``hysteresis`` consecutive newest steps above ``z_threshold`` AND
    ``lag_floor_ms`` of absolute lag — statistically slow but cheap is
    not actionable."""

    def __init__(self, directory: str, *, window: int = 16,
                 z_threshold: float = 4.0, hysteresis: int = 3,
                 lag_floor_ms: float = 1.0, min_ranks: int = 2,
                 generation: Optional[int] = None):
        self.directory = directory
        self.window = max(int(window), 1)
        self.z_threshold = float(z_threshold)
        self.hysteresis = max(int(hysteresis), 1)
        self.lag_floor_ms = float(lag_floor_ms)
        self.min_ranks = max(int(min_ranks), 2)
        #: when set, only heartbeats of this cluster epoch are judged —
        #: a dead previous attempt's records neither flag laggards nor
        #: read as silent ranks (pass the current generation after an
        #: elastic relaunch; see apex_tpu.cluster)
        self.generation = generation

    def check(self) -> List[StragglerReport]:
        """Read every rank's heartbeats and report persistent laggards
        (empty = healthy, or not enough ranks/steps to judge)."""
        beats = read_heartbeats(self.directory,
                                generation=self.generation)
        if len(beats) < self.min_ranks:
            return []
        common = set.intersection(*(set(per) for per in beats.values()))
        if not common:
            return []
        steps = sorted(common)[-self.window:]
        ranks = sorted(beats)
        # per analyzed step: {rank: (lag_ms, z)}
        lag_z: List[Dict[int, tuple]] = []
        for step in steps:
            # step durations, each measured by its own host's clock —
            # immune to cross-host wall-clock offset
            vals = {r: beats[r][step].get("dur_ms") for r in ranks}
            if any(not isinstance(v, (int, float))
                   for v in vals.values()):
                # fallback: arrival wall times (clock-skew-sensitive;
                # only for heartbeats written without a duration)
                ts = {r: beats[r][step].get("wall_time") for r in ranks}
                if any(not isinstance(t, (int, float))
                       for t in ts.values()):
                    continue
                vals = {r: t * 1e3 for r, t in ts.items()}
            med = _median(list(vals.values()))
            lags = {r: v - med for r, v in vals.items()}
            mad = _median([abs(l) for l in lags.values()])
            denom = 1.4826 * mad + self.lag_floor_ms
            lag_z.append({r: (lags[r], lags[r] / denom) for r in ranks})
        if not lag_z:
            return []
        out: List[StragglerReport] = []
        newest = steps[-1]
        for r in ranks:
            consecutive = 0
            for per_step in reversed(lag_z):
                lag, z = per_step[r]
                if z > self.z_threshold and lag > self.lag_floor_ms:
                    consecutive += 1
                else:
                    break
            if consecutive < self.hysteresis:
                continue
            lag, z = lag_z[-1][r]
            spans = beats[r][newest].get("spans") or {}
            slowest = max(spans, key=spans.get) if spans else None
            from apex_tpu.monitor.goodput import classify_span
            out.append(StragglerReport(
                rank=r, step=newest, lag_ms=lag, z=z,
                consecutive=consecutive, slowest_span=slowest,
                span_class=(classify_span(slowest)
                            if slowest is not None else None),
                slowest_span_ms=(spans[slowest]
                                 if slowest is not None else None),
                n_ranks=len(ranks)))
        return out


class StragglerWatch:
    """Daemon-thread poller: detector → events + watchdog early warning.

    Every ``poll_s`` it runs :meth:`StragglerDetector.check`; each
    report is emitted through ``event_sink`` (wire
    ``MetricsLogger.record_goodput``) and handed to the watchdog's
    :meth:`~apex_tpu.trace.HangWatchdog.early_warning` — alerting tier
    only, never the escalation path (``on_stall`` stays the hard
    deadline's). Re-reports a still-lagging rank at most once per
    ``renotify_s``. ``recorder`` additionally feeds every report to
    :meth:`apex_tpu.trace.FlightRecorder.note_straggler`, so a later
    crash dump's header names the rank (and span) the pod was already
    waiting on — the renotify debounce does NOT apply there: the ring
    is bounded and forensics want the freshest picture."""

    def __init__(self, detector: StragglerDetector, *,
                 poll_s: float = 5.0, watchdog=None,
                 event_sink: Optional[Callable[[Dict], None]] = None,
                 renotify_s: float = 60.0, recorder=None):
        self.detector = detector
        self.poll_s = float(poll_s)
        self.watchdog = watchdog
        self.event_sink = event_sink
        self.recorder = recorder
        self.renotify_s = float(renotify_s)
        self._last_notified: Dict[int, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.flag_count = 0

    def poll_once(self) -> List[StragglerReport]:
        reports = self.detector.check()
        now = time.monotonic()
        for rep in reports:
            if self.recorder is not None:
                # undebounced: the crash-header ring wants every fresh
                # report, not one per renotify window
                self.recorder.note_straggler(rep.to_event())
            last = self._last_notified.get(rep.rank)
            if last is not None and now - last < self.renotify_s:
                continue
            self._last_notified[rep.rank] = now
            self.flag_count += 1
            ev = rep.to_event()
            if self.event_sink is not None:
                try:
                    self.event_sink(dict(ev))
                except Exception:
                    pass
            if self.watchdog is not None:
                self.watchdog.early_warning(ev)
        return reports

    def start(self) -> "StragglerWatch":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="apex_tpu.trace.straggler",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(self.poll_s * 2, 1.0))
        self._thread = None

    def __enter__(self) -> "StragglerWatch":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:
                pass          # a broken poll must not kill the daemon
