"""XSpace (xplane.pb) trace parser — the pyprof.parse equivalent.

The reference parses nvprof's SQLite database and correlates kernels with
NVTX ranges (`apex/pyprof/parse/parse.py`, `db.py`, `kernel.py`). The TPU
analogue: ``jax.profiler.trace`` writes an XSpace protobuf per host
(``*.xplane.pb``) containing one plane per device with an "XLA Ops" line —
one timed event per executed HLO instruction, whose metadata carries the
full HLO text (op name, shapes, fusion kind). This module decodes that
file into per-op records and aggregates them.

Decoding prefers the xplane proto bundled with tensorflow
(``tensorflow.tsl.profiler.protobuf.xplane_pb2``) — imported lazily so
apex_tpu itself never depends on tensorflow — and falls back to a
**minimal pure-python wire-format decoder** (:func:`decode_xspace`)
covering exactly the fields this parser reads (plane/line/event
hierarchy + event metadata), so CI parses committed ``*.xplane.pb``
fixtures without tensorflow (``tests/fixtures/``; set
``APEX_TPU_XPLANE_PURE=1`` to force the fallback).
"""

from __future__ import annotations

import dataclasses
import glob
import os
import re
from typing import Dict, List, Optional

__all__ = ["OpRecord", "TraceProfile", "parse_trace", "latest_xplane",
           "COLLECTIVE_PREFIXES", "decode_xspace"]

# HLO instruction text → opcode: "%fusion.3 = f32[8]{0} fusion(...)" → the
# word after the result shape. Shapes may be tuples "(f32[...], u32[])"
# whose layout annotations themselves contain parens ("T(8,128)S(1)"), so
# the tuple alternative must match balanced parens one level deep.
_OPCODE_RE = re.compile(
    r"^%?(?P<name>[^ ]+) = (?:\((?:[^()]|\([^()]*\))*\)|[^ ]+) "
    r"(?P<opcode>[\w-]+)\(")

# named-scope path in HLO op metadata: metadata={op_name="jit(f)/amp/fwd/..."}
_OP_NAME_RE = re.compile(r'op_name="([^"]+)"')

# transform-wrapper path components jax interleaves with user scopes —
# dropped by by_scope() so "jit(step)/transpose(jvp(amp))/fwd" and
# "jit(step)/amp/fwd" aggregate under the same user-named key
_TRANSFORM_WRAPPERS = ("jit(", "transpose(", "jvp(", "vmap(", "pmap(",
                      "shard_map(", "scan(", "while(", "remat(")


def strip_scope(op_name: str) -> str:
    """User-named components of a metadata scope path:
    ``jit(step)/transpose(jvp(amp/fwd))/tanh`` → ``amp/fwd/tanh``.

    A user scope containing ``/`` splits the wrapper parens across path
    components, so besides dropping self-contained wrapper components
    each kept fragment is scrubbed of wrapper prefixes and dangling
    parens. Shared by :meth:`TraceProfile.by_scope` and the
    buffer-attribution in :mod:`apex_tpu.prof.memory`."""
    parts = []
    for p in op_name.split("/"):
        if (p.startswith(_TRANSFORM_WRAPPERS)
                and p.count("(") == p.count(")")):
            continue          # self-contained wrapper, e.g. "jit(step)"
        while p.startswith(_TRANSFORM_WRAPPERS):
            p = p.split("(", 1)[1]      # fragment: keep the user content
        p = p.strip(")")
        if p:
            parts.append(p)
    return "/".join(parts)

# The one canonical list of collective opcode prefixes — longest-prefix
# entries first so e.g. ragged-all-to-all is not folded into all-to-all.
# apex_tpu.monitor.collectives buckets traffic by the same tuple; keep
# trace categorization and live accounting in lockstep here.
COLLECTIVE_PREFIXES = (
    "ragged-all-to-all",
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
    "collective-broadcast",
)

_CATEGORIES = (
    ("convolution", "conv"),
    ("dot", "gemm"),
) + tuple((p, "collective") for p in COLLECTIVE_PREFIXES) + (
    ("copy", "copy"),
    ("fusion", "fusion"),
    ("custom-call", "custom-call"),
    ("scatter", "scatter"),
    ("reduce", "reduction"),
    ("sort", "sort"),
    ("dynamic-update-slice", "slice"),     # before dynamic-slice would
    ("dynamic-slice", "slice"),            # NOT prefix-match it, but keep
    ("while", "control-flow"),             # the specific one first anyway
)


def _categorize(opcode: str, hlo_text: str) -> str:
    for prefix, cat in _CATEGORIES:
        if opcode.startswith(prefix):
            if cat == "fusion":
                m = re.search(r"kind=(\w+)", hlo_text)
                return f"fusion.{m.group(1)[1:].lower()}" if m else "fusion"
            return cat
    return "other"


@dataclasses.dataclass
class OpRecord:
    """Aggregated timing for one HLO instruction across a trace."""

    name: str           # instruction name, e.g. "fusion.31"
    opcode: str         # HLO opcode, e.g. "fusion", "convolution"
    category: str       # coarse category (gemm/conv/fusion.*/collective/...)
    occurrences: int
    total_us: float
    hlo: str            # full HLO instruction text

    @property
    def avg_us(self) -> float:
        return self.total_us / max(self.occurrences, 1)


@dataclasses.dataclass
class TraceProfile:
    """Parsed device activity of one xplane.pb."""

    path: str
    device: str                       # plane name, e.g. "/device:TPU:0"
    ops: List[OpRecord]               # sorted by total_us desc
    module_runs: int                  # XLA Modules line event count
    module_total_us: float            # wall device time inside XLA modules

    def by_category(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for r in self.ops:
            out[r.category] = out.get(r.category, 0.0) + r.total_us
        return dict(sorted(out.items(), key=lambda kv: -kv[1]))

    def by_scope(self, depth: int = 2) -> Dict[str, float]:
        """Device time per named-scope prefix (``trace.span`` names).

        HLO op metadata carries the full scope path each op was traced
        under (``op_name="jit(step)/amp/fwd/conv"``); this aggregates
        ``total_us`` by the first ``depth`` path components after the
        ``jit(...)`` / transform wrappers — so ``trace.span("amp/fwd")``
        spans show up here with their *measured device* time, the
        counterpart of the tracer's host wall-clock timeline. Ops with
        no scope metadata land under ``"(unscoped)"``.
        """
        out: Dict[str, float] = {}
        for r in self.ops:
            m = _OP_NAME_RE.search(r.hlo)
            parts = strip_scope(m.group(1)).split("/") if m else []
            parts = [p for p in parts if p]
            key = "/".join(parts[:depth]) if parts else "(unscoped)"
            out[key] = out.get(key, 0.0) + r.total_us
        return dict(sorted(out.items(), key=lambda kv: -kv[1]))

    def table(self, top: int = 20) -> str:
        total = sum(r.total_us for r in self.ops) or 1.0
        lines = [f"{'op':<40} {'category':<16} {'count':>6} "
                 f"{'total_us':>12} {'avg_us':>10} {'%':>6}"]
        for r in self.ops[:top]:
            lines.append(
                f"{r.name[:40]:<40} {r.category:<16} {r.occurrences:>6} "
                f"{r.total_us:>12.1f} {r.avg_us:>10.2f} "
                f"{100 * r.total_us / total:>5.1f}%")
        return "\n".join(lines)


def latest_xplane(logdir: str) -> Optional[str]:
    """Newest ``*.xplane.pb`` under a profiler logdir, or None."""
    files = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                      recursive=True)
    return max(files, key=os.path.getmtime) if files else None


# --- minimal pure-python XSpace decoder --------------------------------------
#
# Protobuf wire format is stable and tiny to read: every field is a
# (tag = field_no << 3 | wire_type, payload) pair; messages are
# length-delimited. This decoder covers exactly the XSpace subset
# parse_trace consumes (field numbers pinned against the tsl proto:
# XSpace.planes=1; XPlane.name=2/lines=3/event_metadata=4 with map
# entries key=1/value=2; XLine.name=2/events=4; XEvent.metadata_id=1/
# duration_ps=3; XEventMetadata.id=1/name=2/display_name=4), so a
# committed fixture parses in CI without tensorflow.

class _Msg:
    """Attribute bag for decoded messages."""

    def __init__(self, **kw):
        self.__dict__.update(kw)


def _uvarint(buf: bytes, i: int):
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) — value is an int for
    varints/fixed, bytes for length-delimited."""
    i = 0
    while i < len(buf):
        tag, i = _uvarint(buf, i)
        fno, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _uvarint(buf, i)
        elif wt == 1:
            v = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        elif wt == 2:
            n, i = _uvarint(buf, i)
            if i + n > len(buf):      # slicing would silently truncate
                raise ValueError(f"truncated field {fno} "
                                 f"({n} bytes past end)")
            v = buf[i:i + n]
            i += n
        elif wt == 5:
            v = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fno, wt, v


def _decode_event(buf: bytes) -> _Msg:
    ev = _Msg(metadata_id=0, duration_ps=0)
    for fno, _wt, v in _fields(buf):
        if fno == 1:
            ev.metadata_id = v
        elif fno == 3:
            ev.duration_ps = v
    return ev


def _decode_line(buf: bytes) -> _Msg:
    line = _Msg(name="", events=[])
    for fno, _wt, v in _fields(buf):
        if fno == 2:
            line.name = v.decode("utf-8", "replace")
        elif fno == 4:
            line.events.append(_decode_event(v))
    return line


def _decode_event_metadata(buf: bytes) -> _Msg:
    md = _Msg(id=0, name="", display_name="")
    for fno, _wt, v in _fields(buf):
        if fno == 1:
            md.id = v
        elif fno == 2:
            md.name = v.decode("utf-8", "replace")
        elif fno == 4:
            md.display_name = v.decode("utf-8", "replace")
    return md


def _decode_plane(buf: bytes) -> _Msg:
    plane = _Msg(name="", lines=[], event_metadata={})
    for fno, _wt, v in _fields(buf):
        if fno == 2:
            plane.name = v.decode("utf-8", "replace")
        elif fno == 3:
            plane.lines.append(_decode_line(v))
        elif fno == 4:
            key, md = 0, None
            for efno, _ewt, ev in _fields(v):     # map entry
                if efno == 1:
                    key = ev
                elif efno == 2:
                    md = _decode_event_metadata(ev)
            if md is not None:
                plane.event_metadata[key or md.id] = md
    return plane


def decode_xspace(data: bytes) -> _Msg:
    """Decode a serialized XSpace with the pure-python reader — the
    tensorflow-free fallback behind :func:`parse_trace`."""
    xs = _Msg(planes=[])
    for fno, _wt, v in _fields(data):
        if fno == 1:
            xs.planes.append(_decode_plane(v))
    return xs


def _load_xspace(path: str):
    if os.environ.get("APEX_TPU_XPLANE_PURE") != "1":
        try:
            from tensorflow.tsl.profiler.protobuf import xplane_pb2
            xs = xplane_pb2.XSpace()
            with open(path, "rb") as f:
                xs.ParseFromString(f.read())
            return xs
        except OSError:
            raise                     # file problems are not decode paths
        except Exception:
            # no/broken tensorflow (a partial install can raise far
            # more than ImportError): the minimal decoder below
            pass
    try:
        with open(path, "rb") as f:
            return decode_xspace(f.read())
    except (ValueError, IndexError) as e:
        raise ValueError(
            f"could not decode {path!r} as an XSpace proto (pure-python "
            f"fallback): {e!r}. If tensorflow is available its bundled "
            "proto (tensorflow.tsl.profiler.protobuf.xplane_pb2) handles "
            "schema extensions; without trace files at all, use the "
            "XLA-cost-analysis path instead — apex_tpu.prof.hlo."
            "op_estimates / cost_analysis on the jitted step (the "
            "reference degrades its scaler the same way, "
            "apex/amp/scaler.py:39-52)") from e


def parse_trace(logdir_or_file: str, device_index: int = 0) -> TraceProfile:
    """Parse a profiler logdir (or a specific xplane.pb) into per-op records.

    Aggregates every "XLA Ops" event on the selected device plane by HLO
    instruction. On non-TPU backends the device plane may be absent; the
    result then has empty ``ops`` (and ``module_runs == 0``) rather than
    raising, so callers can degrade gracefully.
    """
    path = logdir_or_file
    if os.path.isdir(path):
        found = latest_xplane(path)
        if found is None:
            raise FileNotFoundError(
                f"no *.xplane.pb under {logdir_or_file!r}; did the "
                "jax.profiler trace finish?")
        path = found
    xs = _load_xspace(path)

    device_planes = [p for p in xs.planes if "/device:" in p.name
                     and "CUSTOM" not in p.name and p.lines]
    if not device_planes:
        return TraceProfile(path=path, device="", ops=[], module_runs=0,
                            module_total_us=0.0)
    plane = device_planes[min(device_index, len(device_planes) - 1)]

    agg: Dict[int, OpRecord] = {}
    module_runs, module_total_ps = 0, 0
    for line in plane.lines:
        if line.name == "XLA Modules":
            module_runs = len(line.events)
            module_total_ps = sum(e.duration_ps for e in line.events)
            continue
        if line.name != "XLA Ops":
            continue
        for ev in line.events:
            rec = agg.get(ev.metadata_id)
            if rec is None:
                md = plane.event_metadata[ev.metadata_id]
                text = md.name or md.display_name
                m = _OPCODE_RE.match(text)
                name = m.group("name") if m else text[:40]
                opcode = m.group("opcode") if m else "unknown"
                rec = agg[ev.metadata_id] = OpRecord(
                    name=name, opcode=opcode,
                    category=_categorize(opcode, text),
                    occurrences=0, total_us=0.0, hlo=text)
            rec.occurrences += 1
            rec.total_us += ev.duration_ps / 1e6
    ops = sorted(agg.values(), key=lambda r: -r.total_us)
    return TraceProfile(path=path, device=plane.name, ops=ops,
                        module_runs=module_runs,
                        module_total_us=module_total_ps / 1e6)
