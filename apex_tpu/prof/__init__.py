"""apex_tpu.prof — profiling/tracing subsystem (the pyprof equivalent).

The reference's pyprof pipeline is three offline stages
(`apex/pyprof/nvtx/nvmarker.py` annotate → nvprof → `parse/` → `prof/`
FLOPs analyzers). TPU-native, the same capability is:

- :mod:`~apex_tpu.prof.annotate` — ``scope``/``annotate`` named-scope
  helpers + ``annotate_modules`` flax interceptor (arg shapes/dtypes per
  module call, reversible, no monkey-patching);
- :mod:`~apex_tpu.prof.xplane` — parse ``jax.profiler`` xplane.pb traces
  into per-HLO-op timing records;
- :mod:`~apex_tpu.prof.hlo` — XLA cost analysis + per-instruction
  FLOPs/bytes estimates from optimized HLO;
- :mod:`~apex_tpu.prof.report` — ``profile_step`` one-stop capture →
  parse → MFU report.
"""

from apex_tpu.prof.annotate import (CallRecord, annotate, annotate_modules,
                                    scope)
from apex_tpu.prof.hlo import (OpEstimate, compiled_hlo, cost_analysis,
                               op_estimates)
from apex_tpu.prof.report import (PEAK_FLOPS, StepReport, device_peak_flops,
                                  profile_step, trace)
from apex_tpu.prof.xplane import OpRecord, TraceProfile, parse_trace

__all__ = [
    "CallRecord", "annotate", "annotate_modules", "scope",
    "OpEstimate", "compiled_hlo", "cost_analysis", "op_estimates",
    "PEAK_FLOPS", "StepReport", "device_peak_flops", "profile_step",
    "trace",
    "OpRecord", "TraceProfile", "parse_trace",
]
