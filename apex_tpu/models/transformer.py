"""BERT-style transformer encoder built on the fused ops.

The reference's transformer story is its kernel set — fused MHA
(`apex/contrib/multihead_attn`), FusedLayerNorm, fused softmax-CE, and the
"BERT-Large pretraining with FusedLAMB" config in BASELINE.json. This
module assembles those pieces into the encoder those configs describe:
pre/post-LN blocks over :func:`apex_tpu.ops.fused_layer_norm_affine`,
attention through :mod:`apex_tpu.ops.attention` (fused blockwise softmax
when available), and an MLM head matching
:func:`apex_tpu.ops.softmax_cross_entropy_loss`.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn

from apex_tpu import ops


class FusedLayerNormModule(nn.Module):
    features: int
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x):
        w = self.param("scale", nn.initializers.ones, (self.features,),
                       jnp.float32)
        b = self.param("bias", nn.initializers.zeros, (self.features,),
                       jnp.float32)
        return ops.fused_layer_norm_affine(x, w, b, self.epsilon)


class MultiheadAttention(nn.Module):
    """Thin wrapper over :class:`apex_tpu.ops.SelfMultiheadAttn` taking a
    boolean mask (True = attend) instead of an additive bias — one
    attention implementation for the whole framework."""
    hidden: int
    heads: int
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True):
        from apex_tpu.ops.multihead_attn import SelfMultiheadAttn

        bias = None
        if mask is not None:
            bias = jnp.where(mask, 0.0, -1e9).astype(jnp.float32)
        attn = SelfMultiheadAttn(self.hidden, self.heads,
                                 dropout=self.dropout)
        return attn(x, attn_bias=bias, deterministic=deterministic)


class TransformerLayer(nn.Module):
    hidden: int
    heads: int
    ffn_hidden: int
    dropout: float = 0.0
    pre_ln: bool = False

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True):
        attn = MultiheadAttention(self.hidden, self.heads, self.dropout)
        ln1 = FusedLayerNormModule(self.hidden)
        ln2 = FusedLayerNormModule(self.hidden)
        if self.pre_ln:
            x = x + attn(ln1(x), mask, deterministic)
            y = ln2(x)
            y = nn.Dense(self.ffn_hidden)(y)
            y = jax.nn.gelu(y)
            y = nn.Dense(self.hidden)(y)
            return x + y
        x = ln1(x + attn(x, mask, deterministic))
        y = nn.Dense(self.ffn_hidden)(x)
        y = jax.nn.gelu(y)
        y = nn.Dense(self.hidden)(y)
        return ln2(x + y)


class BertEncoder(nn.Module):
    """BERT-style encoder: embeddings + N layers + optional MLM head."""
    vocab_size: int
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    ffn_hidden: Optional[int] = None
    max_len: int = 512
    dropout: float = 0.0

    @nn.compact
    def __call__(self, tokens, attn_mask=None, deterministic: bool = True):
        ffn = self.ffn_hidden or 4 * self.hidden
        emb = nn.Embed(self.vocab_size, self.hidden, name="tok_emb")(tokens)
        pos = self.param("pos_emb", nn.initializers.normal(0.02),
                         (self.max_len, self.hidden), jnp.float32)
        x = emb + pos[None, :tokens.shape[1]].astype(emb.dtype)
        x = FusedLayerNormModule(self.hidden, epsilon=1e-12)(x)
        mask = None
        if attn_mask is not None:
            mask = attn_mask[:, None, None, :].astype(bool)
        for _ in range(self.layers):
            x = TransformerLayer(self.hidden, self.heads, ffn,
                                 self.dropout)(x, mask, deterministic)
        return x


def BertLarge(vocab_size: int = 30522, **kw):
    return BertEncoder(vocab_size, hidden=1024, layers=24, heads=16, **kw)


def mlm_loss(encoder, variables, tokens, labels, smoothing=0.0):
    """Masked-LM loss over the fused softmax-CE (labels < 0 = unmasked)."""
    hidden = encoder.apply(variables, tokens)
    vocab = encoder.vocab_size
    emb = variables["params"]["tok_emb"]["embedding"]
    logits = hidden @ emb.T.astype(hidden.dtype)
    losses = ops.softmax_cross_entropy_loss(logits, labels, smoothing)
    n = jnp.maximum(jnp.sum(labels >= 0), 1)
    return jnp.sum(losses) / n
