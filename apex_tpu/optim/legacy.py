"""Legacy contrib optimizer surface — externally-scaled gradients.

The reference's deprecated ``apex.contrib.optimizers`` classes
(`fused_adam.py:64-206`, `fused_sgd.py`, `fused_lamb.py`) take
still-scaled gradients directly in ``step(grads=..., scale=...,
output_params=...)`` and unscale INSIDE the kernel, optionally writing a
reduced-precision copy of the updated params in the same pass — the API
their ``FP16_Optimizer`` (`fp16_optimizer.py:4-243`) drives with
flattened grads.

Here the same capability rides the modern arena kernels, which already
fuse ``grad_scale`` (the 1/scale) and ``param_copy_dtype`` (the
``output_params`` copy-out): these classes only adapt the legacy call
shape. Deprecated; prefer ``apex_tpu.optim.Fused*`` + ``amp.Amp``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu import arena
from apex_tpu.ops import optim_kernels as K
from apex_tpu.optim.fused import FusedOptState, Scalar


class _LegacyFused:
    """Shared shape of the deprecated surface: ``step(grads, state,
    params, scale=..., output_dtype=...)`` with in-kernel unscale."""

    def init(self, params) -> FusedOptState:
        spec = arena.plan(params)
        slots = {name: arena.zeros(spec, dtype=jnp.float32)
                 for name in self.slot_names}
        return FusedOptState(count=jnp.int32(0), slots=slots)

    def step(self, grads, state: FusedOptState, params, *,
             scale: float = 1.0, output_dtype=None):
        """One update from externally-scaled grads.

        ``scale`` divides the gradients inside the kernel
        (`fused_adam.py:76-78`: "factor to divide gradient tensor values
        by before applying to weights"). With ``output_dtype`` set, a
        reduced-precision copy of the new params is produced in the same
        pass (``output_params``) and returned as a third element.
        """
        spec = arena.plan(params)
        p_bufs = arena.flatten(params, spec)
        g_bufs = arena.flatten(grads, spec, cast=jnp.float32)
        count = state.count + 1
        lr = self.lr(count) if callable(self.lr) else self.lr
        inv = 1.0 / scale

        new_p, new_slots = {}, {n: {} for n in self.slot_names}
        copies = {}
        for part in spec.partitions:
            dt = part.dtype
            slots = {n: state.slots[n][dt] for n in self.slot_names}
            out = self._kernel(p_bufs[dt], g_bufs[dt], slots, count, lr,
                               inv, output_dtype)
            new_p[dt] = out[0]
            for n, v in zip(self.slot_names, out[1:1 + len(
                    self.slot_names)]):
                new_slots[n][dt] = v
            if output_dtype is not None:
                copies[dt] = out[-1]
        params_out = arena.unflatten(new_p, spec)
        st = FusedOptState(count=count, slots=new_slots)
        if output_dtype is None:
            return params_out, st
        # the copy buffers already carry output_dtype; unflatten only
        # reshapes per-leaf
        return params_out, st, arena.unflatten(copies, spec)


class FusedAdam(_LegacyFused):
    """Deprecated contrib FusedAdam (`contrib/optimizers/fused_adam.py:
    64-206`): Adam/AdamW with in-kernel unscale + optional fp16 param
    copy-out."""

    slot_names = ("m", "v")

    def __init__(self, lr: Scalar = 1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adam_w_mode=True,
                 bias_correction=True):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.bias_correction = bias_correction

    def _kernel(self, p, g, slots, count, lr, inv, output_dtype):
        return K.adam_update(
            p, g, slots["m"], slots["v"], lr=lr, beta1=self.beta1,
            beta2=self.beta2, eps=self.eps,
            weight_decay=self.weight_decay, step=count,
            adam_w_mode=self.adam_w_mode,
            bias_correction=self.bias_correction, grad_scale=inv,
            param_copy_dtype=output_dtype)


class FusedSGD(_LegacyFused):
    """Deprecated contrib FusedSGD (`contrib/optimizers/fused_sgd.py`):
    momentum SGD whose kernel unscales and emits the model copy — the
    ``materialize_master_grads`` interop path."""

    slot_names = ("m",)

    def __init__(self, lr: Scalar = 1e-3, momentum=0.0, dampening=0.0,
                 weight_decay=0.0, nesterov=False,
                 wd_after_momentum=False):
        self.lr = lr
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum

    def _kernel(self, p, g, slots, count, lr, inv, output_dtype):
        first = (count == 1) if self.momentum > 0 else False
        return K.sgd_update(
            p, g, slots["m"], lr=lr, momentum=self.momentum,
            dampening=self.dampening, weight_decay=self.weight_decay,
            nesterov=self.nesterov, first_run=first,
            wd_after_momentum=self.wd_after_momentum, grad_scale=inv,
            param_copy_dtype=output_dtype)
