"""AOT MeshPlan pre-flight explainer: compile the flagship step against
candidate mesh layouts and rank them BEFORE any pod time is spent.

For each candidate the script AOT-compiles the step (``.lower().
compile()`` — the only dispatches are the ZeRO variant's state init),
then prices the compiled module with three per-axis views that all join
through the same primitives the runtime observability uses:

- **per-axis HBM** — :func:`apex_tpu.prof.shard_report`: for every mesh
  axis, which bytes are sharded by it vs replicated over it, closing
  over :func:`apex_tpu.prof.memory_report`'s class totals within 1%
  (ZeRO's annotation-invisible opt-state shards enter via the
  ``overrides=`` declared-layout escape hatch);
- **per-axis wire bytes** — the optimized module's collective result
  shapes joined scope→axis through the ONE planned-collective registry
  (:func:`apex_tpu.monitor.scope_axis_row`; unattributable traffic
  lands in an explicit ``unknown`` row);
- **predicted per-axis comm seconds** — the measured-or-default α–β
  :class:`~apex_tpu.lint.mesh_model.MeshModel` link budgets
  (``hop_seconds``), the same model :func:`apex_tpu.parallel.plan_comm`
  optimizes and :mod:`apex_tpu.monitor.comm_drift` later re-judges
  against measurements — a stale model flags there, not here.

Candidates are ranked by (APX findings, predicted comm seconds): the
pre-flight verdict is the apexlint SPMD pass (APX201–204) over each
module, so a flat DCN-crossing layout ranks below its hierarchical
factorization *with the finding attached*, not just a worse number.

Candidate grammar: a ``parse_mesh_spec`` string with an optional
suffix — ``dp2x4`` (hierarchical DDP + CommPlan), ``dp2x4flat``
(same topology judged, but the step compiled with the FLAT sync —
the APX203 shape), ``dp2x4zero`` / ``ici8zero`` (ZeRO
``DistributedFusedAdam`` sharded over the data axes).

Usage:
  python scripts/mesh_explain.py --candidates dp2x4zero,dp2x4,dp2x4flat
  python scripts/mesh_explain.py --jsonl preflight.jsonl   # sharding
      # channel stream (check_metrics_schema.py --kind sharding)
  python scripts/mesh_explain.py --forecast tp=2,pp=2      # what-if
      # further-axis HBM shrink per candidate (ShardReport.forecast_axes)
  python scripts/mesh_explain.py --cpu8    # asserted 8-device CPU-mesh
      # audit (run_tier1.sh --smoke): HBM closure + ZeRO 1/8 ratio,
      # plan-vs-priced dp-axis agreement, flat-ranks-last + APX203,
      # and JSONL schema validity; exit status is the verdict
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_CANDIDATES = "dp2x4zero,dp2x4,dp2x4flat"
MESSAGE_SIZE = 30_000
IMAGE = 32
PER_CHIP_BATCH = 4

#: agreement tolerance (ratio band) between this script's byte-level
#: per-axis pricing and the CommPlan's α–β hop prediction: the two sit
#: on the same MeshModel link budgets but differ by ring factors,
#: per-hop α and CPU float-normalization of bf16 wires — an order of
#: magnitude apart means a broken join, not a modeling nuance
AGREE_BAND = (0.25, 4.0)


def _small_model():
    from apex_tpu import models
    return models.ResNet(stage_sizes=[1, 1], num_classes=10, width=16,
                         dtype=jnp.bfloat16)


class Candidate:
    """One parsed candidate spec: base mesh model + step variant."""

    def __init__(self, label, mm, kind, flat):
        self.label = label      # the spec as given ("dp2x4zero")
        self.mm = mm            # MeshModel of the base spec
        self.kind = kind        # "ddp" | "zero"
        self.flat = flat        # compile the flat sync (APX203 twin)


def parse_candidate(spec: str) -> Candidate:
    from apex_tpu.lint.mesh_model import parse_mesh_spec
    s = spec.strip()
    kind, flat, base = "ddp", False, s
    if s.endswith("zero"):
        kind, base = "zero", s[:-4]
    elif s.endswith("flat"):
        flat, base = True, s[:-4]
    return Candidate(s, parse_mesh_spec(base), kind, flat)


def _count_params(model, image_size):
    """Flagship param count from avals only (no arrays, no dispatch)."""
    x1 = jnp.ones((2, image_size, image_size, 3), jnp.float32)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), x1, train=True))
    return sum(int(np.prod(l.shape))
               for l in jax.tree_util.tree_leaves(variables["params"]))


# --- pricing (pure: hlo text + mesh model -> numbers; zero compiles) ---------

def price_candidate(hlo_text, mesh_model, known_scopes=()):
    """Price one compiled module against a mesh model. Pure text+model
    arithmetic — no compile, no dispatch — so ``bench.py`` reuses it on
    the module its memory row already compiled.

    Returns ``{"wire_by_axis": {axis: bytes}, "predicted_s": {axis:
    s | None}, "findings": [lint.Finding], "codes": ["APX203", ...],
    "predicted_total_s": float}``. An axis the model has no link budget
    for (the ``unknown`` row) prices to ``None`` — unattributable
    traffic is surfaced, never priced on faith. A composite axis (the
    registry's flat ``data`` over a factored model) rides the model's
    slowest link: the conservative pre-flight direction.
    """
    from apex_tpu.lint.findings import RULES
    from apex_tpu.lint.spmd_pass import lint_spmd_text
    from apex_tpu.monitor.collectives import collective_bytes_by_axis

    wire = {ax: sum(per.values())
            for ax, per in collective_bytes_by_axis(hlo_text).items()}
    axis_link = {a.name: a.link for a in mesh_model.axes}
    slowest = ("dcn" if any(a.link == "dcn" for a in mesh_model.axes)
               else "ici")
    predicted = {}
    for ax, nbytes in wire.items():
        link = axis_link.get(ax)
        if link is None and ax != "unknown":
            link = slowest            # composite axis: slowest link
        predicted[ax] = (mesh_model.hop_seconds(nbytes, link)
                         if link else None)
    findings = lint_spmd_text(hlo_text, mesh_model=mesh_model,
                              known_scopes=known_scopes)
    return {"wire_by_axis": wire, "predicted_s": predicted,
            "findings": findings,
            "codes": sorted({RULES[f.rule].id for f in findings}),
            "predicted_total_s": sum(v for v in predicted.values()
                                     if v is not None)}


# --- per-candidate compile ---------------------------------------------------

def lower_zero_flagship(mesh, axes, model, *, image_size,
                        per_chip_batch):
    """Compile the ZeRO flagship (``DistributedFusedAdam`` sharded over
    ``axes`` — a tuple reduce-scatters per axis in order, so a factored
    mesh routes each hop on its own link). Same structure as
    ``memory_budget.build_programs``'s zero program; the only
    dispatches are the state init and device_put commits."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from apex_tpu import amp, ops
    from apex_tpu.optim import DistributedFusedAdam
    from apex_tpu.trace.spans import span

    axes = tuple(axes)
    tx = DistributedFusedAdam(
        lr=1e-3, axis_name=axes if len(axes) > 1 else axes[0])
    amp_opt = amp.Amp(amp.Policy.from_opt_level("O2"), tx)

    n = mesh.size
    rng = np.random.RandomState(0)
    batch = per_chip_batch * n
    x = jnp.asarray(rng.rand(batch, image_size, image_size, 3)
                    .astype(np.float32))
    y = jnp.asarray(rng.randint(0, 10, batch), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def step(state, bs, xb, yb):
        def loss_fn(mp):
            logits, mut = model.apply(
                {"params": mp, "batch_stats": bs}, xb, train=True,
                mutable=["batch_stats"])
            loss = jnp.mean(ops.softmax_cross_entropy_loss(logits, yb))
            with span("ddp/loss_pmean", kind="collective"):
                for ax in axes:
                    loss = jax.lax.pmean(loss, ax)
            return loss, mut["batch_stats"]

        (loss, new_bs), grads, state, finite = amp_opt.backward(
            state, loss_fn, has_aux=True)
        state = amp_opt.apply_gradients(state, grads, finite)
        return state, new_bs, loss

    state = jax.jit(jax.shard_map(
        lambda p: amp_opt.init(p), mesh=mesh, in_specs=(P(),),
        out_specs=P(), check_vma=False))(params)
    mapped = jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P(axes), P(axes)),
        out_specs=(P(), P(), P()), check_vma=False)
    args = (state,
            jax.device_put(batch_stats, NamedSharding(mesh, P())),
            jax.device_put(x, NamedSharding(mesh, P(axes))),
            jax.device_put(y, NamedSharding(mesh, P(axes))))
    compiled = jax.jit(mapped).lower(*args).compile()
    return compiled, tx, params


def explain_candidate(cand, model, *, image_size=IMAGE,
                      per_chip_batch=PER_CHIP_BATCH,
                      message_size=MESSAGE_SIZE):
    """Compile one candidate's flagship step and price it. Returns a
    row dict (shard report, per-axis wire/predicted, verdict)."""
    import pod_comm_budget as pcb
    from jax.sharding import Mesh

    from apex_tpu import parallel
    from apex_tpu.parallel import hierarchy
    from apex_tpu.prof import shard_report

    devices = np.asarray(jax.devices()).reshape(-1)
    mm = cand.mm
    if mm.n_devices != devices.size:
        raise SystemExit(
            f"candidate {cand.label}: mesh model wants {mm.n_devices} "
            f"devices, have {devices.size}")
    hier = any(a.link == "dcn" for a in mm.axes)
    plan, tx, params, overrides = None, None, None, None

    if cand.kind == "zero":
        mesh = (pcb.hierarchical_mesh_for_model(mm, devices) if hier
                else Mesh(devices, (parallel.DATA_AXIS,)))
        axes = mm.axis_names if hier else (parallel.DATA_AXIS,)
        compiled, tx, params = lower_zero_flagship(
            mesh, axes, model, image_size=image_size,
            per_chip_batch=per_chip_batch)
        # the manual-sharding escape hatch: shard_map carves the opt
        # slots by hand, so the annotation says replicated — declare
        # the real layout (rows report source="declared")
        overrides = {r"opt_state\.slots": tuple(mm.axis_names)}
    elif hier and not cand.flat:
        mesh = pcb.hierarchical_mesh_for_model(mm, devices)
        plan = hierarchy.plan_comm(
            mm, grad_bytes=4 * _count_params(model, image_size))
        lowered, params = pcb.lower_flagship(
            mesh, devices.size, delay_allreduce=False, model=model,
            image_size=image_size, per_chip_batch=per_chip_batch,
            message_size=message_size, comm_plan=plan)
        compiled = lowered.compile()
    else:
        # flat bucketed sync — for a "...flat" candidate this is the
        # deliberate APX203 twin: compiled flat, judged hierarchical
        mesh = Mesh(devices, (parallel.DATA_AXIS,))
        lowered, params = pcb.lower_flagship(
            mesh, devices.size, delay_allreduce=False, model=model,
            image_size=image_size, per_chip_batch=per_chip_batch,
            bucket_allreduce=True, message_size=message_size)
        compiled = lowered.compile()

    hlo = compiled.as_text()
    sr = shard_report(compiled, mm, batch_size=per_chip_batch,
                      overrides=overrides)
    price = price_candidate(hlo, mm)
    return {"candidate": cand.label, "kind": cand.kind, "mm": mm,
            "plan": plan, "tx": tx, "params": params, "hlo": hlo,
            "sr": sr, "price": price}


# --- the explainer -----------------------------------------------------------

def _fmt_s(v):
    return "-" if v is None else f"{v * 1e3:.3f} ms"


def explain(candidate_specs, *, model=None, jsonl=None, forecast=None,
            image_size=IMAGE, per_chip_batch=PER_CHIP_BATCH,
            message_size=MESSAGE_SIZE):
    """Compile, price, rank and print every candidate; optionally emit
    the sharding-channel JSONL stream. Returns the ranked rows."""
    model = model if model is not None else _small_model()
    rows = [explain_candidate(parse_candidate(s), model,
                              image_size=image_size,
                              per_chip_batch=per_chip_batch,
                              message_size=message_size)
            for s in candidate_specs]
    # rank: findings first (a clean layout beats a flagged one no
    # matter the predicted number), then predicted comm seconds
    order = sorted(rows, key=lambda r: (len(r["price"]["findings"]),
                                        r["price"]["predicted_total_s"]))
    for i, r in enumerate(order):
        r["rank"] = i + 1

    for r in order:
        p = r["price"]
        verdict = (", ".join(p["codes"]) if p["codes"] else "APX clean")
        print(f"\n== rank {r['rank']}: {r['candidate']} "
              f"[{r['kind']}{', flat sync' if 'flat' in r['candidate'] else ''}]"
              f" — {verdict}, predicted comm "
              f"{p['predicted_total_s'] * 1e3:.3f} ms")
        sr = r["sr"]
        axis_rows = list(sr.axis_names) + [
            a for a in p["wire_by_axis"] if a not in sr.axis_names]
        print(f"   {'axis':<12} {'hbm sharded':>12} {'hbm repl':>12} "
              f"{'wire':>12} {'pred':>10}")
        for ax in axis_rows:
            b = (sr.axis_bytes(ax) if ax in sr.axis_table
                 else {"sharded_bytes": 0, "replicated_bytes": 0})
            print(f"   {ax:<12} {b['sharded_bytes']:>12} "
                  f"{b['replicated_bytes']:>12} "
                  f"{p['wire_by_axis'].get(ax, 0):>12} "
                  f"{_fmt_s(p['predicted_s'].get(ax)):>10}")
        for f in p["findings"]:
            print(f"   finding {f.rule}: {f.message[:110]}")
        if forecast:
            fc = sr.forecast_axes(forecast)
            print(f"   forecast {fc['factors']}: "
                  f"{fc['total_now']} -> {fc['total_forecast']} B")

    if jsonl:
        from apex_tpu.monitor import JSONLSink, MetricsLogger
        logger = MetricsLogger(sinks=[], sharding_sink=JSONLSink(jsonl))
        for r in order:
            logger.attach_shard_report(
                r["sr"], candidate=r["candidate"],
                wire_by_axis=r["price"]["wire_by_axis"],
                predicted_s=r["price"]["predicted_s"])
        logger.close()
        print(f"\nsharding stream -> {jsonl} "
              f"(check_metrics_schema.py --kind sharding)")
    return order


# --- the asserted cpu8 audit (run_tier1.sh --smoke) --------------------------

def main_cpu8() -> int:
    jax.config.update("jax_platforms", "cpu")
    from apex_tpu import _compat
    _compat.request_cpu_devices(8)

    import tempfile

    import check_metrics_schema as cms

    n = 8
    model = _small_model()
    path = os.path.join(tempfile.mkdtemp(prefix="mesh_explain_"),
                        "sharding.jsonl")
    print("mesh pre-flight audit, 8-device CPU mesh")
    rows = explain(DEFAULT_CANDIDATES.split(","), model=model,
                   jsonl=path, forecast={"tp": 2})
    by = {r["candidate"]: r for r in rows}

    # (a) per-axis HBM closes over the memory report on the ZeRO
    # flagship, and the declared opt-state shards show the ~1/8 ratio
    z = by["dp2x4zero"]
    sr = z["sr"]
    rep = sr.memory
    rel = (abs(rep.attributed_total() - rep.total_bytes)
           / max(rep.total_bytes, 1))
    ok, worst = sr.closure()
    print(f"\n(a) zero flagship: memory attribution rel err {rel:.4%}, "
          f"per-axis closure worst rel {worst:.4%}")
    assert rel < 0.01, (
        f"memory attribution off by {rel:.2%} on the zero flagship")
    assert ok, f"per-axis HBM table does not close: {worst:.2%}"
    ratio = sr.class_shard_ratio("optimizer_state")
    analytic = z["tx"].state_bytes(z["params"], world=n)
    print(f"    opt-state local/global ratio {ratio:.4f} "
          f"(1/N = {1 / n:.4f}, analytic padding slack "
          f"{analytic['ratio'] * n:.3f}x)")
    assert 0.8 / n <= ratio <= 1.5 / n, (
        f"declared ZeRO opt-state shards not ~1/{n}: {ratio:.4f}")
    for ax in sr.axis_names:
        shb = sr.axis_table[ax]["sharded"].get("optimizer_state", 0)
        assert shb > 0, (
            f"axis {ax}: no opt-state bytes attributed sharded — the "
            "declared-override join broke")

    # (b) priced dp-axis comm seconds agree with the CommPlan's α–β
    # hop prediction (the pod_comm_budget numbers) within tolerance
    h = by["dp2x4"]
    plan_pred = h["plan"].predicted_seconds()
    mine = h["price"]["predicted_s"]
    print(f"(b) dp2x4 priced vs plan: "
          + ", ".join(f"{ax} {_fmt_s(mine.get(ax))}" for ax in mine)
          + " | plan "
          + ", ".join(f"{k} {v * 1e3:.3f} ms"
                      for k, v in plan_pred.items()))
    for ax, link in (("data_intra", "ici"), ("data_inter", "dcn")):
        a, b = mine.get(ax), plan_pred.get(link)
        assert a and b, (
            f"missing {ax} pricing ({a}) or plan {link} prediction "
            f"({b}) — the scope→axis join or the plan broke")
        r = a / b
        print(f"    {ax}/{link}: priced/plan ratio {r:.3f}")
        assert AGREE_BAND[0] <= r <= AGREE_BAND[1], (
            f"{ax} priced {a:.2e}s vs plan {link} {b:.2e}s: ratio "
            f"{r:.2f} outside {AGREE_BAND}")

    # (c) the flat candidate ranks below the hierarchical one and
    # carries the APX203 finding — the negative twin of the ranking
    flat, hier = by["dp2x4flat"], by["dp2x4"]
    print(f"(c) ranks: dp2x4 #{hier['rank']}, dp2x4flat "
          f"#{flat['rank']} ({', '.join(flat['price']['codes'])})")
    assert "APX203" in flat["price"]["codes"], (
        "flat candidate lost its APX203 finding — the verdict would "
        "pass a DCN-flat layout")
    assert flat["rank"] > hier["rank"], (
        f"flat candidate ranked {flat['rank']} above hierarchical "
        f"{hier['rank']}")
    assert not hier["price"]["codes"], (
        f"hierarchical candidate has findings: {hier['price']['codes']}")

    # (d) the emitted sharding stream validates
    with open(path) as f:
        errors = cms.check_sharding_lines(f)
    assert not errors, "sharding stream invalid:\n" + "\n".join(errors)
    with open(path) as f:
        n_lines = sum(1 for _ in f)
    print(f"(d) sharding stream {path}: {n_lines} events, schema ok")
    print("\nmesh pre-flight audit ok")
    return 0


def _parse_forecast(arg):
    out = {}
    for part in arg.split(","):
        k, _, v = part.partition("=")
        out[k.strip()] = int(v)
    return out


def main() -> int:
    argv = sys.argv[1:]
    if "--cpu8" in argv:
        return main_cpu8()
    candidates = DEFAULT_CANDIDATES
    jsonl = forecast = None
    it = iter(argv)
    for a in it:
        if a == "--candidates":
            candidates = next(it, "")
        elif a == "--jsonl":
            jsonl = next(it, None)
        elif a == "--forecast":
            forecast = _parse_forecast(next(it, ""))
        elif a in ("-h", "--help"):
            print(__doc__)
            return 2
    explain([c for c in candidates.split(",") if c],
            jsonl=jsonl, forecast=forecast)
    return 0


if __name__ == "__main__":
    sys.exit(main())
