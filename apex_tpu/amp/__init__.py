"""apex_tpu.amp — the precision engine.

TPU-native rebuild of ``apex.amp`` (see SURVEY.md §2.1): opt-level presets
become immutable :class:`Policy` values, the dynamic loss scaler becomes
functional state advanced on-device, and O1's namespace patching becomes a
policy applied at the library boundary (ambient :func:`policy_scope` for
``apex_tpu.ops``, :func:`auto_cast` interceptor for stock flax models).
"""

from apex_tpu.amp.policy import Policy, current_policy, policy_scope
from apex_tpu.amp.scaler import (
    LossScaleConfig,
    LossScaleState,
    loss_scale_init,
    loss_scale_update,
    scale_loss,
    select_if_finite,
    unscale_grads,
    unscale_grads_with_stashed,
    value_and_scaled_grad,
)
from apex_tpu.amp.api import (
    Amp,
    AmpState,
    initialize,
    half_function,
    float_function,
    promote_function,
)
from apex_tpu.amp.scale_history import (
    ScaleHistoryConfig,
    ScaleHistoryState,
    scale_history_init,
    scale_history_update,
    scale_update_events,
)
from apex_tpu.amp.interceptor import auto_cast, make_interceptor
from apex_tpu.amp.opt import OptimWrapper
from apex_tpu.amp.lists import (
    register_half_op,
    register_float_op,
    register_promote_op,
    unregister_op,
    register_half_module,
    register_float_module,
)

__all__ = [
    "Policy", "current_policy", "policy_scope",
    "LossScaleConfig", "LossScaleState", "loss_scale_init",
    "loss_scale_update", "scale_loss", "select_if_finite", "unscale_grads",
    "unscale_grads_with_stashed", "value_and_scaled_grad",
    "ScaleHistoryConfig", "ScaleHistoryState", "scale_history_init",
    "scale_history_update", "scale_update_events",
    "Amp", "AmpState", "initialize",
    "half_function", "float_function", "promote_function",
    "auto_cast", "make_interceptor", "OptimWrapper",
    "register_half_op", "register_float_op", "register_promote_op",
    "unregister_op",
    "register_half_module", "register_float_module",
]
