#!/usr/bin/env python
"""Numerics audit: measure per-tensor dynamic range, prove the verdicts.

The asserting sibling of ``roofline_audit.py`` for the numeric-health
axis (``run_tier1.sh --smoke`` runs it; exit status is the verdict).
Four claims, each printed and asserted:

(a) **zero-surprise clean run, zero extra dispatch** — a structural
    BERT MLM step (the apexlint-flagship CPU downscale: 2-layer
    ``models.BertEncoder``, amp O1 + FusedLAMB, ``auto_cast`` forward)
    instrumented with the :mod:`apex_tpu.monitor.numerics` fold
    (``Amp.step(numerics=…)``) + an in-graph
    :class:`~apex_tpu.amp.ScaleHistory` over the grad sites emits a
    verdict list with ZERO surprises (no site's measured range demands
    more precision than it runs at today), while driving the step
    under full host polling (check events, scale events, verdicts
    every step) leaves the compiled HLO BIT-IDENTICAL with no host ops
    (the ``numerics/no-extra-dispatch`` compile-check case pins the
    donated half);
(b) **a small-magnitude tensor is flagged at the right site** — a
    seeded log-uniform tensor straddling the e4m3 underflow boundary
    (2⁻⁶) is flagged at ITS site (the well-scaled sibling site stays
    clean): the verdict names the minimum safe format (fp8_e4m3 —
    range-safe only WITH scaling) and a ``recommended_scale`` that,
    applied to the tensor and re-measured, drives the measured
    unscaled-e4m3 underflow fraction below the threshold;
(c) **ScaleHistory matches its oracle exactly** — a synthetic amax
    ramp (with an injected overflow step) drives the in-graph state
    machine through grow/shrink/backoff, and every per-step scale
    equals a pure-numpy oracle of the documented semantics bitwise;
(d) **the stream validates** — every emitted event passes
    ``check_metrics_schema.py --kind numerics`` and all three kinds
    are present.

``--write-fixture PATH`` additionally serializes claim (a)'s measured
statistics (:func:`apex_tpu.monitor.numerics.stats_to_json`) — the
generator for the committed ``tests/fixtures/bert_numerics_stats.json``
that CI pins ``precision_report()`` verdicts on, device-free.

Usage: python scripts/numerics_audit.py --cpu8
       python scripts/numerics_audit.py --cpu8 --write-fixture out.json
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_STEPS = 8
CHECK_EVERY = 2
BATCH, SEQ = 8, 32


def _build_bert_step():
    """The claim-(a) subject: the flagship BERT MLM construction
    (bench._bert_step_builder's structural CPU downscale, the same
    encoder shape apexlint's smoke gate lints) with the numerics fold
    + grad-site ScaleHistory threaded through ``Amp.step``."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu import amp, models
    from apex_tpu.monitor import numerics as nx
    from apex_tpu.optim import FusedLAMB

    policy = amp.Policy.from_opt_level("O1")
    enc = models.BertEncoder(30000, hidden=128, layers=2, heads=2,
                             max_len=SEQ)
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 30000, (BATCH, SEQ)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, 30000, (BATCH, SEQ)), jnp.int32)
    variables = enc.init(jax.random.PRNGKey(0), toks[:1])
    amp_opt = amp.Amp(policy, FusedLAMB(lr=1e-3))
    state = amp_opt.init(variables["params"])

    sites = amp_opt.numerics_sites(state.params)
    ncfg = nx.NumericsConfig(check_every=CHECK_EVERY)
    ns = nx.numerics_init(ncfg, sites=sites)
    grad_rows = tuple(i for i, s in enumerate(sites)
                      if s.startswith("amp/grads/"))
    scfg = amp.ScaleHistoryConfig(window=4)
    sh = amp.scale_history_init(scfg, n_sites=len(grad_rows))

    def loss_fn(mp, toks, labels):
        with amp.auto_cast(policy):
            return models.mlm_loss(enc, {"params": mp}, toks, labels)

    def step(state, ns, sh, toks, labels):
        state, loss, finite, ns = amp_opt.step(
            state, loss_fn, toks, labels, numerics=(ns, ncfg))
        # scale_amax, not ns.amax: the state's amax is the FINITE max
        # by design — only this feed carries the overflow signal the
        # backoff keys on
        sh = amp.scale_history_update(sh, scfg,
                                      nx.scale_amax(ns, grad_rows))
        return state, ns, sh, loss

    jstep = jax.jit(step)
    return (jstep, state, ns, sh, (toks, labels), sites, grad_rows,
            ncfg, scfg, policy)


def _current_dtypes(sites, policy):
    """The per-site current formats of the amp O1 step: the cast copy
    runs at the policy's half dtype, grads and updates at fp32."""
    half = str(policy.compute_dtype) if policy.cast_model_type \
        is not None else "float32"
    return {s: (half if s.startswith("amp/cast/") else "float32")
            for s in sites}


def claim_a(workdir, write_fixture=None):
    import jax
    import numpy as np

    from apex_tpu import amp, monitor
    from apex_tpu.monitor import numerics as nx
    from apex_tpu.monitor.check import module_count_and_host_ops

    (jstep, state, ns0, sh0, (toks, labels), sites, grad_rows, ncfg,
     scfg, policy) = _build_bert_step()
    hlo_before = jstep.lower(state, ns0, sh0, toks,
                             labels).compile().as_text()
    events_path = os.path.join(workdir, "numerics_clean.jsonl")
    logger = monitor.MetricsLogger(
        sinks=[], numerics_sink=monitor.JSONLSink(events_path))
    grad_sites = tuple(sites[i] for i in grad_rows)
    state_, ns, sh = state, ns0, sh0
    for i in range(N_STEPS):
        prev_sh = jax.device_get(sh)
        state_, ns, sh, loss = jstep(state_, ns, sh, toks, labels)
        # full host polling, every step (most being off-steps)
        for ev in nx.check_events(ns, sites, current_dtype="bfloat16"):
            logger.record_numerics(ev)
        for ev in amp.scale_update_events(prev_sh, sh, grad_sites):
            logger.record_numerics(ev)
    cur = _current_dtypes(sites, policy)
    report = nx.precision_report(ns, sites, current_dtypes=cur)
    for ev in report.to_events():
        logger.record_numerics(ev)
    logger.close()
    hlo_after = jstep.lower(state, ns0, sh0, toks,
                            labels).compile().as_text()
    assert hlo_after == hlo_before, \
        "numerics observation changed the compiled BERT step"
    _n, host = module_count_and_host_ops(jstep, state, ns0, sh0, toks,
                                         labels)
    assert not host, f"instrumented BERT step compiled host traffic: " \
                     f"{host}"
    n_checks = int(np.asarray(jax.device_get(ns.check_count)))
    assert n_checks == N_STEPS // CHECK_EVERY, n_checks
    surprises = report.surprises()
    assert not surprises, (
        "clean BERT step produced surprise verdicts: "
        + ", ".join(f"{r.site} needs {r.required_dtype}"
                    for r in surprises))
    assert all(r.nonfinite_frac == 0 for r in report.rows)
    # the update-to-weight companion folded where registered
    assert any(r.uw_ratio is not None and r.uw_ratio > 0
               for r in report.rows if r.kind == "amp")
    if write_fixture:
        with open(write_fixture, "w") as f:
            f.write(nx.stats_to_json(ns, sites))
        print(f"      fixture written: {write_fixture}")
    print(f"  (a) clean BERT step ({N_STEPS} steps, {n_checks} folds, "
          f"{len(sites)} sites): ZERO surprise verdicts, compiled HLO "
          f"bit-identical under per-step host polling, no host ops")
    return events_path, report


def claim_b(workdir):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.monitor import numerics as nx

    rng = np.random.RandomState(7)
    # log-uniform magnitudes straddling e4m3's min normal 2^-6:
    # exponents in [-12, -2] — roughly half the mass underflows at
    # scale 1, all of it fits with the recommended power-of-two shift
    tiny = jnp.asarray(
        (2.0 ** rng.uniform(-12, -2, (4096,))
         * np.where(rng.rand(4096) < 0.5, -1.0, 1.0)).astype("float32"))
    # the sibling sits squarely inside e4m3's normal range [-6, 8] —
    # a unit normal would NOT (≈1% of |N(0,1)| is below 2^-6; that is
    # a real fp8 hazard, not a test artifact)
    normal = jnp.asarray(
        (2.0 ** rng.uniform(-4, 4, (4096,))
         * np.where(rng.rand(4096) < 0.5, -1.0, 1.0)).astype("float32"))
    trees = {"probe": {"tiny": tiny, "normal": normal}}
    sites = nx.site_names(trees)
    ncfg = nx.NumericsConfig()
    ns = jax.jit(lambda ns: nx.numerics_observe(
        ns, ncfg, trees))(nx.numerics_init(ncfg, sites=sites))
    report = nx.precision_report(ns, sites)
    by_site = {r.site: r for r in report.rows}
    t = by_site["probe/['tiny']"]
    n = by_site["probe/['normal']"]
    # flagged at the correct site: the tiny tensor underflows e4m3
    # badly UNSCALED, its sibling does not
    t_u0 = t.by_format["fp8_e4m3"]["unscaled_underflow"]
    n_u0 = n.by_format["fp8_e4m3"]["unscaled_underflow"]
    assert t_u0 > 0.3, f"seeded tensor not flagged (u0={t_u0})"
    assert n_u0 <= report.underflow_threshold, \
        f"well-scaled sibling site flagged (u0={n_u0})"
    # the verdict names the minimum safe format + a scale that fixes it
    assert t.required_dtype == "fp8_e4m3", t.required_dtype
    assert t.recommended_scale > 1.0, t.recommended_scale
    assert t.predicted_underflow_frac <= report.underflow_threshold
    # ... and the prediction holds when the scale is APPLIED and the
    # scaled tensor re-measured from scratch
    scaled = {"probe": {"tiny": tiny * t.recommended_scale,
                        "normal": normal}}
    ns2 = jax.jit(lambda ns: nx.numerics_observe(
        ns, ncfg, scaled))(nx.numerics_init(ncfg, sites=sites))
    rep2 = nx.precision_report(ns2, sites)
    t2 = {r.site: r for r in rep2.rows}["probe/['tiny']"]
    u_after = t2.by_format["fp8_e4m3"]["unscaled_underflow"]
    s_after = t2.by_format["fp8_e4m3"]["unscaled_saturation"]
    assert u_after <= report.underflow_threshold, \
        f"recommended scale did not clear the underflow ({u_after})"
    assert s_after <= report.saturation_threshold, s_after
    print(f"  (b) e4m3-straddling tensor: flagged at probe/['tiny'] "
          f"(unscaled underflow {t_u0:.1%} vs sibling {n_u0:.1%}), "
          f"verdict names fp8_e4m3 + scale {t.recommended_scale:g}; "
          f"applied, measured underflow drops to {u_after:.2%} "
          f"(threshold {report.underflow_threshold:.2%})")


def claim_c():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu import amp

    cfg = amp.ScaleHistoryConfig(window=4, growth_interval=2,
                                 growth_factor=2.0)
    # a ramp through 6 octaves with one injected overflow step
    steps = 24
    ramp = np.array([[2.0 ** (t / 4.0 - 8.0),
                      2.0 ** (-t / 3.0)] for t in range(steps)],
                    np.float32)
    ramp[13, 0] = np.inf                      # the overflow event
    sh = amp.scale_history_init(cfg, n_sites=2)
    upd = jax.jit(lambda sh, a: amp.scale_history_update(sh, cfg, a))

    # oracle: replay the documented semantics in numpy, bit-for-bit
    from apex_tpu.monitor.numerics import FORMAT_TABLE
    fmt = FORMAT_TABLE[cfg.fmt]
    hist = np.zeros((2, cfg.window), np.float32)
    scale = np.ones((2,), np.float32)
    tracker = np.zeros((2,), np.int64)
    mismatches = 0
    for t in range(steps):
        amax = ramp[t]
        finite = np.isfinite(amax)
        prev_max = hist.max(axis=1)
        hist[:, t % cfg.window] = np.where(finite, amax, prev_max)
        wmax = hist.max(axis=1)
        ratio = (np.float32(fmt.max_finite)
                 / (np.float32(cfg.margin) * wmax)).astype(np.float32)
        _m, e = np.frexp(ratio)
        target = np.where(wmax > 0, np.ldexp(np.float32(1.0), e - 1),
                          scale).astype(np.float32)
        target = np.clip(target, cfg.min_scale,
                         cfg.max_scale).astype(np.float32)
        tracker = np.where(finite, tracker + 1, 0)
        may_grow = tracker >= cfg.growth_interval
        grown = np.minimum(target, np.minimum(
            scale * np.float32(cfg.growth_factor),
            np.float32(cfg.max_scale))).astype(np.float32)
        clean = np.where(target < scale, target,
                         np.where(may_grow, grown, scale))
        new_scale = np.where(finite, clean,
                             np.maximum(scale * np.float32(
                                 cfg.backoff_factor),
                                 np.float32(cfg.min_scale))
                             ).astype(np.float32)
        tracker = np.where(finite & may_grow & (grown > scale), 0,
                           tracker)
        scale = new_scale

        sh = upd(sh, jnp.asarray(amax))
        got = np.asarray(jax.device_get(sh.scale))
        if not np.array_equal(got, scale):
            mismatches += 1
            print(f"      step {t}: device {got} != oracle {scale}")
    assert mismatches == 0, f"{mismatches} oracle mismatches"
    assert int(np.asarray(jax.device_get(sh.overflow_count))[0]) == 1
    assert int(np.asarray(jax.device_get(sh.overflow_count))[1]) == 0
    print(f"  (c) ScaleHistory vs oracle: {steps}/{steps} steps "
          f"bitwise-equal through grow/shrink/backoff (1 injected "
          f"overflow backed off and recovered)")


def claim_d(events_path):
    from scripts.check_metrics_schema import check_numerics_lines
    with open(events_path) as f:
        errors = check_numerics_lines(f)
    assert not errors, ("numerics event schema violations:\n"
                        + "\n".join(errors))
    with open(events_path) as f:
        kinds = {json.loads(l)["kind"] for l in f if l.strip()}
    assert kinds == {"numerics_check", "scale_update",
                     "precision_verdict"}, kinds
    with open(events_path) as f:
        n = sum(1 for l in f if l.strip())
    print(f"  (d) {n} numerics events validate (--kind numerics); "
          f"all three kinds present")


def main_audit(write_fixture=None):
    tmp = tempfile.mkdtemp(prefix="apex_numerics_audit_")
    events_path, _report = claim_a(tmp, write_fixture=write_fixture)
    claim_b(tmp)
    claim_c()
    claim_d(events_path)
    print("numerics audit ok")


def main():
    write_fixture = None
    if "--write-fixture" in sys.argv:
        write_fixture = sys.argv[sys.argv.index("--write-fixture") + 1]
    if "--cpu8" in sys.argv:
        import jax
        from apex_tpu import _compat
        jax.config.update("jax_platforms", "cpu")
        _compat.request_cpu_devices(8)
    main_audit(write_fixture=write_fixture)


if __name__ == "__main__":
    sys.exit(main())
