"""``python -m apex_tpu.data`` — loader-only throughput probe.

    python -m apex_tpu.data --bench DIR -b 128 --size 224 --workers 8
    python -m apex_tpu.data --bench DIR --cache CACHEDIR    # packed path
    python -m apex_tpu.data --build-cache DIR --cache CACHEDIR
    python -m apex_tpu.data --make-fake /tmp/fakeimagenet

Prints images/sec of decode+augment+batch assembly alone; compare with
the model's synthetic-data img/s to tell input-bound from compute-bound.
With ``--cache`` the bench reads the packed pre-decoded shards (built
on first use) — the DALI-class path.
"""

import argparse

from apex_tpu.data import (ImageFolderSource, PackedSource, build_cache,
                           make_fake_imagefolder, measure_source)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--bench", metavar="DIR")
    p.add_argument("--build-cache", metavar="DIR")
    p.add_argument("--cache", metavar="CACHEDIR")
    p.add_argument("--make-fake", metavar="DIR")
    p.add_argument("-b", "--batch", type=int, default=128)
    p.add_argument("--size", type=int, default=224)
    p.add_argument("--store-size", type=int, default=256)
    p.add_argument("--rrc", action="store_true",
                   help="true RandomResizedCrop from the cache")
    p.add_argument("--workers", type=int, default=None)
    p.add_argument("--steps", type=int, default=20)
    args = p.parse_args()
    if args.make_fake:
        make_fake_imagefolder(args.make_fake)
        print(f"wrote fake ImageFolder tree at {args.make_fake}")
    if args.build_cache:
        if not args.cache:
            p.error("--build-cache requires --cache CACHEDIR")
        build_cache(args.build_cache, args.cache,
                    store_size=args.store_size, workers=args.workers)
        print(f"packed cache ready at {args.cache}")
    if args.bench:
        if args.cache:
            build_cache(args.bench, args.cache,
                        store_size=args.store_size, workers=args.workers)
            src = PackedSource(args.cache, args.batch, args.size,
                               rrc=args.rrc, workers=args.workers)
            kind = f"packed cache ({'rrc' if args.rrc else 'crop+flip'})"
        else:
            src = ImageFolderSource(args.bench, args.batch, args.size,
                                    workers=args.workers)
            kind = "live decode"
        with src:
            rate = measure_source(src.batches(args.steps + 1),
                                  steps=args.steps)
        print(f"loader: {rate:.1f} img/s (batch {args.batch}, "
              f"size {args.size}, workers {src.workers}, {kind})")


if __name__ == "__main__":
    main()
